"""Automatic symbol naming.

Reference: ``python/mxnet/name.py`` (NameManager with per-op-type counters,
``Prefix`` variant) and ``python/mxnet/attribute.py`` (AttrScope — attaches
attrs like ``ctx_group`` / ``lr_mult`` to every symbol created in scope).
"""
from __future__ import annotations

import threading
from typing import Dict, Optional

__all__ = ["NameManager", "Prefix", "AttrScope", "current_name_manager",
           "current_attr_scope"]

_local = threading.local()


class NameManager:
    """Per-op-type counter naming: ``fullyconnected0``, ``conv1``, ...
    (reference: python/mxnet/name.py NameManager.get)."""

    def __init__(self):
        self._counter: Dict[str, int] = {}
        self._old: Optional[NameManager] = None

    def get(self, name: Optional[str], hint: str) -> str:
        if name:
            return name
        hint = hint.lower()
        idx = self._counter.get(hint, 0)
        self._counter[hint] = idx + 1
        return "%s%d" % (hint, idx)

    def __enter__(self):
        self._old = current_name_manager()
        _local.name_manager = self
        return self

    def __exit__(self, *exc):
        _local.name_manager = self._old


class Prefix(NameManager):
    """Prepends a fixed prefix to every auto name (reference: name.py Prefix)."""

    def __init__(self, prefix: str):
        super().__init__()
        self._prefix = prefix

    def get(self, name, hint):
        return self._prefix + super().get(name, hint)


def current_name_manager() -> NameManager:
    nm = getattr(_local, "name_manager", None)
    if nm is None:
        nm = NameManager()
        _local.name_manager = nm
    return nm


class AttrScope:
    """``with mx.AttrScope(ctx_group='dev1'):`` — attach attributes to every
    symbol created inside the scope (reference: python/mxnet/attribute.py;
    the mechanism behind model-parallel ctx_group placement,
    example/model-parallel-lstm/lstm.py:65-129)."""

    def __init__(self, **kwargs):
        self._attrs = {k: str(v) for k, v in kwargs.items()}
        self._old = None

    @property
    def attrs(self) -> Dict[str, str]:
        return dict(self._attrs)

    def get(self, user_attrs: Optional[Dict[str, str]]) -> Dict[str, str]:
        out = dict(self._attrs)
        if user_attrs:
            out.update(user_attrs)
        return out

    def __enter__(self):
        parent = current_attr_scope()
        merged = dict(parent._attrs) if parent else {}
        merged.update(self._attrs)
        self._old = parent
        self._attrs = merged
        _local.attr_scope = self
        return self

    def __exit__(self, *exc):
        _local.attr_scope = self._old


def current_attr_scope() -> Optional[AttrScope]:
    return getattr(_local, "attr_scope", None)

"""Graph executor.

Reference: ``src/executor/graph_executor.cc`` + ``include/mxnet/executor.h``
(SURVEY.md §2.6): the reference binds a symbol into per-node engine ops with a
memory plan; Forward/Backward push cached ops in topo order.

TPU design: the whole bound graph is ONE jitted XLA program (SURVEY.md §7 —
the dependency engine, PlanMemory pass and bulk-exec segments all collapse
into XLA compilation/buffer assignment). Three compiled entry points per
executor:

* forward (inference): jitted graph function.
* forward+backward (training): one jitted program computing outputs AND all
  requested input gradients via ``jax.vjp`` — ``Executor.forward(is_train=
  True)`` defers computation so ``backward()`` runs the fused program once
  (no duplicated forward FLOPs in the fit loop).
* aux states (BatchNorm moving stats) are returned functionally and committed
  after each step (the reference mutates them in-place during Forward).

Model parallelism (`group2ctx`, reference graph_executor.cc:279-393
AssignContext + PlaceDevice + _CrossDeviceCopy): bound arrays are placed on
their group's device and the graph executes op-by-op with explicit boundary
transfers — the reference's one-engine-op-per-node schedule with copy
nodes. One XLA program cannot span explicit single-device placements, so
this mode is NOT wrapped in an outer jit (see graph_function).
"""
from __future__ import annotations

import inspect
from typing import Any, Dict, List, Optional, Sequence, Union

import numpy as np
import jax
import jax.numpy as jnp

from .base import MXNetError
from .context import Context
from . import ndarray as _nd
from . import random as _random
from .obs import compiles as _obs_compiles

__all__ = ["Executor", "graph_function"]


def _accepts_is_train(op) -> bool:
    cached = getattr(op, "_accepts_is_train", None)
    if cached is None:
        try:
            cached = "_is_train" in inspect.signature(op.fn).parameters
        except (TypeError, ValueError):
            cached = False
        op._accepts_is_train = cached
    return cached


def graph_function(symbol, node_device=None, scan_plan=None):
    """Compile a Symbol into a pure function
    ``fn(args_dict, aux_dict, rng_key, is_train) -> (outputs, new_aux_dict)``.

    The TPU analogue of GraphExecutor::InitCachedOps + RunOps
    (graph_executor.cc:1013-1231): instead of one engine op per node, the
    topo-ordered node list becomes one traced JAX program for XLA to fuse
    and schedule.

    ``node_device`` (optional) maps a node to a jax device for model
    parallelism (group2ctx): each op then runs on its group's device with
    explicit boundary transfers — the PlaceDevice + CopyNode pass of the
    reference (graph_executor.cc:279-393). One XLA program cannot span
    explicit single-device placements, so this mode executes op-by-op
    (exactly the reference's one-engine-op-per-node schedule) and must not
    be wrapped in an outer jit.

    ``scan_plan`` (optional, incompatible with ``node_device``): a
    verified :class:`~mxnet_tpu.symbol.scan.ScanPlan` — the repeated
    homogeneous chain it describes executes as ONE ``jax.lax.scan`` over
    stacked per-layer parameters instead of unrolled per-layer tracing,
    so trace time and HLO size stop growing with depth
    (docs/architecture/program_model.md, compile-time control).
    """
    from .symbol.symbol import _topo_order

    nodes = _topo_order(symbol._entries)
    node_index = {id(n): i for i, n in enumerate(nodes)}
    entries = list(symbol._entries)
    if scan_plan is not None and node_device is not None:
        raise MXNetError("scan-over-layers cannot combine with group2ctx "
                         "op-by-op placement")

    def fn(args: Dict[str, Any], aux: Dict[str, Any], key, is_train: bool):
        vals: Dict[Any, Any] = {}
        new_aux: Dict[str, Any] = {}

        def exec_node(node):
            idx = node_index[id(node)]
            if node.is_variable:
                if node.name in args:
                    v = args[node.name]
                elif node.name in aux:
                    v = aux[node.name]
                else:
                    raise MXNetError("unbound variable %r" % node.name)
                vals[(id(node), 0)] = v
                return
            ins = [vals[(id(n), i)] for n, i in node.inputs]
            outs = _run_node(node, ins, key, idx, is_train, node_device)
            for i, o in enumerate(outs):
                vals[(id(node), i)] = o
            n_aux = node.op.num_aux
            if n_aux:
                for (src, _), val in zip(node.inputs[-n_aux:],
                                         outs[-n_aux:]):
                    if src.is_variable:
                        new_aux[src.name] = val

        if scan_plan is not None:
            for node in scan_plan.pre_nodes:
                exec_node(node)
            scan_plan.execute(vals, args, key, is_train,
                              lambda node, ins, k, idx, it:
                              _run_node(node, ins, k, idx, it, None))
            for node in scan_plan.post_nodes:
                exec_node(node)
        else:
            for node in nodes:
                exec_node(node)
        outputs = [vals[(id(n), i)] for n, i in entries]
        return outputs, new_aux

    return fn


def _run_node(node, ins, key, idx, is_train, node_device=None):
    """Execute one graph node: implicit attrs (_is_train, per-node RNG),
    group2ctx boundary transfer, tuple-normalized outputs. The single
    definition both graph_function and Executor.monitor_values dispatch
    through, so monitored values cannot drift from executed values."""
    attrs = dict(node.attrs)
    attrs.pop("name", None)
    if _accepts_is_train(node.op):
        attrs["_is_train"] = is_train
    if node.op.needs_rng:
        attrs["_rng"] = jax.random.fold_in(key, idx)
    if node_device is not None:
        dev = node_device(node)
        if dev is not None:
            # boundary transfer: inputs produced on another group's device
            # hop here (the reference's copy node)
            ins = [jax.device_put(x, dev) for x in ins]
    outs = node.op.fn(*ins, **attrs)
    return outs if isinstance(outs, tuple) else (outs,)


def _normalize_dict(values, names, what):
    if values is None:
        return None
    if isinstance(values, dict):
        return dict(values)
    if isinstance(values, (list, tuple)):
        if len(values) != len(names):
            raise MXNetError("%s: expected %d entries, got %d"
                             % (what, len(names), len(values)))
        return dict(zip(names, values))
    raise MXNetError("%s must be list or dict" % what)


class Executor:
    """Bound computation (reference: include/mxnet/executor.h:52-152)."""

    def __init__(self, symbol, ctx: Context, args, args_grad=None,
                 grad_req="write", aux_states=None, group2ctx=None,
                 shared_exec=None):
        self._symbol = symbol
        self._ctx = ctx
        self._arg_names = symbol.list_arguments()
        self._aux_names = symbol.list_auxiliary_states()
        self._output_names = symbol.list_outputs()

        self.arg_dict: Dict[str, _nd.NDArray] = \
            _normalize_dict(args, self._arg_names, "args") or {}
        missing = [n for n in self._arg_names if n not in self.arg_dict]
        if missing:
            raise MXNetError("bind: missing arguments %s" % missing)
        self.aux_dict: Dict[str, _nd.NDArray] = \
            _normalize_dict(aux_states, self._aux_names, "aux_states") or {}
        missing = [n for n in self._aux_names if n not in self.aux_dict]
        if missing:
            raise MXNetError("bind: missing auxiliary states %s" % missing)

        if isinstance(grad_req, str):
            self._grad_req = {n: grad_req for n in self._arg_names}
        elif isinstance(grad_req, (list, tuple)):
            self._grad_req = dict(zip(self._arg_names, grad_req))
        else:
            self._grad_req = {n: grad_req.get(n, "null")
                              for n in self._arg_names}
        self.grad_dict: Dict[str, _nd.NDArray] = \
            _normalize_dict(args_grad, self._arg_names, "args_grad") or {}
        self._wrt = [n for n in self._arg_names
                     if self._grad_req.get(n, "null") != "null"
                     and n in self.grad_dict]

        # bind-time static analysis (ISSUE 3): graph passes run over the
        # symbol with the bound shapes BEFORE any trace/compile. Gated on
        # the MXNET_TPU_ANALYZE knob with a lazy import so the default
        # (off) pays one dict lookup and never imports the analyzer.
        from . import config as _config
        _analyze_mode = _config.get("MXNET_TPU_ANALYZE")
        if _analyze_mode != "off":
            from .analysis import check_bind as _check_bind
            shapes = {n: tuple(a.shape) for n, a in self.arg_dict.items()}
            shapes.update(
                {n: tuple(a.shape) for n, a in self.aux_dict.items()})
            dtypes = {n: a.dtype for n, a in self.arg_dict.items()}
            # aux dtypes too: the memory passes price BatchNorm running
            # stats against the HBM budget at their real width
            dtypes.update({n: a.dtype for n, a in self.aux_dict.items()})
            _check_bind(symbol, input_shapes=shapes,
                        input_dtypes=dtypes, mode=_analyze_mode,
                        context="bind")

        self._group2ctx = group2ctx
        self._shared_exec = shared_exec
        # compile-accounting label: every jit dispatch below runs under
        # an obs compile scope so a bind/trace wedge is attributable to
        # this executor in mx.obs.report() (docs/architecture/
        # observability.md)
        self._obs_label = "graph:%s" % (
            self._output_names[0] if self._output_names else "?")
        self._remat_name = "off"
        self._scan_plan = self._build_scan_plan(_config)
        self._fn = graph_function(symbol, self._node_device_fn(),
                                  scan_plan=self._scan_plan)
        # programs embedding host-callback custom ops must run
        # synchronously with the frontend: async execution + concurrent
        # eager dispatch deadlocks the CPU runtime (the train_rcnn eval
        # hang — see operator.prop_uses_host_callback)
        from . import operator as _operator
        self._sync_host_callbacks = \
            _operator.symbol_has_host_callback(symbol)
        self._base_key = _random.next_key()
        self._step = 0
        self._outputs: Optional[List[_nd.NDArray]] = None
        self._pending = None   # (arg_vals, aux_vals, key) awaiting fused bwd
        self._monitor_callback = None

        in_shardings = self._arg_shardings()
        if in_shardings is not None:
            # the PlaceDevice step (reference graph_executor.cc:279-393):
            # move the bound arrays onto their group's device; the graph
            # then executes op-by-op with boundary transfers (one XLA
            # program cannot span explicit single-device placements)
            arg_sh, aux_sh = in_shardings
            for name, sh in arg_sh.items():
                nd_arr = self.arg_dict[name]
                if nd_arr.data.sharding != sh:
                    nd_arr._data = jax.device_put(nd_arr.data, sh)
                gbuf = self.grad_dict.get(name)
                if gbuf is not None and gbuf.data.sharding != sh:
                    gbuf._data = jax.device_put(gbuf.data, sh)
            for name, sh in aux_sh.items():
                nd_arr = self.aux_dict[name]
                if nd_arr.data.sharding != sh:
                    nd_arr._data = jax.device_put(nd_arr.data, sh)
            self._jit_fwd = self._fn          # staged eager execution
        else:
            self._jit_fwd = jax.jit(self._fn, static_argnums=(3,))
        # AOT warm starts for the forward path (serve restarts): one
        # serialized executable per is_train variant, resolved lazily at
        # first dispatch (aot.py; single-device programs only)
        self._aot_fwd: Dict[bool, Any] = {}

        # ---- applied remat on the NON-FUSED training path (the other
        # PR 9 close-out flag): forward_backward + update drivers
        # (kvstore binds, custom updaters, monitor mode) trace fwd_bwd
        # below, which never went through Module._build_fused_step's
        # wrap. With a scan plan the body_wrapper already checkpointed
        # each repeated block (and that wrap lives inside self._fn, so
        # fwd_bwd inherits it); only the plan-less whole-forward form is
        # applied here. Kept on a SEPARATE attribute from _remat_name:
        # the fused step keys its own wrap off _remat_name, and this
        # wrap does not reach the fused step's loss_fn.
        self._fwd_bwd_remat = None
        if self._wrt and self._remat_name == "off" and \
                self._scan_plan is None and (
                    _config.get("MXNET_TPU_REMAT") != "off"
                    or _config.get("MXNET_EXEC_ENABLE_REMAT")):
            from . import remat as _remat
            shapes = {n: tuple(a.shape) for n, a in self.arg_dict.items()}
            shapes.update({n: tuple(a.shape)
                           for n, a in self.aux_dict.items()})
            dts = {n: a.dtype for n, a in self.arg_dict.items()}
            dts.update({n: a.dtype for n, a in self.aux_dict.items()})
            policy, name = _remat.resolve_policy(
                self._symbol, input_shapes=shapes, input_dtypes=dts)
            if policy is not None:
                self._fwd_bwd_remat = policy
                self._fwd_bwd_remat_name = name
                from . import profiler as _profiler
                _profiler.incr_counter("remat_applied")

        def fwd_bwd(arg_vals, aux_vals, key, head_grads):
            diff = {n: arg_vals[n] for n in self._wrt}
            rest = {n: v for n, v in arg_vals.items() if n not in diff}

            def f(d):
                outs, new_aux = self._fn({**rest, **d}, aux_vals, key, True)
                return outs, new_aux

            if self._fwd_bwd_remat is not None:
                f = jax.checkpoint(f, policy=self._fwd_bwd_remat)
            (outs, new_aux), vjp = jax.vjp(f, diff, has_aux=False)
            cts = [g if g is not None else jnp.ones_like(o)
                   for g, o in zip(head_grads, outs)]
            grads = vjp((cts, {k: jnp.zeros_like(v)
                               for k, v in new_aux.items()}))[0]
            return outs, new_aux, grads

        # group2ctx mode: jax.vjp over the staged fn runs forward op-by-op
        # on the placed devices and replays transposed transfers backward
        self._jit_fwd_bwd = fwd_bwd if in_shardings is not None \
            else jax.jit(fwd_bwd)

    # ------------------------------------------------------------- forward AOT
    def _dispatch_fwd(self, arg_vals, aux_vals, key, is_train):
        """Forward dispatch with optional AOT warm start
        (MXNET_TPU_COMPILE_CACHE): the first call per ``is_train``
        variant loads — or compiles and serializes — a concrete
        executable; later calls (and later *processes*) run it without
        trace or compile. Multi-device bindings (mesh-sharded values,
        group2ctx) always take the plain path: deserialized multi-device
        executables mis-execute on this jax version (aot.py)."""
        is_train = bool(is_train)
        if self._group2ctx:
            return self._jit_fwd(arg_vals, aux_vals, key, is_train)
        from . import config as _config
        if _config.get("MXNET_TPU_COMPILE_CACHE"):
            # per-shape runners: serve's bucket padding re-enters this
            # executor with different batch geometries, each its own
            # executable (exactly like the jit cache it replaces)
            vkey = (is_train, tuple(v.shape for v in arg_vals.values()))
            runner = self._aot_fwd.get(vkey)
            if runner is None:
                runner = self._aot_fwd_setup(arg_vals, aux_vals, key,
                                             is_train, vkey)
            if runner is not False:
                try:
                    return runner(arg_vals, aux_vals, key)
                except Exception as exc:                    # noqa: BLE001
                    from . import profiler as _profiler
                    _profiler.incr_counter("aot_error")
                    import logging
                    logging.getLogger(__name__).warning(
                        "aot: forward executable failed (%s); falling "
                        "back to jit", exc)
                    self._aot_fwd[vkey] = False
        return self._jit_fwd(arg_vals, aux_vals, key, is_train)

    def _aot_fwd_setup(self, arg_vals, aux_vals, key, is_train, vkey):
        """Resolve the AOT runner for one is_train variant (False =
        permanently use the jit path for this binding)."""
        from . import aot as _aot
        from . import profiler as _profiler

        def _multi(v):
            sh = getattr(v, "sharding", None)
            devs = getattr(sh, "device_set", None)
            return devs is not None and len(devs) > 1

        runner = False
        vals = list(arg_vals.values()) + list(aux_vals.values())
        if any(_multi(v) for v in vals):
            _profiler.incr_counter("aot_skip_multidevice")
        elif _aot.supported():
            try:
                from . import amp as _amp
                sig = (
                    "graph_fwd", self._symbol.tojson(),
                    sorted((n, tuple(v.shape), str(v.dtype))
                           for n, v in arg_vals.items()),
                    sorted((n, tuple(v.shape), str(v.dtype))
                           for n, v in aux_vals.items()),
                    is_train,
                    self._scan_plan.n_layers
                    if self._scan_plan is not None else 0,
                    (_amp.active(),
                     str(_amp.compute_dtype()) if _amp.active() else ""),
                )
                digest = _aot.digest(sig)
                runner = _aot.load("graph_fwd", digest)
                if runner is None:
                    # fresh compile (bypass jax's persistent cache): a
                    # cache-loaded executable cannot be re-serialized
                    with _aot.bypass_persistent_cache():
                        compiled = self._jit_fwd.lower(
                            arg_vals, aux_vals, key, is_train).compile()
                    _aot.store("graph_fwd", digest, compiled)
                    runner = compiled
            except Exception:                               # noqa: BLE001
                import logging
                logging.getLogger(__name__).warning(
                    "aot: forward warm-start setup failed; using jit",
                    exc_info=True)
                runner = False
        self._aot_fwd[vkey] = runner
        return runner

    @property
    def requires_sync_loop(self) -> bool:
        """True when programs from this executor must execute synchronously
        with the frontend (host-callback CustomOps — the PR 2 async-drain
        deadlock). The fit loop consults this to force
        ``MXNET_TPU_ASYNC_WINDOW=0`` behavior and skip device prefetch:
        background jax dispatch concurrent with a callback-bearing program
        is exactly the deadlock shape."""
        return self._sync_host_callbacks

    @staticmethod
    def _forced_sync(values) -> None:
        """Block on ``values`` because the program carries host callbacks —
        the one sync the async loop can never remove, counted so tests and
        the analysis self-check can see it (``loop_forced_sync``)."""
        from . import profiler as _profiler
        _profiler.incr_counter("loop_forced_sync")
        jax.block_until_ready(values)

    # ------------------------------------------------------------ scan
    def _build_scan_plan(self, _config):
        """Scan-over-layers (MXNET_TPU_SCAN_LAYERS, default auto): detect
        a repeated homogeneous chain and lower it through one
        ``jax.lax.scan`` so bind time stops growing with depth. Detection
        that does not verify falls back to the unrolled path silently;
        ``scan_applied``/``scan_layers`` report what happened."""
        if self._group2ctx:
            return None
        mode = _config.get("MXNET_TPU_SCAN_LAYERS")
        if mode == "off":
            return None
        from .symbol.scan import DEFAULT_MIN_REPEAT, build_scan_plan
        min_repeat = DEFAULT_MIN_REPEAT if mode == "auto" else int(mode)
        shapes = {n: tuple(a.shape) for n, a in self.arg_dict.items()}
        shapes.update({n: tuple(a.shape)
                       for n, a in self.aux_dict.items()})
        dtypes = {n: a.dtype for n, a in self.arg_dict.items()}
        dtypes.update({n: a.dtype for n, a in self.aux_dict.items()})
        plan = build_scan_plan(self._symbol, min_repeat=min_repeat,
                               shapes=shapes, dtypes=dtypes)
        from . import profiler as _profiler
        if plan is not None:
            _profiler.incr_counter("scan_applied")
            _profiler.set_gauge("scan_layers", plan.n_layers)
            # applied remat at block granularity: wrapping the scan body
            # in jax.checkpoint IS the "wrap each repeated block" form
            # the analysis remat-opportunity suggestion prescribes
            if _config.get("MXNET_TPU_REMAT") != "off" or \
                    _config.get("MXNET_EXEC_ENABLE_REMAT"):
                from . import remat as _remat
                policy, name = _remat.resolve_policy(
                    self._symbol, input_shapes=shapes,
                    input_dtypes=dtypes)
                if policy is not None:
                    import jax as _jax
                    plan.body_wrapper = (
                        lambda body: _jax.checkpoint(body, policy=policy))
                    self._remat_name = name
                    _profiler.incr_counter("remat_applied")
        return plan

    # ------------------------------------------------------------ placement
    def _node_device_fn(self):
        """Node -> jax device from its ctx_group (None without group2ctx)."""
        if not self._group2ctx:
            return None
        group2ctx = self._group2ctx
        default = self._ctx

        def node_device(node):
            g = node.str_attrs.get("ctx_group")
            ctx = group2ctx.get(g, default) if g else default
            return ctx.jax_device

        return node_device

    # ------------------------------------------------------------ shardings
    def _arg_shardings(self):
        """group2ctx → per-argument SingleDeviceSharding (the PlaceDevice
        pass, reference graph_executor.cc:279-393)."""
        if not self._group2ctx:
            return None
        from .symbol.symbol import _topo_order
        from jax.sharding import SingleDeviceSharding

        group_of: Dict[str, str] = {}
        for node in _topo_order(self._symbol._entries):
            g = node.str_attrs.get("ctx_group")
            if not g:
                continue
            if node.is_variable:
                group_of.setdefault(node.name, g)
            else:
                for src, _ in node.inputs:
                    if src.is_variable:
                        group_of.setdefault(src.name, g)

        def dev_for(name):
            g = group_of.get(name)
            ctx = self._group2ctx.get(g, self._ctx) if g else self._ctx
            return SingleDeviceSharding(ctx.jax_device)

        arg_sh = {n: dev_for(n) for n in self._arg_names}
        aux_sh = {n: dev_for(n) for n in self._aux_names}
        return arg_sh, aux_sh

    # ------------------------------------------------------------ running
    def _gather(self):
        arg_vals = {n: a.data for n, a in self.arg_dict.items()}
        aux_vals = {n: a.data for n, a in self.aux_dict.items()}
        self._step += 1
        key = jax.random.fold_in(self._base_key, self._step)
        return arg_vals, aux_vals, key

    def forward(self, is_train: bool = False, **kwargs) -> List[_nd.NDArray]:
        """(reference: GraphExecutor::Forward, graph_executor.cc:50). With
        ``is_train=True`` the computation is deferred so ``backward`` can run
        the fused forward+backward program once."""
        for k, v in kwargs.items():
            if k not in self.arg_dict:
                raise MXNetError("forward: unknown argument %r" % k)
            self.arg_dict[k]._data = v.data if isinstance(v, _nd.NDArray) \
                else jnp.asarray(v)
            self.arg_dict[k]._version += 1
        arg_vals, aux_vals, key = self._gather()
        self._last_is_train = bool(is_train)
        if is_train and self._wrt:
            # deferred: backward() runs the fused fwd+bwd once; forcing
            # outputs here (e.g. for a monitor) would double the forward
            self._pending = (arg_vals, aux_vals, key)
            self._outputs = None
        else:
            with _obs_compiles.scope(self._obs_label):
                outs, new_aux = self._dispatch_fwd(arg_vals, aux_vals,
                                                   key, is_train)
            if self._sync_host_callbacks:
                self._forced_sync(outs)
            self._commit(outs, new_aux)
            self._pending = None
        return self.outputs

    def backward(self, out_grads=None) -> None:
        """(reference: GraphExecutor::Backward, graph_executor.cc:63).
        Runs the fused forward+backward program; gradients are committed to
        ``grad_dict`` honoring grad_req write/add (kAddTo semantics,
        include/mxnet/op_attr_types.h:45-58)."""
        if self._pending is None:
            raise MXNetError("backward called without forward(is_train=True)")
        arg_vals, aux_vals, key = self._pending
        if out_grads is None:
            heads = [None] * len(self._output_names)
        elif isinstance(out_grads, (list, tuple)):
            heads = [g.data if isinstance(g, _nd.NDArray) else jnp.asarray(g)
                     for g in out_grads]
        else:
            heads = [out_grads.data if isinstance(out_grads, _nd.NDArray)
                     else jnp.asarray(out_grads)]
        from . import profiler as _profiler
        if _profiler.state() == "run":
            import time as _time
            _t0 = _time.perf_counter()
            with _obs_compiles.scope(self._obs_label):
                outs, new_aux, grads = self._jit_fwd_bwd(arg_vals, aux_vals,
                                                         key, heads)
            jax.block_until_ready(outs)
            _profiler.record_event("graph_fwd_bwd", _t0,
                                   _time.perf_counter(), "graph")
        else:
            with _obs_compiles.scope(self._obs_label):
                outs, new_aux, grads = self._jit_fwd_bwd(arg_vals, aux_vals,
                                                         key, heads)
        if self._sync_host_callbacks:
            self._forced_sync((outs, grads))
        self._commit(outs, new_aux)
        self._pending = None
        for n, g in grads.items():
            req = self._grad_req.get(n, "null")
            buf = self.grad_dict.get(n)
            if buf is None or req == "null":
                continue
            if req == "add":
                buf._data = buf.data + g.astype(buf.dtype)
            else:
                buf._data = g.astype(buf.dtype)
            buf._version += 1

    def _commit(self, outs, new_aux):
        self._outputs = [_nd.NDArray(o) for o in outs]
        for n, v in new_aux.items():
            a = self.aux_dict[n]
            a._data = v
            a._version += 1
        # monitor fires when real outputs materialize — deduped by step so
        # a forward-then-backward pair (two commits of the same step)
        # reports once
        if self._monitor_callback and \
                getattr(self, "_mon_step", -1) != self._step:
            self._mon_step = self._step
            self._run_monitor()

    @property
    def outputs(self) -> List[_nd.NDArray]:
        """(reference: executor.h outputs). Computes lazily if a deferred
        training forward is pending."""
        if self._outputs is None and self._pending is not None:
            arg_vals, aux_vals, key = self._pending
            with _obs_compiles.scope(self._obs_label):
                outs, new_aux = self._dispatch_fwd(arg_vals, aux_vals,
                                                   key, True)
            if self._sync_host_callbacks:
                self._forced_sync(outs)
            self._commit(outs, new_aux)
        if self._outputs is None:
            raise MXNetError("no forward has been run")
        return self._outputs

    @property
    def arg_arrays(self) -> List[_nd.NDArray]:
        return [self.arg_dict[n] for n in self._arg_names]

    @property
    def grad_arrays(self) -> List[Optional[_nd.NDArray]]:
        return [self.grad_dict.get(n) for n in self._arg_names]

    @property
    def aux_arrays(self) -> List[_nd.NDArray]:
        return [self.aux_dict[n] for n in self._aux_names]

    @property
    def output_dict(self) -> Dict[str, _nd.NDArray]:
        return dict(zip(self._output_names, self.outputs))

    def copy_params_from(self, arg_params: Dict[str, _nd.NDArray],
                         aux_params: Optional[Dict[str, _nd.NDArray]] = None,
                         allow_extra_params: bool = False) -> None:
        """(reference: executor.py copy_params_from)."""
        for k, v in arg_params.items():
            if k in self.arg_dict:
                v.copyto(self.arg_dict[k])
            elif not allow_extra_params:
                raise MXNetError("unknown parameter %r" % k)
        if aux_params:
            for k, v in aux_params.items():
                if k in self.aux_dict:
                    v.copyto(self.aux_dict[k])
                elif not allow_extra_params:
                    raise MXNetError("unknown aux state %r" % k)

    def reshape(self, partial_shaping=False, allow_up_sizing=False, **kwargs):
        """Return a new executor bound to new shapes (reference: executor.py
        reshape). jit re-specializes per shape automatically; parameters are
        shared by reference."""
        arg_shapes, _, aux_shapes = self._symbol.infer_shape(**kwargs)
        new_args = {}
        for n, s in zip(self._arg_names, arg_shapes):
            cur = self.arg_dict[n]
            if tuple(cur.shape) == tuple(s):
                new_args[n] = cur
            else:
                new_args[n] = _nd.NDArray(np.zeros(s, dtype=cur.dtype),
                                          ctx=self._ctx)
        new_grads = None
        if self.grad_dict:
            new_grads = {}
            for n in self.grad_dict:
                s = arg_shapes[self._arg_names.index(n)]
                cur = self.grad_dict[n]
                new_grads[n] = cur if tuple(cur.shape) == tuple(s) else \
                    _nd.NDArray(np.zeros(s, dtype=cur.dtype), ctx=self._ctx)
        new_aux = {}
        for n, s in zip(self._aux_names, aux_shapes):
            cur = self.aux_dict[n]
            new_aux[n] = cur if tuple(cur.shape) == tuple(s) else \
                _nd.NDArray(np.zeros(s, dtype=cur.dtype), ctx=self._ctx)
        return Executor(self._symbol, self._ctx, new_args, new_grads,
                        self._grad_req, new_aux, group2ctx=self._group2ctx,
                        shared_exec=self)

    # ------------------------------------------------------------ monitor
    def monitor_values(self):
        """Eagerly interpret the graph with the current bindings, yielding
        (node_output_name, NDArray) for EVERY node — the per-op stat tap
        the reference's MonitorExecution installs on each engine op
        (src/executor/graph_executor.cc monitor_callback_). Debug path:
        runs outside the fused jit with the SAME per-node dispatch
        (_run_node) and the last forward's is_train/RNG key; aux states
        reflect the post-commit values (approximate for BatchNorm moving
        stats, exact for everything else)."""
        from .symbol.symbol import _topo_order
        nodes = _topo_order(self._symbol._entries)
        key = jax.random.fold_in(self._base_key, self._step)
        is_train = getattr(self, "_last_is_train", True)
        node_device = self._node_device_fn()
        vals = {}
        for idx, node in enumerate(nodes):
            if node.is_variable:
                src_nd = self.arg_dict.get(node.name)
                if src_nd is None:
                    src_nd = self.aux_dict.get(node.name)
                vals[(id(node), 0)] = src_nd.data
                continue
            ins = [vals[(id(n), i)] for n, i in node.inputs]
            outs = _run_node(node, ins, key, idx, is_train, node_device)
            for i, o in enumerate(outs):
                vals[(id(node), i)] = o
                suffix = "_output" if len(outs) == 1 else "_output%d" % i
                yield node.name + suffix, _nd.NDArray(o)

    def set_monitor_callback(self, callback) -> None:
        """(reference: MXExecutorSetMonitorCallback / Monitor support —
        graph_executor.cc:1209 ExecuteMonCallback). Called as
        callback(name, NDArray) for every output after each forward."""
        self._monitor_callback = callback

    def _run_monitor(self):
        for name, arr in zip(self._output_names, self.outputs):
            self._monitor_callback(name, arr)

    def debug_str(self) -> str:
        from .symbol.symbol import _topo_order
        lines = ["Symbol outputs: %s" % ", ".join(self._output_names)]
        for node in _topo_order(self._symbol._entries):
            kind = "var" if node.is_variable else node.op.name
            lines.append("  %-20s %s" % (kind, node.name))
        return "\n".join(lines)

"""Evaluation metrics.

Reference: ``python/mxnet/metric.py`` (1,132 LoC: registry + Accuracy:339,
TopKAccuracy:404, F1:478, Perplexity:573, MAE/MSE/RMSE:678-795,
CrossEntropy:854, PearsonCorrelation:923, Loss, CustomMetric:1020,
CompositeEvalMetric:209).

Device-resident accumulation (docs/architecture/async_loop.md): the
reference's ``update`` pulls every prediction to the host (``asnumpy`` — a
full device sync per batch), which serializes the training pipeline behind
host round-trips. Metrics that decompose into ``(sum, count)`` pairs
additionally implement ``_device_reduce``: ``update_device`` then chains
ONE tiny jitted reduction after the train step, accumulating into device
scalars, and the host sync is deferred to ``get()`` — the Speedometer /
epoch-end log boundary. Metrics that cannot (``CustomMetric``, ``F1``,
mixed ``CompositeEvalMetric``) report ``device_capable() == False`` and the
loop falls back to the per-batch host path automatically.
"""
from __future__ import annotations

import math
from typing import Dict, List, Optional, Sequence, Union

import numpy as numpy_mod

from .ndarray import NDArray
from . import profiler as _profiler

__all__ = ["EvalMetric", "CompositeEvalMetric", "Accuracy", "TopKAccuracy",
           "F1", "Perplexity", "MAE", "MSE", "RMSE", "CrossEntropy",
           "PearsonCorrelation", "Loss", "Torch", "Caffe", "CustomMetric",
           "np", "create", "register"]

_METRIC_REGISTRY: Dict[str, type] = {}
# (metric class, statics) -> jitted device accumulate, shared across
# instances; bounded in practice by the handful of metric configurations
# a process uses
_DEV_ACC_CACHE: Dict[tuple, object] = {}


def register(klass):
    _METRIC_REGISTRY[klass.__name__.lower()] = klass
    return klass


def create(metric, *args, **kwargs) -> "EvalMetric":
    """(reference: metric.py create — str name, callable, or list)."""
    if callable(metric):
        return CustomMetric(metric, *args, **kwargs)
    if isinstance(metric, EvalMetric):
        return metric
    if isinstance(metric, (list, tuple)):
        composite = CompositeEvalMetric()
        for m in metric:
            composite.add(create(m, *args, **kwargs))
        return composite
    name = str(metric).lower()
    aliases = {"acc": "accuracy", "ce": "crossentropy",
               "top_k_accuracy": "topkaccuracy", "top_k_acc": "topkaccuracy"}
    name = aliases.get(name, name)
    if name not in _METRIC_REGISTRY:
        raise ValueError("Metric must be either callable or in %s; got %s"
                         % (sorted(_METRIC_REGISTRY), metric))
    return _METRIC_REGISTRY[name](*args, **kwargs)


def _as_np(x) -> numpy_mod.ndarray:
    return x.asnumpy() if isinstance(x, NDArray) else numpy_mod.asarray(x)


def check_label_shapes(labels, preds, shape=0):
    if shape == 0:
        label_shape, pred_shape = len(labels), len(preds)
    else:
        label_shape, pred_shape = labels.shape, preds.shape
    if label_shape != pred_shape:
        raise ValueError("Shape of labels %s does not match shape of "
                         "predictions %s" % (label_shape, pred_shape))


def _as_device(x):
    """Raw jax array view of a label/pred — no transfer when it already
    lives on device (the fit loop hands over the step's own arrays)."""
    import jax.numpy as jnp
    return x.data if isinstance(x, NDArray) else jnp.asarray(x)


class EvalMetric(object):
    """Base metric (reference: metric.py EvalMetric)."""

    def __init__(self, name, output_names=None, label_names=None, **kwargs):
        self.name = name
        self.output_names = output_names
        self.label_names = label_names
        self._kwargs = kwargs
        self._dev_fn = None
        self.reset()

    def update_dict(self, label: Dict, pred: Dict):
        if self.output_names is not None:
            pred = [pred[name] for name in self.output_names]
        else:
            pred = list(pred.values())
        if self.label_names is not None:
            label = [label[name] for name in self.label_names]
        else:
            label = list(label.values())
        self.update(label, pred)

    def update(self, labels, preds):
        raise NotImplementedError

    # ------------------------------------------------- device-resident path
    # Subclasses that decompose into (sum, count) set _device_capable and
    # implement _device_reduce(label, pred) -> (sum, count) in jnp ops
    # mirroring their host update arithmetic. _device_statics() must list
    # every instance attribute the reduce reads, so the jitted accumulate
    # can be shared across instances (fit() creates a fresh metric per
    # call — a per-instance cache would recompile every epoch).
    _device_capable = False

    def _device_reduce(self, label, pred):
        raise NotImplementedError

    def _device_statics(self) -> tuple:
        return ()

    def device_capable(self) -> bool:
        """True when this metric can accumulate on-device (and the
        MXNET_TPU_DEVICE_METRICS knob is on) — queried by the fit loop
        BEFORE updating so mixed composites fall back atomically."""
        if not self._device_capable:
            return False
        from . import config as _config
        return bool(_config.get("MXNET_TPU_DEVICE_METRICS"))

    def _device_acc(self):
        """Jitted chained accumulate: (acc_sum, acc_num, label, pred) ->
        (acc_sum', acc_num'). One tiny device program per batch, no host
        sync; cached per (class, statics) so every same-configured
        instance shares one compiled accumulate."""
        if self._dev_fn is None:
            key = (type(self), self._device_statics())
            fn = _DEV_ACC_CACHE.get(key)
            if fn is None:
                import copy
                import jax
                import jax.numpy as jnp
                # the closure must capture a SNAPSHOT, not self: the cache
                # outlives this instance, and a later retrace (new input
                # shape) would otherwise read the donor's *current*
                # attributes — wrong if they drifted from the cache key
                snap = copy.copy(self)

                def acc(acc_s, acc_n, label, pred):
                    s, n = snap._device_reduce(label, pred)
                    # counts are integral: a float32 accumulator stops
                    # incrementing past 2^24 samples between syncs
                    return (acc_s + jnp.asarray(s, jnp.float32),
                            acc_n + jnp.asarray(n, jnp.int32))

                fn = jax.jit(acc)
                _DEV_ACC_CACHE[key] = fn
            self._dev_fn = fn
        return self._dev_fn

    def update_device(self, labels, preds) -> bool:
        """Accumulate this batch as a device reduction chained after the
        step. Returns False (and touches nothing) when the metric cannot —
        the caller must then run the host ``update`` path."""
        if not self.device_capable():
            return False
        if labels is not None and not isinstance(labels, (list, tuple)):
            labels = [labels]
        if not isinstance(preds, (list, tuple)):
            preds = [preds]
        check_label_shapes(labels, preds)
        if self._dev_acc_state is None:
            import jax.numpy as jnp
            self._dev_acc_state = (jnp.zeros((), jnp.float32),
                                   jnp.zeros((), jnp.int32))
        acc_s, acc_n = self._dev_acc_state
        fn = self._device_acc()
        try:
            for label, pred in zip(labels, preds):
                acc_s, acc_n = fn(acc_s, acc_n, _as_device(label),
                                  _as_device(pred))
        except Exception:                                  # noqa: BLE001
            # trace-time refusal (shape/dtype this reduce can't express):
            # nothing was committed — the host path runs instead and
            # raises its own (clearer) error if the batch is truly bad
            return False
        self._dev_acc_state = (acc_s, acc_n)
        return True

    def update_dict_device(self, label: Dict, pred: Dict) -> bool:
        """``update_dict`` twin for the device path; same name selection."""
        if not self.device_capable():
            return False
        if self.output_names is not None:
            pred = [pred[name] for name in self.output_names]
        else:
            pred = list(pred.values())
        if self.label_names is not None:
            label = [label[name] for name in self.label_names]
        else:
            label = list(label.values())
        return self.update_device(label, pred)

    def _sync_device(self):
        """Fold the device accumulators into the host totals — THE deferred
        sync point (one per get()/log boundary, counted)."""
        if self._dev_acc_state is None:
            return
        acc_s, acc_n = self._dev_acc_state
        self._dev_acc_state = None
        _profiler.incr_counter("loop_metric_sync")
        self.sum_metric += float(acc_s)
        self.num_inst += int(acc_n)

    def reset(self):
        self.num_inst = 0
        self.sum_metric = 0.0
        self._dev_acc_state = None

    # ---------------------------------------------------- checkpoint state
    def _ckpt_state(self):
        """JSON-able accumulator snapshot for mid-epoch checkpoints
        (mx.checkpoint). Folds any device accumulator into the host totals
        first — the checkpoint boundary is a sync point anyway — so the
        scalar pair is the COMPLETE state for every (sum, count) metric."""
        self._sync_device()
        return {"kind": "scalar", "name": self.name,
                "sum_metric": float(self.sum_metric),
                "num_inst": int(self.num_inst)}

    def _ckpt_restore(self, state) -> bool:
        """Inverse of :meth:`_ckpt_state`; returns False (leaving the
        freshly-reset metric untouched) on a shape it can't consume, so a
        resumed fit degrades to epoch-start totals instead of crashing."""
        if not isinstance(state, dict) or state.get("kind") != "scalar":
            return False
        self.reset()
        self.sum_metric = float(state["sum_metric"])
        self.num_inst = int(state["num_inst"])
        return True

    def get(self):
        self._sync_device()
        if self.num_inst == 0:
            return (self.name, float("nan"))
        return (self.name, self.sum_metric / self.num_inst)

    def get_name_value(self):
        name, value = self.get()
        if not isinstance(name, list):
            name = [name]
        if not isinstance(value, list):
            value = [value]
        return list(zip(name, value))

    def __str__(self):
        return "EvalMetric: {}".format(dict(self.get_name_value()))


@register
class CompositeEvalMetric(EvalMetric):
    """(reference: metric.py:209)."""

    def __init__(self, metrics=None, name="composite", output_names=None,
                 label_names=None):
        super().__init__(name, output_names, label_names)
        self.metrics = [create(m) for m in metrics] if metrics else []

    def add(self, metric):
        self.metrics.append(create(metric))

    def get_metric(self, index):
        return self.metrics[index]

    def update(self, labels, preds):
        for metric in self.metrics:
            metric.update(labels, preds)

    def device_capable(self) -> bool:
        """A composite is device-capable only when EVERY child is — a mixed
        set falls back to the host path as one unit, so children never see
        a batch twice."""
        return bool(self.metrics) and \
            all(m.device_capable() for m in self.metrics)

    def update_device(self, labels, preds) -> bool:
        if not self.device_capable():
            return False
        for metric in self.metrics:
            if not metric.update_device(labels, preds):
                # a child refused mid-flight (shape it can't reduce):
                # keep totals consistent by host-updating it — a REAL
                # per-batch device round-trip, so count it where the fit
                # loop can't see it (update_device returned True)
                _profiler.incr_counter("loop_host_sync")
                metric.update(labels, preds)
        return True

    def reset(self):
        for metric in getattr(self, "metrics", []):
            metric.reset()

    def _ckpt_state(self):
        return {"kind": "composite",
                "children": [m._ckpt_state() for m in self.metrics]}

    def _ckpt_restore(self, state) -> bool:
        if not isinstance(state, dict) or state.get("kind") != "composite":
            return False
        children = state.get("children") or []
        if len(children) != len(self.metrics):
            return False
        restored = [m._ckpt_restore(s)
                    for m, s in zip(self.metrics, children)]
        if all(restored):
            return True
        # all-or-nothing: a half-restored composite (one child carrying
        # full-epoch totals, the next tail-only) reports internally
        # inconsistent metrics — on any child failure reset them ALL back
        # to the tail-only state the caller's warning describes
        for m in self.metrics:
            m.reset()
        return False

    def get(self):
        names, values = [], []
        for metric in self.metrics:
            name, value = metric.get()
            names.extend(name if isinstance(name, list) else [name])
            values.extend(value if isinstance(value, list) else [value])
        return (names, values)


@register
class Accuracy(EvalMetric):
    """(reference: metric.py:339). axis: class axis of predictions."""

    _device_capable = True

    def __init__(self, axis=1, name="accuracy", output_names=None,
                 label_names=None):
        super().__init__(name, output_names, label_names)
        self.axis = axis

    def update(self, labels, preds):
        check_label_shapes(labels, preds)
        for label, pred in zip(labels, preds):
            label, pred = _as_np(label), _as_np(pred)
            if pred.ndim > label.ndim:
                pred = numpy_mod.argmax(pred, axis=self.axis)
            pred = pred.astype(numpy_mod.int32).flatten()
            label = label.astype(numpy_mod.int32).flatten()
            check_label_shapes(label, pred, shape=1)
            self.sum_metric += (pred == label).sum()
            self.num_inst += len(label)

    def _device_reduce(self, label, pred):
        import jax.numpy as jnp
        if pred.ndim > label.ndim:
            pred = jnp.argmax(pred, axis=self.axis)
        pred = pred.astype(jnp.int32).ravel()
        label = label.astype(jnp.int32).ravel()
        check_label_shapes(label, pred, shape=1)
        return (pred == label).sum(), label.size

    def _device_statics(self):
        return (self.axis,)


@register
class TopKAccuracy(EvalMetric):
    """(reference: metric.py:404)."""

    _device_capable = True

    def __init__(self, top_k=1, name="top_k_accuracy", output_names=None,
                 label_names=None):
        super().__init__(name, output_names, label_names)
        self.top_k = top_k
        assert self.top_k > 1, "Use Accuracy if top_k is no more than 1"
        self.name += "_%d" % self.top_k

    def update(self, labels, preds):
        check_label_shapes(labels, preds)
        for label, pred in zip(labels, preds):
            label, pred = _as_np(label), _as_np(pred)
            assert pred.ndim == 2, "Predictions should be 2 dims"
            # stable sort: jnp.argsort (the device reduce) is stable, and
            # numpy's default introsort breaks ties differently — tied
            # scores would then make host and device top-k disagree
            pred = numpy_mod.argsort(pred.astype(numpy_mod.float32), axis=1,
                                     kind="stable")
            label = label.astype(numpy_mod.int32)
            num_samples, num_classes = pred.shape
            top_k = min(num_classes, self.top_k)
            # one membership test over the top_k highest-score columns
            # (argsort ascending, so the last top_k) — a label matches at
            # most one distinct column, identical to the per-column loop
            top = pred[:, num_classes - top_k:]
            self.sum_metric += (
                top == label.reshape(-1, 1)).sum()
            self.num_inst += num_samples

    def _device_reduce(self, label, pred):
        import jax.numpy as jnp
        assert pred.ndim == 2, "Predictions should be 2 dims"
        order = jnp.argsort(pred.astype(jnp.float32), axis=1)
        label = label.astype(jnp.int32).reshape(-1, 1)
        num_samples, num_classes = order.shape
        top_k = min(num_classes, self.top_k)
        hits = (order[:, num_classes - top_k:] == label).sum()
        return hits, num_samples

    def _device_statics(self):
        return (self.top_k,)


@register
class F1(EvalMetric):
    """Binary F1 (reference: metric.py:478)."""

    def __init__(self, name="f1", output_names=None, label_names=None):
        super().__init__(name, output_names, label_names)

    def update(self, labels, preds):
        check_label_shapes(labels, preds)
        for label, pred in zip(labels, preds):
            pred = _as_np(pred)
            label = _as_np(label).astype(numpy_mod.int32)
            pred_label = numpy_mod.argmax(pred, axis=1)
            check_label_shapes(label.flatten(), pred_label.flatten(), shape=1)
            if len(numpy_mod.unique(label)) > 2:
                raise ValueError("F1 currently only supports binary "
                                 "classification.")
            tp = numpy_mod.sum((pred_label == 1) & (label.flatten() == 1))
            fp = numpy_mod.sum((pred_label == 1) & (label.flatten() == 0))
            fn = numpy_mod.sum((pred_label == 0) & (label.flatten() == 1))
            precision = tp / (tp + fp) if tp + fp > 0 else 0.0
            recall = tp / (tp + fn) if tp + fn > 0 else 0.0
            if precision + recall > 0:
                f1 = 2 * precision * recall / (precision + recall)
            else:
                f1 = 0.0
            self.sum_metric += f1
            self.num_inst += 1


@register
class Perplexity(EvalMetric):
    """(reference: metric.py:573)."""

    _device_capable = True

    def __init__(self, ignore_label=None, axis=-1, name="perplexity",
                 output_names=None, label_names=None):
        super().__init__(name, output_names, label_names)
        self.ignore_label = ignore_label
        self.axis = axis

    def _device_reduce(self, label, pred):
        import jax.numpy as jnp
        assert label.size == pred.size / pred.shape[-1], \
            "shape mismatch: %s vs. %s" % (label.shape, pred.shape)
        label = label.reshape(-1).astype(jnp.int32)
        probs = jnp.take_along_axis(
            pred.reshape(-1, pred.shape[-1]), label[:, None], axis=1)[:, 0]
        num = label.size
        if self.ignore_label is not None:
            ignore = (label == self.ignore_label)
            probs = jnp.where(ignore, 1.0, probs)
            num = num - ignore.sum()
        loss = -jnp.sum(jnp.log(jnp.maximum(1e-10, probs)))
        return loss, num

    def _device_statics(self):
        return (self.ignore_label, self.axis)

    def update(self, labels, preds):
        assert len(labels) == len(preds)
        loss, num = 0.0, 0
        for label, pred in zip(labels, preds):
            label, pred = _as_np(label), _as_np(pred)
            assert label.size == pred.size / pred.shape[-1], \
                "shape mismatch: %s vs. %s" % (label.shape, pred.shape)
            label = label.reshape(-1).astype(numpy_mod.int64)
            probs = pred.reshape(-1, pred.shape[-1])[
                numpy_mod.arange(label.size), label]
            if self.ignore_label is not None:
                ignore = (label == self.ignore_label)
                probs = numpy_mod.where(ignore, 1.0, probs)
                num -= int(ignore.sum())
            loss -= float(numpy_mod.sum(numpy_mod.log(numpy_mod.maximum(1e-10, probs))))
            num += label.size
        self.sum_metric += loss
        self.num_inst += num

    def get(self):
        self._sync_device()
        if self.num_inst == 0:
            return (self.name, float("nan"))
        return (self.name, math.exp(self.sum_metric / self.num_inst))


@register
class MAE(EvalMetric):
    """(reference: metric.py:678)."""

    _device_capable = True

    def __init__(self, name="mae", output_names=None, label_names=None):
        super().__init__(name, output_names, label_names)

    def update(self, labels, preds):
        check_label_shapes(labels, preds)
        for label, pred in zip(labels, preds):
            label, pred = _as_np(label), _as_np(pred)
            if len(label.shape) == 1:
                label = label.reshape(label.shape[0], 1)
            self.sum_metric += numpy_mod.abs(label - pred).mean()
            self.num_inst += 1

    def _device_reduce(self, label, pred):
        import jax.numpy as jnp
        if label.ndim == 1:
            label = label.reshape(label.shape[0], 1)
        return jnp.abs(label - pred).mean(), 1


@register
class MSE(EvalMetric):
    """(reference: metric.py:717)."""

    _device_capable = True

    def __init__(self, name="mse", output_names=None, label_names=None):
        super().__init__(name, output_names, label_names)

    def update(self, labels, preds):
        check_label_shapes(labels, preds)
        for label, pred in zip(labels, preds):
            label, pred = _as_np(label), _as_np(pred)
            if len(label.shape) == 1:
                label = label.reshape(label.shape[0], 1)
            self.sum_metric += ((label - pred) ** 2.0).mean()
            self.num_inst += 1

    def _device_reduce(self, label, pred):
        import jax.numpy as jnp
        if label.ndim == 1:
            label = label.reshape(label.shape[0], 1)
        return ((label - pred) ** 2.0).mean(), 1


@register
class RMSE(EvalMetric):
    """(reference: metric.py:756)."""

    _device_capable = True

    def __init__(self, name="rmse", output_names=None, label_names=None):
        super().__init__(name, output_names, label_names)

    def update(self, labels, preds):
        check_label_shapes(labels, preds)
        for label, pred in zip(labels, preds):
            label, pred = _as_np(label), _as_np(pred)
            if len(label.shape) == 1:
                label = label.reshape(label.shape[0], 1)
            self.sum_metric += numpy_mod.sqrt(((label - pred) ** 2.0).mean())
            self.num_inst += 1

    def _device_reduce(self, label, pred):
        import jax.numpy as jnp
        if label.ndim == 1:
            label = label.reshape(label.shape[0], 1)
        return jnp.sqrt(((label - pred) ** 2.0).mean()), 1


@register
class CrossEntropy(EvalMetric):
    """(reference: metric.py:854)."""

    _device_capable = True

    def __init__(self, eps=1e-12, name="cross-entropy", output_names=None,
                 label_names=None):
        super().__init__(name, output_names, label_names)
        self.eps = eps

    def update(self, labels, preds):
        check_label_shapes(labels, preds)
        for label, pred in zip(labels, preds):
            label, pred = _as_np(label), _as_np(pred)
            label = label.ravel()
            assert label.shape[0] == pred.shape[0]
            prob = pred[numpy_mod.arange(label.shape[0]), numpy_mod.int64(label)]
            self.sum_metric += (-numpy_mod.log(prob + self.eps)).sum()
            self.num_inst += label.shape[0]

    def _device_reduce(self, label, pred):
        import jax.numpy as jnp
        label = label.ravel().astype(jnp.int32)
        assert label.shape[0] == pred.shape[0]
        prob = jnp.take_along_axis(pred, label[:, None], axis=1)[:, 0]
        return (-jnp.log(prob + self.eps)).sum(), label.shape[0]

    def _device_statics(self):
        return (self.eps,)


@register
class PearsonCorrelation(EvalMetric):
    """(reference: metric.py:923)."""

    def __init__(self, name="pearsonr", output_names=None, label_names=None):
        super().__init__(name, output_names, label_names)

    def update(self, labels, preds):
        check_label_shapes(labels, preds)
        for label, pred in zip(labels, preds):
            label, pred = _as_np(label), _as_np(pred)
            check_label_shapes(label, pred, 1)
            self.sum_metric += numpy_mod.corrcoef(pred.ravel(), label.ravel())[0, 1]
            self.num_inst += 1


@register
class Loss(EvalMetric):
    """Mean of the raw outputs — for loss symbols (reference: metric.py Loss)."""

    _device_capable = True

    def __init__(self, name="loss", output_names=None, label_names=None):
        super().__init__(name, output_names, label_names)

    def update(self, _, preds):
        for pred in preds:
            pred = _as_np(pred)
            self.sum_metric += pred.sum()
            self.num_inst += pred.size

    def _device_reduce(self, label, pred):
        return pred.sum(), pred.size

    def update_device(self, labels, preds) -> bool:
        # labels are ignored (and may be absent/mismatched) — feed the
        # preds through the base accumulator with dummy labels
        if not self.device_capable():
            return False
        if not isinstance(preds, (list, tuple)):
            preds = [preds]
        return super().update_device(list(preds), list(preds))


@register
class Torch(Loss):
    """(reference: metric.py Torch — mean of outputs, legacy name)."""

    def __init__(self, name="torch", output_names=None, label_names=None):
        super().__init__(name, output_names, label_names)


@register
class Caffe(Torch):
    def __init__(self, name="caffe", output_names=None, label_names=None):
        super().__init__(name, output_names, label_names)


@register
class CustomMetric(EvalMetric):
    """Wrap ``feval(label, pred) -> float`` (reference: metric.py:1020)."""

    def __init__(self, feval, name=None, allow_extra_outputs=False,
                 output_names=None, label_names=None):
        if name is None:
            name = feval.__name__
            if name.find("<") != -1:
                name = "custom(%s)" % name
        super().__init__(name, output_names, label_names)
        self._feval = feval
        self._allow_extra_outputs = allow_extra_outputs

    def update(self, labels, preds):
        if not self._allow_extra_outputs:
            check_label_shapes(labels, preds)
        for label, pred in zip(labels, preds):
            label, pred = _as_np(label), _as_np(pred)
            reval = self._feval(label, pred)
            if isinstance(reval, tuple):
                sum_metric, num_inst = reval
                self.sum_metric += sum_metric
                self.num_inst += num_inst
            else:
                self.sum_metric += reval
                self.num_inst += 1


def np(numpy_feval, name=None, allow_extra_outputs=False):
    """Create a CustomMetric from a numpy function (reference: metric.py np)."""

    def feval(label, pred):
        return numpy_feval(label, pred)

    feval.__name__ = numpy_feval.__name__
    return CustomMetric(feval, name, allow_extra_outputs)

"""Automatic mixed precision — bf16 compute with fp32 master weights.

Reference parity: the reference's fp16 story is "cast the symbol/data to
float16 and use SGD(multi_precision=True)" (python/mxnet/optimizer.py SGD
multi_precision; tests/python/train/test_dtype.py). On TPU the idiomatic
equivalent is bfloat16 *compute* with float32 *storage*: parameters and
optimizer state stay fp32, and the MXU-bound ops (Convolution,
FullyConnected, Deconvolution, fused RNN) cast their operands to bf16 at
trace time, accumulating in fp32 on the MXU (``preferred_element_type``).

This is a trace-time policy: set it before building jitted programs
(``Module.bind`` / ``init_optimizer`` / first ``HybridBlock`` call)::

    mx.amp.init("bfloat16")      # turn on for subsequently-built programs
    mx.amp.off()                  # back to full precision
    with mx.amp.scope("bfloat16"):
        ...                       # policy active within the block

Already-compiled programs are unaffected (XLA caches by shape/dtype, and
the policy is read when the graph is traced, not when it runs).
"""
from __future__ import annotations

from contextlib import contextmanager

import jax.numpy as jnp

__all__ = ["init", "off", "active", "compute_dtype", "cast_compute",
           "mxu_operands", "scope"]

_COMPUTE_DTYPE = None

_ALLOWED = ("bfloat16", "float16")


def init(dtype="bfloat16"):
    """Enable mixed precision: matmul/conv operands cast to ``dtype``."""
    global _COMPUTE_DTYPE
    name = jnp.dtype(dtype).name
    if name not in _ALLOWED:
        raise ValueError("amp compute dtype must be one of %s, got %r"
                         % (_ALLOWED, name))
    _COMPUTE_DTYPE = jnp.dtype(dtype)


def off():
    """Disable mixed precision for subsequently-traced programs."""
    global _COMPUTE_DTYPE
    _COMPUTE_DTYPE = None


def active() -> bool:
    return _COMPUTE_DTYPE is not None


def compute_dtype():
    """The low-precision compute dtype, or None when amp is off."""
    return _COMPUTE_DTYPE


def cast_compute(*arrays):
    """Cast float32 operands to the compute dtype (no-op when amp is off).

    Non-float32 operands (ints, already-low-precision floats, None bias)
    pass through untouched.
    """
    if _COMPUTE_DTYPE is None:
        return arrays if len(arrays) != 1 else arrays[0]
    out = tuple(a.astype(_COMPUTE_DTYPE)
                if a is not None and getattr(a, "dtype", None) == jnp.float32
                else a for a in arrays)
    return out if len(out) != 1 else out[0]


def mxu_operands(a, b, conv=False):
    """Cast two MXU operands under the amp policy and pick the accumulation
    request for ``lax.dot_general`` / ``lax.conv_general_dilated``.

    Returns ``(a, b, acc_kwargs)``. ``dot_general``'s transpose rule accepts
    a fp32 cotangent against low-precision operands, so bf16/fp16 matmuls
    always request fp32 accumulation explicitly. ``conv_general_dilated``'s
    transpose requires operand/cotangent dtypes to match, so convs request it
    only when the operands are fp32 — on TPU the MXU accumulates bf16
    products in fp32 natively either way, so this loses nothing on the
    target hardware (non-TPU backends may accumulate low-precision convs in
    the operand dtype).
    """
    a, b = cast_compute(a, b)
    rt = jnp.result_type(a, b)
    low = rt in (jnp.bfloat16, jnp.float16)
    if rt == jnp.float32 or (low and not conv):
        acc = {"preferred_element_type": jnp.float32}
    else:
        acc = {}
    return a, b, acc


@contextmanager
def scope(dtype="bfloat16"):
    """Context manager form of :func:`init`/:func:`off`."""
    global _COMPUTE_DTYPE
    prev = _COMPUTE_DTYPE
    init(dtype)
    try:
        yield
    finally:
        _COMPUTE_DTYPE = prev

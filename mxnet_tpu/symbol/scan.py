"""Scan-over-layers: lower repeated homogeneous blocks through
``jax.lax.scan`` so trace time and HLO size stop growing with depth.

The round-5 bench wedged 25 minutes inside one bind; the unrolled
graph_function traces every transformer layer separately, so both the
jaxpr and the XLA module grow linearly in depth (and XLA compile time
superlinearly). A 48-layer decoder is 48 copies of ONE block — this
module detects that repetition in the Symbol DAG and executes the chain
as a single ``lax.scan`` whose xs are the per-layer parameters stacked
on a leading axis: the block traces and compiles once, whatever the
depth.

Detection is structural (``MXNET_TPU_SCAN_LAYERS``, default ``auto``):

1. **Layer families** from parameter names: the framework auto-names
   per-layer parameters with the layer index embedded
   (``layer3_att_qkv_weight``), so variables whose names differ only in
   one integer position form an indexed family. All families must agree
   on one index set (the layer axis, 0..L-1).
2. **Segmentation**: a node belongs to layer *i* when the deepest layer
   family it transitively depends on is *i* — this places the
   auto-named residual adds (no index in their names) in the right
   block.
3. **Verification**: segments must be pairwise isomorphic — matched
   node-by-node from each block's single output backwards (same op,
   same attrs, same wiring), with exactly ONE streaming activation
   entering each block (the previous block's output), per-layer
   parameters mapping position-for-position with identical
   shapes/dtypes, and shared values (a causal mask computed once in the
   prefix, a weight shared by every block) being the *same* graph entry
   everywhere. The last raw segment also contains the suffix (final LN,
   head); it is trimmed by matching the template against it and
   splitting off the unmatched tail.

Anything that does not verify — heterogeneous blocks (ResNet stage
transitions), shared-weight RNN unrolls (one variable node in every
step leaves no per-layer family), cross-layer skip connections,
aux-state ops (BatchNorm) inside blocks, internal block outputs
consumed outside (``get_internals``) — silently falls back to the
unrolled path; falling back is always correct. The lowering is
bit-identical to unrolled execution (same op sequence per layer, RNG
keys folded with the same per-node topo indices, carried as scan xs),
which ``tests/test_scan_layers.py`` locks.

Supported inside blocks: multi-output ops (consumed within the block)
and ``needs_rng`` ops (Dropout — the per-node fold indices ride the
scan xs so dropout masks match the unrolled program exactly).
"""
from __future__ import annotations

import logging
import re
from typing import Any, Dict, List, Optional, Tuple

__all__ = ["ScanPlan", "build_scan_plan", "DEFAULT_MIN_REPEAT"]

log = logging.getLogger(__name__)

# auto mode only scans chains at least this deep: shallow stacks gain
# little compile time and keeping them unrolled narrows the blast
# radius of the transform (override: MXNET_TPU_SCAN_LAYERS=<int>)
DEFAULT_MIN_REPEAT = 4

# candidate out-node tries when trimming the suffix off the last raw
# segment (every residual add shares the out node's op)
_MAX_OUT_CANDIDATES = 8

_INT_RE = re.compile(r"\d+")


class ScanPlan:
    """Everything graph_function needs to run the repeated chain as one
    ``lax.scan``: the execution split (pre / scan / post), the template
    block's nodes, per-layer parameter stacks, and per-node topo
    indices (RNG parity with the unrolled program)."""

    __slots__ = (
        "n_layers", "template", "pre_nodes", "post_nodes",
        "stream_in", "out_idx", "var_lists", "tvar_names",
        "node_index", "scanned_ids", "final_out_key", "layer_table",
        "body_wrapper",
    )

    def __init__(self):
        self.n_layers = 0
        self.template: List[Any] = []        # seg-0 nodes, topo order
        self.pre_nodes: List[Any] = []       # nodes the scan depends on
        self.post_nodes: List[Any] = []      # nodes depending on it
        self.stream_in: Tuple[Any, int] = None   # entry feeding block 0
        self.out_idx = 0                     # block output's out index
        # id(template var node) -> [per-layer arg names, layer order]
        self.var_lists: Dict[int, List[str]] = {}
        self.tvar_names: Dict[int, str] = {}     # id -> template name
        self.node_index: Dict[int, int] = {}     # id(node) -> topo idx
        self.scanned_ids: set = set()
        # layer_table[layer][t_pos] = id of layer's node for template
        # position t_pos (template itself is layer 0)
        self.layer_table: List[List[int]] = []
        # vals[] key the scan result lands under: the LAST layer's out
        # entry, so post nodes look it up exactly like unrolled code
        self.final_out_key: Tuple[int, int] = None
        # optional transform of the scan body — the applied-remat hook:
        # jax.checkpoint(body, policy) wraps each repeated block, which
        # is exactly the remat-opportunity suggestion's granularity
        self.body_wrapper = None

    # ------------------------------------------------------------ checks
    def check_bindings(self, shapes: Dict[str, tuple],
                       dtypes: Dict[str, Any]) -> bool:
        """Per-layer parameters must agree on shape AND dtype across
        layers or they cannot stack on a leading axis."""
        for names in self.var_lists.values():
            s0, d0 = shapes.get(names[0]), dtypes.get(names[0])
            if s0 is None:
                return False
            for nm in names[1:]:
                if shapes.get(nm) != s0 or dtypes.get(nm) != d0:
                    return False
        return True

    # ---------------------------------------------------------- lowering
    def execute(self, vals, args, key, is_train, run_node):
        """Run the scanned chain: stack per-layer params, scan the
        template body once, land the result under ``final_out_key``.
        ``vals`` already holds every pre-node output; ``args`` is the
        full name->value argument dict (per-layer params included)."""
        import jax
        import jax.numpy as jnp
        import numpy as np

        stacked = {tid: jnp.stack([args[nm] for nm in names])
                   for tid, names in self.var_lists.items()}
        # per-(layer, template-node) topo index of the unrolled program:
        # RNG folds must produce the identical key the unrolled graph
        # would, so dropout masks cannot depend on the lowering
        idx_rows = jnp.asarray(np.asarray(
            [[self.node_index[self.layer_table[layer][t_pos]]
              for t_pos in range(len(self.template))]
             for layer in range(self.n_layers)], dtype=np.int32))

        template = self.template
        stream_key = (id(self.stream_in[0]), self.stream_in[1])
        out_key = (id(template[-1]), 0)  # overwritten below if not last
        out_node_id = self.layer_table[0][self._out_pos()]
        out_key = (out_node_id, self.out_idx)
        tvar_ids = set(self.var_lists)

        def body(carry, xs):
            p_slice, idxv = xs
            seg_vals: Dict[Tuple[int, int], Any] = {}

            def entry_val(ent):
                node, ei = ent
                k = (id(node), ei)
                if k == stream_key:
                    return carry
                if k in seg_vals:
                    return seg_vals[k]
                if id(node) in tvar_ids:
                    return p_slice[id(node)]
                # shared value: computed by the pre pass (same entry
                # for every layer, verified at plan build)
                return vals[k]

            for j, node in enumerate(template):
                ins = [entry_val(e) for e in node.inputs]
                outs = run_node(node, ins, key, idxv[j], is_train)
                for i, o in enumerate(outs):
                    seg_vals[(id(node), i)] = o
            return seg_vals[out_key], None

        if self.body_wrapper is not None:
            body = self.body_wrapper(body)
        carry0 = vals[stream_key]
        final, _ = jax.lax.scan(body, carry0, (stacked, idx_rows))
        vals[self.final_out_key] = final

    def _out_pos(self) -> int:
        """Template position of the block's output node."""
        final_id = self.final_out_key[0]
        last = self.layer_table[-1]
        return last.index(final_id)


# --------------------------------------------------------------- builder


def _attrs_equal(a, b) -> bool:
    try:
        if a.attrs == b.attrs and a.str_attrs == b.str_attrs:
            return True
    except Exception:                                       # noqa: BLE001
        pass
    try:
        return repr(sorted(a.attrs.items())) == \
            repr(sorted(b.attrs.items())) and \
            repr(sorted(a.str_attrs.items())) == \
            repr(sorted(b.str_attrs.items()))
    except Exception:                                       # noqa: BLE001
        return False


class _Reject(Exception):
    """Internal: this graph does not verify; fall back to unrolled."""


def _var_families(variables):
    """Group per-layer parameters by name templates: for each integer
    position in a variable name, starring it out yields a template; a
    template shared by >=2 variables at distinct indices is a family.
    All families must agree on ONE index set (the layer axis). Returns
    (layer_sets, L) with layer_sets[i] = the variables of layer i, or
    None."""
    for pos in range(4):
        templates: Dict[str, Dict[int, Any]] = {}
        for v in variables:
            ints = list(_INT_RE.finditer(v.name))
            if len(ints) <= pos:
                continue
            m = ints[pos]
            tpl = v.name[:m.start()] + "<*>" + v.name[m.end():]
            templates.setdefault(tpl, {})[int(m.group())] = v
        families = {t: mbrs for t, mbrs in templates.items()
                    if len(mbrs) >= 2}
        if not families:
            continue
        index_sets = {frozenset(m) for m in families.values()}
        if len(index_sets) != 1:
            continue
        idxs = sorted(next(iter(index_sets)))
        layer_sets: List[List[Any]] = [[] for _ in idxs]
        for mbrs in families.values():
            for raw_idx, v in mbrs.items():
                layer_sets[idxs.index(raw_idx)].append(v)
        return layer_sets, len(idxs)
    return None


def build_scan_plan(symbol, min_repeat: int = DEFAULT_MIN_REPEAT,
                    shapes: Optional[Dict[str, tuple]] = None,
                    dtypes: Optional[Dict[str, Any]] = None
                    ) -> Optional["ScanPlan"]:
    """Detect and verify a repeated homogeneous chain in ``symbol``.

    Returns a :class:`ScanPlan`, or ``None`` when no chain of at least
    ``min_repeat`` verified-isomorphic blocks exists (the caller then
    uses the unrolled path). When ``shapes``/``dtypes`` are given,
    per-layer parameters are also checked stackable."""
    try:
        return _build(symbol, min_repeat, shapes, dtypes)
    except _Reject:
        return None
    except Exception:                                       # noqa: BLE001
        # detection must never take down a bind
        log.debug("scan: plan construction failed", exc_info=True)
        return None


def _build(symbol, min_repeat, shapes, dtypes):
    from .symbol import _topo_order

    nodes = _topo_order(symbol._entries)
    node_index = {id(n): i for i, n in enumerate(nodes)}
    by_id = {id(n): n for n in nodes}
    variables = [n for n in nodes if n.is_variable]
    fam = _var_families(variables)
    if fam is None:
        return None
    layer_sets, L = fam
    if L < max(2, int(min_repeat)):
        return None

    var_layer: Dict[int, int] = {}
    for i, vs in enumerate(layer_sets):
        for v in vs:
            if v.is_aux:
                raise _Reject()  # aux-state threading unsupported
            var_layer[id(v)] = i

    # ---- segmentation: deepest layer family each node depends on
    maxlayer: Dict[int, int] = {}
    for n in nodes:
        if n.is_variable:
            ml = var_layer.get(id(n), -1)
        else:
            ml = -1
            for src, _ in n.inputs:
                ml = max(ml, maxlayer[id(src)])
        maxlayer[id(n)] = ml
    segs: List[List[Any]] = [[] for _ in range(L)]
    for n in nodes:
        if not n.is_variable and maxlayer[id(n)] >= 0:
            segs[maxlayer[id(n)]].append(n)       # topo order preserved
    if any(not s for s in segs):
        raise _Reject()

    consumers: Dict[Tuple[int, int], List[Any]] = {}
    for n in nodes:
        for src, ei in n.inputs:
            consumers.setdefault((id(src), ei), []).append(n)

    def escapes(seg):
        """Entries of ``seg`` consumed outside it, plus symbol outputs
        pointing into it."""
        seg_ids = {id(n) for n in seg}
        outs = []
        for (nid, ei), cons in consumers.items():
            if nid in seg_ids and any(id(c) not in seg_ids
                                      for c in cons):
                outs.append((by_id[nid], ei))
        for n, ei in symbol._entries:
            if id(n) in seg_ids and (n, ei) not in outs:
                outs.append((n, ei))
        return outs

    # interior segments: exactly one escaping value, consumed only by
    # that segment itself and the NEXT one
    out_entries: List[Tuple[Any, int]] = []
    for i in range(L - 1):
        outs = escapes(segs[i])
        if len(outs) != 1:
            raise _Reject()
        node, ei = outs[0]
        allowed = {id(n) for n in segs[i]} | {id(n) for n in segs[i + 1]}
        cons = consumers.get((id(node), ei), [])
        if not cons or any(id(c) not in allowed for c in cons):
            raise _Reject()
        if any(n is node and e == ei for n, e in symbol._entries):
            raise _Reject()       # internal block output exposed
        out_entries.append((node, ei))

    # layer-invariant equivalence of prefix entries: blocks often
    # rebuild identical constant subgraphs per layer (the causal mask's
    # arange/compare chain) — structurally equal, depending on nothing
    # layer-indexed, and RNG-free, they compute the same value, so the
    # scan body can read the template's copy for every layer
    _equiv_memo: Dict[Tuple[int, int], bool] = {}

    def _equiv_outside(a, b) -> bool:
        if a is b:
            return True
        key = (id(a), id(b))
        hit = _equiv_memo.get(key)
        if hit is not None:
            return hit
        ok = (not a.is_variable and not b.is_variable
              and maxlayer[id(a)] == -1 and maxlayer[id(b)] == -1
              and a.op is b.op and not getattr(a.op, "needs_rng", False)
              and len(a.inputs) == len(b.inputs)
              and _attrs_equal(a, b))
        if ok:
            for (asrc, ai), (bsrc, bi) in zip(a.inputs, b.inputs):
                if ai != bi or not _equiv_outside(asrc, bsrc):
                    ok = False
                    break
        _equiv_memo[key] = ok
        return ok

    # ---- pairwise matching from block outputs backward
    def match_pair(a_root, b_root, seg_b_ids, b_stream, layer_i):
        """Map the template onto segment ``layer_i``. ``b_stream`` is
        the entry feeding that segment from outside (the previous
        block's output). Returns (node_map a->b, var_map a->b,
        template-side stream entry or None)."""
        a_ids = {id(n) for n in segs[0]}
        node_map: Dict[int, Any] = {}
        var_map: Dict[int, Any] = {}
        a_stream: List[Tuple[Any, int]] = []

        def match_entry(ae, be):
            (an, ai), (bn, bi) = ae, be
            if ai != bi:
                raise _Reject()
            a_in, b_in = id(an) in a_ids, id(bn) in seg_b_ids
            if a_in != b_in:
                raise _Reject()
            if a_in:
                match_node(an, bn)
                return
            if an is bn:
                return                       # shared value / variable
            # THE stream crossing: the previous block's output on the b
            # side; the a side is whatever feeds the template (an op
            # output, or a plain variable — the chain may start at the
            # graph input)
            if b_stream is not None and bn is b_stream[0] \
                    and bi == b_stream[1]:
                if a_stream and a_stream[0] != (an, ai):
                    raise _Reject()
                if not a_stream:
                    a_stream.append((an, ai))
                return
            if an.is_variable != bn.is_variable:
                raise _Reject()
            if an.is_variable:
                # per-layer parameter pair: template side must belong
                # to layer 0, the b side to THIS layer
                if var_layer.get(id(an)) != 0 or \
                        var_layer.get(id(bn)) != layer_i:
                    raise _Reject()
                prev = var_map.setdefault(id(an), bn)
                if prev is not bn:
                    raise _Reject()
                return
            if _equiv_outside(an, bn):
                return    # layer-invariant prefix computation: the
                          # body reads the template's copy (value-equal)
            raise _Reject()

        def match_node(a, b):
            prev = node_map.get(id(a))
            if prev is not None:
                if prev is not b:
                    raise _Reject()
                return
            if a.is_variable or b.is_variable:
                raise _Reject()
            if a.op is not b.op or len(a.inputs) != len(b.inputs):
                raise _Reject()
            if not _attrs_equal(a, b):
                raise _Reject()
            node_map[id(a)] = b
            for ae, be in zip(a.inputs, b.inputs):
                match_entry(ae, be)

        match_node(a_root, b_root)
        return node_map, var_map, (a_stream[0] if a_stream else None)

    template_seg = segs[0]
    n_tmpl = len(template_seg)
    t_out_node, t_out_idx = out_entries[0]
    maps: List[Dict[int, Any]] = []
    vmaps: List[Dict[int, Any]] = []
    t_stream = None

    for i in range(1, L - 1):
        if out_entries[i][1] != t_out_idx:
            raise _Reject()
        nm, vm, st = match_pair(t_out_node, out_entries[i][0],
                                {id(n) for n in segs[i]},
                                out_entries[i - 1], i)
        if len(nm) != n_tmpl or len(nm) != len(segs[i]):
            raise _Reject()
        if st is not None:
            if t_stream is None:
                t_stream = st
            elif st != t_stream:
                raise _Reject()
        maps.append(nm)
        vmaps.append(vm)

    # last raw segment = block L-1 + suffix; find the block's out node
    # by trying template-shaped candidates from the back
    last_seg = segs[L - 1]
    last_ids = {id(n) for n in last_seg}
    tried = 0
    last_map = last_vmap = last_out = None
    for cand in reversed(last_seg):
        if cand.op is not t_out_node.op:
            continue
        tried += 1
        if tried > _MAX_OUT_CANDIDATES:
            break
        try:
            nm, vm, st = match_pair(t_out_node, cand, last_ids,
                                    out_entries[L - 2], L - 1)
        except _Reject:
            continue
        if len(nm) != n_tmpl:
            continue
        if st is not None and t_stream is not None and st != t_stream:
            continue
        last_map, last_vmap, last_out = nm, vm, (cand, t_out_idx)
        if st is not None and t_stream is None:
            t_stream = st
        break
    if last_map is None:
        raise _Reject()
    maps.append(last_map)
    vmaps.append(last_vmap)

    if t_stream is None:
        raise _Reject()   # no block reads its streaming input: no chain

    # the matched block inside the last raw segment must escape ONLY
    # through its out entry
    matched_last = {id(b) for b in last_map.values()}
    for (nid, ei), cons in consumers.items():
        if nid in matched_last and (nid, ei) != (id(last_out[0]),
                                                 last_out[1]):
            if any(id(c) not in matched_last for c in cons):
                raise _Reject()
    for n, ei in symbol._entries:
        if id(n) in matched_last and (n is not last_out[0]
                                      or ei != last_out[1]):
            raise _Reject()

    # ---- template nodes must be pure tensor ops (no aux states)
    for n in template_seg:
        if getattr(n.op, "num_aux", 0):
            raise _Reject()

    # ---- assemble
    plan = ScanPlan()
    plan.n_layers = L
    plan.template = list(template_seg)
    plan.stream_in = t_stream
    plan.out_idx = t_out_idx
    plan.node_index = node_index

    all_maps = [{id(t): t for t in template_seg}] + maps
    for layer in range(L):
        m = all_maps[layer]
        row = [id(m[id(t)]) for t in template_seg]
        plan.layer_table.append(row)
        plan.scanned_ids |= set(row)

    tvar_ids = set()
    for vm in vmaps:
        tvar_ids |= set(vm)
    tvar_nodes = {id(v): v for v in layer_sets[0]}
    matched_vars = set()
    for tv in tvar_ids:
        tnode = tvar_nodes.get(tv)
        if tnode is None:
            raise _Reject()
        names = [tnode.name]
        matched_vars.add(tv)
        for vm in vmaps:
            mapped = vm.get(tv)
            if mapped is None:
                raise _Reject()   # a layer never consumed this param
            names.append(mapped.name)
            matched_vars.add(id(mapped))
        plan.var_lists[tv] = names
        plan.tvar_names[tv] = tnode.name
    # a per-layer var that is consumed somewhere but never matched
    # would silently lose its gradient path — reject
    for vs in layer_sets:
        for v in vs:
            if (id(v), 0) in consumers and id(v) not in matched_vars:
                raise _Reject()

    last_out_node = last_map[id(t_out_node)]
    plan.final_out_key = (id(last_out_node), t_out_idx)

    # ---- execution split: pre = not scanned & not depending on the
    # scan; post = the rest (suffix + anything downstream)
    dep_scan: Dict[int, bool] = {}
    for n in nodes:
        if id(n) in plan.scanned_ids:
            dep_scan[id(n)] = True
        else:
            dep_scan[id(n)] = any(dep_scan[id(src)]
                                  for src, _ in n.inputs)
    stacked_names = {nm for names in plan.var_lists.values()
                     for nm in names}
    pre_nodes = [
        n for n in nodes
        if id(n) not in plan.scanned_ids and not dep_scan[id(n)]
        and not (n.is_variable and n.name in stacked_names)]
    plan.post_nodes = [n for n in nodes
                       if id(n) not in plan.scanned_ids
                       and dep_scan[id(n)]]

    # prune prefix work the scan made dead: layers 1..L-1's copies of
    # layer-invariant subgraphs (the per-layer causal masks) are never
    # read once the body aliases them to the template's — without
    # pruning, the prefix trace would still grow O(L). Roots that must
    # stay: the template's outside inputs, the stream, everything post
    # nodes and symbol outputs read, and any aux-writing op (its
    # new_aux side effect is part of unrolled semantics).
    keep_roots = {id(plan.stream_in[0])}
    for t in template_seg:
        for src, _ in t.inputs:
            if id(src) not in plan.scanned_ids and \
                    not (src.is_variable and src.name in stacked_names):
                keep_roots.add(id(src))
    for n in plan.post_nodes:
        for src, _ in n.inputs:
            keep_roots.add(id(src))
    for n, _ in symbol._entries:
        keep_roots.add(id(n))
    for n in pre_nodes:
        if not n.is_variable and getattr(n.op, "num_aux", 0):
            keep_roots.add(id(n))
    keep: set = set()
    stack = [by_id[r] for r in keep_roots if r in by_id]
    while stack:
        n = stack.pop()
        if id(n) in keep:
            continue
        keep.add(id(n))
        for src, _ in n.inputs:
            stack.append(src)
    plan.pre_nodes = [n for n in pre_nodes
                      if n.is_variable or id(n) in keep]

    # post nodes may only read pre values, other post values, or the
    # final block output — a reference into a scanned interior (e.g. a
    # suffix node reading block L-2's output) has no materialized value
    visible = {id(n) for n in plan.pre_nodes} | \
        {id(n) for n in plan.post_nodes}
    for n in plan.post_nodes:
        for src, ei in n.inputs:
            if id(src) in visible:
                continue
            if (id(src), ei) == plan.final_out_key:
                continue
            raise _Reject()
    # symbol outputs likewise
    for n, ei in symbol._entries:
        if id(n) in visible or (id(n), ei) == plan.final_out_key:
            continue
        raise _Reject()

    if shapes is not None and not plan.check_bindings(shapes,
                                                      dtypes or {}):
        raise _Reject()
    return plan

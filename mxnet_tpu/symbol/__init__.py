"""mx.sym — symbolic API.

Wrappers are auto-generated from the op registry, exactly like the
reference's ``_init_symbol_module`` (python/mxnet/symbol.py tail) generates
them from the C op registry.
"""
from .symbol import (Symbol, Variable, var, Group, load, load_json,
                     make_symbol_function, _create)
from ..ops import OP_REGISTRY, get_op

__all__ = ["Symbol", "Variable", "var", "Group", "load", "load_json"]


def _init_symbol_module():
    seen = {}
    for name, op in OP_REGISTRY.items():
        if name.startswith("_Function"):
            continue
        if id(op) not in seen:
            seen[id(op)] = make_symbol_function(op)
        fn = seen[id(op)]
        globals()[name] = fn
        if name not in __all__:
            __all__.append(name)


def _attach_symbol_methods():
    """Common ops as Symbol methods (reference: generated Symbol methods)."""
    names = [
        "sum", "mean", "max", "min", "prod", "argmax", "argmin", "clip",
        "abs", "sign", "round", "floor", "ceil", "sqrt", "square", "exp",
        "log", "sigmoid", "tanh", "relu", "softmax", "log_softmax",
        "transpose", "swapaxes", "flatten", "expand_dims", "repeat", "tile",
        "flip", "sort", "argsort", "topk", "take", "one_hot",
        "broadcast_to", "slice_axis", "squeeze", "norm", "split", "slice",
        "reshape", "dot", "astype",
    ]
    for nm in names:
        if nm not in OP_REGISTRY or hasattr(Symbol, nm):
            continue

        def make(nm):
            def method(self, *args, **kwargs):
                op = get_op(nm)
                syms = [self] + [a for a in args if isinstance(a, Symbol)]
                attrs = {k: v for k, v in kwargs.items()
                         if not isinstance(v, Symbol)}
                pos_attrs = [a for a in args if not isinstance(a, Symbol)]
                if pos_attrs:
                    # positional non-symbol args (e.g. reshape(shape))
                    import inspect as _i
                    try:
                        params = [p for p in
                                  _i.signature(op.fn).parameters.values()][1:]
                        for p, v in zip(params, pos_attrs):
                            attrs[p.name] = v
                    except (TypeError, ValueError):
                        pass
                name = attrs.pop("name", None)
                return _create(op, syms, attrs, name)
            method.__name__ = nm
            return method

        setattr(Symbol, nm, make(nm))


_init_symbol_module()
_attach_symbol_methods()

# later-reference-style alias: mx.sym.contrib.MultiBoxPrior (canonical home is
# mx.contrib.sym, reference python/mxnet/contrib/symbol.py)
from ..contrib import symbol as contrib  # noqa: E402


def __getattr__(name):
    """Ops registered after import (rtc.PallasKernel.register, user custom
    kernels) resolve lazily — PEP 562 module fallback."""
    if name in OP_REGISTRY:
        fn = make_symbol_function(OP_REGISTRY[name])
        globals()[name] = fn
        return fn
    raise AttributeError("module %r has no attribute %r" % (__name__, name))

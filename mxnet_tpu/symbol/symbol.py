"""Symbol — the declarative graph API.

Reference: ``python/mxnet/symbol.py`` (Symbol class at line 67, composition,
``infer_shape:921``, ``simple_bind:1266``, ``bind:1502``) over the nnvm graph
(SURVEY.md §2.9). The reference Symbol is a C++ nnvm::Symbol handle; here a
Symbol is a small immutable Python DAG over the op registry, and everything
downstream (shape inference, execution, gradients) is JAX tracing of the same
graph:

* ``infer_shape``/``infer_type`` ≡ ``jax.eval_shape`` of the traced graph —
  the reference's per-op FInferShape/FInferType rules disappear.
* ``bind`` produces an :class:`~mxnet_tpu.executor.Executor` that compiles
  the traced graph with ``jax.jit`` (the GraphExecutor + engine collapse).
* JSON save/load keeps the reference's checkpoint container shape
  (``nodes``/``arg_nodes``/``heads`` — src/c_api/c_api_symbolic.cc
  MXSymbolSaveToJSON) so model zoo checkpoints stay portable.
"""
from __future__ import annotations

import ast
import json
import logging
from typing import Any, Dict, List, Optional, Sequence, Tuple

import numpy as np
import jax

from ..base import MXNetError
from ..name import current_name_manager, current_attr_scope
from ..ops import OP_REGISTRY, OpDef, get_op

__all__ = ["Symbol", "Variable", "var", "Group", "load", "load_json"]


class _Node:
    """One graph node: an op application or a variable (op=None)."""

    __slots__ = ("op", "name", "attrs", "str_attrs", "inputs", "is_aux")

    def __init__(self, op: Optional[OpDef], name: str,
                 attrs: Optional[Dict[str, Any]] = None,
                 inputs: Optional[List[Tuple["_Node", int]]] = None,
                 is_aux: bool = False):
        self.op = op
        self.name = name
        self.attrs = attrs or {}          # op kwargs (python values)
        self.str_attrs: Dict[str, str] = {}  # user attrs (ctx_group, lr_mult…)
        self.inputs = inputs or []
        self.is_aux = is_aux

    @property
    def is_variable(self) -> bool:
        return self.op is None


def _topo_order(entries: Sequence[Tuple[_Node, int]]) -> List[_Node]:
    order: List[_Node] = []
    seen = set()

    def visit(node: _Node):
        if id(node) in seen:
            return
        seen.add(id(node))
        for n, _ in node.inputs:
            visit(n)
        order.append(node)

    for n, _ in entries:
        visit(n)
    return order


class Symbol:
    """An output list over the graph (reference: python/mxnet/symbol.py:67)."""

    __slots__ = ("_entries",)

    def __init__(self, entries: Sequence[Tuple[_Node, int]]):
        self._entries = list(entries)

    # ------------------------------------------------------------ identity
    @property
    def name(self) -> Optional[str]:
        if len(self._entries) == 1:
            return self._entries[0][0].name
        return None

    def __repr__(self):
        names = ", ".join(n.name for n, _ in self._entries)
        return "<Symbol %s>" % names

    def __iter__(self):
        return (self[i] for i in range(len(self._entries)))

    def __len__(self):
        return len(self._entries)

    def __getitem__(self, idx):
        if isinstance(idx, str):
            outputs = self.list_outputs()
            if idx in outputs:
                idx = outputs.index(idx)
            else:
                raise ValueError("output %s not found" % idx)
        return Symbol([self._entries[idx]])

    def get_internals(self) -> "Symbol":
        """Symbol grouping every internal output (reference: symbol.py
        get_internals — the feature-extraction / fine-tune hook)."""
        entries = []
        for node in _topo_order(self._entries):
            if node.is_variable:
                entries.append((node, 0))
            else:
                for i in range(_num_visible_outputs(node)):
                    entries.append((node, i))
        return Symbol(entries)

    def get_children(self) -> Optional["Symbol"]:
        if len(self._entries) != 1 or self._entries[0][0].is_variable:
            return None
        return Symbol(list(self._entries[0][0].inputs))

    # ------------------------------------------------------------ attrs
    def attr(self, key: str) -> Optional[str]:
        if len(self._entries) == 1:
            return self._entries[0][0].str_attrs.get(key)
        return None

    def list_attr(self) -> Dict[str, str]:
        if len(self._entries) == 1:
            return dict(self._entries[0][0].str_attrs)
        return {}

    def attr_dict(self) -> Dict[str, Dict[str, str]]:
        out = {}
        for node in _topo_order(self._entries):
            d = dict(node.str_attrs)
            if node.op is not None:
                d.update({k: _attr_str(v) for k, v in node.attrs.items()})
            if d:
                out[node.name] = d
        return out

    def _set_attr(self, **kwargs):
        for node, _ in self._entries:
            node.str_attrs.update({k: str(v) for k, v in kwargs.items()})

    # ------------------------------------------------------------ listing
    def list_arguments(self) -> List[str]:
        """(reference: symbol.py list_arguments — topo order of variable
        inputs, excluding auxiliary states)."""
        return [n.name for n in _topo_order(self._entries)
                if n.is_variable and not n.is_aux]

    def list_outputs(self) -> List[str]:
        names = []
        for node, idx in self._entries:
            if node.is_variable:
                names.append(node.name)
            else:
                suffix = "_output" if idx == 0 else "_output%d" % idx
                names.append(node.name + suffix)
        return names

    def list_auxiliary_states(self) -> List[str]:
        return [n.name for n in _topo_order(self._entries)
                if n.is_variable and n.is_aux]

    # ------------------------------------------------------------ compose
    def __call__(self, *args, **kwargs):
        """Composition: replace variable inputs with other symbols
        (reference: symbol.py __call__/_compose)."""
        if args and kwargs:
            raise TypeError("compose with either positional or keyword args")
        arg_names = self.list_arguments()
        mapping: Dict[str, Symbol] = {}
        if args:
            for name, s in zip(arg_names, args):
                mapping[name] = s
        else:
            mapping = dict(kwargs)
        replace: Dict[int, Tuple[_Node, int]] = {}
        for node in _topo_order(self._entries):
            if node.is_variable and node.name in mapping:
                sub = mapping[node.name]
                if len(sub._entries) != 1:
                    raise ValueError("can only compose with single-output symbols")
                replace[id(node)] = sub._entries[0]
        memo: Dict[int, _Node] = {}

        def copy(node: _Node) -> Tuple[_Node, int]:
            if id(node) in replace:
                return replace[id(node)]
            if id(node) in memo:
                return (memo[id(node)], 0)
            if node.is_variable:
                return (node, 0)
            new_inputs = []
            for n, i in node.inputs:
                nn, base = copy(n)
                new_inputs.append((nn, i if base == 0 else base))
            nn = _Node(node.op, node.name, dict(node.attrs), new_inputs,
                       node.is_aux)
            nn.str_attrs = dict(node.str_attrs)
            memo[id(node)] = nn
            return (nn, 0)

        entries = []
        for node, idx in self._entries:
            nn, base = copy(node)
            entries.append((nn, idx if isinstance(nn, _Node) and base == 0 else base))
        return Symbol(entries)

    # ------------------------------------------------------------ math
    def _binop(self, other, opname, scalar_op, reverse=False):
        if isinstance(other, Symbol):
            a, b = (other, self) if reverse else (self, other)
            return _create(get_op(opname), [a, b], {}, None)
        return _create(get_op(scalar_op), [self], {"scalar": float(other)}, None)

    def __add__(self, o):
        return self._binop(o, "elemwise_add", "_plus_scalar")

    __radd__ = __add__

    def __sub__(self, o):
        return self._binop(o, "elemwise_sub", "_minus_scalar")

    def __rsub__(self, o):
        return self._binop(o, "elemwise_sub", "_rminus_scalar", reverse=True)

    def __mul__(self, o):
        return self._binop(o, "elemwise_mul", "_mul_scalar")

    __rmul__ = __mul__

    def __truediv__(self, o):
        return self._binop(o, "elemwise_div", "_div_scalar")

    def __rtruediv__(self, o):
        return self._binop(o, "elemwise_div", "_rdiv_scalar", reverse=True)

    __div__ = __truediv__
    __rdiv__ = __rtruediv__

    def __pow__(self, o):
        return self._binop(o, "broadcast_power", "_power_scalar")

    def __neg__(self):
        return _create(get_op("negative"), [self], {}, None)

    # ------------------------------------------------------------ analysis
    def analyze(self, input_shapes=None, input_dtypes=None,
                **shape_kwargs):
        """Run the static graph analyzer (``mxnet_tpu.analysis``) over this
        symbol: cycle / duplicate-name / dead-node / shape-conflict
        detection plus the FLOP/bytes/memory cost model. Shapes may be
        passed as a dict or as kwargs (``net.analyze(data=(32, 784))``).
        Returns an ``analysis.Report``. Imported lazily — symbols that
        never call this never load the analyzer."""
        from ..analysis import analyze_symbol
        shapes = {k: tuple(v) for k, v in (input_shapes or {}).items()}
        shapes.update({k: tuple(v) for k, v in shape_kwargs.items()
                       if v is not None})
        return analyze_symbol(self, input_shapes=shapes or None,
                              input_dtypes=input_dtypes,
                              context=self.name or "symbol")

    # ------------------------------------------------------------ shape/type
    def infer_shape(self, *args, **kwargs):
        """(reference: symbol.py:921). Returns (arg_shapes, out_shapes,
        aux_shapes); unknown args yield None entries. Failures name the
        offending op node and its input shapes (not the raw
        ``jax.eval_shape`` traceback of the whole graph)."""
        return self._infer_shape_impl(False, *args, **kwargs)

    def infer_shape_partial(self, *args, **kwargs):
        return self._infer_shape_impl(True, *args, **kwargs)

    def _infer_shape_impl(self, partial, *args, **kwargs):
        arg_names = self.list_arguments()
        aux_names = self.list_auxiliary_states()
        known: Dict[str, Tuple[int, ...]] = {}
        if args:
            for n, s in zip(arg_names, args):
                if s is not None:
                    known[n] = tuple(s)
        batch_hint = kwargs.pop("__batch_size__", None)
        known.update({k: tuple(v) for k, v in kwargs.items() if v is not None})
        if batch_hint is not None:
            known["__batch_size__"] = int(batch_hint)

        # Variables whose shapes are derivable from graph structure get
        # resolved by abstract evaluation; others must be provided.
        shapes = _infer_shapes(self, known, partial=partial)
        if shapes is None:
            return None, None, None
        arg_shapes = [shapes.get(n) for n in arg_names]
        aux_shapes = [shapes.get(n) for n in aux_names]
        out_shapes = shapes["__outputs__"]
        return arg_shapes, out_shapes, aux_shapes

    def infer_type(self, *args, **kwargs):
        """(reference: symbol.py infer_type). Everything defaults float32
        unless pinned by the caller or the variable's ``dtype=`` attr.
        Bad dtypes fail naming the offending variable node, not with a
        numpy traceback."""
        arg_names = self.list_arguments()
        dtypes = {}
        for node in _topo_order(self._entries):
            if node.is_variable and "__dtype__" in node.str_attrs:
                dtypes[node.name] = node.str_attrs["__dtype__"]
        if args:
            for n, t in zip(arg_names, args):
                if t is not None:
                    dtypes[n] = t
        dtypes.update({k: v for k, v in kwargs.items() if v is not None})
        arg_types = []
        for n in arg_names:
            try:
                arg_types.append(np.dtype(dtypes.get(n, np.float32)))
            except TypeError as exc:
                raise MXNetError(
                    "infer_type: variable %r has invalid dtype %r (%s)"
                    % (n, dtypes.get(n), exc)) from None
        out_types = [np.dtype(np.float32)] * len(self._entries)
        aux_types = [np.dtype(np.float32)] * len(self.list_auxiliary_states())
        return arg_types, out_types, aux_types

    # ------------------------------------------------------------ save/load
    def tojson(self) -> str:
        """Serialize to the REFERENCE's symbol-JSON schema
        (MXSymbolSaveToJSON -> nnvm saveload; the exact container the
        reference's own checkpoints use and ``MXSymbolCreateFromJSON``
        loads — see tests/python/unittest/save_000800.json): per node
        ``op``/``param`` (op attrs, stringified)/``name``/``inputs``/
        ``attr`` (user attrs), plus ``arg_nodes`` and ``heads``. Files
        written here load in the reference and vice versa."""
        # auto-created aux-state variables (BatchNorm moving stats) are NOT
        # part of the reference's serialized graph — they are re-derived
        # from op metadata on load. Trim them from op inputs, then drop
        # only the aux nodes nothing references anymore (an aux variable
        # used as a head — get_internals — or bound explicitly by the user
        # stays serialized, like the reference's 1.x files).
        topo = _topo_order(self._entries)
        trimmed: Dict[int, list] = {}
        for n in topo:
            ins = list(n.inputs)
            if not n.is_variable and n.op.num_aux:
                k = n.op.num_aux
                tail = ins[len(ins) - k:]
                if len(tail) == k and all(
                        src.is_variable and src.is_aux for src, _ in tail):
                    ins = ins[:len(ins) - k]
            trimmed[id(n)] = ins
        referenced = {id(src) for n in topo for src, _ in trimmed[id(n)]}
        referenced |= {id(n) for n, _ in self._entries}
        nodes = [n for n in topo
                 if not (n.is_variable and n.is_aux
                         and id(n) not in referenced)]
        index = {id(n): i for i, n in enumerate(nodes)}
        out_nodes = []
        for n in nodes:
            ins = trimmed[id(n)]
            entry = {
                "op": "null" if n.is_variable else n.op.name,
                "param": {} if n.is_variable else
                         {k: _attr_str(v) for k, v in n.attrs.items()},
                "name": n.name,
                "inputs": [[index[id(src)], i] for src, i in ins],
                "backward_source_id": -1,
            }
            if n.str_attrs:
                entry["attr"] = dict(n.str_attrs)
            out_nodes.append(entry)
        arg_nodes = [i for i, n in enumerate(nodes) if n.is_variable]
        heads = [[index[id(n)], i] for n, i in self._entries]
        return json.dumps({
            "nodes": out_nodes, "arg_nodes": arg_nodes,
            "heads": heads}, indent=2)

    def save(self, fname: str) -> None:
        from ..checkpoint.atomic import atomic_open
        with atomic_open(fname, "w") as f:
            f.write(self.tojson())

    # ------------------------------------------------------------ eval/bind
    def eval(self, ctx=None, aux_states=None, **kwargs):
        """Evaluate with NDArray inputs (reference: symbol.py eval)."""
        from .. import ndarray as nd
        from ..executor import graph_function
        from .. import autograd as ag
        arg_names = self.list_arguments()
        missing = [n for n in arg_names if n not in kwargs]
        if missing:
            raise MXNetError("eval: missing arguments %s" % missing)
        args = {k: kwargs[k].data for k in arg_names}
        aux_names = self.list_auxiliary_states()
        aux = {}
        for n in aux_names:
            if aux_states and n in aux_states:
                v = aux_states[n]
                aux[n] = v.data if hasattr(v, "data") else jax.numpy.asarray(v)
            else:
                raise MXNetError("eval: missing auxiliary state %s" % n)
        fn = graph_function(self)
        from .. import random as _rnd
        outs, _newaux = fn(args, aux, _rnd.next_key(), ag.is_training())
        return [nd.NDArray(o) for o in outs]

    def bind(self, ctx, args, args_grad=None, grad_req="write",
             aux_states=None, group2ctx=None, shared_exec=None):
        """(reference: symbol.py:1502 → Executor::Bind)."""
        from ..executor import Executor
        return Executor(self, ctx, args, args_grad, grad_req, aux_states,
                        group2ctx=group2ctx, shared_exec=shared_exec)

    def simple_bind(self, ctx, grad_req="write", type_dict=None,
                    group2ctx=None, shared_exec=None, **kwargs):
        """(reference: symbol.py:1266 → 40-arg MXExecutorSimpleBind; here:
        infer shapes, allocate args/grads/aux, construct the Executor)."""
        from .. import ndarray as nd
        from ..executor import Executor
        arg_shapes, _, aux_shapes = self.infer_shape(**kwargs)
        arg_names = self.list_arguments()
        aux_names = self.list_auxiliary_states()
        if arg_shapes is None or any(s is None for s in arg_shapes):
            missing = [n for n, s in zip(arg_names, arg_shapes or []) if s is None]
            raise MXNetError("simple_bind: cannot infer shapes for %s" % missing)
        type_dict = type_dict or {}
        args = {}
        for n, s in zip(arg_names, arg_shapes):
            dt = np.dtype(type_dict.get(n, np.float32))
            args[n] = nd.NDArray(np.zeros(s, dtype=dt), ctx=ctx)
        aux = {}
        for n, s in zip(aux_names, aux_shapes):
            aux[n] = nd.NDArray(np.zeros(s, dtype=np.float32), ctx=ctx)
        args_grad = None
        if grad_req != "null":
            args_grad = {n: nd.NDArray(np.zeros(s, dtype=np.float32), ctx=ctx)
                         for n, s in zip(arg_names, arg_shapes)}
        return Executor(self, ctx, args, args_grad, grad_req, aux,
                        group2ctx=group2ctx, shared_exec=shared_exec)

    # attached op methods (sum, reshape, ...) installed by _attach_methods()


def _attr_str(v) -> str:
    return str(v)


def _parse_attr(s: str):
    try:
        return ast.literal_eval(s)
    except (ValueError, SyntaxError):
        return s


def _num_visible_outputs(node: _Node) -> int:
    op = node.op
    nout = getattr(op, "num_outputs", 1)
    if callable(nout):
        nout = nout(node.attrs)
    return int(nout)


# ------------------------------------------------------------------ factory


def Variable(name: str, attr=None, shape=None, lr_mult=None, wd_mult=None,
             dtype=None, init=None, **kwargs) -> Symbol:
    """(reference: symbol.py Variable)."""
    node = _Node(None, name)
    scope = current_attr_scope()
    attrs = scope.get(attr) if scope else dict(attr or {})
    if shape is not None:
        attrs["__shape__"] = str(tuple(shape))
    if lr_mult is not None:
        attrs["__lr_mult__"] = str(lr_mult)
    if wd_mult is not None:
        attrs["__wd_mult__"] = str(wd_mult)
    if dtype is not None:
        attrs["__dtype__"] = str(np.dtype(dtype))
    if init is not None:
        attrs["__init__"] = init if isinstance(init, str) else init.dumps()
    attrs.update({k: str(v) for k, v in kwargs.items()})
    node.str_attrs = attrs
    return Symbol([(node, 0)])


var = Variable


def Group(symbols: Sequence[Symbol]) -> Symbol:
    """(reference: symbol.py Group)."""
    entries = []
    for s in symbols:
        entries.extend(s._entries)
    return Symbol(entries)


def _create(op: OpDef, input_syms: List[Symbol], attrs: Dict[str, Any],
            name: Optional[str], aux_syms: Optional[List[Symbol]] = None) -> Symbol:
    """Create an op node (the symbolic twin of imperative_invoke)."""
    nm = current_name_manager()
    name = nm.get(name, op.name.lower().replace("_", ""))
    entries: List[Tuple[_Node, int]] = []
    for s in input_syms + (aux_syms or []):
        if len(s._entries) != 1:
            raise MXNetError(
                "op %s input must be single-output symbol" % op.name)
        entries.append(s._entries[0])
    node = _Node(op, name, attrs, entries)
    scope = current_attr_scope()
    if scope:
        node.str_attrs = scope.get(None)
    n_visible = _num_visible_outputs(node)
    return Symbol([(node, i) for i in range(n_visible)])


def make_symbol_function(op: OpDef):
    """Generate the mx.sym.<Op> wrapper from the registry — the analogue of
    the reference's _init_symbol_module autogen (python/mxnet/symbol.py tail).

    Missing weight/bias/aux inputs are auto-created as Variables named
    ``<name>_<input>`` exactly like the reference (e.g. ``fc1_weight``).
    """
    input_names = op.input_names
    aux_names = op.aux_input_names

    def fn(*args, **kwargs):
        name = kwargs.pop("name", None)
        nm = current_name_manager()
        name = nm.get(name, op.name.lower().replace("_", ""))

        inputs: Dict[str, Symbol] = {}
        # ops with attr-dependent interfaces (Custom: the Prop declares
        # list_arguments) resolve their input names from the non-symbol kwargs
        names_fn = getattr(op, "input_names_fn", None)
        if names_fn is not None:
            attr_kwargs = {k: v for k, v in kwargs.items()
                           if not isinstance(v, Symbol)}
            input_names_l = names_fn(attr_kwargs)
        else:
            input_names_l = input_names
        if op.num_inputs is None and names_fn is None and args and all(
                isinstance(a, Symbol) for a in args) and len(args) > 1 \
                and not any(k in kwargs for k in input_names_l):
            # variadic (Concat-style): positional symbols are THE inputs
            attrs = {k: v for k, v in kwargs.items()}
            return _create(op, list(args), attrs, name)
        attrs = {}
        # positional args: Symbols fill tensor-input slots; non-Symbols are
        # positional *attrs* and map onto the op function's parameter at
        # the same position (so sym.reshape(x, (1, 2, 3)) works like the
        # imperative nd.reshape — previously the shape was silently lost)
        fn_param_names = None
        for i, a in enumerate(args):
            if isinstance(a, Symbol):
                if i < len(input_names_l):
                    inputs[input_names_l[i]] = a
                else:
                    raise MXNetError(
                        "%s: too many symbol inputs (expected %s)"
                        % (op.name, input_names_l))
            else:
                if fn_param_names is None:
                    import inspect as _inspect
                    try:
                        fn_param_names = [
                            p.name for p in _inspect.signature(
                                op.fn).parameters.values()
                            if p.kind in (p.POSITIONAL_ONLY,
                                          p.POSITIONAL_OR_KEYWORD)]
                    except (TypeError, ValueError):
                        fn_param_names = []
                if i < len(fn_param_names):
                    attrs[fn_param_names[i]] = a
                else:
                    raise MXNetError(
                        "%s: unexpected positional argument %r"
                        % (op.name, a))
        for k, v in kwargs.items():
            if isinstance(v, Symbol):
                inputs[k] = v
            else:
                attrs[k] = v
        in_syms = []
        for nm_i in input_names_l:
            if nm_i in inputs:
                in_syms.append(inputs[nm_i])
            else:
                if nm_i == "label":
                    in_syms.append(Variable("%s_label" % name))
                else:
                    in_syms.append(Variable("%s_%s" % (name, nm_i)))
        aux_syms = []
        for nm_a in aux_names:
            if nm_a in inputs:
                aux_syms.append(inputs[nm_a])
            else:
                v = Variable("%s_%s" % (name, nm_a))
                v._entries[0][0].is_aux = True
                aux_syms.append(v)
        # no_bias / variadic single-input trimming
        if attrs.get("no_bias") and "bias" in input_names:
            idx = input_names.index("bias")
            if "bias" not in inputs:
                in_syms = in_syms[:idx] + in_syms[idx + 1:]
        return _create(op, in_syms, attrs, name, aux_syms)

    fn.__name__ = op.name
    fn.__doc__ = op.__doc__
    return fn


# ------------------------------------------------------------------ loading


def load_json(json_str: str) -> Symbol:
    """Load a reference-format symbol JSON (the schema of
    ``MXSymbolCreateFromJSON``, src/c_api/c_api_symbolic.cc). Accepts
    every vintage of the container: 0.8-era ``param``+``attr`` (see the
    reference fixture tests/python/unittest/save_000800.json), 1.x-era
    merged ``attrs``, and 2-element or 3-element input/head tuples.
    Auxiliary states are re-derived from each op's aux arity, like the
    reference re-derives them from op metadata on load."""
    g = json.loads(json_str)
    built: List[_Node] = []
    for rn in g["nodes"]:
        if rn["op"] == "null":
            node = _Node(None, rn["name"],
                         is_aux=bool(rn.get("is_aux", False)))
            node.str_attrs = {
                k: str(v) for k, v in
                (rn.get("attr") or rn.get("attrs") or
                 rn.get("str_attrs") or {}).items()}
        else:
            op = get_op(rn["op"])
            if "param" in rn:              # 0.8 era: op attrs live here
                op_attrs = rn["param"]
                user_attrs = rn.get("attr", {})
            else:                          # 1.x era: one merged dict
                merged = dict(rn.get("attrs", {}))
                user_keys = ("ctx_group", "lr_mult", "wd_mult",
                             "__shape__", "__layout__", "__dtype__",
                             "__init__", "force_mirroring")
                user_attrs = {k: merged.pop(k) for k in list(merged)
                              if k in user_keys or k.startswith("__")}
                op_attrs = merged
                user_attrs.update(rn.get("str_attrs", {}))
            attrs = {k: _parse_attr(v) for k, v in op_attrs.items()}
            attrs = _filter_op_attrs(op, attrs, rn["name"])
            inputs = [(built[e[0]], e[1]) for e in rn["inputs"]]
            n_aux = op.num_aux
            if n_aux:
                visible = len(op.input_names)
                if attrs.get("no_bias") and "bias" in op.input_names:
                    visible -= 1
                if len(inputs) >= visible + n_aux:
                    # file serialized the aux states as graph inputs
                    # (reference 1.x style) — adopt them as aux
                    for src, _ in inputs[-n_aux:]:
                        if src.is_variable:
                            src.is_aux = True
                else:
                    # 0.8-style file omits aux states — re-create them by
                    # the <name>_<aux> convention (make_symbol_function)
                    for aux_name in op.aux_input_names:
                        v = _Node(None, "%s_%s" % (rn["name"], aux_name),
                                  is_aux=True)
                        inputs.append((v, 0))
            node = _Node(op, rn["name"], attrs, inputs)
            node.str_attrs = {k: str(v) for k, v in user_attrs.items()}
        built.append(node)
    entries = [(built[e[0]], e[1]) for e in g["heads"]]
    return Symbol(entries)


def _filter_op_attrs(op, attrs, node_name):
    """Drop serialized op params this build doesn't take (workspace,
    cudnn_tune, ... — backend tuning knobs of the reference with no TPU
    meaning), so reference checkpoints load instead of erroring."""
    import inspect
    try:
        params = inspect.signature(op.fn).parameters
    except (TypeError, ValueError):
        return attrs
    if any(p.kind is p.VAR_KEYWORD for p in params.values()):
        return attrs
    known = set(params)
    dropped = [k for k in attrs if k not in known]
    if dropped:
        logging.getLogger(__name__).debug(
            "load_json: dropping unsupported attrs %s of node %r (%s)",
            dropped, node_name, op.name)
    return {k: v for k, v in attrs.items() if k in known}


def load(fname: str) -> Symbol:
    with open(fname) as f:
        return load_json(f.read())


# ------------------------------------------------------------------ shapes


def _eval_node_abstract(node: _Node, in_avals):
    """Abstract-evaluate ONE graph node: the single home of the implicit
    op-invocation protocol (drop ``name``, default ``_is_train``, thread a
    per-node RNG key for sampler ops), shared by ``_derive_param_shapes``,
    the ``infer_shape`` error localizer, and the analyzer's shape pass so
    the protocol cannot drift between them. ``in_avals`` are
    ``jax.ShapeDtypeStruct``s; returns a tuple of them (raises whatever
    the op raises)."""
    import inspect
    attrs = dict(node.attrs)
    attrs.pop("name", None)
    try:
        params = inspect.signature(node.op.fn).parameters
    except (TypeError, ValueError):
        params = {}
    if "_is_train" in params:
        attrs.setdefault("_is_train", True)
    if node.op.needs_rng:
        outs = jax.eval_shape(
            lambda key, *xs: node.op.fn(*xs, _rng=key, **attrs),
            jax.ShapeDtypeStruct((2,), np.uint32), *in_avals)
    else:
        outs = jax.eval_shape(
            lambda *xs: node.op.fn(*xs, **attrs), *in_avals)
    return outs if isinstance(outs, tuple) else (outs,)


def _infer_shapes(sym: Symbol, known: Dict[str, Tuple[int, ...]],
                  partial: bool = False):
    """Abstract-evaluate the graph with jax.eval_shape to derive all
    variable/output shapes (the TPU replacement for nnvm InferShape)."""
    from ..executor import graph_function
    arg_names = sym.list_arguments()
    aux_names = sym.list_auxiliary_states()

    resolved = dict(known)
    batch_size = resolved.pop("__batch_size__", None)
    if batch_size is None:
        # derive the batch hint from the caller-provided input shapes:
        # prefer the canonical "data" input's leading dim (NT/NTC layouts;
        # pass __batch_size__ explicitly for time-major data)
        data_like = [(n, s) for n, s in resolved.items()
                     if s and not str(n).endswith(
                         ("weight", "bias", "gamma", "beta",
                          "moving_mean", "moving_var"))]
        for n, s in data_like:
            if n == "data":
                batch_size = s[0]
                break
        else:
            if data_like:
                batch_size = data_like[0][1][0]
    # shapes pinned on Variables via shape= attr; wildcard (0) dims stand
    # for the batch dimension (reference convention: state_info shapes are
    # (0, H) with __layout__ marking the N axis) and resolve from the
    # caller-provided batch hint
    for node in _topo_order(sym._entries):
        if node.is_variable and "__shape__" in node.str_attrs and \
                node.name not in resolved:
            shape = list(ast.literal_eval(node.str_attrs["__shape__"]))
            if any(s == 0 for s in shape) and batch_size:
                layout = node.str_attrs.get("__layout__", "")
                n_axis = layout.find("N")
                if 0 <= n_axis < len(shape) and shape[n_axis] == 0:
                    shape[n_axis] = int(batch_size)
                else:
                    shape = [int(batch_size) if s == 0 else s
                             for s in shape]
            resolved[node.name] = tuple(shape)

    missing = [n for n in arg_names + aux_names if n not in resolved]
    if missing:
        # derive parameter shapes structurally: walk nodes, use op shape hints
        derived = _derive_param_shapes(sym, resolved)
        resolved.update(derived)
        missing = [n for n in arg_names + aux_names if n not in resolved]
    if missing and not partial:
        raise MXNetError("infer_shape: cannot infer %s (provide its shape)"
                         % missing)
    if missing:
        return None

    fn = graph_function(sym)
    args = {n: jax.ShapeDtypeStruct(tuple(resolved[n]), np.float32)
            for n in arg_names}
    aux = {n: jax.ShapeDtypeStruct(tuple(resolved[n]), np.float32)
           for n in aux_names}
    key = jax.ShapeDtypeStruct((2,), np.uint32)
    try:
        outs, _ = jax.eval_shape(lambda a, x, k: fn(a, x, k, True),
                                 args, aux, key)
    except MXNetError:
        raise
    except Exception as exc:
        raise _shape_error_with_context(sym, resolved, exc) from exc
    shapes = {n: tuple(resolved[n]) for n in arg_names + aux_names}
    shapes["__outputs__"] = [tuple(o.shape) for o in outs]
    return shapes


def _shape_error_with_context(sym, resolved, exc) -> MXNetError:
    """Localize a whole-graph ``jax.eval_shape`` failure to the offending
    op node: re-walk the graph evaluating one node at a time and name the
    first node that rejects its inputs, with the op, the node name, and
    the actual input shapes — instead of a jax traceback that mentions
    neither (ISSUE 3 satellite)."""
    first_line = str(exc).strip().splitlines()
    first_line = first_line[0] if first_line else type(exc).__name__
    shapes: Dict[Tuple[int, int], tuple] = {}

    def shape_of(entry):
        node, idx = entry
        if node.is_variable:
            s = resolved.get(node.name)
            return tuple(s) if s is not None else None
        return shapes.get((id(node), idx))

    for node in _topo_order(sym._entries):
        if node.is_variable:
            continue
        in_shapes = [shape_of(e) for e in node.inputs]
        if any(s is None for s in in_shapes):
            continue
        try:
            outs = _eval_node_abstract(
                node, [jax.ShapeDtypeStruct(s, np.float32)
                       for s in in_shapes])
        except Exception as node_exc:                       # noqa: BLE001
            node_line = str(node_exc).strip().splitlines()
            node_line = node_line[0] if node_line \
                else type(node_exc).__name__
            in_desc = ", ".join(
                "%s=(%s)" % (src.name, ",".join(map(str, s)))
                for (src, _), s in zip(node.inputs, in_shapes))
            return MXNetError(
                "infer_shape: op %s (node %r) rejects its input shapes "
                "[%s]: %s" % (node.op.name, node.name, in_desc, node_line))
        for i, o in enumerate(outs):
            shapes[(id(node), i)] = tuple(o.shape)
    # per-node walk could not localize it (a cross-node interaction):
    # still better than a raw traceback — summarize the failure
    return MXNetError("infer_shape failed: %s" % first_line)


def _derive_param_shapes(sym: Symbol, known: Dict[str, Tuple[int, ...]]):
    """Forward-walk the graph deriving weight/bias/aux shapes from op attrs +
    input shapes (the role of the reference's per-op InferShape rules, e.g.
    convolution-inl.h InferShape). Parameter-owning ops have explicit
    derivation rules; output shapes of every node are then propagated with
    ``jax.eval_shape`` so downstream parameter shapes resolve too — MLP-style
    ``data -> fc -> act -> fc`` infers all weights from the data shape alone,
    exactly like the reference."""
    derived: Dict[str, Tuple[int, ...]] = {}
    shapes: Dict[Tuple[int, int], Tuple[int, ...]] = {}  # (node id, out idx)
    eval_memo: Dict[tuple, Optional[tuple]] = {}         # per-call memo

    def shape_of(entry):
        node, idx = entry
        if node.is_variable:
            s = known.get(node.name) or derived.get(node.name)
            return tuple(s) if s is not None else None
        return shapes.get((id(node), idx))

    for node in _topo_order(sym._entries):
        if node.is_variable:
            continue
        opname = node.op.name
        a = node.attrs
        in_shapes = [shape_of(e) for e in node.inputs]
        ds = in_shapes[0] if in_shapes else None

        def setvar(pos, shape):
            if pos >= len(node.inputs):
                return
            n, _ = node.inputs[pos]
            if n.is_variable and n.name not in known and \
                    n.name not in derived and shape is not None:
                derived[n.name] = tuple(int(x) for x in shape)

        # ---- parameter derivation rules (subset of ops that own params)
        try:
            if ds is not None:
                if opname == "FullyConnected":
                    nh = int(a.get("num_hidden"))
                    flat = int(np.prod(ds[1:])) if a.get("flatten", True) else ds[-1]
                    setvar(1, (nh, flat))
                    setvar(2, (nh,))
                elif opname in ("Convolution", "Convolution_v1"):
                    nf = int(a.get("num_filter"))
                    k = _shape_attr(a.get("kernel"), len(ds) - 2, 1)
                    g = int(a.get("num_group", 1))
                    setvar(1, (nf, ds[1] // g) + k)
                    setvar(2, (nf,))
                elif opname == "Deconvolution":
                    nf = int(a.get("num_filter"))
                    k = _shape_attr(a.get("kernel"), len(ds) - 2, 1)
                    g = int(a.get("num_group", 1))
                    setvar(1, (ds[1], nf // g) + k)
                    setvar(2, (nf,))
                elif opname in ("BatchNorm", "BatchNorm_v1"):
                    ax = int(a.get("axis", 1)) % len(ds)
                    for pos in range(1, 5):
                        setvar(pos, (ds[ax],))
                elif opname == "InstanceNorm":
                    setvar(1, (ds[1],))
                    setvar(2, (ds[1],))
                elif opname == "LayerNorm":
                    ax = int(a.get("axis", -1)) % len(ds)
                    setvar(1, (ds[ax],))
                    setvar(2, (ds[ax],))
                elif opname == "IdentityAttachKLSparseReg":
                    setvar(1, (int(np.prod(ds[1:])),))
                elif opname == "Embedding":
                    setvar(1, (int(a.get("input_dim")),
                               int(a.get("output_dim"))))
                elif opname == "LeakyReLU" and a.get("act_type") == "prelu":
                    setvar(1, (ds[1],))
                elif opname in ("SoftmaxOutput", "LinearRegressionOutput",
                                "MAERegressionOutput",
                                "LogisticRegressionOutput", "SVMOutput"):
                    lbl = (ds[0],) if opname in ("SoftmaxOutput", "SVMOutput") \
                        else ds
                    setvar(1, lbl)
                elif opname == "RNN":
                    # ds = (T, N, input); packed params + initial states
                    from ..ops.rnn_op import rnn_param_size
                    H = int(a.get("state_size"))
                    L = int(a.get("num_layers", 1))
                    mode = a.get("mode", "lstm")
                    dirs = 2 if a.get("bidirectional") else 1
                    setvar(1, (rnn_param_size(L, ds[2], H, mode,
                                              bool(a.get("bidirectional"))),))
                    setvar(2, (L * dirs, ds[1], H))
                    if mode == "lstm":
                        setvar(3, (L * dirs, ds[1], H))
                elif opname == "Custom":
                    # the user's Prop owns the shape rules; its infer_shape
                    # may choke on partially-None shapes (user validation
                    # code) — any failure just skips derivation for the node
                    try:
                        from ..operator import _make_prop
                        prop = _make_prop(a["op_type"], a)
                        ish, _, _ = prop.infer_shape(
                            [list(s) if s is not None else None
                             for s in in_shapes])
                        for pos, s in enumerate(ish):
                            if s is not None:
                                setvar(pos, tuple(int(x) for x in s))
                    except Exception:
                        pass
        except (TypeError, KeyError, ValueError):
            pass

        # ---- abstract-evaluate this node if all inputs are now known.
        # Repeated structures (the 12 identical transformer blocks, say)
        # produce the same (op, attrs, input shapes) over and over; memoize
        # so each unique signature traces once — for custom_vjp-heavy ops
        # (flash attention) this is the difference between seconds and
        # minutes of bind time.
        in_shapes = [shape_of(e) for e in node.inputs]
        if any(s is None for s in in_shapes):
            continue
        ckey = (node.op.name, tuple(in_shapes),
                tuple(sorted((k, repr(v)) for k, v in a.items())))
        if ckey in eval_memo:
            outs = eval_memo[ckey]
            if outs is not None:
                for i, o in enumerate(outs):
                    shapes[(id(node), i)] = o
            continue
        try:
            outs = _eval_node_abstract(
                node, [jax.ShapeDtypeStruct(s, np.float32)
                       for s in in_shapes])
            out_shapes = tuple(tuple(o.shape) for o in outs)
            eval_memo[ckey] = out_shapes
            for i, o in enumerate(out_shapes):
                shapes[(id(node), i)] = o
        except Exception:
            eval_memo[ckey] = None
    return derived


def _shape_attr(v, n, default):
    if v is None:
        return (default,) * n
    if isinstance(v, int):
        return (v,) * n
    t = tuple(int(x) for x in v)
    return t * n if len(t) == 1 else t

"""Imperative autograd.

Reference: ``src/ndarray/autograd.{h,cc}`` + ``python/mxnet/autograd.py``
(SURVEY.md §2.4): MXNet records an AGNode tape during imperative execution and
computes gradients by reconstructing an nnvm graph and running a throwaway
GraphExecutor backward.

TPU design: same tape-by-reconstruction idea, but the reconstruction target is
a *pure JAX function* and the backward engine is ``jax.vjp``. Replaying the
tape re-traces every recorded op with its captured attrs (including the exact
PRNG keys, so dropout masks replay identically) and lets XLA differentiate,
fuse and schedule the whole backward — the reference's per-op FGradient
registrations and backward executor disappear.
"""
from __future__ import annotations

import threading
from typing import List, Optional, Sequence

import jax
import jax.numpy as jnp

__all__ = [
    "record", "pause", "train_mode", "predict_mode", "is_recording",
    "is_training", "mark_variables", "backward", "set_recording",
    "set_training",
]

_state = threading.local()


def _st():
    if not hasattr(_state, "recording"):
        _state.recording = False
        _state.training = False
        _state.tape = []
        _state.marked = {}
    return _state


class _TapeEntry:
    __slots__ = ("op", "attrs", "inputs", "input_consts", "outputs")

    def __init__(self, op, attrs, inputs, outputs):
        self.op = op
        self.attrs = attrs
        self.inputs = inputs          # list of NDArray refs
        self.input_consts = [a.data for a in inputs]  # values at record time
        self.outputs = outputs        # list of NDArray refs


def _record_op(op, attrs, inputs, outputs) -> None:
    """Called by the imperative dispatch layer for every op executed while
    recording (reference hook: MXImperativeInvoke -> RecordImperativeFCompute,
    src/c_api/c_api_ndarray.cc:400, src/ndarray/autograd.cc:104)."""
    _st().tape.append(_TapeEntry(op, attrs, list(inputs), list(outputs)))


def is_recording() -> bool:
    return _st().recording


def is_training() -> bool:
    return _st().training


def set_recording(is_record: bool) -> bool:
    s = _st()
    prev, s.recording = s.recording, is_record
    return prev


def set_training(train: bool) -> bool:
    s = _st()
    prev, s.training = s.training, train
    return prev


class _RecordingStateScope:
    """(reference: python/mxnet/autograd.py _RecordingStateScope)."""

    def __init__(self, is_record: Optional[bool], train: Optional[bool]):
        self._rec, self._train = is_record, train
        self._prev_rec = self._prev_train = None

    def __enter__(self):
        if self._rec is not None:
            self._prev_rec = set_recording(self._rec)
        if self._train is not None:
            self._prev_train = set_training(self._train)
        return self

    def __exit__(self, *exc):
        if self._rec is not None:
            set_recording(self._prev_rec)
        if self._train is not None:
            set_training(self._prev_train)


def record(train_mode: bool = True):
    """``with autograd.record():`` (reference: python/mxnet/autograd.py:120)."""
    return _RecordingStateScope(True, train_mode)


def pause(train_mode: bool = False):
    """(reference: python/mxnet/autograd.py:144)."""
    return _RecordingStateScope(False, train_mode)


def train_mode():
    """(reference: python/mxnet/autograd.py train_mode)."""
    return _RecordingStateScope(None, True)


def predict_mode():
    return _RecordingStateScope(None, False)


def mark_variables(variables, gradients, grad_reqs="write") -> None:
    """Attach gradient buffers to arrays (reference:
    src/ndarray/autograd.cc:78-102, python surface autograd.py:195)."""
    if isinstance(grad_reqs, str):
        grad_reqs = [grad_reqs] * len(variables)
    s = _st()
    for var, grad, req in zip(variables, gradients, grad_reqs):
        var._grad = grad
        var._grad_req = req
        s.marked[id(var)] = var


def backward(heads, head_grads=None, retain_graph: bool = False,
             train_mode: bool = True) -> None:
    """Compute gradients of heads w.r.t. all marked variables (reference:
    AutogradRuntime::ComputeGradient, src/ndarray/autograd.cc:229-320).

    Reconstructs a pure function marked-vars -> heads by replaying the tape,
    then runs one ``jax.vjp``. Gradients land in each variable's attached
    grad buffer honoring its grad_req (write/add/null — reference
    OpReqType semantics, include/mxnet/op_attr_types.h:45-58).
    """
    from .ndarray import NDArray  # cycle-free at call time

    if not isinstance(heads, (list, tuple)):
        heads = [heads]
    if head_grads is not None and not isinstance(head_grads, (list, tuple)):
        head_grads = [head_grads]

    s = _st()
    tape: List[_TapeEntry] = s.tape

    # Which marked variables feed the heads? Walk tape backwards from heads.
    needed = {id(h) for h in heads}
    used_entries = []
    for entry in reversed(tape):
        if any(id(o) in needed for o in entry.outputs):
            used_entries.append(entry)
            needed.update(id(i) for i in entry.inputs)
    used_entries.reverse()

    variables = [v for vid, v in s.marked.items() if vid in needed]
    if not variables:
        raise ValueError(
            "backward: no marked variables reach the heads — call "
            "mark_variables/attach_grad and compute inside autograd.record()")

    var_ids = [id(v) for v in variables]
    head_ids = [id(h) for h in heads]

    def replay(var_values):
        env = dict(zip(var_ids, var_values))
        for entry in used_entries:
            args = [
                env.get(id(inp), const)
                for inp, const in zip(entry.inputs, entry.input_consts)
            ]
            outs = entry.op.fn(*args, **entry.attrs)
            if not isinstance(outs, tuple):
                outs = (outs,)
            for o_nd, o_val in zip(entry.outputs, outs):
                env[id(o_nd)] = o_val
        return [env[h] for h in head_ids]

    primals = [v.data for v in variables]
    head_vals, vjp_fn = jax.vjp(lambda *vs: replay(list(vs)), *primals)
    if head_grads is None:
        cts = [jnp.ones_like(h) for h in head_vals]
    else:
        cts = [
            (g.data if isinstance(g, NDArray) else jnp.asarray(g))
            if g is not None else jnp.ones_like(h)
            for g, h in zip(head_grads, head_vals)
        ]
    grads = vjp_fn(cts)
    for var, g in zip(variables, grads):
        req = getattr(var, "_grad_req", "write")
        if req == "null" or var._grad is None:
            continue
        if req == "add":
            var._grad._data = var._grad.data + g
        else:
            var._grad._data = g.astype(var._grad.dtype)
    if not retain_graph:
        s.tape = []


def get_symbol(x):  # pragma: no cover - reference-API stub
    """The reference exposes autograd.get_symbol; the TPU build's tape has no
    nnvm symbol to return. Use Symbol tracing instead."""
    raise NotImplementedError("use mxnet_tpu.symbol tracing instead")

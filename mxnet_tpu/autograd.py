"""Imperative autograd.

Reference: ``src/ndarray/autograd.{h,cc}`` + ``python/mxnet/autograd.py``
(SURVEY.md §2.4): MXNet records an AGNode tape during imperative execution and
computes gradients by reconstructing an nnvm graph and running a throwaway
GraphExecutor backward.

TPU design: same tape-by-reconstruction idea, but the reconstruction target is
a *pure JAX function* and the backward engine is ``jax.vjp``. Replaying the
tape re-traces every recorded op with its captured attrs (including the exact
PRNG keys, so dropout masks replay identically) and lets XLA differentiate,
fuse and schedule the whole backward — the reference's per-op FGradient
registrations and backward executor disappear.

Versioned tape: every NDArray carries a process-unique ``_uid`` plus a
``_version`` counter bumped on each in-place rebind of its buffer
(``x[:] = v``, ``x += y``, ``out=`` kwargs, aux-state commits). Tape entries
key their inputs/outputs by ``(uid, version)`` and capture input *values* at
record time, so:

* gradients are computed at the values the forward actually consumed, even if
  a variable is mutated after recording (the reference gets this by tracking
  the autograd node on the array itself);
* recorded in-place ops (``x *= 2`` routed through ``out=self``) chain
  correctly through versions instead of silently dropping gradient;
* uid keys cannot alias after garbage collection (unlike ``id()``).

Entries hold only weak references to their output arrays; dead subgraphs are
pruned when a new outermost ``record()`` scope begins, so recording without
ever calling ``backward`` does not leak.
"""
from __future__ import annotations

import itertools
import threading
import weakref
from typing import List, Optional

import jax
import jax.numpy as jnp

__all__ = [
    "record", "pause", "train_mode", "predict_mode", "is_recording",
    "is_training", "mark_variables", "backward", "set_recording",
    "set_training", "Function",
]

_state = threading.local()
_uid_counter = itertools.count()


def new_uid() -> int:
    """Process-unique array id for tape keys (never reused, unlike id())."""
    return next(_uid_counter)


def _st():
    if not hasattr(_state, "recording"):
        _state.recording = False
        _state.training = False
        _state.tape = []
        _state.marked = {}  # uid -> weakref(NDArray)
    return _state


class _TapeEntry:
    __slots__ = ("op", "attrs", "in_keys", "in_consts", "out_keys", "out_refs")

    def __init__(self, op, attrs, in_keys, in_consts, out_targets):
        self.op = op
        self.attrs = attrs
        self.in_keys = in_keys            # [(uid, version)] at record time
        self.in_consts = in_consts        # input jax values at record time
        self.out_keys = [(t._uid, t._version) for t in out_targets]
        self.out_refs = [weakref.ref(t) for t in out_targets]


def _record_op(op, attrs, in_keys, in_consts, out_targets) -> None:
    """Called by the imperative dispatch layer for every op executed while
    recording (reference hook: MXImperativeInvoke -> RecordImperativeFCompute,
    src/c_api/c_api_ndarray.cc:400, src/ndarray/autograd.cc:104)."""
    _st().tape.append(_TapeEntry(op, attrs, in_keys, in_consts, out_targets))


def _prune_tape(s) -> None:
    """Drop entries no live array can reach — keeps long-lived processes that
    record without calling backward from accumulating tape forever."""
    live_keys = set()
    keep: List[_TapeEntry] = []
    for e in reversed(s.tape):
        if any(r() is not None for r in e.out_refs) or \
                any(k in live_keys for k in e.out_keys):
            keep.append(e)
            live_keys.update(e.in_keys)
    keep.reverse()
    s.tape = keep


def is_recording() -> bool:
    return _st().recording


def is_training() -> bool:
    return _st().training


def set_recording(is_record: bool) -> bool:
    s = _st()
    prev, s.recording = s.recording, is_record
    if is_record and not prev:
        _prune_tape(s)
    return prev


def set_training(train: bool) -> bool:
    s = _st()
    prev, s.training = s.training, train
    return prev


class _RecordingStateScope:
    """(reference: python/mxnet/autograd.py _RecordingStateScope)."""

    def __init__(self, is_record: Optional[bool], train: Optional[bool]):
        self._rec, self._train = is_record, train
        self._prev_rec = self._prev_train = None

    def __enter__(self):
        if self._rec is not None:
            self._prev_rec = set_recording(self._rec)
        if self._train is not None:
            self._prev_train = set_training(self._train)
        return self

    def __exit__(self, *exc):
        if self._rec is not None:
            set_recording(self._prev_rec)
        if self._train is not None:
            set_training(self._prev_train)


def record(train_mode: bool = True):
    """``with autograd.record():`` (reference: python/mxnet/autograd.py:120)."""
    return _RecordingStateScope(True, train_mode)


def pause(train_mode: bool = False):
    """(reference: python/mxnet/autograd.py:144)."""
    return _RecordingStateScope(False, train_mode)


def train_mode():
    """(reference: python/mxnet/autograd.py train_mode)."""
    return _RecordingStateScope(None, True)


def predict_mode():
    return _RecordingStateScope(None, False)


def mark_variables(variables, gradients, grad_reqs="write") -> None:
    """Attach gradient buffers to arrays (reference:
    src/ndarray/autograd.cc:78-102, python surface autograd.py:195)."""
    if isinstance(grad_reqs, str):
        grad_reqs = [grad_reqs] * len(variables)
    s = _st()
    for var, grad, req in zip(variables, gradients, grad_reqs):
        var._grad = grad
        var._grad_req = req
        s.marked[var._uid] = weakref.ref(var)


# Structure-keyed compile cache shared with the fused optimizer step —
# one signature scheme for both hot paths (mxnet_tpu/_fused.py).
from ._fused import (CompileCache as _CompileCache,       # noqa: E402
                     Uncacheable as _Uncacheable,
                     op_identity as _op_identity,
                     static_key as _static_key_shared,
                     structural_failure as _structural_failure)

_BWD_CACHE = _CompileCache("backward", max_entries=128)


def _is_jax_value(v):
    return isinstance(v, jax.Array) or hasattr(v, "aval")


def _compiled_backward(used, seed_keys, head_keys, primals, cts_in):
    """Jit-compiled tape backward with a structure-keyed cache.

    The tape slice is normalized into a position-based plan (keys
    relabeled by first appearance, captured tensors and PRNG-key attrs
    lifted to dynamic arguments), so two slices with identical op
    structure and operand shapes/dtypes share one compiled program
    regardless of the concrete arrays involved — the repeated-structure
    training loop compiles once and afterwards costs one dispatch.

    Op identity in the signature comes from the shared scheme
    (_fused.op_identity): registry ops key by name, closure-backed
    cached-op jits fold in a per-fn token, and per-call Function ops are
    uncacheable — two same-shaped Function instances must never replay
    each other's compiled program.
    """
    import numpy as _np

    _static_key = _static_key_shared

    key_index = {k: i for i, k in enumerate(seed_keys)}
    dyn_vals: List = []
    plan = []
    sig_entries = []
    as_dyn = lambda v: (_is_jax_value(v) or
                        isinstance(v, _np.ndarray) or
                        hasattr(v, "__array_interface__"))
    for e in used:
        slots = []
        sig_slots = []
        for k, c in zip(e.in_keys, e.in_consts):
            if k is not None and k in key_index:
                slots.append(("env", key_index[k]))
                sig_slots.append(("env", key_index[k]))
            elif as_dyn(c):
                slots.append(("dyn", len(dyn_vals)))
                sig_slots.append(("dyn", len(dyn_vals)))
                dyn_vals.append(jnp.asarray(c))
            else:
                slots.append(("static", c))
                sig_slots.append(("static", _static_key(c)))
        attr_static, attr_dyn = [], []
        for name in sorted(e.attrs):
            v = e.attrs[name]
            if as_dyn(v):
                attr_dyn.append((name, len(dyn_vals)))
                dyn_vals.append(jnp.asarray(v))
            else:
                attr_static.append((name, v))
        outs_idx = []
        for k in e.out_keys:
            if k not in key_index:
                key_index[k] = len(key_index)
            outs_idx.append(key_index[k])
        plan.append((e.op.fn, tuple(slots), tuple(attr_static),
                     tuple(attr_dyn), tuple(outs_idx)))
        sig_entries.append((
            _op_identity(e.op), tuple(sig_slots),
            tuple((n, _static_key(v)) for n, v in attr_static),
            tuple(attr_dyn), tuple(outs_idx)))
    head_slots = tuple(key_index[h] for h in head_keys)
    env_size = len(key_index)
    n_seeds = len(seed_keys)

    aval = lambda v: (tuple(v.shape), str(v.dtype))
    sig = (tuple(sig_entries), head_slots, n_seeds,
           tuple(aval(p) for p in primals),
           tuple(aval(d) for d in dyn_vals),
           tuple(aval(c) if c is not None else None
                 for c in (cts_in or [])) if cts_in is not None else None)

    if _BWD_CACHE.should_skip(sig):
        # negative cache with bounded retry: structurally untraceable
        # sigs are pinned to eager permanently; transient failures get a
        # few re-trace attempts before giving up (a single flaky failure
        # must not demote a structure to per-op dispatch forever)
        raise _Uncacheable("structure previously failed to compile")
    runner = _BWD_CACHE.get(sig)
    if runner is None:
        def fwd(seed_vals, dyn):
            env = [None] * env_size
            env[:n_seeds] = list(seed_vals)
            for op_fn, slots, attr_static, attr_dyn, outs_idx in plan:
                args = [env[i] if tag == "env"
                        else (dyn[i] if tag == "dyn" else i)
                        for tag, i in slots]
                attrs = dict(attr_static)
                for name, j in attr_dyn:
                    attrs[name] = dyn[j]
                outs = op_fn(*args, **attrs)
                if not isinstance(outs, tuple):
                    outs = (outs,)
                for i, o in zip(outs_idx, outs):
                    env[i] = o
            return [env[i] for i in head_slots]

        @jax.jit
        def runner(seed_vals, dyn, cts):
            heads, vjp_fn = jax.vjp(lambda sv: fwd(sv, dyn),
                                    list(seed_vals))
            full_cts = [jnp.ones_like(h) if (cts is None or
                                             cts[i] is None)
                        else cts[i]
                        for i, h in enumerate(heads)]
            (grads,) = vjp_fn(full_cts)
            return grads

        # cache only after a successful first run (a broken runner in
        # the cache would re-trace + fail on every later step)
        try:
            out = runner(list(primals), dyn_vals, cts_in)
        except Exception as e:
            _BWD_CACHE.mark_failed(sig,
                                   permanent=_structural_failure(e))
            raise
        _BWD_CACHE.put(sig, runner)
        return out

    return runner(list(primals), dyn_vals, cts_in)


def backward(heads, head_grads=None, retain_graph: bool = False,
             train_mode: bool = True) -> None:
    """Compute gradients of heads w.r.t. all marked variables (reference:
    AutogradRuntime::ComputeGradient, src/ndarray/autograd.cc:229-320).

    Reconstructs a pure function marked-vars -> heads by replaying the tape,
    then runs one ``jax.vjp``. Gradients land in each variable's attached
    grad buffer honoring its grad_req (write/add/null — reference
    OpReqType semantics, include/mxnet/op_attr_types.h:45-58). All values are
    the ones recorded at trace time; later mutations of inputs do not change
    the result (matching the reference's saved-node semantics).
    """
    from .ndarray import NDArray  # cycle-free at call time

    if not isinstance(heads, (list, tuple)):
        heads = [heads]
    if head_grads is not None and not isinstance(head_grads, (list, tuple)):
        head_grads = [head_grads]

    s = _st()
    head_keys = [(h._uid, h._version) for h in heads]

    # Backward slice of the tape reaching the heads.
    needed = set(head_keys)
    used: List[_TapeEntry] = []
    for entry in reversed(s.tape):
        if any(k in needed for k in entry.out_keys):
            used.append(entry)
            needed.update(entry.in_keys)
    used.reverse()

    produced = set()
    for e in used:
        produced.update(e.out_keys)

    # Record-time constants per key (first occurrence wins: values at the
    # version are identical wherever captured).
    const_of = {}
    for e in used:
        for k, c in zip(e.in_keys, e.in_consts):
            if k is not None:   # None = non-array positional constant
                const_of.setdefault(k, c)

    # Seeds: every (uid, version) of a marked variable that the slice consumes
    # but does not itself produce is a differentiation leaf. A variable
    # mutated *outside* the tape mid-recording contributes one leaf per
    # version; gradients of the versions are summed into its grad buffer.
    seeds = []  # (var, key, primal value)
    for uid, ref in list(s.marked.items()):
        var = ref()
        if var is None:
            del s.marked[uid]
            continue
        for k, c in const_of.items():
            if k[0] == uid and k not in produced:
                seeds.append((var, k, c))
        cur_key = (var._uid, var._version)
        if cur_key in needed and cur_key not in produced and \
                all(sk != cur_key for _, sk, _ in seeds):
            seeds.append((var, cur_key, var._data))

    if not seeds:
        raise ValueError(
            "backward: no marked variables reach the heads — call "
            "mark_variables/attach_grad and compute inside autograd.record()")

    seed_keys = [k for _, k, _ in seeds]

    def replay(seed_vals):
        env = dict(zip(seed_keys, seed_vals))
        for entry in used:
            args = [env.get(k, c) for k, c in zip(entry.in_keys, entry.in_consts)]
            outs = entry.op.fn(*args, **entry.attrs)
            if not isinstance(outs, tuple):
                outs = (outs,)
            for k, v in zip(entry.out_keys, outs):
                env[k] = v
        try:
            return [env[h] for h in head_keys]
        except KeyError:
            raise ValueError(
                "backward: a head was not produced by the recorded graph "
                "(was it computed outside autograd.record(), or mutated "
                "in-place after recording?)") from None

    primals = [p for _, _, p in seeds]
    grads = None
    if head_grads is not None:
        cts_in = [
            (g.data if isinstance(g, NDArray) else jnp.asarray(g))
            if g is not None else None
            for g in head_grads
        ]
    else:
        cts_in = None
    try:
        # fast path: the tape slice compiles to ONE cached XLA program
        # keyed on its structure — repeated same-shape training loops
        # (the gluon hot path) stop paying per-op dispatch in both
        # directions and recompile nothing after the first step
        grads = _compiled_backward(used, seed_keys, head_keys, primals,
                                   cts_in)
    except Exception:                                  # noqa: BLE001
        # correctness over speed: any structure the compiled path cannot
        # express falls back to the original eager replay
        head_vals, vjp_fn = jax.vjp(lambda *vs: replay(list(vs)), *primals)
        if cts_in is None:
            cts = [jnp.ones_like(h) for h in head_vals]
        else:
            cts = [c if c is not None else jnp.ones_like(h)
                   for c, h in zip(cts_in, head_vals)]
        grads = vjp_fn(cts)

    # Sum per-variable (a var may seed several versions), then commit.
    acc = {}
    for (var, _, _), g in zip(seeds, grads):
        if var._uid in acc:
            acc[var._uid] = (var, acc[var._uid][1] + g)
        else:
            acc[var._uid] = (var, g)
    for var, g in acc.values():
        req = getattr(var, "_grad_req", "write")
        if req == "null" or var._grad is None:
            continue
        if req == "add":
            var._grad._data = var._grad.data + g.astype(var._grad.dtype)
        else:
            var._grad._data = g.astype(var._grad.dtype)
        var._grad._version += 1

    if not retain_graph:
        used_set = set(map(id, used))
        s.tape = [e for e in s.tape if id(e) not in used_set]


def grad(heads, variables, head_grads=None, retain_graph=None,
         create_graph=False, train_mode=True):
    """Return gradients of heads w.r.t. ``variables`` without touching their
    attached grad buffers (reference: later mx.autograd.grad; provided for
    Gluon-style code)."""
    from .ndarray import NDArray
    single = not isinstance(variables, (list, tuple))
    if single:
        variables = [variables]
    saved = [(v._grad, v._grad_req) for v in variables]
    outs = []
    try:
        for v in variables:
            v._grad = NDArray(jnp.zeros_like(v._data))
            v._grad_req = "write"
            _st().marked[v._uid] = weakref.ref(v)
        backward(heads, head_grads,
                 retain_graph=True if retain_graph is None else retain_graph,
                 train_mode=train_mode)
        outs = [v._grad for v in variables]
    finally:
        for v, (g, r) in zip(variables, saved):
            v._grad, v._grad_req = g, r
    return outs[0] if single else outs


class Function:
    """User-defined differentiable function (reference:
    python/mxnet/autograd.py:308-424 ``Function`` with forward/backward).

    Subclass and override :meth:`forward` (NDArray computation) and
    :meth:`backward` (maps output gradients to input gradients). During tape
    replay the call is wrapped in ``jax.custom_vjp``; ``backward`` may use
    tensors saved on ``self`` during ``forward`` — the forward is re-run
    inside the backward trace so the saved state is trace-consistent (the
    TPU-era equivalent of the reference saving output NDArrays on the node).
    """

    def forward(self, *inputs):
        raise NotImplementedError

    def backward(self, *output_grads):
        raise NotImplementedError

    def __call__(self, *inputs):
        from .ndarray import NDArray
        from .ops.registry import OpDef

        with pause():
            outputs = self.forward(*inputs)
        single = not isinstance(outputs, (list, tuple))
        out_list = [outputs] if single else list(outputs)

        if is_recording():
            n_in = len(inputs)

            def _run_fwd(*vals):
                nds = [NDArray(v) for v in vals]
                with pause():
                    outs = self.forward(*nds)
                outs = [outs] if not isinstance(outs, (list, tuple)) else outs
                return tuple(o._data for o in outs)

            @jax.custom_vjp
            def fn(*vals):
                return _run_fwd(*vals)

            def fn_fwd(*vals):
                return _run_fwd(*vals), vals

            def fn_bwd(res_vals, gs):
                # Re-run forward so self-saved tensors belong to this trace.
                nds = [NDArray(v) for v in res_vals]
                with pause():
                    self.forward(*nds)
                    igrads = self.backward(*[NDArray(g) for g in gs])
                igrads = [igrads] if not isinstance(igrads, (list, tuple)) \
                    else list(igrads)
                if len(igrads) != n_in:
                    raise ValueError(
                        "Function.backward returned %d gradients for %d inputs"
                        % (len(igrads), n_in))
                return tuple(g._data for g in igrads)

            fn.defvjp(fn_fwd, fn_bwd)
            op = OpDef("_Function_%s" % type(self).__name__, fn,
                       num_inputs=len(inputs))
            in_keys = [(a._uid, a._version) for a in inputs]
            in_consts = [a._data for a in inputs]
            _record_op(op, {}, in_keys, in_consts, out_list)

        return out_list[0] if single else out_list


def get_symbol(x):  # pragma: no cover - reference-API stub
    """The reference exposes autograd.get_symbol; the TPU build's tape has no
    nnvm symbol to return. Use Symbol tracing instead."""
    raise NotImplementedError("use mxnet_tpu.symbol tracing instead")

"""mxnet_tpu — a TPU-native deep-learning framework with the capability
surface of Apache MXNet v0.11 (reference: Guneet-Dhillon/mxnet).

Idiomatic re-design, not a port (SURVEY.md §7): the reference's dependency
engine / memory planner / CUDA kernels are replaced by XLA's async dispatch,
buffer assignment and codegen; distribution is mesh-sharding + collectives
instead of ps-lite; custom kernels are Pallas instead of NVRTC.

Usage mirrors the reference::

    import mxnet_tpu as mx
    a = mx.nd.ones((2, 3), ctx=mx.tpu(0))
    net = mx.sym.FullyConnected(mx.sym.Variable("data"), num_hidden=10)
    mod = mx.mod.Module(net, context=mx.tpu(0))
"""
from .base import MXNetError
from .context import Context, cpu, gpu, tpu, current_context, num_devices
from .name import NameManager, AttrScope
from . import amp
from . import ops
from . import operator
from . import ndarray
from . import ndarray as nd
from . import random
from . import autograd
from . import symbol
from . import symbol as sym
from . import executor
from . import test_utils
from . import optimizer
from . import optimizer as opt
from . import initializer
from . import initializer as init
from . import metric
from . import lr_scheduler
from . import callback
from . import model
from . import config
from . import filesystem
from . import storage
from . import io
from . import image
from . import profiler
from . import obs
from . import monitor
from . import monitor as mon
from . import visualization
from . import visualization as viz
from . import rtc
from . import contrib
from . import recordio
from . import kvstore
from . import kvstore as kv
from . import parallel
from . import module
from . import module as mod
from . import predictor
from .predictor import Predictor
from . import serve
from . import gluon
from . import models
from . import rnn
from .initializer import Xavier, Uniform, Normal, Orthogonal, Zero, One, Constant

config._apply_import_knobs()


def __getattr__(name):
    # mx.analysis resolves lazily (PEP 562): the analyzer must never load
    # unless used — the MXNET_TPU_ANALYZE=off bind path is asserted to be
    # import-free (tests/test_analysis.py::test_analyze_off_is_zero_cost).
    # elastic/faults ride the same hook (the supervisor is subprocess
    # tooling, not a training-path dependency). data too: a fit fed by
    # any other iterator must never import the streaming loader or its
    # multiprocessing machinery (tools/data_smoke.py zero-cost gate).
    # importlib, NOT `from . import analysis`: the fromlist form re-enters
    # this __getattr__ via importlib._handle_fromlist -> infinite recursion
    # tune likewise: MXNET_TPU_TUNE unset must mean the tuner is never
    # imported (tools/tune_smoke.py zero-cost gate)
    # fleet likewise: a plain serve process must never import the
    # multi-replica gateway or pay its counters (tools/fleet_smoke.py
    # zero-cost gate)
    if name in ("analysis", "checkpoint", "data", "elastic", "faults",
                "fleet", "tune"):
        import importlib
        return importlib.import_module("." + name, __name__)
    raise AttributeError("module %r has no attribute %r" % (__name__, name))


__version__ = "0.1.0"

__all__ = [
    "MXNetError", "Context", "cpu", "gpu", "tpu", "current_context",
    "num_devices", "nd", "ndarray", "random", "autograd", "sym", "symbol",
    "executor", "NameManager", "AttrScope", "test_utils",
]

"""``mx.storage`` — memory spaces, host staging, and allocation stats.

Reference: the Storage layer (``include/mxnet/storage.h:35-93``,
``src/storage/``) with its device pools and ``PinnedMemoryStorage``
(cudaMallocHost for fast DMA, SURVEY.md §2.2). On TPU, PJRT owns the
allocator (the pooling job of GPUPooledStorageManager), so this layer
exposes what remains meaningful:

* **memory spaces** — every device advertises ``device`` (HBM),
  ``pinned_host`` and ``unpinned_host`` kinds; ``as_in_memory`` moves an
  NDArray between them. Pinned host memory is the TPU twin of the
  reference's PinnedMemoryStorage: staged there, device transfers are
  DMA-fast, and large cold tensors (optimizer state, checkpoint shards)
  can live off-HBM.
* **host offload** — ``offload``/``restore`` move whole param/state dicts
  between HBM and pinned host memory (the activation/optimizer-state
  offload pattern of large-model training).
* **allocation stats** — ``memory_stats`` surfaces the PJRT allocator
  counters (bytes_in_use, peak_bytes_in_use, ...) that the reference's
  storage managers tracked internally.
"""
from __future__ import annotations

from typing import Dict, List, Optional

from .context import Context, current_context

__all__ = ["memory_kinds", "memory_stats", "as_in_memory", "memory_kind_of",
           "offload", "restore", "PINNED_HOST", "DEVICE"]

DEVICE = "device"
PINNED_HOST = "pinned_host"


def _device(ctx: Optional[Context]):
    return (ctx or current_context()).jax_device


def memory_kinds(ctx: Optional[Context] = None) -> List[str]:
    """Memory spaces addressable by ``ctx``'s device."""
    return [m.kind for m in _device(ctx).addressable_memories()]


def memory_stats(ctx: Optional[Context] = None) -> Dict[str, int]:
    """PJRT allocator counters (empty dict when the backend exposes none,
    e.g. CPU)."""
    return dict(_device(ctx).memory_stats() or {})


def memory_kind_of(arr) -> str:
    """The memory space an NDArray currently lives in."""
    data = arr.data if hasattr(arr, "data") else arr
    kind = getattr(data.sharding, "memory_kind", None)
    return kind or DEVICE


def as_in_memory(arr, kind: str, ctx: Optional[Context] = None):
    """Copy an NDArray into the given memory space of ``ctx``'s device
    (reference parity: Storage::Alloc with a pinned/device context)."""
    import jax
    from jax.sharding import SingleDeviceSharding
    from . import ndarray as nd
    data = arr.data if hasattr(arr, "data") else arr
    sharding = SingleDeviceSharding(_device(ctx), memory_kind=kind)
    return nd.NDArray(jax.device_put(data, sharding))


def offload(params: Dict[str, object], ctx: Optional[Context] = None,
            kind: str = PINNED_HOST) -> Dict[str, object]:
    """Stage a dict of NDArrays into host memory, freeing their HBM."""
    return {k: as_in_memory(v, kind, ctx) for k, v in params.items()}


def restore(params: Dict[str, object],
            ctx: Optional[Context] = None) -> Dict[str, object]:
    """Bring an offloaded dict back into device memory."""
    return {k: as_in_memory(v, DEVICE, ctx) for k, v in params.items()}

"""``mx.storage`` — memory spaces, host staging, and allocation stats.

Reference: the Storage layer (``include/mxnet/storage.h:35-93``,
``src/storage/``) with its device pools and ``PinnedMemoryStorage``
(cudaMallocHost for fast DMA, SURVEY.md §2.2). On TPU, PJRT owns the
allocator (the pooling job of GPUPooledStorageManager), so this layer
exposes what remains meaningful:

* **memory spaces** — accelerator devices advertise ``device`` (HBM),
  ``pinned_host`` and ``unpinned_host`` kinds; ``as_in_memory`` moves an
  NDArray between them. Pinned host memory is the TPU twin of the
  reference's PinnedMemoryStorage: staged there, device transfers are
  DMA-fast, and large cold tensors (optimizer state, checkpoint shards)
  can live off-HBM.
* **host offload** — ``offload``/``restore`` move whole param/state dicts
  between HBM and pinned host memory (the activation/optimizer-state
  offload pattern of large-model training).
* **allocation stats** — ``memory_stats`` surfaces the PJRT allocator
  counters (bytes_in_use, peak_bytes_in_use, ...) that the reference's
  storage managers tracked internally.

Capability note: the memory-kinds surface drifts across jax/PJRT
versions and backends — this build's CPU backend advertises only
``unpinned_host`` (which doubles as its default/"device" space).
Everything here degrades gracefully: ``supports_memory_kind`` is the
capability probe, ``memory_kind_of`` reports ``DEVICE`` for whatever the
device's *default* space is called, and ``as_in_memory`` falls back to
the nearest advertised space instead of raising on backends without a
distinct pinned pool.
"""
from __future__ import annotations

from typing import Dict, List, Optional

from .context import Context, current_context

__all__ = ["memory_kinds", "memory_stats", "as_in_memory", "memory_kind_of",
           "supports_memory_kind", "default_memory_kind",
           "offload", "restore", "PINNED_HOST", "UNPINNED_HOST", "DEVICE"]

DEVICE = "device"
PINNED_HOST = "pinned_host"
UNPINNED_HOST = "unpinned_host"


def _device(ctx: Optional[Context]):
    return (ctx or current_context()).jax_device


def memory_kinds(ctx: Optional[Context] = None) -> List[str]:
    """Memory spaces addressable by ``ctx``'s device (empty when the
    runtime predates the memories API)."""
    dev = _device(ctx)
    try:
        return [m.kind for m in dev.addressable_memories()]
    except (AttributeError, NotImplementedError):
        return []


def default_memory_kind(ctx: Optional[Context] = None) -> str:
    """The kind of the device's default memory space — what ``DEVICE``
    means on this backend (``device`` on TPU HBM, ``unpinned_host`` on
    this build's CPU backend)."""
    dev = _device(ctx)
    try:
        return dev.default_memory().kind
    except (AttributeError, NotImplementedError):
        return DEVICE


def supports_memory_kind(kind: str, ctx: Optional[Context] = None) -> bool:
    """Capability probe: can arrays be placed in ``kind`` on this
    device? ``DEVICE`` additionally matches the default space whatever
    its advertised name — and is always available, even on runtimes
    predating the memories API (where ``memory_kind_of``/
    ``as_in_memory`` likewise fall back to the default space)."""
    if kind == DEVICE:
        return True
    return kind in memory_kinds(ctx)


def memory_stats(ctx: Optional[Context] = None) -> Dict[str, int]:
    """PJRT allocator counters (empty dict when the backend exposes none,
    e.g. CPU)."""
    return dict(_device(ctx).memory_stats() or {})


def memory_kind_of(arr) -> str:
    """The memory space an NDArray currently lives in. The device's
    default space reports as ``DEVICE`` regardless of its
    backend-specific name, so "is this on-device?" checks are portable."""
    data = arr.data if hasattr(arr, "data") else arr
    kind = getattr(data.sharding, "memory_kind", None)
    if kind is None:
        return DEVICE
    try:
        if kind == data.sharding._device_assignment[0].default_memory().kind:
            return DEVICE
    except (AttributeError, IndexError, NotImplementedError):
        pass
    return kind


def _resolve_kind(kind: str, ctx: Optional[Context]) -> Optional[str]:
    """Map a requested kind onto what this device actually advertises
    (graceful fallback), or None for a plain default-space placement."""
    kinds = memory_kinds(ctx)
    if not kinds:
        return None                  # memories API absent: default space
    if kind in kinds:
        return kind
    if kind == DEVICE:
        return None                  # default space IS the device space
    if kind == PINNED_HOST and UNPINNED_HOST in kinds:
        # no pinned pool on this backend (CPU): stage to plain host
        # memory — offload still works, transfers just aren't DMA-pinned
        return UNPINNED_HOST
    raise ValueError(
        "memory kind %r not addressable by this device (advertised: %s)"
        % (kind, kinds))


def as_in_memory(arr, kind: str, ctx: Optional[Context] = None):
    """Copy an NDArray into the given memory space of ``ctx``'s device
    (reference parity: Storage::Alloc with a pinned/device context).
    Falls back to the nearest advertised space on backends without the
    requested one — probe with :func:`supports_memory_kind` when exact
    placement matters."""
    import jax
    from jax.sharding import SingleDeviceSharding
    from . import ndarray as nd
    data = arr.data if hasattr(arr, "data") else arr
    resolved = _resolve_kind(kind, ctx)
    if resolved is None:
        return nd.NDArray(jax.device_put(data, _device(ctx)))
    sharding = SingleDeviceSharding(_device(ctx), memory_kind=resolved)
    return nd.NDArray(jax.device_put(data, sharding))


def offload(params: Dict[str, object], ctx: Optional[Context] = None,
            kind: str = PINNED_HOST) -> Dict[str, object]:
    """Stage a dict of NDArrays into host memory, freeing their HBM."""
    return {k: as_in_memory(v, kind, ctx) for k, v in params.items()}


def restore(params: Dict[str, object],
            ctx: Optional[Context] = None) -> Dict[str, object]:
    """Bring an offloaded dict back into device memory."""
    return {k: as_in_memory(v, DEVICE, ctx) for k, v in params.items()}

"""``mx.config`` — the environment-variable knob layer.

Reference: the ~30 ``MXNET_*`` env vars of ``docs/how_to/env_var.md:8-125``
backed by ``dmlc::Parameter`` reflection. Same surface here: typed,
documented knobs read from the environment with runtime override, each
wired to a real control point (not parity theater):

* ``MXNET_ENGINE_TYPE=NaiveEngine`` — synchronous dispatch: every
  imperative op blocks until its result is ready, serializing execution
  exactly like the reference's debug engine (env_var.md: the race-
  detection/debug mode, SURVEY §5.2). Default ``ThreadedEngine`` keeps
  XLA's async dispatch.
* ``MXNET_CPU_WORKER_NTHREADS`` — decode/augment worker threads of the
  record iterators (reference: same knob feeding the IO thread pool).
* ``MXNET_PREFETCH_BUFFER`` — batches buffered ahead by the record
  iterators (reference: iter_prefetcher.h depth).
* ``MXNET_EXEC_ENABLE_REMAT`` — rematerialize the fused train step's
  forward under ``jax.checkpoint``: trades recompute FLOPs for activation
  HBM (the TPU form of the reference's memory-saving exec knobs,
  MXNET_EXEC_ENABLE_INPLACE / bulk-exec family).
* ``MXNET_COMPILATION_CACHE_DIR`` — persistent XLA compile cache
  directory (reference: MXNET_CUDNN_AUTOTUNE et al. cache compiled
  choices across runs).
* ``MXNET_PROFILER_AUTOSTART`` — start the profiler at import
  (reference: same knob).
* ``MXNET_KVSTORE_HEARTBEAT_STALE_SECS`` — seconds without a heartbeat
  before a worker counts as dead (reference: ps-lite
  PS_HEARTBEAT_TIMEOUT feeding get_num_dead_node, SURVEY §5.3).
"""
from __future__ import annotations

import os
from typing import Any, Callable, Dict

__all__ = ["get", "set", "describe", "register", "KNOBS"]


class _Knob:
    def __init__(self, name: str, typ: Callable, default: Any, doc: str):
        self.name = name
        self.typ = typ
        self.default = default
        self.doc = doc


KNOBS: Dict[str, _Knob] = {}
_overrides: Dict[str, Any] = {}
_listeners: Dict[str, list] = {}


def register(name: str, typ, default, doc: str) -> None:
    KNOBS[name] = _Knob(name, typ, default, doc)


def on_change(name: str, fn: Callable[[Any], None]) -> None:
    """Call ``fn(new_value)`` whenever ``set``/``reset`` changes the knob —
    lets hot paths cache a knob as a module-level constant instead of
    re-reading the environment per call."""
    KNOBS[name]   # raise on unknown
    _listeners.setdefault(name, []).append(fn)


def _notify(name: str) -> None:
    for fn in _listeners.get(name, ()):
        fn(get(name))


def _parse_bool(v) -> bool:
    if isinstance(v, bool):
        return v
    return str(v).strip().lower() in ("1", "true", "yes", "on")


register("MXNET_ENGINE_TYPE", str, "ThreadedEngine",
         "NaiveEngine = synchronous op dispatch (debug/race detection); "
         "ThreadedEngine = XLA async dispatch")
register("MXNET_CPU_WORKER_NTHREADS", int, 4,
         "decode/augment worker threads in record iterators")
register("MXNET_PREFETCH_BUFFER", int, 4,
         "batches buffered ahead by record iterators")
register("MXNET_EXEC_ENABLE_REMAT", _parse_bool, False,
         "jax.checkpoint the fused train step's forward (less HBM, more "
         "FLOPs)")
register("MXNET_COMPILATION_CACHE_DIR", str, "",
         "persistent XLA compile cache directory")
register("MXNET_PROFILER_AUTOSTART", _parse_bool, False,
         "start mx.profiler at import")
register("MXNET_KVSTORE_HEARTBEAT_STALE_SECS", float, 20.0,
         "heartbeat staleness threshold for get_num_dead_node")
register("MXNET_KVSTORE_BIGARRAY_BOUND", int, 1000000,
         "elements per fused-allreduce chunk in the dist kvstore "
         "(reference: big-array server sharding, kvstore_dist.h:292)")
register("MXNET_USE_NATIVE_IO", _parse_bool, True,
         "use the C++ data path (libmxnative: RecordIO codec, jpeg/png "
         "decode, threaded augment pipeline); 0 = pure-Python/cv2 path")
register("MXNET_TPU_FUSED_TRAINER", _parse_bool, True,
         "gluon Trainer.step / Module.update: batch all parameter updates "
         "into one structure-cached, donated jitted program; 0 = eager "
         "per-param dispatch")
register("MXNET_TPU_SERVE", _parse_bool, True,
         "serve.InferenceServer: coalesce concurrent requests into "
         "bucket-padded micro-batches served by a finite executable set; "
         "0 = per-request eager forward in the caller thread (no "
         "batching, no bucketing — the debugging/bisection fallback)")
register("MXNET_TPU_SERVE_MAX_BATCH", int, 32,
         "serve: default micro-batch row bound (requests coalesced per "
         "dispatch; the largest batch bucket)")
register("MXNET_TPU_SERVE_MAX_DELAY_US", int, 2000,
         "serve: default batching window — how long the oldest queued "
         "request may wait for co-riders before the batch launches")
register("MXNET_TPU_SERVE_QUEUE_BOUND", int, 1024,
         "serve: default admission bound; submit() load-sheds (QueueFull) "
         "when this many requests are already queued")
register("MXNET_TPU_SERVE_KV_INT8", _parse_bool, False,
         "serve.GenerativeServer: store the decode KV cache as int8 with "
         "per-page f32 scales instead of f32 — the cache reservation "
         "shrinks ~4x (≈2x max resident sequences under a typical "
         "MXNET_TPU_ANALYZE_HBM_BUDGET once scales and slack are paid), "
         "at a documented logits tolerance (tests/test_serve_decode.py)")
register("MXNET_TPU_SERVE_MAX_SEQUENCES", int, 8,
         "serve.GenerativeServer: default max resident decode sequences "
         "(the KV cache's preallocated slot count; also the decode "
         "batch width). Overridden by the max_sequences argument")
register("MXNET_TPU_SERVE_PREFILL_TOKENS", int, 2048,
         "serve.GenerativeServer: prefill token budget per scheduler "
         "iteration — joins admitted between two decode steps may "
         "prefill at most this many (bucket-padded) prompt tokens, so "
         "a burst of long prompts cannot starve the running batch's "
         "inter-token latency")
register("MXNET_TPU_SERVE_DECODE_BUCKETS", str, "",
         "serve.GenerativeServer: explicit comma-separated decode "
         "sequence-length bucket ladder (e.g. '128,256,512'); empty = "
         "powers of two from the page size up to the model's max "
         "sequence length. Every bucket must be a multiple of the KV "
         "page size (the int8 per-page scale grid)")
register("MXNET_TPU_SERVE_KV_PAGE", int, 16,
         "serve.GenerativeServer: KV-cache page size in tokens — slot "
         "capacity is allocated and freed page-at-a-time, and int8 mode "
         "keeps one quantization scale per page. Must divide every "
         "decode bucket")
register("MXNET_TPU_FLEET", _parse_bool, False,
         "fleet.Gateway: opt-in switch for the multi-replica serving "
         "fleet (mxnet_tpu.fleet). Off = the gateway refuses to start "
         "and the package is never imported by the serve path — "
         "spawning replica subprocesses is an explicit deployment "
         "decision, not a framework default")
register("MXNET_TPU_FLEET_REPLICAS", int, 2,
         "fleet: default replica-world size when Gateway(replicas=) / "
         "python -m mxnet_tpu.fleet serve --replicas is not given — "
         "the env-discovery path for launcher-provisioned worlds")
register("MXNET_TPU_FLEET_STATS_PERIOD", float, 0.5,
         "fleet.Gateway: heartbeat cadence in seconds — each tick "
         "polls every replica's stats() snapshot (queue depth + KV "
         "occupancy feed least-loaded routing) and doubles as the "
         "liveness probe (connection REFUSED marks the replica dead; "
         "a timeout is ambiguous and never kills, the ProbeRing rule)")
register("MXNET_TPU_FLEET_QUEUE_BOUND", int, 256,
         "fleet.Gateway: admission bound on gateway-resident in-flight "
         "requests; beyond it submits shed with QueueFull instead of "
         "growing an unbounded backlog (same contract as the serve "
         "queue bound, one level up)")
register("MXNET_TPU_FLEET_MAX_RESPAWNS", int, 16,
         "fleet: per-replica supervisor respawn budget — a replica "
         "that dies more than this many times is marked failed and "
         "left down (the elastic bounded-restart discipline; backoff "
         "reuses MXNET_TPU_ELASTIC_BACKOFF/_MAX between attempts)")
register("MXNET_TPU_FLEET_SPAWN_TIMEOUT", float, 240.0,
         "fleet: seconds a freshly spawned replica may take to answer "
         "its first PING (model build + bind + AOT warm start); past "
         "it the spawn is scored failed and retried under the respawn "
         "budget (PhaseGuard discipline — no unbounded waits)")
def _parse_analyze_mode(v) -> str:
    s = str(v).strip().lower()
    if s in ("", "0", "off", "false", "no", "none"):
        return "off"
    if s in ("warn", "warning", "1", "on", "true", "yes"):
        return "warn"
    if s == "strict":
        return "strict"
    raise ValueError(
        "MXNET_TPU_ANALYZE must be off|warn|strict, got %r" % (v,))


register("MXNET_TPU_ANALYZE", _parse_analyze_mode, "off",
         "run mxnet_tpu.analysis graph passes at Executor/Module bind: "
         "off = analyzer never imported (zero cost), warn = log "
         "WARNING+ findings, strict = raise MXNetError on ERROR "
         "findings before any compile")
register("MXNET_TPU_ANALYZE_HBM_BUDGET", str, "",
         "per-device memory budget for the analysis hbm-budget pass "
         "(bytes, K/M/G/T suffixes: '16G'); when the static peak "
         "estimate (bound buffers + activation high-water) exceeds it "
         "the bind gets an ERROR finding naming the offending arrays — "
         "rejected before any compile under MXNET_TPU_ANALYZE=strict. "
         "Empty = no budget")
register("MXNET_TPU_ANALYZE_HBM_GBPS", float, 0.0,
         "HBM bandwidth (GB/s) for the analysis roofline balance point; "
         "0 = auto-detect from the TPU device_kind table (v2-v6); set "
         "explicitly on unknown devices and in CPU tests")
register("MXNET_TPU_ANALYZE_ICI_GBPS", float, 0.0,
         "per-link ICI bandwidth (GB/s) for the analysis comm cost "
         "model's time estimates; 0 = device_kind table, 50 GB/s for "
         "unknown devices")
register("MXNET_TPU_ASYNC_WINDOW", int, 2,
         "fit(): max train steps dispatched ahead of device completion "
         "(sliding-window sync caps in-flight work); 0 = fully "
         "synchronous per-batch loop (the kill switch — exactly the "
         "pre-async behavior)")
register("MXNET_TPU_DEVICE_PREFETCH", int, 2,
         "fit(): batches device-placed ahead of the step consuming them "
         "(PrefetchingIter device stage, double-buffered H2D overlap); "
         "0 = place each batch synchronously on the critical path")
register("MXNET_TPU_DATA_WORKERS", int, 2,
         "mx.data.DataLoader: default worker PROCESSES decoding disjoint "
         "shard ranges in parallel (overridden by the num_workers "
         "argument); 0 = decode inline in the consumer thread")
register("MXNET_TPU_DATA_QUEUE_DEPTH", int, 4,
         "mx.data.DataLoader: decoded batches buffered per worker "
         "process (the backpressure bound — a stalled consumer parks "
         "the workers instead of buffering the epoch in RAM)")
register("MXNET_TPU_DATA_MP", _parse_bool, True,
         "mx.data.DataLoader: multi-process decode kill switch — 0 "
         "forces the inline single-thread path regardless of "
         "num_workers (same stream order, the bisection fallback)")
register("MXNET_TPU_DEVICE_METRICS", _parse_bool, True,
         "EvalMetric.update_device: accumulate (sum, count) as device "
         "reductions chained after the step, host sync deferred to "
         "get()/log boundaries; 0 = per-batch asnumpy host path")
register("MXNET_TPU_CKPT_ASYNC", _parse_bool, True,
         "mx.checkpoint: hand checkpoint serialization (device fetch, "
         "checksums, npz encode, fsync) to the bounded background writer "
         "thread so the step loop resumes after snapshot capture; 0 = "
         "synchronous saves that block the caller for the full write")
register("MXNET_TPU_CKPT_KEEP", int, 5,
         "mx.checkpoint: retention — keep the newest N valid checkpoints "
         "after each save (keep-every-K survivors and the newest valid "
         "checkpoint are always kept); 0 = keep everything")
register("MXNET_TPU_CKPT_WRITE_RETRIES", int, 3,
         "mx.checkpoint: bounded retry of a failed checkpoint write on "
         "TRANSIENT IO errors (EIO/ENOSPC/EINTR) with exponential "
         "backoff before the failure is recorded and re-raised at "
         "close; each retry counts ckpt_write_retry. 0 = fail on the "
         "first error")
register("MXNET_TPU_FAULTS", str, "",
         "deterministic fault injection: comma list of "
         "<site>@<nth>[:kind] specs fired at named injection points "
         "(ckpt.arrays_write, ckpt.before_rename, ckpt.read_manifest, "
         "fit.batch, serve.submit, ...; kinds eio/enospc/eintr/raise/"
         "sigterm/sigkill/bitflip/truncate — see "
         "docs/architecture/elastic.md). Parsed once at import by "
         "mxnet_tpu.faults; zero-cost when empty. NEVER set in "
         "production")
register("MXNET_TPU_DIST_TIMEOUT", float, 120.0,
         "pod bootstrap: seconds each process waits for the whole pod to "
         "assemble (the roll-call deadline AND jax.distributed's "
         "initialization_timeout). A missing peer fails the bootstrap "
         "with an error naming the absent rank — never a hang")
register("MXNET_TPU_DIST_RETRIES", int, 1,
         "pod bootstrap: re-attempts of the distributed rendezvous after "
         "a timeout (a slow-starting peer gets one more window) before "
         "the error propagates; 0 = fail on the first timeout")
register("MXNET_TPU_HEARTBEAT_PERIOD", float, 5.0,
         "liveness heartbeat publish period in seconds "
         "(dist.heartbeat_start; the staleness deadline is "
         "MXNET_KVSTORE_HEARTBEAT_STALE_SECS on the READER's clock)")
register("MXNET_TPU_ELASTIC_STALL_SECS", float, 0.0,
         "coordinated pod: local stall watchdog — when > 0 and the "
         "training child's progress file stops advancing for this many "
         "seconds, the coordinator requests a POD-WIDE restart (drain + "
         "re-rendezvous; bulk-synchronous training stalls symmetrically, "
         "so one host's wedged child stalls every host — restarting the "
         "pod, not evicting a host, is the only sound response when "
         "every supervisor is still alive). 0 = disabled (long compiles "
         "and first-batch warmup must not trip it)")
register("MXNET_TPU_ELASTIC_DRAIN_GRACE", float, 20.0,
         "coordinated pod drain: seconds between the SIGTERM preemption "
         "notice and the SIGKILL escalation for a child wedged in a "
         "collective whose peer died")
register("MXNET_TPU_CKPT_POD_TIMEOUT", float, 120.0,
         "process-local checkpoint: seconds rank 0 waits for every "
         "host's shard record before the manifest commit (and peers "
         "wait for the commit) — a host dying mid-save aborts the save "
         "as a unit instead of committing a partial checkpoint")
register("MXNET_TPU_KV_RETRIES", int, 2,
         "coordination KV (dist.kv_set/kv_get): bounded re-attempts of a "
         "flaking KV operation (injected via the dist.kv fault site, or "
         "a real transient error) before it propagates; each retry "
         "counts dist_kv_retry. 0 = fail on the first error")
register("MXNET_TPU_PROBE_TIMEOUT", float, 2.0,
         "pod probe ring: per-probe TCP connect/handshake timeout in "
         "seconds (peer liveness adjudication when the control plane is "
         "unreachable; docs/architecture/elastic.md leader fail-over)")
register("MXNET_TPU_PROBE_ATTEMPTS", int, 3,
         "pod probe ring: probes per peer before its status is final — "
         "a single dropped SYN must not misjudge a live host; any "
         "'live' answer wins immediately")
register("MXNET_TPU_FAILOVER_PORT", int, 0,
         "pod control plane: fixed TCP port THIS host would re-host the "
         "coordination KV service on if elected leader (published in "
         "every generation's membership record); 0 = probe a fresh free "
         "port per generation")
register("MXNET_TPU_ELASTIC_MAX_RESTARTS", int, 10,
         "mx.elastic supervisor: restarts allowed before giving up and "
         "returning the child's exit status (exit 143 and crashes both "
         "count as preemptions)")
register("MXNET_TPU_ELASTIC_BACKOFF", float, 1.0,
         "mx.elastic supervisor: base seconds of the exponential "
         "restart backoff (doubles per consecutive restart, plus up to "
         "25 percent jitter)")
register("MXNET_TPU_ELASTIC_BACKOFF_MAX", float, 60.0,
         "mx.elastic supervisor: backoff ceiling in seconds")
register("MXNET_TPU_OBS", _parse_bool, False,
         "mx.obs: record structured spans (per-thread lanes + chrome-trace "
         "flow events linking one batch across prefetch/train/metric/"
         "checkpoint/serve threads) into the profiler event buffer even "
         "while the profiler state is 'stop'; 0 = span() is a shared "
         "no-op (zero allocations — counter-asserted by tests/test_obs.py)")
register("MXNET_TPU_OBS_METRICS_PORT", int, -1,
         "mx.obs: HTTP /metrics exposition (Prometheus text format) "
         "auto-started by serve.InferenceServer: -1 = off, 0 = ephemeral "
         "port (read it back from server.metrics_port), >0 = fixed port")
register("MXNET_TPU_OBS_PEAK_FLOPS", float, 0.0,
         "mx.obs: override the PER-DEVICE peak dense FLOP/s used for "
         "the obs_mfu gauge — a mesh-bound module's denominator is "
         "this times the mesh's device count (0 = auto-detect by TPU "
         "device_kind; set explicitly on unknown devices or in tests)")
register("MXNET_TPU_OBS_BLACKBOX", str, "",
         "mx.obs flight recorder: directory the bounded in-memory event "
         "ring (span closes, counter deltas, fault fires, pod "
         "transitions, checkpoint commit phases) is flushed to as "
         "blackbox-p<rank>.jsonl — periodically and at every terminal "
         "moment (fault fire, SIGTERM/143, NANCHECK abort, watchdog "
         "stall), so a killed host still leaves its last window on "
         "disk. Merge with `python -m mxnet_tpu.obs blackbox <dir>`. "
         "Empty = off (the recorder module is never imported)")
register("MXNET_TPU_OBS_BLACKBOX_RING", int, 512,
         "mx.obs flight recorder: events kept in the in-memory ring "
         "(each flush rewrites the file with exactly this window, so "
         "the on-disk artifact stays bounded at any run length)")
register("MXNET_TPU_OBS_BLACKBOX_FLUSH_SECS", float, 5.0,
         "mx.obs flight recorder: heartbeat flush period in seconds — "
         "the guarantee that a SIGKILL'd host still leaves a window no "
         "older than this on disk; 0 = event-driven flushes only")
register("MXNET_TPU_OBS_STRAGGLER_RATIO", float, 2.0,
         "pod straggler detection: flag a rank when the fastest rank's "
         "local work rate exceeds its by more than this factor "
         "(per-rank step windows published to the coordination KV at "
         "epoch log boundaries — zero extra per-step host syncs; the "
         "leader aggregates into report()'s 'pod' block, the "
         "obs_straggler counter and per-rank /metrics gauges). "
         "0 = disabled (the straggler module is never imported)")
def _parse_scan_layers(v) -> str:
    s = str(v).strip().lower()
    if s in ("", "0", "off", "false", "no", "none"):
        return "off"
    if s in ("auto", "on", "true", "yes", "1"):
        return "auto"
    if s.isdigit() and int(s) >= 2:
        return s
    raise ValueError(
        "MXNET_TPU_SCAN_LAYERS must be off|auto|<min-repeat >= 2>, "
        "got %r" % (v,))


register("MXNET_TPU_SCAN_LAYERS", _parse_scan_layers, "auto",
         "scan-over-layers: lower repeated homogeneous blocks "
         "(transformer layers) through jax.lax.scan so trace/compile "
         "time stops growing with depth; auto = chains of >= 4 verified-"
         "isomorphic blocks, an integer overrides that minimum, off = "
         "always unroll (the scan module is never imported)")
register("MXNET_TPU_GROUP_UPDATE", _parse_bool, True,
         "with a scan plan bound, trace the fused optimizer update as "
         "ONE vmapped body per per-layer parameter family (stacked "
         "(L, ...) arrays) instead of L per-param copies — kills the "
         "remaining O(L) update eqns of deep scanned models; 0 = the "
         "per-param trace (bisection fallback, bit-identical result)")


def _parse_nancheck(v) -> str:
    s = str(v).strip().lower()
    if s in ("", "0", "off", "false", "no", "none"):
        return "off"
    if s in ("warn", "warning", "1", "on", "true", "yes"):
        return "warn"
    if s == "abort":
        return "abort"
    raise ValueError(
        "MXNET_TPU_NANCHECK must be off|warn|abort, got %r" % (v,))


register("MXNET_TPU_NANCHECK", _parse_nancheck, "off",
         "non-finite step guard: chain a device-side isfinite reduction "
         "onto every fused train step (zero host syncs — the flag is "
         "fetched at the epoch log boundary, same place as the metric "
         "sync) and count loop_nonfinite when any output went "
         "NaN/Inf. warn = log naming the first non-finite output, "
         "abort = raise MXNetError there; off = nothing is chained "
         "(zero cost)")


def _parse_lockcheck(v) -> str:
    s = str(v).strip().lower()
    if s in ("", "0", "off", "false", "no", "none"):
        return "off"
    if s in ("warn", "warning", "1", "on", "true", "yes"):
        return "warn"
    if s == "abort":
        return "abort"
    raise ValueError(
        "MXNET_TPU_LOCKCHECK must be off|warn|abort, got %r" % (v,))


register("MXNET_TPU_LOCKCHECK", _parse_lockcheck, "off",
         "runtime lock witness: wrap locks created through the "
         "mx.lockcheck funnels (serve scheduler, checkpoint writer, "
         "obs, pod KV, ...) to record actual acquisition order and "
         "flag the first observed lock-order inversion "
         "(lockcheck_inversion) and any device sync under a held lock "
         "(lockcheck_held_sync). warn = log both chains, abort = raise "
         "MXNetError before the inversion's blocking acquire; off = "
         "plain threading primitives, wrapper never constructed "
         "(one module-bool per lock creation)")


def _parse_remat(v) -> str:
    s = str(v).strip()
    low = s.lower()
    if low in ("", "0", "off", "false", "no", "none"):
        return "off"
    if low == "auto":
        return "auto"
    return s   # a jax.checkpoint_policies name, validated at use


register("MXNET_TPU_REMAT", _parse_remat, "off",
         "applied rematerialization for the fused train step: off = "
         "save all activations, auto = apply the policy the analysis "
         "remat-opportunity pass suggests for this graph "
         "(Report.extras['remat']), any other value = a "
         "jax.checkpoint_policies name applied as-is (e.g. "
         "nothing_saveable, dots_with_no_batch_dims_saveable)")
register("MXNET_TPU_COMPILE_CACHE", str, "",
         "AOT warm starts: directory for serialized fused-step "
         "executables keyed on the program signature (symbol + shapes + "
         "dtypes + optimizer statics + compile knobs + jax/device "
         "fingerprint) so a restarted process skips trace AND compile. "
         "SINGLE-DEVICE executables only (deserialized multi-device "
         "executables mis-execute on this jax version — the fence is "
         "capability-probed, see docs/architecture/program_model.md). "
         "Empty = off")
def _parse_tune(v) -> str:
    s = str(v).strip().lower()
    if s in ("", "0", "off", "false", "no", "none"):
        return "off"
    if s in ("auto", "on", "true", "yes", "1"):
        return "auto"
    if s == "static":
        return "static"
    raise ValueError(
        "MXNET_TPU_TUNE must be off|auto|static, got %r" % (v,))


register("MXNET_TPU_TUNE", _parse_tune, "off",
         "fit(): self-tuning performance search (mxnet_tpu.tune) — "
         "auto = load the stored TunedConfig for this program "
         "fingerprint or run the full static-prune + probe search and "
         "apply the winner's knobs before bind; static = static "
         "pruning/ranking only, no probe subprocesses (deterministic); "
         "off = the tune package is never imported (zero cost)")
register("MXNET_TPU_TUNE_PROBE_SECS", float, 120.0,
         "tune.search: per-probe subprocess deadline in seconds "
         "(PhaseGuard discipline — a timed-out probe is scored failed "
         "and the search keeps its partial results)")
register("MXNET_TPU_TUNE_PROBE_STEPS", int, 8,
         "tune.search: measured steps per probe run (after the 2 "
         "obs-warmup steps that absorb the compile)")
register("MXNET_TPU_TUNE_MAX_PROBES", int, 4,
         "tune.search: empirical probe budget — statically-ranked "
         "candidates probed per search (the default config is always "
         "probed in addition); 0 = static-only ranking")
register("MXNET_TPU_TUNE_STORE", str, "",
         "tune: TunedConfig store directory; empty = co-locate with "
         "MXNET_TPU_COMPILE_CACHE (the aot executable cache), so a "
         "restart finds the tuned knobs next to the executables they "
         "compile into. Both empty = no persistence")
register("MXNET_TPU_LAYERNORM_TWO_PASS", _parse_bool, False,
         "LayerNorm: two-pass E[(x-mean)^2] variance instead of the fused "
         "one-pass E[x^2]-E[x]^2 form — restores precision for "
         "large-offset activations at one extra read of x (takes effect "
         "on the next trace; already-compiled programs keep their form)")


def get(name: str):
    """Current value: runtime override > environment > default."""
    knob = KNOBS[name]
    if name in _overrides:
        return _overrides[name]
    raw = os.environ.get(name)
    if raw is None:
        return knob.default
    return knob.typ(raw)


def set(name: str, value) -> None:     # noqa: A001 (reference-style name)
    """Runtime override (takes precedence over the environment)."""
    knob = KNOBS[name]
    _overrides[name] = knob.typ(value)
    _notify(name)


def reset(name: str) -> None:
    """Drop a runtime override, reverting to environment/default."""
    _overrides.pop(name, None)
    _notify(name)


_NO_OVERRIDE = object()


def snapshot_overrides(names) -> Dict[str, Any]:
    """Capture the runtime-override state of ``names`` for a later
    :func:`restore_overrides` — the scoped-set discipline callers like
    ``fit(tune=...)`` use so their knob winners do not outlive the
    call. A name with no current override is recorded as such (its
    restore is :func:`reset`, not a re-``set`` of the computed value,
    so environment changes in between still show through)."""
    return {str(n): _overrides.get(n, _NO_OVERRIDE) for n in names}


def restore_overrides(snapshot: Dict[str, Any]) -> None:
    """Undo every :func:`set` made since the matching
    :func:`snapshot_overrides`: re-instate the old override, or drop
    the knob back to environment/default."""
    for name, value in snapshot.items():
        if value is _NO_OVERRIDE:
            reset(name)
        else:
            set(name, value)


def describe() -> str:
    """Human-readable table of every knob, its value and source
    (reference: env_var.md as a runtime query)."""
    lines = []
    for name, knob in sorted(KNOBS.items()):
        src = "override" if name in _overrides else \
            ("env" if name in os.environ else "default")
        lines.append("%-36s %-22r (%s)  %s"
                     % (name, get(name), src, knob.doc))
    return "\n".join(lines)


def _apply_import_knobs() -> None:
    """Knobs that act once at package import."""
    cache_dir = get("MXNET_COMPILATION_CACHE_DIR")
    if cache_dir:
        import jax
        jax.config.update("jax_compilation_cache_dir", cache_dir)
        jax.config.update("jax_persistent_cache_min_compile_time_secs", 0.0)
        # HLO only: the AOT kernel cache embeds exact host CPU features
        # and spews loader errors when they drift
        jax.config.update("jax_persistent_cache_enable_xla_caches", "none")
    if get("MXNET_PROFILER_AUTOSTART"):
        from . import profiler
        profiler.set_state("run")

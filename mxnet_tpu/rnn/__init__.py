"""Symbolic RNN toolkit (reference: python/mxnet/rnn/)."""
from .rnn_cell import *
from .io import *

from . import rnn_cell
from . import io

__all__ = rnn_cell.__all__ + io.__all__

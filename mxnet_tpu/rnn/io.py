"""RNN data iterators.

Reference surface: ``python/mxnet/rnn/io.py`` — ``encode_sentences`` and
``BucketSentenceIter:78``. Bucketing pads each sentence to the smallest
bucket length that fits, so every bucket is ONE static shape — on TPU that
is precisely the bounded-jit-cache strategy (SURVEY.md §7): one cached XLA
executable per bucket, picked by ``DataBatch.bucket_key``.
"""
from __future__ import annotations

import logging

import numpy as np

from .. import ndarray as nd
from ..io.io import DataBatch, DataDesc, DataIter

__all__ = ["encode_sentences", "BucketSentenceIter"]


def encode_sentences(sentences, vocab=None, invalid_label=-1,
                     invalid_key="\n", start_label=0):
    """Map token sequences to integer id lists, optionally growing a fresh
    vocab (reference: rnn/io.py encode_sentences)."""
    if vocab is None:
        vocab = {invalid_key: invalid_label}
        frozen = False
    else:
        frozen = True
    next_id = start_label
    encoded = []
    for sentence in sentences:
        ids = []
        for token in sentence:
            if token not in vocab:
                if frozen:
                    raise AssertionError("Unknown token %s" % token)
                if next_id == invalid_label:
                    next_id += 1
                vocab[token] = next_id
                next_id += 1
            ids.append(vocab[token])
        encoded.append(ids)
    return encoded, vocab


class BucketSentenceIter(DataIter):
    """Bucketed iterator over variable-length id sequences (reference:
    rnn/io.py:78 BucketSentenceIter).

    Labels are the next-token shift of the data (language-model targets),
    built once at construction; ``reset`` only reshuffles. Pass ``seed``
    for a deterministic epoch order (an addition over the reference, which
    used the process-global RNG).
    """

    def __init__(self, sentences, batch_size, buckets=None, invalid_label=-1,
                 data_name="data", label_name="softmax_label",
                 dtype="float32", layout="NT", seed=None):
        super().__init__()
        lengths = np.array([len(s) for s in sentences])
        if not buckets:
            # auto-buckets: every sentence length that appears at least
            # batch_size times can sustain full batches of its own shape
            counts = np.bincount(lengths)
            buckets = [int(l) for l in np.nonzero(counts >= batch_size)[0]]
        buckets = sorted(buckets)
        if not buckets:
            raise ValueError("no buckets: pass buckets= explicitly or use a "
                             "smaller batch_size")

        # vectorized placement: smallest bucket that fits, else discard
        slot = np.searchsorted(buckets, lengths)
        n_discard = int(np.sum(slot == len(buckets)))
        if n_discard:
            logging.warning(
                "BucketSentenceIter: %d sentences longer than the largest "
                "bucket (%d) were discarded", n_discard, buckets[-1])

        # one padded (rows, bucket_len) matrix per bucket, labels shifted
        self.data = []
        self._labels = []
        for b, blen in enumerate(buckets):
            rows = [sentences[i] for i in np.nonzero(slot == b)[0]]
            mat = np.full((len(rows), blen), invalid_label, dtype=dtype)
            for r, sent in enumerate(rows):
                mat[r, :len(sent)] = sent
            lab = np.full_like(mat, invalid_label)
            lab[:, :-1] = mat[:, 1:]
            self.data.append(mat)
            self._labels.append(lab)

        self.batch_size = batch_size
        self.buckets = buckets
        self.data_name = data_name
        self.label_name = label_name
        self.dtype = dtype
        self.invalid_label = invalid_label
        self.layout = layout
        self.major_axis = layout.find("N")
        if self.major_axis not in (0, 1):
            raise ValueError("layout must be 'NT' (batch-major) or 'TN' "
                             "(time-major), got %r" % layout)
        self.default_bucket_key = max(buckets)
        self._rng = np.random.RandomState(seed)

        shape = (batch_size, self.default_bucket_key)
        if self.major_axis == 1:
            shape = shape[::-1]
        self.provide_data = [DataDesc(data_name, shape, layout=layout)]
        self.provide_label = [DataDesc(label_name, shape, layout=layout)]

        # (bucket, row-offset) of every full batch; partial tails dropped
        self.idx = [(b, start)
                    for b, mat in enumerate(self.data)
                    for start in range(0, len(mat) - batch_size + 1,
                                       batch_size)]
        self.nddata = []
        self.ndlabel = []
        self.curr_idx = 0
        self.reset()

    def reset(self):
        self.curr_idx = 0
        self._rng.shuffle(self.idx)
        self.nddata = []
        self.ndlabel = []
        for mat, lab in zip(self.data, self._labels):
            perm = self._rng.permutation(len(mat))
            mat[:] = mat[perm]
            lab[:] = lab[perm]
            self.nddata.append(nd.array(mat, dtype=self.dtype))
            self.ndlabel.append(nd.array(lab, dtype=self.dtype))

    def next(self):
        if self.curr_idx >= len(self.idx):
            raise StopIteration
        b, start = self.idx[self.curr_idx]
        self.curr_idx += 1
        data = self.nddata[b][start:start + self.batch_size]
        label = self.ndlabel[b][start:start + self.batch_size]
        if self.major_axis == 1:       # time-major: (T, N)
            data = nd.transpose(data)
            label = nd.transpose(label)
        return DataBatch(
            [data], [label], pad=0, bucket_key=self.buckets[b],
            provide_data=[DataDesc(self.data_name, data.shape,
                                   layout=self.layout)],
            provide_label=[DataDesc(self.label_name, label.shape,
                                    layout=self.layout)])

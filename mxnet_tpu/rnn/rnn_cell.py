"""Symbolic RNN cells.

Reference surface: ``python/mxnet/rnn/rnn_cell.py`` — ``BaseRNNCell:108``,
``RNNCell:362``, ``LSTMCell:408``, ``GRUCell:469``, ``FusedRNNCell:536``,
modifier cells at 827-998. Parameter *names* (``<prefix>i2h_weight`` etc.),
gate orders and state layouts match the reference so checkpoints and
``unpack_weights`` round-trips stay compatible.

TPU-first design notes:

* ``FusedRNNCell`` maps onto the framework's fused RNN op (one ``lax.scan``
  per layer, gate matmuls on the MXU — ops/rnn_op.py), so unlike the
  reference's cuDNN-only fused path it runs on every backend.
* Per-step cells build symbol graphs; under ``BucketingModule`` each bucket
  length becomes one cached XLA executable (SURVEY.md §7).
* The packed-parameter layout is described ONCE by :func:`_packed_segments`;
  slicing, packing and size checks all walk that generator, so the cuDNN
  layout convention lives in a single place.
"""
from __future__ import annotations

from .. import symbol
from .. import initializer as init_mod
from ..ops.rnn_op import rnn_param_size

__all__ = ["BaseRNNCell", "RNNCell", "LSTMCell", "GRUCell", "FusedRNNCell",
           "SequentialRNNCell", "DropoutCell", "ZoneoutCell", "ResidualCell",
           "BidirectionalCell", "RNNParams"]

# gate-name suffixes per mode, in the packed (cuDNN) order
_GATES = {"rnn_relu": ("",), "rnn_tanh": ("",),
          "lstm": ("_i", "_f", "_c", "_o"), "gru": ("_r", "_z", "_o")}

_MODIFIED_ERR = ("this cell has been wrapped by a modifier (Dropout/Zoneout/"
                 "Residual); drive the modifier, not the wrapped cell")


class RNNParams(object):
    """Lazily-created, shareable weight Variables (reference:
    rnn_cell.py:78). Two cells given the same RNNParams share weights."""

    def __init__(self, prefix=""):
        self._prefix = prefix
        self._params = {}

    def get(self, name, **kwargs):
        full = self._prefix + name
        try:
            return self._params[full]
        except KeyError:
            v = symbol.Variable(full, **kwargs)
            self._params[full] = v
            return v


def _as_step_inputs(inputs, length, layout, input_prefix=""):
    """Normalize unroll() input forms to a per-step symbol list.

    Accepts None (auto Variables), one [N,T,C]/[T,N,C] symbol (split on the
    time axis), or an explicit list of per-step symbols.
    """
    if inputs is None:
        return [symbol.Variable("%st%d_data" % (input_prefix, t))
                for t in range(length)]
    if isinstance(inputs, symbol.Symbol):
        if len(inputs.list_outputs()) != 1:
            raise ValueError(
                "unroll needs a single-output symbol to split over time; "
                "pass a list of per-step symbols instead")
        t_axis = layout.find("T")
        return list(symbol.SliceChannel(inputs, axis=t_axis,
                                        num_outputs=length, squeeze_axis=1))
    inputs = list(inputs)
    if len(inputs) != length:
        raise ValueError("unroll got %d inputs for length %d"
                         % (len(inputs), length))
    return inputs


def _merge_time(outputs, t_axis=1):
    """Stack per-step outputs into one symbol with time at ``t_axis``
    (axis 1 = NTC, axis 0 = TNC) so a stacked layer can re-split what the
    previous layer merged under the same layout."""
    return symbol.Concat(*[symbol.expand_dims(o, axis=t_axis)
                           for o in outputs], dim=t_axis)


class BaseRNNCell(object):
    """Stepping/unrolling interface shared by every cell (reference:
    rnn_cell.py:108)."""

    def __init__(self, prefix="", params=None):
        self._prefix = prefix
        self._own_params = params is None
        self._params = RNNParams(prefix) if params is None else params
        self._modified = False
        self.reset()

    def reset(self):
        """Forget step counters so the cell can build a fresh graph."""
        self._init_counter = -1
        self._counter = -1

    def __call__(self, inputs, states):
        """One time step: (input symbol, state symbols) -> (output, states)."""
        raise NotImplementedError

    @property
    def params(self):
        self._own_params = False
        return self._params

    @property
    def state_info(self):
        """Per-state dicts: shape (0 = batch wildcard) and layout."""
        raise NotImplementedError

    @property
    def state_shape(self):
        return [info["shape"] for info in self.state_info]

    @property
    def _gate_names(self):
        return ()

    def begin_state(self, func=symbol.Variable, **kwargs):
        """Create initial-state symbols (reference: rnn_cell.py begin_state).
        With the default func they are zero-initialized Variables whose batch
        dim resolves at bind time."""
        if self._modified:
            raise AssertionError(_MODIFIED_ERR)
        states = []
        for info in self.state_info:
            self._init_counter += 1
            name = "%sbegin_state_%d" % (self._prefix, self._init_counter)
            if func is symbol.Variable:
                kw = {k: info[k] for k in ("shape", "__layout__")
                      if info and info.get(k)}
                states.append(func(name, init=init_mod.Zero(), **kw))
            else:
                states.append(func(name=name, **(info or {})))
        return states

    # --- packed <-> per-gate weight views -------------------------------
    def _gate_param_names(self, group):
        return [("%s%s%s_weight" % (self._prefix, group, g),
                 "%s%s%s_bias" % (self._prefix, group, g))
                for g in self._gate_names]

    def unpack_weights(self, args):
        """Explode fused i2h/h2h tensors into per-gate entries (reference:
        rnn_cell.py unpack_weights; inverse of :meth:`pack_weights`)."""
        args = dict(args)
        if not self._gate_names:
            return args
        h = self._num_hidden
        for group in ("i2h", "h2h"):
            w = args.pop("%s%s_weight" % (self._prefix, group))
            b = args.pop("%s%s_bias" % (self._prefix, group))
            for j, (wname, bname) in enumerate(self._gate_param_names(group)):
                args[wname] = w[j * h:(j + 1) * h].copy()
                args[bname] = b[j * h:(j + 1) * h].copy()
        return args

    def pack_weights(self, args):
        """Concatenate per-gate entries back into fused tensors."""
        from .. import ndarray as nd
        args = dict(args)
        if not self._gate_names:
            return args
        for group in ("i2h", "h2h"):
            names = self._gate_param_names(group)
            args["%s%s_weight" % (self._prefix, group)] = \
                nd.concatenate([args.pop(w) for w, _ in names])
            args["%s%s_bias" % (self._prefix, group)] = \
                nd.concatenate([args.pop(b) for _, b in names])
        return args

    def unroll(self, length, inputs=None, begin_state=None,
               input_prefix="", layout="NTC", merge_outputs=None):
        """Unroll `length` steps into a symbol graph (reference:
        rnn_cell.py unroll)."""
        self.reset()
        inputs = _as_step_inputs(inputs, length, layout, input_prefix)
        states = begin_state if begin_state is not None else \
            self.begin_state()
        outputs = []
        for t in range(length):
            out, states = self(inputs[t], states)
            outputs.append(out)
        if merge_outputs:
            outputs = _merge_time(outputs, max(layout.find("T"), 0))
        return outputs, states


def _linear(name, data, weight, bias, num_hidden):
    """Gate projection: one FullyConnected hitting the MXU."""
    return symbol.FullyConnected(data=data, weight=weight, bias=bias,
                                 num_hidden=num_hidden, name=name)


class RNNCell(BaseRNNCell):
    """Vanilla Elman cell: h' = act(W_i x + W_h h) (reference:
    rnn_cell.py:362)."""

    def __init__(self, num_hidden, activation="tanh", prefix="rnn_",
                 params=None):
        super().__init__(prefix=prefix, params=params)
        self._num_hidden = num_hidden
        self._activation = activation
        p = self.params
        self._iW, self._iB = p.get("i2h_weight"), p.get("i2h_bias")
        self._hW, self._hB = p.get("h2h_weight"), p.get("h2h_bias")

    @property
    def state_info(self):
        return [{"shape": (0, self._num_hidden), "__layout__": "NC"}]

    @property
    def _gate_names(self):
        return ("",)

    def __call__(self, inputs, states):
        self._counter += 1
        n = "%st%d_" % (self._prefix, self._counter)
        pre = _linear(n + "i2h", inputs, self._iW, self._iB,
                      self._num_hidden) \
            + _linear(n + "h2h", states[0], self._hW, self._hB,
                      self._num_hidden)
        out = symbol.Activation(pre, act_type=self._activation,
                                name=n + "out")
        return out, [out]


class LSTMCell(BaseRNNCell):
    """LSTM, gate order i,f,c,o (reference: rnn_cell.py:408)."""

    def __init__(self, num_hidden, prefix="lstm_", params=None,
                 forget_bias=1.0):
        super().__init__(prefix=prefix, params=params)
        self._num_hidden = num_hidden
        p = self.params
        self._iW = p.get("i2h_weight")
        self._hW = p.get("h2h_weight")
        # forget-gate bias offset lives in the initializer so a fresh model
        # starts remembering (reference: LSTMBias)
        self._iB = p.get("i2h_bias",
                         init=init_mod.LSTMBias(forget_bias=forget_bias))
        self._hB = p.get("h2h_bias")

    @property
    def state_info(self):
        return [{"shape": (0, self._num_hidden), "__layout__": "NC"},
                {"shape": (0, self._num_hidden), "__layout__": "NC"}]

    @property
    def _gate_names(self):
        return ("_i", "_f", "_c", "_o")

    def __call__(self, inputs, states):
        self._counter += 1
        n = "%st%d_" % (self._prefix, self._counter)
        h = self._num_hidden
        pre = _linear(n + "i2h", inputs, self._iW, self._iB, 4 * h) \
            + _linear(n + "h2h", states[0], self._hW, self._hB, 4 * h)
        gi, gf, gc, go = symbol.SliceChannel(pre, num_outputs=4,
                                             name=n + "slice")
        i = symbol.Activation(gi, act_type="sigmoid", name=n + "i")
        f = symbol.Activation(gf, act_type="sigmoid", name=n + "f")
        c_tilde = symbol.Activation(gc, act_type="tanh", name=n + "c")
        o = symbol.Activation(go, act_type="sigmoid", name=n + "o")
        c = f * states[1] + i * c_tilde
        h_out = o * symbol.Activation(c, act_type="tanh", name=n + "state")
        return h_out, [h_out, c]


class GRUCell(BaseRNNCell):
    """GRU, gate order r,z,o (reference: rnn_cell.py:469)."""

    def __init__(self, num_hidden, prefix="gru_", params=None):
        super().__init__(prefix=prefix, params=params)
        self._num_hidden = num_hidden
        p = self.params
        self._iW, self._iB = p.get("i2h_weight"), p.get("i2h_bias")
        self._hW, self._hB = p.get("h2h_weight"), p.get("h2h_bias")

    @property
    def state_info(self):
        return [{"shape": (0, self._num_hidden), "__layout__": "NC"}]

    @property
    def _gate_names(self):
        return ("_r", "_z", "_o")

    def __call__(self, inputs, states):
        self._counter += 1
        n = "%st%d_" % (self._prefix, self._counter)
        h_prev = states[0]
        xi = _linear(n + "i2h", inputs, self._iW, self._iB,
                     3 * self._num_hidden)
        hi = _linear(n + "h2h", h_prev, self._hW, self._hB,
                     3 * self._num_hidden)
        xr, xz, xn = symbol.SliceChannel(xi, num_outputs=3,
                                         name=n + "i2h_slice")
        hr, hz, hn = symbol.SliceChannel(hi, num_outputs=3,
                                         name=n + "h2h_slice")
        r = symbol.Activation(xr + hr, act_type="sigmoid", name=n + "r_act")
        z = symbol.Activation(xz + hz, act_type="sigmoid", name=n + "z_act")
        cand = symbol.Activation(xn + r * hn, act_type="tanh",
                                 name=n + "h_act")
        h_new = (1.0 - z) * cand + z * h_prev
        return h_new, [h_new]


class FusedRNNCell(BaseRNNCell):
    """Multi-layer fused cell over the RNN op (reference: rnn_cell.py:536).

    The reference's fused path is cuDNN-only; here it lowers to one
    ``lax.scan`` per layer/direction (ops/rnn_op.py) and runs everywhere.
    All weights live in ONE packed Variable in the cuDNN layout.
    """

    def __init__(self, num_hidden, num_layers=1, mode="lstm",
                 bidirectional=False, dropout=0.0, get_next_state=False,
                 forget_bias=1.0, prefix=None, params=None):
        super().__init__(prefix="%s_" % mode if prefix is None else prefix,
                         params=params)
        self._num_hidden = num_hidden
        self._num_layers = num_layers
        self._mode = mode
        self._bidirectional = bidirectional
        self._dropout = dropout
        self._get_next_state = get_next_state
        self._directions = ["l", "r"] if bidirectional else ["l"]
        self._parameter = self.params.get(
            "parameters", init=init_mod.FusedRNN(
                None, num_hidden, num_layers, mode, bidirectional,
                forget_bias))

    @property
    def state_info(self):
        first = self._num_layers * len(self._directions)
        n_states = 2 if self._mode == "lstm" else 1
        return [{"shape": (first, 0, self._num_hidden),
                 "__layout__": "LNC"}] * n_states

    @property
    def _gate_names(self):
        return list(_GATES[self._mode])

    @property
    def _num_gates(self):
        return len(_GATES[self._mode])

    # --- packed layout: the single source of truth ----------------------
    def _packed_segments(self, input_size):
        """Yield ``(kind, name, rows, cols)`` for every segment of the packed
        vector in order — weights for all layers/directions first, then
        biases (the fused op's cuDNN-style convention, ops/rnn_op.py
        rnn_unpack_params). ``name`` is the per-gate parameter name."""
        h = self._num_hidden
        ndir = len(self._directions)
        for section in ("weight", "bias"):
            for layer in range(self._num_layers):
                in_sz = input_size if layer == 0 else h * ndir
                for d in self._directions:
                    for group, cols in (("i2h", in_sz), ("h2h", h)):
                        for gate in _GATES[self._mode]:
                            name = "%s%s%d_%s%s_%s" % (
                                self._prefix, d, layer, group, gate, section)
                            if section == "weight":
                                yield ("weight", name, h, cols)
                            else:
                                yield ("bias", name, h, 1)

    def _solve_input_size(self, total):
        """Invert rnn_param_size for the layer-0 input width."""
        h, g = self._num_hidden, self._num_gates
        ndir = len(self._directions)
        deeper = sum(ndir * g * h * (h * ndir + h + 2)
                     for _ in range(self._num_layers - 1))
        return (total - deeper) // (ndir * g * h) - h - 2

    def unpack_weights(self, args):
        args = dict(args)
        packed = args.pop("%sparameters" % self._prefix)
        in_sz = self._solve_input_size(packed.size)
        pos = 0
        for kind, name, rows, cols in self._packed_segments(in_sz):
            n = rows * cols
            seg = packed[pos:pos + n]
            args[name] = (seg.reshape((rows, cols)) if kind == "weight"
                          else seg).copy()
            pos += n
        if pos != packed.size:
            raise ValueError(
                "packed parameter vector has %d values; layout expects %d"
                % (packed.size, pos))
        return args

    def pack_weights(self, args):
        from .. import ndarray as nd
        args = dict(args)
        w0 = args["%sl0_i2h%s_weight" % (self._prefix, self._gate_names[0])]
        in_sz = w0.shape[1]
        chunks = [nd.reshape(args.pop(name), (-1,))
                  for _, name, _, _ in self._packed_segments(in_sz)]
        packed = nd.concatenate(chunks)
        expect = rnn_param_size(self._num_layers, in_sz, self._num_hidden,
                                self._mode, self._bidirectional)
        if packed.size != expect:
            raise ValueError("packed %d values, layout expects %d"
                             % (packed.size, expect))
        args["%sparameters" % self._prefix] = packed
        return args

    def __call__(self, inputs, states):
        raise NotImplementedError(
            "the fused cell is a whole-sequence op; use unroll() (or "
            "unfuse() for a steppable stack)")

    def unroll(self, length, inputs=None, begin_state=None, input_prefix="",
               layout="NTC", merge_outputs=None):
        """Emit ONE fused RNN op instead of a per-step graph."""
        self.reset()
        batch_major = layout.find("T") == 1
        if isinstance(inputs, (list, tuple)):
            if len(inputs) != length:
                raise ValueError("unroll got %d inputs for length %d"
                                 % (len(inputs), length))
            inputs = _merge_time(list(inputs))
            batch_major = True
        elif inputs is None:
            inputs = symbol.Variable("%sdata" % input_prefix)
        if batch_major:
            inputs = symbol.SwapAxis(inputs, dim1=0, dim2=1)  # -> TNC

        if begin_state is None:
            begin_state = self.begin_state(
                func=lambda name, **kw: symbol.Variable(name))
        state_kw = {"state": begin_state[0]}
        if self._mode == "lstm":
            state_kw["state_cell"] = begin_state[1]

        out = symbol.RNN(data=inputs, parameters=self._parameter,
                         state_size=self._num_hidden,
                         num_layers=self._num_layers,
                         bidirectional=self._bidirectional, p=self._dropout,
                         state_outputs=self._get_next_state,
                         mode=self._mode, name=self._prefix + "rnn",
                         **state_kw)

        if not self._get_next_state:
            outputs, states = out, []
        else:
            outputs = out[0]
            states = [out[1], out[2]] if self._mode == "lstm" else [out[1]]
        if batch_major:
            outputs = symbol.SwapAxis(outputs, dim1=0, dim2=1)
        if merge_outputs is False:
            t_axis = 1 if batch_major else 0
            outputs = list(symbol.SliceChannel(
                outputs, axis=t_axis, num_outputs=length, squeeze_axis=1))
        return outputs, states

    def unfuse(self):
        """Equivalent steppable stack of unrolled cells (reference:
        rnn_cell.py unfuse)."""
        factories = {
            "rnn_relu": lambda pfx: RNNCell(self._num_hidden,
                                            activation="relu", prefix=pfx),
            "rnn_tanh": lambda pfx: RNNCell(self._num_hidden,
                                            activation="tanh", prefix=pfx),
            "lstm": lambda pfx: LSTMCell(self._num_hidden, prefix=pfx),
            "gru": lambda pfx: GRUCell(self._num_hidden, prefix=pfx),
        }
        make = factories[self._mode]
        stack = SequentialRNNCell()
        for layer in range(self._num_layers):
            if self._bidirectional:
                stack.add(BidirectionalCell(
                    make("%sl%d_" % (self._prefix, layer)),
                    make("%sr%d_" % (self._prefix, layer)),
                    output_prefix="%sbi_l%d_" % (self._prefix, layer)))
            else:
                stack.add(make("%sl%d_" % (self._prefix, layer)))
            if self._dropout > 0 and layer + 1 < self._num_layers:
                stack.add(DropoutCell(
                    self._dropout,
                    prefix="%s_dropout%d_" % (self._prefix, layer)))
        return stack


class SequentialRNNCell(BaseRNNCell):
    """Vertically stacked cells stepped together (reference: rnn_cell.py
    SequentialRNNCell)."""

    def __init__(self, params=None):
        super().__init__(prefix="", params=params)
        self._override_cell_params = params is not None
        self._cells = []

    def add(self, cell):
        self._cells.append(cell)
        if self._override_cell_params:
            if not cell._own_params:
                raise AssertionError(
                    "give params to the stack or to its cells, not both")
            cell.params._params.update(self.params._params)
        self.params._params.update(cell.params._params)

    @property
    def state_info(self):
        return [info for c in self._cells for info in c.state_info]

    def begin_state(self, **kwargs):
        if self._modified:
            raise AssertionError(_MODIFIED_ERR)
        return [s for c in self._cells for s in c.begin_state(**kwargs)]

    def unpack_weights(self, args):
        for cell in self._cells:
            args = cell.unpack_weights(args)
        return args

    def pack_weights(self, args):
        for cell in self._cells:
            args = cell.pack_weights(args)
        return args

    def __call__(self, inputs, states):
        self._counter += 1
        out_states = []
        pos = 0
        for cell in self._cells:
            if isinstance(cell, BidirectionalCell):
                raise TypeError("a bidirectional cell cannot be stepped "
                                "inside a sequential stack; unroll it")
            n = len(cell.state_info)
            inputs, new_s = cell(inputs, states[pos:pos + n])
            pos += n
            out_states.extend(new_s)
        return inputs, out_states

    def reset(self):
        super().reset()
        for cell in getattr(self, "_cells", []):
            cell.reset()


class DropoutCell(BaseRNNCell):
    """Stateless dropout-on-output step (reference: rnn_cell.py
    DropoutCell)."""

    def __init__(self, dropout, prefix="dropout_", params=None):
        super().__init__(prefix=prefix, params=params)
        self.dropout = dropout

    @property
    def state_info(self):
        return []

    def __call__(self, inputs, states):
        if self.dropout > 0:
            inputs = symbol.Dropout(data=inputs, p=self.dropout)
        return inputs, states


class _ModifierCell(BaseRNNCell):
    """Wraps a cell, delegating params/state; the wrapped cell is locked
    against direct use (reference: rnn_cell.py ModifierCell)."""

    def __init__(self, base_cell):
        base_cell._modified = True
        super().__init__()
        self.base_cell = base_cell

    @property
    def params(self):
        self._own_params = False
        return self.base_cell.params

    @property
    def state_info(self):
        return self.base_cell.state_info

    def begin_state(self, func=symbol.Variable, **kwargs):
        if self._modified:
            raise AssertionError(_MODIFIED_ERR)
        self.base_cell._modified = False
        try:
            return self.base_cell.begin_state(func=func, **kwargs)
        finally:
            self.base_cell._modified = True

    def unpack_weights(self, args):
        return self.base_cell.unpack_weights(args)

    def pack_weights(self, args):
        return self.base_cell.pack_weights(args)


class ZoneoutCell(_ModifierCell):
    """Zoneout: randomly keep previous output/state (reference: rnn_cell.py
    ZoneoutCell; paper arXiv:1606.01305)."""

    def __init__(self, base_cell, zoneout_outputs=0.0, zoneout_states=0.0):
        if isinstance(base_cell, FusedRNNCell):
            raise TypeError("zoneout needs per-step access: unfuse() the "
                            "fused cell first")
        if isinstance(base_cell, BidirectionalCell):
            raise TypeError("wrap the directional sub-cells with zoneout, "
                            "not the bidirectional composite")
        super().__init__(base_cell)
        self.zoneout_outputs = zoneout_outputs
        self.zoneout_states = zoneout_states
        self.prev_output = None

    def reset(self):
        super().reset()
        self.prev_output = None

    def __call__(self, inputs, states):
        out, new_states = self.base_cell(inputs, states)

        def keep_mask(p, like):
            # Dropout of ones: 1/(1-p) with prob (1-p), else 0 — nonzero
            # means "take the new value"
            return symbol.Dropout(symbol.ones_like(like), p=p)

        prev = self.prev_output if self.prev_output is not None \
            else symbol.zeros_like(out)
        if self.zoneout_outputs > 0.0:
            out = symbol.where(keep_mask(self.zoneout_outputs, out),
                               out, prev)
        if self.zoneout_states > 0.0:
            new_states = [
                symbol.where(keep_mask(self.zoneout_states, s_new), s_new,
                             s_old)
                for s_new, s_old in zip(new_states, states)]
        self.prev_output = out
        return out, new_states


class ResidualCell(_ModifierCell):
    """Adds the step input to the step output (reference: rnn_cell.py
    ResidualCell)."""

    def __call__(self, inputs, states):
        out, states = self.base_cell(inputs, states)
        return symbol.elemwise_add(out, inputs), states


class BidirectionalCell(BaseRNNCell):
    """Runs one cell forward and one backward over the sequence,
    concatenating outputs per step (reference: rnn_cell.py
    BidirectionalCell)."""

    def __init__(self, l_cell, r_cell, params=None, output_prefix="bi_"):
        super().__init__("", params=params)
        self._output_prefix = output_prefix
        self._override_cell_params = params is not None
        if self._override_cell_params:
            if not (l_cell._own_params and r_cell._own_params):
                raise AssertionError(
                    "give params to the bidirectional composite or to its "
                    "sub-cells, not both")
            l_cell.params._params.update(self.params._params)
            r_cell.params._params.update(self.params._params)
        self.params._params.update(l_cell.params._params)
        self.params._params.update(r_cell.params._params)
        self._cells = [l_cell, r_cell]

    def unpack_weights(self, args):
        for cell in self._cells:
            args = cell.unpack_weights(args)
        return args

    def pack_weights(self, args):
        for cell in self._cells:
            args = cell.pack_weights(args)
        return args

    def __call__(self, inputs, states):
        raise NotImplementedError(
            "a bidirectional cell consumes the whole sequence; use unroll()")

    @property
    def state_info(self):
        return [info for c in self._cells for info in c.state_info]

    def begin_state(self, **kwargs):
        if self._modified:
            raise AssertionError(_MODIFIED_ERR)
        return [s for c in self._cells for s in c.begin_state(**kwargs)]

    def unroll(self, length, inputs=None, begin_state=None, input_prefix="",
               layout="NTC", merge_outputs=None):
        self.reset()
        inputs = _as_step_inputs(inputs, length, layout, input_prefix)
        states = begin_state if begin_state is not None else \
            self.begin_state()
        fwd, bwd = self._cells
        n_fwd = len(fwd.state_info)
        f_out, f_states = fwd.unroll(length, inputs=inputs,
                                     begin_state=states[:n_fwd],
                                     layout=layout, merge_outputs=False)
        b_out, b_states = bwd.unroll(length,
                                     inputs=list(reversed(inputs)),
                                     begin_state=states[n_fwd:],
                                     layout=layout, merge_outputs=False)
        outputs = [
            symbol.Concat(f, b, dim=1,
                          name="%st%d" % (self._output_prefix, t))
            for t, (f, b) in enumerate(zip(f_out, reversed(b_out)))]
        if merge_outputs:
            outputs = _merge_time(outputs, max(layout.find("T"), 0))
        return outputs, f_states + b_states

"""Symbolic RNN cells.

Reference: ``python/mxnet/rnn/rnn_cell.py`` — ``BaseRNNCell`` (line 108)
with begin_state/unroll over Symbols, ``RNNCell:362``, ``LSTMCell:408``,
``GRUCell:469``, ``FusedRNNCell:536`` (maps to the fused RNN op; ``unfuse()``
expands back to unrolled cells), modifier cells at 827-998.
"""
from __future__ import annotations

import numpy as np

from .. import symbol
from ..base import MXNetError
from .. import initializer as init_mod
from ..name import NameManager
from ..ops.rnn_op import rnn_param_size

__all__ = ["BaseRNNCell", "RNNCell", "LSTMCell", "GRUCell", "FusedRNNCell",
           "SequentialRNNCell", "DropoutCell", "ZoneoutCell", "ResidualCell",
           "BidirectionalCell", "RNNParams"]


class RNNParams(object):
    """Container for hold-and-share of cell weights (reference:
    rnn_cell.py:78 RNNParams)."""

    def __init__(self, prefix=""):
        self._prefix = prefix
        self._params = {}

    def get(self, name, **kwargs):
        name = self._prefix + name
        if name not in self._params:
            self._params[name] = symbol.Variable(name, **kwargs)
        return self._params[name]


class BaseRNNCell(object):
    """(reference: rnn_cell.py:108 BaseRNNCell)."""

    def __init__(self, prefix="", params=None):
        if params is None:
            params = RNNParams(prefix)
            self._own_params = True
        else:
            self._own_params = False
        self._prefix = prefix
        self._params = params
        self._modified = False
        self.reset()

    def reset(self):
        self._init_counter = -1
        self._counter = -1

    def __call__(self, inputs, states):
        raise NotImplementedError

    @property
    def params(self):
        self._own_params = False
        return self._params

    @property
    def state_info(self):
        raise NotImplementedError

    @property
    def state_shape(self):
        return [ele["shape"] for ele in self.state_info]

    @property
    def _gate_names(self):
        return ()

    def begin_state(self, func=symbol.Variable, **kwargs):
        """(reference: rnn_cell.py begin_state)."""
        assert not self._modified, \
            "After applying modifier cells (e.g. DropoutCell) the base " \
            "cell cannot be called directly. Call the modifier cell instead."
        states = []
        for info in self.state_info:
            self._init_counter += 1
            name = "%sbegin_state_%d" % (self._prefix, self._init_counter)
            if func is symbol.Variable:
                kw = {}
                if info:
                    if info.get("shape"):
                        kw["shape"] = info["shape"]
                    if info.get("__layout__"):
                        kw["__layout__"] = info["__layout__"]
                # zero initial state; the wildcard (0) batch dim resolves at
                # bind time from the data batch (symbol.py _infer_shapes)
                state = func(name, init=init_mod.Zero(), **kw)
            else:
                state = func(name=name, **(info or {}))
            states.append(state)
        return states

    def unpack_weights(self, args):
        """Split packed fused weights into per-gate entries (reference:
        rnn_cell.py unpack_weights)."""
        args = dict(args)
        if not self._gate_names:
            return args
        h = self._num_hidden
        for group_name in ("i2h", "h2h"):
            weight = args.pop("%s%s_weight" % (self._prefix, group_name))
            bias = args.pop("%s%s_bias" % (self._prefix, group_name))
            for j, gate in enumerate(self._gate_names):
                wname = "%s%s%s_weight" % (self._prefix, group_name, gate)
                args[wname] = weight[j * h:(j + 1) * h].copy()
                bname = "%s%s%s_bias" % (self._prefix, group_name, gate)
                args[bname] = bias[j * h:(j + 1) * h].copy()
        return args

    def pack_weights(self, args):
        """(reference: rnn_cell.py pack_weights)."""
        from .. import ndarray as nd
        args = dict(args)
        if not self._gate_names:
            return args
        for group_name in ("i2h", "h2h"):
            weight = []
            bias = []
            for gate in self._gate_names:
                wname = "%s%s%s_weight" % (self._prefix, group_name, gate)
                weight.append(args.pop(wname))
                bname = "%s%s%s_bias" % (self._prefix, group_name, gate)
                bias.append(args.pop(bname))
            args["%s%s_weight" % (self._prefix, group_name)] = \
                nd.concatenate(weight)
            args["%s%s_bias" % (self._prefix, group_name)] = \
                nd.concatenate(bias)
        return args

    def unroll(self, length, inputs=None, begin_state=None,
               input_prefix="", layout="NTC", merge_outputs=None):
        """Unroll into a symbol graph (reference: rnn_cell.py unroll)."""
        self.reset()
        if inputs is None:
            inputs = [symbol.Variable("%st%d_data" % (input_prefix, i))
                      for i in range(length)]
        elif isinstance(inputs, symbol.Symbol):
            assert len(inputs.list_outputs()) == 1, \
                "unroll doesn't allow grouped symbol as input. Please " \
                "convert to list first or let unroll handle splitting"
            axis = layout.find("T")
            inputs = list(symbol.SliceChannel(
                inputs, axis=axis, num_outputs=length, squeeze_axis=1))
        else:
            assert len(inputs) == length
        if begin_state is None:
            begin_state = self.begin_state()

        states = begin_state
        outputs = []
        for i in range(length):
            output, states = self(inputs[i], states)
            outputs.append(output)
        if merge_outputs:
            outputs = [symbol.expand_dims(i, axis=1) for i in outputs]
            outputs = symbol.Concat(*outputs, dim=1)
        return outputs, states


class RNNCell(BaseRNNCell):
    """(reference: rnn_cell.py:362)."""

    def __init__(self, num_hidden, activation="tanh", prefix="rnn_",
                 params=None):
        super().__init__(prefix=prefix, params=params)
        self._num_hidden = num_hidden
        self._activation = activation
        self._iW = self.params.get("i2h_weight")
        self._iB = self.params.get("i2h_bias")
        self._hW = self.params.get("h2h_weight")
        self._hB = self.params.get("h2h_bias")

    @property
    def state_info(self):
        return [{"shape": (0, self._num_hidden), "__layout__": "NC"}]

    @property
    def _gate_names(self):
        return ("",)

    def __call__(self, inputs, states):
        self._counter += 1
        name = "%st%d_" % (self._prefix, self._counter)
        i2h = symbol.FullyConnected(data=inputs, weight=self._iW,
                                    bias=self._iB,
                                    num_hidden=self._num_hidden,
                                    name="%si2h" % name)
        h2h = symbol.FullyConnected(data=states[0], weight=self._hW,
                                    bias=self._hB,
                                    num_hidden=self._num_hidden,
                                    name="%sh2h" % name)
        output = symbol.Activation(i2h + h2h, act_type=self._activation,
                                   name="%sout" % name)
        return output, [output]


class LSTMCell(BaseRNNCell):
    """(reference: rnn_cell.py:408). Gate order i,f,c,o."""

    def __init__(self, num_hidden, prefix="lstm_", params=None,
                 forget_bias=1.0):
        super().__init__(prefix=prefix, params=params)
        self._num_hidden = num_hidden
        self._iW = self.params.get("i2h_weight")
        self._hW = self.params.get("h2h_weight")
        self._iB = self.params.get(
            "i2h_bias",
            init=init_mod.LSTMBias(forget_bias=forget_bias))
        self._hB = self.params.get("h2h_bias")

    @property
    def state_info(self):
        return [{"shape": (0, self._num_hidden), "__layout__": "NC"},
                {"shape": (0, self._num_hidden), "__layout__": "NC"}]

    @property
    def _gate_names(self):
        return ("_i", "_f", "_c", "_o")

    def __call__(self, inputs, states):
        self._counter += 1
        name = "%st%d_" % (self._prefix, self._counter)
        i2h = symbol.FullyConnected(data=inputs, weight=self._iW,
                                    bias=self._iB,
                                    num_hidden=self._num_hidden * 4,
                                    name="%si2h" % name)
        h2h = symbol.FullyConnected(data=states[0], weight=self._hW,
                                    bias=self._hB,
                                    num_hidden=self._num_hidden * 4,
                                    name="%sh2h" % name)
        gates = i2h + h2h
        slice_gates = symbol.SliceChannel(gates, num_outputs=4,
                                          name="%sslice" % name)
        in_gate = symbol.Activation(slice_gates[0], act_type="sigmoid",
                                    name="%si" % name)
        forget_gate = symbol.Activation(slice_gates[1], act_type="sigmoid",
                                        name="%sf" % name)
        in_transform = symbol.Activation(slice_gates[2], act_type="tanh",
                                         name="%sc" % name)
        out_gate = symbol.Activation(slice_gates[3], act_type="sigmoid",
                                     name="%so" % name)
        next_c = forget_gate * states[1] + in_gate * in_transform
        next_h = out_gate * symbol.Activation(next_c, act_type="tanh",
                                              name="%sstate" % name)
        return next_h, [next_h, next_c]


class GRUCell(BaseRNNCell):
    """(reference: rnn_cell.py:469). Gate order r,z,o."""

    def __init__(self, num_hidden, prefix="gru_", params=None):
        super().__init__(prefix=prefix, params=params)
        self._num_hidden = num_hidden
        self._iW = self.params.get("i2h_weight")
        self._iB = self.params.get("i2h_bias")
        self._hW = self.params.get("h2h_weight")
        self._hB = self.params.get("h2h_bias")

    @property
    def state_info(self):
        return [{"shape": (0, self._num_hidden), "__layout__": "NC"}]

    @property
    def _gate_names(self):
        return ("_r", "_z", "_o")

    def __call__(self, inputs, states):
        self._counter += 1
        name = "%st%d_" % (self._prefix, self._counter)
        prev_state_h = states[0]
        i2h = symbol.FullyConnected(data=inputs, weight=self._iW,
                                    bias=self._iB,
                                    num_hidden=self._num_hidden * 3,
                                    name="%si2h" % name)
        h2h = symbol.FullyConnected(data=prev_state_h, weight=self._hW,
                                    bias=self._hB,
                                    num_hidden=self._num_hidden * 3,
                                    name="%sh2h" % name)
        i2h_r, i2h_z, i2h = symbol.SliceChannel(
            i2h, num_outputs=3, name="%si2h_slice" % name)
        h2h_r, h2h_z, h2h = symbol.SliceChannel(
            h2h, num_outputs=3, name="%sh2h_slice" % name)
        reset_gate = symbol.Activation(i2h_r + h2h_r, act_type="sigmoid",
                                       name="%sr_act" % name)
        update_gate = symbol.Activation(i2h_z + h2h_z, act_type="sigmoid",
                                        name="%sz_act" % name)
        next_h_tmp = symbol.Activation(i2h + reset_gate * h2h,
                                       act_type="tanh",
                                       name="%sh_act" % name)
        next_h = (1.0 - update_gate) * next_h_tmp + update_gate * prev_state_h
        return next_h, [next_h]


class FusedRNNCell(BaseRNNCell):
    """Fused multi-layer cell over the RNN op (reference: rnn_cell.py:536
    FusedRNNCell — cuDNN there, lax.scan here, so it runs on every
    backend)."""

    def __init__(self, num_hidden, num_layers=1, mode="lstm",
                 bidirectional=False, dropout=0.0, get_next_state=False,
                 forget_bias=1.0, prefix=None, params=None):
        if prefix is None:
            prefix = "%s_" % mode
        super().__init__(prefix=prefix, params=params)
        self._num_hidden = num_hidden
        self._num_layers = num_layers
        self._mode = mode
        self._bidirectional = bidirectional
        self._dropout = dropout
        self._get_next_state = get_next_state
        self._directions = ["l", "r"] if bidirectional else ["l"]
        initializer = init_mod.FusedRNN(
            None, num_hidden, num_layers, mode, bidirectional, forget_bias)
        self._parameter = self.params.get("parameters", init=initializer)

    @property
    def state_info(self):
        b = self._num_layers * (2 if self._bidirectional else 1)
        n = 2 if self._mode == "lstm" else 1
        return [{"shape": (b, 0, self._num_hidden),
                 "__layout__": "LNC"}] * n

    @property
    def _gate_names(self):
        return {"rnn_relu": [""], "rnn_tanh": [""],
                "lstm": ["_i", "_f", "_c", "_o"],
                "gru": ["_r", "_z", "_o"]}[self._mode]

    @property
    def _num_gates(self):
        return len(self._gate_names)

    def _slice_weights(self, arr, li, lh):
        """Map the packed vector to per-layer cell names (reference:
        rnn_cell.py _slice_weights)."""
        args = {}
        gate_names = self._gate_names
        directions = self._directions
        b = len(directions)
        p = 0
        for layer in range(self._num_layers):
            for direction in directions:
                for group_name in ("i2h", "h2h"):
                    ni = li if group_name == "i2h" else lh
                    if layer > 0 and group_name == "i2h":
                        ni = b * lh
                    size = lh * ni * self._num_gates
                    w = arr[p:p + size].reshape(
                        (lh * self._num_gates, ni))
                    for j, gate in enumerate(gate_names):
                        name = "%s%s%d_%s%s_weight" % (
                            self._prefix, direction, layer, group_name, gate)
                        args[name] = w[j * lh:(j + 1) * lh].copy()
                    p += size
        for layer in range(self._num_layers):
            for direction in directions:
                for group_name in ("i2h", "h2h"):
                    size = lh * self._num_gates
                    bias = arr[p:p + size]
                    for j, gate in enumerate(gate_names):
                        name = "%s%s%d_%s%s_bias" % (
                            self._prefix, direction, layer, group_name, gate)
                        args[name] = bias[j * lh:(j + 1) * lh].copy()
                    p += size
        assert p == arr.size, "Invalid parameters size for FusedRNNCell"
        return args

    def unpack_weights(self, args):
        args = dict(args)
        arr = args.pop("%sparameters" % self._prefix)

        input_size = self._input_size_from(arr)
        args.update(self._slice_weights(arr, input_size, self._num_hidden))
        return args

    def pack_weights(self, args):
        from .. import ndarray as nd
        args = dict(args)
        w0 = args["%sl0_i2h%s_weight" % (self._prefix, self._gate_names[0])]
        input_size = w0.shape[1]
        arr = nd.zeros((rnn_param_size(self._num_layers, input_size,
                                       self._num_hidden, self._mode,
                                       self._bidirectional),),
                       dtype=w0.dtype)
        shapes = self._slice_weights(arr, input_size, self._num_hidden)
        # write values back in packed order
        from .. import ndarray as _nd
        chunks = []
        b = len(self._directions)
        lh = self._num_hidden
        for layer in range(self._num_layers):
            for direction in self._directions:
                for group_name in ("i2h", "h2h"):
                    for gate in self._gate_names:
                        name = "%s%s%d_%s%s_weight" % (
                            self._prefix, direction, layer, group_name, gate)
                        chunks.append(_nd.reshape(args.pop(name), (-1,)))
        for layer in range(self._num_layers):
            for direction in self._directions:
                for group_name in ("i2h", "h2h"):
                    for gate in self._gate_names:
                        name = "%s%s%d_%s%s_bias" % (
                            self._prefix, direction, layer, group_name, gate)
                        chunks.append(args.pop(name))
        args["%sparameters" % self._prefix] = _nd.concatenate(chunks)
        return args

    def _input_size_from(self, arr):
        """Solve for the input size given the packed array length."""
        gates = self._num_gates
        b = len(self._directions)
        lh = self._num_hidden
        L = self._num_layers
        total = arr.size
        # total = b*gates*lh*(I + lh + 2) + (L-1)*b*gates*lh*(b*lh + lh + 2)
        rest = (L - 1) * b * gates * lh * (b * lh + lh + 2)
        first = total - rest
        return first // (b * gates * lh) - lh - 2

    def __call__(self, inputs, states):
        raise NotImplementedError(
            "FusedRNNCell cannot be stepped. Please use unroll")

    def unroll(self, length, inputs=None, begin_state=None, input_prefix="",
               layout="NTC", merge_outputs=None):
        """One fused RNN op instead of an unrolled graph (reference:
        rnn_cell.py FusedRNNCell.unroll)."""
        self.reset()
        axis = layout.find("T")
        if inputs is None:
            inputs = symbol.Variable("%sdata" % input_prefix)
        elif isinstance(inputs, (list, tuple)):
            assert len(inputs) == length
            inputs = [symbol.expand_dims(i, axis=1) for i in inputs]
            inputs = symbol.Concat(*inputs, dim=1)
            axis = 1
        if axis == 1:  # NTC -> TNC
            inputs = symbol.SwapAxis(inputs, dim1=0, dim2=1)
        if begin_state is None:
            begin_state = self.begin_state(
                func=lambda name, **kw: symbol.Variable(name))

        states = begin_state
        if self._mode == "lstm":
            states = {"state": states[0], "state_cell": states[1]}
        else:
            states = {"state": states[0]}

        rnn = symbol.RNN(data=inputs, parameters=self._parameter,
                         state_size=self._num_hidden,
                         num_layers=self._num_layers,
                         bidirectional=self._bidirectional, p=self._dropout,
                         state_outputs=self._get_next_state,
                         mode=self._mode, name=self._prefix + "rnn",
                         **states)

        attr = {"num_outputs": 3 if self._mode == "lstm" else 2}
        if not self._get_next_state:
            outputs, states = rnn, []
        elif self._mode == "lstm":
            outputs, states = rnn[0], [rnn[1], rnn[2]]
        else:
            outputs, states = rnn[0], [rnn[1]]
        if axis == 1:
            outputs = symbol.SwapAxis(outputs, dim1=0, dim2=1)
        if merge_outputs is False:
            outputs = list(symbol.SliceChannel(
                outputs, axis=axis, num_outputs=length, squeeze_axis=1))
        return outputs, states

    def unfuse(self):
        """Expand to a SequentialRNNCell of unrolled cells (reference:
        rnn_cell.py unfuse)."""
        stack = SequentialRNNCell()
        get_cell = {
            "rnn_relu": lambda pfx: RNNCell(self._num_hidden,
                                            activation="relu", prefix=pfx),
            "rnn_tanh": lambda pfx: RNNCell(self._num_hidden,
                                            activation="tanh", prefix=pfx),
            "lstm": lambda pfx: LSTMCell(self._num_hidden, prefix=pfx),
            "gru": lambda pfx: GRUCell(self._num_hidden, prefix=pfx),
        }[self._mode]
        for i in range(self._num_layers):
            if self._bidirectional:
                stack.add(BidirectionalCell(
                    get_cell("%sl%d_" % (self._prefix, i)),
                    get_cell("%sr%d_" % (self._prefix, i)),
                    output_prefix="%sbi_l%d_" % (self._prefix, i)))
            else:
                stack.add(get_cell("%sl%d_" % (self._prefix, i)))
            if self._dropout > 0 and i != self._num_layers - 1:
                stack.add(DropoutCell(self._dropout,
                                      prefix="%s_dropout%d_"
                                      % (self._prefix, i)))
        return stack


class SequentialRNNCell(BaseRNNCell):
    """(reference: rnn_cell.py SequentialRNNCell)."""

    def __init__(self, params=None):
        super().__init__(prefix="", params=params)
        self._override_cell_params = params is not None
        self._cells = []

    def add(self, cell):
        self._cells.append(cell)
        if self._override_cell_params:
            assert cell._own_params, \
                "Either specify params for SequentialRNNCell or child " \
                "cells, not both."
            cell.params._params.update(self.params._params)
        self.params._params.update(cell.params._params)

    @property
    def state_info(self):
        return sum([c.state_info for c in self._cells], [])

    def begin_state(self, **kwargs):
        assert not self._modified
        return sum([c.begin_state(**kwargs) for c in self._cells], [])

    def unpack_weights(self, args):
        for cell in self._cells:
            args = cell.unpack_weights(args)
        return args

    def pack_weights(self, args):
        for cell in self._cells:
            args = cell.pack_weights(args)
        return args

    def __call__(self, inputs, states):
        self._counter += 1
        next_states = []
        p = 0
        for cell in self._cells:
            assert not isinstance(cell, BidirectionalCell)
            n = len(cell.state_info)
            state = states[p:p + n]
            p += n
            inputs, state = cell(inputs, state)
            next_states.extend(state)
        return inputs, next_states

    def reset(self):
        super().reset()
        for cell in getattr(self, "_cells", []):
            cell.reset()


class DropoutCell(BaseRNNCell):
    """(reference: rnn_cell.py DropoutCell)."""

    def __init__(self, dropout, prefix="dropout_", params=None):
        super().__init__(prefix=prefix, params=params)
        self.dropout = dropout

    @property
    def state_info(self):
        return []

    def __call__(self, inputs, states):
        if self.dropout > 0:
            inputs = symbol.Dropout(data=inputs, p=self.dropout)
        return inputs, states


class _ModifierCell(BaseRNNCell):
    def __init__(self, base_cell):
        base_cell._modified = True
        super().__init__()
        self.base_cell = base_cell

    @property
    def params(self):
        self._own_params = False
        return self.base_cell.params

    @property
    def state_info(self):
        return self.base_cell.state_info

    def begin_state(self, func=symbol.Variable, **kwargs):
        assert not self._modified
        self.base_cell._modified = False
        begin = self.base_cell.begin_state(func=func, **kwargs)
        self.base_cell._modified = True
        return begin

    def unpack_weights(self, args):
        return self.base_cell.unpack_weights(args)

    def pack_weights(self, args):
        return self.base_cell.pack_weights(args)


class ZoneoutCell(_ModifierCell):
    """(reference: rnn_cell.py ZoneoutCell)."""

    def __init__(self, base_cell, zoneout_outputs=0.0, zoneout_states=0.0):
        assert not isinstance(base_cell, FusedRNNCell), \
            "FusedRNNCell doesn't support zoneout. Use its unfuse() first."
        assert not isinstance(base_cell, BidirectionalCell), \
            "BidirectionalCell doesn't support zoneout since it doesn't " \
            "support step. Please add ZoneoutCell to the cells underneath."
        super().__init__(base_cell)
        self.zoneout_outputs = zoneout_outputs
        self.zoneout_states = zoneout_states
        self.prev_output = None

    def reset(self):
        super().reset()
        self.prev_output = None

    def __call__(self, inputs, states):
        cell, p_outputs, p_states = (self.base_cell, self.zoneout_outputs,
                                     self.zoneout_states)
        next_output, next_states = cell(inputs, states)
        mask = lambda p, like: symbol.Dropout(  # noqa: E731
            symbol.ones_like(like), p=p)
        prev_output = self.prev_output if self.prev_output is not None \
            else symbol.zeros_like(next_output)
        output = symbol.where(mask(p_outputs, next_output), next_output,
                              prev_output) if p_outputs != 0.0 \
            else next_output
        states = [symbol.where(mask(p_states, new_s), new_s, old_s)
                  for new_s, old_s in zip(next_states, states)] \
            if p_states != 0.0 else next_states
        self.prev_output = output
        return output, states


class ResidualCell(_ModifierCell):
    """(reference: rnn_cell.py ResidualCell)."""

    def __call__(self, inputs, states):
        output, states = self.base_cell(inputs, states)
        output = symbol.elemwise_add(output, inputs)
        return output, states


class BidirectionalCell(BaseRNNCell):
    """(reference: rnn_cell.py BidirectionalCell)."""

    def __init__(self, l_cell, r_cell, params=None, output_prefix="bi_"):
        super().__init__("", params=params)
        self._output_prefix = output_prefix
        self._override_cell_params = params is not None
        if self._override_cell_params:
            assert l_cell._own_params and r_cell._own_params, \
                "Either specify params for BidirectionalCell or child " \
                "cells, not both."
            l_cell.params._params.update(self.params._params)
            r_cell.params._params.update(self.params._params)
        self.params._params.update(l_cell.params._params)
        self.params._params.update(r_cell.params._params)
        self._cells = [l_cell, r_cell]

    def unpack_weights(self, args):
        for cell in self._cells:
            args = cell.unpack_weights(args)
        return args

    def pack_weights(self, args):
        for cell in self._cells:
            args = cell.pack_weights(args)
        return args

    def __call__(self, inputs, states):
        raise NotImplementedError(
            "Bidirectional cannot be stepped. Please use unroll")

    @property
    def state_info(self):
        return sum([c.state_info for c in self._cells], [])

    def begin_state(self, **kwargs):
        assert not self._modified
        return sum([c.begin_state(**kwargs) for c in self._cells], [])

    def unroll(self, length, inputs=None, begin_state=None, input_prefix="",
               layout="NTC", merge_outputs=None):
        self.reset()
        if inputs is None:
            inputs = [symbol.Variable("%st%d_data" % (input_prefix, i))
                      for i in range(length)]
        elif isinstance(inputs, symbol.Symbol):
            axis = layout.find("T")
            inputs = list(symbol.SliceChannel(
                inputs, axis=axis, num_outputs=length, squeeze_axis=1))
        if begin_state is None:
            begin_state = self.begin_state()

        states = begin_state
        l_cell, r_cell = self._cells
        l_outputs, l_states = l_cell.unroll(
            length, inputs=inputs,
            begin_state=states[:len(l_cell.state_info)],
            layout=layout, merge_outputs=False)
        r_outputs, r_states = r_cell.unroll(
            length, inputs=list(reversed(inputs)),
            begin_state=states[len(l_cell.state_info):],
            layout=layout, merge_outputs=False)

        outputs = [symbol.Concat(l_o, r_o, dim=1,
                                 name="%st%d" % (self._output_prefix, i))
                   for i, (l_o, r_o) in
                   enumerate(zip(l_outputs, reversed(r_outputs)))]
        if merge_outputs:
            outputs = [symbol.expand_dims(i, axis=1) for i in outputs]
            outputs = symbol.Concat(*outputs, dim=1)
        return outputs, l_states + r_states

"""Model checkpoint helpers (+ legacy FeedForward surface lives in Module).

Reference: ``python/mxnet/model.py`` — ``save_checkpoint:340`` /
``load_checkpoint:370`` write ``prefix-symbol.json`` + ``prefix-%04d.params``
with ``arg:``/``aux:`` prefixed tensor names; ``_create_kvstore:57`` decides
``update_on_kvstore``.
"""
from __future__ import annotations

import logging
from typing import Dict, Optional, Tuple

from . import ndarray as nd
from .ndarray import NDArray

__all__ = ["save_checkpoint", "load_checkpoint", "BatchEndParam"]


def save_checkpoint(prefix: str, epoch: int, symbol, arg_params: Dict[str, NDArray],
                    aux_params: Dict[str, NDArray]) -> None:
    """(reference: model.py:340).

    Both files land atomically (temp + fsync + rename via
    ``mx.checkpoint.atomic_open`` inside ``Symbol.save``/``nd.save``): a
    crash mid-save can no longer tear an existing checkpoint. For
    crash-safe *resumable* training state (optimizer, RNG, loop
    position), use ``Module.fit(checkpoint=...)`` / ``mx.checkpoint``
    instead — this writes params + symbol only."""
    if symbol is not None:
        symbol.save("%s-symbol.json" % prefix)
    save_dict = {("arg:%s" % k): v for k, v in arg_params.items()}
    save_dict.update({("aux:%s" % k): v for k, v in aux_params.items()})
    param_name = "%s-%04d.params" % (prefix, epoch)
    nd.save(param_name, save_dict)
    logging.info("Saved checkpoint to \"%s\"", param_name)


def load_checkpoint(prefix: str, epoch: int):
    """(reference: model.py:370). Returns (symbol, arg_params, aux_params)."""
    from . import symbol as sym
    symbol = sym.load("%s-symbol.json" % prefix)
    save_dict = nd.load("%s-%04d.params" % (prefix, epoch))
    arg_params, aux_params = {}, {}
    for k, v in save_dict.items():
        tp, name = k.split(":", 1)
        if tp == "arg":
            arg_params[name] = v
        elif tp == "aux":
            aux_params[name] = v
    return symbol, arg_params, aux_params


def _create_kvstore(kvstore, num_device: int, arg_params):
    """Decide kvstore + update_on_kvstore (reference: model.py:57)."""
    from . import kvstore as kvs
    update_on_kvstore = True
    if kvstore is None:
        kv = None
    elif isinstance(kvstore, kvs.KVStore):
        kv = kvstore
    elif isinstance(kvstore, str):
        if num_device == 1 and "dist" not in kvstore:
            kv = None
        else:
            kv = kvs.create(kvstore)
            if kvstore == "local":
                max_size = max(int(nd_arr.size) for nd_arr in arg_params.values())
                if max_size < 1024 * 1024 * 16:
                    update_on_kvstore = False
    else:
        raise TypeError("kvstore must be KVStore, str or None")
    if kv is None:
        update_on_kvstore = False
    return kv, update_on_kvstore


from .callback import BatchEndParam  # noqa: E402  (re-export, reference parity)

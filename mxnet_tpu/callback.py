"""Training callbacks.

Reference: ``python/mxnet/callback.py`` (Speedometer:120, do_checkpoint,
module_checkpoint, log_train_metric, ProgressBar:176).
"""
from __future__ import annotations

import logging
import math
import sys
import time

__all__ = ["Speedometer", "do_checkpoint", "module_checkpoint",
           "subsystem_checkpoint", "log_train_metric", "ProgressBar",
           "BatchEndParam"]


class BatchEndParam(object):
    """Namedtuple-style callback payload (reference: callback module's
    BatchEndParam namedtuple)."""

    def __init__(self, epoch, nbatch, eval_metric, locals=None):
        self.epoch = epoch
        self.nbatch = nbatch
        self.eval_metric = eval_metric
        self.locals = locals


def module_checkpoint(mod, prefix, period=1, save_optimizer_states=False):
    """(reference: callback.py module_checkpoint)."""
    period = int(max(1, period))

    def _callback(iter_no, sym=None, arg=None, aux=None):
        if (iter_no + 1) % period == 0:
            mod.save_checkpoint(prefix, iter_no + 1, save_optimizer_states)

    return _callback


def do_checkpoint(prefix, period=1):
    """Epoch callback saving prefix-symbol.json + prefix-%04d.params
    (reference: callback.py do_checkpoint; model.py save_checkpoint:340).

    Rebased onto the atomic write path: both files go through
    ``mx.checkpoint.atomic_open`` (temp + fsync + rename), so a crash
    mid-save never tears a previously-saved epoch. This remains the
    params-only legacy layout; for resumable training state prefer
    ``fit(checkpoint=...)`` or :func:`subsystem_checkpoint`."""
    period = int(max(1, period))

    def _callback(iter_no, sym, arg, aux):
        if (iter_no + 1) % period == 0:
            from .model import save_checkpoint
            save_checkpoint(prefix, iter_no + 1, sym, arg, aux)

    return _callback


def subsystem_checkpoint(module, manager, period=1):
    """Epoch callback driving the ``mx.checkpoint`` subsystem — for loops
    composed from callbacks instead of ``fit(checkpoint=...)`` (which
    owns scheduling, SIGTERM, and teardown itself). Each firing snapshots
    the FULL resumable state (params + optimizer + RNG) and hands it to
    the manager's bounded async writer; call ``manager.close()`` when
    training ends to drain it.

    ``manager`` may be a ``CheckpointManager``, a ``CheckpointConfig``,
    or a bare directory path."""
    from . import checkpoint as _ckpt
    if not isinstance(manager, _ckpt.CheckpointManager):
        manager = _ckpt.CheckpointManager(manager)
    period = int(max(1, period))

    def _callback(iter_no, sym=None, arg=None, aux=None):
        if (iter_no + 1) % period == 0:
            manager.save_module(module, epoch=iter_no)

    _callback.manager = manager
    return _callback


def log_train_metric(period, auto_reset=False):
    """(reference: callback.py log_train_metric)."""

    def _callback(param):
        if param.nbatch % period == 0 and param.eval_metric is not None:
            name_value = param.eval_metric.get_name_value()
            for name, value in name_value:
                logging.info("Iter[%d] Batch[%d] Train-%s=%f",
                             param.epoch, param.nbatch, name, value)
            if auto_reset:
                param.eval_metric.reset()

    return _callback


class Speedometer(object):
    """Throughput logger (reference: callback.py:120)."""

    def __init__(self, batch_size, frequent=50, auto_reset=True):
        self.batch_size = batch_size
        self.frequent = frequent
        self.init = False
        self.tic = 0
        self.last_count = 0
        self.auto_reset = auto_reset

    def __call__(self, param):
        count = param.nbatch
        if self.last_count > count:
            self.init = False
        self.last_count = count

        if self.init:
            if count % self.frequent == 0:
                speed = self.frequent * self.batch_size / (
                    time.perf_counter() - self.tic)
                if param.eval_metric is not None:
                    name_value = param.eval_metric.get_name_value()
                    if self.auto_reset:
                        param.eval_metric.reset()
                    msg = "Epoch[%d] Batch [%d]\tSpeed: %.2f samples/sec"
                    msg += "\t%s=%f" * len(name_value)
                    logging.info(msg, param.epoch, count, speed,
                                 *sum(name_value, ()))
                else:
                    logging.info("Iter[%d] Batch [%d]\tSpeed: %.2f samples/sec",
                                 param.epoch, count, speed)
                self.tic = time.perf_counter()
        else:
            self.init = True
            self.tic = time.perf_counter()


class ProgressBar(object):
    """(reference: callback.py:176)."""

    def __init__(self, total, length=80):
        self.bar_len = length
        self.total = total

    def __call__(self, param):
        count = param.nbatch
        filled_len = int(round(self.bar_len * count / float(self.total)))
        percents = math.ceil(100.0 * count / float(self.total))
        prog_bar = "=" * filled_len + "-" * (self.bar_len - filled_len)
        sys.stdout.write("[%s] %s%s\r" % (prog_bar, percents, "%"))

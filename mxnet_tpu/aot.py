"""AOT warm starts: serialized executables so restarts skip compilation.

Two independent layers, both fenced against the known jax bug where a
**deserialized multi-device executable mis-executes** on this jax/XLA
version (root-caused in PR 2: collective-bearing CPU executables loaded
from the persistent compile cache intermittently compute wrong results
— diffs ~2.0 with a warm cache, zero with a cold one):

1. **Executable cache** (``MXNET_TPU_COMPILE_CACHE=<dir>``): the fused
   train step and the executor forward serialize their compiled
   executables (``jax.experimental.serialize_executable``) keyed on the
   framework-level program signature — symbol JSON, bound
   shapes/dtypes, optimizer statics, compile-affecting knobs, and the
   jax/device fingerprint — so a restarted ``fit``/``serve`` process
   skips trace AND lower AND backend-compile for warm programs
   (``aot_hit``; the CI ``compile-time`` job asserts a warm second
   process records zero backend-compile phases for the fused step in
   the obs compile accounting). Single-device programs only
   (``aot_skip_multidevice``), and only after :func:`supported` proves
   a serialize → deserialize → execute → compare round-trip on this
   backend (``aot_unsupported``).

2. **Persistent-cache fence** (:func:`install_persistent_cache_fence`):
   jax's own persistent compile cache (``MXNET_COMPILATION_CACHE_DIR``,
   ``tests/.jax_cache``) gets a root-cause fence instead of the old
   conftest module-name exclusion: the cache get/put entry points skip
   any executable whose ``num_replicas * num_partitions > 1``
   (``compile_cache_fence_skip``), so multi-device programs always
   compile fresh while single-device programs keep warm starts
   everywhere. Fail-closed: anything unexpected about the compile
   options skips the cache (a fresh compile is always correct).

Layout: one ``<name>-<sha256>.aotx`` pickle per executable (payload +
pytree defs + fingerprint), written atomically (`checkpoint.atomic`) so
a killed process can never tear an entry. A corrupt or
wrong-fingerprint entry is a miss, never an error.
"""
from __future__ import annotations

import hashlib
import logging
import os
import pickle
import threading
from typing import Any, Callable, Iterable, Optional

from . import lockcheck as _lockcheck
from . import profiler as _profiler

__all__ = [
    "enabled", "supported", "fingerprint", "digest", "load", "store",
    "load_or_compile", "install_persistent_cache_fence",
    "config_store_dir",
]

log = logging.getLogger(__name__)

_FORMAT_VERSION = 1
_probe_result: Optional[bool] = None


def enabled() -> Optional[str]:
    """The executable-cache directory, or None when the knob is off."""
    from . import config as _config
    d = _config.get("MXNET_TPU_COMPILE_CACHE")
    return d or None


def config_store_dir() -> Optional[str]:
    """Directory for persisted ``TunedConfig`` records (mxnet_tpu.tune):
    ``MXNET_TPU_TUNE_STORE`` when set, else co-located with the AOT
    executable cache — a restarted ``fit(tune="auto")`` finds the tuned
    knobs next to the executables they compile into, keyed by the same
    :func:`digest` fingerprint scheme. None = no persistence."""
    from . import config as _config
    d = _config.get("MXNET_TPU_TUNE_STORE")
    return d or enabled()


# knobs ops read at TRACE time: their value is baked into the compiled
# program, so they must invalidate serialized executables (a stale
# entry would silently run the other variant of the op)
_TRACE_KNOBS = ("MXNET_TPU_LAYERNORM_TWO_PASS",)


def fingerprint() -> str:
    """Everything that invalidates a serialized executable wholesale:
    jax/jaxlib versions, backend platform + device kind, XLA flags,
    trace-time op knobs, and the framework version (op implementations
    change programs)."""
    import jax
    import jaxlib
    from . import __version__ as mx_version
    from . import config as _config
    dev = jax.devices()[0]
    parts = (
        "v%d" % _FORMAT_VERSION, jax.__version__, jaxlib.__version__,
        jax.default_backend(), getattr(dev, "device_kind", "?"),
        os.environ.get("XLA_FLAGS", ""), mx_version,
    ) + tuple("%s=%r" % (k, _config.get(k)) for k in _TRACE_KNOBS)
    return hashlib.sha256("|".join(parts).encode()).hexdigest()


def supported() -> bool:
    """Capability probe, once per process: serialize a trivial compiled
    program, deserialize it, execute it, and compare values. A backend
    or jax build where the round-trip is unavailable or wrong disables
    the executable cache entirely (``aot_unsupported``)."""
    global _probe_result
    if _probe_result is not None:
        return _probe_result
    # unlocked on purpose: a racing second probe just repeats the same
    # idempotent round-trip (holding a mutex across jax dispatch is the
    # lock-dispatch hazard the repo lint rejects)
    try:
        import numpy as np
        import jax
        import jax.numpy as jnp
        from jax.experimental.serialize_executable import (
            deserialize_and_load, serialize)

        # salt the probe program so it can never be served from jax's
        # persistent compile cache: a cache-LOADED executable does not
        # re-serialize on this backend ("Symbols not found") — that case
        # is handled per-store by the verify in store(), and must not
        # fail the whole capability probe
        salt = float(int.from_bytes(os.urandom(4), "big")) / 2**32 + 2.0
        fn = jax.jit(lambda x: x * salt + 1.0)
        x = jnp.arange(8, dtype=jnp.float32)
        compiled = fn.lower(x).compile()
        blob = pickle.dumps(serialize(compiled))
        loaded = deserialize_and_load(*pickle.loads(blob))
        ok = bool(np.array_equal(np.asarray(loaded(x)),
                                 np.asarray(fn(x))))
    except Exception:                                       # noqa: BLE001
        ok = False
    if not ok:
        _profiler.incr_counter("aot_unsupported")
        log.warning(
            "MXNET_TPU_COMPILE_CACHE: executable serialization "
            "round-trip failed on this jax/backend; AOT warm starts "
            "disabled")
    _probe_result = ok
    return ok


def digest(parts: Iterable[Any]) -> str:
    """Collision-resistant digest of the program signature parts (the
    caller supplies symbol JSON, shapes/dtypes, optimizer statics,
    knobs); the device/jax fingerprint is always mixed in."""
    h = hashlib.sha256(fingerprint().encode())
    for p in parts:
        h.update(b"\x00")
        h.update(repr(p).encode())
    return h.hexdigest()


def _path(directory: str, name: str, key: str) -> str:
    return os.path.join(directory, "%s-%s.aotx" % (name, key))


def load(name: str, key: str) -> Optional[Callable]:
    """Deserialize the cached executable for ``(name, key)``; a missing,
    corrupt, or wrong-fingerprint entry is a miss (``aot_miss``)."""
    directory = enabled()
    if directory is None or not supported():
        return None
    path = _path(directory, name, key)
    try:
        with open(path, "rb") as f:
            entry = pickle.load(f)
        if entry.get("version") != _FORMAT_VERSION or \
                entry.get("fingerprint") != fingerprint():
            raise ValueError("stale entry")
        from jax.experimental.serialize_executable import \
            deserialize_and_load
        loaded = deserialize_and_load(entry["payload"], entry["in_tree"],
                                      entry["out_tree"])
    except FileNotFoundError:
        _profiler.incr_counter("aot_miss")
        return None
    except Exception as exc:                                # noqa: BLE001
        _profiler.incr_counter("aot_miss")
        log.info("aot: ignoring unusable cache entry %s (%s)", path, exc)
        return None
    _profiler.incr_counter("aot_hit")
    return loaded


def store(name: str, key: str, compiled) -> bool:
    """Serialize ``compiled`` under ``(name, key)``, atomically
    (``aot_store``). Serialization failures only cost the warm start."""
    directory = enabled()
    if directory is None or not supported():
        return False
    try:
        from jax.experimental.serialize_executable import (
            deserialize_and_load, serialize)
        payload, in_tree, out_tree = serialize(compiled)
        # verify the payload actually deserializes before persisting:
        # an executable that was itself loaded from jax's persistent
        # compile cache serializes "successfully" but its payload lacks
        # the kernel symbols ("Symbols not found" on load) — storing it
        # would cost every future process an aot_error round
        deserialize_and_load(payload, in_tree, out_tree)
        entry = {
            "version": _FORMAT_VERSION, "fingerprint": fingerprint(),
            "name": name, "payload": payload,
            "in_tree": in_tree, "out_tree": out_tree,
        }
        os.makedirs(directory, exist_ok=True)
        from .checkpoint.atomic import atomic_open
        with atomic_open(_path(directory, name, key), "wb") as f:
            pickle.dump(entry, f)
    except Exception as exc:                                # noqa: BLE001
        _profiler.incr_counter("aot_store_unverified")
        log.warning("aot: could not serialize %s: %s", name, exc)
        return False
    _profiler.incr_counter("aot_store")
    return True


def load_or_compile(name: str, key: str, jitted, *args):
    """The warm-start recipe the executor forward and fused step
    hand-roll, as one call: return the cached executable for
    ``(name, key)`` when present, else seed the cache — lower + compile
    ``jitted`` on ``args`` with jax's persistent compile cache bypassed
    (a cache-loaded executable serializes to an unloadable payload) and
    ``store`` the result.

    Returns ``(compiled, hit)``. Callers keep the first post-``load``
    invocation on COPIES of donated buffers (a bad cache entry must not
    invalidate live state — the ``_fused`` discipline). When the cache is
    off/unsupported the compile still happens (without the bypass), so
    the caller always gets an executable.
    """
    loaded = load(name, key)
    if loaded is not None:
        return loaded, True
    if enabled() is not None and supported():
        with bypass_persistent_cache():
            compiled = jitted.lower(*args).compile()
        store(name, key, compiled)
    else:
        compiled = jitted.lower(*args).compile()
    return compiled, False


# ------------------------------------------------- persistent-cache fence

_fence_lock = _lockcheck.Lock(name="aot.fence_lock")
_fence_installed = False
_tls = threading.local()


class bypass_persistent_cache:
    """Compile fresh, ignoring jax's persistent compile cache, on this
    thread. The AOT store path needs this: an executable jax loaded from
    its persistent cache serializes to a payload without kernel symbols
    (unloadable), so the one compile that seeds the executable cache
    must be a real backend compile. Requires the fence (best-effort
    installed on entry); without it the bypass is a no-op and
    ``store()``'s deserialize-verify refuses the bad payload instead."""

    def __enter__(self):
        install_persistent_cache_fence()
        _tls.bypass = True
        return self

    def __exit__(self, *exc):
        _tls.bypass = False
        return False


def install_persistent_cache_fence() -> bool:
    """Fence jax's persistent compile cache to single-device executables.

    Root cause (PR 2): on this jax/XLA version a deserialized
    multi-device (collective-bearing) CPU executable intermittently
    mis-executes; the conftest used to exclude whole test modules from
    the cache by NAME. This fence moves the exclusion to the actual
    hazard: the cache's get/put entry points skip any program whose
    compile options say ``num_replicas * num_partitions > 1``
    (``compile_cache_fence_skip``), and anything unexpected about the
    options **fails closed** (skip the cache — a fresh compile is
    always correct). Idempotent; returns False when the jax internals
    drifted past the capability probe (callers should then disable the
    persistent cache wholesale)."""
    global _fence_installed
    with _fence_lock:
        if _fence_installed:
            return True
        try:
            from jax._src import compilation_cache as cc
            orig_get = cc.get_executable_and_time
            orig_put = cc.put_executable_and_time
            if not callable(orig_get) or not callable(orig_put):
                raise TypeError("compilation_cache API drifted")
        except Exception:                                   # noqa: BLE001
            log.warning("persistent-cache fence: jax internals drifted; "
                        "NOT installed — disable the persistent cache "
                        "for multi-device work")
            return False

        def _multi(compile_options) -> bool:
            try:
                ebo = compile_options.executable_build_options
                return int(ebo.num_replicas) * int(ebo.num_partitions) > 1
            except Exception:                               # noqa: BLE001
                return True        # fail closed: treat as multi-device

        def fenced_get(cache_key, compile_options, backend):
            if getattr(_tls, "bypass", False):
                return None, None     # AOT seeding compile: stay fresh
            if _multi(compile_options):
                _profiler.incr_counter("compile_cache_fence_skip")
                return None, None
            return orig_get(cache_key, compile_options, backend)

        def fenced_put(cache_key, module_name, executable, backend,
                       compile_time):
            # the get fence is the correctness fence (nothing skipped
            # here is ever loaded); skipping the put as well keeps the
            # cache free of unusable multi-device entries
            try:
                multi = int(getattr(executable, "num_replicas", 1)) * \
                    int(getattr(executable, "num_partitions", 1)) > 1
            except Exception:                               # noqa: BLE001
                multi = True
            if multi:
                _profiler.incr_counter("compile_cache_fence_skip")
                return None
            return orig_put(cache_key, module_name, executable, backend,
                            compile_time)

        cc.get_executable_and_time = fenced_get
        cc.put_executable_and_time = fenced_put
        _fence_installed = True
        return True

"""Device context abstraction.

Reference: ``python/mxnet/context.py`` (Context class + thread-local default
stack, ``cpu()``/``gpu()`` constructors). The TPU build maps a Context onto a
concrete ``jax.Device``:

* ``cpu(i)``  -> i-th host (CPU) device
* ``tpu(i)``  -> i-th accelerator device (TPU on real hardware)
* ``gpu(i)``  -> alias of ``tpu(i)`` so reference-era scripts that say
  ``mx.gpu(0)`` run unchanged on TPU.

Unlike the reference there is no per-context CUDA stream — XLA owns scheduling
(SURVEY.md §2.1 TPU translation note).
"""
from __future__ import annotations

import threading
from typing import Optional

import jax

__all__ = ["Context", "cpu", "gpu", "tpu", "current_context", "num_devices"]

_devtype2id = {"cpu": 1, "tpu": 2, "gpu": 2}
_devid2type = {1: "cpu", 2: "tpu"}


class Context:
    """A device context, usable as a ``with`` block to set the default device
    (reference: python/mxnet/context.py Context.__enter__/__exit__)."""

    _local = threading.local()
    devtype2str = {1: "cpu", 2: "tpu"}
    devstr2type = {"cpu": 1, "tpu": 2, "gpu": 2}

    def __init__(self, device_type: str, device_id: int = 0):
        if isinstance(device_type, Context):
            self.device_typeid = device_type.device_typeid
            self.device_id = device_type.device_id
        else:
            self.device_typeid = Context.devstr2type[device_type]
            self.device_id = device_id
        self._old_ctx: Optional[Context] = None

    @property
    def device_type(self) -> str:
        return Context.devtype2str[self.device_typeid]

    @property
    def jax_device(self) -> jax.Device:
        """Resolve to the concrete jax.Device (lazy: devices may not exist
        until the backend initializes)."""
        # device ids index the PROCESS-LOCAL view (the reference's gpu(i) is
        # worker-local too); under jax.distributed the global list contains
        # other hosts' non-addressable devices
        if self.device_type == "cpu":
            return jax.local_devices(backend="cpu")[self.device_id]
        # accelerator: prefer the default backend's devices when it is not CPU
        devs = jax.local_devices()
        if devs and devs[0].platform != "cpu":
            return devs[self.device_id]
        # No accelerator present (pure-CPU test run): fall back to host devices
        # so tpu(i) still resolves — mirrors the reference test trick of running
        # "multi-device" suites on cpu(0)/cpu(1) (tests/python/unittest/
        # test_multi_device_exec.py, SURVEY.md §4).
        cpus = jax.local_devices(backend="cpu")
        return cpus[self.device_id % len(cpus)]

    def __eq__(self, other):
        return (
            isinstance(other, Context)
            and self.device_typeid == other.device_typeid
            and self.device_id == other.device_id
        )

    def __hash__(self):
        return hash((self.device_typeid, self.device_id))

    def __repr__(self):
        return "%s(%d)" % (self.device_type, self.device_id)

    def __str__(self):
        return self.__repr__()

    def __enter__(self):
        self._old_ctx = getattr(Context._local, "default_ctx", None)
        Context._local.default_ctx = self
        return self

    def __exit__(self, exc_type, exc_value, traceback):
        Context._local.default_ctx = self._old_ctx


def cpu(device_id: int = 0) -> Context:
    """Host (CPU) context (reference: python/mxnet/context.py cpu())."""
    return Context("cpu", device_id)


def tpu(device_id: int = 0) -> Context:
    """TPU chip context — the TPU build's accelerator device."""
    return Context("tpu", device_id)


def gpu(device_id: int = 0) -> Context:
    """Compatibility alias: reference scripts use mx.gpu(i); on the TPU build
    this addresses the i-th accelerator chip."""
    return Context("tpu", device_id)


def current_context() -> Context:
    """Default context (thread-local stack; reference context.py
    current_context). Falls back to cpu(0)."""
    ctx = getattr(Context._local, "default_ctx", None)
    return ctx if ctx is not None else Context("cpu", 0)


def num_devices(device_type: str = "tpu") -> int:
    """Number of visible devices of a type — replaces the reference's
    mx.context.num_gpus()."""
    try:
        if device_type == "cpu":
            return len(jax.local_devices(backend="cpu"))
        devs = jax.local_devices()
        if devs and devs[0].platform != "cpu":
            return len(devs)
        return 0
    except RuntimeError:
        return 0

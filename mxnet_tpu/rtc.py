"""Runtime custom kernels — the TPU twin of ``mx.rtc`` (SURVEY.md §2.22).

Reference: ``include/mxnet/mxrtc.h:42-101`` + ``python/mxnet/rtc.py:24-78``
compile CUDA C strings with NVRTC at runtime and launch them on GPU data.
On TPU the escape hatch is **Pallas**: users write a kernel as a Python
function over ``pl.Ref`` blocks, and :class:`PallasKernel` compiles it with
Mosaic and runs it on NDArrays — same role (hand-written kernels for the
few ops XLA fusion can't produce), idiomatic toolchain.

A kernel can also be registered as a framework op
(:meth:`PallasKernel.register`), making it usable from ``mx.nd.*``,
``mx.sym.*`` and Gluon exactly like built-ins — the TPU analogue of
wiring an RTC kernel behind a Custom op.

Off-TPU the kernel runs in Pallas interpreter mode (numerically identical,
slow) so tests and CPU development work; ``interpret`` can be forced
either way.
"""
from __future__ import annotations

from typing import Any, Callable, Optional, Sequence

__all__ = ["PallasKernel", "CudaModule"]


def _on_tpu() -> bool:
    import jax
    try:
        return jax.local_devices()[0].platform == "tpu"
    except Exception:
        return False


def resolve_interpret(arrays) -> bool:
    """True (interpreter mode) unless the inputs live on TPU.

    Compute follows data placement, not the default backend (this machine's
    axon plugin pins the default to TPU even when arrays sit on CPU), so
    the decision reads the concrete inputs' devices; tracers (symbolic use
    under someone else's jit) fall back to the default backend's platform.
    """
    for a in arrays:
        try:
            devs = a.devices() if callable(getattr(a, "devices", None)) \
                else None
        except Exception:
            devs = None
        if devs:
            return not any(d.platform == "tpu" for d in devs)
    return not _on_tpu()


class PallasKernel:
    """A compiled Pallas kernel callable on NDArrays.

    Parameters mirror ``pl.pallas_call``: ``kernel_fn`` takes input refs,
    output refs, then scratch refs; ``out_shape`` is one
    ``(shape, dtype)`` pair or a list of them. Extra pallas_call
    keyword arguments (``grid``, ``in_specs``, ``out_specs``,
    ``scratch_shapes``, ``compiler_params``, ...) pass through verbatim.
    """

    def __init__(self, kernel_fn: Callable, out_shape, name: Optional[str]
                 = None, interpret: Optional[bool] = None, **pallas_kwargs):
        import jax
        self._name = name or getattr(kernel_fn, "__name__", "pallas_kernel")
        self._kernel_fn = kernel_fn

        def to_sds(s):
            if isinstance(s, jax.ShapeDtypeStruct):
                return s
            shape, dtype = s
            return jax.ShapeDtypeStruct(tuple(shape), dtype)

        # a (shape, dtype) pair has a non-sequence second element; a list
        # of outputs is a sequence of pairs/ShapeDtypeStructs
        if isinstance(out_shape, (list, tuple)) and out_shape and \
                (isinstance(out_shape[0], jax.ShapeDtypeStruct) or
                 (len(out_shape) != 2 or
                  isinstance(out_shape[1],
                             (list, tuple, jax.ShapeDtypeStruct)))):
            self._out_shape = [to_sds(s) for s in out_shape]
            self._multi = True
        else:
            self._out_shape = to_sds(out_shape)
            self._multi = False
        self._pallas_kwargs = dict(pallas_kwargs)
        self._interpret = interpret
        self._compiled = {}

    def _build(self, interpret: bool):
        fn = self._compiled.get(interpret)
        if fn is None:
            import jax
            from jax.experimental import pallas as pl
            call = pl.pallas_call(
                self._kernel_fn, out_shape=self._out_shape,
                interpret=interpret, **self._pallas_kwargs)
            fn = jax.jit(call)
            self._compiled[interpret] = fn
        return fn

    def _run(self, raw):
        interpret = self._interpret
        if interpret is None:
            interpret = resolve_interpret(raw)
        return self._build(interpret)(*raw)

    def __call__(self, *args):
        """Run on NDArrays (or raw jax arrays); returns NDArray(s)."""
        from . import ndarray as nd
        raw = [a.data if isinstance(a, nd.NDArray) else a for a in args]
        out = self._run(raw)
        if self._multi:
            return tuple(nd.NDArray(o) for o in out)
        return nd.NDArray(out)

    def register(self, op_name: str, num_inputs: Optional[int] = None):
        """Expose the kernel as a framework op (``mx.nd.<op_name>`` /
        ``mx.sym.<op_name>``)."""
        from .ops.registry import register as reg_op
        run = self._run
        multi = self._multi

        @reg_op(op_name, num_inputs=num_inputs)
        def _kernel_op(*arrays):
            out = run(list(arrays))
            return tuple(out) if multi else out

        if multi:
            _kernel_op.num_outputs = len(self._out_shape)

        _kernel_op.fn.__doc__ = "Pallas kernel %r (registered via " \
            "mx.rtc.PallasKernel.register)" % self._name
        return _kernel_op

    def __repr__(self):
        return "PallasKernel(%s)" % self._name


class CudaModule:
    """Reference-API shim (python/mxnet/rtc.py CudaModule). There is no
    NVRTC on TPU; kernels are written in Pallas instead."""

    def __init__(self, *a, **kw):
        raise NotImplementedError(
            "CUDA RTC does not exist on TPU — write the kernel in Pallas "
            "and wrap it with mx.rtc.PallasKernel (see "
            "mxnet_tpu/ops/pallas/flash_attention.py for a worked example)")

"""``mx.contrib`` — experimental-op namespaces.

Reference: ``python/mxnet/contrib/__init__.py`` re-exports ``ndarray`` /
``symbol`` modules that surface every registry op carrying the
``_contrib_`` prefix under its bare name (``mx.contrib.nd.MultiBoxPrior``
↔ registry ``_contrib_MultiBoxPrior``). Resolution is lazy (PEP 562) so
ops registered after import — e.g. via ``mx.rtc.PallasKernel.register``
— appear automatically.
"""
from . import ndarray
from . import ndarray as nd
from . import symbol
from . import symbol as sym

__all__ = ["ndarray", "nd", "symbol", "sym"]

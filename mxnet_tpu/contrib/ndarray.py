"""``mx.contrib.nd`` — imperative wrappers for ``_contrib_*`` registry ops
(reference: python/mxnet/contrib/ndarray.py, populated by
``_init_ndarray_module(..., "_contrib_")``)."""
from __future__ import annotations

from ..ops import OP_REGISTRY


def __getattr__(name):
    op = OP_REGISTRY.get("_contrib_" + name)
    if op is None:
        raise AttributeError(
            "module %r has no attribute %r (no registry op named "
            "'_contrib_%s')" % (__name__, name, name))
    from ..ndarray.ndarray import imperative_invoke

    def wrapper(*args, **kwargs):
        return imperative_invoke(op, *args, **kwargs)

    wrapper.__name__ = name
    wrapper.__doc__ = op.__doc__
    globals()[name] = wrapper
    return wrapper


def __dir__():
    return sorted(set(globals()) | {
        n[len("_contrib_"):] for n in OP_REGISTRY if n.startswith("_contrib_")})

"""``mx.contrib.sym`` — symbolic wrappers for ``_contrib_*`` registry ops
(reference: python/mxnet/contrib/symbol.py, populated by
``_init_symbol_module(..., "_contrib_")``)."""
from __future__ import annotations

from ..ops import OP_REGISTRY


def __getattr__(name):
    op = OP_REGISTRY.get("_contrib_" + name)
    if op is None:
        raise AttributeError(
            "module %r has no attribute %r (no registry op named "
            "'_contrib_%s')" % (__name__, name, name))
    from ..symbol import make_symbol_function

    fn = make_symbol_function(op)
    globals()[name] = fn
    return fn


def __dir__():
    return sorted(set(globals()) | {
        n[len("_contrib_"):] for n in OP_REGISTRY if n.startswith("_contrib_")})

"""Optimizer update ops.

Reference: ``src/operator/optimizer_op{-inl.h,.cc,.cu}`` — NNVM ops
``sgd_update``, ``sgd_mom_update``, ``adam_update``, ``rmsprop_update``,
``rmspropalex_update`` used both for worker-side updates and server-side
``update_on_kvstore`` updates (SURVEY.md §2.5 "Optimizer update ops").

Each op is a pure jnp function: weight/state inputs -> updated values. The
Python Optimizer classes (mxnet_tpu/optimizer.py) call these so the entire
update is one fused XLA computation per parameter.
"""
from __future__ import annotations

import jax.numpy as jnp

from .registry import register

__all__ = []


import numpy as _np


def _clip_arg(c):
    """Normalize a clip threshold: None / non-positive concrete number ->
    no clipping; a traced scalar (the fused trainer step lifts the clip
    VALUE to a dynamic argument — its presence is the static part, and it
    is only lifted when positive) is always an active threshold."""
    if c is None:
        return None
    if isinstance(c, (int, float, _np.number)):
        return c if c > 0 else None
    return c


def _grad_prep(weight, grad, rescale_grad, clip_gradient, wd):
    g = grad * rescale_grad
    c = _clip_arg(clip_gradient)
    if c is not None:
        g = jnp.clip(g, -c, c)
    return g + wd * weight


@register("sgd_update", num_inputs=2)
def sgd_update(weight, grad, lr=0.01, wd=0.0, rescale_grad=1.0,
               clip_gradient=-1.0):
    """weight -= lr * (rescale*clip(grad) + wd*weight)
    (reference: optimizer_op.cc sgd_update)."""
    g = _grad_prep(weight, grad, rescale_grad, clip_gradient, wd)
    return weight - lr * g


@register("sgd_mom_update", num_inputs=3)
def sgd_mom_update(weight, grad, mom, lr=0.01, momentum=0.0, wd=0.0,
                   rescale_grad=1.0, clip_gradient=-1.0):
    """mom = momentum*mom - lr*(grad_prep); weight += mom
    (reference: optimizer_op.cc sgd_mom_update). Returns (weight, mom)."""
    g = _grad_prep(weight, grad, rescale_grad, clip_gradient, wd)
    new_mom = momentum * mom - lr * g
    return weight + new_mom, new_mom


@register("nag_mom_update", num_inputs=3)
def nag_mom_update(weight, grad, mom, lr=0.01, momentum=0.0, wd=0.0,
                   rescale_grad=1.0, clip_gradient=-1.0):
    """Nesterov momentum (reference: python/mxnet/optimizer.py NAG.update)."""
    g = _grad_prep(weight, grad, rescale_grad, clip_gradient, wd)
    new_mom = momentum * mom + g
    return weight - lr * (g + momentum * new_mom), new_mom


@register("adam_update", num_inputs=4)
def adam_update(weight, grad, mean, var, lr=0.001, beta1=0.9, beta2=0.999,
                epsilon=1e-8, wd=0.0, rescale_grad=1.0, clip_gradient=-1.0):
    """(reference: optimizer_op.cc adam_update). Returns (weight, mean, var);
    lr is expected already bias-corrected by the caller (as the reference's
    Adam.update does)."""
    g = _grad_prep(weight, grad, rescale_grad, clip_gradient, wd)
    new_mean = beta1 * mean + (1 - beta1) * g
    new_var = beta2 * var + (1 - beta2) * jnp.square(g)
    new_w = weight - lr * new_mean / (jnp.sqrt(new_var) + epsilon)
    return new_w, new_mean, new_var


@register("rmsprop_update", num_inputs=3)
def rmsprop_update(weight, grad, n, lr=0.001, gamma1=0.9, epsilon=1e-8,
                   wd=0.0, rescale_grad=1.0, clip_gradient=-1.0,
                   clip_weights=-1.0):
    """Tieleman & Hinton RMSProp (reference: optimizer_op.cc rmsprop_update)."""
    g = _grad_prep(weight, grad, rescale_grad, clip_gradient, wd)
    new_n = (1 - gamma1) * jnp.square(g) + gamma1 * n
    new_w = weight - lr * g / jnp.sqrt(new_n + epsilon)
    cw = _clip_arg(clip_weights)
    if cw is not None:
        new_w = jnp.clip(new_w, -cw, cw)
    return new_w, new_n


@register("rmspropalex_update", num_inputs=5)
def rmspropalex_update(weight, grad, n, g_acc, delta, lr=0.001, gamma1=0.95,
                       gamma2=0.9, epsilon=1e-8, wd=0.0, rescale_grad=1.0,
                       clip_gradient=-1.0, clip_weights=-1.0):
    """Graves' centered RMSProp (reference: optimizer_op.cc
    rmspropalex_update). Returns (weight, n, g_acc, delta)."""
    g = _grad_prep(weight, grad, rescale_grad, clip_gradient, wd)
    new_n = (1 - gamma1) * jnp.square(g) + gamma1 * n
    new_g = (1 - gamma1) * g + gamma1 * g_acc
    new_delta = gamma2 * delta - lr * g / jnp.sqrt(
        new_n - jnp.square(new_g) + epsilon)
    new_w = weight + new_delta
    cw = _clip_arg(clip_weights)
    if cw is not None:
        new_w = jnp.clip(new_w, -cw, cw)
    return new_w, new_n, new_g, new_delta


@register("adagrad_update", num_inputs=3)
def adagrad_update(weight, grad, history, lr=0.01, epsilon=1e-7, wd=0.0,
                   rescale_grad=1.0, clip_gradient=-1.0):
    """(reference: python/mxnet/optimizer.py AdaGrad.update)."""
    g = _grad_prep(weight, grad, rescale_grad, clip_gradient, 0.0)
    new_hist = history + jnp.square(g)
    new_w = weight - lr * (g / jnp.sqrt(new_hist + epsilon) + wd * weight)
    return new_w, new_hist


@register("adadelta_update", num_inputs=4)
def adadelta_update(weight, grad, acc_g, acc_delta, rho=0.9, epsilon=1e-5,
                    wd=0.0, rescale_grad=1.0, clip_gradient=-1.0):
    """(reference: python/mxnet/optimizer.py AdaDelta.update)."""
    g = _grad_prep(weight, grad, rescale_grad, clip_gradient, 0.0)
    new_acc_g = rho * acc_g + (1 - rho) * jnp.square(g)
    delta = jnp.sqrt(acc_delta + epsilon) / jnp.sqrt(new_acc_g + epsilon) * g
    new_acc_delta = rho * acc_delta + (1 - rho) * jnp.square(delta)
    new_w = weight - delta - wd * weight
    return new_w, new_acc_g, new_acc_delta


@register("ftrl_update", num_inputs=4)
def ftrl_update(weight, grad, z, n, lr=0.1, lamda1=0.01, beta=1.0, wd=0.0,
                rescale_grad=1.0, clip_gradient=-1.0):
    """(reference: python/mxnet/optimizer.py Ftrl.update)."""
    g = _grad_prep(weight, grad, rescale_grad, clip_gradient, 0.0)
    new_n = n + jnp.square(g)
    sigma = (jnp.sqrt(new_n) - jnp.sqrt(n)) / lr
    new_z = z + g - sigma * weight
    new_w = jnp.where(
        jnp.abs(new_z) > lamda1,
        -(new_z - jnp.sign(new_z) * lamda1) /
        ((beta + jnp.sqrt(new_n)) / lr + wd),
        0.0)
    return new_w.astype(weight.dtype), new_z, new_n


@register("adamax_update", num_inputs=4)
def adamax_update(weight, grad, mean, u, lr=0.002, beta1=0.9, beta2=0.999,
                  wd=0.0, rescale_grad=1.0, clip_gradient=-1.0):
    """(reference: python/mxnet/optimizer.py Adamax.update); lr already
    bias-corrected by caller."""
    g = _grad_prep(weight, grad, rescale_grad, clip_gradient, wd)
    new_mean = beta1 * mean + (1 - beta1) * g
    new_u = jnp.maximum(beta2 * u, jnp.abs(g))
    return weight - lr * new_mean / new_u, new_mean, new_u


@register("sgld_update", num_inputs=2, needs_rng=True, is_random=True)
def sgld_update(weight, grad, lr=0.01, wd=0.0, rescale_grad=1.0,
                clip_gradient=-1.0, _rng=None):
    """Stochastic Gradient Langevin Dynamics (reference: optimizer.py SGLD)."""
    import jax
    g = _grad_prep(weight, grad, rescale_grad, clip_gradient, wd)
    noise = jax.random.normal(_rng, weight.shape, weight.dtype) * \
        jnp.sqrt(jnp.asarray(lr, weight.dtype))
    return weight - lr / 2 * g + noise

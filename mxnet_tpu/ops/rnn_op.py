"""Fused RNN operator (RNN/LSTM/GRU, multi-layer, bidirectional).

Reference: ``src/operator/rnn-inl.h`` + ``cudnn_rnn-inl.h`` — the reference's
fused RNN is cuDNN-only (CPU path is LOG(FATAL), rnn.cc:31-32); cells had to
be unrolled on CPU. Here the fused path is first-class on every backend:
each layer is one ``lax.scan`` whose step does a single gate matmul on the
MXU — the idiomatic TPU shape for recurrence (no dynamic control flow,
static shapes, weights resident in registers/HBM across steps).

Parameter packing (cuDNN convention, matching FusedRNNCell.unfuse order):
for each layer, for each direction: W_x (G*H, in), W_h (G*H, H); then for
each layer/direction: b_x (G*H), b_h (G*H). Gate order: LSTM i,f,g,o;
GRU r,z,n (cuDNN).
"""
from __future__ import annotations

import numpy as np
import jax
import jax.numpy as jnp
from jax import lax

from .registry import register
from .. import amp

__all__ = ["rnn_param_size", "rnn_unpack_params"]

_GATES = {"rnn_relu": 1, "rnn_tanh": 1, "lstm": 4, "gru": 3}


def rnn_param_size(num_layers, input_size, state_size, mode,
                   bidirectional=False):
    """Total packed parameter count (reference: rnn-inl.h GetParamSize)."""
    gates = _GATES[mode]
    dirs = 2 if bidirectional else 1
    size = 0
    for layer in range(num_layers):
        in_sz = input_size if layer == 0 else state_size * dirs
        size += dirs * gates * state_size * (in_sz + state_size + 2)
    return size


def rnn_unpack_params(params, num_layers, input_size, state_size, mode,
                      bidirectional=False):
    """Split the packed vector into per-layer/direction (Wx, Wh, bx, bh)."""
    gates = _GATES[mode]
    dirs = 2 if bidirectional else 1
    G = gates * state_size
    weights, biases = [], []
    off = 0
    for layer in range(num_layers):
        in_sz = input_size if layer == 0 else state_size * dirs
        for _ in range(dirs):
            wx = params[off:off + G * in_sz].reshape(G, in_sz)
            off += G * in_sz
            wh = params[off:off + G * state_size].reshape(G, state_size)
            off += G * state_size
            weights.append((wx, wh))
    for layer in range(num_layers):
        for _ in range(dirs):
            bx = params[off:off + G]
            off += G
            bh = params[off:off + G]
            off += G
            biases.append((bx, bh))
    return weights, biases


def _cell_step(mode, H):
    """One time step: (h[, c]), gates -> new state and output h."""
    if mode in ("rnn_relu", "rnn_tanh"):
        act = jnp.tanh if mode == "rnn_tanh" else jax.nn.relu

        def step(h, c, pre):
            h_new = act(pre)
            return h_new, None, h_new
    elif mode == "lstm":
        def step(h, c, pre):
            i, f, g, o = jnp.split(pre, 4, axis=-1)
            i, f, o = jax.nn.sigmoid(i), jax.nn.sigmoid(f), jax.nn.sigmoid(o)
            g = jnp.tanh(g)
            c_new = f * c + i * g
            h_new = o * jnp.tanh(c_new)
            return h_new, c_new, h_new
    elif mode == "gru":
        # GRU needs the recurrent term per-gate (n gate uses r*(Wh h)):
        # handled in _scan_layer by passing both x-side and h-side pre-acts
        def step(h, c, pre):
            raise NotImplementedError
    else:
        raise ValueError("unknown RNN mode %r" % mode)
    return step


def _scan_layer(x, h0, c0, wx, wh, bx, bh, mode, reverse=False):
    """Run one direction of one layer over time. x: (T, N, in)."""
    H = h0.shape[-1]
    # hoist the input projection out of the scan: one big MXU matmul
    x_proj = jnp.einsum("tni,gi->tng", x, wx,
                        preferred_element_type=jnp.float32).astype(x.dtype) \
        + (bx + (0.0 if mode == "gru" else bh)).astype(x.dtype)

    if mode == "gru":
        def body(carry, xp):
            h = carry
            rp = h @ wh.T + bh.astype(h.dtype)   # recurrent pre-activation
            xr, xz, xn = jnp.split(xp, 3, axis=-1)
            hr, hz, hn = jnp.split(rp, 3, axis=-1)
            r = jax.nn.sigmoid(xr + hr)
            z = jax.nn.sigmoid(xz + hz)
            n = jnp.tanh(xn + r * hn)
            h_new = (1 - z) * n + z * h
            return h_new, h_new

        h_last, ys = lax.scan(body, h0, x_proj, reverse=reverse)
        return ys, h_last, None

    step = _cell_step(mode, H)

    def body(carry, xp):
        h, c = carry
        pre = xp + h @ wh.T
        if mode != "lstm":
            pre = pre  # bh already folded into x_proj
        h_new, c_new, y = step(h, c, pre)
        return (h_new, c_new if c_new is not None else c), y

    if mode == "lstm":
        init = (h0, c0 if c0 is not None else jnp.zeros_like(h0))
    else:
        init = (h0, jnp.zeros_like(h0))
    (h_last, c_last), ys = lax.scan(body, init, x_proj, reverse=reverse)
    return ys, h_last, (c_last if mode == "lstm" else None)


@register("RNN", num_inputs=None, aliases=("rnn",), needs_rng=True)
def rnn(data, parameters, state, state_cell=None, state_size=None,
        num_layers=1, mode="lstm", bidirectional=False, p=0.0,
        state_outputs=False, lstm_state_clip_min=None,
        lstm_state_clip_max=None, _is_train=False, _rng=None):
    """Fused multi-layer RNN (reference: src/operator/rnn-inl.h RNNOp).

    data: (T, N, input_size); state: (L*dirs, N, H); returns output
    (T, N, H*dirs) and, with ``state_outputs``, final states.
    """
    T, N, input_size = data.shape
    H = int(state_size)
    L = int(num_layers)
    dirs = 2 if bidirectional else 1
    # amp: the whole recurrence (input projection + per-step gate matmul)
    # runs in the compute dtype, matching cuDNN's fp16 RNN semantics; the
    # packed master parameters stay fp32 outside the trace.
    data, parameters = amp.cast_compute(data, parameters)
    state = amp.cast_compute(state)
    if state_cell is not None:
        state_cell = amp.cast_compute(state_cell)
    weights, biases = rnn_unpack_params(parameters, L, input_size, H, mode,
                                        bidirectional)

    x = data
    h_finals, c_finals = [], []
    for layer in range(L):
        outs = []
        for d in range(dirs):
            idx = layer * dirs + d
            wx, wh = weights[idx]
            bx, bh = biases[idx]
            h0 = state[idx]
            c0 = state_cell[idx] if state_cell is not None else None
            ys, h_last, c_last = _scan_layer(
                x, h0, c0, wx, wh, bx, bh, mode, reverse=(d == 1))
            outs.append(ys)
            h_finals.append(h_last)
            if c_last is not None:
                c_finals.append(c_last)
        x = outs[0] if dirs == 1 else jnp.concatenate(outs, axis=-1)
        if p > 0.0 and _is_train and layer < L - 1 and _rng is not None:
            keep = jax.random.bernoulli(
                jax.random.fold_in(_rng, layer), 1.0 - p, x.shape)
            x = jnp.where(keep, x / (1.0 - p), 0.0).astype(x.dtype)

    if not state_outputs:
        return x
    h_out = jnp.stack(h_finals)
    if mode == "lstm":
        return x, h_out, jnp.stack(c_finals)
    return x, h_out


# symbol-layer output arity (reference: RNNParam state_outputs)
from .registry import get_op as _get_op  # noqa: E402
_get_op("RNN").num_outputs = lambda attrs: (
    1 if not attrs.get("state_outputs") else
    (3 if attrs.get("mode", "lstm") == "lstm" else 2))

"""Operator registry — the NNVM ``Op`` registry analogue (SURVEY.md §2.9).

In the reference, every operator registers an ``FCompute<cpu/gpu>`` kernel plus
declarative attributes (``FInferShape``, ``FInferType``, ``FGradient``, ...)
into the NNVM registry (reference: include/mxnet/op_attr_types.h:44-228,
src/operator/tensor/elemwise_binary_op_basic.cc:40-104). On TPU the design
collapses:

* **FCompute** -> a pure JAX function over ``jax.Array`` operands. XLA codegen
  replaces the hand-written cpu/gpu kernel twins.
* **FInferShape/FInferType** -> ``jax.eval_shape`` of the same function; no
  per-op rules to maintain.
* **FGradient** -> ``jax.vjp`` of the same function; no per-op backward
  registrations.
* **FResourceRequest (PRNG)** -> ops that sample declare ``needs_rng`` and are
  handed an explicit ``jax.random`` key by the dispatch layer.

So one pure function per op carries the entire contract. The registry is the
single source of truth from which both the imperative ``mx.nd.*`` wrappers and
the symbolic ``mx.sym.*`` wrappers are auto-generated, exactly like the
reference's ``_init_ndarray_module``/``_init_symbol_module`` generate wrappers
from the C op registry (reference: python/mxnet/ndarray.py, symbol.py tails).
"""
from __future__ import annotations

import functools
from typing import Any, Callable, Dict, List, Optional, Sequence

__all__ = ["OpDef", "register", "alias", "get_op", "list_ops", "OP_REGISTRY"]


class OpDef:
    """A registered operator.

    Parameters
    ----------
    name : canonical op name (matches the reference op name where one exists).
    fn : pure function ``fn(*arrays, **attrs) -> array | tuple``; arrays are
        jax values, attrs are hashable python values.
    num_inputs : fixed input arity, or ``None`` for variadic (e.g. concat).
    needs_rng : if True, dispatch passes attr ``_rng`` (a jax PRNG key).
    is_random : sampler ops (excluded from gradient tracing).
    """

    def __init__(
        self,
        name: str,
        fn: Callable,
        num_inputs: Optional[int] = 1,
        needs_rng: bool = False,
        is_random: bool = False,
        doc: Optional[str] = None,
    ):
        self.name = name
        self.fn = fn
        self.num_inputs = num_inputs
        self.needs_rng = needs_rng
        self.is_random = is_random
        self.__doc__ = doc or fn.__doc__
        self.aliases: List[str] = [name]
        # Aux-state protocol (BatchNorm-style, SURVEY.md §2.5): the op takes
        # `num_aux` auxiliary-state arrays as trailing inputs and returns
        # `num_aux` updated aux values as trailing outputs for the caller to
        # commit. `num_hidden_outputs` are extra forward outputs (before the
        # aux tail) hidden from the user unless an attr exposes them.
        self.num_aux: int = 0
        self.num_hidden_outputs: int = 0
        # Symbol-layer metadata (reference: nnvm FListInputNames — names of
        # tensor inputs so mx.sym can auto-create weight/bias variables, e.g.
        # "fc1_weight"). None -> derived from the fn signature / defaults.
        self._input_names: Optional[List[str]] = None
        self.aux_input_names: List[str] = []

    @property
    def input_names(self) -> List[str]:
        """Names of the op's tensor inputs (excluding aux states)."""
        if self._input_names is None:
            import inspect
            try:
                params = list(inspect.signature(self.fn).parameters.values())
            except (TypeError, ValueError):
                params = []
            names: List[str] = []
            # num_inputs counts ALL tensor inputs including trailing aux
            n = self.num_inputs
            for p in params:
                if p.kind in (p.VAR_POSITIONAL,):
                    names.append("data")
                    break
                if p.kind not in (p.POSITIONAL_ONLY, p.POSITIONAL_OR_KEYWORD):
                    break
                if n is not None and len(names) >= n:
                    break
                if p.default is inspect.Parameter.empty or p.name in (
                        "weight", "bias", "gamma", "beta", "label",
                        "moving_mean", "moving_var", "moving_avg"):
                    names.append(p.name)
                else:
                    break
            if not names and self.num_inputs != 0:
                names = ["data"]
            if self.num_aux:
                self.aux_input_names = names[-self.num_aux:]
                names = names[: len(names) - self.num_aux]
            self._input_names = names
        return self._input_names

    def __call__(self, *args, **kwargs):
        return self.fn(*args, **kwargs)

    def __repr__(self):
        return "OpDef(%s)" % self.name


OP_REGISTRY: Dict[str, OpDef] = {}


def register(
    name: Optional[str] = None,
    num_inputs: Optional[int] = 1,
    aliases: Sequence[str] = (),
    needs_rng: bool = False,
    is_random: bool = False,
):
    """Decorator: register a pure JAX function as a framework op.

    ``@register("dot", num_inputs=2)`` mirrors ``NNVM_REGISTER_OP(dot)``
    (reference: src/operator/tensor/matrix_op.cc).
    """

    def _reg(fn: Callable) -> OpDef:
        opname = name or fn.__name__
        op = OpDef(opname, fn, num_inputs=num_inputs, needs_rng=needs_rng,
                   is_random=is_random)
        if opname in OP_REGISTRY:
            raise ValueError("Op %s already registered" % opname)
        OP_REGISTRY[opname] = op
        for a in aliases:
            if a in OP_REGISTRY:
                raise ValueError("Op alias %s already registered" % a)
            OP_REGISTRY[a] = op
            op.aliases.append(a)
        functools.update_wrapper(op, fn, updated=())
        return op

    return _reg


def alias(existing: str, *names: str) -> None:
    """Add alias names for an already-registered op (the reference does this
    via add_alias, e.g. elemwise_add a.k.a. _plus — reference:
    src/operator/tensor/elemwise_binary_op_basic.cc:40)."""
    op = OP_REGISTRY[existing]
    for n in names:
        if n in OP_REGISTRY and OP_REGISTRY[n] is not op:
            raise ValueError("Op alias %s already registered" % n)
        OP_REGISTRY[n] = op
        op.aliases.append(n)


def get_op(name: str) -> OpDef:
    try:
        return OP_REGISTRY[name]
    except KeyError:
        raise KeyError(
            "Operator %r not registered (have %d ops)" % (name, len(OP_REGISTRY))
        ) from None


def list_ops() -> List[str]:
    return sorted(OP_REGISTRY.keys())

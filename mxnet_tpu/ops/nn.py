"""Neural-network layer ops.

Reference: the legacy ``OperatorProperty`` family under ``src/operator/``
(SURVEY.md §2.5 "NN layers"): Activation, FullyConnected, Convolution,
Deconvolution, Pooling, BatchNorm, Dropout, LRN, SoftmaxOutput, regression
outputs, MakeLoss, SVMOutput, L2Normalization, InstanceNorm, UpSampling, ...

Design notes (TPU-first):

* Convolution/FullyConnected lower to ``lax.conv_general_dilated`` /
  ``lax.dot_general`` — the two ops XLA tiles onto the MXU. The reference's
  cuDNN algo-selection cache (cudnn_algoreg-inl.h) has no equivalent: XLA's
  ahead-of-time compilation plays that role.
* Loss-head ops (SoftmaxOutput & friends) have *non-vjp* backward semantics in
  the reference — their backward emits (p - onehot) regardless of head
  gradient (src/operator/softmax_output-inl.h). We reproduce this exactly with
  ``jax.custom_vjp``.
* Train/eval mode is an explicit ``_is_train`` attr threaded by the dispatch
  layer (the reference passes it via ``OpContext::is_train``,
  include/mxnet/op_attr_types.h:66-84).
* BatchNorm's moving stats are *auxiliary states* (mutated by forward in the
  reference). Functionally: the op returns trailing "new aux" outputs and the
  caller commits them — see ``OpDef.num_aux`` handling in dispatch/executor.
"""
from __future__ import annotations

import functools
from typing import Tuple

import numpy as np
import jax
import jax.numpy as jnp
from jax import lax

from .registry import register, OP_REGISTRY
from .. import amp
from .. import config as _config

# ----------------------------------------------------------------- helpers


def _tup(x, n=None):
    if x is None:
        return None
    t = (int(x),) if isinstance(x, (int, float)) else tuple(int(v) for v in x)
    if n is not None and len(t) == 1:
        t = t * n
    return t


def _conv_dnums(nd: int):
    spatial = "DHW"[-nd:] if nd <= 3 else None
    lhs = "NC" + spatial
    rhs = "OI" + spatial
    return lax.conv_dimension_numbers((0,) * (nd + 2), (0,) * (nd + 2), (lhs, rhs, lhs))


# ----------------------------------------------------------------- simple


@register("Activation", aliases=("activation",))
def activation(data, act_type="relu"):
    """(reference: src/operator/activation.cc; types relu/sigmoid/tanh/softrelu)."""
    if act_type == "relu":
        return jnp.maximum(data, 0)
    if act_type == "sigmoid":
        return lax.logistic(data)
    if act_type == "tanh":
        return jnp.tanh(data)
    if act_type == "softrelu":
        return jnp.logaddexp(data, 0.0)
    if act_type == "softsign":
        return data / (1 + jnp.abs(data))
    raise ValueError("unknown act_type %s" % act_type)


@register("LeakyReLU", num_inputs=None, needs_rng=True)
def leaky_relu(*inputs, act_type="leaky", slope=0.25, lower_bound=0.125,
               upper_bound=0.334, _is_train=False, _rng=None):
    """(reference: src/operator/leaky_relu.cc; leaky/elu/prelu/rrelu).
    prelu takes a second ``gamma`` input; rrelu samples slope in train mode."""
    data = inputs[0]
    if act_type == "leaky":
        return jnp.where(data >= 0, data, slope * data)
    if act_type == "elu":
        return jnp.where(data >= 0, data, slope * jnp.expm1(data))
    if act_type == "prelu":
        gamma = inputs[1]
        g = gamma.reshape((1, -1) + (1,) * (data.ndim - 2))
        return jnp.where(data >= 0, data, g * data)
    if act_type == "rrelu":
        if _is_train:
            s = jax.random.uniform(_rng, data.shape[:1] + data.shape[1:2],
                                   minval=lower_bound, maxval=upper_bound)
            s = s.reshape(data.shape[:2] + (1,) * (data.ndim - 2))
        else:
            s = (lower_bound + upper_bound) / 2.0
        return jnp.where(data >= 0, data, s * data)
    raise ValueError("unknown act_type %s" % act_type)


@register("softmax")
def softmax(data, axis=-1, temperature=None):
    """(reference: src/operator/nn/softmax.cc)."""
    x = data / temperature if temperature else data
    return jax.nn.softmax(x, axis=axis)


@register("log_softmax")
def log_softmax(data, axis=-1, temperature=None):
    x = data / temperature if temperature else data
    return jax.nn.log_softmax(x, axis=axis)


@register("SoftmaxActivation")
def softmax_activation(data, mode="instance"):
    """(reference: src/operator/softmax_activation.cc). mode=instance:
    softmax over flattened trailing axes; mode=channel: over axis 1."""
    if mode == "channel":
        return jax.nn.softmax(data, axis=1)
    flat = data.reshape(data.shape[0], -1)
    return jax.nn.softmax(flat, axis=-1).reshape(data.shape)


# ----------------------------------------------------------------- dense


@register("FullyConnected", num_inputs=None, aliases=("fully_connected",))
def fully_connected(data, weight, bias=None, num_hidden=None, no_bias=False,
                    flatten=True):
    """y = x W^T + b (reference: src/operator/fully_connected-inl.h:65-130).

    The reference checks the cuBLAS handle and calls gemm
    (fully_connected-inl.h:88); here ``dot_general`` hits the MXU with fp32
    accumulation requested explicitly for fp32 and bf16/fp16 inputs alike
    (amp.mxu_operands).
    """
    x = data.reshape(data.shape[0], -1) if (flatten and data.ndim > 2) else data
    x, weight, acc = amp.mxu_operands(x, weight)
    out = lax.dot_general(
        x, weight,
        dimension_numbers=(((x.ndim - 1,), (1,)), ((), ())),
        **acc,
    ).astype(jnp.result_type(x, weight))
    if not no_bias and bias is not None:
        out = out + bias.astype(out.dtype)
    return out


# ----------------------------------------------------------------- conv


@register("Convolution", num_inputs=None, aliases=("convolution",))
def convolution(data, weight, bias=None, kernel=None, stride=None, dilate=None,
                pad=None, num_filter=None, num_group=1, no_bias=False,
                workspace=1024, cudnn_tune=None, cudnn_off=False, layout=None):
    """N-d convolution, NC(D)HW layout (reference:
    src/operator/convolution-inl.h:315-602). One XLA conv HLO; `workspace`
    and `cudnn_*` attrs are accepted for API parity and ignored."""
    nd = data.ndim - 2
    kernel = _tup(kernel, nd)
    stride = _tup(stride, nd) or (1,) * nd
    dilate = _tup(dilate, nd) or (1,) * nd
    pad = _tup(pad, nd) or (0,) * nd
    dn = _conv_dnums(nd)
    data, weight, acc = amp.mxu_operands(data, weight, conv=True)
    out = lax.conv_general_dilated(
        data, weight,
        window_strides=stride,
        padding=[(p, p) for p in pad],
        rhs_dilation=dilate,
        dimension_numbers=dn,
        feature_group_count=int(num_group),
        **acc,
    ).astype(jnp.result_type(data, weight))
    if not no_bias and bias is not None:
        out = out + bias.reshape((1, -1) + (1,) * nd).astype(out.dtype)
    return out


@register("Deconvolution", num_inputs=None, aliases=("deconvolution",))
def deconvolution(data, weight, bias=None, kernel=None, stride=None,
                  dilate=None, pad=None, adj=None, target_shape=None,
                  num_filter=None, num_group=1, no_bias=True, workspace=1024,
                  cudnn_tune=None, cudnn_off=False, layout=None):
    """Transposed convolution (reference: src/operator/deconvolution-inl.h).
    Weight layout matches the reference: (C_in, num_filter/group, *kernel).
    Lowered as input-dilated convolution with a spatially-flipped kernel."""
    nd = data.ndim - 2
    kernel = _tup(kernel, nd)
    stride = _tup(stride, nd) or (1,) * nd
    dilate = _tup(dilate, nd) or (1,) * nd
    pad = _tup(pad, nd) or (0,) * nd
    adj = _tup(adj, nd) or (0,) * nd
    g = int(num_group)
    cin, fpg = weight.shape[0], weight.shape[1]
    f = fpg * g
    # (C_in, F/g, *k) -> (F, C_in/g, *k), grouped correctly
    w = weight.reshape((g, cin // g, fpg) + weight.shape[2:])
    w = jnp.moveaxis(w, 2, 1).reshape((f, cin // g) + weight.shape[2:])
    w = jnp.flip(w, axis=tuple(range(2, 2 + nd)))
    eff_k = tuple((k - 1) * d + 1 for k, d in zip(kernel, dilate))
    pads = [(ek - 1 - p, ek - 1 - p + a) for ek, p, a in zip(eff_k, pad, adj)]
    data, w, acc = amp.mxu_operands(data, w, conv=True)
    out = lax.conv_general_dilated(
        data, w,
        window_strides=(1,) * nd,
        padding=pads,
        lhs_dilation=stride,
        rhs_dilation=dilate,
        dimension_numbers=_conv_dnums(nd),
        feature_group_count=g,
        **acc,
    ).astype(jnp.result_type(data, w))
    if not no_bias and bias is not None:
        out = out + bias.reshape((1, -1) + (1,) * nd).astype(out.dtype)
    return out


# ----------------------------------------------------------------- pooling


@register("Pooling", aliases=("pooling", "Pooling_v1"))
def pooling(data, kernel=None, pool_type="max", global_pool=False,
            stride=None, pad=None, pooling_convention="valid",
            cudnn_off=False, count_include_pad=True):
    """Max/avg/sum pooling over NC(D)HW (reference: src/operator/pooling.cc,
    src/operator/nn/pool.h). Lowered to lax.reduce_window."""
    nd = data.ndim - 2
    if global_pool:
        kernel = data.shape[2:]
        stride = (1,) * nd
        pad = (0,) * nd
    kernel = _tup(kernel, nd)
    stride = _tup(stride, nd) or (1,) * nd
    pad = _tup(pad, nd) or (0,) * nd
    window = (1, 1) + kernel
    strides = (1, 1) + stride
    pads = [(0, 0), (0, 0)]
    for i in range(nd):
        lo = hi = pad[i]
        if pooling_convention == "full":
            # ceil output size (reference: pooling-inl.h full convention)
            size = data.shape[2 + i] + 2 * pad[i] - kernel[i]
            rem = size % stride[i]
            if rem:
                hi += stride[i] - rem
        pads.append((lo, hi))
    if pool_type == "max":
        # init must be a host constant, not a jnp array: reduce_window's
        # autodiff rule can't linearize a traced init value
        if jnp.issubdtype(data.dtype, jnp.floating):
            init = np.array(-np.inf, data.dtype)
        else:
            init = np.array(jnp.iinfo(data.dtype).min, data.dtype)
        out = lax.reduce_window(data, init, lax.max, window, strides, pads)
    elif pool_type in ("avg", "sum"):
        zero = np.zeros((), data.dtype)
        out = lax.reduce_window(data, zero, lax.add, window, strides, pads)
        if pool_type == "avg":
            if count_include_pad:
                out = out / float(np.prod(kernel))
            else:
                ones = jnp.ones_like(data)
                cnt = lax.reduce_window(ones, zero, lax.add, window, strides, pads)
                out = out / cnt
    else:
        raise ValueError("unknown pool_type %s" % pool_type)
    return out.astype(data.dtype)


# ----------------------------------------------------------------- norm


@register("BatchNorm", num_inputs=3, aliases=("batch_norm", "BatchNorm_v1"))
def batch_norm(data, gamma, beta, moving_mean, moving_var, eps=1e-3,
               momentum=0.9, fix_gamma=True, use_global_stats=False,
               output_mean_var=False, axis=1, cudnn_off=False,
               _is_train=False):
    """Batch normalization (reference: src/operator/batch_norm-inl.h).

    Aux-state protocol: inputs 3,4 are auxiliary states (moving_mean/var);
    returns (out, mean, var, new_moving_mean, new_moving_var) where the
    trailing ``OpDef.num_aux`` outputs are the updated aux values the caller
    commits (the reference mutates aux in-place during Forward).
    """
    ax = axis % data.ndim
    red = tuple(i for i in range(data.ndim) if i != ax)
    bshape = tuple(data.shape[ax] if i == ax else 1 for i in range(data.ndim))
    g = jnp.ones_like(gamma) if fix_gamma else gamma
    if _is_train and not use_global_stats:
        x32 = data.astype(jnp.float32)
        mean = jnp.mean(x32, axis=red)
        var = jnp.mean(jnp.square(x32 - mean.reshape(bshape)), axis=red)
        new_mm = momentum * moving_mean + (1 - momentum) * mean
        new_mv = momentum * moving_var + (1 - momentum) * var
    else:
        mean, var = moving_mean, moving_var
        new_mm, new_mv = moving_mean, moving_var
    inv = lax.rsqrt(var.reshape(bshape) + eps)
    out = (data - mean.reshape(bshape)) * inv * g.reshape(bshape) + beta.reshape(bshape)
    return (out.astype(data.dtype), mean, var, new_mm, new_mv)


OP_REGISTRY["BatchNorm"].num_inputs = 5  # incl. the two trailing aux states
OP_REGISTRY["BatchNorm"].num_aux = 2
OP_REGISTRY["BatchNorm"].num_hidden_outputs = 2  # mean,var hidden unless output_mean_var


@register("InstanceNorm")
def instance_norm(data, gamma, beta, eps=0.001):
    """(reference: src/operator/instance_norm.cc): normalize per (n, c) over
    spatial dims."""
    red = tuple(range(2, data.ndim))
    mean = jnp.mean(data, axis=red, keepdims=True)
    var = jnp.var(data, axis=red, keepdims=True)
    g = gamma.reshape((1, -1) + (1,) * (data.ndim - 2))
    b = beta.reshape((1, -1) + (1,) * (data.ndim - 2))
    return (data - mean) * lax.rsqrt(var + eps) * g + b


OP_REGISTRY["InstanceNorm"].num_inputs = 3


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4))
def _layer_norm_p(data, gamma, beta, ax, eps):
    out, _, _ = _layer_norm_fwd_impl(data, gamma, beta, ax, eps)
    return out


def _layer_norm_fwd_impl(data, gamma, beta, ax, eps):
    x32 = data.astype(jnp.float32)
    # one-pass statistics (var = E[x^2] - E[x]^2, f32): both reductions
    # read x once and XLA fuses them into a single pass, vs the
    # two-pass E[(x-mean)^2] form whose second reduction re-reads x
    # after the mean — measured ~2 ms/step on the L12 transformer. The
    # cancellation risk is acceptable in f32 for activation-scale data
    # (flax's use_fast_variance default does the same); models whose
    # activations carry a large common offset can restore the two-pass
    # form with MXNET_TPU_LAYERNORM_TWO_PASS=1.
    mean = jnp.mean(x32, axis=ax, keepdims=True)
    # deliberately read live instead of an on_change-cached constant:
    # on_change only fires on config.set/reset, so a cached value would
    # ignore env mutation after import (how every other knob behaves via
    # config.get). Cost is one dict+environ lookup per op CALL (trace or
    # eager), dwarfed by the reductions below — not per element.
    if _config.get("MXNET_TPU_LAYERNORM_TWO_PASS"):
        var = jnp.mean(jnp.square(x32 - mean), axis=ax, keepdims=True)
    else:
        msq = jnp.mean(jnp.square(x32), axis=ax, keepdims=True)
        var = jnp.maximum(msq - jnp.square(mean), 0.0)
    rstd = lax.rsqrt(var + eps)
    shp = tuple(data.shape[ax] if i == ax else 1
                for i in range(data.ndim))
    out = (x32 - mean) * rstd * gamma.reshape(shp).astype(jnp.float32) \
        + beta.reshape(shp).astype(jnp.float32)
    return out.astype(data.dtype), mean, rstd


def _layer_norm_fwd(data, gamma, beta, ax, eps):
    out, mean, rstd = _layer_norm_fwd_impl(data, gamma, beta, ax, eps)
    # residuals are the (possibly bf16) input plus O(rows) f32 stats —
    # the f32 normalized tensor never persists to HBM, which is the whole
    # point: XLA autodiff of the naive form saved x in f32 and emitted
    # ~2ms/LN of f32 elementwise fusions (measured; see bench notes)
    return out, (data, gamma, beta, mean, rstd)


def _layer_norm_bwd(ax, eps, res, g):
    data, gamma, beta, mean, rstd = res
    shp = tuple(data.shape[ax] if i == ax else 1
                for i in range(data.ndim))
    xhat = (data.astype(jnp.float32) - mean) * rstd
    gy = g.astype(jnp.float32)
    gyg = gy * gamma.reshape(shp).astype(jnp.float32)
    m1 = jnp.mean(gyg, axis=ax, keepdims=True)
    m2 = jnp.mean(gyg * xhat, axis=ax, keepdims=True)
    dx = (rstd * (gyg - m1 - xhat * m2)).astype(data.dtype)
    red = tuple(i for i in range(data.ndim) if i != ax)
    dgamma = jnp.sum(gy * xhat, axis=red).astype(gamma.dtype)
    dbeta = jnp.sum(gy, axis=red).astype(beta.dtype)
    return dx, dgamma.reshape(gamma.shape), dbeta.reshape(beta.shape)


_layer_norm_p.defvjp(_layer_norm_fwd, _layer_norm_bwd)


@register("LayerNorm", num_inputs=3)
def layer_norm(data, gamma, beta, axis=-1, eps=1e-5,
               output_mean_var=False):
    """Layer normalization over ``axis`` (upstream MXNet added this as
    src/operator/nn/layer_norm.cc shortly after the referenced 0.11
    snapshot; included here because it is load-bearing for transformer
    workloads). Stats in fp32, output in the input dtype so bf16
    activations stay bf16 under amp; the analytic custom backward keeps
    only the input + per-row stats as residuals."""
    ax = axis % data.ndim
    if output_mean_var:
        out, mean, rstd = _layer_norm_fwd_impl(data, gamma, beta, ax, eps)
        var = jnp.square(1.0 / rstd) - eps
        return out, jnp.squeeze(mean, ax), jnp.squeeze(var, ax)
    return _layer_norm_p(data, gamma, beta, ax, float(eps))


@register("L2Normalization")
def l2_normalization(data, eps=1e-10, mode="instance"):
    """(reference: src/operator/l2_normalization.cc)."""
    if mode == "instance":
        red = tuple(range(1, data.ndim))
        kd = True
    elif mode == "channel":
        red = (1,)
        kd = True
    elif mode == "spatial":
        red = tuple(range(2, data.ndim))
        kd = True
    else:
        raise ValueError(mode)
    n = jnp.sqrt(jnp.sum(jnp.square(data), axis=red, keepdims=kd) + eps)
    return data / n


@register("LRN")
def lrn(data, alpha=1e-4, beta=0.75, knorm=2.0, nsize=5):
    """Local response norm across channels (reference: src/operator/lrn.cc).

    TPU lowering notes: ``lax.reduce_window`` over a padded channel
    axis miscompiles on this TPU AOT compiler (post-optimization
    "incompatible shapes [...,96] vs [...,92]" internal error, AlexNet
    batch 1, f32 and bf16), so the channel-window sum is instead a
    banded 0/1 matmul over the channel axis — one MXU op, measured
    1.2-1.4x the shifted-slice-add form it replaces (round-5 sweep).
    For the standard beta=0.75 the power lowers to rsqrt/sqrt algebra
    instead of exp/log. Stats in f32."""
    x32 = data.astype(jnp.float32)
    C = data.shape[1]
    half = nsize // 2
    idx = jnp.arange(C)
    band = (jnp.abs(idx[:, None] - idx[None, :]) <= half).astype(
        jnp.float32)
    sq = jnp.square(x32).reshape(data.shape[0], C, -1)
    ssum = jnp.einsum("ij,njk->nik", band, sq,
                      preferred_element_type=jnp.float32)
    ssum = ssum.reshape(x32.shape)
    t = knorm + alpha * ssum / nsize
    if beta == 0.75:
        r = lax.rsqrt(t)                    # t^-0.75 = rsqrt(t)*sqrt(rsqrt(t))
        out = x32 * r * jnp.sqrt(r)
    else:
        out = x32 / jnp.power(t, beta)
    return out.astype(data.dtype)


# ----------------------------------------------------------------- dropout


@register("Dropout", needs_rng=True, aliases=("dropout",))
def dropout(data, p=0.5, mode="training", _is_train=False, _rng=None):
    """Inverted dropout (reference: src/operator/dropout-inl.h). Identity at
    inference (unless mode='always')."""
    if (not _is_train and mode != "always") or p == 0.0:
        return data
    keep = 1.0 - p
    mask = jax.random.bernoulli(_rng, keep, data.shape)
    return jnp.where(mask, data / keep, 0.0).astype(data.dtype)


# ----------------------------------------------------------------- upsample


@register("UpSampling", num_inputs=None)
def upsampling(*data, scale=2, sample_type="nearest", num_filter=0,
               multi_input_mode="concat", num_args=None, workspace=512):
    """(reference: src/operator/upsampling.cc). nearest: repeat; bilinear:
    jax.image.resize (the reference uses a fixed bilinear-kernel Deconvolution)."""
    outs = []
    base = data[0]
    th, tw = base.shape[2] * scale, base.shape[3] * scale
    for d in data:
        if sample_type == "nearest":
            s = th // d.shape[2]
            o = jnp.repeat(jnp.repeat(d, s, axis=2), s, axis=3)
        else:
            o = jax.image.resize(d, d.shape[:2] + (th, tw), method="bilinear")
        outs.append(o)
    if len(outs) == 1:
        return outs[0]
    if multi_input_mode == "sum":
        return functools.reduce(jnp.add, outs)
    return jnp.concatenate(outs, axis=1)


# ------------------------------------------------------- loss-head ops
# These reproduce the reference's "backward ignores head gradient" semantics
# with jax.custom_vjp; attrs ride as a hashable nondiff arg.


def _attrs_key(**attrs):
    return tuple(sorted(attrs.items()))


@functools.partial(jax.custom_vjp, nondiff_argnums=(2,))
def _softmax_output_p(data, label, akey):
    attrs = dict(akey)
    if attrs.get("multi_output"):
        return jax.nn.softmax(data, axis=1)
    if attrs.get("preserve_shape"):
        return jax.nn.softmax(data, axis=-1)
    return jax.nn.softmax(data.reshape(data.shape[0], -1), axis=-1).reshape(data.shape)


def _softmax_output_fwd(data, label, akey):
    out = _softmax_output_p(data, label, akey)
    return out, (out, label)


def _softmax_output_bwd(akey, res, g):
    attrs = dict(akey)
    out, label = res
    grad_scale = attrs.get("grad_scale", 1.0)
    ignore_label = attrs.get("ignore_label", -1.0)
    use_ignore = attrs.get("use_ignore", False)
    normalization = attrs.get("normalization", "null")
    multi_output = attrs.get("multi_output", False)
    preserve_shape = attrs.get("preserve_shape", False)
    orig_shape, orig_label = out.shape, label
    if not multi_output and not preserve_shape and out.ndim > 2:
        # default mode softmaxes over the *flattened* trailing axes
        # (forward reshapes to (N, -1)); the p-minus-onehot formula must use
        # the same geometry or the distribution premise breaks
        out = out.reshape(out.shape[0], -1)
        label = label.reshape(label.shape[0], -1) if label.ndim > 1 \
            else label
    cls_axis = 1 if multi_output else -1
    depth = out.shape[cls_axis]
    lab = label.astype(jnp.int32)
    oh = jax.nn.one_hot(lab, depth, axis=cls_axis, dtype=out.dtype)
    if oh.ndim > out.ndim:  # label had a trailing axis of size 1 etc.
        oh = oh.reshape(out.shape)
    grad = out - oh
    valid = jnp.ones_like(label, dtype=out.dtype)
    if use_ignore:
        valid = (label != ignore_label).astype(out.dtype)
        grad = grad * jnp.expand_dims(valid, cls_axis)
    if normalization == "batch":
        grad = grad / out.shape[0]
    elif normalization == "valid":
        grad = grad / jnp.maximum(jnp.sum(valid), 1.0)
    grad = grad.reshape(orig_shape)
    return (grad * grad_scale).astype(out.dtype), jnp.zeros_like(orig_label)


_softmax_output_p.defvjp(_softmax_output_fwd, _softmax_output_bwd)


@register("SoftmaxOutput", num_inputs=2, aliases=("softmax_output", "Softmax"))
def softmax_output(data, label, grad_scale=1.0, ignore_label=-1.0,
                   use_ignore=False, multi_output=False, preserve_shape=False,
                   normalization="null", out_grad=False, smooth_alpha=0.0):
    """Softmax forward with cross-entropy backward (reference:
    src/operator/softmax_output-inl.h; `Softmax` is the 0.11 alias)."""
    if amp.active() and data.dtype == amp.compute_dtype():
        # keep the loss head in fp32 under mixed precision
        data = data.astype(jnp.float32)
    return _softmax_output_p(
        data, label,
        _attrs_key(grad_scale=grad_scale, ignore_label=ignore_label,
                   use_ignore=use_ignore, multi_output=multi_output,
                   preserve_shape=preserve_shape, normalization=normalization))


@functools.partial(jax.custom_vjp, nondiff_argnums=(2, 3))
def _regression_p(data, label, kind, grad_scale):
    if kind == "logistic":
        return lax.logistic(data)
    return data


def _regression_fwd(data, label, kind, grad_scale):
    out = _regression_p(data, label, kind, grad_scale)
    return out, (out, label)


def _regression_bwd(kind, grad_scale, res, g):
    out, label = res
    n = out.shape[1] if out.ndim > 1 else 1
    if kind == "mae":
        grad = jnp.sign(out - label)
    else:  # linear & logistic share (out - label)
        grad = out - label
    return (grad * grad_scale / n).astype(out.dtype), jnp.zeros_like(label)


_regression_p.defvjp(_regression_fwd, _regression_bwd)


@register("LinearRegressionOutput", num_inputs=2, aliases=("linear_regression_output",))
def linear_regression_output(data, label, grad_scale=1.0):
    """(reference: src/operator/regression_output.cc)."""
    return _regression_p(data, label, "linear", grad_scale)


@register("MAERegressionOutput", num_inputs=2, aliases=("mae_regression_output",))
def mae_regression_output(data, label, grad_scale=1.0):
    return _regression_p(data, label, "mae", grad_scale)


@register("LogisticRegressionOutput", num_inputs=2, aliases=("logistic_regression_output",))
def logistic_regression_output(data, label, grad_scale=1.0):
    return _regression_p(data, label, "logistic", grad_scale)


@functools.partial(jax.custom_vjp, nondiff_argnums=(1,))
def _make_loss_p(data, akey):
    return data


def _make_loss_fwd(data, akey):
    return data, (data,)


def _make_loss_bwd(akey, res, g):
    (data,) = res
    attrs = dict(akey)
    grad = jnp.full_like(data, attrs.get("grad_scale", 1.0))
    if attrs.get("normalization") == "batch":
        grad = grad / data.shape[0]
    elif attrs.get("normalization") == "valid":
        valid = (jnp.abs(data) > attrs.get("valid_thresh", 0.0)).astype(data.dtype)
        grad = grad * valid / jnp.maximum(jnp.sum(valid), 1.0)
    return (grad,)


_make_loss_p.defvjp(_make_loss_fwd, _make_loss_bwd)


@register("MakeLoss", aliases=("make_loss",))
def make_loss(data, grad_scale=1.0, valid_thresh=0.0, normalization="null"):
    """Identity forward, constant backward = this tensor *is* a loss
    (reference: src/operator/make_loss.cc)."""
    return _make_loss_p(data, _attrs_key(grad_scale=grad_scale,
                                         valid_thresh=valid_thresh,
                                         normalization=normalization))


@functools.partial(jax.custom_vjp, nondiff_argnums=(2,))
def _svm_output_p(data, label, akey):
    return data


def _svm_output_fwd(data, label, akey):
    return data, (data, label)


def _svm_output_bwd(akey, res, g):
    attrs = dict(akey)
    data, label = res
    margin = attrs.get("margin", 1.0)
    coef = attrs.get("regularization_coefficient", 1.0)
    use_linear = attrs.get("use_linear", False)
    depth = data.shape[-1]
    oh = jax.nn.one_hot(label.astype(jnp.int32), depth, dtype=data.dtype)
    sgn = 2.0 * oh - 1.0  # +1 for true class, -1 otherwise
    viol = (margin - sgn * data) > 0
    if use_linear:
        grad = jnp.where(viol, -sgn * coef, 0.0)
    else:
        grad = jnp.where(viol, -2.0 * (margin - sgn * data) * sgn * coef, 0.0)
    return grad.astype(data.dtype), jnp.zeros_like(label)


_svm_output_p.defvjp(_svm_output_fwd, _svm_output_bwd)


@register("SVMOutput", num_inputs=2, aliases=("svm_output",))
def svm_output(data, label, margin=1.0, regularization_coefficient=1.0,
               use_linear=False):
    """(reference: src/operator/svm_output.cc)."""
    return _svm_output_p(data, label,
                         _attrs_key(margin=margin,
                                    regularization_coefficient=regularization_coefficient,
                                    use_linear=use_linear))


@functools.partial(jax.custom_vjp, nondiff_argnums=(2,))
def _kl_sparse_p(data, moving_avg, akey):
    return data


def _kl_sparse_fwd(data, moving_avg, akey):
    return data, (data, moving_avg)


def _kl_sparse_bwd(akey, res, g):
    attrs = dict(akey)
    data, moving_avg = res
    rho = attrs.get("sparseness_target", 0.1)
    penalty = attrs.get("penalty", 0.001)
    momentum = attrs.get("momentum", 0.9)
    flat = data.reshape(data.shape[0], -1)
    avg = jnp.mean(flat, axis=0)
    new_ma = momentum * moving_avg + (1 - momentum) * avg
    grad = g.reshape(flat.shape) + penalty * (
        -rho / new_ma + (1 - rho) / (1 - new_ma))
    return grad.reshape(data.shape).astype(data.dtype), jnp.zeros_like(moving_avg)


_kl_sparse_p.defvjp(_kl_sparse_fwd, _kl_sparse_bwd)


@register("IdentityAttachKLSparseReg", num_inputs=2)
def identity_attach_kl_sparse_reg(data, moving_avg, sparseness_target=0.1,
                                  penalty=0.001, momentum=0.9):
    """Identity forward; backward attaches the KL sparsity penalty gradient
    ``penalty * (-rho/rho_hat + (1-rho)/(1-rho_hat))`` using a momentum
    moving average rho_hat of the per-unit mean activation (reference:
    src/operator/identity_attach_KL_sparse_reg-inl.h Backward). Pair only
    with sigmoid activations. Input 1 is the ``moving_avg`` aux state; the
    updated average is returned as the trailing aux output."""
    flat = data.reshape(data.shape[0], -1)
    avg = jnp.mean(flat, axis=0)
    new_ma = momentum * moving_avg + (1 - momentum) * avg
    out = _kl_sparse_p(data, moving_avg,
                       _attrs_key(sparseness_target=sparseness_target,
                                  penalty=penalty, momentum=momentum))
    return out, new_ma


OP_REGISTRY["IdentityAttachKLSparseReg"].num_aux = 1

# legacy-generation alias (reference: src/operator/convolution_v1.cc — the
# pre-NNVM Convolution registration; identical math on the XLA path)
from .registry import alias as _alias  # noqa: E402
_alias("Convolution", "Convolution_v1")

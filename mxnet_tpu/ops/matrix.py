"""Matrix / shape-manipulation ops.

Reference: ``src/operator/tensor/matrix_op.cc`` (dot, batch_dot, transpose,
reshape with special codes, slice, expand_dims, repeat, tile, flip, ...) and
``src/operator/tensor/la_op.cc`` (linalg family). ``dot`` is the MXU workhorse:
we lower through ``lax.dot_general`` with a bfloat16-friendly
``preferred_element_type`` so XLA tiles it onto the systolic array
(SURVEY.md §6 / pallas_guide: keep matmuls large + batched).
"""
from __future__ import annotations

import numpy as np
import jax.numpy as jnp
from jax import lax

from .registry import register, alias
from .. import amp

# ------------------------------------------------------------------ dot


@register("dot", num_inputs=2)
def dot(lhs, rhs, transpose_a=False, transpose_b=False):
    """Matrix product (reference: src/operator/tensor/matrix_op.cc dot).

    2-D x 2-D -> matmul on the MXU. Higher-rank behavior follows the
    reference: contract last axis of lhs with first axis of rhs.
    Accumulation in float32 regardless of input dtype (TPU best practice).
    """
    a = lhs.T if transpose_a and lhs.ndim == 2 else (jnp.swapaxes(lhs, -1, -2) if transpose_a else lhs)
    b = rhs.T if transpose_b and rhs.ndim == 2 else (jnp.swapaxes(rhs, -1, -2) if transpose_b else rhs)
    a, b, acc = amp.mxu_operands(a, b)
    if a.ndim == 1 and b.ndim == 1:
        return jnp.dot(a, b, preferred_element_type=jnp.float32).astype(jnp.result_type(a, b))
    out = jnp.tensordot(a, b, axes=([a.ndim - 1], [0]), **acc)
    return out.astype(jnp.result_type(a, b))


@register("batch_dot", num_inputs=2)
def batch_dot(lhs, rhs, transpose_a=False, transpose_b=False):
    """Batched matmul over leading axis (reference: matrix_op.cc batch_dot;
    used heavily by attention-style models). Maps to one XLA BatchDot on
    the MXU — operands cast under the amp policy like FullyConnected."""
    dn = (((1,) if transpose_a else (2,), (2,) if transpose_b else (1,)),
          ((0,), (0,)))
    lhs, rhs, acc = amp.mxu_operands(lhs, rhs)
    out = lax.dot_general(lhs, rhs, dimension_numbers=dn, **acc)
    return out.astype(jnp.result_type(lhs, rhs))


# ------------------------------------------------------------------ shape


@register("transpose")
def transpose(data, axes=None):
    """Permute axes (reference: matrix_op.cc transpose)."""
    if axes is None or axes == ():
        axes = tuple(reversed(range(data.ndim)))
    return jnp.transpose(data, axes)


@register("expand_dims")
def expand_dims(data, axis=0):
    return jnp.expand_dims(data, axis)


@register("Reshape", aliases=("reshape",))
def reshape(data, shape=None, reverse=False, target_shape=None, keep_highest=False):
    """Reshape with MXNet's special codes (reference: matrix_op.cc Reshape,
    doc in matrix_op-inl.h):

      0  -> copy this dim from input
      -1 -> infer from remaining elements
      -2 -> copy all remaining input dims
      -3 -> merge two consecutive input dims
      -4 -> split one input dim into the next two listed dims (may contain -1)
    """
    if shape is None or len(tuple(shape)) == 0:
        # legacy target_shape attr (reference keeps it for back-compat)
        return jnp.reshape(data, tuple(target_shape))
    in_shape = list(data.shape)
    if reverse:
        in_shape = in_shape[::-1]
        shape = tuple(shape)[::-1]
    out = []
    src = 0
    spec = list(shape)
    i = 0
    while i < len(spec):
        s = spec[i]
        if s == 0:
            out.append(in_shape[src]); src += 1
        elif s == -1:
            out.append(-1); src += 1
        elif s == -2:
            out.extend(in_shape[src:]); src = len(in_shape)
        elif s == -3:
            out.append(in_shape[src] * in_shape[src + 1]); src += 2
        elif s == -4:
            d1, d2 = spec[i + 1], spec[i + 2]
            whole = in_shape[src]; src += 1
            if d1 == -1:
                d1 = whole // d2
            if d2 == -1:
                d2 = whole // d1
            out.extend([d1, d2]); i += 2
        else:
            out.append(int(s))
            if src < len(in_shape):
                src += 1
        i += 1
    if reverse:
        out = out[::-1]
    total = int(np.prod(data.shape)) if data.ndim else 1
    if -1 in out:
        known = 1
        for d in out:
            if d != -1:
                known *= d
        out[out.index(-1)] = total // max(known, 1)
    return jnp.reshape(data, tuple(out))


@register("Flatten", aliases=("flatten",))
def flatten(data):
    """Collapse all but the first axis (reference: matrix_op.cc Flatten)."""
    return jnp.reshape(data, (data.shape[0], -1))


@register("slice", aliases=("crop",))
def slice_op(data, begin=None, end=None, step=None):
    """Slice along each axis with None-aware begin/end (reference:
    matrix_op.cc slice; `crop` is its 0.11 alias)."""
    begin = tuple(begin) if begin is not None else (None,) * data.ndim
    end = tuple(end) if end is not None else (None,) * data.ndim
    step = tuple(step) if step else (None,) * len(begin)
    ix = tuple(
        np.s_[b:e:s] for b, e, s in
        zip(begin, end, step + (None,) * (len(begin) - len(step)))
    )
    return data[ix]


@register("slice_axis")
def slice_axis(data, axis=0, begin=0, end=None):
    """Slice one axis (reference: matrix_op.cc slice_axis)."""
    axis = axis % data.ndim
    ix = [np.s_[:]] * data.ndim
    ix[axis] = np.s_[begin:end]
    return data[tuple(ix)]


@register("repeat")
def repeat(data, repeats=1, axis=None):
    return jnp.repeat(data, repeats, axis=axis)


@register("tile")
def tile(data, reps=()):
    return jnp.tile(data, tuple(reps))


@register("reverse", aliases=("flip",))
def reverse(data, axis=0):
    axes = (axis,) if isinstance(axis, int) else tuple(axis)
    return jnp.flip(data, axis=axes)


@register("Crop", num_inputs=None)
def crop_like(*inputs, offset=(0, 0), h_w=(0, 0), center_crop=False,
              num_args=None):
    """Spatial crop (reference: src/operator/crop.cc): with one input,
    crop to ``h_w``; with two, crop data (input 0) to the spatial size of
    crop_like (input 1). NCHW layout, crops the trailing two axes."""
    data = inputs[0]
    H, W = data.shape[-2], data.shape[-1]
    if len(inputs) > 1:
        th, tw = inputs[1].shape[-2], inputs[1].shape[-1]
    else:
        th, tw = int(h_w[0]) or H, int(h_w[1]) or W
    if center_crop:
        y0, x0 = (H - th) // 2, (W - tw) // 2
    else:
        y0, x0 = int(offset[0]), int(offset[1])
    if y0 + th > H or x0 + tw > W:
        raise ValueError("Crop: window %dx%d at (%d, %d) exceeds input "
                         "%dx%d" % (th, tw, y0, x0, H, W))
    return data[..., y0:y0 + th, x0:x0 + tw]


@register("SwapAxis", aliases=("swapaxes",))
def swapaxes(data, dim1=0, dim2=0):
    """Swap two axes (reference: src/operator/swapaxis.cc)."""
    return jnp.swapaxes(data, dim1, dim2)


@register("Concat", num_inputs=None, aliases=("concat",))
def concat(*data, dim=1, num_args=None):
    """Concatenate along dim (reference: src/operator/concat.cc)."""
    return jnp.concatenate(data, axis=dim)


@register("stack", num_inputs=None)
def stack(*data, axis=0, num_args=None):
    return jnp.stack(data, axis=axis)


@register("SliceChannel", num_inputs=1, aliases=("split",))
def slice_channel(data, num_outputs=1, axis=1, squeeze_axis=False):
    """Split into equal chunks along axis; multi-output op (reference:
    src/operator/slice_channel.cc)."""
    parts = jnp.split(data, num_outputs, axis=axis)
    if squeeze_axis:
        parts = [jnp.squeeze(p, axis=axis) for p in parts]
    return tuple(parts)


# symbol-layer output arity (reference: SliceChannelParam num_outputs)
from .registry import get_op as _get_op  # noqa: E402
_get_op("SliceChannel").num_outputs = \
    lambda attrs: int(attrs.get("num_outputs", 1))


@register("where", num_inputs=3)
def where(condition, x, y):
    """Elementwise select (reference: src/operator/tensor/control_flow_op.cc).
    Data-dependent select without host control flow — jit-safe."""
    if condition.ndim == 1 and x.ndim > 1:
        condition = condition.reshape((-1,) + (1,) * (x.ndim - 1))
    return jnp.where(condition != 0, x, y)


@register("Pad", aliases=("pad",))
def pad(data, mode="constant", pad_width=(), constant_value=0.0):
    """Pad (reference: src/operator/pad.cc). pad_width is the flat 2*ndim
    tuple exactly as the reference expects."""
    pw = tuple(pad_width)
    pairs = tuple((pw[2 * i], pw[2 * i + 1]) for i in range(len(pw) // 2))
    if mode == "constant":
        return jnp.pad(data, pairs, mode="constant", constant_values=constant_value)
    if mode == "edge":
        return jnp.pad(data, pairs, mode="edge")
    if mode == "reflect":
        return jnp.pad(data, pairs, mode="reflect")
    raise ValueError("unknown pad mode %s" % mode)


@register("squeeze")
def squeeze(data, axis=None):
    return jnp.squeeze(data, axis=axis)


@register("zeros_like")
def zeros_like(data):
    return jnp.zeros_like(data)


@register("ones_like")
def ones_like(data):
    return jnp.ones_like(data)


@register("add_n", num_inputs=None, aliases=("ElementWiseSum", "element_wise_sum"))
def add_n(*args, num_args=None):
    """Sum of N arrays — the gradient-aggregation primitive (reference:
    src/operator/tensor/elemwise_sum.cc; engine-level ElementwiseSum at
    src/ndarray/ndarray.cc:407)."""
    out = args[0]
    for a in args[1:]:
        out = out + a
    return out


# ------------------------------------------------------------------ linalg
# reference: src/operator/tensor/la_op.cc (gemm, potrf, trsm, trmm, potri,
# sumlogdiag) — cuBLAS/LAPACK there, one XLA op each here.


@register("linalg_gemm", num_inputs=3)
def linalg_gemm(A, B, C, transpose_a=False, transpose_b=False, alpha=1.0, beta=1.0):
    a = jnp.swapaxes(A, -1, -2) if transpose_a else A
    b = jnp.swapaxes(B, -1, -2) if transpose_b else B
    return alpha * (a @ b) + beta * C


@register("linalg_gemm2", num_inputs=2)
def linalg_gemm2(A, B, transpose_a=False, transpose_b=False, alpha=1.0):
    a = jnp.swapaxes(A, -1, -2) if transpose_a else A
    b = jnp.swapaxes(B, -1, -2) if transpose_b else B
    return alpha * (a @ b)


@register("linalg_potrf")
def linalg_potrf(A):
    return jnp.linalg.cholesky(A)


@register("linalg_potri")
def linalg_potri(A):
    L = jnp.linalg.cholesky(A)
    eye = jnp.broadcast_to(jnp.eye(A.shape[-1], dtype=A.dtype), A.shape)
    Linv = lax.linalg.triangular_solve(L, eye, left_side=True, lower=True)
    return jnp.swapaxes(Linv, -1, -2) @ Linv


@register("linalg_trsm", num_inputs=2)
def linalg_trsm(A, B, transpose=False, rightside=False, alpha=1.0):
    a = jnp.swapaxes(A, -1, -2) if transpose else A
    lower = not transpose
    out = lax.linalg.triangular_solve(a, alpha * B, left_side=not rightside, lower=lower)
    return out


@register("linalg_trmm", num_inputs=2)
def linalg_trmm(A, B, transpose=False, rightside=False, alpha=1.0):
    a = jnp.swapaxes(A, -1, -2) if transpose else A
    return alpha * (B @ a if rightside else a @ B)


@register("linalg_sumlogdiag")
def linalg_sumlogdiag(A):
    return jnp.sum(jnp.log(jnp.diagonal(A, axis1=-2, axis2=-1)), axis=-1)


@register("khatri_rao", num_inputs=None)
def khatri_rao(*args, num_args=None):
    """Column-wise Khatri-Rao product (reference: src/operator/contrib/krprod.h)."""
    out = args[0]
    for b in args[1:]:
        out = jnp.einsum("ir,jr->ijr", out, b).reshape(-1, out.shape[1])
    return out

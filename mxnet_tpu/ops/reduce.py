"""Reduction ops.

Reference: ``src/operator/tensor/broadcast_reduce_op_value.cc`` and
``broadcast_reduce_op_index.cc`` (sum/mean/prod/max/min/argmax/argmin/norm,
with ``axis``/``keepdims``/``exclude`` attrs — SURVEY.md §2.5). The reference
implements these with cub/mshadow reduction kernels; XLA's reduce HLO replaces
all of them.
"""
from __future__ import annotations

import jax.numpy as jnp

from .registry import register, alias


def _norm_axis(axis, ndim, exclude=False):
    """Normalize MXNet axis attr: None/() = all axes; int or tuple; exclude
    inverts the set (reference: broadcast_reduce_op.h ReduceAxesShapeImpl)."""
    if axis is None or axis == ():
        ax = tuple(range(ndim))
        return None if not exclude else ()
    if isinstance(axis, int):
        ax = (axis,)
    else:
        ax = tuple(int(a) for a in axis)
    ax = tuple(a % ndim for a in ax)
    if exclude:
        ax = tuple(a for a in range(ndim) if a not in ax)
    return ax


def _make_reduce(name, jfn, aliases=()):
    @register(name, aliases=aliases)
    def _op(data, axis=None, keepdims=False, exclude=False, _jfn=jfn):
        ax = _norm_axis(axis, data.ndim, exclude)
        return _jfn(data, axis=ax, keepdims=bool(keepdims))
    _op.__doc__ = (
        "Reduce-%s over axes (reference: src/operator/tensor/"
        "broadcast_reduce_op_value.cc)." % name
    )
    return _op


_make_reduce("sum", jnp.sum, aliases=("sum_axis",))
_make_reduce("mean", jnp.mean)
_make_reduce("prod", jnp.prod)
_make_reduce("nansum", jnp.nansum)
_make_reduce("nanprod", jnp.nanprod)
_make_reduce("max", jnp.max, aliases=("max_axis",))
_make_reduce("min", jnp.min, aliases=("min_axis",))


@register("argmax")
def argmax(data, axis=None, keepdims=False):
    """Index of max along axis (reference: broadcast_reduce_op_index.cc).
    Matches the reference's float output dtype."""
    out = jnp.argmax(data, axis=axis, keepdims=bool(keepdims))
    return out.astype(jnp.float32)


@register("argmin")
def argmin(data, axis=None, keepdims=False):
    out = jnp.argmin(data, axis=axis, keepdims=bool(keepdims))
    return out.astype(jnp.float32)


@register("argmax_channel")
def argmax_channel(data):
    """argmax over the last axis of 2-D input (reference:
    broadcast_reduce_op_index.cc argmax_channel; used by metrics)."""
    return jnp.argmax(data, axis=-1).astype(jnp.float32)


@register("norm")
def norm(data, ord=2, axis=None, keepdims=False):
    """L2 norm reduction (reference: broadcast_reduce_op_value.cc norm —
    the 0.11 op reduces over all axes; axis is a TPU-build extension)."""
    ax = _norm_axis(axis, data.ndim)
    if ord == 1:
        return jnp.sum(jnp.abs(data), axis=ax, keepdims=bool(keepdims))
    return jnp.sqrt(jnp.sum(jnp.square(data), axis=ax, keepdims=bool(keepdims)))


@register("broadcast_axis", aliases=("broadcast_axes",))
def broadcast_axis(data, axis=None, size=None):
    """Broadcast along given axes of size-1 dims (reference: matrix_op.cc
    broadcast_axis)."""
    if axis is None:
        return data
    axes = (axis,) if isinstance(axis, int) else tuple(axis)
    sizes = (size,) if isinstance(size, int) else tuple(size)
    shape = list(data.shape)
    for a, s in zip(axes, sizes):
        shape[a % data.ndim] = s
    return jnp.broadcast_to(data, tuple(shape))


@register("broadcast_to")
def broadcast_to(data, shape=None):
    """Broadcast to target shape; zeros in shape keep the input dim
    (reference: matrix_op.cc broadcast_to)."""
    tgt = tuple(
        d if s == 0 else s for s, d in zip(shape, data.shape)
    ) if len(shape) == data.ndim else tuple(shape)
    return jnp.broadcast_to(data, tgt)

"""Elementwise unary/binary/scalar/logic ops.

Reference: ``src/operator/tensor/elemwise_unary_op.cc``,
``elemwise_binary_op_basic.cc``, ``elemwise_binary_broadcast_op_*.cc``,
``elemwise_binary_scalar_op_*.cc`` and the ``mshadow_op.h`` functor zoo
(SURVEY.md §2.5 tensor/ family). Each reference op is a hand-written cpu/gpu
kernel pair; here each is one jnp expression — XLA fuses chains of these into
single HBM-bandwidth-bound kernels, which is precisely the TPU-idiomatic
replacement for mshadow expression templates.

Note on broadcast_* vs elemwise_*: the reference distinguishes same-shape
``elemwise_add`` from numpy-broadcasting ``broadcast_add``. XLA handles both
with one HLO, so they alias to the same lowering here.
"""
from __future__ import annotations

import jax.numpy as jnp
from jax import lax

from .registry import register, alias

# ---------------------------------------------------------------- binary


@register("elemwise_add", num_inputs=2, aliases=("_plus", "_Plus", "broadcast_add", "broadcast_plus"))
def elemwise_add(lhs, rhs):
    """lhs + rhs (reference: src/operator/tensor/elemwise_binary_op_basic.cc:40)."""
    return jnp.add(lhs, rhs)


@register("elemwise_sub", num_inputs=2, aliases=("_minus", "_Minus", "broadcast_sub", "broadcast_minus"))
def elemwise_sub(lhs, rhs):
    return jnp.subtract(lhs, rhs)


@register("elemwise_mul", num_inputs=2, aliases=("_mul", "_Mul", "broadcast_mul"))
def elemwise_mul(lhs, rhs):
    return jnp.multiply(lhs, rhs)


@register("elemwise_div", num_inputs=2, aliases=("_div", "_Div", "broadcast_div"))
def elemwise_div(lhs, rhs):
    return jnp.divide(lhs, rhs)


@register("broadcast_power", num_inputs=2, aliases=("_power", "_Power", "pow"))
def broadcast_power(lhs, rhs):
    return jnp.power(lhs, rhs)


@register("broadcast_maximum", num_inputs=2, aliases=("_maximum", "maximum"))
def broadcast_maximum(lhs, rhs):
    return jnp.maximum(lhs, rhs)


@register("broadcast_minimum", num_inputs=2, aliases=("_minimum", "minimum"))
def broadcast_minimum(lhs, rhs):
    return jnp.minimum(lhs, rhs)


@register("broadcast_hypot", num_inputs=2, aliases=("_hypot",))
def broadcast_hypot(lhs, rhs):
    return jnp.hypot(lhs, rhs)


@register("broadcast_mod", num_inputs=2, aliases=("_mod",))
def broadcast_mod(lhs, rhs):
    return jnp.mod(lhs, rhs)


# ---------------------------------------------------------------- logic

def _logic(fn):
    def wrapped(lhs, rhs):
        return fn(lhs, rhs).astype(jnp.result_type(lhs))
    return wrapped


register("broadcast_equal", num_inputs=2, aliases=("_equal",))(_logic(jnp.equal))
register("broadcast_not_equal", num_inputs=2, aliases=("_not_equal",))(_logic(jnp.not_equal))
register("broadcast_greater", num_inputs=2, aliases=("_greater",))(_logic(jnp.greater))
register("broadcast_greater_equal", num_inputs=2, aliases=("_greater_equal",))(_logic(jnp.greater_equal))
register("broadcast_lesser", num_inputs=2, aliases=("_lesser",))(_logic(jnp.less))
register("broadcast_lesser_equal", num_inputs=2, aliases=("_lesser_equal",))(_logic(jnp.less_equal))


# ---------------------------------------------------------------- scalar

@register("_plus_scalar", aliases=("_PlusScalar",))
def _plus_scalar(data, scalar=0.0):
    return data + scalar


@register("_minus_scalar", aliases=("_MinusScalar",))
def _minus_scalar(data, scalar=0.0):
    return data - scalar


@register("_rminus_scalar", aliases=("_RMinusScalar",))
def _rminus_scalar(data, scalar=0.0):
    return scalar - data


@register("_mul_scalar", aliases=("_MulScalar",))
def _mul_scalar(data, scalar=1.0):
    return data * scalar


@register("_div_scalar", aliases=("_DivScalar",))
def _div_scalar(data, scalar=1.0):
    return data / scalar


@register("_rdiv_scalar", aliases=("_RDivScalar",))
def _rdiv_scalar(data, scalar=1.0):
    return scalar / data


@register("_power_scalar", aliases=("_PowerScalar",))
def _power_scalar(data, scalar=1.0):
    return jnp.power(data, scalar)


@register("_rpower_scalar", aliases=("_RPowerScalar",))
def _rpower_scalar(data, scalar=1.0):
    return jnp.power(scalar, data)


@register("_maximum_scalar", aliases=("_MaximumScalar",))
def _maximum_scalar(data, scalar=0.0):
    return jnp.maximum(data, scalar)


@register("_minimum_scalar", aliases=("_MinimumScalar",))
def _minimum_scalar(data, scalar=0.0):
    return jnp.minimum(data, scalar)


@register("_mod_scalar")
def _mod_scalar(data, scalar=1.0):
    return jnp.mod(data, scalar)


@register("_equal_scalar")
def _equal_scalar(data, scalar=0.0):
    return (data == scalar).astype(jnp.result_type(data))


@register("_not_equal_scalar")
def _not_equal_scalar(data, scalar=0.0):
    return (data != scalar).astype(jnp.result_type(data))


@register("_greater_scalar")
def _greater_scalar(data, scalar=0.0):
    return (data > scalar).astype(jnp.result_type(data))


@register("_greater_equal_scalar")
def _greater_equal_scalar(data, scalar=0.0):
    return (data >= scalar).astype(jnp.result_type(data))


@register("_lesser_scalar")
def _lesser_scalar(data, scalar=0.0):
    return (data < scalar).astype(jnp.result_type(data))


@register("_lesser_equal_scalar")
def _lesser_equal_scalar(data, scalar=0.0):
    return (data <= scalar).astype(jnp.result_type(data))


# ---------------------------------------------------------------- unary
# reference: src/operator/tensor/elemwise_unary_op.cc + mshadow_op.h functors

_UNARY = {
    "negative": jnp.negative,
    "reciprocal": jnp.reciprocal,
    "abs": jnp.abs,
    "sign": jnp.sign,
    "round": jnp.round,
    "rint": jnp.rint,
    "ceil": jnp.ceil,
    "floor": jnp.floor,
    "trunc": jnp.trunc,
    "fix": jnp.trunc,  # fix == trunc (round toward zero)
    "square": jnp.square,
    "sqrt": jnp.sqrt,
    "rsqrt": lax.rsqrt,
    "cbrt": jnp.cbrt,
    "rcbrt": lambda x: 1.0 / jnp.cbrt(x),
    "exp": jnp.exp,
    "log": jnp.log,
    "log10": jnp.log10,
    "log2": jnp.log2,
    "log1p": jnp.log1p,
    "expm1": jnp.expm1,
    "sin": jnp.sin,
    "cos": jnp.cos,
    "tan": jnp.tan,
    "arcsin": jnp.arcsin,
    "arccos": jnp.arccos,
    "arctan": jnp.arctan,
    "sinh": jnp.sinh,
    "cosh": jnp.cosh,
    "tanh": jnp.tanh,
    "arcsinh": jnp.arcsinh,
    "arccosh": jnp.arccosh,
    "arctanh": jnp.arctanh,
    "degrees": jnp.degrees,
    "radians": jnp.radians,
    "gamma": lambda x: jnp.exp(lax.lgamma(x)),
    "gammaln": lambda x: lax.lgamma(x),
    "erf": lax.erf,
    "erfinv": lax.erf_inv,
    "sigmoid": lambda x: jax_nn_sigmoid(x),
    "relu": lambda x: jnp.maximum(x, 0),
    "softsign": lambda x: x / (1 + jnp.abs(x)),
}


def jax_nn_sigmoid(x):
    return lax.logistic(x)


def _make_unary(name, fn):
    @register(name)
    def _op(data, _fn=fn):
        return _fn(data)
    _op.__doc__ = "Elementwise %s (reference: src/operator/tensor/elemwise_unary_op.cc)." % name
    return _op


for _name, _fn in _UNARY.items():
    _make_unary(_name, _fn)

alias("gamma", "tgamma")


@register("BlockGrad", aliases=("stop_gradient", "block_grad"))
def block_grad(data):
    """Identity forward, zero gradient (reference:
    src/operator/tensor/elemwise_unary_op.cc BlockGrad). TPU lowering:
    lax.stop_gradient."""
    return lax.stop_gradient(data)


@register("identity", aliases=("_copy",))
def identity(data):
    return jnp.asarray(data)


@register("Cast", aliases=("cast",))
def cast(data, dtype="float32"):
    """Cast to dtype (reference: elemwise_unary_op.cc Cast)."""
    return data.astype(jnp.dtype(dtype))


@register("clip")
def clip(data, a_min=0.0, a_max=1.0):
    """Clip values to [a_min, a_max] (reference: src/operator/tensor/matrix_op.cc clip)."""
    return jnp.clip(data, a_min, a_max)


@register("smooth_l1")
def smooth_l1(data, scalar=1.0):
    """Smooth L1 (reference: mshadow_op.h smooth_l1_loss; used by RCNN)."""
    s2 = scalar * scalar
    return jnp.where(
        jnp.abs(data) < 1.0 / s2,
        0.5 * s2 * jnp.square(data),
        jnp.abs(data) - 0.5 / s2,
    )

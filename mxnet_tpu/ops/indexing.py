"""Indexing / embedding / ordering ops.

Reference: ``src/operator/tensor/indexing_op.cc`` (Embedding, take, one_hot,
pick, batch_take) and ``ordering_op.cc`` (sort, argsort, topk). On TPU, gather
is the lowering for all of take/Embedding/pick; sort/topk map to XLA's
variadic sort — static output shapes keep everything jit-compatible.
"""
from __future__ import annotations

import jax.numpy as jnp
from jax import lax

from .registry import register


@register("Embedding", num_inputs=2, aliases=("embedding",))
def embedding(data, weight, input_dim=None, output_dim=None, dtype="float32"):
    """Lookup rows of ``weight`` by integer ids (reference: indexing_op.cc
    Embedding). One XLA gather; gradient is a scatter-add, which is exactly
    kAddTo semantics from the reference (op_attr_types.h:45-58) for free."""
    idx = data.astype(jnp.int32)
    return jnp.take(weight, idx, axis=0)


@register("take", num_inputs=2)
def take(a, indices, axis=0, mode="clip"):
    """Take elements along axis (reference: indexing_op.cc take)."""
    idx = indices.astype(jnp.int32)
    return jnp.take(a, idx, axis=axis, mode=mode if mode != "raise" else "clip")


@register("batch_take", num_inputs=2)
def batch_take(a, indices):
    """a[i, indices[i]] (reference: indexing_op.cc batch_take)."""
    return jnp.take_along_axis(
        a, indices.astype(jnp.int32)[:, None], axis=1
    ).squeeze(1)


@register("one_hot")
def one_hot(indices, depth=None, on_value=1.0, off_value=0.0, dtype="float32"):
    """(reference: indexing_op.cc one_hot)."""
    idx = indices.astype(jnp.int32)
    oh = jnp.equal(idx[..., None], jnp.arange(depth, dtype=jnp.int32))
    return jnp.where(oh, on_value, off_value).astype(jnp.dtype(dtype))


@register("pick", num_inputs=2)
def pick(data, index, axis=-1, keepdims=False):
    """Pick one element per row along axis by index (reference:
    broadcast_reduce_op_index.cc pick; the backbone of cross-entropy)."""
    idx = index.astype(jnp.int32)
    axis = axis % data.ndim
    idx_exp = jnp.expand_dims(idx, axis) if idx.ndim < data.ndim else idx
    out = jnp.take_along_axis(data, idx_exp, axis=axis)
    if not keepdims:
        out = jnp.squeeze(out, axis=axis)
    return out


@register("gather_nd", num_inputs=2)
def gather_nd(data, indices):
    """N-d gather (TPU-build extension; appears in later reference versions)."""
    idx = tuple(indices.astype(jnp.int32))
    return data[idx]


# ------------------------------------------------------------- ordering


@register("sort")
def sort(data, axis=-1, is_ascend=True):
    """(reference: ordering_op.cc sort)."""
    out = jnp.sort(data, axis=axis)
    return out if is_ascend else jnp.flip(out, axis=axis)


@register("argsort")
def argsort(data, axis=-1, is_ascend=True):
    out = jnp.argsort(data, axis=axis)
    if not is_ascend:
        out = jnp.flip(out, axis=axis)
    return out.astype(jnp.float32)


@register("topk")
def topk(data, axis=-1, k=1, ret_typ="indices", is_ascend=False):
    """Top-k along axis (reference: ordering_op.cc topk). Static k keeps the
    output shape jit-compatible. ret_typ: value|indices|mask|both."""
    axis = axis % data.ndim
    neg = data if not is_ascend else -data
    moved = jnp.moveaxis(neg, axis, -1)
    vals, idxs = lax.top_k(moved, k)
    if is_ascend:
        vals = -vals
    vals = jnp.moveaxis(vals, -1, axis)
    idxs = jnp.moveaxis(idxs, -1, axis).astype(jnp.float32)
    if ret_typ == "value":
        return vals
    if ret_typ == "both":
        return vals, idxs
    if ret_typ == "mask":
        moved_idx = jnp.moveaxis(idxs.astype(jnp.int32), axis, -1)
        mask = jnp.zeros(jnp.moveaxis(data, axis, -1).shape, dtype=data.dtype)
        mask = jnp.put_along_axis(mask, moved_idx, jnp.ones((), data.dtype),
                                  axis=-1, inplace=False)
        return jnp.moveaxis(mask, -1, axis)
    return idxs

"""Sequence ops (TNC layout, optional per-batch lengths).

Reference: ``src/operator/sequence_last.cc``, ``sequence_mask.cc``,
``sequence_reverse.cc`` (SURVEY.md §5.7). Layout matches the reference:
axis 0 = time, axis 1 = batch. All lowerings are gather/select HLOs with
static shapes — no dynamic control flow, so they compose with scan/jit.
"""
from __future__ import annotations

import jax.numpy as jnp

from .registry import register


@register("SequenceLast", num_inputs=None, aliases=("sequence_last",))
def sequence_last(data, sequence_length=None, use_sequence_length=False):
    """Last valid timestep per batch element (reference: sequence_last.cc)."""
    if not use_sequence_length or sequence_length is None:
        return data[-1]
    idx = (sequence_length.astype(jnp.int32) - 1).clip(0, data.shape[0] - 1)
    return jnp.take_along_axis(
        data, idx.reshape((1, -1) + (1,) * (data.ndim - 2)), axis=0
    )[0]


@register("SequenceMask", num_inputs=None, aliases=("sequence_mask",))
def sequence_mask(data, sequence_length=None, use_sequence_length=False,
                  value=0.0):
    """Zero (or `value`) out steps beyond each sequence's length (reference:
    sequence_mask.cc)."""
    if not use_sequence_length or sequence_length is None:
        return data
    t = jnp.arange(data.shape[0]).reshape((-1, 1) + (1,) * (data.ndim - 2))
    keep = t < sequence_length.astype(jnp.int32).reshape(
        (1, -1) + (1,) * (data.ndim - 2))
    return jnp.where(keep, data, jnp.array(value, data.dtype))


@register("SequenceReverse", num_inputs=None, aliases=("sequence_reverse",))
def sequence_reverse(data, sequence_length=None, use_sequence_length=False):
    """Reverse along time, respecting per-sequence lengths (reference:
    sequence_reverse.cc)."""
    if not use_sequence_length or sequence_length is None:
        return jnp.flip(data, axis=0)
    T = data.shape[0]
    t = jnp.arange(T).reshape((-1, 1))
    L = sequence_length.astype(jnp.int32).reshape((1, -1))
    src = jnp.where(t < L, L - 1 - t, t)  # within length: mirrored; after: keep
    src = src.reshape((T, -1) + (1,) * (data.ndim - 2))
    return jnp.take_along_axis(data, src, axis=0)

"""Detection operators: MultiBoxPrior/Target/Detection (SSD), Proposal
(Faster R-CNN RPN), CTCLoss.

Reference: ``src/operator/contrib/multibox_prior.cc`` (anchor enumeration),
``multibox_target.cc`` (matching + encoding), ``multibox_detection.cc``
(decode + NMS), ``proposal.cc``/``multi_proposal.cc`` (RPN),
``contrib/ctc_loss.cc`` (warp-ctc).

TPU design: everything is fixed-shape. Matching loops become IoU-matrix
argmax/scatter; NMS is a sorted O(A²) suppression mask driven by
``lax.fori_loop``; invalid slots are padded with -1 exactly like the
reference's outputs. CTC's dynamic-programming recursion is a ``lax.scan``
over time in log space, and its gradient is jax autodiff of that scan
(the reference hand-codes warp-ctc's backward).
"""
from __future__ import annotations

import numpy as np
import jax
import jax.numpy as jnp
from jax import lax

from .registry import register, get_op


def _ftup(v, n=None):
    if isinstance(v, (int, float)):
        t = (float(v),)
    else:
        t = tuple(float(x) for x in v)
    if n is not None and len(t) == 1:
        t = t * n
    return t


def box_iou(a, b):
    """Pairwise IoU of corner-format boxes: a (A, 4) x b (B, 4) -> (A, B)."""
    area_a = jnp.maximum(a[:, 2] - a[:, 0], 0) * \
        jnp.maximum(a[:, 3] - a[:, 1], 0)
    area_b = jnp.maximum(b[:, 2] - b[:, 0], 0) * \
        jnp.maximum(b[:, 3] - b[:, 1], 0)
    lt = jnp.maximum(a[:, None, :2], b[None, :, :2])
    rb = jnp.minimum(a[:, None, 2:], b[None, :, 2:])
    wh = jnp.maximum(rb - lt, 0)
    inter = wh[..., 0] * wh[..., 1]
    union = area_a[:, None] + area_b[None, :] - inter
    return jnp.where(union > 0, inter / union, 0.0)


def _nms_keep(boxes, scores, classes, thresh, force_suppress, topk):
    """Greedy NMS over score-sorted boxes; returns (order, keep-in-order).

    Scores <= -inf mark invalid slots. ``topk`` bounds how many sorted
    boxes may act as suppressors (reference nms_topk)."""
    A = boxes.shape[0]
    order = jnp.argsort(-scores)
    bs = boxes[order]
    cs = classes[order]
    valid = scores[order] > -jnp.inf
    iou = box_iou(bs, bs)
    same = jnp.ones((A, A), bool) if force_suppress \
        else (cs[:, None] == cs[None, :])
    sup = (iou > thresh) & same
    limit = A if (topk is None or topk < 0) else min(int(topk), A)
    idx = jnp.arange(A)

    def body(i, keep):
        row = sup[i] & (idx > i) & keep[i] & valid[i]
        return keep & ~row

    keep = lax.fori_loop(0, limit, body, valid)
    if topk is not None and topk >= 0:
        # reference nms_topk also drops boxes ranked beyond top-k entirely
        keep = keep & (idx < limit)
    return order, keep


# ----------------------------------------------------------------- priors


@register("MultiBoxPrior", num_inputs=1,
          aliases=("_contrib_MultiBoxPrior", "multibox_prior"))
def multibox_prior(data, sizes=(1.0,), ratios=(1.0,), clip=False,
                   steps=(-1.0, -1.0), offsets=(0.5, 0.5)):
    """SSD anchor generation (reference:
    src/operator/contrib/multibox_prior.cc MultiBoxPriorForward): per cell,
    one box per size at ratio[0], then one per extra ratio at sizes[0];
    output (1, H*W*A, 4) normalized corners."""
    H, W = data.shape[2], data.shape[3]
    sizes = _ftup(sizes)
    ratios = _ftup(ratios)
    steps = _ftup(steps, 2)
    offsets = _ftup(offsets, 2)
    step_y = steps[0] if steps[0] > 0 else 1.0 / H
    step_x = steps[1] if steps[1] > 0 else 1.0 / W

    # (w, h) half-extents in the reference's enumeration order
    wh = [(s / 2.0, s / 2.0) for s in sizes]
    wh += [(sizes[0] * np.sqrt(r) / 2.0, sizes[0] / np.sqrt(r) / 2.0)
           for r in ratios[1:]]
    wh = jnp.asarray(wh, data.dtype)                        # (A, 2)

    cy = (jnp.arange(H, dtype=data.dtype) + offsets[0]) * step_y
    cx = (jnp.arange(W, dtype=data.dtype) + offsets[1]) * step_x
    cyx = jnp.stack(jnp.meshgrid(cy, cx, indexing="ij"),
                    axis=-1).reshape(H * W, 1, 2)           # (HW, 1, [y,x])
    half = wh[None, :, ::-1]                                # (1, A, [h,w])
    mins = cyx - half                                       # y-x order
    maxs = cyx + half
    boxes = jnp.concatenate(
        [mins[..., 1:2], mins[..., 0:1], maxs[..., 1:2], maxs[..., 0:1]],
        axis=-1).reshape(1, -1, 4)                          # x1 y1 x2 y2
    if clip:
        boxes = jnp.clip(boxes, 0.0, 1.0)
    return boxes


# ----------------------------------------------------------------- target


def _encode_box(anchor, gt, variances):
    aw = anchor[:, 2] - anchor[:, 0]
    ah = anchor[:, 3] - anchor[:, 1]
    ax = (anchor[:, 0] + anchor[:, 2]) * 0.5
    ay = (anchor[:, 1] + anchor[:, 3]) * 0.5
    gw = gt[:, 2] - gt[:, 0]
    gh = gt[:, 3] - gt[:, 1]
    gx = (gt[:, 0] + gt[:, 2]) * 0.5
    gy = (gt[:, 1] + gt[:, 3]) * 0.5
    vx, vy, vw, vh = variances
    return jnp.stack([(gx - ax) / aw / vx, (gy - ay) / ah / vy,
                      jnp.log(jnp.maximum(gw / aw, 1e-12)) / vw,
                      jnp.log(jnp.maximum(gh / ah, 1e-12)) / vh], axis=1)


@register("MultiBoxTarget", num_inputs=3,
          aliases=("_contrib_MultiBoxTarget", "multibox_target"))
def multibox_target(anchor, label, cls_pred, overlap_threshold=0.5,
                    ignore_label=-1.0, negative_mining_ratio=-1.0,
                    negative_mining_thresh=0.5, minimum_negative_samples=0,
                    variances=(0.1, 0.1, 0.2, 0.2)):
    """SSD training targets (reference:
    src/operator/contrib/multibox_target.cc MultiBoxTargetForward).

    anchor (1, A, 4); label (N, O, 5) rows [cls, x1, y1, x2, y2], cls = -1
    padding; cls_pred (N, C, A) (consulted only for negative mining).
    Returns (box_target (N, A*4), box_mask (N, A*4), cls_target (N, A)).
    Matching: each gt claims its best anchor (bipartite step), then anchors
    with best-gt IoU >= threshold match that gt.
    """
    variances = _ftup(variances, 4)
    anchors = anchor.reshape(-1, 4)
    A = anchors.shape[0]

    def one(lbl, cpred):
        O = lbl.shape[0]
        valid = lbl[:, 0] >= 0
        iou = box_iou(anchors, lbl[:, 1:5])                 # (A, O)
        iou = jnp.where(valid[None, :], iou, 0.0)
        best_gt = jnp.argmax(iou, axis=1)
        best_iou = jnp.max(iou, axis=1)
        # bipartite step (reference: each gt gets a distinct forced anchor):
        # greedily claim the globally-best remaining (anchor, gt) pair and
        # retire both, O rounds — two gts can't collide on one anchor
        def bip(_, st):
            f_matched, f_gt, m = st
            flat = jnp.argmax(m)
            a, o = flat // O, flat % O
            good = m.ravel()[flat] > 1e-12
            f_matched = jnp.where(good, f_matched.at[a].set(True), f_matched)
            f_gt = jnp.where(good, f_gt.at[a].set(o), f_gt)
            m = jnp.where(good,
                          m.at[a, :].set(-1.0).at[:, o].set(-1.0), m)
            return f_matched, f_gt, m

        f_matched, f_gt, _ = lax.fori_loop(
            0, O, bip, (jnp.zeros(A, bool), jnp.zeros(A, jnp.int32), iou))
        matched = f_matched | (best_iou >= overlap_threshold)
        best_gt = jnp.where(f_matched, f_gt, best_gt)

        gt = lbl[best_gt]                                   # (A, 5)
        box_t = _encode_box(anchors, gt[:, 1:5], variances)
        box_t = jnp.where(matched[:, None], box_t, 0.0)
        box_m = jnp.where(matched[:, None],
                          jnp.ones((A, 4), box_t.dtype), 0.0)
        cls_t = jnp.where(matched, gt[:, 0] + 1.0, 0.0)
        if negative_mining_ratio > 0:
            # hard negatives: unmatched anchors ranked by background
            # confidence loss (low bg prob = hard), capped at
            # ratio * num_pos (reference NegativeMining)
            num_pos = matched.sum()
            max_neg = jnp.maximum(
                (negative_mining_ratio * num_pos).astype(jnp.int32),
                int(minimum_negative_samples))
            neg_ok = (~matched) & (best_iou < negative_mining_thresh)
            hardness = jnp.where(neg_ok, -cpred[0], -jnp.inf)
            rank = jnp.argsort(jnp.argsort(-hardness))
            selected = neg_ok & (rank < max_neg)
            cls_t = jnp.where(matched, cls_t,
                              jnp.where(selected, 0.0, ignore_label))
        return box_t.reshape(-1), box_m.reshape(-1), cls_t

    bt, bm, ct = jax.vmap(one)(label, cls_pred)
    return bt, bm, ct


get_op("MultiBoxTarget").num_outputs = 3
get_op("MultiBoxTarget")._input_names = ["anchor", "label", "cls_pred"]


# --------------------------------------------------------------- detection


def _decode_boxes(anchors, loc, variances, clip):
    aw = anchors[:, 2] - anchors[:, 0]
    ah = anchors[:, 3] - anchors[:, 1]
    ax = (anchors[:, 0] + anchors[:, 2]) * 0.5
    ay = (anchors[:, 1] + anchors[:, 3]) * 0.5
    vx, vy, vw, vh = variances
    ox = loc[:, 0] * vx * aw + ax
    oy = loc[:, 1] * vy * ah + ay
    ow = jnp.exp(loc[:, 2] * vw) * aw * 0.5
    oh = jnp.exp(loc[:, 3] * vh) * ah * 0.5
    out = jnp.stack([ox - ow, oy - oh, ox + ow, oy + oh], axis=1)
    return jnp.clip(out, 0.0, 1.0) if clip else out


@register("MultiBoxDetection", num_inputs=3,
          aliases=("_contrib_MultiBoxDetection", "multibox_detection"))
def multibox_detection(cls_prob, loc_pred, anchor, clip=True,
                       threshold=0.01, background_id=0, nms_threshold=0.5,
                       force_suppress=False,
                       variances=(0.1, 0.1, 0.2, 0.2), nms_topk=-1):
    """SSD inference: decode + per-class NMS (reference:
    src/operator/contrib/multibox_detection.cc). cls_prob (N, C, A) with
    background class; returns (N, A, 6) rows [cls_id, score, x1, y1, x2,
    y2], invalid rows marked cls_id = -1."""
    variances = _ftup(variances, 4)
    anchors = anchor.reshape(-1, 4)
    A = anchors.shape[0]

    def one(probs, loc):
        # best non-background class per anchor; output ids compact away the
        # background slot (reference: `id = j - 1` in multibox_detection.cc
        # for background_id=0; ids below the background keep their index)
        p = probs.at[background_id].set(-jnp.inf)
        j = jnp.argmax(p, axis=0)
        cls = jnp.where(j > background_id, j - 1, j).astype(loc.dtype)
        score = jnp.max(p, axis=0)
        keep0 = score > threshold
        boxes = _decode_boxes(anchors, loc.reshape(A, 4), variances, clip)
        scores = jnp.where(keep0, score, -jnp.inf)
        order, keep = _nms_keep(boxes, scores, cls, nms_threshold,
                                force_suppress, nms_topk)
        out = jnp.concatenate(
            [jnp.where(keep, cls[order], -1.0)[:, None],
             jnp.where(keep, scores[order], -1.0)[:, None],
             boxes[order]], axis=1)
        return out

    return jax.vmap(one)(cls_prob, loc_pred)


get_op("MultiBoxDetection")._input_names = ["cls_prob", "loc_pred", "anchor"]


# ---------------------------------------------------------------- proposal


def _rpn_base_anchors(base_size, scales, ratios):
    """py-faster-rcnn style anchor enumeration (reference: proposal.cc
    GenerateAnchors): keep area under ratio change, then scale."""
    w = h = float(base_size)
    x = y = (base_size - 1) / 2.0
    out = []
    size = w * h
    for r in ratios:
        ws = np.round(np.sqrt(size / r))
        hs = np.round(ws * r)
        for s in scales:
            w2, h2 = ws * s, hs * s
            out.append([x - (w2 - 1) / 2, y - (h2 - 1) / 2,
                        x + (w2 - 1) / 2, y + (h2 - 1) / 2])
    return np.asarray(out, np.float32)


@register("Proposal", num_inputs=3,
          aliases=("_contrib_Proposal", "proposal",
                   "_contrib_MultiProposal", "MultiProposal"))
def proposal(cls_prob, bbox_pred, im_info, rpn_pre_nms_top_n=6000,
             rpn_post_nms_top_n=300, threshold=0.7, rpn_min_size=16,
             scales=(4, 8, 16, 32), ratios=(0.5, 1, 2), feature_stride=16,
             output_score=False, iou_loss=False):
    """RPN proposal generation (reference:
    src/operator/contrib/proposal.cc / multi_proposal.cc).

    cls_prob (N, 2*A, H, W); bbox_pred (N, 4*A, H, W); im_info (N, 3)
    [height, width, scale]. Returns rois (N*post, 5) [batch_idx, x1, y1,
    x2, y2]; suppressed slots repeat the best box like the reference's
    padding."""
    scales = _ftup(scales)
    ratios = _ftup(ratios)
    N, twoA, H, W = cls_prob.shape
    A = twoA // 2
    base = jnp.asarray(_rpn_base_anchors(feature_stride, scales, ratios))
    sy = jnp.arange(H, dtype=jnp.float32) * feature_stride
    sx = jnp.arange(W, dtype=jnp.float32) * feature_stride
    shift = jnp.stack([sx[None, :].repeat(H, 0).ravel(),
                       sy[:, None].repeat(W, 1).ravel()] * 2, axis=1)
    anchors = (base[None, :, :] + shift[:, None, :]).reshape(-1, 4)
    K = anchors.shape[0]          # H*W*A

    def one(probs, deltas, info):
        fg = probs[A:].reshape(A, H * W).T.reshape(-1)       # (K,)
        d = deltas.reshape(A, 4, H * W).transpose(2, 0, 1).reshape(-1, 4)
        aw = anchors[:, 2] - anchors[:, 0] + 1.0
        ah = anchors[:, 3] - anchors[:, 1] + 1.0
        ax = anchors[:, 0] + aw * 0.5
        ay = anchors[:, 1] + ah * 0.5
        cx = d[:, 0] * aw + ax
        cy = d[:, 1] * ah + ay
        w = jnp.exp(d[:, 2]) * aw
        h = jnp.exp(d[:, 3]) * ah
        boxes = jnp.stack([cx - 0.5 * (w - 1), cy - 0.5 * (h - 1),
                           cx + 0.5 * (w - 1), cy + 0.5 * (h - 1)], axis=1)
        boxes = jnp.stack([jnp.clip(boxes[:, 0], 0, info[1] - 1),
                           jnp.clip(boxes[:, 1], 0, info[0] - 1),
                           jnp.clip(boxes[:, 2], 0, info[1] - 1),
                           jnp.clip(boxes[:, 3], 0, info[0] - 1)], axis=1)
        min_sz = rpn_min_size * info[2]
        ok = ((boxes[:, 2] - boxes[:, 0] + 1) >= min_sz) & \
             ((boxes[:, 3] - boxes[:, 1] + 1) >= min_sz)
        scores = jnp.where(ok, fg, -jnp.inf)
        pre = min(int(rpn_pre_nms_top_n), K)
        top_scores, top_idx = lax.top_k(scores, pre)
        top_boxes = boxes[top_idx]
        order, keep = _nms_keep(top_boxes, top_scores,
                                jnp.zeros(pre), threshold, True, -1)
        post = int(rpn_post_nms_top_n)
        # unkept (and kept beyond post) entries scatter to index `post`,
        # which mode="drop" discards — no slot collisions
        kept_rank = jnp.where(keep, jnp.cumsum(keep) - 1, post)
        out_boxes = jnp.zeros((post, 4), boxes.dtype)
        out_boxes = out_boxes.at[kept_rank].set(top_boxes[order],
                                                mode="drop")
        out_scores = jnp.zeros((post,), scores.dtype)
        out_scores = out_scores.at[kept_rank].set(top_scores[order],
                                                  mode="drop")
        n_kept = keep.sum()
        # pad empty slots with the best proposal (reference pads with
        # the first box)
        pad_mask = jnp.arange(post) >= n_kept
        out_boxes = jnp.where(pad_mask[:, None], out_boxes[0], out_boxes)
        out_scores = jnp.where(pad_mask, out_scores[0], out_scores)
        return out_boxes, out_scores

    boxes, scores = jax.vmap(one)(cls_prob, bbox_pred, im_info)
    post = int(rpn_post_nms_top_n)
    bidx = jnp.repeat(jnp.arange(N, dtype=boxes.dtype), post)[:, None]
    rois = jnp.concatenate([bidx, boxes.reshape(-1, 4)], axis=1)
    if output_score:
        return rois, scores.reshape(-1, 1)
    return rois


get_op("Proposal").num_outputs = \
    lambda attrs: 2 if attrs.get("output_score") else 1
get_op("Proposal")._input_names = ["cls_prob", "bbox_pred", "im_info"]


# ---------------------------------------------------------------- CTC loss


@register("CTCLoss", num_inputs=2,
          aliases=("_contrib_CTCLoss", "ctc_loss"))
def ctc_loss(data, label):
    """Connectionist Temporal Classification loss (reference:
    src/operator/contrib/ctc_loss.cc over warp-ctc).

    data: (T, N, C) raw activations (softmax applied internally, like
    warp-ctc); label: (N, L) with 0 = padding (labels use 1..C-1, blank is
    class 0). Returns per-sequence negative log likelihood (N,). The
    forward α-recursion is a ``lax.scan`` over time in log space; gradients
    come from autodiff of that scan.
    """
    T, N, C = data.shape
    L = label.shape[1]
    S = 2 * L + 1
    logp = jax.nn.log_softmax(data, axis=-1)                 # (T, N, C)
    lbl = label.astype(jnp.int32)                            # (N, L)
    lengths = (lbl != 0).sum(axis=1)                         # (N,)

    # extended sequence: blank, l1, blank, l2, ..., blank
    ext = jnp.zeros((N, S), jnp.int32)
    ext = ext.at[:, 1::2].set(lbl)
    # allowed skip: s -> s-2 when ext[s] != blank and != ext[s-2]
    skip_ok = jnp.zeros((N, S), bool)
    skip_ok = skip_ok.at[:, 2:].set(
        (ext[:, 2:] != 0) & (ext[:, 2:] != ext[:, :-2]))
    s_valid = jnp.arange(S)[None, :] < (2 * lengths[:, None] + 1)

    neg_inf = jnp.array(-1e30, logp.dtype)
    alpha0 = jnp.full((N, S), neg_inf)
    alpha0 = alpha0.at[:, 0].set(logp[0, :, 0])
    alpha0 = alpha0.at[:, 1].set(
        jnp.where(lengths > 0,
                  jnp.take_along_axis(logp[0], ext[:, 1:2], axis=1)[:, 0],
                  neg_inf))

    def step(alpha, lp):
        # lp: (N, C) log-probs at time t
        stay = alpha
        prev1 = jnp.concatenate([jnp.full((N, 1), neg_inf),
                                 alpha[:, :-1]], axis=1)
        prev2 = jnp.concatenate([jnp.full((N, 2), neg_inf),
                                 alpha[:, :-2]], axis=1)
        prev2 = jnp.where(skip_ok, prev2, neg_inf)
        tot = jnp.logaddexp(jnp.logaddexp(stay, prev1), prev2)
        emit = jnp.take_along_axis(lp, ext, axis=1)          # (N, S)
        new = tot + emit
        return jnp.where(s_valid, new, neg_inf), None

    alpha_T, _ = lax.scan(step, alpha0, logp[1:])
    last = 2 * lengths                                       # final blank
    a_last = jnp.take_along_axis(alpha_T, last[:, None], axis=1)[:, 0]
    a_prev = jnp.where(
        lengths > 0,
        jnp.take_along_axis(alpha_T,
                            jnp.maximum(last - 1, 0)[:, None],
                            axis=1)[:, 0],
        neg_inf)
    ll = jnp.logaddexp(a_last, a_prev)
    return -ll


get_op("CTCLoss")._input_names = ["data", "label"]

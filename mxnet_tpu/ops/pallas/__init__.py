"""Hand-written Pallas TPU kernels (the §2.22 RTC tier — see
mxnet_tpu/rtc.py for the user-facing API)."""
from .flash_attention import flash_attention  # noqa: F401

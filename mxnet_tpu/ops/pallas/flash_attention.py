"""Blocked online-softmax (flash) attention as a Pallas TPU kernel.

The showcase custom kernel (SURVEY.md §2.22 calls Pallas ports "the only
real kernel engineering in the project"): attention with O(S) memory —
the S×S score matrix never leaves VMEM, materialized one
(BLOCK_Q, BLOCK_K) tile at a time while running max/sum statistics fold
each tile into the output accumulator (Dao et al., FlashAttention;
Rabe & Staats, self-attention does not need O(n²) memory).

Kernel layout: grid (batch*heads, S/BLOCK_Q, S/BLOCK_K); the innermost
grid axis walks KV tiles, carrying (m, l, acc) in VMEM scratch that lives
across grid steps; the normalized output tile is written on the last KV
step. QKᵀ and PV both hit the MXU with fp32 accumulation.

Backward is the standard XLA recompute path behind ``jax.custom_vjp`` —
the memory win matters in the forward (inference / activation footprint);
a fused backward kernel is a further optimization, not a semantics change.

Off-TPU the same kernel runs in interpreter mode (exact, slow) so the
CPU test rig can check numerics; ``flash_attention`` falls back to plain
XLA attention when ``interpret=False`` is forced on a non-TPU backend.
"""
from __future__ import annotations

import functools

import numpy as np
import jax
import jax.numpy as jnp
from jax import lax

__all__ = ["flash_attention"]

_NEG_INF = -1e30


def _fa_kernel(q_ref, k_ref, v_ref, o_ref, m_scr, l_scr, acc_scr, *,
               scale, causal, block_q, block_k, skip_masked):
    import jax.experimental.pallas as pl

    kv_step = pl.program_id(2)
    n_kv = pl.num_programs(2)

    @pl.when(kv_step == 0)
    def _init():
        m_scr[:] = jnp.full_like(m_scr, _NEG_INF)
        l_scr[:] = jnp.zeros_like(l_scr)
        acc_scr[:] = jnp.zeros_like(acc_scr)

    # causal: a KV tile strictly above the diagonal band contributes
    # nothing — skip its matmuls entirely (~2x for long sequences).
    # Compiled mode only: the HLO interpreter can't lower a traced
    # pl.when predicate.
    live = (kv_step * block_k <= (pl.program_id(1) + 1) * block_q - 1) \
        if (causal and skip_masked) else True

    @pl.when(live)
    def _update():
        q = q_ref[0]                               # (block_q, d)
        k = k_ref[0]                               # (block_k, d)
        v = v_ref[0]
        s = jax.lax.dot_general(
            q, k, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32) * scale  # (block_q, block_k)

        if causal:
            q_pos = pl.program_id(1) * block_q + \
                jax.lax.broadcasted_iota(jnp.int32, s.shape, 0)
            k_pos = kv_step * block_k + \
                jax.lax.broadcasted_iota(jnp.int32, s.shape, 1)
            s = jnp.where(q_pos >= k_pos, s, _NEG_INF)

        m_prev = m_scr[:, 0]                       # (block_q,)
        l_prev = l_scr[:, 0]
        m_new = jnp.maximum(m_prev, jnp.max(s, axis=-1))
        alpha = jnp.exp(m_prev - m_new)
        p = jnp.exp(s - m_new[:, None])
        l_new = l_prev * alpha + jnp.sum(p, axis=-1)
        acc_scr[:] = acc_scr[:] * alpha[:, None] + jax.lax.dot_general(
            p.astype(v.dtype), v, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)
        m_scr[:, 0] = m_new
        l_scr[:, 0] = l_new

    @pl.when(kv_step == n_kv - 1)
    def _finish():
        denom = jnp.maximum(l_scr[:, 0], 1e-37)
        o_ref[0] = (acc_scr[:] / denom[:, None]).astype(o_ref.dtype)


def _fa_forward(q, k, v, scale, causal, block_q, block_k, interpret):
    import jax.experimental.pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    BH, S, D = q.shape
    Sk = k.shape[1]
    nq = S // block_q
    nk = Sk // block_k
    kernel = functools.partial(_fa_kernel, scale=scale, causal=causal,
                               block_q=block_q, block_k=block_k,
                               skip_masked=not interpret)
    return pl.pallas_call(
        kernel,
        out_shape=jax.ShapeDtypeStruct((BH, S, D), q.dtype),
        grid=(BH, nq, nk),
        in_specs=[
            pl.BlockSpec((1, block_q, D), lambda b, i, j: (b, i, 0)),
            pl.BlockSpec((1, block_k, D), lambda b, i, j: (b, j, 0)),
            pl.BlockSpec((1, block_k, D), lambda b, i, j: (b, j, 0)),
        ],
        out_specs=pl.BlockSpec((1, block_q, D), lambda b, i, j: (b, i, 0)),
        scratch_shapes=[
            pltpu.VMEM((block_q, 1), jnp.float32),
            pltpu.VMEM((block_q, 1), jnp.float32),
            pltpu.VMEM((block_q, D), jnp.float32),
        ],
        interpret=interpret,
    )(q, k, v)


def _xla_attention(q, k, v, scale, causal):
    s = jnp.einsum("bqd,bkd->bqk", q, k,
                   preferred_element_type=jnp.float32) * scale
    if causal:
        # top-aligned mask (k <= q in absolute positions) — must agree with
        # the kernel's q_pos >= k_pos even when q carries block padding,
        # since this path is also the recompute backward of the kernel
        S, Sk = s.shape[-2], s.shape[-1]
        mask = jnp.tril(jnp.ones((S, Sk), bool))
        s = jnp.where(mask, s, _NEG_INF)
    p = jax.nn.softmax(s, axis=-1)
    return jnp.einsum("bqk,bkd->bqd", p.astype(v.dtype), v)


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5, 6, 7))
def _fa(q, k, v, scale, causal, block_q, block_k, interpret):
    return _fa_forward(q, k, v, scale, causal, block_q, block_k, interpret)


def _fa_fwd(q, k, v, scale, causal, block_q, block_k, interpret):
    out = _fa_forward(q, k, v, scale, causal, block_q, block_k, interpret)
    return out, (q, k, v)


def _fa_bwd(scale, causal, block_q, block_k, interpret, res, g):
    q, k, v = res
    _, vjp = jax.vjp(lambda q_, k_, v_: _xla_attention(q_, k_, v_, scale,
                                                       causal), q, k, v)
    return vjp(g.astype(jnp.float32).astype(q.dtype))


_fa.defvjp(_fa_fwd, _fa_bwd)


def flash_attention(q, k, v, causal=False, scale=None, block_q=512,
                    block_k=512, interpret=None):
    """Flash attention over (B, H, S, D) inputs.

    The query length is padded to ``block_q`` (padded rows are computed
    then sliced off — they influence nothing). The key length must divide
    ``block_k`` — padded keys would need in-kernel masking to stay out of
    the softmax, so an unaligned key length raises instead of silently
    attending to padding. ``causal`` assumes S == Sk (self-attention).
    Gradients flow via an XLA recompute backward.
    """
    B, H, S, D = q.shape
    Sk = k.shape[2]
    if scale is None:
        scale = 1.0 / np.sqrt(D)
    from ...rtc import resolve_interpret
    if interpret is None:
        interpret = resolve_interpret((q, k, v))
    elif not interpret and resolve_interpret((q, k, v)):
        # compiled Mosaic requested but the data is off-TPU: fall back to
        # plain XLA attention instead of failing to lower
        out = _xla_attention(q.reshape(B * H, S, D),
                             k.reshape(B * H, Sk, D),
                             v.reshape(B * H, Sk, D), float(scale),
                             bool(causal))
        return out.reshape(B, H, S, D)

    bq = min(block_q, S)
    bk = min(block_k, Sk)
    if Sk % bk:
        raise ValueError(
            "flash_attention: key length %d must be a multiple of block_k "
            "%d (padded keys would join the softmax)" % (Sk, bk))
    pad_q = (-S) % bq
    qf = q.reshape(B * H, S, D)
    kf = k.reshape(B * H, Sk, D)
    vf = v.reshape(B * H, Sk, D)
    if pad_q:
        qf = jnp.pad(qf, ((0, 0), (0, pad_q), (0, 0)))
    out = _fa(qf, kf, vf, float(scale), bool(causal), bq, bk,
              bool(interpret))
    if pad_q:
        out = out[:, :S]
    return out.reshape(B, H, S, D)


# registered as an ordinary framework op so Symbol/Gluon graphs can use it
from ..registry import register as _register  # noqa: E402


@_register("FlashAttention", num_inputs=3,
           aliases=("_contrib_FlashAttention",))
def _flash_attention_op(q, k, v, causal=False, scale=None, block_q=512,
                        block_k=512, interpret=None):
    """Pallas flash attention over (B, H, S, D) q/k/v (see module
    docstring; the mx.rtc escape-hatch showcase kernel). Pass
    ``interpret=True`` when building a CPU-bound symbol graph (tracers
    carry no device, so auto-detection falls back to the default
    backend)."""
    return flash_attention(q, k, v, causal=causal, scale=scale,
                           block_q=block_q, block_k=block_k,
                           interpret=interpret)

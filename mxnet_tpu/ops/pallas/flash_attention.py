"""Blocked online-softmax (flash) attention as a Pallas TPU kernel.

The showcase custom kernel (SURVEY.md §2.22 calls Pallas ports "the only
real kernel engineering in the project"): attention with O(S) memory —
the S×S score matrix never leaves VMEM, materialized one
(BLOCK_Q, BLOCK_K) tile at a time while running max/sum statistics fold
each tile into the output accumulator (Dao et al., FlashAttention;
Rabe & Staats, self-attention does not need O(n²) memory).

Kernel layout: grid (batch*heads, S/BLOCK_Q, S/BLOCK_K); the innermost
grid axis walks KV tiles, carrying (m, l, acc) in VMEM scratch that lives
across grid steps; the normalized output tile is written on the last KV
step. QKᵀ and PV both hit the MXU with fp32 accumulation.

Backward is fused too (FlashAttention-2 style): the forward additionally
writes the per-row logsumexp, and two Pallas kernels — one accumulating
dQ over KV tiles, one accumulating dK/dV over Q tiles — rebuild each
P tile as ``exp(s - lse)`` so the S×S probability matrix never hits HBM
in either direction. ``exp(s - lse)`` needs no running rescale: lse is
the final statistic, making the backward tiles embarrassingly
order-independent (unlike the forward's online softmax).

Off-TPU the same kernel runs in interpreter mode (exact, slow) so the
CPU test rig can check numerics; ``flash_attention`` falls back to plain
XLA attention when ``interpret=False`` is forced on a non-TPU backend.
"""
from __future__ import annotations

import functools

import numpy as np
import jax
import jax.numpy as jnp
from jax import lax

__all__ = ["flash_attention"]

_NEG_INF = -1e30
# lse/delta ride as (BH, S, _LANES) with the row value replicated across
# lanes: Mosaic wants >=2D tiles whose last block dim divides 128 OR equals
# the array dim — 8 lanes satisfies the latter at 1/16th the HBM of 128
_LANES = 8


def _fa_kernel(q_ref, k_ref, v_ref, o_ref, lse_ref, m_scr, l_scr, acc_scr,
               *, scale, causal, block_q, block_k, skip_masked):
    import jax.experimental.pallas as pl

    kv_step = pl.program_id(2)
    n_kv = pl.num_programs(2)

    @pl.when(kv_step == 0)
    def _init():
        m_scr[:] = jnp.full_like(m_scr, _NEG_INF)
        l_scr[:] = jnp.zeros_like(l_scr)
        acc_scr[:] = jnp.zeros_like(acc_scr)

    # causal: a KV tile strictly above the diagonal band contributes
    # nothing — skip its matmuls entirely (~2x for long sequences).
    # Compiled mode only: the HLO interpreter can't lower a traced
    # pl.when predicate.
    live = (kv_step * block_k <= (pl.program_id(1) + 1) * block_q - 1) \
        if (causal and skip_masked) else True

    @pl.when(live)
    def _update():
        q = q_ref[0]                               # (block_q, d)
        k = k_ref[0]                               # (block_k, d)
        v = v_ref[0]
        s = jax.lax.dot_general(
            q, k, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32) * scale  # (block_q, block_k)

        if causal:
            q_pos = pl.program_id(1) * block_q + \
                jax.lax.broadcasted_iota(jnp.int32, s.shape, 0)
            k_pos = kv_step * block_k + \
                jax.lax.broadcasted_iota(jnp.int32, s.shape, 1)
            s = jnp.where(q_pos >= k_pos, s, _NEG_INF)

        m_prev = m_scr[:, 0]                       # (block_q,)
        l_prev = l_scr[:, 0]
        m_new = jnp.maximum(m_prev, jnp.max(s, axis=-1))
        alpha = jnp.exp(m_prev - m_new)
        p = jnp.exp(s - m_new[:, None])
        l_new = l_prev * alpha + jnp.sum(p, axis=-1)
        acc_scr[:] = acc_scr[:] * alpha[:, None] + jax.lax.dot_general(
            p.astype(v.dtype), v, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)
        m_scr[:, 0] = m_new
        l_scr[:, 0] = l_new

    @pl.when(kv_step == n_kv - 1)
    def _finish():
        denom = jnp.maximum(l_scr[:, 0], 1e-37)
        o_ref[0] = (acc_scr[:] / denom[:, None]).astype(o_ref.dtype)
        # lane-replicated across the _LANES trailing dim (see _LANES note)
        lse_ref[0] = jnp.broadcast_to(
            (m_scr[:, 0] + jnp.log(denom))[:, None], lse_ref[0].shape)


def _fa_forward(q, k, v, scale, causal, block_q, block_k, interpret):
    import jax.experimental.pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    BH, S, D = q.shape
    Sk = k.shape[1]
    nq = S // block_q
    nk = Sk // block_k
    kernel = functools.partial(_fa_kernel, scale=scale, causal=causal,
                               block_q=block_q, block_k=block_k,
                               skip_masked=not interpret)
    return pl.pallas_call(
        kernel,
        out_shape=(jax.ShapeDtypeStruct((BH, S, D), q.dtype),
                   jax.ShapeDtypeStruct((BH, S, _LANES), jnp.float32)),
        grid=(BH, nq, nk),
        in_specs=[
            pl.BlockSpec((1, block_q, D), lambda b, i, j: (b, i, 0)),
            pl.BlockSpec((1, block_k, D), lambda b, i, j: (b, j, 0)),
            pl.BlockSpec((1, block_k, D), lambda b, i, j: (b, j, 0)),
        ],
        out_specs=(
            pl.BlockSpec((1, block_q, D), lambda b, i, j: (b, i, 0)),
            pl.BlockSpec((1, block_q, _LANES), lambda b, i, j: (b, i, 0)),
        ),
        scratch_shapes=[
            pltpu.VMEM((block_q, 1), jnp.float32),
            pltpu.VMEM((block_q, 1), jnp.float32),
            pltpu.VMEM((block_q, D), jnp.float32),
        ],
        interpret=interpret,
    )(q, k, v)


def _fa_bwd_dq_kernel(q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref,
                      dq_ref, dq_scr, *, scale, causal, block_q, block_k,
                      skip_masked):
    """dQ accumulator: grid (BH, nq, nk), KV tiles innermost.

    Rebuilds P = exp(s - lse) from the saved logsumexp (exact — lse is the
    final softmax statistic, so no online rescaling is needed), then
    dS = P * (dO·Vᵀ - Δ) and dQ += dS·K, all tiles resident in VMEM.
    """
    import jax.experimental.pallas as pl

    j = pl.program_id(2)
    n_kv = pl.num_programs(2)

    @pl.when(j == 0)
    def _init():
        dq_scr[:] = jnp.zeros_like(dq_scr)

    live = (j * block_k <= (pl.program_id(1) + 1) * block_q - 1) \
        if (causal and skip_masked) else True

    @pl.when(live)
    def _update():
        q = q_ref[0]
        k = k_ref[0]
        v = v_ref[0]
        do = do_ref[0]
        s = jax.lax.dot_general(
            q, k, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32) * scale
        if causal:
            q_pos = pl.program_id(1) * block_q + \
                jax.lax.broadcasted_iota(jnp.int32, s.shape, 0)
            k_pos = j * block_k + \
                jax.lax.broadcasted_iota(jnp.int32, s.shape, 1)
            s = jnp.where(q_pos >= k_pos, s, _NEG_INF)
        p = jnp.exp(s - lse_ref[0][:, 0:1])            # (bq, bk)
        dp = jax.lax.dot_general(
            do, v, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32)        # (bq, bk)
        ds = p * (dp - delta_ref[0][:, 0:1]) * scale
        dq_scr[:] += jax.lax.dot_general(
            ds.astype(k.dtype), k, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)

    @pl.when(j == n_kv - 1)
    def _finish():
        dq_ref[0] = dq_scr[:].astype(dq_ref.dtype)


def _fa_bwd_dkv_kernel(q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref,
                       dk_ref, dv_ref, dk_scr, dv_scr, *, scale, causal,
                       block_q, block_k, skip_masked):
    """dK/dV accumulator: grid (BH, nk, nq), Q tiles innermost.

    dV += Pᵀ·dO and dK += dSᵀ·Q per Q tile; writing per-KV-tile outputs
    from a KV-major grid means no cross-tile races and no atomics.
    """
    import jax.experimental.pallas as pl

    i = pl.program_id(2)
    n_q = pl.num_programs(2)

    @pl.when(i == 0)
    def _init():
        dk_scr[:] = jnp.zeros_like(dk_scr)
        dv_scr[:] = jnp.zeros_like(dv_scr)

    # causal: a Q tile entirely above (before) this KV tile sees none of it
    live = ((i + 1) * block_q - 1 >= pl.program_id(1) * block_k) \
        if (causal and skip_masked) else True

    @pl.when(live)
    def _update():
        q = q_ref[0]
        k = k_ref[0]
        v = v_ref[0]
        do = do_ref[0]
        s = jax.lax.dot_general(
            q, k, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32) * scale  # (bq, bk)
        if causal:
            q_pos = i * block_q + \
                jax.lax.broadcasted_iota(jnp.int32, s.shape, 0)
            k_pos = pl.program_id(1) * block_k + \
                jax.lax.broadcasted_iota(jnp.int32, s.shape, 1)
            s = jnp.where(q_pos >= k_pos, s, _NEG_INF)
        p = jnp.exp(s - lse_ref[0][:, 0:1])              # (bq, bk)
        dv_scr[:] += jax.lax.dot_general(
            p.astype(do.dtype), do, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)          # (bk, d)
        dp = jax.lax.dot_general(
            do, v, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32)          # (bq, bk)
        ds = p * (dp - delta_ref[0][:, 0:1]) * scale
        dk_scr[:] += jax.lax.dot_general(
            ds.astype(q.dtype), q, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)          # (bk, d)

    @pl.when(i == n_q - 1)
    def _finish():
        dk_ref[0] = dk_scr[:].astype(dk_ref.dtype)
        dv_ref[0] = dv_scr[:].astype(dv_ref.dtype)


def _fa_backward(q, k, v, out, lse, do, scale, causal, block_q, block_k,
                 interpret):
    import jax.experimental.pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    BH, S, D = q.shape
    Sk = k.shape[1]
    nq = S // block_q
    nk = Sk // block_k
    # Δ_i = rowsum(dO ⊙ O): tiny elementwise+reduce, XLA fuses it
    delta = jnp.sum(do.astype(jnp.float32) * out.astype(jnp.float32),
                    axis=-1)                              # (BH, S)
    delta = jnp.broadcast_to(delta[:, :, None], (BH, S, _LANES))
    common = dict(scale=scale, causal=causal, block_q=block_q,
                  block_k=block_k, skip_masked=not interpret)

    dq = pl.pallas_call(
        functools.partial(_fa_bwd_dq_kernel, **common),
        out_shape=jax.ShapeDtypeStruct((BH, S, D), q.dtype),
        grid=(BH, nq, nk),
        in_specs=[
            pl.BlockSpec((1, block_q, D), lambda b, i, j: (b, i, 0)),
            pl.BlockSpec((1, block_k, D), lambda b, i, j: (b, j, 0)),
            pl.BlockSpec((1, block_k, D), lambda b, i, j: (b, j, 0)),
            pl.BlockSpec((1, block_q, D), lambda b, i, j: (b, i, 0)),
            pl.BlockSpec((1, block_q, _LANES), lambda b, i, j: (b, i, 0)),
            pl.BlockSpec((1, block_q, _LANES), lambda b, i, j: (b, i, 0)),
        ],
        out_specs=pl.BlockSpec((1, block_q, D), lambda b, i, j: (b, i, 0)),
        scratch_shapes=[pltpu.VMEM((block_q, D), jnp.float32)],
        interpret=interpret,
    )(q, k, v, do, lse, delta)

    dk, dv = pl.pallas_call(
        functools.partial(_fa_bwd_dkv_kernel, **common),
        out_shape=(jax.ShapeDtypeStruct((BH, Sk, D), k.dtype),
                   jax.ShapeDtypeStruct((BH, Sk, D), v.dtype)),
        grid=(BH, nk, nq),
        in_specs=[
            pl.BlockSpec((1, block_q, D), lambda b, j, i: (b, i, 0)),
            pl.BlockSpec((1, block_k, D), lambda b, j, i: (b, j, 0)),
            pl.BlockSpec((1, block_k, D), lambda b, j, i: (b, j, 0)),
            pl.BlockSpec((1, block_q, D), lambda b, j, i: (b, i, 0)),
            pl.BlockSpec((1, block_q, _LANES), lambda b, j, i: (b, i, 0)),
            pl.BlockSpec((1, block_q, _LANES), lambda b, j, i: (b, i, 0)),
        ],
        out_specs=(
            pl.BlockSpec((1, block_k, D), lambda b, j, i: (b, j, 0)),
            pl.BlockSpec((1, block_k, D), lambda b, j, i: (b, j, 0)),
        ),
        scratch_shapes=[pltpu.VMEM((block_k, D), jnp.float32),
                        pltpu.VMEM((block_k, D), jnp.float32)],
        interpret=interpret,
    )(q, k, v, do, lse, delta)
    return dq, dk, dv


def _xla_attention(q, k, v, scale, causal):
    s = jnp.einsum("bqd,bkd->bqk", q, k,
                   preferred_element_type=jnp.float32) * scale
    if causal:
        # top-aligned mask (k <= q in absolute positions) — must agree with
        # the kernel's q_pos >= k_pos even when q carries block padding,
        # since this path is also the recompute backward of the kernel
        S, Sk = s.shape[-2], s.shape[-1]
        mask = jnp.tril(jnp.ones((S, Sk), bool))
        s = jnp.where(mask, s, _NEG_INF)
    p = jax.nn.softmax(s, axis=-1)
    return jnp.einsum("bqk,bkd->bqd", p.astype(v.dtype), v)


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5, 6, 7))
def _fa(q, k, v, scale, causal, block_q, block_k, interpret):
    out, _ = _fa_forward(q, k, v, scale, causal, block_q, block_k,
                         interpret)
    return out


def _fa_fwd(q, k, v, scale, causal, block_q, block_k, interpret):
    out, lse = _fa_forward(q, k, v, scale, causal, block_q, block_k,
                           interpret)
    return out, (q, k, v, out, lse)


def _fa_bwd(scale, causal, block_q, block_k, interpret, res, g):
    q, k, v, out, lse = res
    return _fa_backward(q, k, v, out, lse, g.astype(q.dtype), scale,
                        causal, block_q, block_k, interpret)


_fa.defvjp(_fa_fwd, _fa_bwd)


def flash_attention(q, k, v, causal=False, scale=None, block_q=512,
                    block_k=512, interpret=None):
    """Flash attention over (B, H, S, D) inputs.

    The query length is padded to ``block_q`` (padded rows are computed
    then sliced off — they influence nothing). The key length must divide
    ``block_k`` — padded keys would need in-kernel masking to stay out of
    the softmax, so an unaligned key length raises instead of silently
    attending to padding. ``causal`` assumes S == Sk (self-attention).
    Gradients flow through fused Pallas dQ and dK/dV kernels (the forward
    saves the per-row logsumexp); the S×S matrix never reaches HBM in
    either direction.
    """
    B, H, S, D = q.shape
    Sk = k.shape[2]
    if scale is None:
        scale = 1.0 / np.sqrt(D)
    from ...rtc import resolve_interpret
    if interpret is None:
        interpret = resolve_interpret((q, k, v))
    elif not interpret and resolve_interpret((q, k, v)):
        # compiled Mosaic requested but the data is off-TPU: fall back to
        # plain XLA attention instead of failing to lower
        out = _xla_attention(q.reshape(B * H, S, D),
                             k.reshape(B * H, Sk, D),
                             v.reshape(B * H, Sk, D), float(scale),
                             bool(causal))
        return out.reshape(B, H, S, D)

    bq = min(block_q, S)
    bk = min(block_k, Sk)
    if Sk % bk:
        raise ValueError(
            "flash_attention: key length %d must be a multiple of block_k "
            "%d (padded keys would join the softmax)" % (Sk, bk))
    pad_q = (-S) % bq
    qf = q.reshape(B * H, S, D)
    kf = k.reshape(B * H, Sk, D)
    vf = v.reshape(B * H, Sk, D)
    if pad_q:
        qf = jnp.pad(qf, ((0, 0), (0, pad_q), (0, 0)))
    out = _fa(qf, kf, vf, float(scale), bool(causal), bq, bk,
              bool(interpret))
    if pad_q:
        out = out[:, :S]
    return out.reshape(B, H, S, D)


# registered as an ordinary framework op so Symbol/Gluon graphs can use it
from ..registry import register as _register  # noqa: E402


@_register("FlashAttention", num_inputs=3,
           aliases=("_contrib_FlashAttention",))
def _flash_attention_op(q, k, v, causal=False, scale=None, block_q=512,
                        block_k=512, interpret=None):
    """Pallas flash attention over (B, H, S, D) q/k/v (see module
    docstring; the mx.rtc escape-hatch showcase kernel). Pass
    ``interpret=True`` when building a CPU-bound symbol graph (tracers
    carry no device, so auto-detection falls back to the default
    backend)."""
    return flash_attention(q, k, v, causal=causal, scale=scale,
                           block_q=block_q, block_k=block_k,
                           interpret=interpret)

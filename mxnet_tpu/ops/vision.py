"""Spatial / sampling operators: ROIPooling, PSROIPooling, BilinearSampler,
GridGenerator, SpatialTransformer, Correlation, DeformableConvolution.

Reference: ``src/operator/roi_pooling.cc``, ``bilinear_sampler.cc``,
``grid_generator.cc``, ``spatial_transformer.cc``, ``correlation.cc``,
``src/operator/contrib/{psroi_pooling,deformable_convolution}.cc``.

TPU design: every op is a fixed-shape tensor program — region loops become
masked reductions, sampling becomes vectorized 4-corner gathers, and the
displacement/kernel enumerations are static Python loops over small
constants that XLA unrolls and fuses. Gradients fall out of jax autodiff
(the reference hand-writes each backward kernel).
"""
from __future__ import annotations

import numpy as np
import jax
import jax.numpy as jnp
from jax import lax

from .registry import register, get_op
from .nn import _tup
from .. import amp


# ----------------------------------------------------------------- ROI pool


@register("ROIPooling", num_inputs=2, aliases=("roi_pooling",))
def roi_pooling(data, rois, pooled_size=None, spatial_scale=1.0):
    """Max-pool each ROI onto a fixed (ph, pw) grid (reference:
    src/operator/roi_pooling.cc ROIPoolForward).

    data: (N, C, H, W); rois: (R, 5) rows [batch_idx, x1, y1, x2, y2] in
    image coords. Region loops -> per-bin boolean masks + masked max, vmapped
    over ROIs; empty bins yield 0 like the reference.
    """
    ph, pw = _tup(pooled_size, 2)
    N, C, H, W = data.shape
    iy = jnp.arange(H)
    ix = jnp.arange(W)

    def one(roi):
        b = roi[0].astype(jnp.int32)
        x1 = jnp.round(roi[1] * spatial_scale).astype(jnp.int32)
        y1 = jnp.round(roi[2] * spatial_scale).astype(jnp.int32)
        x2 = jnp.round(roi[3] * spatial_scale).astype(jnp.int32)
        y2 = jnp.round(roi[4] * spatial_scale).astype(jnp.int32)
        rh = jnp.maximum(y2 - y1 + 1, 1)
        rw = jnp.maximum(x2 - x1 + 1, 1)
        bin_h = rh.astype(jnp.float32) / ph
        bin_w = rw.astype(jnp.float32) / pw
        hs = jnp.clip(jnp.floor(jnp.arange(ph) * bin_h).astype(jnp.int32)
                      + y1, 0, H)
        he = jnp.clip(jnp.ceil((jnp.arange(ph) + 1) * bin_h).astype(jnp.int32)
                      + y1, 0, H)
        ws = jnp.clip(jnp.floor(jnp.arange(pw) * bin_w).astype(jnp.int32)
                      + x1, 0, W)
        we = jnp.clip(jnp.ceil((jnp.arange(pw) + 1) * bin_w).astype(jnp.int32)
                      + x1, 0, W)
        mh = (iy[None, :] >= hs[:, None]) & (iy[None, :] < he[:, None])
        mw = (ix[None, :] >= ws[:, None]) & (ix[None, :] < we[:, None])
        m = mh[:, None, :, None] & mw[None, :, None, :]       # (ph,pw,H,W)
        img = jnp.take(data, b, axis=0)                       # (C,H,W)
        masked = jnp.where(m[None], img[:, None, None],
                           jnp.array(-jnp.inf, data.dtype))
        out = masked.max(axis=(-1, -2))                       # (C,ph,pw)
        empty = ~jnp.any(m, axis=(-1, -2))
        return jnp.where(empty[None], jnp.zeros((), data.dtype), out)

    return jax.vmap(one)(rois)


@register("PSROIPooling", num_inputs=2,
          aliases=("_contrib_PSROIPooling", "psroi_pooling"))
def psroi_pooling(data, rois, spatial_scale=1.0, output_dim=None,
                  pooled_size=None, group_size=0):
    """Position-sensitive ROI average pooling (reference:
    src/operator/contrib/psroi_pooling.cc, R-FCN). Output channel c at bin
    (i, j) averages input channel c*g*g + i*g + j inside the bin."""
    g = int(group_size) or int(pooled_size)
    p = int(pooled_size)
    od = int(output_dim)
    N, C, H, W = data.shape
    iy = jnp.arange(H)
    ix = jnp.arange(W)

    def one(roi):
        b = roi[0].astype(jnp.int32)
        # R-FCN rounds the roi to pixel centers at feature scale
        x1 = jnp.round(roi[1] * spatial_scale)
        y1 = jnp.round(roi[2] * spatial_scale)
        x2 = jnp.round(roi[3] * spatial_scale)
        y2 = jnp.round(roi[4] * spatial_scale)
        rw = jnp.maximum(x2 - x1, 0.1)
        rh = jnp.maximum(y2 - y1, 0.1)
        hs = jnp.clip(jnp.floor(jnp.arange(p) * rh / p + y1)
                      .astype(jnp.int32), 0, H)
        he = jnp.clip(jnp.ceil((jnp.arange(p) + 1) * rh / p + y1)
                      .astype(jnp.int32), 0, H)
        ws = jnp.clip(jnp.floor(jnp.arange(p) * rw / p + x1)
                      .astype(jnp.int32), 0, W)
        we = jnp.clip(jnp.ceil((jnp.arange(p) + 1) * rw / p + x1)
                      .astype(jnp.int32), 0, W)
        mh = (iy[None, :] >= hs[:, None]) & (iy[None, :] < he[:, None])
        mw = (ix[None, :] >= ws[:, None]) & (ix[None, :] < we[:, None])
        m = (mh[:, None, :, None] & mw[None, :, None, :]).astype(data.dtype)
        img = jnp.take(data, b, axis=0).reshape(od, g * g, H, W)
        # bin (i,j) reads channel plane i*g+j of each output channel's block
        plane_idx = (jnp.arange(p)[:, None] * g
                     + jnp.arange(p)[None, :]).reshape(-1)
        planes = jnp.take(img, plane_idx, axis=1)      # (od, p*p, H, W)
        mk = m.reshape(p * p, H, W)
        s = jnp.einsum("khw,ckhw->ck", mk, planes)     # bin k pools plane k
        cnt = jnp.maximum(mk.sum((-1, -2)), 1.0)
        return (s / cnt[None]).reshape(od, p, p)

    return jax.vmap(one)(rois)


# ------------------------------------------------------------- sampling ops


def _bilinear_gather(data, gx, gy):
    """Sample data (C, H, W) at fractional pixel coords gx/gy (...,) with
    zero padding outside — the reference samplers' border behavior."""
    C, H, W = data.shape
    x0 = jnp.floor(gx)
    y0 = jnp.floor(gy)
    wx = gx - x0
    wy = gy - y0
    out = 0.0
    for dy, dx, w in ((0, 0, (1 - wx) * (1 - wy)), (0, 1, wx * (1 - wy)),
                      (1, 0, (1 - wx) * wy), (1, 1, wx * wy)):
        xi = x0.astype(jnp.int32) + dx
        yi = y0.astype(jnp.int32) + dy
        valid = (xi >= 0) & (xi < W) & (yi >= 0) & (yi < H)
        xi = jnp.clip(xi, 0, W - 1)
        yi = jnp.clip(yi, 0, H - 1)
        v = data[:, yi, xi]                    # (C, ...) advanced indexing
        out = out + v * (w * valid)[None]
    return out


@register("BilinearSampler", num_inputs=2, aliases=("bilinear_sampler",))
def bilinear_sampler(data, grid):
    """Sample data at grid locations (reference:
    src/operator/bilinear_sampler.cc). grid: (N, 2, Ho, Wo), channel 0 = x,
    channel 1 = y, both normalized to [-1, 1]; outside is zero-padded."""
    N, C, H, W = data.shape
    gx = (grid[:, 0] + 1.0) * (W - 1) / 2.0
    gy = (grid[:, 1] + 1.0) * (H - 1) / 2.0
    return jax.vmap(_bilinear_gather)(data, gx, gy)


@register("GridGenerator", num_inputs=1)
def grid_generator(data, transform_type="affine", target_shape=None):
    """Generate a sampling grid (reference: src/operator/grid_generator.cc).

    affine: data (N, 6) row-major 2x3 theta -> (N, 2, H, W) source coords.
    warp: data (N, 2, H, W) pixel flow added to the identity grid.
    """
    if transform_type == "affine":
        H, W = _tup(target_shape, 2)
        ys, xs = jnp.meshgrid(jnp.linspace(-1.0, 1.0, H),
                              jnp.linspace(-1.0, 1.0, W), indexing="ij")
        tgt = jnp.stack([xs.ravel(), ys.ravel(),
                         jnp.ones(H * W)])                  # (3, HW)
        theta = data.reshape(-1, 2, 3)
        src = jnp.einsum("nij,jk->nik", theta, tgt)         # (N, 2, HW)
        return src.reshape(-1, 2, H, W)
    elif transform_type == "warp":
        N, _, H, W = data.shape
        ys, xs = jnp.meshgrid(jnp.arange(H, dtype=data.dtype),
                              jnp.arange(W, dtype=data.dtype), indexing="ij")
        gx = (data[:, 0] + xs) * 2.0 / jnp.maximum(W - 1, 1) - 1.0
        gy = (data[:, 1] + ys) * 2.0 / jnp.maximum(H - 1, 1) - 1.0
        return jnp.stack([gx, gy], axis=1)
    raise ValueError("transform_type must be 'affine' or 'warp'")


@register("SpatialTransformer", num_inputs=2,
          aliases=("spatial_transformer",))
def spatial_transformer(data, loc, target_shape=None,
                        transform_type="affine", sampler_type="bilinear"):
    """STN: affine grid from loc + bilinear sampling (reference:
    src/operator/spatial_transformer.cc; Jaderberg et al. 2015)."""
    if transform_type != "affine" or sampler_type != "bilinear":
        raise ValueError("only affine/bilinear is supported (as in the "
                         "reference)")
    grid = grid_generator.fn(loc, transform_type="affine",
                             target_shape=target_shape)
    return bilinear_sampler.fn(data, grid)


# ------------------------------------------------------------- correlation


@register("Correlation", num_inputs=2)
def correlation(data1, data2, kernel_size=1, max_displacement=1, stride1=1,
                stride2=1, pad_size=0, is_multiply=True):
    """Patch cross-correlation between two feature maps (reference:
    src/operator/correlation.cc; FlowNet). For each displacement on a
    (2d+1)^2 grid, the channel-mean of the kernel-window product — the
    displacement enumeration is a static loop XLA unrolls."""
    N, C, H, W = data1.shape
    k = int(kernel_size)
    d = int(max_displacement)
    s1, s2, pad = int(stride1), int(stride2), int(pad_size)
    steps = d // s2
    p1 = jnp.pad(data1, ((0, 0), (0, 0), (pad, pad), (pad, pad)))
    p2 = jnp.pad(data2, ((0, 0), (0, 0), (pad, pad), (pad, pad)))
    Hp, Wp = H + 2 * pad, W + 2 * pad
    bk = k // 2
    win = jnp.ones((1, 1, k, k), data1.dtype)

    maps = []
    for dy in range(-steps, steps + 1):
        for dx in range(-steps, steps + 1):
            shifted = jnp.roll(p2, (-dy * s2, -dx * s2), axis=(2, 3))
            prod = p1 * shifted if is_multiply else jnp.abs(p1 - shifted)
            summed = lax.conv_general_dilated(
                prod.reshape(N * C, 1, Hp, Wp), win, (1, 1),
                [(bk, bk), (bk, bk)]).reshape(N, C, Hp, Wp)
            maps.append(summed.mean(axis=1))
    out = jnp.stack(maps, axis=1)          # (N, D², Hp, Wp)
    out = out[:, :, bk + d:Hp - bk - d:s1, bk + d:Wp - bk - d:s1]
    return out / (k * k)


# ----------------------------------------------------- deformable conv


@register("DeformableConvolution", num_inputs=None,
          aliases=("_contrib_DeformableConvolution",))
def deformable_convolution(data, offset, weight, bias=None, kernel=None,
                           stride=None, dilate=None, pad=None,
                           num_filter=None, num_group=1,
                           num_deformable_group=1, no_bias=False,
                           workspace=1024):
    """Deformable convolution v1 (reference:
    src/operator/contrib/deformable_convolution.cc, Dai et al. 2017).

    offset: (N, 2*dg*kh*kw, Ho, Wo) — per kernel tap (dy, dx) pairs. Each
    tap bilinearly samples the input at its offset position; the conv then
    reduces over taps via einsum — im2col becomes gather + matmul (MXU).
    """
    kh, kw = _tup(kernel, 2)
    sh, sw = _tup(stride, 2) or (1, 1)
    dh, dw = _tup(dilate, 2) or (1, 1)
    ph_, pw_ = _tup(pad, 2) or (0, 0)
    N, C, H, W = data.shape
    F = int(num_filter)
    g = int(num_group)
    dg = int(num_deformable_group)
    Ho = (H + 2 * ph_ - (dh * (kh - 1) + 1)) // sh + 1
    Wo = (W + 2 * pw_ - (dw * (kw - 1) + 1)) // sw + 1
    data, weight = amp.cast_compute(data, weight)

    base_y = jnp.arange(Ho) * sh - ph_
    base_x = jnp.arange(Wo) * sw - pw_
    off = offset.reshape(N, dg, kh * kw, 2, Ho, Wo)
    cpg = C // dg    # channels per deformable group

    taps = []
    for ki in range(kh):
        for kj in range(kw):
            t = ki * kw + kj
            gy = base_y[:, None] + ki * dh + off[:, :, t, 0]    # (N,dg,Ho,Wo)
            gx = base_x[None, :] + kj * dw + off[:, :, t, 1]

            def sample(img, gy_, gx_):
                # img (dg, cpg, H, W) ; gy_/gx_ (dg, Ho, Wo)
                return jax.vmap(_bilinear_gather)(img, gx_, gy_)

            smp = jax.vmap(sample)(data.reshape(N, dg, cpg, H, W),
                                   gy.astype(data.dtype),
                                   gx.astype(data.dtype))
            taps.append(smp.reshape(N, C, Ho, Wo))
    col = jnp.stack(taps, axis=2)           # (N, C, kh*kw, Ho, Wo)
    col = col.reshape(N, g, C // g, kh * kw, Ho, Wo)
    w = weight.reshape(g, F // g, C // g, kh * kw)
    out = jnp.einsum("ngckhw,gfck->ngfhw", col, w,
                     preferred_element_type=jnp.float32)
    out = out.reshape(N, F, Ho, Wo).astype(jnp.result_type(data, weight))
    if not no_bias and bias is not None:
        out = out + bias.reshape(1, -1, 1, 1).astype(out.dtype)
    return out


get_op("DeformableConvolution")._input_names = ["data", "offset", "weight",
                                                "bias"]

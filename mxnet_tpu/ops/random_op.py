"""Random sampling ops.

Reference: ``src/operator/random/sample_op.cc`` — samplers backed by the
per-device PRNG ``Resource`` (SURVEY.md §2.5 random/). The TPU design replaces
the stateful resource with explicit ``jax.random`` keys: every sampler op
declares ``needs_rng=True`` and receives a fresh key as ``_rng`` from the
dispatch layer (imperative path: split off the global seed state in
``mxnet_tpu.random``; symbolic path: the Executor threads a key per forward).
Counter-based threefry keys make runs reproducible across meshes — something
the reference's per-GPU mtrand streams never guaranteed.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp

from .registry import register


@register("_random_uniform", num_inputs=0, needs_rng=True, is_random=True,
          aliases=("uniform", "random_uniform", "_sample_uniform"))
def random_uniform(low=0.0, high=1.0, shape=(), dtype="float32", _rng=None):
    shape = (shape,) if isinstance(shape, int) else tuple(shape)
    return jax.random.uniform(_rng, shape, jnp.dtype(dtype), low, high)


@register("_random_normal", num_inputs=0, needs_rng=True, is_random=True,
          aliases=("normal", "random_normal", "_sample_normal"))
def random_normal(loc=0.0, scale=1.0, shape=(), dtype="float32", _rng=None):
    shape = (shape,) if isinstance(shape, int) else tuple(shape)
    return loc + scale * jax.random.normal(_rng, shape, jnp.dtype(dtype))


@register("_random_gamma", num_inputs=0, needs_rng=True, is_random=True,
          aliases=("random_gamma", "_sample_gamma"))
def random_gamma(alpha=1.0, beta=1.0, shape=(), dtype="float32", _rng=None):
    shape = (shape,) if isinstance(shape, int) else tuple(shape)
    return beta * jax.random.gamma(_rng, alpha, shape, jnp.dtype(dtype))


@register("_random_exponential", num_inputs=0, needs_rng=True, is_random=True,
          aliases=("random_exponential", "_sample_exponential"))
def random_exponential(lam=1.0, shape=(), dtype="float32", _rng=None):
    shape = (shape,) if isinstance(shape, int) else tuple(shape)
    return jax.random.exponential(_rng, shape, jnp.dtype(dtype)) / lam


@register("_random_poisson", num_inputs=0, needs_rng=True, is_random=True,
          aliases=("random_poisson", "_sample_poisson"))
def random_poisson(lam=1.0, shape=(), dtype="float32", _rng=None):
    shape = (shape,) if isinstance(shape, int) else tuple(shape)
    return jax.random.poisson(_rng, lam, shape).astype(jnp.dtype(dtype))


@register("_random_negative_binomial", num_inputs=0, needs_rng=True, is_random=True,
          aliases=("random_negative_binomial", "_sample_negbinomial"))
def random_negative_binomial(k=1, p=1.0, shape=(), dtype="float32", _rng=None):
    """NB(k,p) as Poisson-Gamma mixture (reference: sample_op.cc
    NegativeBinomialSampler)."""
    shape = (shape,) if isinstance(shape, int) else tuple(shape)
    k1, k2 = jax.random.split(_rng)
    lam = jax.random.gamma(k1, k, shape) * ((1 - p) / p)
    return jax.random.poisson(k2, lam, shape).astype(jnp.dtype(dtype))


@register("_random_generalized_negative_binomial", num_inputs=0, needs_rng=True,
          is_random=True,
          aliases=("random_generalized_negative_binomial", "_sample_gennegbinomial"))
def random_gen_negative_binomial(mu=1.0, alpha=1.0, shape=(), dtype="float32", _rng=None):
    shape = (shape,) if isinstance(shape, int) else tuple(shape)
    k1, k2 = jax.random.split(_rng)
    r = 1.0 / alpha
    g = jax.random.gamma(k1, r, shape) * (mu * alpha)
    return jax.random.poisson(k2, g, shape).astype(jnp.dtype(dtype))


@register("_sample_multinomial", num_inputs=1, needs_rng=True, is_random=True,
          aliases=("sample_multinomial",))
def sample_multinomial(data, shape=(), get_prob=False, dtype="int32", _rng=None):
    """Sample categorical ids from probability rows (reference:
    src/operator/random/multisample_op.cc-era sampling; used by SAP too)."""
    n = 1
    if shape:
        n = int(shape) if isinstance(shape, int) else int(jnp.prod(jnp.array(shape)))
    logits = jnp.log(jnp.maximum(data, 1e-37))
    out = jax.random.categorical(_rng, logits, axis=-1,
                                 shape=(n,) + data.shape[:-1])
    out = jnp.moveaxis(out, 0, -1)
    if n == 1:
        out = out.squeeze(-1)
    out = out.astype(jnp.dtype(dtype))
    if get_prob:
        p = jnp.take_along_axis(
            data, out[..., None].astype(jnp.int32), axis=-1
        ).squeeze(-1)
        return out, jnp.log(p)
    return out


@register("shuffle", num_inputs=1, needs_rng=True, is_random=True,
          aliases=("_shuffle",))
def shuffle(data, _rng=None):
    return jax.random.permutation(_rng, data, axis=0)

"""Contrib ops + the fork's Stochastic Activation Pruning operator.

Reference: ``src/operator/contrib/`` (SURVEY.md §2.5 contrib/) and the fork
delta ``src/operator/stochastic_activation_pruning-inl.h:1-277`` (the repo's
one divergence from upstream Apache MXNet 0.11 — the ICLR'18 SAP
adversarial-defense op).
"""
from __future__ import annotations

import jax
import jax.numpy as jnp

from .registry import register


@register("stochastic_activation_pruning", num_inputs=2, needs_rng=True,
          aliases=("StochasticActivationPruning", "sap"))
def stochastic_activation_pruning(act, prob, frac=1.0, _rng=None):
    """Stochastic Activation Pruning (reference:
    src/operator/stochastic_activation_pruning-inl.h:66-137).

    Inputs flatten to 2-D (rows, cols). Per row, draw ``k = frac * cols``
    categorical samples from ``prob``; kept activations are rescaled by the
    inverse retention propensity ``1 / (1 - (1-p)^k)``; the rest are zeroed.
    Backward flows ``grad * mask`` into ``act`` and zero into ``prob``
    (reference lines 139-178) — here that falls out of vjp because ``mask``
    is built from ``stop_gradient`` samples.

    TPU lowering: a batched inverse-CDF draw (cumsum + uniform +
    searchsorted — compiles far faster than ``jax.random.categorical``'s
    batched-logits path) + a scatter; the reference's nested OpenMP/CUDA
    sampling loop becomes a handful of fused HLOs.
    """
    shape = act.shape
    rows = shape[0] if act.ndim > 1 else 1
    a2 = act.reshape(rows, -1)
    p2 = prob.reshape(rows, -1)
    cols = a2.shape[1]
    k = max(int(frac * cols), 1)
    cdf = jnp.cumsum(jax.lax.stop_gradient(p2), axis=1)
    u = jax.random.uniform(_rng, (rows, k), dtype=cdf.dtype) * cdf[:, -1:]
    # side="right" skips zero-probability plateaus (u==0 or u exactly at a
    # plateau edge must not select a p=0 category — its importance weight
    # 1/(1-(1-0)^k) would be inf); clip guards the u→total rounding edge
    idx = jax.vmap(lambda c, v: jnp.searchsorted(c, v, side="right"))(cdf, u)
    idx = jnp.minimum(idx, cols - 1)
    weights = 1.0 / (1.0 - jnp.power(1.0 - jax.lax.stop_gradient(p2), k))
    mask = jnp.zeros_like(a2)
    rowix = jnp.arange(rows)[:, None]
    mask = mask.at[rowix, idx].set(jnp.take_along_axis(weights, idx, axis=1))
    return (a2 * mask).reshape(shape)


@register("quantize", num_inputs=3, aliases=("_contrib_quantize",))
def quantize(data, min_range, max_range, out_type="uint8"):
    """Affine quantization (reference: src/operator/contrib/quantize.cc)."""
    if out_type == "uint8":
        qmin, qmax = 0.0, 255.0
        dt = jnp.uint8
    else:
        qmin, qmax = -127.0, 127.0
        dt = jnp.int8
    scale = (qmax - qmin) / (max_range - min_range)
    q = jnp.clip(jnp.round((data - min_range) * scale + qmin), qmin, qmax)
    return q.astype(dt), min_range, max_range


@register("dequantize", num_inputs=3, aliases=("_contrib_dequantize",))
def dequantize(data, min_range, max_range, out_type="float32"):
    """(reference: src/operator/contrib/dequantize.cc)."""
    if data.dtype == jnp.uint8:
        qmin, qmax = 0.0, 255.0
    else:
        qmin, qmax = -127.0, 127.0
    scale = (max_range - min_range) / (qmax - qmin)
    return ((data.astype(jnp.float32) - qmin) * scale + min_range).astype(
        jnp.dtype(out_type))


@register("count_sketch", num_inputs=3, aliases=("_contrib_count_sketch",))
def count_sketch(data, h, s, out_dim=None, processing_batch_size=32):
    """Count-sketch projection (reference: src/operator/contrib/count_sketch.cc).
    out[n, h[i]] += s[i] * data[n, i] — a scatter-add on TPU."""
    idx = h.reshape(-1).astype(jnp.int32)
    sign = s.reshape(-1).astype(data.dtype)
    n = data.shape[0]
    out = jnp.zeros((n, int(out_dim)), dtype=data.dtype)
    return out.at[:, idx].add(data * sign)


@register("fft", aliases=("_contrib_fft",))
def fft(data, compute_size=128):
    """FFT along last axis, complex packed as interleaved re/im like the
    reference cuFFT op (reference: src/operator/contrib/fft-inl.h)."""
    out = jnp.fft.fft(data.astype(jnp.float32), axis=-1)
    packed = jnp.stack([out.real, out.imag], axis=-1)
    return packed.reshape(data.shape[:-1] + (2 * data.shape[-1],)).astype(data.dtype)


@register("ifft", aliases=("_contrib_ifft",))
def ifft(data, compute_size=128):
    """(reference: src/operator/contrib/ifft-inl.h). Input packs re/im
    interleaved; output is the real part scaled like cuFFT (unnormalized)."""
    n = data.shape[-1] // 2
    x = data.reshape(data.shape[:-1] + (n, 2)).astype(jnp.float32)
    c = x[..., 0] + 1j * x[..., 1]
    out = jnp.fft.ifft(c, axis=-1) * n
    return out.real.astype(data.dtype)


@register("MoE", num_inputs=4, aliases=("_contrib_MoE",))
def moe(data, router, wi, wo, top_k=2, capacity_factor=1.25):
    """Mixture-of-experts FFN over tokens (no reference counterpart —
    SURVEY.md §2.21 marks expert parallel absent upstream; this exposes
    parallel/moe.py's Switch/GShard dense-dispatch MoE as a framework op
    so nd/sym/gluon callers get it like any other layer).

    data: (..., d_model) tokens (leading axes flattened for routing),
    router: (d_model, E), wi: (E, d_model, d_hidden), wo: (E, d_hidden,
    d_model). Returns (out, aux_loss): out matches data's shape; aux is
    the scalar GShard load-balance loss. To shard experts over a mesh
    axis, use ``parallel.moe_apply(mesh=...)`` directly or bind the
    module with expert-sharded param_shardings.
    """
    from ..parallel.moe import moe_apply
    lead = data.shape[:-1]
    toks = data.reshape(-1, data.shape[-1])
    out, aux = moe_apply({"router": router, "wi": wi, "wo": wo}, toks,
                         top_k=int(top_k),
                         capacity_factor=float(capacity_factor))
    return out.reshape(lead + (data.shape[-1],)), aux


moe.num_outputs = 2

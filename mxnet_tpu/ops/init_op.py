"""Creation ops (no array inputs).

Reference: ``src/operator/tensor/init_op.cc`` (zeros/ones/arange/full).
These ops have ``num_inputs=0``; the dispatch layer places results on the
requested context's device.
"""
from __future__ import annotations

import jax.numpy as jnp

from .registry import register


@register("_zeros", num_inputs=0, aliases=("zeros",))
def zeros(shape=(), dtype="float32"):
    shape = (shape,) if isinstance(shape, int) else tuple(shape)
    return jnp.zeros(shape, dtype=jnp.dtype(dtype))


@register("_ones", num_inputs=0, aliases=("ones",))
def ones(shape=(), dtype="float32"):
    shape = (shape,) if isinstance(shape, int) else tuple(shape)
    return jnp.ones(shape, dtype=jnp.dtype(dtype))


@register("_full", num_inputs=0, aliases=("full",))
def full(shape=(), value=0.0, dtype="float32"):
    shape = (shape,) if isinstance(shape, int) else tuple(shape)
    return jnp.full(shape, value, dtype=jnp.dtype(dtype))


@register("_arange", num_inputs=0, aliases=("arange",))
def arange(start=0, stop=None, step=1.0, repeat=1, dtype="float32"):
    """(reference: init_op.cc _arange, incl. the odd `repeat` attr)."""
    out = jnp.arange(start, stop, step, dtype=jnp.dtype(dtype))
    if repeat and repeat > 1:
        out = jnp.repeat(out, repeat)
    return out


@register("_eye", num_inputs=0, aliases=("eye",))
def eye(N=0, M=0, k=0, dtype="float32"):
    return jnp.eye(int(N), int(M) if M else None, k=int(k), dtype=jnp.dtype(dtype))

"""Operator catalog: importing this package populates the registry.

The registry is the single source of truth for both ``mx.nd.*`` and
``mx.sym.*`` auto-generated wrappers (SURVEY.md §7 step 2).
"""
from .registry import OpDef, OP_REGISTRY, register, alias, get_op, list_ops

from . import elemwise  # noqa: F401
from . import reduce  # noqa: F401
from . import matrix  # noqa: F401
from . import indexing  # noqa: F401
from . import init_op  # noqa: F401
from . import random_op  # noqa: F401
from . import nn  # noqa: F401
from . import sequence  # noqa: F401
from . import contrib  # noqa: F401
from . import vision  # noqa: F401
from . import detection  # noqa: F401
from . import pallas  # noqa: F401
from . import optimizer_op  # noqa: F401
from . import rnn_op  # noqa: F401

__all__ = ["OpDef", "OP_REGISTRY", "register", "alias", "get_op", "list_ops"]

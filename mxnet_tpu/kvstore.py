"""KVStore — the gradient-exchange / parameter-synchronization surface.

Reference: ``include/mxnet/kvstore.h:44-348`` + ``src/kvstore/`` (SURVEY.md
§2.7): ``local`` aggregates on CPU, ``device`` on GPUs with P2P,
``dist_sync``/``dist_async`` ride a ps-lite parameter server.

TPU design (SURVEY §2.7 translation): the *API* (init/push/pull/set_updater/
rank/barrier) is kept so Module/Trainer code is parallelism-agnostic, but
aggregation is XLA arithmetic:

* ``local``/``device`` — multi-device values are summed with jnp adds; under
  a jitted data-parallel step the same reduction is a mesh ``psum`` riding
  ICI (see mxnet_tpu/parallel/).
* ``dist_sync``/``dist_async`` — multi-host via ``jax.distributed``: every
  process runs the same SPMD program, rank/size map to
  ``jax.process_index/process_count``, and cross-host reduction happens in
  the compiled collective — there is no separate server process to run, so
  ``RunServer``/server-command plumbing reduces to no-ops kept for API parity
  (an explicitly non-idiomatic PS mode is descoped, SURVEY §5.8).
"""
from __future__ import annotations

import pickle
from typing import Any, Callable, Dict, List, Optional, Union

import numpy as np
import jax

from . import ndarray as nd
from .ndarray import NDArray
from .base import MXNetError
from . import optimizer as opt

__all__ = ["KVStore", "create"]


def _key_list(key):
    single = not isinstance(key, (list, tuple))
    return ([key] if single else list(key)), single


def _val_list(value, n_keys):
    """Normalize to list-of-lists: per key, a list of per-device values."""
    if not isinstance(value, (list, tuple)):
        value = [value]
    if n_keys == 1:
        if all(isinstance(v, NDArray) for v in value):
            return [list(value)]
    out = []
    for v in value:
        out.append(list(v) if isinstance(v, (list, tuple)) else [v])
    return out


class KVStore(object):
    """(reference: python/mxnet/kvstore.py:62 KVStore; C++ api
    include/mxnet/kvstore.h:44)."""

    def __init__(self, kind: str):
        self._kind = kind
        self._store: Dict[Any, NDArray] = {}
        self._updater: Optional[Callable] = None
        self._updater_obj: Optional[opt.Updater] = None

    # ------------------------------------------------------------ topology
    @property
    def type(self) -> str:
        return self._kind

    @property
    def rank(self) -> int:
        """(reference: kvstore.h get_rank)."""
        try:
            return jax.process_index()
        except Exception:
            return 0

    @property
    def num_workers(self) -> int:
        """(reference: kvstore.h get_group_size)."""
        try:
            return jax.process_count()
        except Exception:
            return 1

    def barrier(self):
        """Global barrier (reference: kvstore.h Barrier). All outstanding
        device work is flushed; with multiple processes the next collective
        synchronizes them."""
        nd.waitall()

    # ------------------------------------------------------------ data
    def init(self, key, value):
        """(reference: kvstore.py init — run once per key before push/pull)."""
        keys, _ = _key_list(key)
        vals = _val_list(value, len(keys))
        for k, vlist in zip(keys, vals):
            if k in self._store:
                raise MXNetError("key %r already initialized" % (k,))
            self._store[k] = vlist[0].copy()

    @staticmethod
    def _local_reduce(vlist):
        """Sum per-device values onto the first value's device (reference:
        local reduce src/kvstore/comm.h:85)."""
        acc = vlist[0].data
        dev = acc.device if hasattr(acc, "device") else None
        for v in vlist[1:]:
            d = v.data
            if dev is not None and getattr(d, "device", None) != dev:
                d = jax.device_put(d, dev)
            acc = acc + d
        return acc

    def push(self, key, value, priority: int = 0):
        """Aggregate (sum) pushed values; if an updater is set, apply it to
        the stored weight (reference: kvstore.py push; local reduce
        src/kvstore/comm.h:85; server-side update
        kvstore_dist_server.h:164-230)."""
        keys, _ = _key_list(key)
        vals = _val_list(value, len(keys))
        for k, vlist in zip(keys, vals):
            if k not in self._store:
                raise MXNetError("key %r not initialized" % (k,))
            merged = vlist[0] if len(vlist) == 1 \
                else NDArray(self._local_reduce(vlist))
            if self._updater is not None:
                self._updater(k, merged, self._store[k])
            else:
                self._store[k]._data = self._store[k].data + merged.data
                self._store[k]._version += 1

    def pull(self, key, out=None, priority: int = 0):
        """Copy stored weights into out arrays (reference: kvstore.py pull;
        broadcast src/kvstore/kvstore_local.h:92-119)."""
        assert out is not None
        keys, _ = _key_list(key)
        if len(keys) == 1:
            outs = [out] if isinstance(out, NDArray) else list(out)
            outs = [outs]
        else:
            outs = []
            for o in out:
                outs.append([o] if isinstance(o, NDArray) else list(o))
        for k, olist in zip(keys, outs):
            if k not in self._store:
                raise MXNetError("key %r not initialized" % (k,))
            src = self._store[k]
            for o in olist:
                src.copyto(o)

    # ------------------------------------------------------------ updater
    def set_updater(self, updater: Callable):
        """(reference: kvstore.py _set_updater)."""
        self._updater = updater

    def set_optimizer(self, optimizer: opt.Optimizer):
        """(reference: kvstore.py set_optimizer — in dist mode the reference
        pickles the optimizer to the servers; here every process constructs
        the same updater locally, which is the SPMD equivalent)."""
        self._updater_obj = opt.get_updater(optimizer)
        self._updater = self._updater_obj

    # ------------------------------------------------------------ states
    def save_optimizer_states(self, fname: str):
        if self._updater_obj is None:
            raise MXNetError("Cannot save states for distributed training")
        with open(fname, "wb") as fout:
            fout.write(self._updater_obj.get_states())

    def load_optimizer_states(self, fname: str):
        if self._updater_obj is None:
            raise MXNetError("Cannot load states for distributed training")
        with open(fname, "rb") as fin:
            self._updater_obj.set_states(fin.read())

    # ------------------------------------------------------------ cluster
    def send_command_to_servers(self, head: int, body: str):
        """(reference: kvstore.h SendCommandToServers). No separate server
        processes exist in the SPMD design; kept for API parity."""

    def get_num_dead_node(self, node_id: int, timeout: int = 0) -> int:
        """(reference: kvstore.h:287 — ps-lite heartbeat probe). In a
        single process there is nothing to probe and the correct answer is
        zero; DistKVStore overrides this with a real heartbeat check."""
        return 0

    @staticmethod
    def is_worker_node() -> bool:
        return True

    @staticmethod
    def is_server_node() -> bool:
        return False

    @staticmethod
    def is_scheduler_node() -> bool:
        return False


class DistKVStore(KVStore):
    """Multi-process kvstore over ``parallel.dist`` (reference:
    src/kvstore/kvstore_dist.h:50-320 + kvstore_dist_server.h:105-250).

    There is no server role: every process holds a replica of the store and
    applies the same updater to the same cross-process gradient sum, so
    replicas stay bit-identical — the SPMD equivalent of the server's
    single authoritative copy.

    ``push`` is asynchronous like the reference's ZPush: the local-reduced
    gradient is *staged*, and staged keys are flattened into one fused
    allreduce per dtype at the next ``pull``/``barrier`` (chunked at
    ``MXNET_KVSTORE_BIGARRAY_BOUND`` elements — the reference shards big
    arrays across servers at the same knob, kvstore_dist.h:292). On this
    rig a collective dispatch costs ~50 ms of RPC, so one-allreduce-per-key
    made Trainer-style training pay seconds per step; fusing makes it one
    round trip per step — more precisely, one allreduce per dtype per
    ``MXNET_KVSTORE_BIGARRAY_BOUND``-element chunk of the staged total.

    Staging changes multi-push semantics vs the reference: several pushes
    to one key between pulls are *summed* and the updater runs once on the
    sum, whereas the reference's dist server applies the updater per push
    (kvstore_dist_server.h:164-230) — a stateful optimizer installed via
    ``set_optimizer`` takes one step instead of N. Identical for the
    push-once-per-batch pattern every trainer here uses; push-per-
    accumulation callers should pull between pushes.

    ``dist_async`` is accepted but behaves synchronously: XLA collectives
    are bulk-synchronous by construction; there is no stale-push mode.
    """

    def __init__(self, kind: str):
        super().__init__(kind)
        from .parallel import dist
        self._dist = dist
        self._pending: Dict[Any, Any] = {}   # key -> staged local sum
        # liveness heartbeat via the coordinator's KV store (reference:
        # ps-lite worker heartbeats, SURVEY §5.3 failure detection)
        dist.heartbeat_start()

    def get_num_dead_node(self, node_id: int, timeout: int = 0) -> int:
        """Workers with a missing/stale heartbeat (reference:
        kvstore.h:287 over ps-lite's scheduler heartbeat table)."""
        from . import config as _config
        stale = _config.get("MXNET_KVSTORE_HEARTBEAT_STALE_SECS")
        return self._dist.num_dead_nodes(
            stale_after=stale, timeout_ms=max(int(timeout) * 1000, 1000))

    @property
    def rank(self) -> int:
        return self._dist.rank()

    @property
    def num_workers(self) -> int:
        return self._dist.num_workers()

    def barrier(self):
        self._flush()
        nd.waitall()
        self._dist.barrier()

    def init(self, key, value):
        """Rank 0's value wins (reference: only one worker's init reaches
        the server; others' are ignored, kvstore_dist.h Push_ init path)."""
        keys, _ = _key_list(key)
        vals = _val_list(value, len(keys))
        for k, vlist in zip(keys, vals):
            if k in self._store:
                raise MXNetError("key %r already initialized" % (k,))
            synced = self._dist.broadcast(vlist[0].data, root=0)
            self._store[k] = NDArray(synced)

    def push(self, key, value, priority: int = 0):
        """Stage the local-reduced gradient; the cross-process allreduce
        happens fused at the next pull/barrier (see class docstring)."""
        keys, _ = _key_list(key)
        vals = _val_list(value, len(keys))
        for k, vlist in zip(keys, vals):
            if k not in self._store:
                raise MXNetError("key %r not initialized" % (k,))
            merged = self._local_reduce(vlist)
            if k in self._pending:
                self._pending[k] = self._pending[k] + merged
            else:
                self._pending[k] = merged

    def pull(self, key, out=None, priority: int = 0):
        self._flush()
        super().pull(key, out=out, priority=priority)

    def _flush(self):
        """Fused allreduce of all staged pushes: keys are ordered
        deterministically (every rank must concatenate identically),
        grouped by dtype, flattened, and reduced in
        ``MXNET_KVSTORE_BIGARRAY_BOUND``-element chunks; then the updater
        (or +=) applies per key."""
        if not self._pending:
            return
        import jax.numpy as jnp
        from . import config as _config
        bound = max(int(_config.get("MXNET_KVSTORE_BIGARRAY_BOUND")), 1)
        items = sorted(self._pending.items(), key=lambda kv: repr(kv[0]))
        self._pending = {}
        by_dtype: Dict[str, list] = {}
        for k, v in items:
            by_dtype.setdefault(str(v.dtype), []).append((k, v))
        for dt in sorted(by_dtype):
            kvs = by_dtype[dt]
            flat = jnp.concatenate([v.ravel() for _, v in kvs]) \
                if len(kvs) > 1 or kvs[0][1].ndim != 1 else kvs[0][1]
            parts = [self._dist.allreduce_sum(flat[s:s + bound])
                     for s in range(0, flat.size, bound)]
            summed = jnp.concatenate(parts) if len(parts) > 1 else parts[0]
            off = 0
            for k, v in kvs:
                merged = NDArray(
                    summed[off:off + v.size].reshape(v.shape))
                off += v.size
                if self._updater is not None:
                    self._updater(k, merged, self._store[k])
                else:
                    self._store[k]._data = self._store[k].data + merged.data
                    self._store[k]._version += 1


def create(name: str = "local") -> KVStore:
    """Factory (reference: src/kvstore/kvstore.cc:34-61 — substring grammar:
    'device' → device-side reduce, 'dist' → multi-process, '_async' → async
    server mode which is descoped on TPU to sync SPMD)."""
    if not isinstance(name, str):
        raise TypeError("name must be a string")
    valid = ("local", "local_allreduce_cpu", "local_update_cpu", "device",
             "dist_sync", "dist_dev_sync", "dist_device_sync", "dist_async",
             "dist")
    if name not in valid:
        raise MXNetError("Unknown KVStore type %r" % name)
    if "dist" in name:
        from .parallel import dist
        if not dist.is_initialized():
            # NB: probe only env + coordination state here — calling
            # num_workers() could initialize a backend as a side effect,
            # which would make the remedy below impossible
            if dist.cluster_env() is None and not dist.coordination_active():
                raise MXNetError(
                    "kvstore %r needs a cluster: launch with tools/launch.py "
                    "-n N (sets the DMLC_* env) or call "
                    "mxnet_tpu.parallel.dist.initialize(...) first; for "
                    "single-host multi-device training use 'device'" % name)
            dist.initialize()
        return DistKVStore(name)
    return KVStore(name)

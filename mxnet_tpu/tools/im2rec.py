#!/usr/bin/env python
"""im2rec — pack an image directory/list into a RecordIO dataset.

Reference: ``tools/im2rec.py`` / ``tools/im2rec.cc`` (SURVEY.md §2.16):
makes a ``.lst`` (index\\tlabel\\tpath) from a directory tree, then encodes
images into ``.rec`` (+ ``.idx``) via multiprocess workers.

Usage:
    python tools/im2rec.py prefix image_root --list        # make prefix.lst
    python tools/im2rec.py prefix image_root               # make prefix.rec
"""
from __future__ import annotations

import argparse
import os
import random
import sys


def list_image(root, recursive, exts):
    i = 0
    if recursive:
        cat = {}
        for path, dirs, files in os.walk(root, followlinks=True):
            dirs.sort()
            files.sort()
            for fname in files:
                fpath = os.path.join(path, fname)
                suffix = os.path.splitext(fname)[1].lower()
                if os.path.isfile(fpath) and (suffix in exts):
                    if path not in cat:
                        cat[path] = len(cat)
                    yield (i, os.path.relpath(fpath, root), cat[path])
                    i += 1
    else:
        for fname in sorted(os.listdir(root)):
            fpath = os.path.join(root, fname)
            suffix = os.path.splitext(fname)[1].lower()
            if os.path.isfile(fpath) and (suffix in exts):
                yield (i, os.path.relpath(fpath, root), 0)
                i += 1


def write_list(path_out, image_list):
    with open(path_out, "w") as fout:
        for i, item in enumerate(image_list):
            line = "%d\t" % item[0]
            for j in item[2:]:
                line += "%f\t" % j
            line += "%s\n" % item[1]
            fout.write(line)


def read_list(path_in):
    with open(path_in) as fin:
        for line in fin:
            line = line.strip().split("\t")
            if len(line) < 3:
                continue
            yield (int(line[0]), line[-1], [float(x) for x in line[1:-1]])


def make_rec(args):
    import cv2
    from mxnet_tpu import recordio

    lst = args.prefix + ".lst"
    items = list(read_list(lst))
    rec = recordio.MXIndexedRecordIO(args.prefix + ".idx",
                                     args.prefix + ".rec", "w")
    for idx, path, labels in items:
        fullpath = os.path.join(args.root, path)
        img = cv2.imread(fullpath, args.color)
        if img is None:
            print("imread failed:", fullpath)
            continue
        if args.resize:
            h, w = img.shape[:2]
            if h > w:
                newsize = (args.resize, int(h * args.resize / w))
            else:
                newsize = (int(w * args.resize / h), args.resize)
            img = cv2.resize(img, newsize)
        label = labels[0] if len(labels) == 1 else labels
        flag = 0 if len(labels) == 1 else len(labels)
        header = recordio.IRHeader(flag, label, idx, 0)
        rec.write_idx(idx, recordio.pack_img(header, img,
                                             quality=args.quality,
                                             img_fmt=args.encoding))
    rec.close()
    print("wrote %s.rec (%d records)" % (args.prefix, len(items)))


def main():
    parser = argparse.ArgumentParser(description="Create an image RecordIO dataset")
    parser.add_argument("prefix", help="prefix of .lst/.rec files")
    parser.add_argument("root", help="image root directory")
    parser.add_argument("--list", action="store_true",
                        help="make a .lst file instead of .rec")
    parser.add_argument("--exts", nargs="+",
                        default=[".jpeg", ".jpg", ".png"])
    parser.add_argument("--recursive", action="store_true", default=True)
    parser.add_argument("--shuffle", action="store_true")
    parser.add_argument("--resize", type=int, default=0)
    parser.add_argument("--quality", type=int, default=95)
    parser.add_argument("--encoding", type=str, default=".jpg")
    parser.add_argument("--color", type=int, default=1)
    args = parser.parse_args()
    if args.list:
        images = list(list_image(args.root, args.recursive, args.exts))
        if args.shuffle:
            random.seed(100)
            random.shuffle(images)
        write_list(args.prefix + ".lst", images)
        print("wrote %s.lst (%d entries)" % (args.prefix, len(images)))
    else:
        if not os.path.isfile(args.prefix + ".lst"):
            images = list(list_image(args.root, args.recursive, args.exts))
            write_list(args.prefix + ".lst", images)
        make_rec(args)


if __name__ == "__main__":
    main()

"""Packaged CLI tools (reference: tools/ — im2rec, launch)."""

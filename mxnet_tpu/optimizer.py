"""Optimizers.

Reference: ``python/mxnet/optimizer.py`` (992 LoC — registry at line 30/331,
SGD/DCASGD/NAG/SGLD/ccSGD/Adam/AdaGrad/RMSProp/AdaDelta/Ftrl/Adamax/Nadam/
Test at lines 334-923, ``Updater`` at 940). The numeric updates run through
the registered optimizer-update *ops* (mxnet_tpu/ops/optimizer_op.py ≡
src/operator/optimizer_op.cc), so each parameter update is one fused XLA
computation.
"""
from __future__ import annotations

import math
import pickle
from typing import Any, Dict, Optional

import numpy as np

from . import ndarray as nd
from .ndarray import NDArray
from .ops import get_op
from .ndarray.ndarray import imperative_invoke

__all__ = ["Optimizer", "SGD", "NAG", "SGLD", "DCASGD", "Adam", "AdaGrad",
           "RMSProp", "AdaDelta", "Ftrl", "Adamax", "Nadam", "Test",
           "create", "get_updater", "Updater", "register"]


class Optimizer(object):
    """Base optimizer (reference: optimizer.py:30)."""

    opt_registry: Dict[str, type] = {}

    @staticmethod
    def register(klass):
        """(reference: optimizer.py Optimizer.register)."""
        name = klass.__name__.lower()
        Optimizer.opt_registry[name] = klass
        return klass

    @staticmethod
    def create_optimizer(name: str, **kwargs) -> "Optimizer":
        if name.lower() in Optimizer.opt_registry:
            return Optimizer.opt_registry[name.lower()](**kwargs)
        raise ValueError("Cannot find optimizer %s" % name)

    def __init__(self, rescale_grad=1.0, param_idx2name=None, wd=0.0,
                 clip_gradient=None, learning_rate=0.01, lr_scheduler=None,
                 sym=None, begin_num_update=0):
        self.rescale_grad = rescale_grad
        self.lr = learning_rate
        self.lr_scheduler = lr_scheduler
        if lr_scheduler is not None:
            self.lr_scheduler.base_lr = learning_rate
        self.wd = wd
        self.lr_mult: Dict[Any, float] = {}
        self.wd_mult: Dict[Any, float] = {}
        self.begin_num_update = begin_num_update
        self.num_update = begin_num_update
        self._index_update_count: Dict[Any, int] = {}
        self.clip_gradient = clip_gradient
        if param_idx2name is None:
            param_idx2name = {}
        self.idx2name = param_idx2name.copy()
        self.sym = sym
        # traced-mode overrides (see raw_update): when set, _get_lr/_update_
        # count use these possibly-traced scalars instead of python floats so
        # one XLA compilation serves every step of an LR schedule.
        self._traced_lr = None
        self._traced_t = None

    def create_state(self, index, weight):
        return None

    def update(self, index, weight, grad, state):
        raise NotImplementedError

    def set_lr_mult(self, args_lr_mult: Dict[str, float]):
        """(reference: optimizer.py set_lr_mult — merges symbol attr
        __lr_mult__)."""
        self.lr_mult = {}
        if self.sym is not None:
            attr = self.sym.attr_dict()
            for name in self.sym.list_arguments():
                if name in attr and "__lr_mult__" in attr[name]:
                    self.lr_mult[name] = float(attr[name]["__lr_mult__"])
        self.lr_mult.update(args_lr_mult)

    def set_wd_mult(self, args_wd_mult: Dict[str, float]):
        """(reference: optimizer.py set_wd_mult — bias/gamma/beta default to
        wd_mult 0)."""
        self.wd_mult = {}
        for n in self.idx2name.values():
            if not (n.endswith("_weight") or n.endswith("_gamma")):
                self.wd_mult[n] = 0.0
        if self.sym is not None:
            attr = self.sym.attr_dict()
            for name in self.sym.list_arguments():
                if name in attr and "__wd_mult__" in attr[name]:
                    self.wd_mult[name] = float(attr[name]["__wd_mult__"])
        self.wd_mult.update(args_wd_mult)

    def _update_count(self, index):
        if self._traced_t is not None:
            self._index_update_count[index] = self._traced_t
            return
        if index not in self._index_update_count:
            self._index_update_count[index] = self.begin_num_update
        self._index_update_count[index] += 1
        self.num_update = max(self._index_update_count[index], self.num_update)

    def _get_lr(self, index) -> float:
        if self._traced_lr is not None:
            lr = self._traced_lr
        elif self.lr_scheduler is not None:
            lr = self.lr_scheduler(self.num_update)
        else:
            lr = self.lr
        if index in self.lr_mult:
            lr *= self.lr_mult[index]
        elif index in self.idx2name:
            lr *= self.lr_mult.get(self.idx2name[index], 1.0)
        return lr

    def _get_wd(self, index) -> float:
        wd = self.wd
        if index in self.wd_mult:
            wd *= self.wd_mult[index]
        elif index in self.idx2name:
            wd *= self.wd_mult.get(self.idx2name[index], 1.0)
        return wd

    def raw_update(self, index, weight, grad, state, lr=None, t=None):
        """Functionally apply this optimizer's update to raw (possibly
        traced) jax arrays, returning ``(new_weight, new_state)``.

        The TPU fit hot path (Module._fit_step) traces this inside ONE jitted
        train step — the analogue of the reference running `sgd_mom_update`
        engine ops right after the backward ops (SURVEY.md §2.5 optimizer
        update ops, §7 "fit() must run fully jitted"). ``lr`` and the update
        count ``t`` enter as traced scalars so LR schedules and Adam bias
        correction do not force a recompile every step.
        """
        from .ndarray import NDArray

        def wrap(v):
            if v is None:
                return None
            if isinstance(v, tuple):
                return tuple(wrap(x) for x in v)
            return NDArray(v)

        def unwrap(v):
            if v is None:
                return None
            if isinstance(v, tuple):
                return tuple(unwrap(x) for x in v)
            return v._data

        w, g, s = NDArray(weight), NDArray(grad), wrap(state)
        self._traced_lr, self._traced_t = lr, t
        # snapshot ALL instance attrs: a traced update() must not leak
        # tracers into persistent optimizer state (state flows through the
        # returned pytree instead)
        saved = {k: (dict(v) if isinstance(v, dict) else v)
                 for k, v in self.__dict__.items()}
        try:
            self.update(index, w, g, s)
        finally:
            self.__dict__.clear()
            self.__dict__.update(saved)
            self._traced_lr = self._traced_t = None
        return w._data, unwrap(s)

    def _common_kwargs(self, index):
        kw = {"rescale_grad": self.rescale_grad}
        if self.clip_gradient is not None:
            kw["clip_gradient"] = self.clip_gradient
        return kw


register = Optimizer.register
create = Optimizer.create_optimizer


def _invoke(opname, arrays, out_arrays, **attrs):
    """Run an optimizer-update op and commit results in place."""
    op = get_op(opname)
    res = imperative_invoke(op, *arrays, **attrs)
    if not isinstance(res, (list, tuple)):
        res = [res]
    for dst, src in zip(out_arrays, res):
        dst._data = src.data
        dst._version += 1


@register
class SGD(Optimizer):
    """SGD with momentum, weight decay and multi-precision support
    (reference: optimizer.py:334 SGD)."""

    def __init__(self, momentum=0.0, multi_precision=False, **kwargs):
        super().__init__(**kwargs)
        self.momentum = momentum
        self.multi_precision = multi_precision

    def create_state(self, index, weight):
        momentum = None
        weight_master = None
        if self.multi_precision and weight.dtype == np.float16:
            weight_master = weight.astype(np.float32)
        if self.momentum != 0.0:
            base = weight_master if weight_master is not None else weight
            momentum = nd.zeros(base.shape, dtype=base.dtype, ctx=base.context)
        if weight_master is not None:
            return (momentum, weight_master)
        return momentum

    def update(self, index, weight, grad, state):
        lr, wd = self._get_lr(index), self._get_wd(index)
        self._update_count(index)
        kw = self._common_kwargs(index)
        master = None
        mom = state
        if isinstance(state, tuple):
            mom, master = state
        w = master if master is not None else weight
        g = grad.astype(w.dtype) if grad.dtype != w.dtype else grad
        if self.momentum == 0.0:
            _invoke("sgd_update", [w, g], [w], lr=lr, wd=wd, **kw)
        else:
            _invoke("sgd_mom_update", [w, g, mom], [w, mom], lr=lr, wd=wd,
                    momentum=self.momentum, **kw)
        if master is not None:
            weight._data = w.data.astype(weight.dtype)
            weight._version += 1


@register
class NAG(Optimizer):
    """Nesterov accelerated SGD (reference: optimizer.py NAG)."""

    def __init__(self, momentum=0.0, **kwargs):
        super().__init__(**kwargs)
        self.momentum = momentum

    def create_state(self, index, weight):
        if self.momentum == 0.0:
            return None
        return nd.zeros(weight.shape, dtype=weight.dtype, ctx=weight.context)

    def update(self, index, weight, grad, state):
        lr, wd = self._get_lr(index), self._get_wd(index)
        self._update_count(index)
        kw = self._common_kwargs(index)
        if state is None:
            _invoke("sgd_update", [weight, grad], [weight], lr=lr, wd=wd, **kw)
        else:
            _invoke("nag_mom_update", [weight, grad, state], [weight, state],
                    lr=lr, wd=wd, momentum=self.momentum, **kw)


@register
class SGLD(Optimizer):
    """Langevin dynamics sampler (reference: optimizer.py SGLD)."""

    def update(self, index, weight, grad, state):
        lr, wd = self._get_lr(index), self._get_wd(index)
        self._update_count(index)
        kw = self._common_kwargs(index)
        _invoke("sgld_update", [weight, grad], [weight], lr=lr, wd=wd, **kw)


@register
class DCASGD(Optimizer):
    """Delay-compensated async SGD (reference: optimizer.py DCASGD)."""

    def __init__(self, momentum=0.0, lamda=0.04, **kwargs):
        super().__init__(**kwargs)
        self.momentum = momentum
        self.weight_previous: Dict[Any, NDArray] = {}
        self.lamda = lamda

    def create_state(self, index, weight):
        mom = nd.zeros(weight.shape, dtype=weight.dtype, ctx=weight.context) \
            if self.momentum != 0.0 else None
        return (mom, weight.copy())

    def update(self, index, weight, grad, state):
        lr, wd = self._get_lr(index), self._get_wd(index)
        self._update_count(index)
        mom, prev = state
        g = grad * self.rescale_grad
        if self.clip_gradient is not None:
            g = nd.clip(g, -self.clip_gradient, self.clip_gradient)
        comp = g + wd * weight + self.lamda * g * g * (weight - prev)
        if mom is None:
            step = (-lr) * comp
        else:
            mom *= self.momentum
            mom -= lr * comp
            step = mom
        prev._data = weight.data
        prev._version += 1
        weight += step


@register
class Adam(Optimizer):
    """(reference: optimizer.py Adam; update op optimizer_op.cc adam_update)."""

    def __init__(self, learning_rate=0.001, beta1=0.9, beta2=0.999,
                 epsilon=1e-8, **kwargs):
        super().__init__(learning_rate=learning_rate, **kwargs)
        self.beta1, self.beta2, self.epsilon = beta1, beta2, epsilon

    def create_state(self, index, weight):
        return (nd.zeros(weight.shape, dtype=weight.dtype, ctx=weight.context),
                nd.zeros(weight.shape, dtype=weight.dtype, ctx=weight.context))

    def update(self, index, weight, grad, state):
        lr, wd = self._get_lr(index), self._get_wd(index)
        self._update_count(index)
        t = self._index_update_count[index]
        coef1 = 1.0 - self.beta1 ** t
        coef2 = 1.0 - self.beta2 ** t
        # ** 0.5, not math.sqrt: t may be a traced scalar on the fused path
        lr *= coef2 ** 0.5 / coef1
        mean, var = state
        _invoke("adam_update", [weight, grad, mean, var], [weight, mean, var],
                lr=lr, beta1=self.beta1, beta2=self.beta2,
                epsilon=self.epsilon, wd=wd, **self._common_kwargs(index))


@register
class AdaGrad(Optimizer):
    """(reference: optimizer.py AdaGrad)."""

    def __init__(self, eps=1e-7, **kwargs):
        super().__init__(**kwargs)
        self.float_stable_eps = eps

    def create_state(self, index, weight):
        return nd.zeros(weight.shape, dtype=weight.dtype, ctx=weight.context)

    def update(self, index, weight, grad, state):
        lr, wd = self._get_lr(index), self._get_wd(index)
        self._update_count(index)
        _invoke("adagrad_update", [weight, grad, state], [weight, state],
                lr=lr, wd=wd, epsilon=self.float_stable_eps,
                **self._common_kwargs(index))


@register
class RMSProp(Optimizer):
    """(reference: optimizer.py RMSProp — centered=True selects Graves'
    variant rmspropalex_update)."""

    def __init__(self, learning_rate=0.001, gamma1=0.9, gamma2=0.9,
                 epsilon=1e-8, centered=False, clip_weights=None, **kwargs):
        super().__init__(learning_rate=learning_rate, **kwargs)
        self.gamma1, self.gamma2 = gamma1, gamma2
        self.centered = centered
        self.epsilon = epsilon
        self.clip_weights = clip_weights

    def create_state(self, index, weight):
        if self.centered:
            return (nd.zeros(weight.shape, dtype=weight.dtype, ctx=weight.context),
                    nd.zeros(weight.shape, dtype=weight.dtype, ctx=weight.context),
                    nd.zeros(weight.shape, dtype=weight.dtype, ctx=weight.context))
        return nd.zeros(weight.shape, dtype=weight.dtype, ctx=weight.context)

    def update(self, index, weight, grad, state):
        lr, wd = self._get_lr(index), self._get_wd(index)
        self._update_count(index)
        kw = self._common_kwargs(index)
        if self.clip_weights:
            kw["clip_weights"] = self.clip_weights
        if self.centered:
            n, g, delta = state
            _invoke("rmspropalex_update", [weight, grad, n, g, delta],
                    [weight, n, g, delta], lr=lr, gamma1=self.gamma1,
                    gamma2=self.gamma2, epsilon=self.epsilon, wd=wd, **kw)
        else:
            _invoke("rmsprop_update", [weight, grad, state], [weight, state],
                    lr=lr, gamma1=self.gamma1, epsilon=self.epsilon, wd=wd,
                    **kw)


@register
class AdaDelta(Optimizer):
    """(reference: optimizer.py AdaDelta)."""

    def __init__(self, rho=0.90, epsilon=1e-5, **kwargs):
        super().__init__(**kwargs)
        self.rho, self.epsilon = rho, epsilon

    def create_state(self, index, weight):
        return (nd.zeros(weight.shape, dtype=weight.dtype, ctx=weight.context),
                nd.zeros(weight.shape, dtype=weight.dtype, ctx=weight.context))

    def update(self, index, weight, grad, state):
        wd = self._get_wd(index)
        self._update_count(index)
        acc_g, acc_delta = state
        _invoke("adadelta_update", [weight, grad, acc_g, acc_delta],
                [weight, acc_g, acc_delta], rho=self.rho,
                epsilon=self.epsilon, wd=wd, **self._common_kwargs(index))


@register
class Ftrl(Optimizer):
    """(reference: optimizer.py Ftrl)."""

    def __init__(self, lamda1=0.01, learning_rate=0.1, beta=1.0, **kwargs):
        super().__init__(learning_rate=learning_rate, **kwargs)
        self.lamda1, self.beta = lamda1, beta

    def create_state(self, index, weight):
        return (nd.zeros(weight.shape, dtype=weight.dtype, ctx=weight.context),
                nd.zeros(weight.shape, dtype=weight.dtype, ctx=weight.context))

    def update(self, index, weight, grad, state):
        lr, wd = self._get_lr(index), self._get_wd(index)
        self._update_count(index)
        z, n = state
        _invoke("ftrl_update", [weight, grad, z, n], [weight, z, n],
                lr=lr, lamda1=self.lamda1, beta=self.beta, wd=wd,
                **self._common_kwargs(index))


@register
class Adamax(Optimizer):
    """(reference: optimizer.py Adamax)."""

    def __init__(self, learning_rate=0.002, beta1=0.9, beta2=0.999, **kwargs):
        super().__init__(learning_rate=learning_rate, **kwargs)
        self.beta1, self.beta2 = beta1, beta2

    def create_state(self, index, weight):
        return (nd.zeros(weight.shape, dtype=weight.dtype, ctx=weight.context),
                nd.zeros(weight.shape, dtype=weight.dtype, ctx=weight.context))

    def update(self, index, weight, grad, state):
        lr, wd = self._get_lr(index), self._get_wd(index)
        self._update_count(index)
        t = self._index_update_count[index]
        lr /= (1.0 - self.beta1 ** t)
        mean, u = state
        _invoke("adamax_update", [weight, grad, mean, u], [weight, mean, u],
                lr=lr, beta1=self.beta1, beta2=self.beta2, wd=wd,
                **self._common_kwargs(index))


@register
class Nadam(Optimizer):
    """Adam with Nesterov momentum (reference: optimizer.py Nadam)."""

    def __init__(self, learning_rate=0.001, beta1=0.9, beta2=0.999,
                 epsilon=1e-8, schedule_decay=0.004, **kwargs):
        super().__init__(learning_rate=learning_rate, **kwargs)
        self.beta1, self.beta2, self.epsilon = beta1, beta2, epsilon
        self.schedule_decay = schedule_decay

    def create_state(self, index, weight):
        # the cumulative momentum schedule lives in per-param state (not on
        # the instance, unlike the reference) so the traced fused-update path
        # threads it functionally across steps
        return (nd.zeros(weight.shape, dtype=weight.dtype, ctx=weight.context),
                nd.zeros(weight.shape, dtype=weight.dtype, ctx=weight.context),
                nd.ones((1,), dtype=np.float32, ctx=weight.context))

    def update(self, index, weight, grad, state):
        lr, wd = self._get_lr(index), self._get_wd(index)
        self._update_count(index)
        t = self._index_update_count[index]
        g = grad * self.rescale_grad + wd * weight
        if self.clip_gradient is not None:
            g = nd.clip(g, -self.clip_gradient, self.clip_gradient)
        momentum_t = self.beta1 * (1.0 - 0.5 * 0.96 ** (t * self.schedule_decay))
        momentum_t_1 = self.beta1 * (
            1.0 - 0.5 * 0.96 ** ((t + 1) * self.schedule_decay))
        mean, var, msch = state
        m_schedule = msch * momentum_t
        m_schedule_next = m_schedule * momentum_t_1
        mean._data = (self.beta1 * mean + (1.0 - self.beta1) * g).data
        var._data = (self.beta2 * var + (1.0 - self.beta2) * g * g).data
        mean._version += 1
        var._version += 1
        g_prime = g / (1.0 - m_schedule)
        m_t_prime = mean / (1.0 - m_schedule_next)
        v_t_prime = var / (1.0 - self.beta2 ** t)
        m_t_bar = (1.0 - momentum_t) * g_prime + momentum_t_1 * m_t_prime
        weight -= lr * m_t_bar / (nd.sqrt(v_t_prime) + self.epsilon)
        msch._data = m_schedule.data
        msch._version += 1


@register
class Test(Optimizer):
    """(reference: optimizer.py Test — simplest possible, for unit tests)."""

    def create_state(self, index, weight):
        return nd.zeros(weight.shape, dtype=weight.dtype, ctx=weight.context)

    def update(self, index, weight, grad, state):
        weight += grad * self.rescale_grad
        state._data = weight.data
        state._version += 1


# ccSGD was a C++ twin of SGD in the reference (optimizer.py ccSGD)
Optimizer.opt_registry["ccsgd"] = SGD


class Updater(object):
    """Applies an optimizer to indexed weights, creating per-index state
    lazily (reference: optimizer.py:940 get_updater/Updater; serialized to
    KVStore servers via set_optimizer)."""

    def __init__(self, optimizer: Optimizer):
        self.optimizer = optimizer
        self.states: Dict[Any, Any] = {}

    def __call__(self, index, grad, weight):
        if index not in self.states:
            self.states[index] = self.optimizer.create_state(index, weight)
        self.optimizer.update(index, weight, grad, self.states[index])

    def set_states(self, states: bytes):
        self.states = pickle.loads(states)

    def get_states(self) -> bytes:
        states = {}
        for k, v in self.states.items():
            states[k] = _state_to_np(v)
        return pickle.dumps(states)


def _state_to_np(v):
    if v is None:
        return None
    if isinstance(v, NDArray):
        return v.asnumpy()
    if isinstance(v, tuple):
        return tuple(_state_to_np(x) for x in v)
    return v


def get_updater(optimizer: Optimizer) -> Updater:
    """(reference: optimizer.py get_updater)."""
    return Updater(optimizer)

"""Optimizers.

Reference: ``python/mxnet/optimizer.py`` (992 LoC — registry at line 30/331,
SGD/DCASGD/NAG/SGLD/ccSGD/Adam/AdaGrad/RMSProp/AdaDelta/Ftrl/Adamax/Nadam/
Test at lines 334-923, ``Updater`` at 940). The numeric updates run through
the registered optimizer-update *ops* (mxnet_tpu/ops/optimizer_op.py ≡
src/operator/optimizer_op.cc), so each parameter update is one fused XLA
computation.
"""
from __future__ import annotations

import math
import pickle
from typing import Any, Dict, Optional

import numpy as np

from . import ndarray as nd
from .ndarray import NDArray
from .ops import get_op
from .ndarray.ndarray import imperative_invoke

__all__ = ["Optimizer", "SGD", "NAG", "SGLD", "DCASGD", "Adam", "AdaGrad",
           "RMSProp", "AdaDelta", "Ftrl", "Adamax", "Nadam", "Test",
           "create", "get_updater", "Updater", "register"]


class Optimizer(object):
    """Base optimizer (reference: optimizer.py:30)."""

    opt_registry: Dict[str, type] = {}

    # Safe to trace this optimizer's update into the fused whole-model
    # step (mxnet_tpu/_fused.py FusedUpdater)? Updates that draw fresh
    # randomness per step (SGLD) must keep the eager path — a jitted
    # replay would bake one PRNG key into the compiled program and repeat
    # identical noise every step.
    fused_supported = True

    # Instance attrs NOT baked into a compiled fused step: per-step
    # dynamic hyperparameters (entering as traced scalars), per-index
    # bookkeeping, and symbol-layer metadata. Everything else in
    # ``__dict__`` is a static hyperparameter and keys the compile cache.
    _FUSED_DYNAMIC_ATTRS = frozenset({
        "lr", "wd", "rescale_grad", "clip_gradient", "lr_scheduler",
        "lr_mult", "wd_mult", "idx2name", "sym", "num_update",
        "begin_num_update", "_index_update_count", "_traced_lr",
        "_traced_t", "weight_previous",
    })

    @staticmethod
    def register(klass):
        """(reference: optimizer.py Optimizer.register)."""
        name = klass.__name__.lower()
        Optimizer.opt_registry[name] = klass
        return klass

    @staticmethod
    def create_optimizer(name: str, **kwargs) -> "Optimizer":
        if name.lower() in Optimizer.opt_registry:
            return Optimizer.opt_registry[name.lower()](**kwargs)
        raise ValueError("Cannot find optimizer %s" % name)

    def __init__(self, rescale_grad=1.0, param_idx2name=None, wd=0.0,
                 clip_gradient=None, learning_rate=0.01, lr_scheduler=None,
                 sym=None, begin_num_update=0):
        self.rescale_grad = rescale_grad
        self.lr = learning_rate
        self.lr_scheduler = lr_scheduler
        if lr_scheduler is not None:
            self.lr_scheduler.base_lr = learning_rate
        self.wd = wd
        self.lr_mult: Dict[Any, float] = {}
        self.wd_mult: Dict[Any, float] = {}
        self.begin_num_update = begin_num_update
        self.num_update = begin_num_update
        self._index_update_count: Dict[Any, int] = {}
        self.clip_gradient = clip_gradient
        if param_idx2name is None:
            param_idx2name = {}
        self.idx2name = param_idx2name.copy()
        self.sym = sym
        # traced-mode overrides (see raw_update): when set, _get_lr/_update_
        # count use these possibly-traced scalars instead of python floats so
        # one XLA compilation serves every step of an LR schedule.
        self._traced_lr = None
        self._traced_t = None

    def create_state(self, index, weight):
        return None

    def update(self, index, weight, grad, state):
        raise NotImplementedError

    def set_lr_mult(self, args_lr_mult: Dict[str, float]):
        """(reference: optimizer.py set_lr_mult — merges symbol attr
        __lr_mult__)."""
        self.lr_mult = {}
        if self.sym is not None:
            attr = self.sym.attr_dict()
            for name in self.sym.list_arguments():
                if name in attr and "__lr_mult__" in attr[name]:
                    self.lr_mult[name] = float(attr[name]["__lr_mult__"])
        self.lr_mult.update(args_lr_mult)

    def set_wd_mult(self, args_wd_mult: Dict[str, float]):
        """(reference: optimizer.py set_wd_mult — bias/gamma/beta default to
        wd_mult 0)."""
        self.wd_mult = {}
        for n in self.idx2name.values():
            if not (n.endswith("_weight") or n.endswith("_gamma")):
                self.wd_mult[n] = 0.0
        if self.sym is not None:
            attr = self.sym.attr_dict()
            for name in self.sym.list_arguments():
                if name in attr and "__wd_mult__" in attr[name]:
                    self.wd_mult[name] = float(attr[name]["__wd_mult__"])
        self.wd_mult.update(args_wd_mult)

    def _update_count(self, index):
        if self._traced_t is not None:
            self._index_update_count[index] = self._traced_t
            return
        if index not in self._index_update_count:
            self._index_update_count[index] = self.begin_num_update
        self._index_update_count[index] += 1
        self.num_update = max(self._index_update_count[index], self.num_update)

    def _resolve_mult(self, mults: Dict[Any, float], index) -> float:
        """Per-param lr/wd multiplier lookup (index first, then mapped
        name; reference: optimizer.py _get_lr/_get_wd)."""
        if index in mults:
            return mults[index]
        if index in self.idx2name:
            return mults.get(self.idx2name[index], 1.0)
        return 1.0

    def _get_lr(self, index) -> float:
        if self._traced_lr is not None:
            lr = self._traced_lr
        elif self.lr_scheduler is not None:
            lr = self.lr_scheduler(self.num_update)
        else:
            lr = self.lr
        return lr * self._resolve_mult(self.lr_mult, index)

    def _get_wd(self, index) -> float:
        return self.wd * self._resolve_mult(self.wd_mult, index)

    def raw_update(self, index, weight, grad, state, lr=None, t=None,
                   wd=None, rescale_grad=None, clip_gradient=None,
                   _check_pure=False):
        """Functionally apply this optimizer's update to raw (possibly
        traced) jax arrays, returning ``(new_weight, new_state)``.

        The TPU fit hot path (Module._fit_step) and the fused trainer step
        (mxnet_tpu/_fused.py) trace this inside ONE jitted program — the
        analogue of the reference running `sgd_mom_update` engine ops right
        after the backward ops (SURVEY.md §2.5 optimizer update ops, §7
        "fit() must run fully jitted"). ``lr``, the update count ``t``, and
        the optional ``wd``/``rescale_grad``/``clip_gradient`` overrides
        enter as traced scalars so LR schedules, weight-decay changes and
        batch-size changes do not force a recompile every step.
        """
        from .ndarray import NDArray

        def wrap(v):
            if v is None:
                return None
            if isinstance(v, tuple):
                return tuple(wrap(x) for x in v)
            return NDArray(v)

        def unwrap(v):
            if v is None:
                return None
            if isinstance(v, tuple):
                return tuple(unwrap(x) for x in v)
            return v._data

        w, g, s = NDArray(weight), NDArray(grad), wrap(state)
        # snapshot ALL instance attrs: a traced update() must not leak
        # tracers into persistent optimizer state (state flows through the
        # returned pytree instead)
        saved = {k: (dict(v) if isinstance(v, dict) else v)
                 for k, v in self.__dict__.items()}
        self._traced_lr, self._traced_t = lr, t
        if wd is not None:
            self.wd = wd
        if rescale_grad is not None:
            self.rescale_grad = rescale_grad
        if clip_gradient is not None and self.clip_gradient is not None:
            # only the VALUE is dynamic; clip presence is structural
            self.clip_gradient = clip_gradient
        try:
            self.update(index, w, g, s)
            if _check_pure:
                # the snapshot/restore below DISCARDS any instance-attr
                # mutation update() made beyond the sanctioned dynamic/
                # bookkeeping set — an optimizer keeping per-step state on
                # the instance (reference-style warmup counters, schedule
                # accumulators) would silently train with a frozen value,
                # so the fused replay refuses it (eager path instead)
                self._check_update_purity(saved)
        finally:
            self.__dict__.clear()
            self.__dict__.update(saved)
        return w._data, unwrap(s)

    def _check_update_purity(self, saved):
        """Raise Uncacheable if update() rebound or mutated any instance
        attr outside _FUSED_DYNAMIC_ATTRS (whose per-step values are
        threaded dynamically or restored by design). Conservative: any
        non-scalar rebinding counts as a mutation."""
        from ._fused import Uncacheable

        def same(a, b):
            if a is b:
                return True
            if a is None or isinstance(a, (bool, int, float, str, bytes)):
                return type(a) is type(b) and a == b
            return False

        sanctioned = self._FUSED_DYNAMIC_ATTRS
        if set(self.__dict__) != set(saved):
            raise Uncacheable("update() added/removed instance attrs")
        for k, old in saved.items():
            if k in sanctioned:
                continue
            cur = self.__dict__[k]
            if isinstance(old, dict):
                if not isinstance(cur, dict) or set(cur) != set(old) or \
                        any(not same(old[dk], cur[dk]) for dk in old):
                    raise Uncacheable(
                        "update() mutated optimizer attr %s" % k)
            elif not same(old, cur):
                raise Uncacheable("update() mutated optimizer attr %s" % k)

    def _common_kwargs(self, index):
        kw = {"rescale_grad": self.rescale_grad}
        if self.clip_gradient is not None:
            kw["clip_gradient"] = self.clip_gradient
        return kw

    # --------------------------------------------------- fused step form
    def _clip_active(self) -> bool:
        """Whether gradient clipping actually fires: the eager update ops
        treat None AND non-positive thresholds as disabled, so only a
        positive value is lifted to a dynamic traced threshold."""
        return self.clip_gradient is not None and self.clip_gradient > 0

    def _fused_static_key(self):
        """Hashable tuple of every attr a compiled fused step bakes in
        (per-step dynamic hypers and bookkeeping excluded). Must be
        collision-free: unhashable statics make the optimizer unfusable
        rather than risk aliasing two configurations onto one program."""
        from ._fused import Uncacheable

        def value_key(v, name):
            # value-hashable types only: objects with identity-based
            # hashes (NDArray, arbitrary instances) would alias a stale
            # baked constant after in-place mutation — those make the
            # optimizer unfusable instead
            if v is None or isinstance(v, (bool, int, float, str, bytes)):
                return v
            if isinstance(v, tuple):
                return tuple(value_key(x, name) for x in v)
            raise Uncacheable("non-value-hashable optimizer attr %s" % name)

        items = []
        for k in sorted(self.__dict__):
            if k in self._FUSED_DYNAMIC_ATTRS:
                continue
            items.append((k, value_key(self.__dict__[k], k)))
        # clip structure: an ACTIVE threshold is lifted to a dynamic arg
        # ("dyn"); an inactive one (None or non-positive) is baked into the
        # traced program (python-side optimizers branch on `is not None`),
        # so its concrete value must key the cache to avoid aliasing the
        # no-clip program with a baked-disabled-clip program
        clip_key = "dyn" if self._clip_active() else self.clip_gradient
        return (type(self).__module__ + "." + type(self).__qualname__,
                clip_key, tuple(items))

    def _fused_hypers(self, pos, index, hypers):
        """Per-param (lr, wd) from the dynamic base scalars + static
        multipliers — the traced twin of _get_lr/_get_wd. ``lrs`` is one
        base lr per param so the scheduler's eager read-then-advance
        sequence is reproduced exactly."""
        return (hypers["lrs"][pos] * self._resolve_mult(self.lr_mult, index),
                hypers["wd"] * self._resolve_mult(self.wd_mult, index))

    def _fused_common(self, hypers):
        kw = {"rescale_grad": hypers["rescale_grad"]}
        if "clip" in hypers:
            kw["clip_gradient"] = hypers["clip"]
        return kw

    def update_fused(self, indices, weights, grads, states, hypers):
        """Pure functional whole-model step — the tree-map form of the
        per-index :meth:`update`, traced into ONE XLA program by
        ``FusedUpdater``. ``weights``/``grads`` are lists of raw jax
        arrays, ``states`` a list of raw-array pytrees, ``hypers`` the
        dynamic scalars (``lr``, ``wd``, ``rescale_grad``, optional
        ``clip``, and per-param update counts ``ts``). Returns
        ``(new_weights, new_states)``; :meth:`update` remains the
        reference semantics the parity suite checks against."""
        new_ws, new_ss = [], []
        for pos, idx in enumerate(indices):
            nw, ns = self._fused_one(pos, idx, weights[pos], grads[pos],
                                     states[pos], hypers)
            new_ws.append(nw)
            new_ss.append(ns)
        return new_ws, new_ss

    def _fused_one(self, pos, idx, weight, grad, state, hypers):
        """Single-param functional update. The base form replays the
        eager :meth:`update` under the trace via :meth:`raw_update`
        (exact parity by construction, covers custom subclasses);
        built-ins override with direct calls into the same update ops."""
        return self.raw_update(
            idx, weight, grad, state, lr=hypers["lrs"][pos],
            t=hypers["ts"][pos], wd=hypers["wd"],
            rescale_grad=hypers["rescale_grad"],
            clip_gradient=hypers.get("clip"), _check_pure=True)


register = Optimizer.register
create = Optimizer.create_optimizer


def _invoke(opname, arrays, out_arrays, **attrs):
    """Run an optimizer-update op and commit results in place."""
    op = get_op(opname)
    res = imperative_invoke(op, *arrays, **attrs)
    if not isinstance(res, (list, tuple)):
        res = [res]
    for dst, src in zip(out_arrays, res):
        dst._data = src.data
        dst._version += 1


@register
class SGD(Optimizer):
    """SGD with momentum, weight decay and multi-precision support
    (reference: optimizer.py:334 SGD)."""

    def __init__(self, momentum=0.0, multi_precision=False, **kwargs):
        super().__init__(**kwargs)
        self.momentum = momentum
        self.multi_precision = multi_precision

    def create_state(self, index, weight):
        momentum = None
        weight_master = None
        if self.multi_precision and weight.dtype == np.float16:
            weight_master = weight.astype(np.float32)
        if self.momentum != 0.0:
            base = weight_master if weight_master is not None else weight
            momentum = nd.zeros(base.shape, dtype=base.dtype, ctx=base.context)
        if weight_master is not None:
            return (momentum, weight_master)
        return momentum

    def update(self, index, weight, grad, state):
        lr, wd = self._get_lr(index), self._get_wd(index)
        self._update_count(index)
        kw = self._common_kwargs(index)
        master = None
        mom = state
        if isinstance(state, tuple):
            mom, master = state
        w = master if master is not None else weight
        g = grad.astype(w.dtype) if grad.dtype != w.dtype else grad
        if self.momentum == 0.0:
            _invoke("sgd_update", [w, g], [w], lr=lr, wd=wd, **kw)
        else:
            _invoke("sgd_mom_update", [w, g, mom], [w, mom], lr=lr, wd=wd,
                    momentum=self.momentum, **kw)
        if master is not None:
            weight._data = w.data.astype(weight.dtype)
            weight._version += 1

    def _fused_one(self, pos, idx, weight, grad, state, hypers):
        lr, wd = self._fused_hypers(pos, idx, hypers)
        kw = self._fused_common(hypers)
        mom, master = state if isinstance(state, tuple) else (state, None)
        w = master if master is not None else weight
        g = grad.astype(w.dtype) if grad.dtype != w.dtype else grad
        if self.momentum == 0.0:
            new_w = get_op("sgd_update").fn(w, g, lr=lr, wd=wd, **kw)
            new_mom = None
        else:
            new_w, new_mom = get_op("sgd_mom_update").fn(
                w, g, mom, lr=lr, wd=wd, momentum=self.momentum, **kw)
        if master is not None:
            return new_w.astype(weight.dtype), (new_mom, new_w)
        return new_w, new_mom


@register
class NAG(Optimizer):
    """Nesterov accelerated SGD (reference: optimizer.py NAG)."""

    def __init__(self, momentum=0.0, **kwargs):
        super().__init__(**kwargs)
        self.momentum = momentum

    def create_state(self, index, weight):
        if self.momentum == 0.0:
            return None
        return nd.zeros(weight.shape, dtype=weight.dtype, ctx=weight.context)

    def update(self, index, weight, grad, state):
        lr, wd = self._get_lr(index), self._get_wd(index)
        self._update_count(index)
        kw = self._common_kwargs(index)
        if state is None:
            _invoke("sgd_update", [weight, grad], [weight], lr=lr, wd=wd, **kw)
        else:
            _invoke("nag_mom_update", [weight, grad, state], [weight, state],
                    lr=lr, wd=wd, momentum=self.momentum, **kw)

    def _fused_one(self, pos, idx, weight, grad, state, hypers):
        lr, wd = self._fused_hypers(pos, idx, hypers)
        kw = self._fused_common(hypers)
        if state is None:
            return get_op("sgd_update").fn(weight, grad, lr=lr, wd=wd,
                                           **kw), None
        return get_op("nag_mom_update").fn(
            weight, grad, state, lr=lr, wd=wd, momentum=self.momentum, **kw)


@register
class SGLD(Optimizer):
    """Langevin dynamics sampler (reference: optimizer.py SGLD)."""

    # fresh Langevin noise every step: a compiled replay would bake one
    # PRNG key and repeat the same noise — keep the eager per-param path
    fused_supported = False

    def update(self, index, weight, grad, state):
        lr, wd = self._get_lr(index), self._get_wd(index)
        self._update_count(index)
        kw = self._common_kwargs(index)
        _invoke("sgld_update", [weight, grad], [weight], lr=lr, wd=wd, **kw)


@register
class DCASGD(Optimizer):
    """Delay-compensated async SGD (reference: optimizer.py DCASGD)."""

    def __init__(self, momentum=0.0, lamda=0.04, **kwargs):
        super().__init__(**kwargs)
        self.momentum = momentum
        self.weight_previous: Dict[Any, NDArray] = {}
        self.lamda = lamda

    def create_state(self, index, weight):
        mom = nd.zeros(weight.shape, dtype=weight.dtype, ctx=weight.context) \
            if self.momentum != 0.0 else None
        return (mom, weight.copy())

    def update(self, index, weight, grad, state):
        lr, wd = self._get_lr(index), self._get_wd(index)
        self._update_count(index)
        mom, prev = state
        g = grad * self.rescale_grad
        if self.clip_gradient is not None:
            g = nd.clip(g, -self.clip_gradient, self.clip_gradient)
        comp = g + wd * weight + self.lamda * g * g * (weight - prev)
        if mom is None:
            step = (-lr) * comp
        else:
            mom *= self.momentum
            mom -= lr * comp
            step = mom
        prev._data = weight.data
        prev._version += 1
        weight += step


@register
class Adam(Optimizer):
    """(reference: optimizer.py Adam; update op optimizer_op.cc adam_update)."""

    def __init__(self, learning_rate=0.001, beta1=0.9, beta2=0.999,
                 epsilon=1e-8, **kwargs):
        super().__init__(learning_rate=learning_rate, **kwargs)
        self.beta1, self.beta2, self.epsilon = beta1, beta2, epsilon

    def create_state(self, index, weight):
        return (nd.zeros(weight.shape, dtype=weight.dtype, ctx=weight.context),
                nd.zeros(weight.shape, dtype=weight.dtype, ctx=weight.context))

    def update(self, index, weight, grad, state):
        lr, wd = self._get_lr(index), self._get_wd(index)
        self._update_count(index)
        t = self._index_update_count[index]
        coef1 = 1.0 - self.beta1 ** t
        coef2 = 1.0 - self.beta2 ** t
        # ** 0.5, not math.sqrt: t may be a traced scalar on the fused path
        lr *= coef2 ** 0.5 / coef1
        mean, var = state
        _invoke("adam_update", [weight, grad, mean, var], [weight, mean, var],
                lr=lr, beta1=self.beta1, beta2=self.beta2,
                epsilon=self.epsilon, wd=wd, **self._common_kwargs(index))

    def _fused_one(self, pos, idx, weight, grad, state, hypers):
        lr, wd = self._fused_hypers(pos, idx, hypers)
        t = hypers["ts"][pos]
        lr = lr * (1.0 - self.beta2 ** t) ** 0.5 / (1.0 - self.beta1 ** t)
        mean, var = state
        new_w, new_mean, new_var = get_op("adam_update").fn(
            weight, grad, mean, var, lr=lr, beta1=self.beta1,
            beta2=self.beta2, epsilon=self.epsilon, wd=wd,
            **self._fused_common(hypers))
        return new_w, (new_mean, new_var)


@register
class AdaGrad(Optimizer):
    """(reference: optimizer.py AdaGrad)."""

    def __init__(self, eps=1e-7, **kwargs):
        super().__init__(**kwargs)
        self.float_stable_eps = eps

    def create_state(self, index, weight):
        return nd.zeros(weight.shape, dtype=weight.dtype, ctx=weight.context)

    def update(self, index, weight, grad, state):
        lr, wd = self._get_lr(index), self._get_wd(index)
        self._update_count(index)
        _invoke("adagrad_update", [weight, grad, state], [weight, state],
                lr=lr, wd=wd, epsilon=self.float_stable_eps,
                **self._common_kwargs(index))

    def _fused_one(self, pos, idx, weight, grad, state, hypers):
        lr, wd = self._fused_hypers(pos, idx, hypers)
        return get_op("adagrad_update").fn(
            weight, grad, state, lr=lr, wd=wd,
            epsilon=self.float_stable_eps, **self._fused_common(hypers))


@register
class RMSProp(Optimizer):
    """(reference: optimizer.py RMSProp — centered=True selects Graves'
    variant rmspropalex_update)."""

    def __init__(self, learning_rate=0.001, gamma1=0.9, gamma2=0.9,
                 epsilon=1e-8, centered=False, clip_weights=None, **kwargs):
        super().__init__(learning_rate=learning_rate, **kwargs)
        self.gamma1, self.gamma2 = gamma1, gamma2
        self.centered = centered
        self.epsilon = epsilon
        self.clip_weights = clip_weights

    def create_state(self, index, weight):
        if self.centered:
            return (nd.zeros(weight.shape, dtype=weight.dtype, ctx=weight.context),
                    nd.zeros(weight.shape, dtype=weight.dtype, ctx=weight.context),
                    nd.zeros(weight.shape, dtype=weight.dtype, ctx=weight.context))
        return nd.zeros(weight.shape, dtype=weight.dtype, ctx=weight.context)

    def update(self, index, weight, grad, state):
        lr, wd = self._get_lr(index), self._get_wd(index)
        self._update_count(index)
        kw = self._common_kwargs(index)
        if self.clip_weights:
            kw["clip_weights"] = self.clip_weights
        if self.centered:
            n, g, delta = state
            _invoke("rmspropalex_update", [weight, grad, n, g, delta],
                    [weight, n, g, delta], lr=lr, gamma1=self.gamma1,
                    gamma2=self.gamma2, epsilon=self.epsilon, wd=wd, **kw)
        else:
            _invoke("rmsprop_update", [weight, grad, state], [weight, state],
                    lr=lr, gamma1=self.gamma1, epsilon=self.epsilon, wd=wd,
                    **kw)

    def _fused_one(self, pos, idx, weight, grad, state, hypers):
        lr, wd = self._fused_hypers(pos, idx, hypers)
        kw = self._fused_common(hypers)
        if self.clip_weights:
            kw["clip_weights"] = self.clip_weights
        if self.centered:
            n, g_acc, delta = state
            new_w, new_n, new_g, new_d = get_op("rmspropalex_update").fn(
                weight, grad, n, g_acc, delta, lr=lr, gamma1=self.gamma1,
                gamma2=self.gamma2, epsilon=self.epsilon, wd=wd, **kw)
            return new_w, (new_n, new_g, new_d)
        return get_op("rmsprop_update").fn(
            weight, grad, state, lr=lr, gamma1=self.gamma1,
            epsilon=self.epsilon, wd=wd, **kw)


@register
class AdaDelta(Optimizer):
    """(reference: optimizer.py AdaDelta)."""

    def __init__(self, rho=0.90, epsilon=1e-5, **kwargs):
        super().__init__(**kwargs)
        self.rho, self.epsilon = rho, epsilon

    def create_state(self, index, weight):
        return (nd.zeros(weight.shape, dtype=weight.dtype, ctx=weight.context),
                nd.zeros(weight.shape, dtype=weight.dtype, ctx=weight.context))

    def update(self, index, weight, grad, state):
        wd = self._get_wd(index)
        self._update_count(index)
        acc_g, acc_delta = state
        _invoke("adadelta_update", [weight, grad, acc_g, acc_delta],
                [weight, acc_g, acc_delta], rho=self.rho,
                epsilon=self.epsilon, wd=wd, **self._common_kwargs(index))

    def _fused_one(self, pos, idx, weight, grad, state, hypers):
        _lr, wd = self._fused_hypers(pos, idx, hypers)
        acc_g, acc_delta = state
        new_w, new_g, new_d = get_op("adadelta_update").fn(
            weight, grad, acc_g, acc_delta, rho=self.rho,
            epsilon=self.epsilon, wd=wd, **self._fused_common(hypers))
        return new_w, (new_g, new_d)


@register
class Ftrl(Optimizer):
    """(reference: optimizer.py Ftrl)."""

    def __init__(self, lamda1=0.01, learning_rate=0.1, beta=1.0, **kwargs):
        super().__init__(learning_rate=learning_rate, **kwargs)
        self.lamda1, self.beta = lamda1, beta

    def create_state(self, index, weight):
        return (nd.zeros(weight.shape, dtype=weight.dtype, ctx=weight.context),
                nd.zeros(weight.shape, dtype=weight.dtype, ctx=weight.context))

    def update(self, index, weight, grad, state):
        lr, wd = self._get_lr(index), self._get_wd(index)
        self._update_count(index)
        z, n = state
        _invoke("ftrl_update", [weight, grad, z, n], [weight, z, n],
                lr=lr, lamda1=self.lamda1, beta=self.beta, wd=wd,
                **self._common_kwargs(index))

    def _fused_one(self, pos, idx, weight, grad, state, hypers):
        lr, wd = self._fused_hypers(pos, idx, hypers)
        z, n = state
        new_w, new_z, new_n = get_op("ftrl_update").fn(
            weight, grad, z, n, lr=lr, lamda1=self.lamda1, beta=self.beta,
            wd=wd, **self._fused_common(hypers))
        return new_w, (new_z, new_n)


@register
class Adamax(Optimizer):
    """(reference: optimizer.py Adamax)."""

    def __init__(self, learning_rate=0.002, beta1=0.9, beta2=0.999, **kwargs):
        super().__init__(learning_rate=learning_rate, **kwargs)
        self.beta1, self.beta2 = beta1, beta2

    def create_state(self, index, weight):
        return (nd.zeros(weight.shape, dtype=weight.dtype, ctx=weight.context),
                nd.zeros(weight.shape, dtype=weight.dtype, ctx=weight.context))

    def update(self, index, weight, grad, state):
        lr, wd = self._get_lr(index), self._get_wd(index)
        self._update_count(index)
        t = self._index_update_count[index]
        lr /= (1.0 - self.beta1 ** t)
        mean, u = state
        _invoke("adamax_update", [weight, grad, mean, u], [weight, mean, u],
                lr=lr, beta1=self.beta1, beta2=self.beta2, wd=wd,
                **self._common_kwargs(index))

    def _fused_one(self, pos, idx, weight, grad, state, hypers):
        lr, wd = self._fused_hypers(pos, idx, hypers)
        t = hypers["ts"][pos]
        lr = lr / (1.0 - self.beta1 ** t)
        mean, u = state
        new_w, new_mean, new_u = get_op("adamax_update").fn(
            weight, grad, mean, u, lr=lr, beta1=self.beta1,
            beta2=self.beta2, wd=wd, **self._fused_common(hypers))
        return new_w, (new_mean, new_u)


@register
class Nadam(Optimizer):
    """Adam with Nesterov momentum (reference: optimizer.py Nadam)."""

    def __init__(self, learning_rate=0.001, beta1=0.9, beta2=0.999,
                 epsilon=1e-8, schedule_decay=0.004, **kwargs):
        super().__init__(learning_rate=learning_rate, **kwargs)
        self.beta1, self.beta2, self.epsilon = beta1, beta2, epsilon
        self.schedule_decay = schedule_decay

    def create_state(self, index, weight):
        # the cumulative momentum schedule lives in per-param state (not on
        # the instance, unlike the reference) so the traced fused-update path
        # threads it functionally across steps
        return (nd.zeros(weight.shape, dtype=weight.dtype, ctx=weight.context),
                nd.zeros(weight.shape, dtype=weight.dtype, ctx=weight.context),
                nd.ones((1,), dtype=np.float32, ctx=weight.context))

    def update(self, index, weight, grad, state):
        lr, wd = self._get_lr(index), self._get_wd(index)
        self._update_count(index)
        t = self._index_update_count[index]
        g = grad * self.rescale_grad + wd * weight
        if self.clip_gradient is not None:
            g = nd.clip(g, -self.clip_gradient, self.clip_gradient)
        momentum_t = self.beta1 * (1.0 - 0.5 * 0.96 ** (t * self.schedule_decay))
        momentum_t_1 = self.beta1 * (
            1.0 - 0.5 * 0.96 ** ((t + 1) * self.schedule_decay))
        mean, var, msch = state
        m_schedule = msch * momentum_t
        m_schedule_next = m_schedule * momentum_t_1
        mean._data = (self.beta1 * mean + (1.0 - self.beta1) * g).data
        var._data = (self.beta2 * var + (1.0 - self.beta2) * g * g).data
        mean._version += 1
        var._version += 1
        g_prime = g / (1.0 - m_schedule)
        m_t_prime = mean / (1.0 - m_schedule_next)
        v_t_prime = var / (1.0 - self.beta2 ** t)
        m_t_bar = (1.0 - momentum_t) * g_prime + momentum_t_1 * m_t_prime
        weight -= lr * m_t_bar / (nd.sqrt(v_t_prime) + self.epsilon)
        msch._data = m_schedule.data
        msch._version += 1


@register
class Test(Optimizer):
    """(reference: optimizer.py Test — simplest possible, for unit tests)."""

    def create_state(self, index, weight):
        return nd.zeros(weight.shape, dtype=weight.dtype, ctx=weight.context)

    def update(self, index, weight, grad, state):
        weight += grad * self.rescale_grad
        state._data = weight.data
        state._version += 1


# ccSGD was a C++ twin of SGD in the reference (optimizer.py ccSGD)
Optimizer.opt_registry["ccsgd"] = SGD


class Updater(object):
    """Applies an optimizer to indexed weights, creating per-index state
    lazily (reference: optimizer.py:940 get_updater/Updater; serialized to
    KVStore servers via set_optimizer)."""

    def __init__(self, optimizer: Optimizer):
        self.optimizer = optimizer
        self.states: Dict[Any, Any] = {}

    def __call__(self, index, grad, weight):
        if index not in self.states:
            self.states[index] = self.optimizer.create_state(index, weight)
        self.optimizer.update(index, weight, grad, self.states[index])

    def set_states(self, states: bytes):
        # NDArray leaves were serialized as tagged numpy (get_states);
        # rewrap exactly those so in-place update commits (eager) and the
        # fused step's state threading both keep working after a load,
        # while genuinely-numpy custom state passes through untouched.
        # Legacy blobs (untagged dicts from older checkpoints) rewrap
        # every numpy leaf — the pre-tagging best effort.
        payload = pickle.loads(states)
        legacy = not (isinstance(payload, dict)
                      and "__nd_tagged__" in payload)
        if not legacy:
            payload = payload["states"]
        self.states = {k: _state_from_np(v, legacy)
                       for k, v in payload.items()}

    def get_states(self) -> bytes:
        states = {}
        for k, v in self.states.items():
            states[k] = _state_to_np(v)
        return pickle.dumps({"__nd_tagged__": 1, "states": states})


class _NDTag(object):
    """Marks a pickled numpy leaf as having been an NDArray before
    serialization, so deserialization rewraps exactly those."""

    __slots__ = ("value",)

    def __init__(self, value):
        self.value = value

    def __getstate__(self):
        return self.value

    def __setstate__(self, value):
        self.value = value


def _state_to_np(v):
    if v is None:
        return None
    if isinstance(v, NDArray):
        return _NDTag(v.asnumpy())
    if isinstance(v, tuple):
        return tuple(_state_to_np(x) for x in v)
    return v


def _state_from_np(v, legacy=False):
    """Inverse of _state_to_np: rewrap exactly the leaves tagged as
    NDArray at serialization time; any other leaf (custom optimizer
    state: raw numpy, scalars, dicts, ...) passes through untouched.
    ``legacy`` (pre-tag checkpoint blobs) rewraps untagged numpy leaves
    as a best effort — built-in optimizer states were always NDArray."""
    if isinstance(v, tuple):
        return tuple(_state_from_np(x, legacy) for x in v)
    if isinstance(v, _NDTag) or (legacy and isinstance(v, np.ndarray)):
        import jax.numpy as jnp
        raw = v.value if isinstance(v, _NDTag) else v
        # jnp.array, NOT jnp.asarray: on the CPU backend asarray can
        # zero-copy ALIAS the unpickled numpy buffer, and a buffer that
        # shares host memory must never be donated to the fused update
        # program (use-after-free once XLA recycles it). An owned copy
        # also keeps the restored state independent of the caller's blob.
        return NDArray(jnp.array(raw))
    return v


def get_updater(optimizer: Optimizer) -> Updater:
    """(reference: optimizer.py get_updater)."""
    return Updater(optimizer)

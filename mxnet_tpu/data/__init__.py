"""``mx.data`` — the sharded multi-worker streaming data plane.

A multi-process loader that feeds a POD, not a chip: worker processes
own disjoint RecordIO shard ranges partitioned deterministically from
``(seed, epoch, world_size, num_workers)``, decode/augment in parallel,
and hand batches to ``fit``'s device-prefetch stage (per-host
``device_put`` onto the mesh's ``data`` axis) in a delivery order that
is a pure function of ``(seed, epoch, world)`` — so checkpoints resume
the stream bit-exactly even after an elastic worker-count or pod-world
change.

Import discipline: this package is LAZY (``mx.data`` resolves through
the top-level ``__getattr__``) and nothing in the training path imports
it — a fit over any other iterator never loads it and never moves a
``data_*`` counter (the zero-cost gate in tools/data_smoke.py asserts
both). Design: docs/architecture/data_plane.md.
"""
from .partition import PartitionPlan, epoch_order
from .loader import DataLoader
from .transforms import ImageTransform, RawTransform, StallTransform

__all__ = ["DataLoader", "PartitionPlan", "epoch_order", "RawTransform",
           "ImageTransform", "StallTransform"]

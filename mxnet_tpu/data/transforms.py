"""Record decoders for the streaming data plane.

A transform is any picklable callable ``raw_record_bytes ->
(data_ndarray, label_ndarray)`` — picklable because worker PROCESSES
receive it (top-level classes with plain attributes, never closures).
The stock ones cover the two shapes the tests and benches need:

* :class:`RawTransform` — float32 payload + IRHeader label, the exact
  format ``im2rec``-style float datasets and the determinism tests use.
* :class:`ImageTransform` — JPEG/PNG decode (cv2 when present, PIL
  fallback) + resize + HWC->CHW, the decode-bound pipeline of
  BENCH_data.json.
* :class:`StallTransform` — wraps another transform with a fixed
  per-record stall, emulating remote-storage fetch latency; the bench
  uses it to model IO-bound decode honestly on small CI hosts, and the
  straggler regression drill uses it to build a "healthy rank, slow
  loader" shape.
"""
from __future__ import annotations

import time

import numpy as np

from .. import recordio as _recordio

__all__ = ["RawTransform", "ImageTransform", "StallTransform"]


def _shape_label(label, width: int) -> np.ndarray:
    """IRHeader label -> float32 array: scalar-shaped for ``width=1``
    (batches stack to ``(B,)``, matching NDArrayIter and what
    SoftmaxOutput/LinearRegressionOutput infer shapes from), a
    ``(width,)`` vector otherwise (padded/truncated)."""
    lab = np.asarray(label, dtype=np.float32).reshape(-1)
    if lab.size < width:
        lab = np.pad(lab, (0, width - lab.size))
    if width == 1:
        return np.float32(lab[0])
    return lab[:width].copy()


class RawTransform(object):
    """Unpack ``recordio.pack`` records: float32 payload reshaped to
    ``data_shape``, the IRHeader label as a float32 vector of
    ``label_width`` (scalar-shaped when 1, so batches stack to the
    ``(B,)`` labels NDArrayIter and the loss heads expect)."""

    def __init__(self, data_shape, label_width: int = 1):
        self.data_shape = tuple(int(d) for d in data_shape)
        self.label_width = int(label_width)

    def __call__(self, raw: bytes):
        header, payload = _recordio.unpack(raw)
        data = np.frombuffer(payload, dtype=np.float32).reshape(
            self.data_shape).copy()
        return data, _shape_label(header.label, self.label_width)


class ImageTransform(object):
    """JPEG/PNG decode + resize to ``data_shape=(C, H, W)`` float32 —
    the minimal twin of ``ImageRecordIter``'s decode/augment stage for
    the multi-process path (mean/scale only; heavier augmentation
    composes as another transform)."""

    def __init__(self, data_shape=(3, 224, 224), label_width: int = 1,
                 mean: float = 0.0, scale: float = 1.0):
        self.data_shape = tuple(int(d) for d in data_shape)
        self.label_width = int(label_width)
        self.mean = float(mean)
        self.scale = float(scale)

    def _decode(self, buf: bytes) -> np.ndarray:
        c, h, w = self.data_shape
        try:
            import cv2
            flag = cv2.IMREAD_COLOR if c == 3 else cv2.IMREAD_GRAYSCALE
            img = cv2.imdecode(np.frombuffer(buf, dtype=np.uint8), flag)
            if img is None:
                raise ValueError("cv2.imdecode returned None")
            if (img.shape[1], img.shape[0]) != (w, h):
                img = cv2.resize(img, (w, h),
                                 interpolation=cv2.INTER_LINEAR)
            if c == 3:
                img = cv2.cvtColor(img, cv2.COLOR_BGR2RGB)
        except ImportError:
            import io as _io
            from PIL import Image
            pil = Image.open(_io.BytesIO(buf))
            pil = pil.convert("RGB" if c == 3 else "L")
            if pil.size != (w, h):
                pil = pil.resize((w, h))
            img = np.asarray(pil)
        if img.ndim == 2:
            img = img[:, :, None]
        return img

    def __call__(self, raw: bytes):
        header, payload = _recordio.unpack(raw)
        img = self._decode(payload).astype(np.float32)
        img = (img - self.mean) * self.scale
        data = np.transpose(img, (2, 0, 1))          # HWC -> CHW
        return data, _shape_label(header.label, self.label_width)


class StallTransform(object):
    """``inner`` plus a fixed per-record stall — deterministic latency
    emulation (remote storage fetch, slow decoder). Test/bench-only."""

    def __init__(self, inner, stall_s: float):
        self.inner = inner
        self.stall_s = float(stall_s)

    def __call__(self, raw: bytes):
        if self.stall_s > 0:
            time.sleep(self.stall_s)
        return self.inner(raw)

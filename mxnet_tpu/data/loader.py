"""``mx.data.DataLoader`` — the multi-process streaming loader facade.

A ``DataIter`` over an indexed RecordIO file whose decode runs in
worker PROCESSES owning disjoint shard ranges (``partition.
PartitionPlan``): the facade delivers host batches in deterministic
epoch order regardless of worker count, ``fit`` wraps it in the
device-prefetch stage (``PrefetchingIter(device_placer=...)``) exactly
like any other iterator, and the checkpoint manifest carries its cursor
so a mid-epoch resume — even with a different worker count or pod
world — restarts the stream bit-exactly (docs/architecture/
data_plane.md).

Delivery protocol: batch ``k`` is owned by worker ``k % num_workers``
and every worker emits its owned batches in ascending order, so the
facade pops batch ``k`` from queue ``k % W`` — in-order reassembly with
ZERO reorder buffering in the steady state. A dead worker (``data.
worker`` fault, OOM-killer, a real crash) is detected on the poll path
and respawned over exactly its undelivered range; batches its corpse
left in the old queue are salvaged first, so the replay is exact and
nothing is delivered twice.

Observability (always-on counters/gauges, trace lane ``data`` when
spans record):

* ``data_batches`` / ``data_records`` — delivered volume
* ``data_stall`` — the consumer outran the workers in steady state
  (first fetch of an epoch excluded, mirroring
  ``loop_prefetch_stall``'s cold-queue discipline)
* ``data_worker_respawn`` — dead-worker recoveries
* ``data_batch_poisoned`` — batches dropped by a decode fault
* ``data_queue_depth`` — gauge, decoded batches waiting at last fetch
"""
from __future__ import annotations

import logging
import multiprocessing as mp
import queue as _queue_mod
import time
from typing import Optional

import numpy as np

from .. import config as _config
from .. import profiler as _profiler
from ..base import MXNetError
from ..io.io import DataBatch, DataDesc, DataIter
from .partition import PartitionPlan
from .worker import worker_main

__all__ = ["DataLoader"]

log = logging.getLogger(__name__)

# cursor schema version — bumped if the partition function ever changes
# incompatibly (a resume across versions must fail loudly, not skew)
CURSOR_VERSION = 1


class DataLoader(DataIter):
    """Sharded multi-worker streaming iterator over indexed RecordIO.

    Parameters
    ----------
    rec_path : str
        The ``.rec`` file.
    idx_path : str, optional
        The ``.idx`` sidecar (default: ``rec_path`` with ``.idx``).
    batch_size : int
    transform : callable
        Picklable ``raw_bytes -> (data, label)`` decoder
        (``mx.data.RawTransform`` / ``ImageTransform`` / custom).
    shuffle : bool
        Per-epoch deterministic shuffle (seeded permutation).
    seed : int
        The determinism root: two loaders with equal
        ``(seed, batch_size, world)`` deliver identical streams.
    num_workers : int, optional
        Worker processes; default ``MXNET_TPU_DATA_WORKERS``. ``0`` =
        decode inline in the consumer thread (also forced by the
        ``MXNET_TPU_DATA_MP=0`` kill switch).
    queue_depth : int, optional
        Decoded batches buffered per worker; default
        ``MXNET_TPU_DATA_QUEUE_DEPTH``.
    part : "auto" | (rank, world)
        Host ownership: ``"auto"`` derives (rank, world) from the mesh
        / pod (``parallel.mesh.host_partition``); a tuple pins it.
    mesh : jax Mesh, optional
        Resolves ``part="auto"`` against this mesh's process set.
    begin_epoch : int
        First epoch's index (shuffle permutation parity on restarts).
    data_name / label_name : str
        Names for ``provide_data`` / ``provide_label``.
    """

    def __init__(self, rec_path: str, idx_path: Optional[str] = None,
                 batch_size: int = 32, transform=None, shuffle: bool = True,
                 seed: int = 0, num_workers: Optional[int] = None,
                 queue_depth: Optional[int] = None, part="auto", mesh=None,
                 begin_epoch: int = 0, data_name: str = "data",
                 label_name: str = "label"):
        super(DataLoader, self).__init__(batch_size=int(batch_size))
        if transform is None:
            raise ValueError(
                "DataLoader needs a transform (mx.data.RawTransform / "
                "ImageTransform or any picklable raw->(data,label) "
                "callable)")
        self.rec_path = rec_path
        self.idx_path = idx_path if idx_path is not None else \
            rec_path.rsplit(".", 1)[0] + ".idx"
        self.transform = transform
        self.shuffle = bool(shuffle)
        self.seed = int(seed)
        if num_workers is None:
            num_workers = int(_config.get("MXNET_TPU_DATA_WORKERS"))
        if not _config.get("MXNET_TPU_DATA_MP"):
            num_workers = 0            # kill switch: inline decode
        self.num_workers = max(0, int(num_workers))
        self.queue_depth = max(1, int(
            _config.get("MXNET_TPU_DATA_QUEUE_DEPTH")
            if queue_depth is None else queue_depth))
        if part == "auto":
            from ..parallel.mesh import host_partition
            self.rank, self.world_size = host_partition(mesh)
        else:
            self.rank, self.world_size = int(part[0]), int(part[1])
        self.data_name = data_name
        self.label_name = label_name

        # index keys in file order — the record-id space the partition
        # permutes. Loaded once here; workers reopen their own handles.
        from .. import recordio as _recordio
        self._rec = _recordio.MXIndexedRecordIO(self.idx_path, rec_path,
                                                "r")
        self._keys = list(self._rec.keys)
        if len(self._keys) < self.batch_size * max(1, self.world_size):
            raise MXNetError(
                "DataLoader: %d records in %s cannot fill one batch of "
                "%d on every one of %d hosts"
                % (len(self._keys), rec_path, self.batch_size,
                   max(1, self.world_size)))

        # shapes/dtypes from record 0 (any record — the stream is
        # homogeneous by contract)
        d0, l0 = transform(self._rec.read_idx(self._keys[0]))
        d0, l0 = np.asarray(d0), np.asarray(l0)
        self.provide_data = [DataDesc(data_name,
                                      (self.batch_size,) + d0.shape,
                                      d0.dtype)]
        self.provide_label = [DataDesc(label_name,
                                       (self.batch_size,) + l0.shape,
                                       l0.dtype)]

        # ---------------------------------------------------- epoch state
        self._epoch = int(begin_epoch)
        self._start_batch = 0          # cursor within the epoch
        self._plan: Optional[PartitionPlan] = None
        self._next_batch = 0           # next batch index to deliver
        self._first_fetch = True
        self._cold = set()             # worker queues not yet popped
        self._mp = self.num_workers > 0
        # a queue-pop fetch is a data-plane wait, not local work: the
        # straggler window re-marks after it (base_module.fit)
        self._mx_offthread_fetch = self._mp
        self._procs = []               # per-worker Process
        self._queues = []              # per-worker mp.Queue
        self._done = []                # per-worker clean-exit flag
        self._gen = []                 # per-worker respawn generation
        self._salvaged = {}            # batch_idx -> entry (respawn path)
        self._closed = False
        self._mx_device_placer = None  # fit-attached device placement

    # ------------------------------------------------------------ plumbing
    def _make_plan(self) -> PartitionPlan:
        return PartitionPlan(
            len(self._keys), self.batch_size, seed=self.seed,
            epoch=self._epoch, rank=self.rank,
            world_size=self.world_size,
            num_workers=max(1, self.num_workers), shuffle=self.shuffle)

    def _owned_payload(self, worker: int, start_batch: int):
        """[(batch_idx, [record keys])...] for one worker from a start
        position — the spawn/respawn work list."""
        plan = self._plan
        return [(k, [self._keys[i] for i in plan.batch_records(k)])
                for k in plan.owned_batches(worker, start_batch)]

    def _spawn_worker(self, w: int, start_batch: int) -> None:
        # fork when the platform has it: worker start is milliseconds
        # and faults.install() state is inherited. The workers never
        # touch jax (pure file IO + numpy), so the usual fork-after-
        # runtime-init hazards don't apply; spawn platforms re-parse
        # MXNET_TPU_FAULTS from the environment instead.
        try:
            ctx = mp.get_context("fork")
        except ValueError:
            ctx = mp.get_context()
        q = ctx.Queue(maxsize=self.queue_depth)
        proc = ctx.Process(
            target=worker_main,
            args=(w, self._gen[w], self.rec_path, self.idx_path,
                  self._owned_payload(w, start_batch), self.transform, q),
            daemon=True, name="mx-data-w%d" % w)
        import warnings
        with warnings.catch_warnings():
            # CPython warns that fork under a multithreaded jax runtime
            # may deadlock — the children here run pure file IO + numpy
            # and never enter jax, which is the case the warning cannot
            # see; silencing it here keeps training logs clean
            warnings.filterwarnings(
                "ignore", message=".*fork.*", category=RuntimeWarning)
            proc.start()
        self._procs[w] = proc
        self._queues[w] = q
        self._done[w] = False

    def _activate(self) -> None:
        """Lazy epoch start: build the plan and (mp mode) the worker
        pool from the cursor position."""
        self._plan = self._make_plan()
        self._next_batch = self._start_batch
        self._first_fetch = True
        # every worker queue is cold by construction at epoch start:
        # the FIRST pop from each is ramp, not a steady-state bubble
        # (the per-queue generalization of loop_prefetch_stall's
        # first-fetch discipline)
        self._cold = set(range(max(1, self.num_workers)))
        self._salvaged = {}
        if self._mp:
            nw = self.num_workers
            self._procs = [None] * nw
            self._queues = [None] * nw
            self._done = [False] * nw
            self._gen = [0] * nw
            for w in range(nw):
                self._spawn_worker(w, self._start_batch)

    def _teardown(self) -> None:
        """Stop the pool (idempotent). Workers blocked on a full queue
        die on terminate; exited ones just get joined."""
        for proc in self._procs:
            if proc is not None and proc.is_alive():
                proc.terminate()
        for proc in self._procs:
            if proc is not None:
                proc.join(timeout=5.0)
                if proc.is_alive():
                    proc.kill()
                    proc.join(timeout=5.0)
        for q in self._queues:
            if q is not None:
                q.close()
                q.cancel_join_thread()
        self._procs, self._queues, self._done, self._gen = [], [], [], []
        self._plan = None
        self._salvaged = {}
        _profiler.set_gauge("data_queue_depth", 0)

    def _salvage_queue(self, w: int) -> None:
        """Drain a dead worker's queue into the salvage buffer: batches
        its corpse already produced must be delivered, not replayed."""
        q = self._queues[w]
        while True:
            try:
                entry = q.get_nowait()
            except (_queue_mod.Empty, EOFError, OSError):
                break
            if entry[0] in ("data", "error"):
                self._salvaged[entry[1]] = entry
            elif entry[0] == "done":
                self._done[w] = True

    def _respawn(self, w: int) -> None:
        """A worker died mid-epoch (``data.worker`` fault, crash, OOM):
        salvage what it delivered to its queue, then replay its shard
        range from the first undelivered batch."""
        self._salvage_queue(w)
        self._gen[w] += 1
        _profiler.incr_counter("data_worker_respawn")
        first_undelivered = self._next_batch
        while first_undelivered < self._plan.num_batches and \
                (self._plan.worker_of(first_undelivered) != w
                 or first_undelivered in self._salvaged):
            first_undelivered += 1
        log.warning(
            "data: worker %d died (gen %d); respawning over its range "
            "from batch %d", w, self._gen[w], first_undelivered)
        self._spawn_worker(w, first_undelivered)

    def _pop(self, k: int):
        """Entry for batch ``k`` from its owner's queue, with stall
        accounting, dead-worker detection and salvage fallback."""
        if k in self._salvaged:
            return self._salvaged.pop(k)
        w = self._plan.worker_of(k)
        q = self._queues[w]
        try:
            entry = q.get_nowait()
        except _queue_mod.Empty:
            # the consumer outran the decode pool: a pipeline bubble —
            # except on the first pop from this worker's queue, which
            # is cold by construction at epoch start
            if w not in self._cold:
                _profiler.incr_counter("data_stall")
            while True:
                try:
                    entry = q.get(timeout=0.2)
                    break
                except _queue_mod.Empty:
                    proc = self._procs[w]
                    if not self._done[w] and proc is not None \
                            and not proc.is_alive():
                        self._respawn(w)
                        if k in self._salvaged:
                            entry = self._salvaged.pop(k)
                            break
                        q = self._queues[w]
                    elif self._done[w]:
                        raise MXNetError(
                            "data: worker %d finished but batch %d of "
                            "its range was never delivered (partition "
                            "drift — file a bug)" % (w, k))
        self._cold.discard(w)
        _profiler.set_gauge("data_queue_depth", q.qsize()
                            if hasattr(q, "qsize") else 0)
        if entry[0] == "done":
            self._done[w] = True
            return self._pop(k)
        return entry

    # ------------------------------------------------------- DataIter API
    def reset(self):
        """Epoch boundary: advance the epoch counter (fresh shuffle
        permutation) and restart the stream at batch 0."""
        self._teardown()
        self._epoch += 1
        self._start_batch = 0

    def next(self):
        if self._closed:
            raise MXNetError("DataLoader used after close()")
        if self._plan is None:
            self._activate()
        plan = self._plan
        while True:
            k = self._next_batch
            if k >= plan.num_batches:
                # epoch exhausted: reap the pool now so no worker
                # outlives the epoch that spawned it
                self._teardown()
                # re-arm the plan lazily for a bare re-iteration
                # without reset() (fit always resets)
                self._plan = None
                self._start_batch = 0
                raise StopIteration
            with _profiler.span("data_fetch", "io", lane="data"):
                if self._mp:
                    entry = self._pop(k)
                else:
                    entry = self._decode_inline(k)
            self._first_fetch = False
            self._next_batch = k + 1
            kind = entry[0]
            if kind == "error":
                _profiler.incr_counter("data_batch_poisoned")
                log.warning(
                    "data: batch %d of epoch %d poisoned by a decode "
                    "fault (%s); continuing with the next batch",
                    k, self._epoch, entry[2])
                continue
            if entry[1] != k:
                raise MXNetError(
                    "data: out-of-order delivery (got batch %r, "
                    "expected %d) — worker ownership drift, file a bug"
                    % (entry[1], k))
            data_arr, label_arr = entry[2], entry[3]
            _profiler.incr_counter("data_batches")
            _profiler.incr_counter("data_records", self.batch_size)
            batch = DataBatch(
                data=[data_arr], label=[label_arr], pad=0, index=None,
                provide_data=self.provide_data,
                provide_label=self.provide_label)
            placer = self._mx_device_placer
            if placer is not None:
                # fit's device-placement stage runs HERE, on the batch
                # the workers just decoded: per-host device_put onto the
                # mesh data axis (async dispatch — the H2D overlaps the
                # in-flight steps) instead of handing host numpy to a
                # separate prefetch wrapper that re-copies it (ROADMAP
                # item 5 REMAINING: the extra host hop is gone)
                with _profiler.span("data_place", "io", lane="data"):
                    placer(batch)
                _profiler.incr_counter("data_device_placed")
            return batch

    def _decode_inline(self, k: int):
        """num_workers=0 / MXNET_TPU_DATA_MP=0: the zero-process
        bisection fallback — same order, same fault semantics, decode
        on the consumer thread."""
        from .. import faults as _faults
        try:
            if _faults.ARMED:
                _faults.fire("data.decode", default_kind="raise")
            datas, labels = [], []
            for i in self._plan.batch_records(k):
                d, lab = self.transform(self._rec.read_idx(self._keys[i]))
                datas.append(d)
                labels.append(lab)
            return ("data", k, np.stack(datas), np.stack(labels))
        except StopIteration:
            raise
        except Exception as exc:                       # noqa: BLE001
            return ("error", k, "%s: %s" % (type(exc).__name__, exc),
                    None)

    # ------------------------------------------------- device placement
    def _mx_set_device_placer(self, placer) -> None:
        """fit() attaches the module's device placer so every delivered
        batch already carries device arrays (``batch._mx_placed``) —
        the loader IS the prefetch stage, no ``PrefetchingIter`` wrapper
        and no extra host copy. ``None`` detaches (fit's ``finally``)."""
        self._mx_device_placer = placer

    # ----------------------------------------------- checkpoint integration
    def _mx_cursor(self, epoch: Optional[int] = None,
                   batches_done: Optional[int] = None) -> dict:
        """The manifest's loader cursor: position (supplied by fit — the
        CONSUMED count, not the delivered one, which runs prefetch-depth
        ahead) plus the static parameters that make a resume checkable."""
        return {"version": CURSOR_VERSION,
                "epoch": self._epoch if epoch is None else int(epoch),
                "batches_done": 0 if batches_done is None
                else int(batches_done),
                "seed": self.seed, "batch_size": self.batch_size,
                "num_records": len(self._keys), "shuffle": self.shuffle,
                "world_size": self.world_size, "rank": self.rank,
                "num_workers": self.num_workers}

    def _mx_fast_forward(self, epoch: int, batches_done: int,
                         cursor: Optional[dict] = None) -> None:
        """Cursor resume: position the stream at ``(epoch,
        batches_done)`` WITHOUT decoding the skipped batches — the
        partition is a pure function, so the skip is free. ``cursor``
        (the manifest's, when present) is validated: a resume against a
        different dataset/seed/batch size would silently train on the
        wrong stream; a different worker count or world just
        re-partitions (the elastic path) and is logged."""
        if cursor:
            if int(cursor.get("version", CURSOR_VERSION)) > CURSOR_VERSION:
                raise MXNetError(
                    "data: checkpoint loader cursor version %r is newer "
                    "than this loader (%d)"
                    % (cursor.get("version"), CURSOR_VERSION))
            for field, mine in (("seed", self.seed),
                                ("batch_size", self.batch_size),
                                ("num_records", len(self._keys)),
                                ("shuffle", self.shuffle)):
                theirs = cursor.get(field)
                if theirs is not None and theirs != mine:
                    raise MXNetError(
                        "data: resume cursor mismatch on %s (checkpoint "
                        "%r vs loader %r) — this is not the stream the "
                        "interrupted run was consuming" % (field, theirs,
                                                           mine))
            if cursor.get("num_workers") not in (None, self.num_workers):
                log.info(
                    "data: resuming with %d workers (checkpoint ran "
                    "%s) — shard ranges re-partitioned, stream order "
                    "unchanged", self.num_workers,
                    cursor.get("num_workers"))
            if cursor.get("world_size") not in (None, self.world_size):
                log.warning(
                    "data: resuming on a world of %d (checkpoint ran "
                    "%s) — per-host streams re-stride from this batch "
                    "on", self.world_size, cursor.get("world_size"))
        self._teardown()
        self._epoch = int(epoch)
        self._start_batch = max(0, int(batches_done))

    # ------------------------------------------------------------ lifecycle
    @property
    def epoch(self) -> int:
        return self._epoch

    @property
    def batches_per_epoch(self) -> int:
        plan = self._plan or self._make_plan()
        return plan.num_batches

    def close(self):
        if self._closed:
            return
        self._closed = True
        self._teardown()
        try:
            self._rec.close()
        except Exception:                              # noqa: BLE001
            pass

    def __del__(self):
        try:
            self.close()
        except Exception:                              # noqa: BLE001
            pass

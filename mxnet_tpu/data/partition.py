"""Deterministic shard partitioning for the streaming data plane.

The whole data plane hangs off one pure function family: given
``(seed, epoch, world_size, rank, num_workers, batch_size)`` and the
dataset's record count, every process in the pod — and every worker
process inside it — derives the SAME answer to "which records make up
batch ``k`` of epoch ``e``, and who decodes it". Nothing is negotiated
at runtime, so determinism, elastic resharding and mid-epoch resume all
reduce to re-evaluating the function with different arguments:

* **ordering** — ``epoch_order(seed, epoch)`` permutes the record ids
  (identity when ``shuffle=False``); the permutation depends only on
  ``(seed, epoch)``, never on worker count or world size.
* **host ownership** — host ``r`` of ``w`` owns the strided slice
  ``order[r::w]`` (the striding ``ImageRecordIter`` already uses for
  ``part_index``/``num_parts``), chopped into consecutive batches of
  ``batch_size`` (the ragged tail is dropped — every rank must step the
  same number of times or the pod's collectives deadlock).
* **worker ownership** — batch ``k`` belongs to worker ``k %
  num_workers``. Worker count therefore re-partitions WHO decodes a
  batch, never WHAT the batch contains or WHEN it is delivered: the
  delivered stream is bit-identical across ``num_workers`` (the
  determinism tests pin {1, 2, 4}).
* **cursor** — a mid-epoch position is just ``(epoch, batches_done)``;
  resuming is re-evaluating the plan at the same ``(seed, epoch)`` and
  starting at batch ``batches_done`` — even with a different worker
  count (the kill/reshard/resume drill's acceptance).

Ordering contract and failure semantics: docs/architecture/data_plane.md.
"""
from __future__ import annotations

from typing import List, Sequence

import numpy as np

__all__ = ["epoch_order", "PartitionPlan"]


def epoch_order(num_records: int, seed: int, epoch: int,
                shuffle: bool = True) -> np.ndarray:
    """The epoch's record-id permutation — a pure function of
    ``(seed, epoch)``. PCG64 under an explicit SeedSequence: stable
    across processes and runs, and epochs draw independent streams
    without consuming shared RNG state."""
    if not shuffle:
        return np.arange(num_records, dtype=np.int64)
    rng = np.random.Generator(np.random.PCG64(
        np.random.SeedSequence([int(seed), int(epoch)])))
    return rng.permutation(num_records).astype(np.int64)


class PartitionPlan(object):
    """One epoch's resolved partition for one host: the host-local
    batch list plus the worker-ownership map. Construction is cheap
    (one permutation + one stride) — workers and the facade both
    rebuild it from the scalar parameters instead of shipping arrays.
    """

    def __init__(self, num_records: int, batch_size: int, *, seed: int,
                 epoch: int, rank: int = 0, world_size: int = 1,
                 num_workers: int = 1, shuffle: bool = True):
        if batch_size <= 0:
            raise ValueError("batch_size must be positive, got %d"
                             % batch_size)
        if not (0 <= rank < max(1, world_size)):
            raise ValueError("rank %d outside world of %d"
                             % (rank, world_size))
        self.num_records = int(num_records)
        self.batch_size = int(batch_size)
        self.seed = int(seed)
        self.epoch = int(epoch)
        self.rank = int(rank)
        self.world_size = max(1, int(world_size))
        self.num_workers = max(1, int(num_workers))
        self.shuffle = bool(shuffle)
        order = epoch_order(self.num_records, self.seed, self.epoch,
                            self.shuffle)
        # host-local record sequence: strided so a world change
        # re-partitions without reshuffling what exists
        self.local_order = order[self.rank::self.world_size]
        # drop the ragged tail: every rank must deliver the same batch
        # count or the pod's bulk-synchronous step deadlocks
        self.num_batches = len(self.local_order) // self.batch_size

    # ------------------------------------------------------------ lookups
    def batch_records(self, k: int) -> np.ndarray:
        """Record ids of host-local batch ``k`` (epoch order)."""
        if not (0 <= k < self.num_batches):
            raise IndexError("batch %d outside epoch of %d batches"
                             % (k, self.num_batches))
        lo = k * self.batch_size
        return self.local_order[lo:lo + self.batch_size]

    def worker_of(self, k: int) -> int:
        """Which worker decodes host-local batch ``k``."""
        return k % self.num_workers

    def owned_batches(self, worker: int, start_batch: int = 0
                      ) -> List[int]:
        """Batch indices worker ``worker`` owns from ``start_batch`` on —
        the worker's (disjoint) shard range of the epoch. Respawn-after-
        death replays exactly this list recomputed at the first
        undelivered batch."""
        if not (0 <= worker < self.num_workers):
            raise IndexError("worker %d outside pool of %d"
                             % (worker, self.num_workers))
        first = max(0, int(start_batch))
        return [k for k in range(first, self.num_batches)
                if k % self.num_workers == worker]

    def owned_ranges(self, worker: int, start_batch: int = 0
                     ) -> List[Sequence[int]]:
        """The record-id lists for :meth:`owned_batches` — what the
        worker process actually receives (keys to ``read_idx``)."""
        return [self.batch_records(k).tolist()
                for k in self.owned_batches(worker, start_batch)]

    def describe(self) -> dict:
        return {"num_records": self.num_records,
                "batch_size": self.batch_size, "seed": self.seed,
                "epoch": self.epoch, "rank": self.rank,
                "world_size": self.world_size,
                "num_workers": self.num_workers, "shuffle": self.shuffle,
                "num_batches": self.num_batches}

"""The data-plane worker process body.

Each worker owns a DISJOINT list of batch ranges (``PartitionPlan.
owned_ranges``) of one epoch, opens its own ``MXIndexedRecordIO``
handle (file handles never cross the fork), decodes batch-at-a-time and
puts finished host batches on its bounded queue — backpressure is the
queue bound, so a stalled consumer parks the workers instead of
buffering the epoch in RAM.

Failure semantics (docs/architecture/data_plane.md):

* ``data.worker`` fault site — fires at each batch START, default kind
  ``sigkill``: the honest worker-death shape. Only generation 0 fires
  it: a respawned worker replaying the dead one's undelivered range
  must make progress, not re-die at the same arrival forever.
* ``data.decode`` fault site + any real decode error — poisons ONE
  batch: the error is carried to the facade as an ``("error", k, msg)``
  entry (never a worker exit), the facade counts
  ``data_batch_poisoned`` and the epoch continues with batch ``k+1``.
* Clean exhaustion of the owned ranges ends with a ``("done", wid)``
  entry so the facade can tell "finished" from "died".

The worker NEVER touches jax — pure file IO + numpy — so a forked
worker cannot deadlock on the parent's runtime locks.
"""
from __future__ import annotations

import os
import numpy as np

__all__ = ["worker_main"]


def worker_main(wid: int, generation: int, rec_path: str, idx_path: str,
                owned, transform, out_queue) -> None:
    """Decode ``owned`` = [(batch_idx, [record keys]), ...] in order.

    Top-level (picklable) so both fork and spawn start methods work;
    fault specs arrive via fork inheritance or the ``MXNET_TPU_FAULTS``
    environment (spawned children re-parse it at import).
    """
    from .. import faults as _faults
    from .. import recordio as _recordio

    # tag the fault marker lines with the worker identity: a drill
    # asserting "worker 1 died at its 2nd batch" can read it back
    os.environ.setdefault("MXNET_TPU_DATA_WORKER_ID", str(wid))
    rec = _recordio.MXIndexedRecordIO(idx_path, rec_path, "r")
    try:
        for bidx, keys in owned:
            if _faults.ARMED and generation == 0:
                _faults.fire("data.worker", default_kind="sigkill")
            try:
                if _faults.ARMED:
                    _faults.fire("data.decode", default_kind="raise")
                datas, labels = [], []
                for key in keys:
                    d, lab = transform(rec.read_idx(key))
                    datas.append(d)
                    labels.append(lab)
                out_queue.put(("data", bidx,
                               np.stack(datas), np.stack(labels)))
            except Exception as exc:               # noqa: BLE001
                # ONE poisoned batch, not a dead worker: decode errors
                # (injected or real — a corrupt record, a failed jpeg)
                # ride the queue as data so the facade can skip exactly
                # this batch and keep the epoch alive
                out_queue.put(("error", bidx,
                               "%s: %s" % (type(exc).__name__, exc),
                               None))
        out_queue.put(("done", wid, None, None))
    finally:
        rec.close()

"""The tuner's knob space: one frozen :class:`Candidate` per point.

A candidate is exactly the set of PR 9/14 performance levers a restart
can re-apply from a stored record: remat policy x grad_accum x
scan-over-layers x grouped update x async window x ``SpecLayout``
factorization. :func:`enumerate_space` yields the cross product in a
deterministic order with the DEFAULT configuration first — the search
always probes the default, so the winner is >= default by construction.
"""
from __future__ import annotations

from dataclasses import dataclass, field, replace  # noqa: F401
from typing import Any, Dict, List, Optional, Tuple

__all__ = ["Candidate", "DEFAULT", "enumerate_space", "GRAD_ACCUMS"]

# the microbatching ladder the ISSUE pins
GRAD_ACCUMS = (1, 2, 4, 8)


@dataclass(frozen=True)
class Candidate:
    """One point of the knob space. :meth:`order_key` is the
    deterministic tie-break (field order, with ``layout=None`` mapped
    to the empty tuple); the dataclass itself is deliberately NOT
    ``order=True`` — comparing a ``layout`` of None against a tuple
    raises TypeError, exactly when candidates tie on a score prefix."""
    remat: str = "off"            # off | auto | a checkpoint-policy name
    grad_accum: int = 1
    scan_layers: str = "auto"     # off | auto
    group_update: bool = True
    async_window: int = 2
    layout: Optional[Tuple[int, int, int]] = None   # (data, fsdp, tp)

    def order_key(self) -> tuple:
        """Total-orderable deterministic sort tail: field order, the
        default arm of each knob first, ``layout=None`` below any
        factorization (None -> ``()``)."""
        return (self.remat, self.grad_accum, self.scan_layers,
                not self.group_update, self.async_window,
                self.layout or ())

    def knobs(self) -> Dict[str, Any]:
        """The config-knob dict this candidate applies (grad_accum and
        layout are applied through their dedicated Module setters, not
        the environment)."""
        return {
            "MXNET_TPU_REMAT": self.remat,
            "MXNET_TPU_SCAN_LAYERS": self.scan_layers,
            "MXNET_TPU_GROUP_UPDATE": self.group_update,
            "MXNET_TPU_ASYNC_WINDOW": self.async_window,
        }

    def to_dict(self) -> Dict[str, Any]:
        return {
            "remat": self.remat, "grad_accum": self.grad_accum,
            "scan_layers": self.scan_layers,
            "group_update": self.group_update,
            "async_window": self.async_window,
            "layout": list(self.layout) if self.layout else None,
        }

    @classmethod
    def from_dict(cls, d: Dict[str, Any]) -> "Candidate":
        lay = d.get("layout")
        return cls(remat=str(d.get("remat", "off")),
                   grad_accum=int(d.get("grad_accum", 1)),
                   scan_layers=str(d.get("scan_layers", "auto")),
                   group_update=bool(d.get("group_update", True)),
                   async_window=int(d.get("async_window", 2)),
                   layout=tuple(int(x) for x in lay) if lay else None)


DEFAULT = Candidate()


def enumerate_space(batch_size: int, n_devices: int = 1,
                    remat_policies: Tuple[str, ...] = ("off", "auto"),
                    layouts: Optional[List[Tuple[int, int, int]]] = None,
                    ) -> List[Candidate]:
    """The full candidate list, deterministically ordered with
    :data:`DEFAULT` first. ``grad_accum`` keeps only the ladder rungs
    dividing the batch (the fused step's own contract); ``layouts`` is
    the pre-ranked ``(data, fsdp, tp)`` list from
    ``analysis.tuning.rank_layouts`` (None on a single device)."""
    accums = [n for n in GRAD_ACCUMS if batch_size % n == 0]
    lays: List[Optional[Tuple[int, int, int]]] = [None]
    if n_devices > 1 and layouts:
        lays = [tuple(int(x) for x in la) for la in layouts]
    out: List[Candidate] = [DEFAULT]
    seen = {DEFAULT}
    for lay in lays:
        for remat in remat_policies:
            for accum in accums:
                for scan in ("auto", "off"):
                    for group in (True, False):
                        for window in (2, 0):
                            c = Candidate(
                                remat=remat, grad_accum=accum,
                                scan_layers=scan, group_update=group,
                                async_window=window, layout=lay)
                            if c not in seen:
                                seen.add(c)
                                out.append(c)
    return out

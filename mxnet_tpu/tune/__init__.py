"""mxnet_tpu.tune — the configuration autotuner (ISSUE 19 tentpole).

Given a module, an optimizer, a batch source and an HBM/wall-clock
budget, :func:`search` finds the training configuration — remat policy
x ``grad_accum`` x scan-over-layers x grouped update x async window x
``SpecLayout`` — in three phases:

1. **enumerate** the knob space (:mod:`.space`);
2. **prune statically** with the analysis cost/memory/comm models
   (:mod:`.prune` over ``analysis.tuning``) — configs that cannot bind
   under the HBM budget are rejected without spending a compile;
3. **confirm empirically** with short obs-instrumented probe
   subprocesses under hard deadlines (:mod:`.probe`), scored by
   ``obs_mfu`` / steps-per-sec (pod throughput on a pod) with
   ``loop_recompile == 0`` required.

The winner persists next to the AOT executable cache (:mod:`.store`,
keyed by the ``aot`` fingerprint scheme), so ``fit(tune="auto")`` on a
restart is pre-tuned AND pre-compiled: zero search cost, zero backend
compiles.

This package is LAZY (PEP 562 in ``mxnet_tpu/__init__``) and imported
only when the tuner is armed — ``MXNET_TPU_TUNE`` unset means it never
loads (zero-cost gate, subprocess-asserted). CLI:
``python -m mxnet_tpu.tune --net mlp --budget 16G``.
"""
from __future__ import annotations

from .probe import make_spec, run_probe
from .search import search
from .space import Candidate, DEFAULT, enumerate_space
from .store import TunedConfig, load_config, program_key, store_config

__all__ = [
    "search", "Candidate", "DEFAULT", "enumerate_space",
    "TunedConfig", "program_key", "load_config", "store_config",
    "make_spec", "run_probe", "tune_fit",
]


def tune_fit(module, train_data, optimizer, optimizer_params,
             mode: str = "auto", budget=None, seed: int = 0):
    """``fit(tune=...)``'s backend: search (or load) the tuned config
    for this module's program and return the :class:`TunedConfig`.

    ``train_data`` must already expose ``provide_data``/``provide_label``
    (fit calls this after reset). The module is NOT mutated here —
    ``fit`` applies the winner's knobs itself so explicit user arguments
    keep precedence."""
    import numpy as np

    data_shapes = [(d.name if hasattr(d, "name") else d[0],
                    tuple(d.shape if hasattr(d, "shape") else d[1]))
                   for d in train_data.provide_data]
    label_desc = getattr(train_data, "provide_label", None) or []
    label_shapes = [(d.name if hasattr(d, "name") else d[0],
                     tuple(d.shape if hasattr(d, "shape") else d[1]))
                    for d in label_desc]

    def _dtypes(descs):
        out = {}
        for d in descs:
            dt = getattr(d, "dtype", None)
            if dt is not None:
                out[d.name if hasattr(d, "name") else d[0]] = \
                    np.dtype(dt).name
        return out

    n_devices = 1
    mesh = getattr(module, "_mesh", None)
    if mesh is not None:
        n_devices = int(getattr(mesh, "size", 1))

    cfg = search(
        module.symbol, data_shapes, label_shapes,
        optimizer=optimizer if isinstance(optimizer, str)
        else type(optimizer).__name__.lower(),
        optimizer_params=optimizer_params, budget=budget,
        n_devices=n_devices, mode=mode, seed=seed,
        data_dtypes=_dtypes(train_data.provide_data),
        label_dtypes=_dtypes(label_desc),
        log=module.logger.info if hasattr(module, "logger") else None)
    return cfg

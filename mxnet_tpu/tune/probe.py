"""Empirical confirmation: short obs-instrumented probe runs.

Each probe is a SUBPROCESS (``python -m mxnet_tpu.tune --probe spec``)
under a hard deadline — the PhaseGuard discipline from ``bench.py``: a
candidate that wedges in trace/compile or thrashes cannot stall the
search; it times out, scores failed, and the partial results stand. The
child applies the candidate's knobs, runs a real ``fit`` over synthetic
batches shaped exactly like the target program, and reports
``mx.obs.probe_score()``: MFU / steps-per-sec measured from the
OBS-warmup boundary (compile excluded), the pod throughput block when a
pod is live, and ``loop_recompile`` — asserted zero, so a thrashing
config can never win.

Process isolation is the point, not a convenience: a probe compiles
executables, mutates config knobs and bumps counters — none of which
may leak into the searching process (subprocess-asserted by the probe
isolation test, same discipline as the zero-cost gates). The child
inherits ``MXNET_TPU_COMPILE_CACHE``, so the winning probe's fused-step
executable seeds the AOT cache under the exact signature the tuned
``fit`` computes later — the zero-compile warm restart.
"""
from __future__ import annotations

import json
import os
import subprocess
import sys
import tempfile
import time
from typing import Any, Dict, List, Optional

from .. import profiler as _profiler
from .space import Candidate

__all__ = ["make_spec", "run_probe", "run_probe_child"]

# obs opens its rate window after this many steps (obs.mfu contract);
# probes run warmup + measured steps in one epoch
WARMUP_STEPS = 2


def make_spec(symbol_json: str, data_shapes, label_shapes,
              data_dtypes: Dict[str, str], label_dtypes: Dict[str, str],
              optimizer: str, optimizer_params, candidate: Candidate,
              steps: int, seed: int = 0) -> Dict[str, Any]:
    """The JSON-serializable probe job description."""
    return {
        "symbol": symbol_json,
        "data_shapes": [[str(n), list(s)] for n, s in data_shapes],
        "label_shapes": [[str(n), list(s)]
                         for n, s in (label_shapes or [])],
        "data_dtypes": dict(data_dtypes or {}),
        "label_dtypes": dict(label_dtypes or {}),
        "optimizer": str(optimizer),
        "optimizer_params": dict(optimizer_params or {}),
        "candidate": candidate.to_dict(),
        "steps": int(steps),
        "seed": int(seed),
    }


def _synth_arrays(shapes, dtypes, nbatch: int):
    """Synthetic batches: zeros of the bound dtype — index-safe for
    embedding/label inputs, full-cost for the arithmetic (the values
    are runtime inputs, XLA cannot fold them)."""
    import numpy as np
    out = {}
    for name, shape in shapes:
        dt = np.dtype(dtypes.get(name, "float32"))
        full = (int(shape[0]) * nbatch,) + tuple(int(d)
                                                 for d in shape[1:])
        out[name] = np.zeros(full, dtype=dt)
    return out


def run_probe_child(spec: Dict[str, Any]) -> Dict[str, Any]:
    """Execute one probe in THIS process (the ``--probe`` child entry).
    Returns the score record the parent parses from stdout."""
    import mxnet_tpu as mx

    cand = Candidate.from_dict(spec["candidate"])
    for knob, val in cand.knobs().items():
        mx.config.set(knob, val)
    # a probe must never recurse into the tuner
    mx.config.set("MXNET_TPU_TUNE", "off")

    sym = mx.sym.load_json(spec["symbol"])
    data_shapes = [(n, tuple(s)) for n, s in spec["data_shapes"]]
    label_shapes = [(n, tuple(s)) for n, s in spec["label_shapes"]]
    steps = max(1, int(spec["steps"]))
    nbatch = steps + WARMUP_STEPS

    data = _synth_arrays(data_shapes, spec.get("data_dtypes") or {},
                         nbatch)
    label = _synth_arrays(label_shapes, spec.get("label_dtypes") or {},
                          nbatch) or None
    label_names = [n for n, _ in label_shapes]
    it = mx.io.NDArrayIter(
        data, label, batch_size=int(data_shapes[0][1][0]),
        label_name=label_names[0] if label_names else "softmax_label")

    layout = None
    if cand.layout is not None:
        from ..parallel.layout import SpecLayout
        layout = SpecLayout(data=cand.layout[0], fsdp=cand.layout[1],
                            tp=cand.layout[2])

    mx.random.seed(int(spec.get("seed", 0)))
    mod = mx.mod.Module(sym,
                        data_names=[n for n, _ in data_shapes],
                        label_names=label_names)
    t0 = time.perf_counter()
    # Loss is shape-agnostic (works for seq outputs where "acc" shape
    # checks fail) and device-capable (no async-loop host syncs)
    mod.fit(it, num_epoch=1, optimizer=spec["optimizer"],
            eval_metric=mx.metric.Loss(),
            optimizer_params=dict(spec.get("optimizer_params") or {}),
            grad_accum=cand.grad_accum if cand.grad_accum > 1 else None,
            layout=layout)
    wall = time.perf_counter() - t0
    score = mx.obs.probe_score()
    score["wall_s"] = round(wall, 3)
    score["steps"] = steps
    score["ok"] = bool(score.get("steps_per_sec")) \
        and int(score.get("loop_recompile") or 0) == 0
    if not score["ok"] and not score.get("steps_per_sec"):
        score["why"] = "no rate measured (probe too short?)"
    elif not score["ok"]:
        score["why"] = "loop_recompile=%d — the config thrashes the " \
            "executable cache" % score["loop_recompile"]
    return score


def run_probe(spec: Dict[str, Any],
              deadline_s: float) -> Dict[str, Any]:
    """Launch one probe subprocess and score it. Never raises: a
    timeout, crash or unparseable child yields ``{"ok": False, "why":
    ...}`` and the search moves on (partial results kept)."""
    _profiler.incr_counter("tune_probe")
    root = os.path.dirname(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))))
    env = dict(os.environ)
    # the probe inherits the platform and (critically) the AOT compile
    # cache — including runtime config.set overrides, which subprocesses
    # would otherwise not see; it must not inherit an armed tuner
    from .. import config as _config
    for knob in ("MXNET_TPU_COMPILE_CACHE", "MXNET_TPU_TUNE_STORE"):
        val = _config.get(knob)
        if val:
            env[knob] = str(val)
    env["MXNET_TPU_TUNE"] = ""
    env["PYTHONPATH"] = root + (
        os.pathsep + env["PYTHONPATH"] if env.get("PYTHONPATH") else "")
    fd, path = tempfile.mkstemp(prefix="mx-tune-probe-", suffix=".json")
    try:
        with os.fdopen(fd, "w") as f:
            json.dump(spec, f)
        t0 = time.perf_counter()
        try:
            proc = subprocess.run(
                [sys.executable, "-m", "mxnet_tpu.tune", "--probe",
                 path],
                capture_output=True, text=True, env=env,
                timeout=max(1.0, float(deadline_s)))
        except subprocess.TimeoutExpired:
            _profiler.incr_counter("tune_probe_fail")
            return {"ok": False,
                    "why": "deadline (%.0fs) expired" % deadline_s,
                    "wall_s": round(time.perf_counter() - t0, 3)}
        wall = round(time.perf_counter() - t0, 3)
        # parse the score line FIRST: a failed probe exits nonzero but
        # still reports its structured "why" on the last stdout line
        for line in reversed(proc.stdout.splitlines()):
            if line.startswith("{"):
                try:
                    score = json.loads(line)
                except ValueError:
                    break
                if not score.get("ok"):
                    _profiler.incr_counter("tune_probe_fail")
                score["wall_s"] = wall
                return score
        _profiler.incr_counter("tune_probe_fail")
        return {"ok": False, "wall_s": wall,
                "why": "probe exited %d with no score line: %s"
                       % (proc.returncode,
                          (proc.stderr or "").strip()[-500:])}
    finally:
        try:
            os.unlink(path)
        except OSError:
            pass


def probe_many(specs: List[Dict[str, Any]], deadline_s: float,
               total_deadline_s: Optional[float] = None,
               log=None) -> List[Optional[Dict[str, Any]]]:
    """Run probes sequentially (each owns the machine's devices for an
    honest rate) under per-probe AND total deadlines; entries past an
    expired total budget are ``None`` (never probed, vs failed)."""
    out: List[Optional[Dict[str, Any]]] = []
    t0 = time.perf_counter()
    for spec in specs:
        if total_deadline_s is not None \
                and time.perf_counter() - t0 > total_deadline_s:
            out.append(None)
            continue
        score = run_probe(spec, deadline_s)
        if log is not None:
            log(spec, score)
        out.append(score)
    return out

"""The search: store-first, statically pruned, empirically confirmed.

``search()`` is the tentpole entry point. Given a bound-able program
(symbol + shapes + optimizer) and a budget, it:

1. computes the :func:`~.store.program_key` and returns a stored
   :class:`~.store.TunedConfig` immediately when one exists (a restart
   pays ZERO search cost — and because the winning probe compiled under
   the same AOT cache, zero backend compiles too);
2. enumerates the knob space (:func:`~.space.enumerate_space`),
   statically prunes and ranks it against the HBM budget and the comm
   model (:func:`~.prune.static_rank` over ``analysis.tuning``) — no
   compiles spent on configs the model already rejects;
3. probes the default plus the top-ranked survivors in subprocesses
   under per-probe and total deadlines (:mod:`~.probe`), scoring by
   ``obs_mfu`` (pod throughput when a pod is live, steps/s as the
   denominator-free fallback) with ``loop_recompile == 0`` required;
4. persists the winner next to the AOT executables and returns it.

Determinism: with probing disabled (``mode="static"`` or
``max_probes=0``) the result is a pure function of (program, budget,
space) — the search-determinism test pins this. With probes, rate noise
can reorder near-ties, but the candidate LIST and every static decision
remain reproducible (the audit trail records them), and ties fall back
to static rank order.
"""
from __future__ import annotations

import time
from typing import Any, Dict, List, Optional, Tuple

from .. import profiler as _profiler
from . import probe as _probe
from .prune import static_rank
from .space import DEFAULT, Candidate, enumerate_space
from .store import TunedConfig, load_config, program_key, store_config

__all__ = ["search"]


def _score_key(score: Dict[str, Any]) -> Tuple[float, float, float]:
    """Higher is better: pod throughput (whole-job view when a pod is
    live), then MFU, then raw steps/s."""
    pod = score.get("pod") or {}
    return (float(pod.get("flops_per_sec") or 0.0),
            float(score.get("mfu") or 0.0),
            float(score.get("steps_per_sec") or 0.0))


def search(sym, data_shapes, label_shapes=None, *,
           optimizer: str = "sgd", optimizer_params=None,
           budget: Optional[str] = None, n_devices: int = 1,
           mode: str = "auto", probe_steps: Optional[int] = None,
           probe_deadline_s: Optional[float] = None,
           max_probes: Optional[int] = None, seed: int = 0,
           data_dtypes=None, label_dtypes=None,
           use_store: bool = True, log=None) -> TunedConfig:
    """Tune the training configuration for ``sym``.

    ``data_shapes``/``label_shapes`` are ``[(name, shape), ...]`` as
    bound (batch leading). ``budget`` is an HBM byte budget (``"16G"``
    style, parsed by ``analysis.parse_bytes``) or None for unbudgeted.
    ``mode="static"`` skips probing entirely (deterministic model-only
    winner); ``mode="auto"`` probes. Knob defaults come from
    ``MXNET_TPU_TUNE_PROBE_STEPS`` / ``_PROBE_SECS`` / ``_MAX_PROBES``.
    """
    from .. import config as _config
    from ..analysis import parse_bytes
    from ..analysis import tuning as _tuning

    t0 = time.perf_counter()
    if log is None:
        def log(msg):
            pass

    if probe_steps is None:
        probe_steps = int(_config.get("MXNET_TPU_TUNE_PROBE_STEPS"))
    if probe_deadline_s is None:
        probe_deadline_s = float(_config.get("MXNET_TPU_TUNE_PROBE_SECS"))
    if max_probes is None:
        max_probes = int(_config.get("MXNET_TPU_TUNE_MAX_PROBES"))
    if mode == "static":
        max_probes = 0

    symbol_json = sym.tojson()
    data_shapes = [(str(n), tuple(int(d) for d in s))
                   for n, s in data_shapes]
    label_shapes = [(str(n), tuple(int(d) for d in s))
                    for n, s in (label_shapes or [])]
    optimizer_params = dict(optimizer_params or {})
    key = program_key(symbol_json, data_shapes, label_shapes, optimizer,
                      optimizer_params, budget, n_devices)

    if use_store:
        stored = load_config(key)
        if stored is not None:
            log("tune: stored config hit (%s)" % key[:12])
            return stored

    budget_bytes = parse_bytes(budget) if budget else None
    batch = int(data_shapes[0][1][0])

    # ---- static phase: enumerate, model-prune, rank -----------------
    layout_rank = None
    layouts = None
    if n_devices > 1:
        rep1 = _tuning.cost_report(
            sym, dict(data_shapes + label_shapes),
            batch_inputs=[n for n, _ in data_shapes + label_shapes])
        cost = rep1.extras.get("cost") or {}
        param_bytes = max(0, int(cost.get("bound_bytes") or 0))
        act_bytes = max(0, int(cost.get("activation_peak_bytes") or 0))
        layout_rank = _tuning.rank_layouts(n_devices, param_bytes,
                                           act_bytes)
        layouts = [(r["data"], r["fsdp"], r["tp"])
                   for r in layout_rank]

    space = enumerate_space(batch, n_devices=n_devices,
                            layouts=layouts)
    ranked, audit = static_rank(
        sym, dict(data_shapes + label_shapes),
        [n for n, _ in data_shapes + label_shapes], space,
        budget_bytes=budget_bytes, layout_rank=layout_rank)
    n_pruned = len(space) - len(ranked)
    log("tune: %d candidates, %d survive the static model"
        % (len(space), len(ranked)))

    if not ranked:
        # nothing binds under the budget: surface the default with the
        # audit trail rather than failing — the caller sees why
        cfg = TunedConfig(candidate=DEFAULT, key=key, source="default",
                          searched_s=time.perf_counter() - t0,
                          n_pruned=n_pruned, audit=audit)
        if use_store:
            store_config(cfg)
        return cfg

    static_winner = ranked[0]

    # ---- empirical phase: probe the default + the ranked frontier ---
    to_probe: List[Candidate] = []
    if max_probes > 0:
        # the default is always probed IN ADDITION to the max_probes
        # budget (the MXNET_TPU_TUNE_MAX_PROBES contract: the winner is
        # >= default by construction, and even max_probes=1 gives one
        # ranked candidate an empirical shot), then the static frontier
        # in rank order
        to_probe = [DEFAULT] + [c for c in ranked
                                if c != DEFAULT][:int(max_probes)]

    scores: Dict[Candidate, Dict[str, Any]] = {}
    if to_probe:
        specs = [_probe.make_spec(symbol_json, data_shapes,
                                  label_shapes, data_dtypes or {},
                                  label_dtypes or {}, optimizer,
                                  optimizer_params, c, probe_steps,
                                  seed=seed)
                 for c in to_probe]

        def _plog(spec, score):
            log("tune: probe %s -> %s"
                % (spec["candidate"],
                   {k: score.get(k) for k in
                    ("ok", "mfu", "steps_per_sec", "wall_s", "why")
                    if score.get(k) is not None}))

        results = _probe.probe_many(
            specs, probe_deadline_s,
            total_deadline_s=probe_deadline_s * len(specs), log=_plog)
        for cand, res in zip(to_probe, results):
            if res is not None:
                scores[cand] = res

    ok_scores = {c: s for c, s in scores.items() if s.get("ok")}
    audit.extend({**c.to_dict(), "fate": "probed", "score": s}
                 for c, s in scores.items())

    if ok_scores:
        # static rank is the deterministic tie-break: sort candidates
        # by rank first, then take the max by score (max keeps the
        # FIRST of equals)
        order = {c: i for i, c in enumerate(ranked)}
        order.setdefault(DEFAULT, len(ranked))
        winner = max(sorted(ok_scores, key=lambda c: order[c]),
                     key=lambda c: _score_key(ok_scores[c]))
        cfg = TunedConfig(candidate=winner, key=key, source="probe",
                          score=ok_scores[winner],
                          baseline=scores.get(DEFAULT),
                          searched_s=time.perf_counter() - t0,
                          n_probed=len(scores), n_pruned=n_pruned,
                          audit=audit)
    else:
        # every probe failed or probing was off: the static model's
        # pick stands (deterministic)
        cfg = TunedConfig(candidate=static_winner, key=key,
                          source="static",
                          searched_s=time.perf_counter() - t0,
                          n_probed=len(scores), n_pruned=n_pruned,
                          audit=audit)
    if use_store:
        store_config(cfg)
    _profiler.incr_counter("tune_search")
    return cfg

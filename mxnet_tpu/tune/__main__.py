"""CLI: ``python -m mxnet_tpu.tune``.

Two modes:

* ``--probe spec.json`` — INTERNAL: the probe child. Runs one candidate
  in this process and prints its score as the last JSON line (the
  parent in :mod:`.probe` parses exactly that). Not for humans.
* ``--net <zoo name> | --symbol file.json`` — the user-facing search:
  tune a model against a budget and print the winner + audit trail.

Examples::

    python -m mxnet_tpu.tune --net mlp --budget 16G
    python -m mxnet_tpu.tune --net transformer --steps 8 --max-probes 4
    python -m mxnet_tpu.tune --symbol net.json --shape data=32,784 \\
        --shape softmax_label=32 --optimizer adam
"""
from __future__ import annotations

import argparse
import json
import sys


def _cmd_probe(path: str) -> int:
    with open(path) as f:
        spec = json.load(f)
    from .probe import run_probe_child
    try:
        score = run_probe_child(spec)
    except Exception as exc:   # scored failure, not a traceback dump
        score = {"ok": False, "why": "%s: %s"
                 % (type(exc).__name__, exc)}
    sys.stdout.flush()
    print(json.dumps(score))
    return 0 if score.get("ok") else 3


def _zoo(name: str, batch: int):
    """Probe-scale zoo builds: (symbol, data_shapes, label_shapes,
    data_dtypes)."""
    from ..analysis.__main__ import _zoo_symbol
    sym, shapes = _zoo_symbol(name)
    data_shapes, label_shapes = [], []
    for n, s in shapes.items():
        s = (batch,) + tuple(s[1:])
        (label_shapes if "label" in n else data_shapes).append((n, s))
    dtypes = {"data": "int32"} if name == "transformer" else {}
    return sym, data_shapes, label_shapes, dtypes


def main(argv=None) -> int:
    p = argparse.ArgumentParser(
        prog="python -m mxnet_tpu.tune",
        description="search the training-config space for a model")
    p.add_argument("--probe", metavar="SPEC",
                   help=argparse.SUPPRESS)   # internal child mode
    p.add_argument("--net", help="zoo model (mlp, resnet8, transformer)")
    p.add_argument("--symbol", help="symbol JSON file")
    p.add_argument("--shape", action="append", default=[],
                   metavar="name=d0,d1,...",
                   help="input shape (repeatable; required with "
                        "--symbol, overrides zoo defaults)")
    p.add_argument("--batch", type=int, default=32,
                   help="batch size for zoo nets (default 32)")
    p.add_argument("--budget", default=None,
                   help="HBM budget, e.g. 16G (default: unbudgeted)")
    p.add_argument("--optimizer", default="sgd")
    p.add_argument("--mode", choices=("auto", "static"), default="auto",
                   help="static = model-only, no probe subprocesses")
    p.add_argument("--steps", type=int, default=None,
                   help="measured steps per probe "
                        "(default MXNET_TPU_TUNE_PROBE_STEPS)")
    p.add_argument("--max-probes", type=int, default=None,
                   help="probe budget (default MXNET_TPU_TUNE_MAX_PROBES)")
    p.add_argument("--deadline", type=float, default=None,
                   help="per-probe deadline seconds "
                        "(default MXNET_TPU_TUNE_PROBE_SECS)")
    p.add_argument("--seed", type=int, default=0)
    p.add_argument("--no-store", action="store_true",
                   help="do not read/write the persisted config store")
    p.add_argument("--json", action="store_true",
                   help="print the full TunedConfig record as JSON")
    args = p.parse_args(argv)

    if args.probe:
        return _cmd_probe(args.probe)

    if not args.net and not args.symbol:
        p.error("one of --net / --symbol is required")

    from ..analysis.__main__ import _parse_shapes
    dtypes = {}
    if args.net:
        sym, data_shapes, label_shapes, dtypes = _zoo(args.net,
                                                      args.batch)
        over = _parse_shapes(args.shape)
        data_shapes = [(n, over.get(n, s)) for n, s in data_shapes]
        label_shapes = [(n, over.get(n, s)) for n, s in label_shapes]
    else:
        from ..symbol import load
        sym = load(args.symbol)
        shapes = _parse_shapes(args.shape)
        if not shapes:
            p.error("--symbol requires at least one --shape")
        data_shapes = [(n, s) for n, s in shapes.items()
                       if "label" not in n]
        label_shapes = [(n, s) for n, s in shapes.items()
                        if "label" in n]

    from .search import search
    cfg = search(sym, data_shapes, label_shapes,
                 optimizer=args.optimizer, budget=args.budget,
                 mode=args.mode, probe_steps=args.steps,
                 probe_deadline_s=args.deadline,
                 max_probes=args.max_probes, seed=args.seed,
                 data_dtypes=dtypes, use_store=not args.no_store,
                 log=lambda m: print(m, file=sys.stderr))

    if args.json:
        print(json.dumps(cfg.to_dict(), indent=1, sort_keys=True))
    else:
        print("winner (%s): %s" % (cfg.source, cfg.candidate.to_dict()))
        if cfg.score:
            print("score: mfu=%s steps/s=%s"
                  % (cfg.score.get("mfu"),
                     cfg.score.get("steps_per_sec")))
        if cfg.baseline and cfg.score:
            b, w = cfg.baseline, cfg.score
            if b.get("steps_per_sec"):
                print("vs default: %.2fx steps/s"
                      % (float(w.get("steps_per_sec") or 0)
                         / float(b["steps_per_sec"])))
        print("searched %.1fs, %d probed, %d pruned statically"
              % (cfg.searched_s, cfg.n_probed, cfg.n_pruned))
    return 0


if __name__ == "__main__":
    raise SystemExit(main())

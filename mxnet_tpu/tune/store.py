"""TunedConfig persistence, co-located with the AOT executable cache.

A search result is only worth its wall-clock if a RESTART gets it for
free: the winning :class:`TunedConfig` is serialized as JSON next to the
serialized fused-step executables (``aot.config_store_dir()``), keyed by
the same sha256 fingerprint scheme (``aot.digest`` over symbol JSON +
shapes/dtypes + optimizer statics + budget + device count, mixed with
the jax/device fingerprint). ``fit(tune="auto")`` loads the record, the
applied knobs reproduce the exact fused-step signature the winning probe
compiled under, and the AOT cache serves that executable — pre-tuned AND
pre-compiled, zero search cost, zero backend compiles.
"""
from __future__ import annotations

import json
import os
from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional

from .. import profiler as _profiler
from .space import Candidate

__all__ = ["TunedConfig", "program_key", "store_config", "load_config"]

STORE_VERSION = 1


@dataclass
class TunedConfig:
    """The search's winner plus its provenance."""
    candidate: Candidate
    key: str = ""
    source: str = "default"        # probe | static | default
    score: Optional[Dict[str, Any]] = None
    baseline: Optional[Dict[str, Any]] = None   # the default's probe
    searched_s: float = 0.0
    n_probed: int = 0
    n_pruned: int = 0
    audit: List[Dict[str, Any]] = field(default_factory=list)

    def to_dict(self) -> Dict[str, Any]:
        return {"version": STORE_VERSION, "key": self.key,
                "source": self.source,
                "candidate": self.candidate.to_dict(),
                "score": self.score, "baseline": self.baseline,
                "searched_s": round(self.searched_s, 3),
                "n_probed": self.n_probed, "n_pruned": self.n_pruned,
                "audit": self.audit}

    @classmethod
    def from_dict(cls, d: Dict[str, Any]) -> "TunedConfig":
        return cls(candidate=Candidate.from_dict(d.get("candidate")
                                                 or {}),
                   key=str(d.get("key", "")),
                   source=str(d.get("source", "default")),
                   score=d.get("score"), baseline=d.get("baseline"),
                   searched_s=float(d.get("searched_s", 0.0)),
                   n_probed=int(d.get("n_probed", 0)),
                   n_pruned=int(d.get("n_pruned", 0)),
                   audit=list(d.get("audit") or []))


def program_key(symbol_json: str, data_shapes, label_shapes,
                optimizer: str, optimizer_params, budget,
                n_devices: int) -> str:
    """The store key: everything that makes a tuned record applicable.
    Same scheme (and same device/jax fingerprint salt) as the AOT
    executable keys — a record never outlives the programs it tuned."""
    from .. import aot
    return aot.digest((
        "tune", symbol_json,
        sorted((str(n), tuple(s)) for n, s in data_shapes),
        sorted((str(n), tuple(s)) for n, s in (label_shapes or [])),
        str(optimizer), sorted(dict(optimizer_params or {}).items()),
        str(budget or ""), int(n_devices)))


def _path(key: str) -> Optional[str]:
    from .. import aot
    d = aot.config_store_dir()
    if not d:
        return None
    return os.path.join(d, "tune-%s.json" % key)


def store_config(cfg: TunedConfig) -> Optional[str]:
    """Atomically persist ``cfg``; returns the path, or None when no
    store directory is configured."""
    path = _path(cfg.key)
    if path is None:
        return None
    os.makedirs(os.path.dirname(path), exist_ok=True)
    from ..checkpoint.atomic import atomic_open
    with atomic_open(path, "w") as f:
        json.dump(cfg.to_dict(), f, indent=1, sort_keys=True)
    _profiler.incr_counter("tune_store_write")
    return path


def load_config(key: str) -> Optional[TunedConfig]:
    """The stored record for ``key``, or None (missing store dir,
    missing/corrupt record, or a version from the future)."""
    path = _path(key)
    if path is None or not os.path.exists(path):
        _profiler.incr_counter("tune_store_miss")
        return None
    try:
        with open(path) as f:
            d = json.load(f)
        if int(d.get("version", 0)) > STORE_VERSION:
            _profiler.incr_counter("tune_store_miss")
            return None
        cfg = TunedConfig.from_dict(d)
    except (OSError, ValueError, KeyError):
        _profiler.incr_counter("tune_store_miss")
        return None
    _profiler.incr_counter("tune_store_hit")
    return cfg

"""Static pruning + ranking of the candidate space (the cheap half of
the search — no compiles, no subprocesses).

Three rejections/orderings, all on the PR 8 cost/memory model via the
``analysis.tuning`` candidate hooks:

* **hbm-budget**: a candidate whose static peak (microbatch-aware
  liveness at its ``grad_accum``, minus its remat policy's calibrated
  ``est_peak_saving``, over its layout's per-device sharding) exceeds
  the budget cannot bind — rejected, counted ``tune_pruned``.
* **comm ranking**: layout candidates inherit their
  ``analysis.tuning.rank_layouts`` collective-bytes rank.
* **overhead ordering**: among survivors, prefer the cheaper mechanism
  — no remat over remat (recompute FLOPs), small ``grad_accum`` over
  large (scan overhead), scan+group+async defaults over their off
  arms — so the probe budget is spent on the plausible frontier.
"""
from __future__ import annotations

from typing import Any, Dict, List, Optional, Tuple

from .. import profiler as _profiler
from .space import Candidate

__all__ = ["static_rank"]


def _remat_saving(report, policy: str) -> int:
    from ..analysis import tuning as _tuning
    for cand in _tuning.remat_candidates(report):
        if cand["policy"] == policy or (
                policy == "auto" and cand["policy"] != "off"):
            return int(cand.get("est_peak_saving")
                       or cand.get("est_bytes_saved") or 0)
    return 0


def static_rank(sym, input_shapes: Dict[str, tuple],
                batch_inputs: List[str],
                candidates: List[Candidate],
                budget_bytes: Optional[int] = None,
                layout_rank: Optional[List[Dict[str, Any]]] = None,
                ) -> Tuple[List[Candidate], List[Dict[str, Any]]]:
    """Order ``candidates`` by the static model and drop the ones that
    cannot bind under ``budget_bytes``. Returns ``(ranked_survivors,
    audit)`` where ``audit`` records every candidate's estimated peak,
    remat saving and fate — the machine-readable trail the CLI prints
    and the store persists.

    Deterministic: one analyzer run per distinct ``grad_accum`` (cached
    here), a pure score per candidate, and ``Candidate.order_key()``
    (total-orderable even when layout-None and layout-tuple candidates
    tie on the score prefix) as the final tie-break."""
    from ..analysis import tuning as _tuning

    reports: Dict[int, Any] = {}

    def report_for(accum: int):
        if accum not in reports:
            reports[accum] = _tuning.cost_report(
                sym, input_shapes, grad_accum=accum,
                batch_inputs=batch_inputs)
        return reports[accum]

    lay_pos = {}
    if layout_rank:
        for i, rec in enumerate(layout_rank):
            lay_pos[(rec["data"], rec["fsdp"], rec["tp"])] = (
                i, rec["comm_bytes"])

    audit: List[Dict[str, Any]] = []
    scored: List[Tuple[tuple, Candidate]] = []
    for cand in candidates:
        rep = report_for(cand.grad_accum)
        peak = _tuning.peak_bytes(rep)
        saving = _remat_saving(rep, cand.remat) if cand.remat != "off" \
            else 0
        # floor at the bound buffers: remat recomputes activations but
        # can never erase params/inputs (the calibrated saving is
        # measured on the bigger fwd+bwd program and may exceed this
        # static graph's whole activation term)
        bound = int((rep.extras.get("cost") or {})
                    .get("bound_bytes") or 0)
        est_peak = None if peak is None else max(bound, peak - saving)
        n_shard = 1
        comm_rank, comm_bytes = 0, 0
        if cand.layout is not None:
            pos = lay_pos.get(cand.layout)
            if pos is None:
                _profiler.incr_counter("tune_pruned")
                audit.append({**cand.to_dict(), "fate": "pruned",
                              "why": "layout does not factor the mesh"})
                continue
            comm_rank, comm_bytes = pos
            # params shard over fsdp*tp, activations over the batch
            # axes — the coarse per-device divisor for the budget check
            n_shard = max(1, cand.layout[1] * cand.layout[2])
        rec = {**cand.to_dict(),
               "est_peak_bytes": est_peak,
               "est_remat_saving": saving,
               "comm_bytes": comm_bytes}
        if budget_bytes and est_peak is not None \
                and est_peak // n_shard > budget_bytes:
            _profiler.incr_counter("tune_pruned")
            audit.append({**rec, "fate": "pruned",
                          "why": "static peak %d > budget %d"
                                 % (est_peak // n_shard, budget_bytes)})
            continue
        audit.append({**rec, "fate": "kept"})
        # overhead ordering: comm rank first (layouts), then the cheap
        # mechanisms; order_key() is the deterministic tail — NOT the
        # dataclass itself, whose Optional layout makes None-vs-tuple
        # comparisons raise on a tied prefix (DEFAULT always ties the
        # top-ranked layout candidate with default knobs)
        score = (comm_rank,
                 0 if cand.remat == "off" else 1,
                 cand.grad_accum,
                 0 if cand.scan_layers == "auto" else 1,
                 0 if cand.group_update else 1,
                 0 if cand.async_window else 1,
                 cand.order_key())
        scored.append((score, cand))
    scored.sort(key=lambda t: t[0])
    return [c for _, c in scored], audit

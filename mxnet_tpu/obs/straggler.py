"""Pod straggler / stall detection — federated per-rank step telemetry.

Bulk-synchronous SPMD hides stragglers from naive wall-clock rates: one
slow rank stalls EVERY rank's batch cadence (the fast ranks just spend
the difference waiting on the cross-host reduction), so the pod's
steps/s degrades with no signal pointing at the culprit. This module
measures each rank's **host-side inter-step segment** — previous
batch's metric fetch → this batch's dispatch, where a rank's OWN
slowness lands (fault-injection sleeps, SIGSTOP pulses, input fetch,
callbacks) while a peer-wait never does: under async dispatch the
collective wait surfaces inside the dispatch/metric device syncs,
which the window excludes — and publishes per-rank
``(count, wall_s, work_s)`` windows to the
coordination KV **at the epoch log boundary only** (one KV write per
window, riding the existing ``metric_sync`` host fetch: zero extra
per-step host syncs, zero recompiles — counter-gated by the tests).

The leader (rank 0) aggregates every rank's latest window into the
``report()`` ``"pod"`` block — per-rank steps/s and work rates, the
slowest/fastest work-rate ratio — and flags ranks whose work rate falls
more than ``MXNET_TPU_OBS_STRAGGLER_RATIO`` behind the fastest:
``obs_straggler`` counts one per flagged rank per aggregation, and the
per-rank gauges (``obs_pod_steps_per_sec_r<r>``, ``obs_pod_work_per_sec_r<r>``,
``obs_pod_straggler_r<r>``, ``obs_pod_slow_fast_ratio``) surface on any
``/metrics`` endpoint — including the pod COORDINATOR's, whose monitor
refreshes them from the control-plane KV (the children of a coordinated
pod publish through ``MXNET_TPU_POD_KV``, so the telemetry survives
child restarts and is visible to the supervisor).

Zero-cost gate: a plain single-process fit never imports this module —
``fit`` only reaches for it when a pod channel exists (``MXNET_TPU_POD_KV``
or a multi-worker DMLC env) AND the ratio knob is positive.
"""
from __future__ import annotations

import json
import logging
import os
import threading
import time
from typing import Any, Dict, Optional

from .. import config as _config
from .. import lockcheck as _lockcheck
from .. import profiler as _profiler

__all__ = ["FitPublisher", "aggregate", "refresh_gauges", "pod_block",
           "KEY_FMT"]

log = logging.getLogger(__name__)

# generation-scoped so a pod restart cannot aggregate a previous
# generation's stale windows against fresh ones
KEY_FMT = "mxobs/g%d/steps/%d"

_block_lock = _lockcheck.Lock(name="obs.straggler.block_lock")
_last_block: Optional[Dict[str, Any]] = None
# ranks whose per-rank gauges this process has set: a rank that leaves
# the pod (death, reshard to a smaller world) must have its gauges
# zeroed on the next aggregation, or /metrics serves a permanent false
# straggler alarm for a host that no longer exists
_gauged_ranks: set = set()


class _Channel(object):
    """Where the windows live: the pod coordinator's control-plane KV
    when ``MXNET_TPU_POD_KV`` names it (coordinated children — readable
    by the supervisor, survives child restarts), else the process's own
    coordination KV (``dist.kv_set``/``kv_get`` — plain launcher pods)."""

    def __init__(self, addr: Optional[str]):
        self._client = None
        if addr:
            from ..parallel import dist as _dist
            self._client = _dist.PodKVClient(addr)

    def set(self, key: str, value: str) -> None:
        if self._client is not None:
            self._client.set(key, value)
        else:
            from ..parallel import dist as _dist
            _dist.kv_set(key, value)

    def get(self, key: str, timeout_ms: int) -> Optional[str]:
        if self._client is not None:
            return self._client.get(key, timeout_ms)
        from ..parallel import dist as _dist
        return _dist.kv_get(key, timeout_ms)


def _gen() -> int:
    try:
        return int(os.environ.get("MXNET_TPU_POD_GEN", "0") or 0)
    except ValueError:
        return 0


class FitPublisher(object):
    """Per-process step-window accumulator the fit loop drives.

    ``step(work_s)`` is called once per batch with the LOCAL host-side
    inter-step duration (previous metric fetch → this dispatch — see
    the module docstring for why that segment is peer-wait-free); the
    wall cadence accumulates from the call marks themselves. The first
    batch of every window only sets the baseline (its compile/fill
    time must not skew the rate). ``publish(epoch)`` writes the window
    and — on rank 0 — aggregates."""

    def __init__(self, rank: int, world: int, channel: _Channel,
                 pod_rank: Optional[int] = None):
        self.rank = int(rank)
        self.world = int(world)
        # the STABLE identity stragglers are reported under: the
        # original pod rank when the coordinator exported it (DMLC
        # ranks are generation-renumbered after a fail-over — flagging
        # by them would point an operator at the wrong host; the
        # flight-recorder files use the same original-rank naming)
        self.pod_rank = int(rank if pod_rank is None else pod_rank)
        self._chan = channel
        self._count = 0
        self._wall = 0.0
        self._work = 0.0
        self._last: Optional[float] = None

    @classmethod
    def create(cls) -> Optional["FitPublisher"]:
        """The fit-loop gate: None unless straggler detection is on
        (ratio knob > 0) and a pod with a telemetry channel is active."""
        if float(_config.get("MXNET_TPU_OBS_STRAGGLER_RATIO")) <= 0:
            return None
        addr = os.environ.get("MXNET_TPU_POD_KV")
        if addr:
            try:
                rank = int(os.environ.get("DMLC_WORKER_ID", "0"))
                world = int(os.environ.get("DMLC_NUM_WORKER", "1"))
            except ValueError:
                return None
        else:
            from ..checkpoint.format import pod_info
            rank, world = pod_info()
        if world <= 1:
            return None
        try:
            pod_rank = int(os.environ.get("MXNET_TPU_POD_RANK", rank))
        except ValueError:
            pod_rank = rank
        try:
            return cls(rank, world, _Channel(addr), pod_rank=pod_rank)
        except Exception:                                  # noqa: BLE001
            log.debug("straggler telemetry unavailable", exc_info=True)
            return None

    def step(self, work_s: float) -> None:
        now = time.perf_counter()
        if self._last is not None:
            self._wall += now - self._last
            self._work += float(work_s)
            self._count += 1
        self._last = now

    def publish(self, epoch: int) -> None:
        """One KV write per log boundary; rank 0 also aggregates. A dark
        control plane (mid-fail-over) must never fail the fit loop."""
        if self._count <= 0:
            return
        payload = {"rank": self.rank, "pod_rank": self.pod_rank,
                   "epoch": int(epoch),
                   "gen": _gen(), "count": self._count,
                   "wall_s": round(self._wall, 6),
                   "work_s": round(self._work, 6)}
        try:
            self._chan.set(KEY_FMT % (_gen(), self.rank),
                           json.dumps(payload))
        except Exception:                                  # noqa: BLE001
            _profiler.incr_counter("obs_straggler_publish_failed")
            log.debug("straggler window publish failed", exc_info=True)
        self._count, self._wall, self._work = 0, 0.0, 0.0
        self._last = None
        if self.rank == 0:
            try:
                aggregate(self.world, reader=self._chan.get)
            except Exception:                              # noqa: BLE001
                log.debug("straggler aggregation failed", exc_info=True)


def _read_windows(world: int, reader, timeout_ms: int,
                  gen: Optional[int] = None) -> Dict[int, Dict[str, Any]]:
    windows: Dict[int, Dict[str, Any]] = {}
    gen = _gen() if gen is None else int(gen)
    for r in range(world):
        try:
            raw = reader(KEY_FMT % (gen, r), timeout_ms)
        except Exception:                                  # noqa: BLE001
            raw = None
        if raw is None:
            continue
        try:
            windows[r] = json.loads(raw)
        except ValueError:
            continue
    return windows


def aggregate(world: int, reader, timeout_ms: int = 200,
              gen: Optional[int] = None) -> Optional[Dict[str, Any]]:
    """Leader-side rollup of every rank's latest window: per-rank
    steps/s (wall cadence) and work rate (count / local work seconds),
    the slowest/fastest work-rate ratio, and the flagged stragglers.
    Sets the per-rank gauges, bumps ``obs_straggler`` once per flagged
    rank, and stores the block ``mx.obs.report()`` attaches."""
    global _last_block
    ratio_knob = float(_config.get("MXNET_TPU_OBS_STRAGGLER_RATIO"))
    windows = _read_windows(int(world), reader, int(timeout_ms), gen)
    if not windows:
        return None
    ranks: Dict[str, Dict[str, Any]] = {}
    rates: Dict[int, float] = {}
    for slot, w in sorted(windows.items()):
        # report under the STABLE pod rank the publisher recorded
        # (generation-renumbered DMLC slots would point an operator at
        # the wrong host after a fail-over); pre-pod_rank windows fall
        # back to the slot
        r = int(w.get("pod_rank", w.get("rank", slot)))
        count = max(0, int(w.get("count", 0)))
        wall = float(w.get("wall_s", 0.0))
        work = float(w.get("work_s", 0.0))
        steps_s = count / wall if count and wall > 0 else None
        work_rate = count / work if count and work > 0 else None
        ranks[str(r)] = {"epoch": w.get("epoch"), "steps": count,
                         "steps_per_sec": round(steps_s, 3)
                         if steps_s else None,
                         "work_per_sec": round(work_rate, 3)
                         if work_rate else None}
        if steps_s:
            _profiler.set_gauge("obs_pod_steps_per_sec_r%d" % r, steps_s)
        if work_rate:
            _profiler.set_gauge("obs_pod_work_per_sec_r%d" % r, work_rate)
            rates[r] = work_rate
    stragglers = []
    ratio = None
    if len(rates) >= 2:
        fastest = max(rates.values())
        slowest = min(rates.values())
        ratio = fastest / slowest if slowest > 0 else None
        _profiler.set_gauge("obs_pod_slow_fast_ratio", ratio or 0.0)
        if ratio_knob > 0:
            stragglers = sorted(r for r, rate in rates.items()
                                if fastest / rate > ratio_knob)
    for r in rates:
        _profiler.set_gauge("obs_pod_straggler_r%d" % r,
                            1.0 if r in stragglers else 0.0)
    # a rank that left the pod must not keep serving its last gauges
    # (a dead host flagged 1.0 forever is a permanent false alarm)
    seen = set(rates) | {int(r) for r in ranks}
    for r in sorted(_gauged_ranks - seen):
        _profiler.set_gauge("obs_pod_straggler_r%d" % r, 0.0)
        _profiler.set_gauge("obs_pod_steps_per_sec_r%d" % r, 0.0)
        _profiler.set_gauge("obs_pod_work_per_sec_r%d" % r, 0.0)
    _gauged_ranks.clear()
    _gauged_ranks.update(seen)
    if stragglers:
        _profiler.incr_counter("obs_straggler", len(stragglers))
        log.warning(
            "pod stragglers: rank(s) %s more than %.1fx slower (local "
            "work rate) than the fastest rank — check the host (IO "
            "stalls, thermal throttle, noisy neighbor); per-rank rates: "
            "%s", stragglers, ratio_knob,
            {r: round(v, 3) for r, v in sorted(rates.items())})
    block = {"ranks": ranks, "slow_fast_ratio": round(ratio, 3)
             if ratio else None,
             "stragglers": stragglers, "ratio_threshold": ratio_knob}
    with _block_lock:
        _last_block = block
    return block


def refresh_gauges(world: int, timeout_ms: int = 100,
                   gen: Optional[int] = None) -> Optional[Dict[str, Any]]:
    """Coordinator-side gauge refresh: read the windows from whatever KV
    backend ``dist`` currently routes to (the pod coordinator's is its
    control-plane PodKV client) — the opt-in ``/metrics`` endpoint then
    exposes the leader's per-rank straggler view without the coordinator
    ever touching a jax backend."""
    from ..parallel import dist as _dist
    return aggregate(world, reader=_dist.kv_get, timeout_ms=timeout_ms,
                     gen=gen)


def pod_block() -> Optional[Dict[str, Any]]:
    """The last aggregation result (``mx.obs.report()["pod"]``)."""
    with _block_lock:
        return _last_block

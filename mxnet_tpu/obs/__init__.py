"""``mx.obs`` — unified observability: trace timeline, metrics
exposition, and always-on utilization/compile accounting
(docs/architecture/observability.md).

The reference framework's engine emits a flat chrome://tracing timeline
(src/engine/profiler.cc:127-179); this stack is deeply asynchronous —
prefetch worker, training thread, in-flight window, checkpoint writer,
serve coalescer — so the timeline here is **structured**: spans on
stable named lanes with chrome-trace flow events linking one batch or
request across threads. Four surfaces, one module:

* **Spans / lanes / flows** (re-exported from :mod:`mxnet_tpu.profiler`,
  where subsystems record without importing obs): ``span()``,
  ``new_flow()``, ``register_thread_lane()``; enabled by the profiler
  state or the ``MXNET_TPU_OBS`` knob, shared-no-op otherwise.
* **Metrics exposition**: ``render_prometheus()`` over the always-on
  counters/gauges/histograms, ``parse_prometheus()`` as the pure-Python
  grammar check, and an opt-in HTTP ``/metrics`` endpoint
  (``start_metrics_server``, auto-wired into ``serve.InferenceServer``
  via ``MXNET_TPU_OBS_METRICS_PORT``).
* **Compile accounting** (always on, :mod:`.compiles`): every executable
  build is attributed to its dispatch site + cache signature via
  jax.monitoring and lands in a bounded ring with trace/lower/compile
  phase times — ``obs_bind_ms`` / ``obs_trace_ms`` histograms,
  ``obs_compile_count`` counter. A 25-minute bind wedge is diagnosable
  from ``report()``, not just from the bench harness.
* **Utilization accounting** (:mod:`.mfu`): bound executors export
  ``obs_mfu`` / ``obs_flops_per_sec`` gauges — analysis-cost-model FLOPs
  x measured steps/s between report() calls.

``report()`` is the one-call snapshot of all of it.
"""
from __future__ import annotations

from typing import Any, Dict

from .. import profiler as _profiler
from ..profiler import (span, spans_enabled, new_flow,            # noqa: F401
                        register_thread_lane, Histogram, histogram,
                        observe, counter_delta)
from . import compiles
from .compiles import scope as compile_scope                      # noqa: F401
from .prometheus import (render_prometheus, parse_prometheus,     # noqa: F401
                         pod_labels)
from . import mfu
from .mfu import peak_flops, register_executor                    # noqa: F401
from .http import MetricsServer, start_metrics_server             # noqa: F401

__all__ = [
    "span", "spans_enabled", "new_flow", "register_thread_lane",
    "Histogram", "histogram", "observe", "counter_delta",
    "compile_scope", "compiles",
    "render_prometheus", "parse_prometheus", "pod_labels",
    "mfu", "peak_flops", "register_executor",
    "MetricsServer", "start_metrics_server",
    "report", "probe_score", "blackbox", "straggler",
]


def __getattr__(name):
    # the pod observability layer stays zero-import until something
    # actually arms it: the flight recorder and the straggler publisher
    # are knob-gated at every call site, so the package must not drag
    # them in (the CI multihost zero-cost gate asserts both absent
    # after a plain fit)
    if name in ("blackbox", "straggler"):
        import importlib
        return importlib.import_module("." + name, __name__)
    raise AttributeError("module %r has no attribute %r"
                         % (__name__, name))

# the jax.monitoring compile listener is the always-on layer: installed
# at package import, zero cost outside compiles
compiles.install()


def report() -> Dict[str, Any]:
    """One observability snapshot: per-executor utilization (this call
    is the rate boundary — see :mod:`.mfu`), roofline reconciliation
    (*why* is MFU what it is — compute- vs memory-bound, attainable vs
    measured; attached when the analysis package is already loaded,
    which the MFU collector's lazy import guarantees whenever there is
    a FLOP count to explain), the compile ring, and the ``obs_*``
    counters/gauges/histogram summaries."""
    executors = mfu.collect()
    import sys
    if "mxnet_tpu.analysis" in sys.modules:
        from ..analysis import roofline as _roofline
        for rec in executors:
            cost = rec.get("cost") or {}
            if cost.get("flops") and cost.get("bytes_moved"):
                rec["roofline"] = _roofline.explain(
                    cost["flops"], cost["bytes_moved"],
                    measured_mfu=rec.get("mfu"))
    hist = {}
    for name, h in _profiler.histograms().items():
        if not name.startswith("obs_"):
            continue
        snap = h.snapshot()
        hist[name] = {
            "count": snap["count"],
            "sum": round(snap["sum"], 3),
            "max": snap["max"],
            "p50": h.quantile(0.50),
            "p99": h.quantile(0.99),
        }
    out = {
        "executors": executors,
        "compiles": compiles.snapshot(),
        "counters": {k: v for k, v in _profiler.counters().items()
                     if k.startswith("obs_")},
        "gauges": {k: v for k, v in _profiler.gauges().items()
                   if k.startswith("obs_")},
        "histograms": hist,
    }
    labels = pod_labels()
    if labels:
        # multi-host: every host reports under its own identity so
        # aggregation across the pod is explicit, never a collision
        out["process"] = {"process_index": int(labels["process_index"]),
                          "world_size": int(labels["world_size"])}
    if "mxnet_tpu.obs.straggler" in sys.modules:
        # the pod block: per-rank steps/s + work rates and the flagged
        # stragglers, as of the leader's last log-boundary aggregation
        # (lazy — never imports the pod stack into a plain process)
        from . import straggler as _straggler
        block = _straggler.pod_block()
        if block is not None:
            out["pod"] = block
    return out


def probe_score() -> Dict[str, Any]:
    """Close the current utilization window and return the compact
    verdict a tuner probe is scored by (:mod:`mxnet_tpu.tune`): the
    busiest executor's ``steps_per_sec``/``mfu``/``flops_per_sec``, the
    pod throughput block when a pod is live, and ``loop_recompile`` —
    the disqualifier (a config that thrashes the executable cache can
    never win a probe). Call once after warmup to open the window
    (``report()`` works too) and once after the measured region."""
    rep = report()
    best = None
    for rec in rep["executors"]:
        if rec.get("steps_per_sec") and (
                best is None
                or rec["steps_per_sec"] > best["steps_per_sec"]):
            best = rec
    return {
        "steps_per_sec": best["steps_per_sec"] if best else None,
        "mfu": best.get("mfu") if best else None,
        "flops_per_sec": best.get("flops_per_sec") if best else None,
        "pod": rep.get("pod"),
        "loop_recompile": int(
            _profiler.counters().get("loop_recompile", 0)),
    }

"""Opt-in HTTP ``/metrics`` endpoint (Prometheus scrape target).

A stdlib ``ThreadingHTTPServer`` on a daemon thread serving
:func:`~mxnet_tpu.obs.prometheus.render_prometheus` — no dependencies,
off by default. ``serve.InferenceServer`` auto-starts one when the
``MXNET_TPU_OBS_METRICS_PORT`` knob (or its ``metrics_port=`` argument)
says so; anything else can call :func:`start_metrics_server` directly.
Binds 127.0.0.1 by default: exposing process metrics beyond the host is
a deployment decision, not a framework default.
"""
from __future__ import annotations

import threading
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Optional

from .prometheus import render_prometheus

__all__ = ["MetricsServer", "start_metrics_server"]


class _Handler(BaseHTTPRequestHandler):
    def do_GET(self):                                      # noqa: N802
        if self.path.split("?", 1)[0] != "/metrics":
            self.send_error(404, "try /metrics")
            return
        try:
            # a scrape is a log boundary: refresh the obs_mfu /
            # obs_flops_per_sec gauges (one block on the last dispatched
            # step per registered module — see mfu.collect)
            from . import mfu as _mfu
            _mfu.collect()
        except Exception:                                  # noqa: BLE001
            pass    # exposition must render even if a collector dies
        render = getattr(self.server, "render_fn", None) \
            or render_prometheus
        try:
            text = render()
        except Exception:                                  # noqa: BLE001
            # a federating renderer (fleet gateway pulling replica
            # expositions) may fail mid-poll: fall back to this
            # process's own registry rather than failing the scrape
            text = render_prometheus()
        body = text.encode("utf-8")
        self.send_response(200)
        self.send_header("Content-Type",
                         "text/plain; version=0.0.4; charset=utf-8")
        self.send_header("Content-Length", str(len(body)))
        self.end_headers()
        self.wfile.write(body)

    def log_message(self, *_args):    # scrapes must not spam stderr
        pass


class MetricsServer(object):
    """Daemon-thread /metrics endpoint; ``port=0`` binds an ephemeral
    port (read it back from ``.port``)."""

    def __init__(self, port: int = 0, host: str = "127.0.0.1",
                 render=None):
        self._httpd = ThreadingHTTPServer((host, int(port)), _Handler)
        self._httpd.daemon_threads = True
        # optional exposition override: a federating endpoint (the
        # fleet gateway) renders an AGGREGATED text instead of this
        # process's registry; None keeps render_prometheus
        self._httpd.render_fn = render
        self.host = self._httpd.server_address[0]
        self.port = int(self._httpd.server_address[1])
        self._thread = threading.Thread(
            target=self._httpd.serve_forever,
            name="mxnet_tpu.obs[/metrics:%d]" % self.port, daemon=True)
        self._thread.start()

    @property
    def url(self) -> str:
        return "http://%s:%d/metrics" % (self.host, self.port)

    def close(self) -> None:
        self._httpd.shutdown()
        self._httpd.server_close()
        self._thread.join(timeout=5.0)

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()
        return False


def start_metrics_server(port: int = 0, host: str = "127.0.0.1",
                         render=None) -> MetricsServer:
    """Start (and return) a /metrics endpoint; caller owns ``close()``.
    ``render`` (optional) overrides the exposition text — the fleet
    gateway passes its replica-aggregating renderer here."""
    return MetricsServer(port=port, host=host, render=render)


def maybe_start_from_knob(explicit: Optional[int] = None) \
        -> Optional[MetricsServer]:
    """Endpoint policy shared by subsystems: an explicit ``metrics_port``
    argument wins; None falls back to the ``MXNET_TPU_OBS_METRICS_PORT``
    knob; a resolved value < 0 means off."""
    port = explicit
    if port is None:
        from .. import config as _config
        port = int(_config.get("MXNET_TPU_OBS_METRICS_PORT"))
    if port is None or port < 0:
        return None
    return MetricsServer(port=port)

"""Always-on utilization accounting: ``obs_mfu`` / ``obs_flops_per_sec``.

ROADMAP item 3 demands the MFU campaign be self-auditing — until now MFU
existed only inside ``bench.py``'s arithmetic. Here the framework
computes its own: every ``Module`` with a fused train step registers a
weak collector; ``collect()`` (run by ``mx.obs.report()`` and the
Prometheus exposition) measures completed steps per wall second and
multiplies by the static per-step FLOP count from the
:mod:`mxnet_tpu.analysis` cost model (forward FLOPs x3 for a training
step — the same fwd + ~2x-in-bwd convention ``bench.py`` uses).

Two deliberate choices keep the hot loop untouched:

* The per-step cost is two ``perf_counter`` reads and two attribute
  writes (``Module`` records them inline); no locks, no device syncs.
* Rates are measured **between collects**: a collect blocks on the last
  dispatched step (one sync — it is a diagnostic read, exactly a log
  boundary) and the steps/s is (steps since previous collect)/(wall
  since previous collect). ``mx.obs.report()`` and the HTTP ``/metrics``
  endpoint both collect. Call ``report()`` once after warmup and once
  after the measured region — like a Prometheus ``rate()`` — and the
  window excludes compile time. The analysis import happens lazily at
  the first collect, never at bind, preserving the
  ``MXNET_TPU_ANALYZE=off`` zero-cost guarantee.

Peak FLOP/s resolves from the TPU ``device_kind`` (same table as
``bench.py``'s independent math, which stays separate on purpose — the
acceptance cross-check is only meaningful if the two computations do not
share code paths for the rate) or the ``MXNET_TPU_OBS_PEAK_FLOPS``
override for unknown devices and tests.
"""
from __future__ import annotations

import threading
import time
import weakref
from typing import Any, Dict, List, Optional

import numpy as np

from .. import config as _config
from .. import lockcheck as _lockcheck
from .. import profiler as _profiler

__all__ = ["peak_flops", "register_executor", "collect",
           "OBS_WARMUP_STEPS", "TRAIN_FLOP_MULTIPLIER"]

# steps skipped before the rate window opens (the compile steps)
OBS_WARMUP_STEPS = 2
# training step ~ 3x forward FLOPs (fwd + ~2x in bwd) — bench.py's
# TRAIN_FLOPS_PER_IMG uses the same convention
TRAIN_FLOP_MULTIPLIER = 3.0

# dense bf16 peak FLOP/s by TPU generation (device_kind substring match).
# The ONE copy of this table: bench.py imports it too — its rate and FLOP
# math stay independent for the cross-check, but a constants table that
# drifted between the two would fail (or falsely pass) the comparison.
PEAK_FLOPS_BY_DEVICE_KIND = [
    ("v5 lite", 197e12), ("v5e", 197e12), ("v5p", 459e12),
    ("v6", 918e12), ("v4", 275e12), ("v3", 123e12), ("v2", 45e12)]
_PEAK = PEAK_FLOPS_BY_DEVICE_KIND

_reg_lock = _lockcheck.Lock(name="obs.mfu.reg_lock")
# serializes whole collects: two concurrent collectors (report() + a
# /metrics scrape) must not race the read-modify-write of each module's
# rate baseline. Note the baseline itself is SHARED across consumers —
# every collect closes and reopens the window, so an interleaved scrape
# shortens (never skews) a report() pair's window: rates stay
# steady-state estimates, just noisier. Benches following the
# report()-after-warmup / report()-after-region recipe should not point
# a concurrent scraper at the same process during the timed region.
_collect_lock = _lockcheck.Lock(name="obs.mfu.collect_lock")
_executors: List[weakref.ref] = []


def peak_flops(device_kind: Optional[str] = None) -> Optional[float]:
    """Peak dense FLOP/s: the ``MXNET_TPU_OBS_PEAK_FLOPS`` override wins,
    else the device-kind table; None when unknown (MFU is then not
    fabricated)."""
    override = float(_config.get("MXNET_TPU_OBS_PEAK_FLOPS"))
    if override > 0:
        return override
    if device_kind is None:
        try:
            import jax
            device_kind = jax.devices()[0].device_kind
        except Exception:                                  # noqa: BLE001
            return None
    dk = (device_kind or "").lower()
    for sub, peak in _PEAK:
        if sub in dk:
            return peak
    return None


def register_executor(mod) -> None:
    """Weakly register a Module for collection (called from
    ``Module._build_fused_step``; dead refs are swept on every call)."""
    with _reg_lock:
        _executors[:] = [r for r in _executors
                         if r() is not None and r() is not mod]
        _executors.append(weakref.ref(mod))


def _flops_per_step(mod) -> Optional[float]:
    """Static FLOPs of one fused train step via the analysis cost model,
    cached on the module (0.0 caches a failed/unavailable analysis so it
    is attempted once, not per collect)."""
    cached = getattr(mod, "_obs_flops_per_step", None)
    if cached is not None:
        return cached or None
    val = 0.0
    try:
        report = mod.analyze()
        cost = report.extras.get("cost", {})
        fwd = float(cost.get("flops") or 0)
        mult = TRAIN_FLOP_MULTIPLIER \
            if getattr(mod, "optimizer_initialized", False) else 1.0
        val = fwd * mult
        # cost-model bytes ride along for the roofline reconciliation in
        # mx.obs.report() — the "why" next to the MFU number (the train
        # step touches roughly the same tensors ~3x, so the forward
        # intensity is the step intensity to first order)
        mod._obs_cost = {"flops": val,
                         "bytes_moved": float(cost.get("bytes_moved")
                                              or 0) * mult}
    except Exception:                                      # noqa: BLE001
        pass       # partial graphs / custom ops: report without MFU
    mod._obs_flops_per_step = val
    return val or None


def collect() -> List[Dict[str, Any]]:
    """One utilization sample per live registered module; updates the
    ``obs_mfu`` / ``obs_flops_per_sec`` gauges from the busiest one.
    Serialized: see ``_collect_lock`` for the shared-window semantics."""
    with _collect_lock:
        return _collect_locked()


def _collect_locked() -> List[Dict[str, Any]]:
    with _reg_lock:
        refs = list(_executors)
    live = [m for m in (ref() for ref in refs) if m is not None]
    if not live:
        # nothing to rate — and do NOT resolve the peak (jax.devices()
        # would INITIALIZE a backend): a /metrics scrape of a process
        # that never trains, e.g. the pod coordinator's endpoint, must
        # stay backend-free
        return []
    peak = peak_flops()
    out: List[Dict[str, Any]] = []
    best = None
    for mod in live:
        steps = int(getattr(mod, "_obs_steps", 0))
        mesh = getattr(mod, "_mesh", None)
        rec: Dict[str, Any] = {
            "name": getattr(mod, "_obs_label", type(mod).__name__),
            "steps": steps,
            "flops_per_step": _flops_per_step(mod),
            "steps_per_sec": None,
            "flops_per_sec": None,
            "mfu": None,
            "peak_flops": peak,
            "cost": getattr(mod, "_obs_cost", None),
            # mesh provenance so multi-chip benches report MFU PER MESH
            # SHAPE (no parallel import — read the Mesh object directly)
            "mesh": {str(a): int(s) for a, s in
                     zip(mesh.axis_names, mesh.devices.shape)}
            if mesh is not None else None,
        }
        t0 = getattr(mod, "_obs_t0", None)
        # >= so a collect at EXACTLY warmup steps (bench.py's
        # open-the-window report after its 2 warmup iterations) still
        # sets the baseline; dn == 0 then just reports no rate yet
        if steps >= OBS_WARMUP_STEPS and t0 is not None:
            token = None
            step_token = getattr(mod, "_step_token", None)
            if step_token is not None:
                token = step_token()
            if token is not None:
                try:
                    import jax
                    # the rate window must close on COMPLETED device
                    # work, and serializing whole collects (including
                    # this wait) under _collect_lock IS the documented
                    # shared-window semantics — see _collect_lock
                    jax.block_until_ready(token)  # mx-lint: allow(lock-host-sync)
                except Exception:                          # noqa: BLE001
                    pass
            now = time.perf_counter()
            base = getattr(mod, "_obs_baseline", None) \
                or (OBS_WARMUP_STEPS, t0)
            dn, dt = steps - base[0], now - base[1]
            if dn > 0 and dt > 0:
                rec["steps_per_sec"] = dn / dt
            mod._obs_baseline = (steps, now)
        if rec["steps_per_sec"] and rec["flops_per_step"]:
            fs = rec["steps_per_sec"] * rec["flops_per_step"]
            rec["flops_per_sec"] = fs
            if peak:
                # a mesh-bound module's denominator is the WHOLE mesh's
                # peak — flops_per_step is whole-model work, spread over
                # every device of the mesh
                n_dev = int(np.prod(list(rec["mesh"].values()))) \
                    if rec["mesh"] else 1
                rec["mfu"] = fs / (peak * max(1, n_dev))
            if best is None or fs > best["flops_per_sec"]:
                best = rec
        out.append(rec)
    if best is not None:
        _profiler.set_gauge("obs_flops_per_sec", best["flops_per_sec"])
        if best["mfu"] is not None:
            _profiler.set_gauge("obs_mfu", best["mfu"])
    return out

"""Prometheus text-format exposition of the profiler registries.

``render_prometheus()`` turns the always-on counters/gauges/histograms
(:mod:`mxnet_tpu.profiler`) into the Prometheus text format
(version 0.0.4): counters get a ``_total`` suffix, histograms emit the
standard cumulative ``_bucket{le="..."}`` / ``_sum`` / ``_count`` triple
(sparse: only buckets that hold observations, plus the mandatory
``+Inf``). ``parse_prometheus()`` is the matching pure-Python grammar
check the CI ``obs`` job and the tests run on the rendered text — no
external scrape client needed to prove the exposition is well-formed.
"""
from __future__ import annotations

import math
import re
from typing import Dict, Optional, Tuple

from .. import profiler as _profiler

__all__ = ["render_prometheus", "parse_prometheus", "pod_labels"]

_SANITIZE_RE = re.compile(r"[^a-zA-Z0-9_]")


def _metric_name(prefix: str, name: str) -> str:
    n = _SANITIZE_RE.sub("_", str(name))
    if n and n[0].isdigit():
        n = "_" + n
    return "%s_%s" % (prefix, n)


def _fmt(v) -> str:
    f = float(v)
    if not math.isfinite(f):
        # the text format's spellings — parse_prometheus round-trips them
        return "NaN" if math.isnan(f) else ("+Inf" if f > 0 else "-Inf")
    if f == int(f) and abs(f) < 1e15:
        return str(int(f))
    return repr(f)


def pod_labels() -> Dict[str, str]:
    """Per-host identity labels when a ``jax.distributed`` pod is active
    (empty otherwise): every host of a pod scrapes the same metric
    names, so without these labels federated/aggregated scrapes would
    COLLIDE — rank 3's ``ckpt_saved_total`` silently overwriting rank
    0's. A pure state probe (``checkpoint.format.pod_info``)."""
    from ..checkpoint.format import pod_info
    rank, world = pod_info()
    if world <= 1:
        return {}
    return {"process_index": str(rank), "world_size": str(world)}


def _label_str(labels: Dict[str, str], extra: str = "") -> str:
    parts = ['%s="%s"' % (k, v) for k, v in sorted(labels.items())]
    if extra:
        parts.append(extra)
    return "{%s}" % ",".join(parts) if parts else ""


def render_prometheus(prefix: str = "mxnet_tpu",
                      labels: Optional[Dict[str, str]] = None) -> str:
    """One scrape body over every registered counter, gauge and
    histogram. Metric names are ``<prefix>_<sanitized registry key>``.

    ``labels`` are attached to every sample; by default they are the
    pod identity labels (:func:`pod_labels` — ``process_index`` /
    ``world_size`` under multi-host, nothing single-process), so
    per-host telemetry federates instead of colliding. Pass ``{}`` to
    force bare samples."""
    if labels is None:
        labels = pod_labels()
    lab = _label_str(labels)
    lines = []
    for name, v in sorted(_profiler.counters().items()):
        m = _metric_name(prefix, name)
        if not m.endswith("_total"):    # registry keys like
            m += "_total"               # obs_bind_ms_total keep one suffix
        lines.append("# TYPE %s counter" % m)
        lines.append("%s%s %s" % (m, lab, _fmt(v)))
    for name, v in sorted(_profiler.gauges().items()):
        m = _metric_name(prefix, name)
        lines.append("# TYPE %s gauge" % m)
        lines.append("%s%s %s" % (m, lab, _fmt(v)))
    for name, h in sorted(_profiler.histograms().items()):
        snap = h.snapshot()
        m = _metric_name(prefix, name)
        lines.append("# TYPE %s histogram" % m)
        cum = 0
        for bound, c in zip(snap["bounds"], snap["counts"]):
            cum += c
            if c:
                lines.append('%s_bucket%s %d'
                             % (m, _label_str(labels,
                                              'le="%.6g"' % bound), cum))
        lines.append('%s_bucket%s %d'
                     % (m, _label_str(labels, 'le="+Inf"'),
                        snap["count"]))
        lines.append("%s_sum%s %s" % (m, lab, _fmt(snap["sum"])))
        lines.append("%s_count%s %d" % (m, lab, snap["count"]))
    return "\n".join(lines) + "\n"


# ------------------------------------------------------- grammar check

_METRIC_RE = r"[a-zA-Z_:][a-zA-Z0-9_:]*"
_SAMPLE_RE = re.compile(
    r"^(?P<name>%s)"
    r"(?:\{(?P<labels>[^}]*)\})?"
    r"\s+(?P<value>[^\s]+)"
    r"(?:\s+(?P<ts>-?\d+))?$" % _METRIC_RE)
_LABEL_RE = re.compile(
    r'(?P<k>[a-zA-Z_][a-zA-Z0-9_]*)="(?P<v>(?:[^"\\]|\\.)*)"')
_COMMENT_RE = re.compile(
    r"^# (?:HELP %s .*|TYPE %s (?:counter|gauge|histogram|summary|"
    r"untyped))$" % (_METRIC_RE, _METRIC_RE))


def _parse_value(s: str) -> float:
    if s == "+Inf":
        return math.inf
    if s == "-Inf":
        return -math.inf
    if s == "NaN":
        return math.nan
    return float(s)       # raises ValueError on garbage


def parse_prometheus(text: str) \
        -> Dict[Tuple[str, Tuple[Tuple[str, str], ...]], float]:
    """Strict parse of a text-format exposition; raises ``ValueError``
    on any malformed line. Returns ``{(metric, sorted label tuple):
    value}`` so tests can assert on specific samples."""
    samples: Dict[Tuple[str, Tuple[Tuple[str, str], ...]], float] = {}
    for lineno, line in enumerate(text.split("\n"), 1):
        if not line.strip():
            continue
        if line.startswith("#"):
            if not _COMMENT_RE.match(line):
                raise ValueError(
                    "line %d: malformed comment/metadata: %r"
                    % (lineno, line))
            continue
        m = _SAMPLE_RE.match(line)
        if m is None:
            raise ValueError("line %d: malformed sample: %r"
                             % (lineno, line))
        labels: Tuple[Tuple[str, str], ...] = ()
        raw = m.group("labels")
        if raw is not None:
            pairs = []
            rest = raw
            while rest:
                lm = _LABEL_RE.match(rest)
                if lm is None:
                    raise ValueError("line %d: malformed labels: %r"
                                     % (lineno, raw))
                pairs.append((lm.group("k"), lm.group("v")))
                rest = rest[lm.end():]
                if rest.startswith(","):
                    rest = rest[1:]
                elif rest:
                    raise ValueError("line %d: malformed labels: %r"
                                     % (lineno, raw))
            labels = tuple(sorted(pairs))
        try:
            value = _parse_value(m.group("value"))
        except ValueError:
            raise ValueError("line %d: malformed value: %r"
                             % (lineno, m.group("value")))
        samples[(m.group("name"), labels)] = value
    return samples


def sample(samples, name: str, **labels) -> Optional[float]:
    """Convenience lookup into :func:`parse_prometheus` output."""
    return samples.get((name, tuple(sorted(labels.items()))))

"""Always-on compile accounting: who compiled what, and how long the
trace / lower / backend-compile phases took.

The round-5 bench wedged for 25 minutes inside a bind with nothing but a
stderr breadcrumb to show for it — ``bind_secs`` lived only in
``bench.py``. This module makes compile cost a framework observable:
jax's :mod:`jax.monitoring` duration events
(``/jax/core/compile/jaxpr_trace_duration``,
``jaxpr_to_mlir_module_duration``, ``backend_compile_duration``) fire on
the thread doing the compile, so a registered listener attributes them to
whatever :class:`scope` that thread currently has open (the fused train
step, an executor forward, a serve bucket, the fused optimizer step) at
ZERO cost outside compiles — no per-step timers, no knobs, always on.

Every executable build lands as one record in a bounded ring
(``mx.obs.report()["compiles"]``) carrying the scope name + cache
signature, and feeds the always-on aggregates:

* counter ``obs_compile_count`` — executables built (persistent-cache
  hits still count: they trace + lower + deserialize);
* histograms ``obs_bind_ms`` (trace+lower+compile wall per executable)
  and ``obs_trace_ms`` (trace phase alone);
* counters ``obs_bind_ms_total`` / ``obs_trace_ms_total`` /
  ``obs_compile_ms_total`` — integer-ms totals for rate math.
"""
from __future__ import annotations

import collections
import threading
import time
from typing import Any, Dict, List, Optional

from .. import lockcheck as _lockcheck
from .. import profiler as _profiler

__all__ = ["scope", "install", "snapshot", "RING_CAPACITY"]

RING_CAPACITY = 256

_ring: "collections.deque[Dict[str, Any]]" = \
    collections.deque(maxlen=RING_CAPACITY)
_ring_lock = _lockcheck.Lock(name="obs.compiles.ring_lock")
_tls = threading.local()
_installed = False
_t0 = time.perf_counter()


class scope(object):
    """Attribute compiles triggered inside the ``with`` body to
    ``(name, signature)``. Nestable (innermost wins); costs two
    thread-local writes, so hot paths keep it open around every dispatch
    rather than trying to predict which call will compile."""

    __slots__ = ("name", "signature", "_prev")

    def __init__(self, name: str, signature: Any = None):
        self.name = name
        self.signature = signature

    def __enter__(self):
        self._prev = getattr(_tls, "scope", None)
        _tls.scope = (self.name, self.signature)
        if self._prev is None:
            # drop orphaned trace/lower seconds from an earlier attempt
            # that never reached backend compile (a raising trace, an
            # abstract eval) — they must not inflate THIS scope's first
            # record. Nested scopes keep the accumulation: trace events
            # of one executable all fire within one dispatch.
            _tls.trace_s = 0.0
            _tls.lower_s = 0.0
        return self

    def __exit__(self, *exc):
        _tls.scope = self._prev
        return False


def _sig_str(sig: Any) -> Optional[str]:
    if sig is None:
        return None
    s = repr(sig)
    return s if len(s) <= 512 else s[:509] + "..."


def _on_duration(name: str, dur: float, **_kw) -> None:
    # runs on the compiling thread, between trace and execution — a few
    # dict ops against a multi-second compile
    if name == "/jax/core/compile/jaxpr_trace_duration":
        _tls.trace_s = getattr(_tls, "trace_s", 0.0) + dur
    elif name == "/jax/core/compile/jaxpr_to_mlir_module_duration":
        _tls.lower_s = getattr(_tls, "lower_s", 0.0) + dur
    elif name == "/jax/core/compile/backend_compile_duration":
        trace_s = getattr(_tls, "trace_s", 0.0)
        lower_s = getattr(_tls, "lower_s", 0.0)
        _tls.trace_s = 0.0
        _tls.lower_s = 0.0
        sc = getattr(_tls, "scope", None)
        trace_ms = trace_s * 1e3
        bind_ms = (trace_s + lower_s + dur) * 1e3
        rec = {
            "scope": sc[0] if sc else None,
            "signature": _sig_str(sc[1]) if sc else None,
            "trace_ms": round(trace_ms, 3),
            "lower_ms": round(lower_s * 1e3, 3),
            "compile_ms": round(dur * 1e3, 3),
            "bind_ms": round(bind_ms, 3),
            "t_offset_s": round(time.perf_counter() - _t0, 3),
            "thread": threading.current_thread().name,
        }
        with _ring_lock:
            _ring.append(rec)
        _profiler.incr_counter("obs_compile_count")
        _profiler.incr_counter("obs_trace_ms_total", int(trace_ms))
        _profiler.incr_counter("obs_compile_ms_total", int(dur * 1e3))
        _profiler.incr_counter("obs_bind_ms_total", int(bind_ms))
        _profiler.observe("obs_bind_ms", bind_ms)
        _profiler.observe("obs_trace_ms", trace_ms)


def install() -> None:
    """Register the jax.monitoring listener (idempotent; called at
    ``mx.obs`` import, i.e. package import — always on)."""
    global _installed
    if _installed:
        return
    import jax.monitoring
    jax.monitoring.register_event_duration_secs_listener(_on_duration)
    _installed = True


def snapshot() -> List[Dict[str, Any]]:
    """The compile ring, oldest first (bounded at RING_CAPACITY)."""
    with _ring_lock:
        return list(_ring)

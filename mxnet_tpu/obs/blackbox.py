"""Flight recorder — the always-on bounded event ring that survives the
crash (``MXNET_TPU_OBS_BLACKBOX=<dir>``; docs/architecture/observability.md).

PRs 11-12 moved the system onto a multi-host pod whose interesting
failures — host death, leader fail-over, mid-save kills, silent wedges —
are exactly the moments when per-process telemetry dies with the
process. This module is the aircraft black box for that regime: a
bounded, lock-light in-memory ring of recent events (span closes,
counter deltas, fault fires, pod transitions, checkpoint commit phases)
flushed to ``blackbox-p<rank>.jsonl`` via ``checkpoint.atomic_open``

* on every :func:`flush` call sites make at a terminal moment (fault
  fire, SIGTERM/143 preemption, NANCHECK abort, watchdog stall, pod
  generation transitions), and
* on a periodic heartbeat (``MXNET_TPU_OBS_BLACKBOX_FLUSH_SECS``), so a
  SIGKILL'd host still leaves its last window on disk.

Every flush atomically REWRITES the whole file (header line + the
current ring), so the artifact is bounded no matter how long the run
and a reader never sees a torn tail. ``python -m mxnet_tpu.obs blackbox
<dir>`` merges all ranks' files into one clock-aligned timeline and
prints the post-mortem verdict.

Discipline (the repo's lint rules are wired over this file as a test):

* NO signal handlers are registered here, and nothing here may be
  called from one — the SIGTERM/preemption flush happens on the
  training thread when the flag-only handler's flag is observed (the
  ``signal-unsafe`` lint class).
* Timestamps are ``time.perf_counter()`` everywhere; the wall clock is
  read ONCE at install to anchor the monotonic timeline (cross-host
  alignment needs a wall anchor — monotonic zero is per-boot
  arbitrary), with the per-host offset from the PodKV clock exchange
  recorded in the header so the merger can align ranks.
* Zero cost when the knob is off: call sites gate on the config knob
  and never import this module (subprocess-proven by the CI
  ``multihost`` zero-cost gate).
"""
from __future__ import annotations

import atexit
import collections
import itertools
import json
import os
import sys
import threading
import time
from typing import Any, Dict, List, Optional

from .. import config as _config
from .. import lockcheck as _lockcheck
from .. import profiler as _profiler

__all__ = ["enabled", "record", "flush", "set_identity",
           "set_clock_offset", "path", "reset", "ENV_DIR"]

ENV_DIR = "MXNET_TPU_OBS_BLACKBOX"

# env vars whose values identify the run in the header fingerprint
_FINGERPRINT_PREFIXES = ("MXNET_", "DMLC_", "JAX_PLATFORMS", "XLA_FLAGS")

_lock = _lockcheck.Lock(name="obs.blackbox.lock")   # install / identity
                                                    # / snapshot state
# serializes WHOLE flushes (snapshot + atomic write): without it a
# periodic flush that snapshotted the ring before a terminal flush
# (fault fire, SIGTERM) could finish its rename AFTER it and erase the
# cause-of-death event from the on-disk window. Separate from _lock so
# the disk write never blocks record()/identity state mutation.
_flush_lock = _lockcheck.Lock(name="obs.blackbox.flush_lock")
_seq = itertools.count(1)
_ring: Optional[collections.deque] = None
_installed = False
_dir: Optional[str] = None
_rank = 0
_role = "proc"
_clock_offset = 0.0
_wall_base = 0.0
_perf_base = 0.0
_trace0_wall: Optional[float] = None
_counter_snap: Dict[str, int] = {}
_flush_stop: Optional[threading.Event] = None
_flush_thread: Optional[threading.Thread] = None
_prev_excepthook = None


def enabled() -> bool:
    """True when the recorder is armed (the knob names a directory).
    Call sites normally check the config knob THEMSELVES before
    importing this module — that is the zero-import discipline."""
    return bool(_config.get(ENV_DIR))


def _default_identity() -> tuple:
    """(rank, role) when nobody called :func:`set_identity`: a training
    child of a coordinated pod carries its ORIGINAL pod rank in
    ``MXNET_TPU_POD_RANK`` (stable across control-plane re-hostings);
    a plain launcher worker has ``DMLC_WORKER_ID``."""
    rank = os.environ.get("MXNET_TPU_POD_RANK",
                          os.environ.get("DMLC_WORKER_ID", "0"))
    try:
        rank = int(rank)
    except ValueError:
        rank = 0
    role = "child" if os.environ.get("MXNET_TPU_ELASTIC_COORDINATED") \
        else "proc"
    return rank, role


def _install_locked() -> bool:
    global _installed, _ring, _dir, _rank, _role, _clock_offset
    global _wall_base, _perf_base, _trace0_wall, _flush_stop, _flush_thread
    global _prev_excepthook
    if _installed:
        return True
    directory = str(_config.get(ENV_DIR) or "")
    if not directory:
        return False
    _dir = directory
    try:
        os.makedirs(directory, exist_ok=True)
    except OSError:
        return False
    size = max(16, int(_config.get("MXNET_TPU_OBS_BLACKBOX_RING")))
    _ring = collections.deque(maxlen=size)
    if _rank == 0 and _role == "proc":
        _rank, _role = _default_identity()
    # the ONE wall-clock read: anchors the monotonic timeline so the
    # cross-host merger can align ranks (clock_offset_s in the header
    # re-bases it onto the control-plane host's clock)
    _wall_base = time.time()     # mx-lint: allow(wall-clock)
    _perf_base = time.perf_counter()
    # anchor for merging this process's chrome trace (profiler ts 0)
    _trace0_wall = _wall_base + (_profiler._t0 - _perf_base)
    try:
        off = os.environ.get("MXNET_TPU_OBS_CLOCK_OFFSET")
        if off:
            _clock_offset = float(off)
    except ValueError:
        pass
    period = float(_config.get("MXNET_TPU_OBS_BLACKBOX_FLUSH_SECS"))
    if period > 0:
        _flush_stop = threading.Event()
        stop = _flush_stop

        def _beat():
            while not stop.wait(period):
                try:
                    flush("periodic")
                except Exception:                          # noqa: BLE001
                    pass    # a failing disk must never kill the host

        _flush_thread = threading.Thread(
            target=_beat, name="mxnet_tpu.obs[blackbox]", daemon=True)
        _flush_thread.start()
    # an uncaught exception is a crash: leave the window + the traceback
    _prev_excepthook = sys.excepthook

    def _hook(exc_type, exc, tb):
        try:
            record("crash", exc_type.__name__, message=str(exc)[:500])
            flush("crash")
        except Exception:                                  # noqa: BLE001
            pass
        if _prev_excepthook is not None:
            _prev_excepthook(exc_type, exc, tb)

    sys.excepthook = _hook
    atexit.register(_atexit_flush)
    # span closes land in the ring even when the chrome-trace span
    # recording itself is off (the listener makes span() live)
    _profiler.set_span_listener(_on_span)
    _installed = True
    return True


def _ensure() -> bool:
    if _installed:
        return True
    with _lock:
        return _install_locked()


def _atexit_flush() -> None:
    try:
        flush("exit")
    except Exception:                                      # noqa: BLE001
        pass


def _on_span(name, t_start, t_end, category, lane) -> None:
    ring = _ring
    if ring is None:
        return
    ring.append({"s": next(_seq), "p": float(t_end), "kind": "span",
                 "name": str(name), "cat": str(category),
                 "dur_ms": round((t_end - t_start) * 1e3, 3),
                 "lane": lane})


def set_identity(rank: int, role: str) -> None:
    """Name this process's recorder file (``blackbox-p<rank>.jsonl`` for
    training processes, ``blackbox-p<rank>-coord.jsonl`` for pod
    coordinators). The pod coordinator calls this with its ORIGINAL
    rank before its first :func:`record`."""
    global _rank, _role
    with _lock:
        _rank = int(rank)
        _role = str(role)


def set_clock_offset(offset_s: float) -> None:
    """Record this host's wall-clock offset vs the control-plane host
    (``local_wall - leader_wall``, from the PodKV clock exchange at
    rendezvous); the merger subtracts it to align ranks."""
    global _clock_offset
    _clock_offset = float(offset_s)


def path() -> Optional[str]:
    """The file this recorder flushes to (None while un-installed)."""
    if _dir is None:
        return None
    name = "blackbox-p%d.jsonl" % _rank if _role != "coord" \
        else "blackbox-p%d-coord.jsonl" % _rank
    return os.path.join(_dir, name)


def record(kind: str, name: str = "", /, **data: Any) -> None:
    """Append one event to the ring (lock-light: a deque append). Event
    timestamps are perf_counter; the wall mapping happens at flush.
    ``kind``/``name`` are positional-only so ``data`` may reuse those
    keys (fault events carry a ``kind`` of their own)."""
    if not _ensure():
        return
    ev = {"s": next(_seq), "p": time.perf_counter(), "kind": str(kind),
          "name": str(name)}
    if data:
        ev["data"] = data
    _ring.append(ev)


def _fingerprint() -> Dict[str, Any]:
    env = {k: v for k, v in os.environ.items()
           if k.startswith(_FINGERPRINT_PREFIXES)}
    fp: Dict[str, Any] = {"python": sys.version.split()[0], "env": env}
    jax = sys.modules.get("jax")
    if jax is not None:
        fp["jax"] = getattr(jax, "__version__", "?")
    return fp


def _counter_delta_locked() -> Dict[str, int]:
    now = _profiler.counters()
    # the recorder's own flush counter moves on every flush — counting
    # it would make every window carry a spurious one-entry delta
    delta = {k: v - _counter_snap.get(k, 0) for k, v in now.items()
             if v != _counter_snap.get(k, 0)
             and not k.startswith("obs_blackbox_")}
    _counter_snap.clear()
    _counter_snap.update(now)
    return delta


def flush(reason: str) -> Optional[str]:
    """Atomically rewrite the recorder file with the current window:
    one header line (identity, clock anchors + offset, flush reason,
    counters/gauges snapshot, armed faults, config fingerprint) then
    one line per ring event, newest last. Returns the path. Whole
    flushes are serialized (``_flush_lock``) so snapshot order equals
    on-disk order — an in-flight periodic flush can never rename an
    older window over a terminal one."""
    if not _ensure():
        return None
    from .. import faults as _faults
    from ..checkpoint.atomic import atomic_open
    with _flush_lock:
        return _flush_locked(reason, _faults, atomic_open)


def _flush_locked(reason, _faults, atomic_open) -> Optional[str]:
    with _lock:
        delta = _counter_delta_locked()
        if delta:
            _ring.append({"s": next(_seq), "p": time.perf_counter(),
                          "kind": "counters", "name": "delta",
                          "data": delta})
        events = list(_ring)
        target = path()
        header = {
            "blackbox": 1,
            "rank": _rank,
            "role": _role,
            "pid": os.getpid(),
            "wall_base": _wall_base,
            "perf_base": _perf_base,
            "trace0_wall": _trace0_wall,
            "clock_offset_s": _clock_offset,
            "flush_reason": str(reason),
            "flush_wall": _wall_base + (time.perf_counter() - _perf_base),
            "gen": int(os.environ.get("MXNET_TPU_POD_GEN", "0") or 0),
            "faults_armed": _faults.active_specs(),
            "counters": _profiler.counters(),
            "gauges": _profiler.gauges(),
            "fingerprint": _fingerprint(),
        }
        lines: List[str] = [json.dumps(header, sort_keys=True)]
        for ev in events:
            out = dict(ev)
            out["t"] = round(_wall_base + (out.pop("p") - _perf_base), 6)
            lines.append(json.dumps(out, sort_keys=True, default=str))
    try:
        with atomic_open(target, "w") as f:
            f.write("\n".join(lines) + "\n")
    except OSError:
        return None
    _profiler.incr_counter("obs_blackbox_flush")
    return target


def reset() -> None:
    """Tear the recorder down (tests): stop the heartbeat thread,
    uninstall the span listener and excepthook, drop the ring."""
    global _installed, _ring, _dir, _flush_stop, _flush_thread
    global _prev_excepthook, _rank, _role, _clock_offset
    with _lock:
        if _flush_stop is not None:
            _flush_stop.set()
        thread = _flush_thread
    if thread is not None:
        thread.join(timeout=2.0)
    with _lock:
        _profiler.set_span_listener(None)
        if _prev_excepthook is not None:
            sys.excepthook = _prev_excepthook
            _prev_excepthook = None
        _installed = False
        _ring = None
        _dir = None
        _flush_stop = None
        _flush_thread = None
        _rank, _role = 0, "proc"
        _clock_offset = 0.0
        _counter_snap.clear()

"""``python -m mxnet_tpu.obs`` — offline observability tooling.

``blackbox <dir>`` merges every rank's flight-recorder file
(``blackbox-p<rank>[-coord].jsonl``, written by :mod:`.blackbox`) plus
any per-rank chrome traces (``profile-p<rank>.json``) into ONE
rank-laned, clock-aligned chrome-trace timeline
(``<dir>/pod-timeline.json``; load it in Perfetto), and prints the
post-mortem verdict:

* which rank stopped first (no clean-exit flush, earliest last event),
* that rank's last recorded event and the fault spec armed on it,
* each survivor's view of the death (its pod-transition events —
  dead-host detection, adjudication, election, fail-over, drain),
* every fail-over transition across the pod, clock-ordered.

Clock alignment: each recorder header carries the host's wall anchor
and its ``clock_offset_s`` vs the control-plane host (estimated from
the PodKV clock exchange at rendezvous), so
``aligned = wall - clock_offset_s`` puts every rank on the leader's
timebase; chrome traces align through the ``trace0_wall`` anchor the
recorder stamps (the wall time of profiler tick 0).

The verdict is also emitted machine-readably as one
``POD-BLACKBOX-VERDICT {json}`` line — the CI ``multihost`` drill
asserts on it after a real hostkill / leader-kill pod drill.
"""
from __future__ import annotations

import argparse
import glob
import json
import os
import re
import sys
from typing import Any, Dict, List, Optional

_FILE_RE = re.compile(r"^blackbox-p(\d+)(-coord)?\.jsonl$")
_TRACE_RE = re.compile(r"^profile-p(\d+)\.json$")

# lanes for recorder events in the merged trace (chrome tids; the
# per-rank chrome traces keep their own registered lane ids, which the
# profiler allocates from 1 upward — far from this range)
_TID_CHILD = 990
_TID_COORD = 991


def _load_recorder_files(directory: str) -> List[Dict[str, Any]]:
    out = []
    for path in sorted(glob.glob(os.path.join(directory,
                                              "blackbox-p*.jsonl"))):
        m = _FILE_RE.match(os.path.basename(path))
        if not m:
            continue
        try:
            with open(path) as f:
                lines = [ln for ln in f.read().splitlines() if ln.strip()]
        except OSError:
            continue
        if not lines:
            continue
        try:
            header = json.loads(lines[0])
        except ValueError:
            continue
        events = []
        for ln in lines[1:]:
            try:
                events.append(json.loads(ln))
            except ValueError:
                continue    # lenient: a foreign tool may have torn a line
        off = float(header.get("clock_offset_s") or 0.0)
        for ev in events:
            ev["aligned"] = float(ev.get("t", 0.0)) - off
        out.append({"path": path, "rank": int(m.group(1)),
                    "role": "coord" if m.group(2) else "child",
                    "header": header, "events": events,
                    "offset": off})
    return out


def _rank_summary(files: List[Dict[str, Any]]) -> Dict[int, Dict[str, Any]]:
    ranks: Dict[int, Dict[str, Any]] = {}
    for rec in files:
        r = rec["rank"]
        info = ranks.setdefault(r, {"files": [], "clean": False,
                                    "crashed": False, "last": None,
                                    "armed": [], "fault": None})
        info["files"].append(rec)
        reason = rec["header"].get("flush_reason")
        if reason == "exit":
            info["clean"] = True
        info["armed"] = sorted(set(info["armed"])
                               | set(rec["header"].get("faults_armed")
                                     or []))
        for ev in rec["events"]:
            if info["last"] is None or ev["aligned"] > \
                    info["last"]["aligned"]:
                info["last"] = ev
            if ev.get("kind") == "crash":
                info["crashed"] = True
            if ev.get("kind") == "fault":
                if info["fault"] is None or ev["aligned"] >= \
                        info["fault"]["aligned"]:
                    info["fault"] = ev
    return ranks


def _verdict(ranks: Dict[int, Dict[str, Any]]) -> Dict[str, Any]:
    dead = sorted(r for r, info in ranks.items()
                  if not info["clean"] or info["crashed"])
    survivors = sorted(r for r in ranks if r not in dead)
    first_dead = None
    if dead:
        first_dead = min(
            dead, key=lambda r: ranks[r]["last"]["aligned"]
            if ranks[r]["last"] else float("inf"))
    out: Dict[str, Any] = {"ranks": sorted(ranks),
                           "dead": dead, "survivors": survivors,
                           "first_dead": first_dead}
    if first_dead is not None:
        info = ranks[first_dead]
        last = info["last"]
        out["last_event"] = None if last is None else {
            "t": last["aligned"], "kind": last.get("kind"),
            "name": last.get("name"), "data": last.get("data")}
        out["armed_faults"] = info["armed"]
        fault = info["fault"]
        out["last_fault"] = None if fault is None else {
            "t": fault["aligned"], "site": fault.get("name"),
            "data": fault.get("data")}
    views: Dict[str, List[Dict[str, Any]]] = {}
    failovers: List[Dict[str, Any]] = []
    for r, info in sorted(ranks.items()):
        view = []
        for rec in info["files"]:
            for ev in rec["events"]:
                if ev.get("kind") != "pod":
                    continue
                name = ev.get("name")
                if name in ("dead-hosts", "adjudicate", "drain",
                            "failover", "stall", "coordsvc-kill",
                            "child-exit"):
                    view.append({"t": ev["aligned"], "name": name,
                                 "data": ev.get("data")})
                if name == "failover":
                    failovers.append({"rank": r, "t": ev["aligned"],
                                      "data": ev.get("data")})
        if view and r in survivors:
            views[str(r)] = sorted(view, key=lambda e: e["t"])[:20]
    out["survivor_views"] = views
    out["failovers"] = sorted(failovers, key=lambda e: e["t"])
    return out


def _merged_trace(directory: str, files: List[Dict[str, Any]]
                  ) -> Dict[str, Any]:
    """One chrome trace: pid = pod rank, recorder events on dedicated
    lanes, per-rank chrome traces re-based onto the aligned clock."""
    aligned_min = None
    for rec in files:
        for ev in rec["events"]:
            if aligned_min is None or ev["aligned"] < aligned_min:
                aligned_min = ev["aligned"]
    if aligned_min is None:
        aligned_min = 0.0
    events: List[Dict[str, Any]] = []
    seen_pids = set()
    for rec in files:
        pid = rec["rank"]
        tid = _TID_COORD if rec["role"] == "coord" else _TID_CHILD
        if pid not in seen_pids:
            seen_pids.add(pid)
            events.append({"name": "process_name", "ph": "M", "pid": pid,
                           "args": {"name": "rank %d" % pid}})
            events.append({"name": "process_sort_index", "ph": "M",
                           "pid": pid, "args": {"sort_index": pid}})
        events.append({"name": "thread_name", "ph": "M", "pid": pid,
                       "tid": tid,
                       "args": {"name": "blackbox/%s" % rec["role"]}})
        for ev in rec["events"]:
            ts = (ev["aligned"] - aligned_min) * 1e6
            name = "%s:%s" % (ev.get("kind"), ev.get("name")) \
                if ev.get("name") else str(ev.get("kind"))
            base = {"name": name, "cat": str(ev.get("kind")),
                    "pid": pid, "tid": tid, "ts": round(ts, 1)}
            if ev.get("data") is not None:
                base["args"] = {"data": ev["data"]}
            dur = (ev.get("dur_ms") if ev.get("kind") == "span"
                   else None)
            if dur:
                base.update({"ph": "X", "dur": round(dur * 1e3, 1),
                             "ts": round(ts - dur * 1e3, 1)})
            else:
                base.update({"ph": "i", "s": "t"})
            events.append(base)
        # this rank's chrome trace, shifted onto the aligned clock
        header = rec["header"]
        trace0 = header.get("trace0_wall")
        if trace0 is None or rec["role"] == "coord":
            continue
        tpath = os.path.join(directory, "profile-p%d.json" % pid)
        if not os.path.exists(tpath) and len(files) == 1:
            tpath = os.path.join(directory, "profile.json")
        if not os.path.exists(tpath):
            continue
        try:
            with open(tpath) as f:
                trace = json.load(f)
        except (OSError, ValueError):
            continue
        shift = (float(trace0) - rec["offset"] - aligned_min) * 1e6
        for tev in trace.get("traceEvents", []):
            tev = dict(tev)
            tev["pid"] = pid
            if "ts" in tev:
                tev["ts"] = round(float(tev["ts"]) + shift, 1)
            events.append(tev)
    return {"traceEvents": events, "displayTimeUnit": "ms"}


def cmd_blackbox(directory: str, out: Optional[str] = None) -> int:
    files = _load_recorder_files(directory)
    if not files:
        print("no blackbox-p*.jsonl recorder files under %s" % directory)
        return 2
    ranks = _rank_summary(files)
    verdict = _verdict(ranks)
    merged = _merged_trace(directory, files)
    out = out or os.path.join(directory, "pod-timeline.json")
    with open(out, "w") as f:
        json.dump(merged, f)
    # ------------------------------------------------- human-readable
    print("pod flight-recorder post-mortem over %d file(s), %d rank(s)"
          % (len(files), len(ranks)))
    for r in sorted(ranks):
        info = ranks[r]
        state = "clean exit" if r in verdict["survivors"] else "DEAD"
        last = info["last"]
        print("  rank %d: %s; last event %s"
              % (r, state,
                 "%s:%s @ %.3f" % (last.get("kind"), last.get("name"),
                                   last["aligned"])
                 if last else "<none>"))
    if verdict["first_dead"] is not None:
        fd = verdict["first_dead"]
        print("first dead: rank %d" % fd)
        if verdict.get("last_fault"):
            lf = verdict["last_fault"]
            print("  armed fault spec(s): %s; last fault fired: %s @ "
                  "%.3f" % (", ".join(verdict.get("armed_faults") or
                                      ["<none>"]),
                            lf["site"], lf["t"]))
        for r, view in sorted(verdict["survivor_views"].items()):
            print("  rank %s saw: %s" % (r, ", ".join(
                "%s@%.3f" % (e["name"], e["t"]) for e in view[:6])))
    else:
        print("every rank exited cleanly — nothing to blame")
    for fo in verdict["failovers"]:
        print("fail-over: rank %d re-pointed at %s @ %.3f"
              % (fo["rank"], (fo.get("data") or {}).get("addr", "?"),
                 fo["t"]))
    print("merged timeline: %s (%d events)"
          % (out, len(merged["traceEvents"])))
    print("POD-BLACKBOX-VERDICT %s" % json.dumps(verdict, sort_keys=True))
    return 0


def main(argv: Optional[List[str]] = None) -> int:
    parser = argparse.ArgumentParser(
        prog="python -m mxnet_tpu.obs",
        description="observability tooling (flight-recorder post-mortem)")
    sub = parser.add_subparsers(dest="cmd", required=True)
    bb = sub.add_parser("blackbox",
                        help="merge flight-recorder files into one "
                             "clock-aligned timeline + verdict")
    bb.add_argument("dir", help="directory holding blackbox-p*.jsonl")
    bb.add_argument("--out", default=None,
                    help="merged chrome-trace path "
                         "(default <dir>/pod-timeline.json)")
    args = parser.parse_args(argv)
    if args.cmd == "blackbox":
        return cmd_blackbox(args.dir, args.out)
    parser.error("unknown command")
    return 2


if __name__ == "__main__":
    sys.exit(main())

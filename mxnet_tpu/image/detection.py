"""Detection-aware augmentation + iterator.

Reference surface: ``python/mxnet/image/detection.py`` (941 LoC:
``DetAugmenter``, ``DetBorrowAug``, ``DetRandomSelectAug``,
``DetHorizontalFlipAug``, ``DetRandomCropAug``, ``DetRandomPadAug``,
``CreateDetAugmenter``, ``ImageDetIter``) and the C++ record iterator
``src/io/iter_image_det_recordio.cc:581`` (ImageDetRecordIter).

Labels ride with the image through every geometric transform: each label
is (O, 5+) rows ``[cls, x1, y1, x2, y2, ...]`` with corners normalized to
[0, 1]; cls = -1 marks padding rows. Like the classification pipeline this
is host-side numpy feeding the device.
"""
from __future__ import annotations

import json
import random as pyrandom
from typing import List, Optional

import numpy as np

from .. import io as io_mod
from .. import ndarray as nd
from ..recordio import MXRecordIO, unpack
from .image import (Augmenter, CastAug, ColorJitterAug, ColorNormalizeAug,
                    HueJitterAug, LightingAug, RandomGrayAug, ResizeAug,
                    ForceResizeAug, _to_np, _resize, imdecode, imread)

__all__ = [
    "DetAugmenter", "DetBorrowAug", "DetRandomSelectAug",
    "DetHorizontalFlipAug", "DetRandomCropAug", "DetRandomPadAug",
    "CreateDetAugmenter", "ImageDetIter", "ImageDetRecordIter",
]


def _box_coverage(boxes, crop):
    """Fraction of each (O, 4) corner box covered by the crop window —
    intersection / box area (the reference's min_object_covered measure,
    NOT IoU: a crop fully containing a small object scores 1.0)."""
    lt = np.maximum(boxes[:, :2], crop[:2])
    rb = np.minimum(boxes[:, 2:], crop[2:])
    wh = np.maximum(rb - lt, 0)
    inter = wh[:, 0] * wh[:, 1]
    area_b = np.maximum(boxes[:, 2] - boxes[:, 0], 0) * \
        np.maximum(boxes[:, 3] - boxes[:, 1], 0)
    return np.where(area_b > 0, inter / np.maximum(area_b, 1e-12), 0.0)


class DetAugmenter(object):
    """Detection augmenter: ``__call__(src, label) -> (src, label)``
    (reference: detection.py DetAugmenter)."""

    def __init__(self, **kwargs):
        self._kwargs = kwargs

    def dumps(self):
        return json.dumps([self.__class__.__name__, self._kwargs])

    def __call__(self, src, label):
        raise NotImplementedError


class DetBorrowAug(DetAugmenter):
    """Lift an image-only Augmenter into the detection pipeline —
    photometric transforms don't move boxes (reference:
    detection.py DetBorrowAug)."""

    def __init__(self, augmenter):
        if not isinstance(augmenter, Augmenter):
            raise TypeError("DetBorrowAug needs an image Augmenter")
        super().__init__(augmenter=augmenter.dumps())
        self.augmenter = augmenter

    def __call__(self, src, label):
        return self.augmenter(src), label


class DetRandomSelectAug(DetAugmenter):
    """Randomly pick one of several augmenters (or skip) (reference:
    detection.py DetRandomSelectAug)."""

    def __init__(self, aug_list, skip_prob=0.0):
        super().__init__(skip_prob=skip_prob)
        self.aug_list = list(aug_list)
        self.skip_prob = skip_prob

    def __call__(self, src, label):
        if pyrandom.random() < self.skip_prob or not self.aug_list:
            return src, label
        return pyrandom.choice(self.aug_list)(src, label)


class DetHorizontalFlipAug(DetAugmenter):
    """Mirror the image AND the box x-coordinates (reference:
    detection.py DetHorizontalFlipAug)."""

    def __init__(self, p):
        super().__init__(p=p)
        self.p = p

    def __call__(self, src, label):
        if pyrandom.random() < self.p:
            img = _to_np(src)
            src = nd.array(np.ascontiguousarray(img[:, ::-1]),
                           dtype=img.dtype)
            label = label.copy()
            valid = label[:, 0] >= 0
            x1 = label[:, 1].copy()
            label[valid, 1] = 1.0 - label[valid, 3]
            label[valid, 3] = 1.0 - x1[valid]
        return src, label


class DetRandomCropAug(DetAugmenter):
    """Random crop with a min-IoU constraint against the ground truths;
    boxes are clipped to the crop, fully-escaped boxes are dropped
    (reference: detection.py DetRandomCropAug — the SSD sampling scheme)."""

    def __init__(self, min_object_covered=0.1, aspect_ratio_range=(0.75,
                 1.33), area_range=(0.05, 1.0), max_attempts=50):
        super().__init__(min_object_covered=min_object_covered,
                         aspect_ratio_range=aspect_ratio_range,
                         area_range=area_range, max_attempts=max_attempts)
        self.min_object_covered = min_object_covered
        self.aspect_ratio_range = aspect_ratio_range
        self.area_range = area_range
        self.max_attempts = max_attempts

    def _crop_label(self, label, crop):
        """Clip boxes to a normalized crop window, renormalize, drop
        escapees."""
        x0, y0, x1, y1 = crop
        w, h = x1 - x0, y1 - y0
        out = label.copy()
        valid = out[:, 0] >= 0
        b = out[:, 1:5]
        b = np.stack([np.clip(b[:, 0], x0, x1), np.clip(b[:, 1], y0, y1),
                      np.clip(b[:, 2], x0, x1), np.clip(b[:, 3], y0, y1)],
                     axis=1)
        area = (b[:, 2] - b[:, 0]) * (b[:, 3] - b[:, 1])
        keep = valid & (area > 1e-8)
        out[:, 1:5] = np.stack([(b[:, 0] - x0) / w, (b[:, 1] - y0) / h,
                                (b[:, 2] - x0) / w, (b[:, 3] - y0) / h],
                               axis=1)
        out[~keep, 0] = -1.0
        return out, keep.sum()

    def __call__(self, src, label):
        img = _to_np(src)
        h, w = img.shape[:2]
        valid = label[label[:, 0] >= 0]
        for _ in range(self.max_attempts):
            area = pyrandom.uniform(*self.area_range)
            aspect = pyrandom.uniform(*self.aspect_ratio_range)
            cw = min(np.sqrt(area * aspect), 1.0)
            ch = min(np.sqrt(area / aspect), 1.0)
            cx = pyrandom.uniform(0, 1.0 - cw)
            cy = pyrandom.uniform(0, 1.0 - ch)
            crop = np.array([cx, cy, cx + cw, cy + ch], np.float32)
            if len(valid):
                cov = _box_coverage(valid[:, 1:5], crop)
                if cov.max() < self.min_object_covered:
                    continue
            new_label, kept = self._crop_label(label, crop)
            if len(valid) and kept == 0:
                continue
            x0p, y0p = int(cx * w), int(cy * h)
            wp, hp = max(int(cw * w), 1), max(int(ch * h), 1)
            out = img[y0p:y0p + hp, x0p:x0p + wp]
            return nd.array(out, dtype=img.dtype), new_label
        return src, label


class DetRandomPadAug(DetAugmenter):
    """Pad to a random larger canvas (zoom out); boxes shrink accordingly
    (reference: detection.py DetRandomPadAug)."""

    def __init__(self, aspect_ratio_range=(0.75, 1.33),
                 area_range=(1.0, 3.0), max_attempts=50,
                 pad_val=(127, 127, 127)):
        super().__init__(aspect_ratio_range=aspect_ratio_range,
                         area_range=area_range, max_attempts=max_attempts,
                         pad_val=pad_val)
        self.aspect_ratio_range = aspect_ratio_range
        self.area_range = area_range
        self.max_attempts = max_attempts
        self.pad_val = np.asarray(pad_val, np.float32)

    def __call__(self, src, label):
        img = _to_np(src)
        h, w = img.shape[:2]
        new_w = new_h = 0
        for _ in range(self.max_attempts):
            scale = pyrandom.uniform(*self.area_range)
            aspect = pyrandom.uniform(*self.aspect_ratio_range)
            cand_w = int(w * np.sqrt(scale * aspect))
            cand_h = int(h * np.sqrt(scale / aspect))
            if cand_w >= w and cand_h >= h and (cand_w > w or cand_h > h):
                new_w, new_h = cand_w, cand_h
                break
        if not new_w:
            return src, label
        x0 = pyrandom.randint(0, new_w - w)
        y0 = pyrandom.randint(0, new_h - h)
        canvas = np.empty((new_h, new_w, img.shape[2]), img.dtype)
        canvas[:] = self.pad_val.astype(img.dtype)
        canvas[y0:y0 + h, x0:x0 + w] = img
        label = label.copy()
        valid = label[:, 0] >= 0
        sx, sy = w / new_w, h / new_h
        ox, oy = x0 / new_w, y0 / new_h
        label[valid, 1] = label[valid, 1] * sx + ox
        label[valid, 3] = label[valid, 3] * sx + ox
        label[valid, 2] = label[valid, 2] * sy + oy
        label[valid, 4] = label[valid, 4] * sy + oy
        return nd.array(canvas, dtype=img.dtype), label


def CreateDetAugmenter(data_shape, resize=0, rand_crop=0, rand_pad=0,
                       rand_gray=0, rand_mirror=False, mean=None, std=None,
                       brightness=0, contrast=0, saturation=0, pca_noise=0,
                       hue=0, inter_method=2,
                       min_object_covered=0.1,
                       aspect_ratio_range=(0.75, 1.33),
                       area_range=(0.05, 3.0), max_attempts=50,
                       pad_val=(127, 127, 127)):
    """Standard detection augmenter list (reference:
    detection.py CreateDetAugmenter — same knobs/ordering: resize, crop,
    pad, color, mirror, force-resize to data_shape, cast, normalize)."""
    auglist: List[DetAugmenter] = []
    if resize > 0:
        auglist.append(DetBorrowAug(ResizeAug(resize, inter_method)))
    if rand_crop > 0:
        crop = DetRandomCropAug(min_object_covered,
                                aspect_ratio_range,
                                (area_range[0], min(area_range[1], 1.0)),
                                max_attempts)
        auglist.append(DetRandomSelectAug([crop], 1 - rand_crop))
    if rand_pad > 0:
        pad = DetRandomPadAug(aspect_ratio_range,
                              (max(area_range[0], 1.0), area_range[1]),
                              max_attempts, pad_val)
        auglist.append(DetRandomSelectAug([pad], 1 - rand_pad))
    if rand_mirror:
        auglist.append(DetHorizontalFlipAug(0.5))
    # detection needs exact output size; aspect is already randomized
    auglist.append(DetBorrowAug(ForceResizeAug((data_shape[2],
                                                data_shape[1]),
                                               inter_method)))
    auglist.append(DetBorrowAug(CastAug()))
    if brightness or contrast or saturation:
        auglist.append(DetBorrowAug(ColorJitterAug(brightness, contrast,
                                                   saturation)))
    if hue:
        auglist.append(DetBorrowAug(HueJitterAug(hue)))
    if pca_noise > 0:
        eigval = np.array([55.46, 4.794, 1.148])
        eigvec = np.array([[-0.5675, 0.7192, 0.4009],
                           [-0.5808, -0.0045, -0.8140],
                           [-0.5836, -0.6948, 0.4203]])
        auglist.append(DetBorrowAug(LightingAug(pca_noise, eigval, eigvec)))
    if rand_gray > 0:
        auglist.append(DetBorrowAug(RandomGrayAug(rand_gray)))
    if mean is True:
        mean = np.array([123.68, 116.28, 103.53])
    if std is True:
        std = np.array([58.395, 57.12, 57.375])
    if mean is not None:
        auglist.append(DetBorrowAug(ColorNormalizeAug(mean, std)))
    return auglist


class ImageDetIter(io_mod.DataIter):
    """Detection batch iterator (reference: detection.py ImageDetIter +
    src/io/iter_image_det_recordio.cc:581).

    Sources: ``path_imgrec`` (.rec packed with ``pack_label`` headers) or
    ``imglist`` entries ``[label_rows_flat..., path]``. Labels are padded
    to the max object count: batch label (N, O, 5) with cls=-1 padding.
    """

    def __init__(self, batch_size, data_shape, path_imgrec=None,
                 imglist=None, shuffle=False, aug_list=None,
                 data_name="data", label_name="label", object_width=5,
                 **kwargs):
        super().__init__(batch_size)
        self.data_shape = tuple(data_shape)
        self._data_name = data_name
        self._label_name = label_name
        self._shuffle = shuffle
        self._ow = object_width

        # labels + record offsets only — image bytes stream from disk
        # (the offset-index pattern of io/image_record.py)
        self._records = []
        self._rec = None
        if path_imgrec is not None:
            self._rec = MXRecordIO(path_imgrec, "r")
            while True:
                pos = self._rec.tell()
                buf = self._rec.read()
                if buf is None:
                    break
                header, _ = unpack(buf)
                label = np.asarray(header.label, np.float32)
                self._records.append((self._parse_label(label), pos))
        elif imglist is not None:
            for entry in imglist:
                label = np.asarray(entry[:-1], np.float32)
                self._records.append((self._parse_label(label), entry[-1]))
        else:
            raise ValueError("ImageDetIter needs path_imgrec or imglist")
        if not self._records:
            raise ValueError("empty detection dataset")

        self.max_objects = max(lbl.shape[0] for lbl, _ in self._records)
        self.auglist = aug_list if aug_list is not None else \
            CreateDetAugmenter(data_shape, **kwargs)
        self._order = np.arange(len(self._records))
        self.cur = 0
        self.reset()

    def _parse_label(self, flat):
        """Flat label -> (O, object_width). Accepts either raw rows or the
        det-record header form [header_width, object_width, extras...,
        rows...] used by tools/im2rec detection lists (reference:
        detection.py _parse_label reads header_width = int(raw[0])
        generically)."""
        flat = np.asarray(flat, np.float32).ravel()
        ow = self._ow
        if flat.size >= 2:
            hw, how = int(flat[0]), int(flat[1])
            # a header iff the declared widths are integral, plausible, and
            # consistent with the payload length (coordinates are
            # normalized <1, so real box rows can't satisfy this)
            if float(flat[0]) == hw and float(flat[1]) == how and \
                    2 <= hw <= flat.size and how >= 5 and \
                    (flat.size - hw) % how == 0:
                ow = how
                flat = flat[hw:]
        self._ow = max(self._ow, ow)       # batch layout follows the widest
        n = flat.size // ow
        return flat[:n * ow].reshape(n, ow).copy()

    @property
    def provide_data(self):
        return [io_mod.DataDesc(self._data_name,
                                (self.batch_size,) + self.data_shape,
                                np.float32)]

    @property
    def provide_label(self):
        return [io_mod.DataDesc(
            self._label_name,
            (self.batch_size, self.max_objects, self._ow), np.float32)]

    def reset(self):
        if self._shuffle:
            np.random.shuffle(self._order)
        self.cur = 0

    def next(self):
        c, h, w = self.data_shape
        O = self.max_objects
        batch_data = np.zeros((self.batch_size, c, h, w), np.float32)
        batch_label = np.full((self.batch_size, O, self._ow), -1.0,
                              np.float32)
        i = 0
        pad = 0
        while i < self.batch_size:
            if self.cur >= len(self._records):
                if i == 0:
                    raise StopIteration
                pad = self.batch_size - i
                for j in range(i, self.batch_size):
                    batch_data[j] = batch_data[j - i]
                    batch_label[j] = batch_label[j - i]
                break
            label, src = self._records[self._order[self.cur]]
            self.cur += 1
            if isinstance(src, (int, np.integer)):
                self._rec.handle.seek(src)
                _, img_bytes = unpack(self._rec.read())
                img = imdecode(img_bytes)
            else:
                img = imread(src)
            label = label.copy()
            for aug in self.auglist:
                img, label = aug(img, label) \
                    if isinstance(aug, DetAugmenter) else (aug(img), label)
            arr = _to_np(img).astype(np.float32)
            batch_data[i] = arr.transpose(2, 0, 1)
            batch_label[i, :label.shape[0]] = label[:O]
            i += 1
        return io_mod.DataBatch(
            data=[nd.array(batch_data)], label=[nd.array(batch_label)],
            pad=pad, provide_data=self.provide_data,
            provide_label=self.provide_label)


def ImageDetRecordIter(path_imgrec, data_shape, batch_size, shuffle=False,
                       **kwargs):
    """Record-file detection iterator — the C++ ImageDetRecordIter's
    surface (src/io/iter_image_det_recordio.cc:581) as a thin constructor
    over :class:`ImageDetIter`."""
    return ImageDetIter(batch_size=batch_size, data_shape=data_shape,
                        path_imgrec=path_imgrec, shuffle=shuffle, **kwargs)

"""Image pipeline: decode, geometric/photometric transforms, composable
augmenters, and an in-memory/record-file image iterator.

Reference surface: ``python/mxnet/image/image.py`` (``resize_short:229``,
``fixed_crop:291``, ``random_crop:323``, ``center_crop:362``,
``color_normalize:411``, ``random_size_crop:435``, ``Augmenter:482``,
``CreateAugmenter:861``, ``ImageIter:975``).

Design: augmentation is host-side numpy (float32 HWC RGB) feeding the
device — the TPU twin of the reference's CPU decode/augment worker pool.
Nothing here traces into XLA; the accelerator sees only the final
normalized NCHW batch.
"""
from __future__ import annotations

import json
import os
import random as pyrandom
from typing import List, Optional, Sequence

import numpy as np

from .. import io as io_mod
from .. import ndarray as nd
from ..io.image_record import imdecode, imread  # noqa: F401  (re-export)
from ..recordio import MXRecordIO, MXIndexedRecordIO, unpack

__all__ = [
    "imdecode", "imread", "scale_down", "resize_short", "fixed_crop",
    "random_crop", "center_crop", "color_normalize", "random_size_crop",
    "Augmenter", "SequentialAug", "ResizeAug", "ForceResizeAug",
    "RandomCropAug", "RandomSizedCropAug", "CenterCropAug",
    "RandomOrderAug", "BrightnessJitterAug", "ContrastJitterAug",
    "SaturationJitterAug", "HueJitterAug", "ColorJitterAug", "LightingAug",
    "ColorNormalizeAug", "RandomGrayAug", "HorizontalFlipAug", "CastAug",
    "CreateAugmenter", "ImageIter",
]

# ITU-R BT.601 luma weights (RGB order) — the standard grayscale projection
_GRAY = np.array([0.299, 0.587, 0.114], np.float32)


def _to_np(img):
    if isinstance(img, nd.NDArray):
        return img.asnumpy()
    return np.asarray(img)


def _resize(img, w, h, interp=2):
    import cv2
    interps = {0: cv2.INTER_NEAREST, 1: cv2.INTER_LINEAR,
               2: cv2.INTER_AREA, 3: cv2.INTER_CUBIC,
               4: cv2.INTER_LANCZOS4}
    out = cv2.resize(_to_np(img), (int(w), int(h)),
                     interpolation=interps.get(int(interp),
                                               cv2.INTER_AREA))
    if out.ndim == 2:
        out = out[:, :, None]
    return out


def scale_down(src_size, size):
    """Shrink (w, h) to fit inside src (w, h) keeping aspect (reference:
    image.py:139)."""
    w, h = size
    sw, sh = src_size
    if sh < h:
        w, h = float(w * sh) / h, sh
    if sw < w:
        w, h = sw, float(h * sw) / w
    return int(w), int(h)


def resize_short(src, size, interp=2):
    """Resize so the SHORT side equals ``size`` (reference: image.py:229)."""
    img = _to_np(src)
    h, w = img.shape[:2]
    if h > w:
        new_w, new_h = size, int(h * size / w)
    else:
        new_w, new_h = int(w * size / h), size
    return nd.array(_resize(img, new_w, new_h, interp), dtype=img.dtype)


def fixed_crop(src, x0, y0, w, h, size=None, interp=2):
    """Crop a fixed window, optionally resizing to ``size`` (w, h)
    (reference: image.py:291)."""
    img = _to_np(src)
    out = img[int(y0):int(y0) + int(h), int(x0):int(x0) + int(w)]
    if size is not None and (int(w), int(h)) != tuple(size):
        out = _resize(out, size[0], size[1], interp)
    return nd.array(out, dtype=img.dtype)


def random_crop(src, size, interp=2):
    """Random position crop at target size (scaled down if the image is
    smaller); returns (img, (x0, y0, w, h)) (reference: image.py:323)."""
    img = _to_np(src)
    h, w = img.shape[:2]
    new_w, new_h = scale_down((w, h), size)
    x0 = pyrandom.randint(0, w - new_w)
    y0 = pyrandom.randint(0, h - new_h)
    out = fixed_crop(img, x0, y0, new_w, new_h, size, interp)
    return out, (x0, y0, new_w, new_h)


def center_crop(src, size, interp=2):
    """Center crop; returns (img, (x0, y0, w, h)) (reference:
    image.py:362)."""
    img = _to_np(src)
    h, w = img.shape[:2]
    new_w, new_h = scale_down((w, h), size)
    x0 = (w - new_w) // 2
    y0 = (h - new_h) // 2
    out = fixed_crop(img, x0, y0, new_w, new_h, size, interp)
    return out, (x0, y0, new_w, new_h)


def color_normalize(src, mean, std=None):
    """(src - mean) / std per channel (reference: image.py:411)."""
    img = _to_np(src).astype(np.float32)
    img = img - np.asarray(mean, np.float32)
    if std is not None:
        img = img / np.asarray(std, np.float32)
    return nd.array(img)


def random_size_crop(src, size, min_area, ratio, interp=2):
    """Random area/aspect crop (Inception-style); returns
    (img, (x0, y0, w, h)) (reference: image.py:435)."""
    img = _to_np(src)
    h, w = img.shape[:2]
    area = h * w
    for _ in range(10):
        target_area = pyrandom.uniform(min_area, 1.0) * area
        log_ratio = (np.log(ratio[0]), np.log(ratio[1]))
        aspect = np.exp(pyrandom.uniform(*log_ratio))
        new_w = int(round(np.sqrt(target_area * aspect)))
        new_h = int(round(np.sqrt(target_area / aspect)))
        if new_w <= w and new_h <= h:
            x0 = pyrandom.randint(0, w - new_w)
            y0 = pyrandom.randint(0, h - new_h)
            out = fixed_crop(img, x0, y0, new_w, new_h, size, interp)
            return out, (x0, y0, new_w, new_h)
    return random_crop(img, size, interp)


# ---------------------------------------------------------------- augmenters


class Augmenter(object):
    """Composable image transform (reference: image.py:482). Subclasses
    implement ``__call__(src) -> src``; ``dumps`` serializes the config."""

    def __init__(self, **kwargs):
        self._kwargs = kwargs
        for k, v in kwargs.items():
            if isinstance(v, nd.NDArray):
                kwargs[k] = v.asnumpy().tolist()
            elif isinstance(v, np.ndarray):
                kwargs[k] = v.tolist()

    def dumps(self):
        return json.dumps([self.__class__.__name__, self._kwargs])

    def __call__(self, src):
        raise NotImplementedError


class SequentialAug(Augmenter):
    """Apply a list of augmenters in order (reference: gluon-era
    image.py SequentialAug)."""

    def __init__(self, ts):
        super().__init__()
        self.ts = list(ts)

    def dumps(self):
        return [self.__class__.__name__, [t.dumps() for t in self.ts]]

    def __call__(self, src):
        for t in self.ts:
            src = t(src)
        return src


class ResizeAug(Augmenter):
    """Short-side resize (reference: image.py:508)."""

    def __init__(self, size, interp=2):
        super().__init__(size=size, interp=interp)
        self.size, self.interp = size, interp

    def __call__(self, src):
        return resize_short(src, self.size, self.interp)


class ForceResizeAug(Augmenter):
    """Exact (w, h) resize ignoring aspect (reference: image.py:528)."""

    def __init__(self, size, interp=2):
        super().__init__(size=size, interp=interp)
        self.size, self.interp = size, interp

    def __call__(self, src):
        img = _to_np(src)
        return nd.array(_resize(img, self.size[0], self.size[1],
                                self.interp), dtype=img.dtype)


class RandomCropAug(Augmenter):
    """(reference: image.py:549)."""

    def __init__(self, size, interp=2):
        super().__init__(size=size, interp=interp)
        self.size, self.interp = size, interp

    def __call__(self, src):
        return random_crop(src, self.size, self.interp)[0]


class RandomSizedCropAug(Augmenter):
    """(reference: image.py:569)."""

    def __init__(self, size, min_area, ratio, interp=2):
        super().__init__(size=size, min_area=min_area, ratio=ratio,
                         interp=interp)
        self.size, self.min_area = size, min_area
        self.ratio, self.interp = ratio, interp

    def __call__(self, src):
        return random_size_crop(src, self.size, self.min_area, self.ratio,
                                self.interp)[0]


class CenterCropAug(Augmenter):
    """(reference: image.py:596)."""

    def __init__(self, size, interp=2):
        super().__init__(size=size, interp=interp)
        self.size, self.interp = size, interp

    def __call__(self, src):
        return center_crop(src, self.size, self.interp)[0]


class RandomOrderAug(Augmenter):
    """Apply child augmenters in random order (reference: image.py:616)."""

    def __init__(self, ts):
        super().__init__()
        self.ts = list(ts)

    def dumps(self):
        return [self.__class__.__name__, [t.dumps() for t in self.ts]]

    def __call__(self, src):
        ts = list(self.ts)
        pyrandom.shuffle(ts)
        for t in ts:
            src = t(src)
        return src


class BrightnessJitterAug(Augmenter):
    """src *= 1 + U(-b, b) (reference: image.py:640)."""

    def __init__(self, brightness):
        super().__init__(brightness=brightness)
        self.brightness = brightness

    def __call__(self, src):
        alpha = 1.0 + pyrandom.uniform(-self.brightness, self.brightness)
        return nd.array(_to_np(src).astype(np.float32) * alpha)


class ContrastJitterAug(Augmenter):
    """Blend with the mean gray level (reference: image.py:659)."""

    def __init__(self, contrast):
        super().__init__(contrast=contrast)
        self.contrast = contrast

    def __call__(self, src):
        img = _to_np(src).astype(np.float32)
        alpha = 1.0 + pyrandom.uniform(-self.contrast, self.contrast)
        gray = (img * _GRAY).sum(axis=2).mean()
        return nd.array(img * alpha + gray * (1.0 - alpha))


class SaturationJitterAug(Augmenter):
    """Blend with the per-pixel gray image (reference: image.py:682)."""

    def __init__(self, saturation):
        super().__init__(saturation=saturation)
        self.saturation = saturation

    def __call__(self, src):
        img = _to_np(src).astype(np.float32)
        alpha = 1.0 + pyrandom.uniform(-self.saturation, self.saturation)
        gray = (img * _GRAY).sum(axis=2, keepdims=True)
        return nd.array(img * alpha + gray * (1.0 - alpha))


class HueJitterAug(Augmenter):
    """Rotate hue in YIQ space (reference: image.py:706 — same
    yiq/rotation construction)."""

    def __init__(self, hue):
        super().__init__(hue=hue)
        self.hue = hue

    def __call__(self, src):
        img = _to_np(src).astype(np.float32)
        alpha = pyrandom.uniform(-self.hue, self.hue)
        theta = alpha * np.pi
        u, w = np.cos(theta), np.sin(theta)
        # RGB->YIQ, rotate IQ plane, YIQ->RGB, folded into one 3x3
        t_yiq = np.array([[0.299, 0.587, 0.114],
                          [0.596, -0.274, -0.321],
                          [0.211, -0.523, 0.311]], np.float32)
        t_rgb = np.array([[1.0, 0.956, 0.621],
                          [1.0, -0.272, -0.647],
                          [1.0, -1.107, 1.705]], np.float32)
        rot = np.array([[1.0, 0.0, 0.0],
                        [0.0, u, -w],
                        [0.0, w, u]], np.float32)
        t = t_rgb @ rot @ t_yiq
        return nd.array(img @ t.T)


class ColorJitterAug(RandomOrderAug):
    """Brightness+contrast+saturation in random order (reference:
    image.py:740)."""

    def __init__(self, brightness, contrast, saturation):
        ts = []
        if brightness > 0:
            ts.append(BrightnessJitterAug(brightness))
        if contrast > 0:
            ts.append(ContrastJitterAug(contrast))
        if saturation > 0:
            ts.append(SaturationJitterAug(saturation))
        super().__init__(ts)


class LightingAug(Augmenter):
    """AlexNet-style PCA lighting noise (reference: image.py:763)."""

    def __init__(self, alphastd, eigval, eigvec):
        super().__init__(alphastd=alphastd, eigval=eigval, eigvec=eigvec)
        self.alphastd = alphastd
        self.eigval = np.asarray(eigval, np.float32)
        self.eigvec = np.asarray(eigvec, np.float32)

    def __call__(self, src):
        alpha = np.random.normal(0, self.alphastd, size=(3,))
        rgb = (self.eigvec * alpha * self.eigval).sum(axis=1)
        return nd.array(_to_np(src).astype(np.float32) + rgb)


class ColorNormalizeAug(Augmenter):
    """(reference: image.py:789)."""

    def __init__(self, mean, std):
        super().__init__(mean=mean, std=std)
        self.mean = np.asarray(mean, np.float32)
        self.std = None if std is None else np.asarray(std, np.float32)

    def __call__(self, src):
        return color_normalize(src, self.mean, self.std)


class RandomGrayAug(Augmenter):
    """Randomly convert to 3-channel gray (reference: image.py:809)."""

    def __init__(self, p):
        super().__init__(p=p)
        self.p = p

    def __call__(self, src):
        img = _to_np(src).astype(np.float32)
        if pyrandom.random() < self.p:
            img = np.broadcast_to(
                (img * _GRAY).sum(axis=2, keepdims=True), img.shape).copy()
        return nd.array(img)


class HorizontalFlipAug(Augmenter):
    """(reference: image.py:831)."""

    def __init__(self, p):
        super().__init__(p=p)
        self.p = p

    def __call__(self, src):
        img = _to_np(src)
        if pyrandom.random() < self.p:
            img = img[:, ::-1]
        return nd.array(np.ascontiguousarray(img), dtype=img.dtype)


class CastAug(Augmenter):
    """To float32 (reference: image.py:850)."""

    def __init__(self, typ="float32"):
        super().__init__(type=typ)
        self.typ = typ

    def __call__(self, src):
        return nd.array(_to_np(src).astype(self.typ), dtype=self.typ)


def CreateAugmenter(data_shape, resize=0, rand_crop=False, rand_resize=False,
                    rand_mirror=False, mean=None, std=None, brightness=0,
                    contrast=0, saturation=0, hue=0, pca_noise=0,
                    rand_gray=0, inter_method=2):
    """Build the standard augmenter list (reference: image.py:861 — same
    knobs, same ordering: resize, crop, color, lighting, gray, mirror,
    cast, normalize)."""
    auglist: List[Augmenter] = []
    if resize > 0:
        auglist.append(ResizeAug(resize, inter_method))
    crop_size = (data_shape[2], data_shape[1])
    if rand_resize:
        assert rand_crop
        auglist.append(RandomSizedCropAug(crop_size, 0.08, (3.0 / 4.0,
                                                            4.0 / 3.0),
                                          inter_method))
    elif rand_crop:
        auglist.append(RandomCropAug(crop_size, inter_method))
    else:
        auglist.append(CenterCropAug(crop_size, inter_method))
    if rand_mirror:
        auglist.append(HorizontalFlipAug(0.5))
    auglist.append(CastAug())
    if brightness or contrast or saturation:
        auglist.append(ColorJitterAug(brightness, contrast, saturation))
    if hue:
        auglist.append(HueJitterAug(hue))
    if pca_noise > 0:
        eigval = np.array([55.46, 4.794, 1.148])
        eigvec = np.array([[-0.5675, 0.7192, 0.4009],
                           [-0.5808, -0.0045, -0.8140],
                           [-0.5836, -0.6948, 0.4203]])
        auglist.append(LightingAug(pca_noise, eigval, eigvec))
    if rand_gray > 0:
        auglist.append(RandomGrayAug(rand_gray))
    if mean is True:
        mean = np.array([123.68, 116.28, 103.53])
    elif mean is not None:
        mean = np.asarray(mean, np.float32)
    if std is True:
        std = np.array([58.395, 57.12, 57.375])
    elif std is not None:
        std = np.asarray(std, np.float32)
    if mean is not None:
        assert mean.shape[0] in (1, 3)
        auglist.append(ColorNormalizeAug(mean, std))
    return auglist


# ------------------------------------------------------------------ iterator


class ImageIter(io_mod.DataIter):
    """Image iterator over a .rec file or an image list, with a pluggable
    augmenter pipeline (reference: image.py:975 — same construction forms:
    ``path_imgrec``, or ``imglist`` + ``path_root``).

    Produces NCHW float32 batches; ``aug_list`` defaults to
    ``CreateAugmenter(data_shape, **kwargs)``.
    """

    def __init__(self, batch_size, data_shape, label_width=1,
                 path_imgrec=None, path_imglist=None, path_root=None,
                 shuffle=False, aug_list=None, imglist=None,
                 data_name="data", label_name="softmax_label", **kwargs):
        super().__init__(batch_size)
        assert len(data_shape) == 3 and data_shape[0] in (1, 3)
        self.data_shape = tuple(data_shape)
        self.label_width = label_width
        self._data_name = data_name
        self._label_name = label_name
        self._shuffle = shuffle

        # (label, source) where source is a file path or a record offset —
        # image bytes stay on disk (the offset-index + seek pattern of
        # io/image_record.py) so huge .rec files stream instead of loading
        self._records = []
        self._rec = None
        if path_imgrec is not None:
            self._rec = MXRecordIO(path_imgrec, "r")
            while True:
                pos = self._rec.tell()
                buf = self._rec.read()
                if buf is None:
                    break
                header, _ = unpack(buf)
                label = np.atleast_1d(np.asarray(header.label, np.float32))
                self._records.append((label, pos))
        elif imglist is not None or path_imglist is not None:
            if path_imglist is not None:
                imglist = []
                with open(path_imglist) as f:
                    for line in f:
                        parts = line.strip().split("\t")
                        # idx \t label... \t path
                        imglist.append([float(x) for x in parts[1:-1]]
                                       + [parts[-1]])
            for entry in imglist:
                label = np.atleast_1d(np.asarray(entry[:-1], np.float32))
                path = entry[-1]
                if path_root is not None:
                    path = os.path.join(path_root, path)
                self._records.append((label, path))
        else:
            raise ValueError("ImageIter needs path_imgrec, path_imglist or "
                             "imglist")

        self.auglist = aug_list if aug_list is not None else \
            CreateAugmenter(data_shape, **kwargs)
        self._order = np.arange(len(self._records))
        self.cur = 0
        self.reset()

    @property
    def provide_data(self):
        return [io_mod.DataDesc(self._data_name,
                                (self.batch_size,) + self.data_shape,
                                np.float32)]

    @property
    def provide_label(self):
        shape = (self.batch_size,) if self.label_width == 1 else \
            (self.batch_size, self.label_width)
        return [io_mod.DataDesc(self._label_name, shape, np.float32)]

    def reset(self):
        if self._shuffle:
            np.random.shuffle(self._order)
        self.cur = 0

    def next_sample(self):
        """One (label, decoded HWC image) pair (reference:
        image.py ImageIter.next_sample)."""
        if self.cur >= len(self._records):
            raise StopIteration
        label, src = self._records[self._order[self.cur]]
        self.cur += 1
        return label, self._read_image(src)

    def _read_image(self, src):
        if isinstance(src, (int, np.integer)):    # record offset
            self._rec.handle.seek(src)
            _, img_bytes = unpack(self._rec.read())
            return imdecode(img_bytes)
        return imread(src)

    def next(self):
        c, h, w = self.data_shape
        batch_data = np.zeros((self.batch_size, c, h, w), np.float32)
        batch_label = np.zeros((self.batch_size, self.label_width),
                               np.float32)
        i = 0
        pad = 0
        while i < self.batch_size:
            try:
                label, img = self.next_sample()
            except StopIteration:
                if i == 0:
                    raise
                pad = self.batch_size - i
                # repeat earlier samples like the reference's pad handling
                for j in range(i, self.batch_size):
                    batch_data[j] = batch_data[j - i]
                    batch_label[j] = batch_label[j - i]
                break
            for aug in self.auglist:
                img = aug(img)
            arr = _to_np(img).astype(np.float32)
            if arr.shape[:2] != (h, w):
                raise ValueError(
                    "augmented image has shape %s, expected %dx%d — add a "
                    "crop/resize augmenter" % (arr.shape, h, w))
            batch_data[i] = arr.transpose(2, 0, 1)
            batch_label[i] = label[:self.label_width]
            i += 1
        label_out = batch_label[:, 0] if self.label_width == 1 else \
            batch_label
        return io_mod.DataBatch(
            data=[nd.array(batch_data)], label=[nd.array(label_out)],
            pad=pad, provide_data=self.provide_data,
            provide_label=self.provide_label)

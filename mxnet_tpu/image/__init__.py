"""``mx.image`` — host-side image pipeline (reference:
python/mxnet/image/__init__.py re-exports image + detection)."""
from .image import *  # noqa: F401,F403
from . import image  # noqa: F401
from . import detection  # noqa: F401
from .detection import (  # noqa: F401
    DetAugmenter, DetBorrowAug, DetRandomSelectAug, DetHorizontalFlipAug,
    DetRandomCropAug, DetRandomPadAug, CreateDetAugmenter, ImageDetIter,
    ImageDetRecordIter,
)

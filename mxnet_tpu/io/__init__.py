"""mx.io — data iterators (reference: python/mxnet/io.py + src/io/)."""
from .io import (DataDesc, DataBatch, DataIter, NDArrayIter, ResizeIter,
                 PrefetchingIter, CSVIter, MNISTIter)
from .image_record import ImageRecordIter, ImageRecordUInt8Iter

__all__ = ["DataDesc", "DataBatch", "DataIter", "NDArrayIter", "ResizeIter",
           "PrefetchingIter", "CSVIter", "MNISTIter", "ImageRecordIter",
           "ImageRecordUInt8Iter", "ImageDetRecordIter"]


def __getattr__(name):
    # lazy: mx.image imports mx.io, so the reverse edge must not be eager
    if name == "ImageDetRecordIter":
        from ..image.detection import ImageDetRecordIter
        return ImageDetRecordIter
    raise AttributeError("module %r has no attribute %r" % (__name__, name))

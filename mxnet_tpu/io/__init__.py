"""mx.io — data iterators (reference: python/mxnet/io.py + src/io/)."""
from .io import (DataDesc, DataBatch, DataIter, NDArrayIter, ResizeIter,
                 PrefetchingIter, CSVIter, MNISTIter)
from .image_record import ImageRecordIter, ImageRecordUInt8Iter

__all__ = ["DataDesc", "DataBatch", "DataIter", "NDArrayIter", "ResizeIter",
           "PrefetchingIter", "CSVIter", "MNISTIter", "ImageRecordIter",
           "ImageRecordUInt8Iter"]

"""ImageRecordIter — RecordIO image pipeline.

Reference: ``src/io/iter_image_recordio_2.cc:577`` (ImageRecordIter) =
record parser -> augmenter (image_aug_default.cc: resize/crop/mirror) ->
normalize (mean/std/scale) -> BatchLoader (iter_batchloader.h:41) ->
prefetcher (iter_prefetcher.h:46). Here: a pool of decode worker threads
feeding a bounded batch queue (the v2 iterator's fused thread pool,
iter_image_recordio_2.cc:513-566).
"""
from __future__ import annotations

import queue
import threading
from typing import List, Optional

import numpy as np

from .. import lockcheck as _lockcheck
from .. import ndarray as nd
from ..recordio import MXRecordIO, MXIndexedRecordIO, unpack
from .io import DataBatch, DataDesc, DataIter

__all__ = ["ImageRecordIter", "ImageRecordUInt8Iter", "imdecode", "imread"]


def imdecode(buf, flag=1, to_rgb=True):
    """Decode an encoded image buffer to an HWC uint8 NDArray (reference:
    src/io/image_io.cc imdecode — same (buf, flag, to_rgb) order)."""
    import cv2
    arr = np.frombuffer(buf, dtype=np.uint8) \
        if isinstance(buf, (bytes, bytearray)) else np.asarray(buf, np.uint8)
    img = cv2.imdecode(arr, cv2.IMREAD_COLOR if flag else
                       cv2.IMREAD_GRAYSCALE)
    if img is None:
        raise ValueError("imdecode: cannot decode buffer")
    if flag and to_rgb:
        img = cv2.cvtColor(img, cv2.COLOR_BGR2RGB)
    if img.ndim == 2:
        img = img[:, :, None]
    return nd.array(img, dtype=np.uint8)


def imread(filename, flag=1, to_rgb=True):
    """Read + decode an image file (reference: plugin/opencv cv_api.cc)."""
    with open(filename, "rb") as f:
        return imdecode(f.read(), flag=flag, to_rgb=to_rgb)


class ImageRecordIter(DataIter):
    """(reference: src/io/iter_image_recordio_2.cc:577; parameter names match
    the reference's ImageRecParserParam/ImageRecordParam/ImageNormalizeParam
    so reference training CLIs run unchanged)."""

    def __init__(self, path_imgrec: str, data_shape, batch_size: int,
                 path_imgidx: Optional[str] = None, label_width: int = 1,
                 shuffle: bool = False, rand_crop: bool = False,
                 rand_mirror: bool = False, resize: int = -1,
                 mean_img: Optional[str] = None, mean_r: float = 0.0,
                 mean_g: float = 0.0, mean_b: float = 0.0,
                 std_r: float = 1.0, std_g: float = 1.0, std_b: float = 1.0,
                 scale: float = 1.0, max_random_scale: float = 1.0,
                 min_random_scale: float = 1.0, seed: int = 0,
                 preprocess_threads: Optional[int] = None,
                 prefetch_buffer: Optional[int] = None,
                 round_batch: bool = True, data_name: str = "data",
                 label_name: str = "softmax_label", dtype="float32",
                 silent: bool = False, aug_list=None,
                 num_parts: int = 1, part_index: int = 0, **kwargs):
        super().__init__(batch_size)
        # distributed data sharding (reference: ImageRecParserParam
        # kNumParts/kPartIndex): worker part_index of num_parts reads
        # every num_parts-th record; num_data reports the shard size
        self._num_parts = max(int(num_parts), 1)
        self._part_index = int(part_index)
        if not 0 <= self._part_index < self._num_parts:
            raise ValueError("part_index %d not in [0, num_parts=%d)"
                             % (self._part_index, self._num_parts))
        self.data_shape = tuple(int(x) for x in data_shape)
        self.label_width = label_width
        self._dtype = np.dtype(dtype)
        self._params = dict(
            rand_crop=rand_crop, rand_mirror=rand_mirror, resize=resize,
            mean=np.array([mean_r, mean_g, mean_b], np.float32),
            std=np.array([std_r, std_g, std_b], np.float32),
            scale=scale)
        if mean_img is not None:
            try:
                self._params["mean_arr"] = nd.load(mean_img)["mean_img"].asnumpy()
            except Exception:
                self._params["mean_arr"] = None
        self._rng = np.random.RandomState(seed)
        self._aug_list = aug_list      # mx.image Augmenter pipeline override
        self._path = path_imgrec

        from .. import config as _config
        if preprocess_threads is None:
            preprocess_threads = _config.get("MXNET_CPU_WORKER_NTHREADS")
        if prefetch_buffer is None:
            prefetch_buffer = _config.get("MXNET_PREFETCH_BUFFER")
        self._n_threads = max(1, int(preprocess_threads))
        self._prefetch = max(2, int(prefetch_buffer))
        self._shuffle = shuffle
        self._round_batch = bool(round_batch)

        # Native C++ pipeline (mxnet_tpu/native: RecordIO mmap reader +
        # libjpeg/libpng decode + threaded augment/batch workers) handles
        # the standard crop/mirror/mean-std path entirely off the Python
        # thread; custom Augmenter pipelines and mean_img files fall back
        # to the Python/cv2 path below.
        self._native = None
        if (aug_list is None and self._params.get("mean_arr") is None
                and max_random_scale == 1.0 and min_random_scale == 1.0
                and self.data_shape[0] in (1, 3)):
            self._native = _NativePipe(self, seed)
            if self._native.handle is None:
                self._native = None
        if self._native is not None:
            self._order = np.arange(self._native.count)[
                self._part_index::self._num_parts]
            self._native.start_epoch(self._epoch_order())
            return

        # ---- pure-Python fallback path ----
        # index the record offsets once so shuffle is a permutation of offsets
        self._offsets: List[int] = []
        rec = MXRecordIO(path_imgrec, "r")
        while True:
            pos = rec.tell()
            buf = rec.read()
            if buf is None:
                break
            self._offsets.append(pos)
        rec.close()
        self._order = np.arange(len(self._offsets))[
            self._part_index::self._num_parts]
        self._epoch_queue: "queue.Queue" = queue.Queue()
        self._batch_queue: "queue.Queue" = queue.Queue(maxsize=self._prefetch)
        self._lock = _lockcheck.Lock(name="io.image_record_lock")
        self._cursor = 0
        self._alive = True
        self._loader = threading.Thread(target=self._produce, daemon=True)
        self._reset_evt = threading.Event()
        self._reset_evt.set()
        self._loader.start()

    def _epoch_order(self):
        order = self._order.copy()
        if self._shuffle:
            self._rng.shuffle(order)
        return order

    @property
    def num_data(self) -> int:
        """Number of records in the dataset (both pipeline backends)."""
        return len(self._order)

    # ------------------------------------------------------------ pipeline
    def _decode_and_augment(self, buf: bytes):
        import cv2
        header, img = self._unpack(buf)
        if self._aug_list is not None:
            # composable mx.image.Augmenter pipeline replaces the built-in
            # crop/mirror/normalize params (reference: ImageIter aug_list)
            if img.ndim == 2:
                img = img[:, :, None]
            out = np.ascontiguousarray(img[:, :, ::-1])   # BGR -> RGB
            for aug in self._aug_list:
                out = aug(out)
            if hasattr(out, "asnumpy"):
                out = out.asnumpy()
            arr = np.asarray(out, np.float32)
            c, th, tw = self.data_shape
            if arr.shape[:2] != (th, tw):
                raise ValueError(
                    "aug_list produced image of shape %s, data_shape wants "
                    "%dx%d — add a crop/resize augmenter"
                    % (arr.shape, th, tw))
            return arr.transpose(2, 0, 1), self._label_of(header)
        p = self._params
        if p["resize"] > 0:
            h, w = img.shape[:2]
            if h < w:
                nh, nw = p["resize"], int(w * p["resize"] / h)
            else:
                nh, nw = int(h * p["resize"] / w), p["resize"]
            img = cv2.resize(img, (nw, nh))
        c, th, tw = self.data_shape
        h, w = img.shape[:2]
        if h < th or w < tw:
            img = cv2.resize(img, (max(tw, w), max(th, h)))
            h, w = img.shape[:2]
        if p["rand_crop"]:
            y = self._rng.randint(0, h - th + 1)
            x = self._rng.randint(0, w - tw + 1)
        else:
            y, x = (h - th) // 2, (w - tw) // 2
        img = img[y:y + th, x:x + tw]
        if p["rand_mirror"] and self._rng.rand() < 0.5:
            img = img[:, ::-1]
        img = img.astype(np.float32)
        if img.ndim == 2:
            img = img[:, :, None]
        img = img[:, :, ::-1]  # BGR (cv2) -> RGB, matching the reference
        if p.get("mean_arr") is not None:
            img = img - p["mean_arr"].reshape(img.shape)
        elif p["mean"].any():
            img = img - p["mean"]
        if (p["std"] != 1.0).any():
            img = img / p["std"]
        if p["scale"] != 1.0:
            img = img * p["scale"]
        img = img.transpose(2, 0, 1)  # HWC -> CHW
        return img, self._label_of(header)

    def _label_of(self, header):
        label = header.label
        if isinstance(label, np.ndarray):
            label = label[:self.label_width] if self.label_width > 1 \
                else float(label[0])
        return label

    @staticmethod
    def _unpack(buf):
        return __import__("mxnet_tpu.recordio", fromlist=["unpack_img"]) \
            .unpack_img(buf)

    def _produce(self):
        """Loader thread: stream records, decode via worker pool, emit
        batches in order."""
        from concurrent.futures import ThreadPoolExecutor
        pool = ThreadPoolExecutor(max_workers=self._n_threads)
        while self._alive:
            self._reset_evt.wait()
            if not self._alive:
                break
            self._reset_evt.clear()
            try:
                self._produce_epoch(pool)
            except Exception as exc:   # surface to the consumer, don't hang
                if self._alive:
                    self._batch_queue.put(("error", exc, None, 0))

    def _produce_epoch(self, pool):
        order = self._epoch_order()
        rec = MXRecordIO(self._path, "r")
        bufs = []
        # stream sequentially; shuffled access uses offsets
        for i in order:
            rec.handle.seek(self._offsets[i])
            b = rec.read()
            if b is not None:
                bufs.append(b)
            if len(bufs) == self.batch_size:
                futures = [pool.submit(self._decode_and_augment, x)
                           for x in bufs]
                imgs, labels = zip(*[f.result() for f in futures])
                if not self._alive:
                    break
                self._batch_queue.put(("data", np.stack(imgs),
                                       np.asarray(labels, np.float32), 0))
                bufs = []
        rec.close()
        if bufs and self._alive and self._round_batch:
            pad = self.batch_size - len(bufs)
            futures = [pool.submit(self._decode_and_augment, x)
                       for x in bufs]
            imgs, labels = zip(*[f.result() for f in futures])
            imgs = list(imgs) + [imgs[-1]] * pad
            labels = list(labels) + [labels[-1]] * pad
            self._batch_queue.put(("data", np.stack(imgs),
                                   np.asarray(labels, np.float32), pad))
        if self._alive:
            self._batch_queue.put(("stop", None, None, 0))

    # ------------------------------------------------------------ DataIter
    @property
    def provide_data(self):
        return [DataDesc("data", (self.batch_size,) + self.data_shape,
                         self._dtype)]

    @property
    def provide_label(self):
        shape = (self.batch_size,) if self.label_width == 1 else \
            (self.batch_size, self.label_width)
        return [DataDesc("softmax_label", shape, np.float32)]

    def reset(self):
        if self._native is not None:
            self._native.start_epoch(self._epoch_order())
            return
        while True:
            try:
                self._batch_queue.get_nowait()
            except queue.Empty:
                break
        self._reset_evt.set()

    def next(self):
        if self._native is not None:
            imgs, labels, pad = self._native.next()   # raises StopIteration
            if self.label_width == 1:
                labels = labels[:, 0]
            return DataBatch(data=[nd.array(imgs.astype(self._dtype,
                                                        copy=False),
                                            dtype=self._dtype)],
                             label=[nd.array(labels)], pad=pad,
                             provide_data=self.provide_data,
                             provide_label=self.provide_label)
        kind, imgs, labels, pad = self._batch_queue.get()
        if kind == "error":
            raise imgs                # exception from the loader thread
        if kind == "stop":
            raise StopIteration
        return DataBatch(data=[nd.array(imgs.astype(self._dtype),
                                        dtype=self._dtype)],
                         label=[nd.array(labels)], pad=pad,
                         provide_data=self.provide_data,
                         provide_label=self.provide_label)

    def iter_next(self):
        try:
            self._cached = self.next()
            return True
        except StopIteration:
            return False

    def __del__(self):
        if getattr(self, "_native", None) is not None:
            self._native.close()
            return
        if not hasattr(self, "_reset_evt"):
            return
        self._alive = False
        self._reset_evt.set()
        try:
            self._batch_queue.get_nowait()
        except Exception:
            pass


class _NativePipe:
    """ctypes wrapper around the libmxnative batch pipeline (one instance
    per ImageRecordIter; owns the reader + pipeline handles)."""

    def __init__(self, it: "ImageRecordIter", seed: int):
        import ctypes
        from .. import native
        self.handle = None
        self._rec = None
        lib = native.lib()
        if lib is None:
            return
        rec = lib.mxrio_open(it._path.encode())
        if not rec:
            return
        self._lib = lib
        self._ct = ctypes
        self._rec = rec
        self.count = lib.mxrio_count(rec)
        p = it._params
        c, h, w = it.data_shape
        cfg = native.MXPipeConfig()
        cfg.batch_size = it.batch_size
        cfg.target_h, cfg.target_w, cfg.target_c = h, w, c
        cfg.label_width = it.label_width
        cfg.resize = int(p["resize"])
        cfg.rand_crop = int(bool(p["rand_crop"]))
        cfg.rand_mirror = int(bool(p["rand_mirror"]))
        cfg.mean[:] = [float(x) for x in p["mean"]]
        cfg.std_[:] = [float(x) for x in p["std"]]
        cfg.scale = float(p["scale"])
        cfg.seed = seed
        cfg.num_threads = it._n_threads
        cfg.queue_depth = it._prefetch
        cfg.round_batch = int(it._round_batch)
        self._shape = (it.batch_size, c, h, w)
        self._label_shape = (it.batch_size, it.label_width)
        self.handle = lib.mxpipe_create(rec, ctypes.byref(cfg))
        if not self.handle:
            # caller will discard us on a null handle; release the mmap+fd
            self.handle = None
            self.close()

    def start_epoch(self, order):
        import numpy as _np
        ct = self._ct
        order = _np.ascontiguousarray(order, dtype=_np.int64)
        self._lib.mxpipe_start_epoch(
            self.handle, order.ctypes.data_as(ct.POINTER(ct.c_int64)),
            len(order))

    def next(self):
        import numpy as _np
        ct = self._ct
        data = _np.empty(self._shape, _np.float32)
        label = _np.empty(self._label_shape, _np.float32)
        pad = ct.c_int()
        rc = self._lib.mxpipe_next(
            self.handle, data.ctypes.data_as(ct.POINTER(ct.c_float)),
            label.ctypes.data_as(ct.POINTER(ct.c_float)), ct.byref(pad))
        if rc == 1:
            raise StopIteration
        if rc != 0:
            raise IOError("native pipeline: %s"
                          % self._lib.mxpipe_error(self.handle).decode())
        return data, label, pad.value

    def close(self):
        if self.handle:
            self._lib.mxpipe_close(self.handle)
            self.handle = None
        if self._rec:
            self._lib.mxrio_close(self._rec)
            self._rec = None


class ImageRecordUInt8Iter(ImageRecordIter):
    """uint8 output variant (reference: iter_image_recordio_2.cc:612)."""

    def __init__(self, *args, **kwargs):
        kwargs.setdefault("dtype", "uint8")
        super().__init__(*args, **kwargs)

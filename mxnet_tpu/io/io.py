"""Data iterators.

Reference: ``python/mxnet/io.py`` (DataIter/DataBatch/DataDesc:40-274,
NDArrayIter:513, PrefetchingIter:340, ResizeIter:275, MXDataIter:719) and the
C++ registered iterators ``MNISTIter`` (src/io/iter_mnist.cc:259), ``CSVIter``
(src/io/iter_csv.cc:150) — re-implemented host-side in Python/numpy feeding
the device via async transfers (SURVEY.md §7 step 5). The threaded prefetch
pipeline (dmlc::ThreadedIter, src/io/iter_prefetcher.h:46) is a background
thread + bounded queue in :class:`PrefetchingIter`.
"""
from __future__ import annotations

import gzip
import os
import queue
import struct
import threading
from collections import namedtuple
from typing import Dict, List, Optional, Sequence, Union

import numpy as np

from .. import ndarray as nd
from ..ndarray import NDArray
from ..base import MXNetError

__all__ = ["DataDesc", "DataBatch", "DataIter", "NDArrayIter", "ResizeIter",
           "PrefetchingIter", "CSVIter", "MNISTIter"]


class DataDesc(namedtuple("DataDesc", ["name", "shape", "dtype", "layout"])):
    """(reference: io.py DataDesc — name/shape/dtype/layout of one stream)."""

    def __new__(cls, name, shape, dtype=np.float32, layout="NCHW"):
        return super().__new__(cls, name, tuple(shape), np.dtype(dtype), layout)

    @staticmethod
    def get_batch_axis(layout: Optional[str]) -> int:
        if layout is None:
            return 0
        return layout.find("N")

    @staticmethod
    def get_list(shapes, types=None):
        if types is not None:
            type_dict = dict(types)
            return [DataDesc(x[0], x[1], type_dict[x[0]]) for x in shapes]
        return [DataDesc(x[0], x[1]) for x in shapes]


class DataBatch(object):
    """(reference: io.py DataBatch)."""

    def __init__(self, data, label=None, pad=None, index=None,
                 bucket_key=None, provide_data=None, provide_label=None):
        if data is not None and not isinstance(data, (list, tuple)):
            data = [data]
        if label is not None and not isinstance(label, (list, tuple)):
            label = [label]
        self.data = data
        self.label = label
        self.pad = pad
        self.index = index
        self.bucket_key = bucket_key
        self.provide_data = provide_data
        self.provide_label = provide_label

    def __str__(self):
        data_shapes = [d.shape for d in self.data] if self.data else None
        label_shapes = [l.shape for l in self.label] if self.label else None
        return "{}: data shapes: {} label shapes: {}".format(
            self.__class__.__name__, data_shapes, label_shapes)


class DataIter(object):
    """Base iterator (reference: io.py:40 DataIter)."""

    def __init__(self, batch_size: int = 0):
        self.batch_size = batch_size

    def __iter__(self):
        return self

    def reset(self):
        pass

    def next(self) -> DataBatch:
        if self.iter_next():
            return DataBatch(data=self.getdata(), label=self.getlabel(),
                             pad=self.getpad(), index=self.getindex())
        raise StopIteration

    def __next__(self):
        return self.next()

    def iter_next(self) -> bool:
        raise NotImplementedError

    def getdata(self):
        raise NotImplementedError

    def getlabel(self):
        raise NotImplementedError

    def getindex(self):
        return None

    def getpad(self):
        raise NotImplementedError


def _init_data(data, allow_empty, default_name):
    """Normalize input to list of (name, numpy array) (reference: io.py
    _init_data)."""
    assert data is not None or allow_empty
    if data is None:
        data = []
    if isinstance(data, (np.ndarray, NDArray)):
        data = [data]
    if isinstance(data, (list, tuple)):
        if len(data) == 1:
            data = {default_name: data[0]}
        else:
            data = {"_%d_%s" % (i, default_name): d
                    for i, d in enumerate(data)}
    if not isinstance(data, dict):
        raise TypeError("Input must be NDArray, numpy.ndarray, a list of "
                        "them or dict with them as values")
    out = {}
    for k, v in data.items():
        out[k] = v.asnumpy() if isinstance(v, NDArray) else np.asarray(v)
    return list(sorted(out.items()))


class NDArrayIter(DataIter):
    """Iterate over in-memory arrays (reference: io.py:513 — shuffle,
    last_batch_handle pad/discard/roll_over)."""

    def __init__(self, data, label=None, batch_size=1, shuffle=False,
                 last_batch_handle="pad", data_name="data",
                 label_name="softmax_label"):
        super().__init__(batch_size)
        self.data = _init_data(data, allow_empty=False, default_name=data_name)
        self.label = _init_data(label, allow_empty=True, default_name=label_name)

        self.idx = np.arange(self.data[0][1].shape[0])
        if shuffle:
            np.random.shuffle(self.idx)
            self.data = [(k, v[self.idx]) for k, v in self.data]
            self.label = [(k, v[self.idx]) for k, v in self.label]

        if last_batch_handle == "discard":
            new_n = self.data[0][1].shape[0] - \
                self.data[0][1].shape[0] % batch_size
            self.idx = self.idx[:new_n]

        self.data_list = [x[1] for x in self.data] + [x[1] for x in self.label]
        self.num_source = len(self.data_list)
        self.num_data = self.idx.shape[0]
        assert self.num_data >= batch_size, \
            "batch_size needs to be smaller than data size"
        self.cursor = -batch_size
        self.last_batch_handle = last_batch_handle

    @property
    def provide_data(self) -> List[DataDesc]:
        return [DataDesc(k, (self.batch_size,) + v.shape[1:], v.dtype)
                for k, v in self.data]

    @property
    def provide_label(self) -> List[DataDesc]:
        return [DataDesc(k, (self.batch_size,) + v.shape[1:], v.dtype)
                for k, v in self.label]

    def hard_reset(self):
        self.cursor = -self.batch_size

    def reset(self):
        if self.last_batch_handle == "roll_over" and \
                self.cursor > self.num_data:
            self.cursor = -self.batch_size + (self.cursor % self.num_data) % \
                self.batch_size
        else:
            self.cursor = -self.batch_size

    def iter_next(self):
        self.cursor += self.batch_size
        return self.cursor < self.num_data

    def _getdata(self, data_source):
        assert self.cursor < self.num_data, "DataIter needs reset."
        if self.cursor + self.batch_size <= self.num_data:
            return [nd.array(v[self.cursor:self.cursor + self.batch_size],
                             dtype=v.dtype)
                    for _, v in data_source]
        # padding with wrap-around (reference: io.py NDArrayIter _getdata)
        pad = self.batch_size - self.num_data + self.cursor
        return [nd.array(np.concatenate([v[self.cursor:], v[:pad]], axis=0),
                         dtype=v.dtype)
                for _, v in data_source]

    def getdata(self):
        return self._getdata(self.data)

    def getlabel(self):
        return self._getdata(self.label)

    def getpad(self):
        if self.last_batch_handle == "pad" and \
                self.cursor + self.batch_size > self.num_data:
            return self.cursor + self.batch_size - self.num_data
        return 0

    def getindex(self):
        end = min(self.cursor + self.batch_size, self.num_data)
        return self.idx[self.cursor:end]


class ResizeIter(DataIter):
    """Truncate/extend an iterator to a fixed number of batches per epoch
    (reference: io.py:275)."""

    def __init__(self, data_iter, size, reset_internal=True):
        super().__init__()
        self.data_iter = data_iter
        self.size = size
        self.reset_internal = reset_internal
        self.cur = 0
        self.current_batch = None
        self.provide_data = data_iter.provide_data
        self.provide_label = data_iter.provide_label
        self.batch_size = data_iter.batch_size

    def reset(self):
        self.cur = 0
        if self.reset_internal:
            self.data_iter.reset()

    def iter_next(self):
        if self.cur == self.size:
            return False
        try:
            self.current_batch = self.data_iter.next()
        except StopIteration:
            self.data_iter.reset()
            self.current_batch = self.data_iter.next()
        self.cur += 1
        return True

    def getdata(self):
        return self.current_batch.data

    def getlabel(self):
        return self.current_batch.label

    def getindex(self):
        return self.current_batch.index

    def getpad(self):
        return self.current_batch.pad


class PrefetchingIter(DataIter):
    """Double-buffered prefetch over one or more iterators via background
    threads (reference: io.py:340 PrefetchingIter ≡ dmlc::ThreadedIter,
    src/io/iter_prefetcher.h:46-147)."""

    def __init__(self, iters, rename_data=None, rename_label=None,
                 prefetch_depth: int = 2):
        super().__init__()
        if not isinstance(iters, (list, tuple)):
            iters = [iters]
        self.n_iter = len(iters)
        assert self.n_iter > 0
        self.iters = iters
        self.rename_data = rename_data
        self.rename_label = rename_label
        self.batch_size = self.provide_data[0].shape[0]
        self._queues = [queue.Queue(maxsize=prefetch_depth)
                        for _ in range(self.n_iter)]
        self._started = True
        self._threads = []
        for i in range(self.n_iter):
            t = threading.Thread(target=self._worker, args=(i,), daemon=True)
            t.start()
            self._threads.append(t)
        self._reset_events = [threading.Event() for _ in range(self.n_iter)]

    def _worker(self, i):
        while self._started:
            try:
                batch = self.iters[i].next()
                self._queues[i].put(("data", batch))
            except StopIteration:
                self._queues[i].put(("stop", None))
                # wait for reset signal
                while self._started:
                    if getattr(self, "_reset_events", None) and \
                            self._reset_events[i].wait(timeout=0.05):
                        self._reset_events[i].clear()
                        break

    @property
    def provide_data(self):
        if self.rename_data is None:
            return sum([i.provide_data for i in self.iters], [])
        return sum([[DataDesc(r[x.name], x.shape, x.dtype)
                     if isinstance(r, dict) else x
                     for x in i.provide_data]
                    for r, i in zip(self.rename_data, self.iters)], [])

    @property
    def provide_label(self):
        if self.rename_label is None:
            return sum([i.provide_label for i in self.iters], [])
        return sum([[DataDesc(r[x.name], x.shape, x.dtype)
                     if isinstance(r, dict) else x
                     for x in i.provide_label]
                    for r, i in zip(self.rename_label, self.iters)], [])

    def reset(self):
        # drain queues, reset underlying iters, wake workers
        for q in self._queues:
            while True:
                try:
                    q.get_nowait()
                except queue.Empty:
                    break
        for it in self.iters:
            it.reset()
        for e in self._reset_events:
            e.set()

    def next(self):
        batches = []
        for q in self._queues:
            kind, batch = q.get()
            if kind == "stop":
                raise StopIteration
            batches.append(batch)
        data = sum([b.data for b in batches], [])
        label = sum([(b.label or []) for b in batches], [])
        return DataBatch(data=data, label=label or None,
                         pad=batches[0].pad, index=batches[0].index,
                         provide_data=self.provide_data,
                         provide_label=self.provide_label)

    def iter_next(self):
        try:
            self._next_batch = self.next()
            return True
        except StopIteration:
            return False

    def __del__(self):
        self._started = False
        for e in getattr(self, "_reset_events", []):
            e.set()


class CSVIter(DataIter):
    """Iterate CSV files (reference: src/io/iter_csv.cc:150 — data_csv,
    data_shape, label_csv, batch_size, round_batch)."""

    def __init__(self, data_csv: str, data_shape, label_csv=None,
                 label_shape=(1,), batch_size=1, round_batch=True,
                 dtype=np.float32, **kwargs):
        super().__init__(batch_size)
        self.data_shape = tuple(data_shape)
        self.label_shape = tuple(label_shape)
        data = np.loadtxt(data_csv, delimiter=",", dtype=dtype, ndmin=2)
        self._data = data.reshape((-1,) + self.data_shape)
        if label_csv is not None:
            label = np.loadtxt(label_csv, delimiter=",", dtype=dtype, ndmin=2)
            self._label = label.reshape((-1,) + self.label_shape)
            if self.label_shape == (1,):
                self._label = self._label.reshape(-1)
        else:
            self._label = np.zeros(self._data.shape[0], dtype=dtype)
        self.round_batch = round_batch
        self._iter = NDArrayIter(
            self._data, self._label, batch_size=batch_size,
            last_batch_handle="pad" if round_batch else "discard",
            data_name="data", label_name="label")

    @property
    def provide_data(self):
        return self._iter.provide_data

    @property
    def provide_label(self):
        return self._iter.provide_label

    def reset(self):
        self._iter.reset()

    def iter_next(self):
        return self._iter.iter_next()

    def getdata(self):
        return self._iter.getdata()

    def getlabel(self):
        return self._iter.getlabel()

    def getpad(self):
        return self._iter.getpad()

    def getindex(self):
        return self._iter.getindex()


def _read_idx_file(path: str, expected_magic_dims):
    opener = gzip.open if path.endswith(".gz") else open
    with opener(path, "rb") as f:
        magic = struct.unpack(">I", f.read(4))[0]
        ndim = magic & 0xff
        dims = struct.unpack(">" + "I" * ndim, f.read(4 * ndim))
        data = np.frombuffer(f.read(), dtype=np.uint8)
    return data.reshape(dims)


class MNISTIter(DataIter):
    """MNIST idx-format iterator (reference: src/io/iter_mnist.cc:259 —
    image=, label=, batch_size, shuffle, flat, seed, silent)."""

    def __init__(self, image: str, label: str, batch_size=128, shuffle=True,
                 flat=False, seed=0, silent=False, input_shape=None, **kwargs):
        super().__init__(batch_size)
        images = _read_idx_file(image, 3).astype(np.float32) / 255.0
        labels = _read_idx_file(label, 1).astype(np.float32)
        if flat:
            images = images.reshape(images.shape[0], -1)
        elif input_shape is not None:
            images = images.reshape((-1,) + tuple(input_shape))
        else:
            images = images.reshape(images.shape[0], 1,
                                    images.shape[1], images.shape[2])
        if shuffle:
            rng = np.random.RandomState(seed)
            order = rng.permutation(images.shape[0])
            images, labels = images[order], labels[order]
        self._iter = NDArrayIter(images, labels, batch_size=batch_size,
                                 last_batch_handle="discard",
                                 data_name="data", label_name="label")

    @property
    def provide_data(self):
        return self._iter.provide_data

    @property
    def provide_label(self):
        return self._iter.provide_label

    def reset(self):
        self._iter.reset()

    def iter_next(self):
        return self._iter.iter_next()

    def getdata(self):
        return self._iter.getdata()

    def getlabel(self):
        return self._iter.getlabel()

    def getpad(self):
        return self._iter.getpad()

    def getindex(self):
        return self._iter.getindex()

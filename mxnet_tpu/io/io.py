"""Data iterators.

Reference: ``python/mxnet/io.py`` (DataIter/DataBatch/DataDesc:40-274,
NDArrayIter:513, PrefetchingIter:340, ResizeIter:275, MXDataIter:719) and the
C++ registered iterators ``MNISTIter`` (src/io/iter_mnist.cc:259), ``CSVIter``
(src/io/iter_csv.cc:150) — re-implemented host-side in Python/numpy feeding
the device via async transfers (SURVEY.md §7 step 5). The threaded prefetch
pipeline (dmlc::ThreadedIter, src/io/iter_prefetcher.h:46) is a background
thread + bounded queue in :class:`PrefetchingIter`.
"""
from __future__ import annotations

import gzip
import os
import queue
import struct
import threading
import time
from collections import namedtuple
from typing import Dict, List, Optional, Sequence, Union

import numpy as np

from .. import lockcheck as _lockcheck
from .. import ndarray as nd
from ..ndarray import NDArray
from ..base import MXNetError
from .. import profiler as _profiler

__all__ = ["DataDesc", "DataBatch", "DataIter", "NDArrayIter", "ResizeIter",
           "PrefetchingIter", "CSVIter", "MNISTIter"]


class DataDesc(namedtuple("DataDesc", ["name", "shape", "dtype", "layout"])):
    """(reference: io.py DataDesc — name/shape/dtype/layout of one stream)."""

    def __new__(cls, name, shape, dtype=np.float32, layout="NCHW"):
        return super().__new__(cls, name, tuple(shape), np.dtype(dtype), layout)

    @staticmethod
    def get_batch_axis(layout: Optional[str]) -> int:
        if layout is None:
            return 0
        return layout.find("N")

    @staticmethod
    def get_list(shapes, types=None):
        if types is not None:
            type_dict = dict(types)
            return [DataDesc(x[0], x[1], type_dict[x[0]]) for x in shapes]
        return [DataDesc(x[0], x[1]) for x in shapes]


class DataBatch(object):
    """(reference: io.py DataBatch)."""

    def __init__(self, data, label=None, pad=None, index=None,
                 bucket_key=None, provide_data=None, provide_label=None):
        if data is not None and not isinstance(data, (list, tuple)):
            data = [data]
        if label is not None and not isinstance(label, (list, tuple)):
            label = [label]
        self.data = data
        self.label = label
        self.pad = pad
        self.index = index
        self.bucket_key = bucket_key
        self.provide_data = provide_data
        self.provide_label = provide_label

    def __str__(self):
        data_shapes = [d.shape for d in self.data] if self.data else None
        label_shapes = [l.shape for l in self.label] if self.label else None
        return "{}: data shapes: {} label shapes: {}".format(
            self.__class__.__name__, data_shapes, label_shapes)


class DataIter(object):
    """Base iterator (reference: io.py:40 DataIter)."""

    def __init__(self, batch_size: int = 0):
        self.batch_size = batch_size

    def __iter__(self):
        return self

    def reset(self):
        pass

    def next(self) -> DataBatch:
        if self.iter_next():
            return DataBatch(data=self.getdata(), label=self.getlabel(),
                             pad=self.getpad(), index=self.getindex())
        raise StopIteration

    def __next__(self):
        return self.next()

    def iter_next(self) -> bool:
        raise NotImplementedError

    def getdata(self):
        raise NotImplementedError

    def getlabel(self):
        raise NotImplementedError

    def getindex(self):
        return None

    def getpad(self):
        raise NotImplementedError


def _init_data(data, allow_empty, default_name):
    """Normalize input to list of (name, numpy array) (reference: io.py
    _init_data)."""
    assert data is not None or allow_empty
    if data is None:
        data = []
    if isinstance(data, (np.ndarray, NDArray)):
        data = [data]
    if isinstance(data, (list, tuple)):
        if len(data) == 1:
            data = {default_name: data[0]}
        else:
            data = {"_%d_%s" % (i, default_name): d
                    for i, d in enumerate(data)}
    if not isinstance(data, dict):
        raise TypeError("Input must be NDArray, numpy.ndarray, a list of "
                        "them or dict with them as values")
    out = {}
    for k, v in data.items():
        out[k] = v.asnumpy() if isinstance(v, NDArray) else np.asarray(v)
    return list(sorted(out.items()))


class NDArrayIter(DataIter):
    """Iterate over in-memory arrays (reference: io.py:513 — shuffle,
    last_batch_handle pad/discard/roll_over)."""

    def __init__(self, data, label=None, batch_size=1, shuffle=False,
                 last_batch_handle="pad", data_name="data",
                 label_name="softmax_label"):
        super().__init__(batch_size)
        self.data = _init_data(data, allow_empty=False, default_name=data_name)
        self.label = _init_data(label, allow_empty=True, default_name=label_name)

        self.idx = np.arange(self.data[0][1].shape[0])
        if shuffle:
            np.random.shuffle(self.idx)
            self.data = [(k, v[self.idx]) for k, v in self.data]
            self.label = [(k, v[self.idx]) for k, v in self.label]

        if last_batch_handle == "discard":
            new_n = self.data[0][1].shape[0] - \
                self.data[0][1].shape[0] % batch_size
            self.idx = self.idx[:new_n]

        self.data_list = [x[1] for x in self.data] + [x[1] for x in self.label]
        self.num_source = len(self.data_list)
        self.num_data = self.idx.shape[0]
        assert self.num_data >= batch_size, \
            "batch_size needs to be smaller than data size"
        self.cursor = -batch_size
        self.last_batch_handle = last_batch_handle

    @property
    def provide_data(self) -> List[DataDesc]:
        return [DataDesc(k, (self.batch_size,) + v.shape[1:], v.dtype)
                for k, v in self.data]

    @property
    def provide_label(self) -> List[DataDesc]:
        return [DataDesc(k, (self.batch_size,) + v.shape[1:], v.dtype)
                for k, v in self.label]

    def hard_reset(self):
        self.cursor = -self.batch_size

    def reset(self):
        if self.last_batch_handle == "roll_over" and \
                self.cursor > self.num_data:
            self.cursor = -self.batch_size + (self.cursor % self.num_data) % \
                self.batch_size
        else:
            self.cursor = -self.batch_size

    def iter_next(self):
        self.cursor += self.batch_size
        return self.cursor < self.num_data

    def _getdata(self, data_source):
        assert self.cursor < self.num_data, "DataIter needs reset."
        if self.cursor + self.batch_size <= self.num_data:
            return [nd.array(v[self.cursor:self.cursor + self.batch_size],
                             dtype=v.dtype)
                    for _, v in data_source]
        # padding with wrap-around (reference: io.py NDArrayIter _getdata)
        pad = self.batch_size - self.num_data + self.cursor
        return [nd.array(np.concatenate([v[self.cursor:], v[:pad]], axis=0),
                         dtype=v.dtype)
                for _, v in data_source]

    def getdata(self):
        return self._getdata(self.data)

    def getlabel(self):
        return self._getdata(self.label)

    def getpad(self):
        if self.last_batch_handle == "pad" and \
                self.cursor + self.batch_size > self.num_data:
            return self.cursor + self.batch_size - self.num_data
        return 0

    def getindex(self):
        end = min(self.cursor + self.batch_size, self.num_data)
        return self.idx[self.cursor:end]


class ResizeIter(DataIter):
    """Truncate/extend an iterator to a fixed number of batches per epoch
    (reference: io.py:275)."""

    def __init__(self, data_iter, size, reset_internal=True):
        super().__init__()
        self.data_iter = data_iter
        self.size = size
        self.reset_internal = reset_internal
        self.cur = 0
        self.current_batch = None
        self.provide_data = data_iter.provide_data
        self.provide_label = data_iter.provide_label
        self.batch_size = data_iter.batch_size

    def reset(self):
        self.cur = 0
        if self.reset_internal:
            self.data_iter.reset()

    def iter_next(self):
        if self.cur == self.size:
            return False
        try:
            self.current_batch = self.data_iter.next()
        except StopIteration:
            self.data_iter.reset()
            self.current_batch = self.data_iter.next()
        self.cur += 1
        return True

    def getdata(self):
        return self.current_batch.data

    def getlabel(self):
        return self.current_batch.label

    def getindex(self):
        return self.current_batch.index

    def getpad(self):
        return self.current_batch.pad


class PrefetchingIter(DataIter):
    """Double-buffered prefetch over one or more iterators via background
    threads (reference: io.py:340 PrefetchingIter ≡ dmlc::ThreadedIter,
    src/io/iter_prefetcher.h:46-147).

    Concurrency contract (docs/architecture/async_loop.md):

    * Every queue entry is tagged with the epoch counter at the moment the
      worker *started* reading it; ``reset()`` bumps the counter under the
      per-iterator lock, so a batch a worker was holding across a reset
      (mid-``put`` on a full queue — the old reset race) carries a stale
      tag and is discarded by the consumer instead of leaking into the
      next epoch.
    * ``close()`` stops the workers and joins them — iterators are no
      longer daemon-fire-and-forget; ``fit()`` closes the wrapper it
      creates, and ``__del__`` is only the last-resort cleanup.
    * ``device_placer`` adds a device-prefetch stage: a dedicated thread
      issues the H2D placement (``jax.device_put`` honoring the module's
      input shardings) for the NEXT batch while the current step computes,
      double-buffered to ``device_prefetch`` depth
      (``MXNET_TPU_DEVICE_PREFETCH``).
    """

    # fit's straggler telemetry duck-types this: the consumer-side fetch
    # is a queue pop fed by a background thread, so time spent in it is
    # a data-plane wait (counted as loop_prefetch_stall), not rank-local
    # compute — the inter-step window excludes it (base_module.fit)
    _mx_offthread_fetch = True

    def __init__(self, iters, rename_data=None, rename_label=None,
                 prefetch_depth: int = 2, device_placer=None,
                 device_prefetch: Optional[int] = None):
        super().__init__()
        if not isinstance(iters, (list, tuple)):
            iters = [iters]
        self.n_iter = len(iters)
        assert self.n_iter > 0
        self.iters = iters
        self.rename_data = rename_data
        self.rename_label = rename_label
        self.batch_size = self.provide_data[0].shape[0]
        self._queues = [queue.Queue(maxsize=prefetch_depth)
                        for _ in range(self.n_iter)]
        self._epoch = 0
        self._iter_locks = [_lockcheck.Lock(name="io.iter_lock[%d]" % i)
                            for i in range(self.n_iter)]
        self._closed = False
        self._started = True
        self._first_fetch = True
        self._device_placer = device_placer
        if device_placer is not None:
            # the placement runs inside the (single) worker thread rather
            # than a separate stage: one thread and one queue hop keeps
            # scheduling latency down on small hosts, and the H2D copy
            # still overlaps the consumer's compute
            assert self.n_iter == 1, \
                "device prefetch supports a single wrapped iterator"
            # the device path hands the inner iterator's batch through
            # verbatim (no merge/rewrap), so renames would silently not
            # apply to the yielded batches
            assert rename_data is None and rename_label is None, \
                "device prefetch does not support rename_data/rename_label"
            if device_prefetch is None:
                from .. import config as _config
                device_prefetch = _config.get("MXNET_TPU_DEVICE_PREFETCH")
            self._queues = [queue.Queue(maxsize=max(1, device_prefetch))]
        self._threads = []
        for i in range(self.n_iter):
            t = threading.Thread(target=self._worker, args=(i,), daemon=True)
            t.start()
            self._threads.append(t)

    # -------------------------------------------------------- stage threads
    def _put_tagged(self, q, entry):
        """Blocking put that abandons ship on close and lets reset-stale
        entries through (the consumer discards them by tag)."""
        while self._started:
            try:
                q.put(entry, timeout=0.05)
                return True
            except queue.Full:
                continue
        return False

    def _worker(self, i):
        _profiler.register_thread_lane("prefetch/%d" % i)
        while self._started:
            # the flow id threads this batch's trace slices across lanes
            # (prefetch -> place -> step -> metric); allocated only while
            # spans record, and riding on the batch as ``_mx_flow``
            fid = _profiler.new_flow() if _profiler.spans_enabled() \
                else None
            with self._iter_locks[i]:
                # the tag is read under the same lock reset() bumps it
                # under, so a reset can never interleave with next()
                epoch = self._epoch
                try:
                    with _profiler.span("prefetch_next", "io", flow=fid):
                        batch = self.iters[i].next()
                    if fid is not None:
                        try:
                            batch._mx_flow = fid
                        except AttributeError:
                            pass       # slotted/exotic batch: no flow tag
                    entry = (epoch, "data", batch)
                except StopIteration:
                    entry = (epoch, "stop", None)
                except Exception as exc:               # noqa: BLE001
                    # a dead worker would hang the consumer's blocking
                    # get() forever — carry the error across instead,
                    # re-raised in the thread that can actually catch it
                    entry = (epoch, "error", exc)
            if entry[1] == "data" and self._device_placer is not None \
                    and epoch == self._epoch:
                # device-prefetch stage: issue the H2D placement here so
                # the copy overlaps the consumer's current step (its own
                # trace lane: a stage, not a thread)
                try:
                    with _profiler.span("device_place", "io", flow=fid,
                                        lane="place"):
                        entry = (epoch, "data",
                                 self._device_placer(entry[2]))
                    _profiler.incr_counter("loop_prefetch_placed")
                except Exception as exc:               # noqa: BLE001
                    entry = (epoch, "error", exc)
            self._put_tagged(self._queues[i], entry)
            if entry[1] in ("stop", "error"):
                # parked (end-of-epoch or failed) until reset() bumps the
                # tag — a raising iterator must not be re-driven
                while self._started and self._epoch == epoch:
                    time.sleep(0.01)

    @staticmethod
    def _reraise_worker_error(exc):
        """Re-raise an exception carried over from a prefetch worker, with
        a breadcrumb: the traceback points into the worker thread, which
        surprises users whose iterator fit() auto-wrapped."""
        if hasattr(exc, "add_note"):                       # Python >= 3.11
            exc.add_note(
                "(raised inside a PrefetchingIter worker thread — the "
                "inner iterator's next() runs off the main thread under "
                "device prefetch; set MXNET_TPU_DEVICE_PREFETCH=0 for "
                "thread-affine iterators)")
        raise exc

    def _host_next_tagged(self):
        """One merged host batch off the worker queues, tag-preserving.
        Entries from before the last reset are dropped here."""
        cur = self._epoch
        batches = []
        for q in self._queues:
            while True:
                epoch, kind, batch = q.get()
                if epoch != cur:
                    continue        # pre-reset leftover: discard
                break
            if kind == "error":
                self._reraise_worker_error(batch)
            if kind == "stop":
                return cur, None
            batches.append(batch)
        data = sum([b.data for b in batches], [])
        label = sum([(b.label or []) for b in batches], [])
        return cur, DataBatch(data=data, label=label or None,
                              pad=batches[0].pad, index=batches[0].index,
                              provide_data=self.provide_data,
                              provide_label=self.provide_label)

    # ------------------------------------------------------------- provides
    @property
    def provide_data(self):
        if self.rename_data is None:
            return sum([i.provide_data for i in self.iters], [])
        return sum([[DataDesc(r[x.name], x.shape, x.dtype)
                     if isinstance(r, dict) else x
                     for x in i.provide_data]
                    for r, i in zip(self.rename_data, self.iters)], [])

    @property
    def provide_label(self):
        if self.rename_label is None:
            return sum([i.provide_label for i in self.iters], [])
        return sum([[DataDesc(r[x.name], x.shape, x.dtype)
                     if isinstance(r, dict) else x
                     for x in i.provide_label]
                    for r, i in zip(self.rename_label, self.iters)], [])

    # ------------------------------------------------------------ lifecycle
    def reset(self):
        # bump the epoch under every iterator lock: workers are guaranteed
        # not mid-next(), and anything they already produced (or are
        # blocked putting) carries the old tag and gets discarded
        for lock in self._iter_locks:
            lock.acquire()
        try:
            self._epoch += 1
            for it in self.iters:
                it.reset()
            # drain BEFORE releasing: a worker needs the iterator lock to
            # produce a fresh-epoch batch, so everything in the queues here
            # is stale by construction — draining after release could
            # discard a new epoch's batch 0 (already consumed from the
            # inner iterator = silent data loss). A worker mid-put with a
            # stale batch lands after the drain; the consumer's tag check
            # discards it.
            self._drain()
            self._first_fetch = True
        finally:
            for lock in self._iter_locks:
                lock.release()

    def _drain(self):
        for q in self._queues:
            while True:
                try:
                    q.get_nowait()
                except queue.Empty:
                    break

    def close(self, join_timeout=10.0):
        """Stop and join the prefetch threads (idempotent). After close the
        iterator is dead — create a new one to iterate again. Returns True
        when every worker joined inside `join_timeout` seconds; False means
        a worker is still wedged inside the inner iterator's next() and the
        inner iterator must not be touched from another thread."""
        if self._closed:
            return all(not t.is_alive() for t in self._threads)
        self._closed = True
        self._started = False
        deadline = time.monotonic() + join_timeout
        for t in self._threads:
            # workers blocked on a full queue poll _started with a 50ms
            # timeout; drain anyway so they exit on the fast path
            while t.is_alive() and time.monotonic() < deadline:
                self._drain()
                t.join(timeout=0.05)
        self._drain()
        return all(not t.is_alive() for t in self._threads)

    def next(self):
        if self._closed:
            # the workers are gone and nothing will ever be queued again:
            # a blocking get() here would hang forever, silently
            raise MXNetError("PrefetchingIter used after close()")
        if self._device_placer is not None:
            q = self._queues[0]
            try:
                entry = q.get_nowait()
            except queue.Empty:
                # the step outran the placement stage: pipeline bubble —
                # except on the first fetch of an epoch, where the queue
                # is cold by construction and an empty queue says nothing
                # about steady-state health
                if not self._first_fetch:
                    _profiler.incr_counter("loop_prefetch_stall")
                entry = q.get()
            self._first_fetch = False
            while entry[0] != self._epoch:
                entry = q.get()
            _profiler.set_gauge("loop_prefetch_depth", q.qsize())
            _epoch, kind, batch = entry
            if kind == "stop":
                raise StopIteration
            if kind == "error":
                self._reraise_worker_error(batch)
            return batch
        _epoch, batch = self._host_next_tagged()
        if batch is None:
            raise StopIteration
        return batch

    def iter_next(self):
        try:
            self._next_batch = self.next()
            return True
        except StopIteration:
            return False

    def __del__(self):
        try:
            # GC must never block for seconds on a wedged worker: flip the
            # flags and drain, but don't wait on the join
            self.close(join_timeout=0.0)
        except Exception:                                  # noqa: BLE001
            pass


class CSVIter(DataIter):
    """Iterate CSV files (reference: src/io/iter_csv.cc:150 — data_csv,
    data_shape, label_csv, batch_size, round_batch)."""

    def __init__(self, data_csv: str, data_shape, label_csv=None,
                 label_shape=(1,), batch_size=1, round_batch=True,
                 dtype=np.float32, **kwargs):
        super().__init__(batch_size)
        self.data_shape = tuple(data_shape)
        self.label_shape = tuple(label_shape)
        data = np.loadtxt(data_csv, delimiter=",", dtype=dtype, ndmin=2)
        self._data = data.reshape((-1,) + self.data_shape)
        if label_csv is not None:
            label = np.loadtxt(label_csv, delimiter=",", dtype=dtype, ndmin=2)
            self._label = label.reshape((-1,) + self.label_shape)
            if self.label_shape == (1,):
                self._label = self._label.reshape(-1)
        else:
            self._label = np.zeros(self._data.shape[0], dtype=dtype)
        self.round_batch = round_batch
        self._iter = NDArrayIter(
            self._data, self._label, batch_size=batch_size,
            last_batch_handle="pad" if round_batch else "discard",
            data_name="data", label_name="label")

    @property
    def provide_data(self):
        return self._iter.provide_data

    @property
    def provide_label(self):
        return self._iter.provide_label

    def reset(self):
        self._iter.reset()

    def iter_next(self):
        return self._iter.iter_next()

    def getdata(self):
        return self._iter.getdata()

    def getlabel(self):
        return self._iter.getlabel()

    def getpad(self):
        return self._iter.getpad()

    def getindex(self):
        return self._iter.getindex()


def _read_idx_file(path: str, expected_magic_dims):
    opener = gzip.open if path.endswith(".gz") else open
    with opener(path, "rb") as f:
        magic = struct.unpack(">I", f.read(4))[0]
        ndim = magic & 0xff
        dims = struct.unpack(">" + "I" * ndim, f.read(4 * ndim))
        data = np.frombuffer(f.read(), dtype=np.uint8)
    return data.reshape(dims)


class MNISTIter(DataIter):
    """MNIST idx-format iterator (reference: src/io/iter_mnist.cc:259 —
    image=, label=, batch_size, shuffle, flat, seed, silent)."""

    def __init__(self, image: str, label: str, batch_size=128, shuffle=True,
                 flat=False, seed=0, silent=False, input_shape=None, **kwargs):
        super().__init__(batch_size)
        images = _read_idx_file(image, 3).astype(np.float32) / 255.0
        labels = _read_idx_file(label, 1).astype(np.float32)
        if flat:
            images = images.reshape(images.shape[0], -1)
        elif input_shape is not None:
            images = images.reshape((-1,) + tuple(input_shape))
        else:
            images = images.reshape(images.shape[0], 1,
                                    images.shape[1], images.shape[2])
        if shuffle:
            rng = np.random.RandomState(seed)
            order = rng.permutation(images.shape[0])
            images, labels = images[order], labels[order]
        self._iter = NDArrayIter(images, labels, batch_size=batch_size,
                                 last_batch_handle="discard",
                                 data_name="data", label_name="label")

    @property
    def provide_data(self):
        return self._iter.provide_data

    @property
    def provide_label(self):
        return self._iter.provide_label

    def reset(self):
        self._iter.reset()

    def iter_next(self):
        return self._iter.iter_next()

    def getdata(self):
        return self._iter.getdata()

    def getlabel(self):
        return self._iter.getlabel()

    def getpad(self):
        return self._iter.getpad()

    def getindex(self):
        return self._iter.getindex()

"""Vision datasets.

Reference: ``python/mxnet/gluon/data/vision.py`` — MNIST, FashionMNIST,
CIFAR10/100, ImageRecordDataset, ImageFolderDataset.

No-egress environment: ``_download`` is disabled; datasets read standard
files from ``root`` (idx files for MNIST, binary batches for CIFAR,
RecordIO for ImageRecordDataset) and raise a clear error if absent.
"""
from __future__ import annotations

import gzip
import os
import struct

import numpy as np

from ... import ndarray as nd
from ... import recordio
from .dataset import Dataset, RecordFileDataset

__all__ = ["MNIST", "FashionMNIST", "CIFAR10", "CIFAR100",
           "ImageRecordDataset", "ImageFolderDataset"]


class _DownloadedDataset(Dataset):
    def __init__(self, root, train, transform):
        self._root = os.path.expanduser(root)
        self._train = train
        self._transform = transform
        self._data = None
        self._label = None
        if not os.path.isdir(self._root):
            os.makedirs(self._root, exist_ok=True)
        self._get_data()

    def __getitem__(self, idx):
        if self._transform is not None:
            return self._transform(self._data[idx], self._label[idx])
        return self._data[idx], self._label[idx]

    def __len__(self):
        return len(self._label)

    def _get_data(self):
        raise NotImplementedError


class MNIST(_DownloadedDataset):
    """MNIST from idx files (reference: vision.py MNIST)."""

    _train_files = ("train-images-idx3-ubyte", "train-labels-idx1-ubyte")
    _test_files = ("t10k-images-idx3-ubyte", "t10k-labels-idx1-ubyte")

    def __init__(self, root="~/.mxnet/datasets/mnist", train=True,
                 transform=None):
        super().__init__(root, train, transform)

    def _read(self, name):
        path = os.path.join(self._root, name)
        for cand in (path, path + ".gz"):
            if os.path.exists(cand):
                opener = gzip.open if cand.endswith(".gz") else open
                with opener(cand, "rb") as f:
                    return f.read()
        raise FileNotFoundError(
            "MNIST file %s not found under %s (downloads are disabled in "
            "this environment; place the standard idx files there)"
            % (name, self._root))

    def _get_data(self):
        img_name, lbl_name = self._train_files if self._train \
            else self._test_files
        lbl_buf = self._read(lbl_name)
        magic, num = struct.unpack(">II", lbl_buf[:8])
        label = np.frombuffer(lbl_buf, np.uint8, offset=8).astype(np.int32)
        img_buf = self._read(img_name)
        magic, num, rows, cols = struct.unpack(">IIII", img_buf[:16])
        data = np.frombuffer(img_buf, np.uint8, offset=16).reshape(
            num, rows, cols, 1)
        self._data = nd.array(data, dtype=np.uint8)
        self._label = label


class FashionMNIST(MNIST):
    """(reference: vision.py FashionMNIST — same idx format)."""

    def __init__(self, root="~/.mxnet/datasets/fashion-mnist", train=True,
                 transform=None):
        super().__init__(root, train, transform)


class CIFAR10(_DownloadedDataset):
    """CIFAR-10 from the python/binary batches (reference: vision.py
    CIFAR10)."""

    _num_classes = 10

    def __init__(self, root="~/.mxnet/datasets/cifar10", train=True,
                 transform=None):
        super().__init__(root, train, transform)

    def _read_batch(self, filename):
        with open(filename, "rb") as fin:
            buf = np.frombuffer(fin.read(), np.uint8)
        row = 3072 + (1 if self._num_classes == 10 else 2)
        buf = buf.reshape(-1, row)
        label = buf[:, 0 if self._num_classes == 10 else 1].astype(np.int32)
        data = buf[:, -3072:].reshape(-1, 3, 32, 32).transpose(0, 2, 3, 1)
        return data, label

    def _get_data(self):
        names = ["data_batch_%d.bin" % i for i in range(1, 6)] \
            if self._train else ["test_batch.bin"]
        paths = [os.path.join(self._root, n) for n in names]
        missing = [p for p in paths if not os.path.exists(p)]
        if missing:
            raise FileNotFoundError(
                "CIFAR batches missing: %s (downloads are disabled in this "
                "environment)" % missing)
        parts = [self._read_batch(p) for p in paths]
        data = np.concatenate([p[0] for p in parts])
        label = np.concatenate([p[1] for p in parts])
        self._data = nd.array(data, dtype=np.uint8)
        self._label = label


class CIFAR100(CIFAR10):
    """(reference: vision.py CIFAR100)."""

    _num_classes = 100

    def __init__(self, root="~/.mxnet/datasets/cifar100", train=True,
                 transform=None):
        super().__init__(root, train, transform)

    def _get_data(self):
        names = ["train.bin"] if self._train else ["test.bin"]
        paths = [os.path.join(self._root, n) for n in names]
        missing = [p for p in paths if not os.path.exists(p)]
        if missing:
            raise FileNotFoundError(
                "CIFAR-100 batches missing: %s" % missing)
        parts = [self._read_batch(p) for p in paths]
        self._data = nd.array(np.concatenate([p[0] for p in parts]),
                              dtype=np.uint8)
        self._label = np.concatenate([p[1] for p in parts])


class ImageRecordDataset(RecordFileDataset):
    """Images from a RecordIO pack (reference: vision.py
    ImageRecordDataset)."""

    def __init__(self, filename, flag=1, transform=None):
        super().__init__(filename)
        self._flag = flag
        self._transform = transform

    def __getitem__(self, idx):
        record = super().__getitem__(idx)
        header, img = recordio.unpack(record)
        from ...io.image_record import imdecode
        image = imdecode(img, to_rgb=bool(self._flag))
        label = header.label
        if self._transform is not None:
            return self._transform(image, label)
        return image, label


class ImageFolderDataset(Dataset):
    """``root/class/img.jpg`` layout (reference: vision.py
    ImageFolderDataset)."""

    def __init__(self, root, flag=1, transform=None):
        self._root = os.path.expanduser(root)
        self._flag = flag
        self._transform = transform
        self._exts = (".jpg", ".jpeg", ".png", ".bmp", ".ppm", ".npy")
        self.synsets = []
        self.items = []
        for folder in sorted(os.listdir(self._root)):
            path = os.path.join(self._root, folder)
            if not os.path.isdir(path):
                continue
            label = len(self.synsets)
            self.synsets.append(folder)
            for filename in sorted(os.listdir(path)):
                if filename.lower().endswith(self._exts):
                    self.items.append((os.path.join(path, filename), label))

    def __getitem__(self, idx):
        path, label = self.items[idx]
        if path.endswith(".npy"):
            image = nd.array(np.load(path))
        else:
            from ...io.image_record import imread
            image = imread(path, to_rgb=bool(self._flag))
        if self._transform is not None:
            return self._transform(image, label)
        return image, label

    def __len__(self):
        return len(self.items)

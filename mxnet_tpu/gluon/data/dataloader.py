"""DataLoader.

Reference: ``python/mxnet/gluon/data/dataloader.py`` — batches a Dataset
with a Sampler. The reference's multiprocessing workers are replaced by an
optional background-thread prefetcher (the TPU host pipeline is
IO/decode-bound, and the heavy decode path lives in the C++/threaded
RecordIO iterators — SURVEY.md §2.8).
"""
from __future__ import annotations

import queue
import threading

import numpy as np

from ... import ndarray as nd
from .dataset import Dataset
from .sampler import BatchSampler, RandomSampler, SequentialSampler, Sampler

__all__ = ["DataLoader"]


def default_batchify_fn(data):
    """Stack samples into a batch (reference: dataloader.py
    default_batchify_fn)."""
    if isinstance(data[0], nd.NDArray):
        return nd.stack(*data)
    if isinstance(data[0], tuple):
        data = zip(*data)
        return [default_batchify_fn(i) for i in data]
    data = np.asarray(data)
    return nd.array(data, dtype=data.dtype)


class DataLoader(object):
    """(reference: dataloader.py DataLoader)."""

    def __init__(self, dataset, batch_size=None, shuffle=False, sampler=None,
                 last_batch=None, batch_sampler=None, batchify_fn=None,
                 num_workers=0):
        self._dataset = dataset
        if batch_sampler is None:
            if batch_size is None:
                raise ValueError(
                    "batch_size must be specified unless batch_sampler is "
                    "specified")
            if sampler is None:
                sampler = RandomSampler(len(dataset)) if shuffle \
                    else SequentialSampler(len(dataset))
            elif shuffle:
                raise ValueError(
                    "shuffle must not be specified if sampler is specified")
            batch_sampler = BatchSampler(sampler, batch_size,
                                         last_batch or "keep")
        elif batch_size is not None or shuffle or sampler is not None or \
                last_batch is not None:
            raise ValueError(
                "batch_size, shuffle, sampler and last_batch must not be "
                "specified if batch_sampler is specified.")
        self._batch_sampler = batch_sampler
        self._batchify_fn = batchify_fn or default_batchify_fn
        self._num_workers = num_workers

    def _make_batch(self, indices):
        return self._batchify_fn([self._dataset[i] for i in indices])

    def __iter__(self):
        if self._num_workers == 0:
            for batch_idx in self._batch_sampler:
                yield self._make_batch(batch_idx)
            return

        # double-buffered background prefetch (dmlc::ThreadedIter analogue,
        # reference: src/io/iter_prefetcher.h:46)
        q: "queue.Queue" = queue.Queue(maxsize=max(2, self._num_workers))
        sentinel = object()

        def worker():
            try:
                for batch_idx in self._batch_sampler:
                    q.put(self._make_batch(batch_idx))
            finally:
                q.put(sentinel)

        t = threading.Thread(target=worker, daemon=True)
        t.start()
        while True:
            item = q.get()
            if item is sentinel:
                break
            yield item

    def __len__(self):
        return len(self._batch_sampler)

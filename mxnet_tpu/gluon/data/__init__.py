"""Gluon data API (reference: python/mxnet/gluon/data/)."""
from .dataset import *
from .sampler import *
from .dataloader import *
from . import vision

from . import dataset
from . import sampler
from . import dataloader

__all__ = dataset.__all__ + sampler.__all__ + dataloader.__all__ + ["vision"]

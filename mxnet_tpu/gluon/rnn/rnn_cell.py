"""Gluon recurrent cells.

Reference: ``python/mxnet/gluon/rnn/rnn_cell.py`` — RecurrentCell base with
begin_state/unroll, RNNCell, LSTMCell, GRUCell, SequentialRNNCell,
DropoutCell, ZoneoutCell, ResidualCell, BidirectionalCell.
"""
from __future__ import annotations

from ... import ndarray as nd
from ...base import MXNetError
from ..block import HybridBlock
from .. import nn  # noqa: F401  (API parity)

__all__ = ["RecurrentCell", "RNNCell", "LSTMCell", "GRUCell",
           "SequentialRNNCell", "DropoutCell", "ZoneoutCell", "ResidualCell",
           "BidirectionalCell", "HybridRecurrentCell"]


def _cells_state_info(cells, batch_size):
    return sum([c.state_info(batch_size) for c in cells], [])


def _cells_begin_state(cells, **kwargs):
    return sum([c.begin_state(**kwargs) for c in cells], [])


def _format_sequence(length, inputs, layout, merge):
    """Normalize inputs to a list of (N, C) steps or a merged tensor
    (reference: rnn_cell.py _format_sequence)."""
    assert layout in ("TNC", "NTC")
    axis = layout.find("T")
    if isinstance(inputs, (list, tuple)):
        in_list = list(inputs)
        if merge:
            merged = nd.stack(*in_list, axis=axis)
            return merged, axis
        return in_list, axis
    if length is None:
        length = inputs.shape[axis]
    if merge:
        return inputs, axis
    steps = [nd.squeeze(s, axis=axis)
             for s in nd.split(inputs, num_outputs=length, axis=axis)]
    return steps, axis


class RecurrentCell(HybridBlock):
    """Abstract recurrent cell (reference: rnn_cell.py RecurrentCell)."""

    def __init__(self, prefix=None, params=None):
        super().__init__(prefix=prefix, params=params)
        self._modified = False
        self.reset()

    def reset(self):
        self._init_counter = -1
        self._counter = -1
        for cell in self._children:
            if isinstance(cell, RecurrentCell):
                cell.reset()

    def state_info(self, batch_size=0):
        raise NotImplementedError

    @property
    def _gate_names(self):
        return ()

    def begin_state(self, batch_size=0, func=None, **kwargs):
        """Initial states (reference: rnn_cell.py begin_state)."""
        assert not self._modified, \
            "After applying modifier cells the base cell cannot be called " \
            "directly. Call the modifier cell instead."
        if func is None:
            func = nd.zeros
        states = []
        for info in self.state_info(batch_size):
            self._init_counter += 1
            if info is not None:
                info.update(kwargs)
            else:
                info = kwargs
            state = func(name="%sbegin_state_%d" % (self._prefix,
                                                    self._init_counter),
                         **info) if "name" in _fn_params(func) else \
                func(**info)
            states.append(state)
        return states

    def unroll(self, length, inputs, begin_state=None, layout="NTC",
               merge_outputs=None):
        """Unroll the cell over ``length`` steps (reference: rnn_cell.py
        unroll)."""
        self.reset()
        inputs, axis = _format_sequence(length, inputs, layout, False)
        if begin_state is None:
            batch_size = inputs[0].shape[0]
            begin_state = self.begin_state(batch_size=batch_size)

        states = begin_state
        outputs = []
        for i in range(length):
            output, states = self(inputs[i], states)
            outputs.append(output)

        if merge_outputs:
            outputs = nd.stack(*outputs, axis=axis)
        return outputs, states

    def forward(self, inputs, states):
        self._counter += 1
        return super().forward(inputs, states)


def _fn_params(func):
    import inspect
    try:
        return inspect.signature(func).parameters
    except (TypeError, ValueError):
        return {}


HybridRecurrentCell = RecurrentCell  # later-era alias


class RNNCell(RecurrentCell):
    """Elman cell (reference: rnn_cell.py:362 RNNCell)."""

    def __init__(self, hidden_size, activation="tanh",
                 i2h_weight_initializer=None, h2h_weight_initializer=None,
                 i2h_bias_initializer="zeros", h2h_bias_initializer="zeros",
                 input_size=0, prefix=None, params=None):
        super().__init__(prefix=prefix, params=params)
        from ... import initializer as init_mod
        self._hidden_size = hidden_size
        self._activation = activation
        self._input_size = input_size
        self.i2h_weight = self.params.get(
            "i2h_weight", shape=(hidden_size, input_size),
            init=i2h_weight_initializer, allow_deferred_init=True)
        self.h2h_weight = self.params.get(
            "h2h_weight", shape=(hidden_size, hidden_size),
            init=h2h_weight_initializer, allow_deferred_init=True)
        self.i2h_bias = self.params.get(
            "i2h_bias", shape=(hidden_size,), init=init_mod.Zero(),
            allow_deferred_init=True)
        self.h2h_bias = self.params.get(
            "h2h_bias", shape=(hidden_size,), init=init_mod.Zero(),
            allow_deferred_init=True)

    def state_info(self, batch_size=0):
        return [{"shape": (batch_size, self._hidden_size)}]

    @property
    def _gate_names(self):
        return ("",)

    def _alias(self):
        return "rnn"

    def shape_update(self, inputs, states):
        self.i2h_weight.shape = (self._hidden_size, inputs.shape[1])

    def hybrid_forward(self, F, inputs, states, i2h_weight, h2h_weight,
                       i2h_bias, h2h_bias):
        i2h = F.FullyConnected(inputs, i2h_weight, i2h_bias,
                               num_hidden=self._hidden_size)
        h2h = F.FullyConnected(states[0], h2h_weight, h2h_bias,
                               num_hidden=self._hidden_size)
        output = F.Activation(i2h + h2h, act_type=self._activation)
        return output, [output]


class LSTMCell(RecurrentCell):
    """(reference: rnn_cell.py:408 LSTMCell). Gate order i,f,g,o."""

    def __init__(self, hidden_size, i2h_weight_initializer=None,
                 h2h_weight_initializer=None, i2h_bias_initializer="zeros",
                 h2h_bias_initializer="zeros", input_size=0, prefix=None,
                 params=None):
        super().__init__(prefix=prefix, params=params)
        from ... import initializer as init_mod
        self._hidden_size = hidden_size
        self._input_size = input_size
        self.i2h_weight = self.params.get(
            "i2h_weight", shape=(4 * hidden_size, input_size),
            init=i2h_weight_initializer, allow_deferred_init=True)
        self.h2h_weight = self.params.get(
            "h2h_weight", shape=(4 * hidden_size, hidden_size),
            init=h2h_weight_initializer, allow_deferred_init=True)
        self.i2h_bias = self.params.get(
            "i2h_bias", shape=(4 * hidden_size,), init=init_mod.Zero(),
            allow_deferred_init=True)
        self.h2h_bias = self.params.get(
            "h2h_bias", shape=(4 * hidden_size,), init=init_mod.Zero(),
            allow_deferred_init=True)

    def state_info(self, batch_size=0):
        return [{"shape": (batch_size, self._hidden_size)},
                {"shape": (batch_size, self._hidden_size)}]

    @property
    def _gate_names(self):
        return ("_i", "_f", "_c", "_o")

    def _alias(self):
        return "lstm"

    def shape_update(self, inputs, states):
        self.i2h_weight.shape = (4 * self._hidden_size, inputs.shape[1])

    def hybrid_forward(self, F, inputs, states, i2h_weight, h2h_weight,
                       i2h_bias, h2h_bias):
        i2h = F.FullyConnected(inputs, i2h_weight, i2h_bias,
                               num_hidden=4 * self._hidden_size)
        h2h = F.FullyConnected(states[0], h2h_weight, h2h_bias,
                               num_hidden=4 * self._hidden_size)
        gates = i2h + h2h
        slice_gates = F.SliceChannel(gates, num_outputs=4)
        in_gate = F.Activation(slice_gates[0], act_type="sigmoid")
        forget_gate = F.Activation(slice_gates[1], act_type="sigmoid")
        in_transform = F.Activation(slice_gates[2], act_type="tanh")
        out_gate = F.Activation(slice_gates[3], act_type="sigmoid")
        next_c = forget_gate * states[1] + in_gate * in_transform
        next_h = out_gate * F.Activation(next_c, act_type="tanh")
        return next_h, [next_h, next_c]


class GRUCell(RecurrentCell):
    """(reference: rnn_cell.py:469 GRUCell). Gate order r,z,n (cuDNN)."""

    def __init__(self, hidden_size, i2h_weight_initializer=None,
                 h2h_weight_initializer=None, i2h_bias_initializer="zeros",
                 h2h_bias_initializer="zeros", input_size=0, prefix=None,
                 params=None):
        super().__init__(prefix=prefix, params=params)
        from ... import initializer as init_mod
        self._hidden_size = hidden_size
        self._input_size = input_size
        self.i2h_weight = self.params.get(
            "i2h_weight", shape=(3 * hidden_size, input_size),
            init=i2h_weight_initializer, allow_deferred_init=True)
        self.h2h_weight = self.params.get(
            "h2h_weight", shape=(3 * hidden_size, hidden_size),
            init=h2h_weight_initializer, allow_deferred_init=True)
        self.i2h_bias = self.params.get(
            "i2h_bias", shape=(3 * hidden_size,), init=init_mod.Zero(),
            allow_deferred_init=True)
        self.h2h_bias = self.params.get(
            "h2h_bias", shape=(3 * hidden_size,), init=init_mod.Zero(),
            allow_deferred_init=True)

    def state_info(self, batch_size=0):
        return [{"shape": (batch_size, self._hidden_size)}]

    @property
    def _gate_names(self):
        return ("_r", "_z", "_o")

    def _alias(self):
        return "gru"

    def shape_update(self, inputs, states):
        self.i2h_weight.shape = (3 * self._hidden_size, inputs.shape[1])

    def hybrid_forward(self, F, inputs, states, i2h_weight, h2h_weight,
                       i2h_bias, h2h_bias):
        prev_h = states[0]
        i2h = F.FullyConnected(inputs, i2h_weight, i2h_bias,
                               num_hidden=3 * self._hidden_size)
        h2h = F.FullyConnected(prev_h, h2h_weight, h2h_bias,
                               num_hidden=3 * self._hidden_size)
        i2h_r, i2h_z, i2h_n = F.SliceChannel(i2h, num_outputs=3)
        h2h_r, h2h_z, h2h_n = F.SliceChannel(h2h, num_outputs=3)
        reset_gate = F.Activation(i2h_r + h2h_r, act_type="sigmoid")
        update_gate = F.Activation(i2h_z + h2h_z, act_type="sigmoid")
        next_h_tmp = F.Activation(i2h_n + reset_gate * h2h_n,
                                  act_type="tanh")
        next_h = (1.0 - update_gate) * next_h_tmp + update_gate * prev_h
        return next_h, [next_h]


class SequentialRNNCell(RecurrentCell):
    """Stack cells (reference: rnn_cell.py SequentialRNNCell)."""

    def __init__(self, prefix=None, params=None):
        super().__init__(prefix=prefix, params=params)

    def add(self, cell):
        self.register_child(cell)

    def state_info(self, batch_size=0):
        return _cells_state_info(self._children, batch_size)

    def begin_state(self, **kwargs):
        assert not self._modified
        return _cells_begin_state(self._children, **kwargs)

    def __call__(self, inputs, states):
        self._counter += 1
        next_states = []
        p = 0
        for cell in self._children:
            n = len(cell.state_info())
            state = states[p:p + n]
            p += n
            inputs, state = cell(inputs, state)
            next_states.extend(state)
        return inputs, next_states

    def __len__(self):
        return len(self._children)

    def __getitem__(self, i):
        return self._children[i]

    def forward(self, inputs, states):
        return self.__call__(inputs, states)

    def hybrid_forward(self, *args, **kwargs):
        raise NotImplementedError


class _ModifierCell(RecurrentCell):
    """Base for cells wrapping another cell (reference: rnn_cell.py
    ModifierCell)."""

    def __init__(self, base_cell):
        assert not base_cell._modified, \
            "Cell %s is already modified. One cell cannot be modified " \
            "twice" % base_cell.name
        base_cell._modified = True
        super().__init__(prefix=base_cell.prefix + self._alias() + "_",
                         params=None)
        self.base_cell = base_cell

    @property
    def params(self):
        return self.base_cell.params

    def state_info(self, batch_size=0):
        return self.base_cell.state_info(batch_size)

    def begin_state(self, func=None, **kwargs):
        assert not self._modified
        self.base_cell._modified = False
        begin = self.base_cell.begin_state(func=func, **kwargs)
        self.base_cell._modified = True
        return begin


class DropoutCell(RecurrentCell):
    """Apply dropout on input (reference: rnn_cell.py DropoutCell)."""

    def __init__(self, rate, prefix=None, params=None):
        super().__init__(prefix=prefix, params=params)
        assert isinstance(rate, float)
        self.rate = rate

    def state_info(self, batch_size=0):
        return []

    def _alias(self):
        return "dropout"

    def hybrid_forward(self, F, inputs, states):
        if self.rate > 0:
            inputs = F.Dropout(inputs, p=self.rate)
        return inputs, states


class ZoneoutCell(_ModifierCell):
    """Zoneout regularization (reference: rnn_cell.py ZoneoutCell)."""

    def __init__(self, base_cell, zoneout_outputs=0.0, zoneout_states=0.0):
        assert not isinstance(base_cell, BidirectionalCell), \
            "BidirectionalCell doesn't support zoneout. Apply zoneout to " \
            "the cells underneath instead."
        self.zoneout_outputs = zoneout_outputs
        self.zoneout_states = zoneout_states
        super().__init__(base_cell)
        self._prev_output = None

    def _alias(self):
        return "zoneout"

    def reset(self):
        super().reset()
        self._prev_output = None

    def __call__(self, inputs, states):
        cell = self.base_cell
        next_output, next_states = cell(inputs, states)
        mask = lambda p, like: nd.Dropout(nd.ones_like(like), p=p)  # noqa
        prev_output = self._prev_output if self._prev_output is not None \
            else nd.zeros_like(next_output)
        output = nd.where(mask(self.zoneout_outputs, next_output),
                          next_output, prev_output) \
            if self.zoneout_outputs > 0.0 else next_output
        states = [nd.where(mask(self.zoneout_states, new_s), new_s, old_s)
                  for new_s, old_s in zip(next_states, states)] \
            if self.zoneout_states > 0.0 else next_states
        self._prev_output = output
        return output, states

    def forward(self, inputs, states):
        return self.__call__(inputs, states)

    def hybrid_forward(self, *args, **kwargs):
        raise NotImplementedError


class ResidualCell(_ModifierCell):
    """Residual connection around a cell (reference: rnn_cell.py
    ResidualCell)."""

    def _alias(self):
        return "residual"

    def __call__(self, inputs, states):
        output, states = self.base_cell(inputs, states)
        return output + inputs, states

    def forward(self, inputs, states):
        return self.__call__(inputs, states)

    def hybrid_forward(self, *args, **kwargs):
        raise NotImplementedError


class BidirectionalCell(RecurrentCell):
    """Run two cells over both directions (reference: rnn_cell.py
    BidirectionalCell:998-era)."""

    def __init__(self, l_cell, r_cell, output_prefix="bi_"):
        super().__init__(prefix="", params=None)
        self.register_child(l_cell)
        self.register_child(r_cell)
        self._output_prefix = output_prefix

    def __call__(self, inputs, states):
        raise NotImplementedError(
            "Bidirectional cannot be stepped. Please use unroll")

    def state_info(self, batch_size=0):
        return _cells_state_info(self._children, batch_size)

    def begin_state(self, **kwargs):
        assert not self._modified
        return _cells_begin_state(self._children, **kwargs)

    def unroll(self, length, inputs, begin_state=None, layout="NTC",
               merge_outputs=None):
        self.reset()
        inputs, axis = _format_sequence(length, inputs, layout, False)
        if begin_state is None:
            batch_size = inputs[0].shape[0]
            begin_state = self.begin_state(batch_size=batch_size)

        states = begin_state
        l_cell, r_cell = self._children
        n_l = len(l_cell.state_info())
        l_outputs, l_states = l_cell.unroll(
            length, inputs=inputs, begin_state=states[:n_l], layout=layout,
            merge_outputs=False)
        r_outputs, r_states = r_cell.unroll(
            length, inputs=list(reversed(inputs)),
            begin_state=states[n_l:], layout=layout, merge_outputs=False)

        outputs = [nd.concat(l_o, r_o, dim=1) for l_o, r_o in
                   zip(l_outputs, reversed(r_outputs))]
        if merge_outputs:
            outputs = nd.stack(*outputs, axis=axis)
        return outputs, l_states + r_states

    def hybrid_forward(self, *args, **kwargs):
        raise NotImplementedError

"""Gluon fused recurrent layers.

Reference: ``python/mxnet/gluon/rnn/rnn_layer.py`` — RNN/LSTM/GRU layers
backed by the fused RNN op (cuDNN in the reference, lax.scan here —
first-class on every backend, unlike the reference's GPU-only fused path).
"""
from __future__ import annotations

from ... import ndarray as nd
from ...ops.rnn_op import rnn_param_size
from ..block import HybridBlock

__all__ = ["RNN", "LSTM", "GRU"]


def _zero_init():
    from ... import initializer as init_mod
    return init_mod.Zero()


class _RNNLayer(HybridBlock):
    """(reference: rnn_layer.py _RNNLayer)."""

    def __init__(self, hidden_size, num_layers, layout, dropout,
                 bidirectional, input_size, i2h_weight_initializer,
                 h2h_weight_initializer, i2h_bias_initializer,
                 h2h_bias_initializer, mode, **kwargs):
        super().__init__(**kwargs)
        assert layout in ("TNC", "NTC"), \
            "Invalid layout %s; must be one of ['TNC' or 'NTC']" % layout
        self._hidden_size = hidden_size
        self._num_layers = num_layers
        self._mode = mode
        self._layout = layout
        self._dropout = dropout
        self._dir = 2 if bidirectional else 1
        self._input_size = input_size
        self._i2h_weight_initializer = i2h_weight_initializer
        self._h2h_weight_initializer = h2h_weight_initializer

        # per-layer named params (reference rnn_layer.py naming: l0_i2h_*,
        # r0_* for the reverse direction), packed into the fused-op vector
        # at forward time
        gates = {"rnn_relu": 1, "rnn_tanh": 1, "lstm": 4, "gru": 3}[mode]
        self._rnn_params = []
        for layer in range(num_layers):
            in_sz = input_size if layer == 0 else hidden_size * self._dir
            for d in range(self._dir):
                pfx = "%s%d_" % ("lr"[0] if d == 0 else "r", layer)
                pfx = ("l%d_" if d == 0 else "r%d_") % layer
                quad = (
                    self.params.get(pfx + "i2h_weight",
                                    shape=(gates * hidden_size, in_sz),
                                    init=i2h_weight_initializer,
                                    allow_deferred_init=True),
                    self.params.get(pfx + "h2h_weight",
                                    shape=(gates * hidden_size, hidden_size),
                                    init=h2h_weight_initializer,
                                    allow_deferred_init=True),
                    self.params.get(pfx + "i2h_bias",
                                    shape=(gates * hidden_size,),
                                    init=_zero_init(),
                                    allow_deferred_init=True),
                    self.params.get(pfx + "h2h_bias",
                                    shape=(gates * hidden_size,),
                                    init=_zero_init(),
                                    allow_deferred_init=True),
                )
                self._rnn_params.append(quad)

    def state_info(self, batch_size=0):
        raise NotImplementedError

    def _gates(self):
        return {"rnn_relu": 1, "rnn_tanh": 1, "lstm": 4, "gru": 3}[self._mode]

    def shape_update(self, inputs, *states):
        input_size = inputs.shape[2]
        self._input_size = input_size
        gates = self._gates()
        for idx in range(self._dir):  # layer 0 (both directions)
            wx = self._rnn_params[idx][0]
            wx.shape = (gates * self._hidden_size, input_size)

    def begin_state(self, batch_size=0, func=None, **kwargs):
        """(reference: rnn_layer.py begin_state)."""
        if func is None:
            func = nd.zeros
        states = []
        for info in self.state_info(batch_size):
            info.update(kwargs)
            states.append(func(**info))
        return states

    def __call__(self, inputs, states=None):
        """Accept optional states (reference: rnn_layer.py forward)."""
        return super().__call__(inputs, *([states] if states is not None
                                          else []))

    def forward(self, inputs, states=None):
        batch_size = inputs.shape[self._layout.find("N")]
        skip_states = states is None
        if skip_states:
            states = self.begin_state(batch_size, ctx=inputs.context)
        if isinstance(states, nd.NDArray):
            states = [states]
        for info, state in zip(self.state_info(batch_size), states):
            if state.shape != info["shape"]:
                raise ValueError(
                    "Invalid recurrent state shape. Expecting %s, got %s."
                    % (str(info["shape"]), str(state.shape)))
        try:
            params = self._packed_params()
        except Exception:
            self.shape_update(
                inputs if self._layout == "TNC"
                else nd.swapaxes(inputs, 0, 1))
            for quad in self._rnn_params:
                for p in quad:
                    p._finish_deferred_init()
            params = self._packed_params()
        out = self._forward_kernel(inputs, params, states)
        return out[0] if skip_states else out

    def _packed_params(self):
        """Pack per-layer params into the fused-op vector (weights of all
        layers/directions, then biases — ops/rnn_op.py layout)."""
        flats = [nd.reshape(q[i]._active_data(), (-1,))
                 for q in self._rnn_params for i in (0, 1)]
        flats += [q[i]._active_data() for q in self._rnn_params
                  for i in (2, 3)]
        return nd.concat(*flats, dim=0)

    def _forward_kernel(self, inputs, params, states):
        if self._layout == "NTC":
            inputs = nd.swapaxes(inputs, 0, 1)
        if self._mode == "lstm":
            h, c = states
            ret = nd.RNN(inputs, params, h, c, state_size=self._hidden_size,
                         num_layers=self._num_layers, mode=self._mode,
                         bidirectional=self._dir == 2, p=self._dropout,
                         state_outputs=True)
            outputs, h_out, c_out = ret
            new_states = [h_out, c_out]
        else:
            ret = nd.RNN(inputs, params, states[0],
                         state_size=self._hidden_size,
                         num_layers=self._num_layers, mode=self._mode,
                         bidirectional=self._dir == 2, p=self._dropout,
                         state_outputs=True)
            outputs, h_out = ret
            new_states = [h_out]
        if self._layout == "NTC":
            outputs = nd.swapaxes(outputs, 0, 1)
        return outputs, new_states

    def hybrid_forward(self, F, inputs, *args, **kwargs):
        raise NotImplementedError  # forward() fully overridden


class RNN(_RNNLayer):
    """Elman RNN layer (reference: rnn_layer.py RNN)."""

    def __init__(self, hidden_size, num_layers=1, activation="relu",
                 layout="TNC", dropout=0, bidirectional=False,
                 i2h_weight_initializer=None, h2h_weight_initializer=None,
                 i2h_bias_initializer="zeros", h2h_bias_initializer="zeros",
                 input_size=0, **kwargs):
        super().__init__(hidden_size, num_layers, layout, dropout,
                         bidirectional, input_size, i2h_weight_initializer,
                         h2h_weight_initializer, i2h_bias_initializer,
                         h2h_bias_initializer, "rnn_" + activation, **kwargs)

    def state_info(self, batch_size=0):
        return [{"shape": (self._num_layers * self._dir, batch_size,
                           self._hidden_size)}]


class LSTM(_RNNLayer):
    """LSTM layer (reference: rnn_layer.py LSTM)."""

    def __init__(self, hidden_size, num_layers=1, layout="TNC", dropout=0,
                 bidirectional=False, input_size=0,
                 i2h_weight_initializer=None, h2h_weight_initializer=None,
                 i2h_bias_initializer="zeros", h2h_bias_initializer="zeros",
                 **kwargs):
        super().__init__(hidden_size, num_layers, layout, dropout,
                         bidirectional, input_size, i2h_weight_initializer,
                         h2h_weight_initializer, i2h_bias_initializer,
                         h2h_bias_initializer, "lstm", **kwargs)

    def state_info(self, batch_size=0):
        return [{"shape": (self._num_layers * self._dir, batch_size,
                           self._hidden_size)},
                {"shape": (self._num_layers * self._dir, batch_size,
                           self._hidden_size)}]


class GRU(_RNNLayer):
    """GRU layer (reference: rnn_layer.py GRU)."""

    def __init__(self, hidden_size, num_layers=1, layout="TNC", dropout=0,
                 bidirectional=False, input_size=0,
                 i2h_weight_initializer=None, h2h_weight_initializer=None,
                 i2h_bias_initializer="zeros", h2h_bias_initializer="zeros",
                 **kwargs):
        super().__init__(hidden_size, num_layers, layout, dropout,
                         bidirectional, input_size, i2h_weight_initializer,
                         h2h_weight_initializer, i2h_bias_initializer,
                         h2h_bias_initializer, "gru", **kwargs)

    def state_info(self, batch_size=0):
        return [{"shape": (self._num_layers * self._dir, batch_size,
                           self._hidden_size)}]

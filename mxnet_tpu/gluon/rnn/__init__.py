"""Gluon RNN API (reference: python/mxnet/gluon/rnn/)."""
from .rnn_cell import *
from .rnn_layer import *

from . import rnn_cell
from . import rnn_layer

__all__ = rnn_cell.__all__ + rnn_layer.__all__

"""Gluon convolution & pooling layers.

Reference: ``python/mxnet/gluon/nn/conv_layers.py`` — Conv1D/2D/3D:156-313,
Conv1D-3DTranspose:394-563, Max/Avg/GlobalMax/GlobalAvgPool 1D-3D:678-1006.
"""
from __future__ import annotations

import numpy as np

from ..block import HybridBlock
from .basic_layers import Activation

__all__ = ["Conv1D", "Conv2D", "Conv3D", "Conv1DTranspose",
           "Conv2DTranspose", "Conv3DTranspose", "MaxPool1D", "MaxPool2D",
           "MaxPool3D", "AvgPool1D", "AvgPool2D", "AvgPool3D",
           "GlobalMaxPool1D", "GlobalMaxPool2D", "GlobalMaxPool3D",
           "GlobalAvgPool1D", "GlobalAvgPool2D", "GlobalAvgPool3D"]


def _tup(val, n):
    if isinstance(val, (int, np.integer)):
        return (int(val),) * n
    return tuple(int(v) for v in val)


class _Conv(HybridBlock):
    """Shared conv implementation (reference: conv_layers.py _Conv:33)."""

    def __init__(self, channels, kernel_size, strides, padding, dilation,
                 groups, layout, in_channels=0, activation=None,
                 use_bias=True, weight_initializer=None,
                 bias_initializer="zeros", op_name="Convolution", adj=None,
                 **kwargs):
        super().__init__(**kwargs)
        from ... import initializer as init_mod
        ndim = len(kernel_size)
        self._channels = channels
        self._in_channels = in_channels
        self._op_name = op_name
        self._kwargs = {
            "kernel": kernel_size, "stride": strides, "dilate": dilation,
            "pad": padding, "num_filter": channels, "num_group": groups,
            "no_bias": not use_bias, "layout": layout}
        if adj is not None:
            self._kwargs["adj"] = adj
        with self.name_scope():
            if op_name == "Convolution":
                wshape = (channels, in_channels // groups) + \
                    tuple(kernel_size)
            else:  # Deconvolution: (in, out, *k)
                wshape = (in_channels, channels // groups) + \
                    tuple(kernel_size)
            self.weight = self.params.get(
                "weight", shape=wshape, init=weight_initializer,
                allow_deferred_init=True)
            if use_bias:
                self.bias = self.params.get(
                    "bias", shape=(channels,), init=init_mod.Zero(),
                    allow_deferred_init=True)
            else:
                self.bias = None
            if activation is not None:
                self.act = Activation(activation, prefix=activation + "_")
            else:
                self.act = None

    def shape_update(self, x, *args):
        in_ch = x.shape[1]
        g = self._kwargs["num_group"]
        k = tuple(self._kwargs["kernel"])
        if self._op_name == "Convolution":
            self.weight.shape = (self._channels, in_ch // g) + k
        else:
            self.weight.shape = (in_ch, self._channels // g) + k
        if self.bias is not None:
            self.bias.shape = (self._channels,)

    def hybrid_forward(self, F, x, weight, bias=None):
        op = getattr(F, self._op_name)
        if bias is None:
            out = op(x, weight, **self._kwargs)
        else:
            out = op(x, weight, bias, **self._kwargs)
        if self.act is not None:
            out = self.act(out)
        return out

    def __repr__(self):
        return "%s(%s, kernel_size=%s, stride=%s)" % (
            self.__class__.__name__, self._channels,
            self._kwargs["kernel"], self._kwargs["stride"])


class Conv1D(_Conv):
    """(reference: conv_layers.py:156)."""

    def __init__(self, channels, kernel_size, strides=1, padding=0,
                 dilation=1, groups=1, layout="NCW", activation=None,
                 use_bias=True, weight_initializer=None,
                 bias_initializer="zeros", in_channels=0, **kwargs):
        super().__init__(channels, _tup(kernel_size, 1), _tup(strides, 1),
                         _tup(padding, 1), _tup(dilation, 1), groups, layout,
                         in_channels, activation, use_bias,
                         weight_initializer, bias_initializer, **kwargs)


class Conv2D(_Conv):
    """(reference: conv_layers.py:218)."""

    def __init__(self, channels, kernel_size, strides=(1, 1), padding=(0, 0),
                 dilation=(1, 1), groups=1, layout="NCHW", activation=None,
                 use_bias=True, weight_initializer=None,
                 bias_initializer="zeros", in_channels=0, **kwargs):
        super().__init__(channels, _tup(kernel_size, 2), _tup(strides, 2),
                         _tup(padding, 2), _tup(dilation, 2), groups, layout,
                         in_channels, activation, use_bias,
                         weight_initializer, bias_initializer, **kwargs)


class Conv3D(_Conv):
    """(reference: conv_layers.py:282)."""

    def __init__(self, channels, kernel_size, strides=(1, 1, 1),
                 padding=(0, 0, 0), dilation=(1, 1, 1), groups=1,
                 layout="NCDHW", activation=None, use_bias=True,
                 weight_initializer=None, bias_initializer="zeros",
                 in_channels=0, **kwargs):
        super().__init__(channels, _tup(kernel_size, 3), _tup(strides, 3),
                         _tup(padding, 3), _tup(dilation, 3), groups, layout,
                         in_channels, activation, use_bias,
                         weight_initializer, bias_initializer, **kwargs)


class Conv1DTranspose(_Conv):
    """(reference: conv_layers.py:394)."""

    def __init__(self, channels, kernel_size, strides=1, padding=0,
                 output_padding=0, dilation=1, groups=1, layout="NCW",
                 activation=None, use_bias=True, weight_initializer=None,
                 bias_initializer="zeros", in_channels=0, **kwargs):
        super().__init__(channels, _tup(kernel_size, 1), _tup(strides, 1),
                         _tup(padding, 1), _tup(dilation, 1), groups, layout,
                         in_channels, activation, use_bias,
                         weight_initializer, bias_initializer,
                         op_name="Deconvolution",
                         adj=_tup(output_padding, 1), **kwargs)


class Conv2DTranspose(_Conv):
    """(reference: conv_layers.py:450)."""

    def __init__(self, channels, kernel_size, strides=(1, 1), padding=(0, 0),
                 output_padding=(0, 0), dilation=(1, 1), groups=1,
                 layout="NCHW", activation=None, use_bias=True,
                 weight_initializer=None, bias_initializer="zeros",
                 in_channels=0, **kwargs):
        super().__init__(channels, _tup(kernel_size, 2), _tup(strides, 2),
                         _tup(padding, 2), _tup(dilation, 2), groups, layout,
                         in_channels, activation, use_bias,
                         weight_initializer, bias_initializer,
                         op_name="Deconvolution",
                         adj=_tup(output_padding, 2), **kwargs)


class Conv3DTranspose(_Conv):
    """(reference: conv_layers.py:510)."""

    def __init__(self, channels, kernel_size, strides=(1, 1, 1),
                 padding=(0, 0, 0), output_padding=(0, 0, 0),
                 dilation=(1, 1, 1), groups=1, layout="NCDHW",
                 activation=None, use_bias=True, weight_initializer=None,
                 bias_initializer="zeros", in_channels=0, **kwargs):
        super().__init__(channels, _tup(kernel_size, 3), _tup(strides, 3),
                         _tup(padding, 3), _tup(dilation, 3), groups, layout,
                         in_channels, activation, use_bias,
                         weight_initializer, bias_initializer,
                         op_name="Deconvolution",
                         adj=_tup(output_padding, 3), **kwargs)


class _Pooling(HybridBlock):
    """Shared pooling implementation (reference: conv_layers.py _Pooling)."""

    def __init__(self, pool_size, strides, padding, ceil_mode, global_pool,
                 pool_type, **kwargs):
        super().__init__(**kwargs)
        if strides is None:
            strides = pool_size
        self._kwargs = {
            "kernel": pool_size, "stride": strides, "pad": padding,
            "global_pool": global_pool, "pool_type": pool_type,
            "pooling_convention": "full" if ceil_mode else "valid"}

    def _alias(self):
        return "pool"

    def hybrid_forward(self, F, x):
        return F.Pooling(x, **self._kwargs)

    def __repr__(self):
        return "%s(size=%s, stride=%s)" % (
            self.__class__.__name__, self._kwargs["kernel"],
            self._kwargs["stride"])


class MaxPool1D(_Pooling):
    def __init__(self, pool_size=2, strides=None, padding=0, layout="NCW",
                 ceil_mode=False, **kwargs):
        super().__init__(_tup(pool_size, 1),
                         _tup(strides, 1) if strides is not None else None,
                         _tup(padding, 1), ceil_mode, False, "max", **kwargs)


class MaxPool2D(_Pooling):
    def __init__(self, pool_size=(2, 2), strides=None, padding=0,
                 layout="NCHW", ceil_mode=False, **kwargs):
        super().__init__(_tup(pool_size, 2),
                         _tup(strides, 2) if strides is not None else None,
                         _tup(padding, 2), ceil_mode, False, "max", **kwargs)


class MaxPool3D(_Pooling):
    def __init__(self, pool_size=(2, 2, 2), strides=None, padding=0,
                 layout="NCDHW", ceil_mode=False, **kwargs):
        super().__init__(_tup(pool_size, 3),
                         _tup(strides, 3) if strides is not None else None,
                         _tup(padding, 3), ceil_mode, False, "max", **kwargs)


class AvgPool1D(_Pooling):
    def __init__(self, pool_size=2, strides=None, padding=0, layout="NCW",
                 ceil_mode=False, **kwargs):
        super().__init__(_tup(pool_size, 1),
                         _tup(strides, 1) if strides is not None else None,
                         _tup(padding, 1), ceil_mode, False, "avg", **kwargs)


class AvgPool2D(_Pooling):
    def __init__(self, pool_size=(2, 2), strides=None, padding=0,
                 layout="NCHW", ceil_mode=False, **kwargs):
        super().__init__(_tup(pool_size, 2),
                         _tup(strides, 2) if strides is not None else None,
                         _tup(padding, 2), ceil_mode, False, "avg", **kwargs)


class AvgPool3D(_Pooling):
    def __init__(self, pool_size=(2, 2, 2), strides=None, padding=0,
                 layout="NCDHW", ceil_mode=False, **kwargs):
        super().__init__(_tup(pool_size, 3),
                         _tup(strides, 3) if strides is not None else None,
                         _tup(padding, 3), ceil_mode, False, "avg", **kwargs)


class GlobalMaxPool1D(_Pooling):
    def __init__(self, layout="NCW", **kwargs):
        super().__init__((1,), None, (0,), False, True, "max", **kwargs)


class GlobalMaxPool2D(_Pooling):
    def __init__(self, layout="NCHW", **kwargs):
        super().__init__((1, 1), None, (0, 0), False, True, "max", **kwargs)


class GlobalMaxPool3D(_Pooling):
    def __init__(self, layout="NCDHW", **kwargs):
        super().__init__((1, 1, 1), None, (0, 0, 0), False, True, "max",
                         **kwargs)


class GlobalAvgPool1D(_Pooling):
    def __init__(self, layout="NCW", **kwargs):
        super().__init__((1,), None, (0,), False, True, "avg", **kwargs)


class GlobalAvgPool2D(_Pooling):
    def __init__(self, layout="NCHW", **kwargs):
        super().__init__((1, 1), None, (0, 0), False, True, "avg", **kwargs)


class GlobalAvgPool3D(_Pooling):
    def __init__(self, layout="NCDHW", **kwargs):
        super().__init__((1, 1, 1), None, (0, 0, 0), False, True, "avg",
                         **kwargs)

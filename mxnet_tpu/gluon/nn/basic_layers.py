"""Basic Gluon layers.

Reference: ``python/mxnet/gluon/nn/basic_layers.py`` — Sequential:26,
HybridSequential:65, Dense:104, Activation:187, Dropout:219, BatchNorm:255,
LeakyReLU:342, Embedding:375, Flatten:416.
"""
from __future__ import annotations

import numpy as np

from ..block import Block, HybridBlock
from ..parameter import Parameter

__all__ = ["Sequential", "HybridSequential", "Dense", "Activation",
           "Dropout", "BatchNorm", "LeakyReLU", "Embedding", "Flatten",
           "Lambda", "HybridLambda", "MoE", "collect_aux_losses"]


class Sequential(Block):
    """Stack of Blocks (reference: basic_layers.py:26)."""

    def __init__(self, prefix=None, params=None):
        super().__init__(prefix=prefix, params=params)

    def add(self, *blocks):
        for block in blocks:
            self.register_child(block)

    def forward(self, x):
        for block in self._children:
            x = block(x)
        return x

    def __len__(self):
        return len(self._children)

    def __getitem__(self, i):
        return self._children[i]


class HybridSequential(HybridBlock):
    """Stack of HybridBlocks (reference: basic_layers.py:65)."""

    def __init__(self, prefix=None, params=None):
        super().__init__(prefix=prefix, params=params)

    def add(self, *blocks):
        for block in blocks:
            self.register_child(block)

    def forward(self, x):
        for block in self._children:
            x = block.forward(x) if isinstance(block, HybridBlock) \
                else block(x)
        return x

    def hybrid_forward(self, F, x):
        for block in self._children:
            x = block(x)
        return x

    def __len__(self):
        return len(self._children)

    def __getitem__(self, i):
        return self._children[i]


class Dense(HybridBlock):
    """Fully-connected layer (reference: basic_layers.py:104)."""

    def __init__(self, units, activation=None, use_bias=True,
                 flatten=True, weight_initializer=None,
                 bias_initializer="zeros", in_units=0, **kwargs):
        super().__init__(**kwargs)
        from ... import initializer as init_mod
        with self.name_scope():
            self._units = units
            self._in_units = in_units
            self._flatten = flatten
            self.weight = self.params.get(
                "weight", shape=(units, in_units),
                init=weight_initializer, allow_deferred_init=True)
            if use_bias:
                self.bias = self.params.get(
                    "bias", shape=(units,),
                    init=init_mod.Zero() if bias_initializer == "zeros"
                    else bias_initializer, allow_deferred_init=True)
            else:
                self.bias = None
            if activation is not None:
                self.act = Activation(activation, prefix=activation + "_")
            else:
                self.act = None

    def shape_update(self, x, *args):
        # flatten=False applies the projection to the last axis only
        # (reference basic_layers.py Dense(flatten=False))
        in_units = (int(x.shape[-1]) if not self._flatten
                    else int(np.prod(x.shape[1:])))
        self.weight.shape = (self._units, in_units)
        if self.bias is not None:
            self.bias.shape = (self._units,)

    def hybrid_forward(self, F, x, weight, bias=None):
        out = F.FullyConnected(x, weight, bias, num_hidden=self._units,
                               no_bias=bias is None,
                               flatten=self._flatten)
        if self.act is not None:
            out = self.act(out)
        return out

    def __repr__(self):
        return "Dense(%s -> %s)" % (self.weight.shape[1] or None, self._units)


class Activation(HybridBlock):
    """(reference: basic_layers.py:187)."""

    def __init__(self, activation, **kwargs):
        self._act_type = activation
        super().__init__(**kwargs)

    def _alias(self):
        return self._act_type

    def hybrid_forward(self, F, x):
        return F.Activation(x, act_type=self._act_type)

    def __repr__(self):
        return "Activation(%s)" % self._act_type


class Dropout(HybridBlock):
    """(reference: basic_layers.py:219)."""

    def __init__(self, rate=0.5, **kwargs):
        super().__init__(**kwargs)
        self._rate = rate

    def hybrid_forward(self, F, x):
        return F.Dropout(x, p=self._rate)

    def __repr__(self):
        return "Dropout(p = %s)" % self._rate


class BatchNorm(HybridBlock):
    """(reference: basic_layers.py:255)."""

    def __init__(self, axis=1, momentum=0.9, epsilon=1e-5, center=True,
                 scale=True, use_global_stats=False,
                 beta_initializer="zeros", gamma_initializer="ones",
                 running_mean_initializer="zeros",
                 running_variance_initializer="ones", in_channels=0,
                 **kwargs):
        super().__init__(**kwargs)
        from ... import initializer as init_mod
        self._axis = axis
        self._momentum = momentum
        self._epsilon = epsilon
        self._center = center
        self._scale = scale
        self._use_global_stats = use_global_stats
        self._in_channels = in_channels
        with self.name_scope():
            self.gamma = self.params.get(
                "gamma", grad_req="write" if scale else "null",
                shape=(in_channels,), init=init_mod.One(),
                allow_deferred_init=True)
            self.beta = self.params.get(
                "beta", grad_req="write" if center else "null",
                shape=(in_channels,), init=init_mod.Zero(),
                allow_deferred_init=True)
            self.running_mean = self.params.get(
                "running_mean", grad_req="null", shape=(in_channels,),
                init=init_mod.Zero(), allow_deferred_init=True,
                differentiable=False)
            self.running_var = self.params.get(
                "running_var", grad_req="null", shape=(in_channels,),
                init=init_mod.One(), allow_deferred_init=True,
                differentiable=False)

    def shape_update(self, x, *args):
        ch = x.shape[self._axis]
        for p in (self.gamma, self.beta, self.running_mean,
                  self.running_var):
            p.shape = (ch,)

    def hybrid_forward(self, F, x, gamma, beta, running_mean, running_var):
        return F.BatchNorm(x, gamma, beta, running_mean, running_var,
                           eps=self._epsilon, momentum=self._momentum,
                           fix_gamma=not self._scale,
                           use_global_stats=self._use_global_stats,
                           axis=self._axis)

    def __repr__(self):
        return "BatchNorm(axis=%d, channels=%s)" % (
            self._axis, self.gamma.shape[0] or None)


class LeakyReLU(HybridBlock):
    """(reference: basic_layers.py:342)."""

    def __init__(self, alpha, **kwargs):
        super().__init__(**kwargs)
        self._alpha = alpha

    def hybrid_forward(self, F, x):
        return F.LeakyReLU(x, act_type="leaky", slope=self._alpha)

    def __repr__(self):
        return "LeakyReLU(%s)" % self._alpha


class Embedding(HybridBlock):
    """(reference: basic_layers.py:375)."""

    def __init__(self, input_dim, output_dim, dtype=np.float32,
                 weight_initializer=None, **kwargs):
        super().__init__(**kwargs)
        self._input_dim = input_dim
        self._output_dim = output_dim
        self._dtype = dtype
        with self.name_scope():
            self.weight = self.params.get(
                "weight", shape=(input_dim, output_dim), dtype=dtype,
                init=weight_initializer, allow_deferred_init=True)

    def hybrid_forward(self, F, x, weight):
        return F.Embedding(x, weight, input_dim=self._input_dim,
                           output_dim=self._output_dim)

    def __repr__(self):
        return "Embedding(%d -> %d)" % (self._input_dim, self._output_dim)


class Flatten(HybridBlock):
    """(reference: basic_layers.py:416)."""

    def hybrid_forward(self, F, x):
        return F.Flatten(x)

    def __repr__(self):
        return "Flatten"


class Lambda(Block):
    """Wrap a function into a Block (reference: later-era gluon Lambda —
    provided for custom-op ergonomics)."""

    def __init__(self, function, prefix=None):
        super().__init__(prefix=prefix)
        assert callable(function)
        self._func = function

    def forward(self, *args):
        return self._func(*args)


class HybridLambda(HybridBlock):
    """Wrap a function into a HybridBlock."""

    def __init__(self, function, prefix=None):
        super().__init__(prefix=prefix)
        assert callable(function)
        self._func = function

    def hybrid_forward(self, F, *args):
        return self._func(F, *args)


class MoE(HybridBlock):
    """Mixture-of-experts FFN layer (Switch/GShard dense dispatch).

    No reference counterpart (SURVEY.md §2.21: expert parallel absent
    upstream) — this is the TPU build's modern block over the ``MoE``
    framework op (ops/contrib.py / parallel/moe.py). Input (..., d_model)
    -> output of the same shape.

    The router's load-balance auxiliary loss from the latest forward is
    kept on ``self.aux_loss``; add ``collect_aux_losses(net)`` (weighted)
    to the training loss so the router learns balanced routing::

        out = net(x)
        loss = loss_fn(out, y) + 0.01 * nn.collect_aux_losses(net)

    Keep MoE nets *unhybridized* when training the router: under
    ``hybridize()`` the forward runs once inside a jit trace, so the
    stashed aux loss would be a stale tracer — ``collect_aux_losses``
    detects that and raises instead of silently untraining the router.
    """

    def __init__(self, d_model, d_hidden, n_experts, top_k=2,
                 capacity_factor=1.25, weight_initializer=None, **kwargs):
        super().__init__(**kwargs)
        from ... import initializer as init_mod
        self._attrs = dict(top_k=int(top_k),
                           capacity_factor=float(capacity_factor))
        self.aux_loss = None
        s_in = 1.0 / float(d_model) ** 0.5
        s_hid = 1.0 / float(d_hidden) ** 0.5
        with self.name_scope():
            self.router = self.params.get(
                "router_weight", shape=(d_model, n_experts),
                init=weight_initializer or init_mod.Normal(s_in))
            self.wi = self.params.get(
                "wi_weight", shape=(n_experts, d_model, d_hidden),
                init=weight_initializer or init_mod.Normal(s_in))
            self.wo = self.params.get(
                "wo_weight", shape=(n_experts, d_hidden, d_model),
                init=weight_initializer or init_mod.Normal(s_hid))

    def hybrid_forward(self, F, x, router, wi, wo):
        out, aux = F.MoE(x, router, wi, wo, **self._attrs)
        self.aux_loss = aux
        return out


def collect_aux_losses(block):
    """Sum the ``aux_loss`` of every sub-block that produced one in its
    latest forward (e.g. :class:`MoE` routers). Returns 0.0 when none.

    Raises when an aux loss was captured inside a ``hybridize()`` jit
    trace (a stale tracer that cannot participate in a later loss);
    aux-loss training is an eager-path feature."""
    import jax.core as _jcore
    total = None
    stack = [block]
    while stack:
        b = stack.pop()
        aux = getattr(b, "aux_loss", None)
        if aux is not None:
            data = getattr(aux, "data", aux)
            if isinstance(data, _jcore.Tracer):
                raise RuntimeError(
                    "%s.aux_loss was captured inside a hybridize() trace "
                    "and is stale; run the net unhybridized "
                    "(net.hybridize(False)) to train with auxiliary "
                    "losses" % type(b).__name__)
            total = aux if total is None else total + aux
        stack.extend(getattr(b, "_children", ()))
    return 0.0 if total is None else total

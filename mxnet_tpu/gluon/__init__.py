"""Gluon — the imperative high-level API.

Reference: ``python/mxnet/gluon/`` (SURVEY.md §2.14): Block/HybridBlock
containers, Parameter/ParameterDict, Trainer, nn/rnn layer catalogs, losses,
data pipeline, model zoo.

TPU design: ``hybridize()`` compiles forward (and, under autograd, backward)
into jitted XLA programs — the CachedOp equivalent (see block.py).
"""
from . import block
from . import nn
from . import loss
from . import parameter
from . import trainer
from . import utils
from . import data
from . import model_zoo
from . import rnn

from .parameter import Parameter, ParameterDict, DeferredInitializationError
from .block import Block, HybridBlock, SymbolBlock
from .trainer import Trainer

__all__ = ["nn", "rnn", "loss", "data", "utils", "model_zoo", "Parameter",
           "ParameterDict", "DeferredInitializationError", "Block",
           "HybridBlock", "SymbolBlock", "Trainer"]

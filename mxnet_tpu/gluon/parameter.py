"""Gluon Parameter / ParameterDict.

Reference: ``python/mxnet/gluon/parameter.py`` (611 LoC) — ``Parameter``
holds data+grad per context with deferred initialization; ``ParameterDict``
is a prefix-scoped registry shared across blocks.

TPU note: one ``jax.Array`` (possibly mesh-sharded) replaces the reference's
per-device copy list, so ``list_data``/``list_grad`` return single-element
lists unless multiple contexts were requested explicitly.
"""
from __future__ import annotations

from collections import OrderedDict
from typing import Dict, List, Optional

import numpy as np

from ..base import MXNetError
from ..context import Context, cpu, current_context
from .. import ndarray as nd
from .. import initializer as init_mod
from ..initializer import InitDesc

__all__ = ["Parameter", "ParameterDict", "DeferredInitializationError"]


class DeferredInitializationError(MXNetError):
    """Parameter accessed before its shape was known (reference:
    parameter.py DeferredInitializationError)."""


class Parameter(object):
    """A Block parameter (reference: parameter.py Parameter).

    Holds the value and gradient; supports deferred initialization for
    shapes with unknown (0) dimensions resolved at first forward.
    """

    def __init__(self, name, grad_req="write", shape=None, dtype=np.float32,
                 lr_mult=1.0, wd_mult=1.0, init=None, allow_deferred_init=False,
                 differentiable=True):
        self.name = name
        self.shape = tuple(shape) if shape is not None else None
        self.dtype = dtype
        self.lr_mult = lr_mult
        self.wd_mult = wd_mult
        self.init = init
        self.allow_deferred_init = allow_deferred_init
        if not differentiable:
            grad_req = "null"
        self._grad_req = grad_req
        self._data: Optional[nd.NDArray] = None
        self._grad: Optional[nd.NDArray] = None
        self._deferred_init = ()  # (init, ctx, default_init)

    def __repr__(self):
        return "Parameter %s (shape=%s, dtype=%s)" % (
            self.name, self.shape, np.dtype(self.dtype).name)

    @property
    def grad_req(self):
        return self._grad_req

    @grad_req.setter
    def grad_req(self, req):
        assert req in ("write", "add", "null")
        if self._grad_req == req:
            return
        self._grad_req = req
        if req == "null":
            self._grad = None
        elif self._data is not None:
            # re-mark even when a grad buffer already exists: the tape
            # keeps the req it was marked with, so switching an
            # initialized parameter write->add (the gradient-
            # accumulation idiom) must re-register or backward() keeps
            # overwriting (the fresh zero grad matches the reference's
            # re-alloc semantics)
            self._init_grad()

    # ------------------------------------------------------------- init
    def initialize(self, init=None, ctx=None, default_init=None,
                   force_reinit=False):
        """(reference: parameter.py Parameter.initialize)."""
        if default_init is None:
            default_init = init_mod.Uniform()
        if self._data is not None and not force_reinit:
            return
        if ctx is None:
            ctx = current_context()
        if isinstance(ctx, Context):
            ctx = [ctx]
        if self.shape is None or any(s == 0 for s in self.shape):
            if self.allow_deferred_init:
                self._deferred_init = (init, ctx, default_init)
                return
            raise ValueError(
                "Cannot initialize Parameter %s because it has invalid "
                "shape: %s." % (self.name, str(self.shape)))
        self._finish_init(init, ctx, default_init)

    def _finish_deferred_init(self):
        if not self._deferred_init:
            return
        init, ctx, default_init = self._deferred_init
        self._deferred_init = ()
        if self.shape is None or any(s == 0 for s in self.shape):
            raise DeferredInitializationError(
                "deferred init of %s failed: shape still unknown (%s)"
                % (self.name, self.shape))
        self._finish_init(init, ctx, default_init)

    def _finish_init(self, init, ctx, default_init):
        data = nd.zeros(self.shape, dtype=self.dtype, ctx=ctx[0])
        initializer = init if init is not None else \
            (self.init if self.init is not None else default_init)
        initializer(InitDesc(self.name, {"__init__": ""}), data)
        self._data = self._place(data, ctx)
        if self._grad_req != "null":
            self._init_grad()

    def _place(self, data, ctx):
        """Multi-device: ONE array replicated over a data-parallel mesh
        (the TPU form of the reference's per-ctx copies, parameter.py
        _init_impl); batch-sharded inputs from split_and_load then train
        data-parallel via GSPMD with the grad psum inserted by XLA."""
        if ctx is not None and len(ctx) > 1:
            from ..parallel.mesh import data_parallel_mesh, replicate
            self._ctx_list = list(ctx)
            return nd.NDArray(replicate(data_parallel_mesh(ctx), data.data))
        self._ctx_list = None
        return data

    def _init_grad(self):
        import jax.numpy as jnp
        # zeros_like keeps the data's sharding (replicated on a mesh when
        # initialized with several contexts)
        self._grad = nd.NDArray(jnp.zeros_like(self._data.data))
        from .. import autograd
        autograd.mark_variables([self._data], [self._grad],
                                grad_reqs=self._grad_req)

    def _load_init(self, data, ctx=None):
        """Load from a checkpoint value (reference: parameter.py
        _load_init)."""
        if self.shape is not None and not any(s == 0 for s in self.shape):
            if tuple(data.shape) != tuple(self.shape):
                raise ValueError(
                    "Failed loading Parameter %s from saved params: shape "
                    "mismatch %s vs %s" % (self.name, data.shape, self.shape))
        self.shape = tuple(data.shape)
        self._deferred_init = ()
        if np.dtype(data.dtype) != np.dtype(self.dtype):
            data = data.astype(self.dtype)
        # keep the mesh-replication invariant: a multi-ctx parameter must
        # stay replicated after loading from a (single-device) checkpoint
        self._data = self._place(data, getattr(self, "_ctx_list", None)
                                 or (ctx if isinstance(ctx, list) else None))
        if self._grad_req != "null":
            self._init_grad()

    # ------------------------------------------------------------- access
    def _check_initialized(self):
        if self._data is not None:
            return
        if self._deferred_init:
            raise DeferredInitializationError(
                "Parameter %s has not been initialized yet because "
                "initialization was deferred. Actual initialization happens "
                "during the first forward pass." % self.name)
        raise RuntimeError(
            "Parameter %s has not been initialized. You should initialize "
            "parameters with Block.collect_params().initialize(...)"
            % self.name)

    def data(self, ctx=None) -> nd.NDArray:
        """(reference: parameter.py Parameter.data)."""
        self._check_initialized()
        return self._data

    def list_data(self) -> List[nd.NDArray]:
        self._check_initialized()
        return [self._data]

    def grad(self, ctx=None) -> nd.NDArray:
        self._check_initialized()
        if self._grad is None:
            raise RuntimeError(
                "Cannot get gradient array for Parameter %s because "
                "grad_req='null'" % self.name)
        return self._grad

    def list_grad(self) -> List[nd.NDArray]:
        return [self.grad()]

    def list_ctx(self) -> List[Context]:
        self._check_initialized()
        if getattr(self, "_ctx_list", None):
            return list(self._ctx_list)
        return [self._data.context]

    def set_data(self, data):
        """(reference: parameter.py set_data)."""
        self._check_initialized()
        if not isinstance(data, nd.NDArray):
            data = nd.array(data, dtype=self.dtype)
        self._data[:] = data

    def zero_grad(self):
        if self._grad is not None:
            self._grad[:] = 0

    def var(self):
        """Symbol variable for this parameter (reference: parameter.py
        var)."""
        from .. import symbol as sym
        return sym.Variable(self.name, shape=self.shape,
                            lr_mult=self.lr_mult, wd_mult=self.wd_mult)

    def cast(self, dtype):
        self.dtype = dtype
        if self._data is not None:
            self._data = self._data.astype(dtype)
            if self._grad is not None:
                self._grad = self._grad.astype(dtype)
                from .. import autograd
                autograd.mark_variables([self._data], [self._grad],
                                        grad_reqs=self._grad_req)

    def reset_ctx(self, ctx):
        """Move to a new context (reference: parameter.py reset_ctx)."""
        if self._data is not None:
            ctx_list = [ctx] if isinstance(ctx, Context) else list(ctx)
            self._data = self._place(self._data.copyto(ctx_list[0]),
                                     ctx_list)
            if self._grad_req != "null":
                self._init_grad()


class ParameterDict(object):
    """A prefix-scoped dict of Parameters (reference: parameter.py:
    ParameterDict)."""

    def __init__(self, prefix="", shared=None):
        self._prefix = prefix
        self._params: "OrderedDict[str, Parameter]" = OrderedDict()
        self._shared = shared

    @property
    def prefix(self):
        return self._prefix

    def __repr__(self):
        s = "%s(\n%s\n)" % (self._prefix or "ParameterDict",
                            "\n".join("  " + repr(p)
                                      for p in self._params.values()))
        return s

    def __getitem__(self, key) -> Parameter:
        return self._params[key]

    def __iter__(self):
        return iter(self._params)

    def items(self):
        return self._params.items()

    def keys(self):
        return self._params.keys()

    def values(self):
        return self._params.values()

    def get(self, name, **kwargs) -> Parameter:
        """Create-or-retrieve ``self.prefix + name`` (reference:
        parameter.py ParameterDict.get)."""
        name = self._prefix + name
        param = self._get_impl(name)
        if param is None:
            param = Parameter(name, **kwargs)
            self._params[name] = param
        else:
            for k, v in kwargs.items():
                if getattr(param, k, None) is not None and v is not None:
                    existing = getattr(param, k)
                    if k == "shape" and len(v) == len(existing):
                        # merge unknown dims
                        merged = tuple(a if a != 0 else b
                                       for a, b in zip(v, existing))
                        param.shape = merged
                        continue
                    assert str(existing) == str(v) or k in ("init",), \
                        "Parameter %s already exists with different %s" \
                        % (name, k)
                else:
                    setattr(param, k if k != "grad_req" else "_grad_req", v)
        return param

    def _get_impl(self, name):
        if name in self._params:
            return self._params[name]
        if self._shared is not None and name in self._shared._params:
            self._params[name] = self._shared._params[name]
            return self._params[name]
        return None

    def update(self, other):
        for k, v in other.items():
            if k in self._params:
                assert self._params[k] is v, \
                    "Cannot update because keys have different values"
            else:
                self._params[k] = v

    def initialize(self, init=None, ctx=None, verbose=False,
                   force_reinit=False):
        """(reference: parameter.py ParameterDict.initialize)."""
        if init is None:
            init = init_mod.Uniform()
        for _, v in self.items():
            v.initialize(None, ctx, init, force_reinit=force_reinit)

    def zero_grad(self):
        for v in self.values():
            v.zero_grad()

    def reset_ctx(self, ctx):
        for v in self.values():
            v.reset_ctx(ctx)

    def setattr(self, name, value):
        for v in self.values():
            setattr(v, name, value)

    def save(self, filename, strip_prefix=""):
        """(reference: parameter.py ParameterDict.save)."""
        arg_dict = {}
        for param in self.values():
            block = param.list_data()
            weight = sum(w.copyto(cpu()) for w in block) / len(block)
            if not param.name.startswith(strip_prefix):
                raise ValueError(
                    "Prefix %s is to be stripped before saving, but "
                    "Parameter %s does not start with %s"
                    % (strip_prefix, param.name, strip_prefix))
            arg_dict[param.name[len(strip_prefix):]] = weight
        nd.save(filename, arg_dict)

    def load(self, filename, ctx=None, allow_missing=False,
             ignore_extra=False, restore_prefix=""):
        """(reference: parameter.py ParameterDict.load)."""
        arg_dict = nd.load(filename)
        if restore_prefix:
            arg_dict = {restore_prefix + k: v for k, v in arg_dict.items()}
        if not allow_missing:
            for name in self.keys():
                assert name in arg_dict, \
                    "Parameter %s is missing in file %s" % (name, filename)
        for name, value in arg_dict.items():
            if name not in self._params:
                if not ignore_extra:
                    raise ValueError(
                        "Parameter %s loaded from file %s is not present in "
                        "ParameterDict" % (name, filename))
                continue
            self[name]._load_init(value, ctx)

"""Gluon Block / HybridBlock / SymbolBlock.

Reference: ``python/mxnet/gluon/block.py`` — ``Block`` (line 115) is the
imperative layer container; ``HybridBlock`` (line 283) records a symbolic
graph on first call and swaps in a ``CachedOp`` (``_build_cache:361``);
``SymbolBlock`` (line 433) wraps an existing Symbol.

TPU design: ``hybridize()`` compiles the block's forward into ONE jitted XLA
program per input signature (the jit cache is the CachedOp). Under
``autograd.record()`` the whole compiled forward is recorded as a single
composite tape op, so ``backward()`` runs one ``jax.vjp`` over the compiled
function — the CachedOp forward+backward speedup, the XLA way.
"""
from __future__ import annotations

import re
import threading
from collections import OrderedDict
from typing import Dict, List, Optional

import numpy as np
import jax

from ..base import MXNetError
from ..context import Context, cpu, current_context
from .. import ndarray as nd
from ..ndarray import NDArray
from ..ops.registry import OpDef
from .. import autograd
from .parameter import Parameter, ParameterDict, DeferredInitializationError

__all__ = ["Block", "HybridBlock", "SymbolBlock"]


class _BlockScope(object):
    """Name manager for Blocks (reference: block.py _BlockScope)."""

    _current = threading.local()

    def __init__(self, block):
        self._block = block
        self._counter: Dict[str, int] = {}
        self._old_scope = None

    @staticmethod
    def create(prefix, params, hint):
        current = getattr(_BlockScope._current, "value", None)
        if current is None:
            if prefix is None:
                prefix = _name_prefix(hint)
            if params is None:
                params = ParameterDict(prefix)
            else:
                params = ParameterDict(params.prefix, params)
            return prefix, params
        if prefix is None:
            count = current._counter.get(hint, 0)
            current._counter[hint] = count + 1
            prefix = "%s%d_" % (hint, count)
        if params is None:
            parent = current._block.params
            params = ParameterDict(parent.prefix + prefix, parent._shared)
        else:
            params = ParameterDict(params.prefix, params)
        return current._block.prefix + prefix, params

    def __enter__(self):
        self._old_scope = getattr(_BlockScope._current, "value", None)
        _BlockScope._current.value = self
        return self

    def __exit__(self, *exc):
        _BlockScope._current.value = self._old_scope


_GLOBAL_NAME_COUNTS: Dict[str, int] = {}


def _name_prefix(hint):
    count = _GLOBAL_NAME_COUNTS.get(hint, 0)
    _GLOBAL_NAME_COUNTS[hint] = count + 1
    return "%s%d_" % (hint, count)


def _flatten(args):
    """Flatten nested list/tuple structure, returning (flat, fmt)."""
    if isinstance(args, NDArray):
        return [args], 0
    if isinstance(args, (list, tuple)):
        flat, fmts = [], []
        for a in args:
            f, fmt = _flatten(a)
            flat.extend(f)
            fmts.append(fmt)
        return flat, fmts
    return [args], -1


def _regroup(flat, fmt):
    if fmt == 0:
        return flat[0], flat[1:]
    if fmt == -1:
        return flat[0], flat[1:]
    ret = []
    for f in fmt:
        res, flat = _regroup(flat, f)
        ret.append(res)
    return ret, flat


class Block(object):
    """Base building block (reference: block.py:115)."""

    def __init__(self, prefix=None, params=None):
        self._empty_prefix = prefix == ""
        self._prefix, self._params = _BlockScope.create(
            prefix, params, self._alias())
        self._name = self._prefix[:-1] if self._prefix.endswith("_") \
            else self._prefix
        self._scope = _BlockScope(self)
        self._children: List[Block] = []
        self._reg_params: Dict[str, Parameter] = {}

    def _alias(self):
        return self.__class__.__name__.lower()

    def __repr__(self):
        s = "{name}(\n{modstr}\n)"
        modstr = "\n".join("  ({key}): {block}".format(
            key=i, block=repr(b).replace("\n", "\n  "))
            for i, b in enumerate(self._children))
        return s.format(name=self.__class__.__name__, modstr=modstr)

    def __setattr__(self, name, value):
        if isinstance(value, Block):
            self.register_child(value)
        elif isinstance(value, Parameter):
            assert name not in self._reg_params or \
                self._reg_params[name] is value, \
                "Overriding Parameter attribute %s is not allowed." % name
            self._reg_params[name] = value
        super().__setattr__(name, value)

    @property
    def prefix(self):
        return self._prefix

    @property
    def name(self):
        return self._name

    def name_scope(self):
        """``with self.name_scope():`` (reference: block.py name_scope)."""
        return self._scope

    @property
    def params(self) -> ParameterDict:
        return self._params

    def collect_params(self) -> ParameterDict:
        """All Parameters of this Block and its children (reference:
        block.py collect_params)."""
        ret = ParameterDict(self._params.prefix)
        ret.update(self.params)
        for child in self._children:
            ret.update(child.collect_params())
        return ret

    def save_params(self, filename):
        """(reference: block.py:216 save_params — full parameter names, the
        v0.11 behavior; prefix-stripping arrived in later MXNet)."""
        self.collect_params().save(filename)

    def load_params(self, filename, ctx=None, allow_missing=False,
                    ignore_extra=False):
        """(reference: block.py:240 load_params)."""
        self.collect_params().load(filename, ctx, allow_missing,
                                   ignore_extra)

    def register_child(self, block):
        self._children.append(block)

    def initialize(self, init=None, ctx=None, verbose=False,
                   force_reinit=False):
        """Initialize all parameters (reference: block.py initialize)."""
        from .. import initializer as init_mod
        self.collect_params().initialize(
            init or init_mod.Uniform(), ctx, verbose,
            force_reinit=force_reinit)

    def hybridize(self, active=True):
        """Activate graph compilation in child HybridBlocks."""
        for child in self._children:
            child.hybridize(active)

    def cast(self, dtype):
        for child in self._children:
            child.cast(dtype)
        for _, param in self.params.items():
            param.cast(dtype)

    def __call__(self, *args):
        return self.forward(*args)

    def forward(self, *args):
        raise NotImplementedError


class HybridBlock(Block):
    """A Block convertible to one compiled program (reference:
    block.py:283)."""

    def __init__(self, prefix=None, params=None):
        super().__init__(prefix=prefix, params=params)
        self._active = False
        self._cached_op = None         # signature -> (jitted fn, OpDef)
        self._in_sig = None

    def __setattr__(self, name, value):
        super().__setattr__(name, value)
        if isinstance(value, HybridBlock):
            self._clear_cached_op()

    def hybridize(self, active=True):
        self._active = active
        self._clear_cached_op()
        super().hybridize(active)

    def cast(self, dtype):
        self._clear_cached_op()
        super().cast(dtype)

    def _clear_cached_op(self):
        if getattr(self, "_cached_op", None) is not None:
            self._cached_op = {}
        else:
            self._cached_op = {}

    def register_child(self, block):
        if not isinstance(block, HybridBlock):
            raise ValueError(
                "Children of HybridBlock must also be HybridBlock, but %s "
                "has type %s." % (str(block), str(type(block))))
        super().register_child(block)
        self._clear_cached_op()

    def infer_shape(self, *args):
        """Run a deferred-shape probe (reference: block.py infer_shape)."""
        self._deferred_infer_shape(*args)

    def _deferred_infer_shape(self, *args):
        """Resolve 0-dims in child parameters by running the imperative
        forward once with recording off (the reference walks the symbolic
        graph; a concrete probe is equivalent and simpler here)."""
        with autograd.pause(train_mode=False):
            self.forward(*args)

    def __call__(self, *args):
        if self._active:
            return self._call_cached_op(*args)
        return self.forward(*args)

    # --------------------------------------------------- CachedOp (jit)
    def _make_cached_op(self, flat_args):
        params = [p for _, p in sorted(self.collect_params().items())]
        # non-differentiable params (BatchNorm running stats) follow the
        # aux-state protocol: the traced program returns their updated
        # values as extra outputs to commit after the call
        aux_idx = [i for i, p in enumerate(params) if p.grad_req == "null"]
        n_in = len(flat_args)
        out_fmt = {}   # filled at trace time

        def raw(*vals, _rng=None):
            in_vals = vals[:n_in]
            p_vals = vals[n_in:]
            wrapped = [NDArray(v) for v in in_vals]
            for p, v in zip(params, p_vals):
                p._data_override = NDArray(v)
            # Thread the PRNG key explicitly: sampler ops (Dropout) split
            # the thread-local key, which inside this trace would replace
            # the global key with a tracer (UnexpectedTracerError at the
            # next eager op). Seed the chain with the traced _rng and
            # restore the caller's key after tracing; the concrete _rng is
            # recorded in the tape attrs so backward replays exact masks.
            from .. import random as _random
            saved_key = _random.current_key()
            if _rng is not None:
                _random._state.key = _rng
            try:
                with autograd.pause(train_mode=autograd.is_training()):
                    out = self.forward(*wrapped)
                aux_new = tuple(params[i]._data_override._data
                                for i in aux_idx)
            finally:
                for p in params:
                    p._data_override = None
                _random._state.key = saved_key
            flat_out, fmt = _flatten(out)
            out_fmt["fmt"] = fmt
            out_fmt["n_out"] = len(flat_out)
            return tuple(o._data for o in flat_out) + aux_new

        jitted = jax.jit(raw)
        op = OpDef("_cached_op_%s" % self.name, jitted, num_inputs=None)
        return jitted, op, params, aux_idx, out_fmt

    def _call_cached_op(self, *args):
        flat_args, _ = _flatten(args)
        try:
            if any(isinstance(a._data, jax.core.Tracer)
                   for a in flat_args):
                # inside an enclosing trace (parent CachedOp): run the
                # imperative body so the parent's jit sees the whole graph
                return self.forward(*args)
            sig = tuple((tuple(a.shape), str(a.dtype)) for a in flat_args) \
                + (autograd.is_training(),)
        except AttributeError:
            return self.forward(*args)  # non-NDArray inputs: eager
        entry = self._cached_op.get(sig) if self._cached_op else None
        if entry is None:
            # materialize deferred params before tracing (probe if needed)
            if any(p._data is None
                   for p in self.collect_params().values()):
                self._deferred_infer_shape(*args)
            for _, p in sorted(self.collect_params().items()):
                p._finish_deferred_init()
            entry = self._make_cached_op(flat_args)
            if self._cached_op is None:
                self._cached_op = {}
            self._cached_op[sig] = entry
        jitted, op, params, aux_idx, out_fmt = entry

        in_nds = list(flat_args) + [p.data() for p in params]
        in_vals = [a._data for a in in_nds]
        from .. import random as _random
        call_rng = _random.next_key()
        all_outs = jitted(*in_vals, _rng=call_rng)
        n_out = out_fmt["n_out"]
        out_nds = [NDArray(o) for o in all_outs[:n_out]]
        # commit updated aux states (BatchNorm moving stats)
        aux_targets = []
        for i, v in zip(aux_idx, all_outs[n_out:]):
            arr = params[i]._data
            arr._data = v
            arr._version += 1
            aux_targets.append(arr)
        if autograd.is_recording():
            # record the compiled forward as ONE composite tape op: backward
            # is one jax.vjp over the jitted program (CachedOp backward)
            in_keys = [(a._uid, a._version) for a in in_nds]
            autograd._record_op(op, {"_rng": call_rng}, in_keys, in_vals,
                                out_nds + aux_targets)
        fmt = out_fmt.get("fmt", 0)
        if fmt == 0:
            return out_nds[0]
        res, _ = _regroup(out_nds, fmt)
        return res

    # --------------------------------------------------- imperative path
    def forward(self, x, *args):
        """Gather params and defer to hybrid_forward (reference:
        block.py HybridBlock.forward)."""
        try:
            params = {k: p._active_data()
                      for k, p in self._reg_params.items()}
        except DeferredInitializationError:
            self._infer_param_shapes(x, *args)
            params = {k: p._active_data()
                      for k, p in self._reg_params.items()}
        return self.hybrid_forward(nd, x, *args, **params)

    def _infer_param_shapes(self, x, *args):
        """Resolve deferred shapes from the first input (layers override
        shape hooks via their own logic in hybrid_forward pre-checks)."""
        self.shape_update(x, *args)
        for p in self._reg_params.values():
            p._finish_deferred_init()

    def shape_update(self, x, *args):
        """Layers with deferred params override to set shapes from input."""
        raise DeferredInitializationError(
            "%s has uninitialized parameters and does not implement "
            "shape inference" % type(self).__name__)

    def hybrid_forward(self, F, x, *args, **kwargs):
        raise NotImplementedError

    def export(self, path):
        """Export symbol json + params for the predict path (reference:
        block.py export via HybridBlock symbols). Uses the symbolic twin of
        hybrid_forward."""
        raise NotImplementedError(
            "export requires the symbolic tracing frontend; use "
            "mx.mod.Module checkpoints for deployment")


def _param_active_data(self):
    override = getattr(self, "_data_override", None)
    if override is not None:
        return override
    if self._data is None:
        if self._deferred_init:
            raise DeferredInitializationError(
                "Parameter %s pending deferred init" % self.name)
        self._check_initialized()
    return self._data


# attach the trace-override accessor used by the CachedOp path
Parameter._active_data = _param_active_data
Parameter._data_override = None


class SymbolBlock(HybridBlock):
    """Wrap a Symbol into a Block (reference: block.py:433)."""

    def __init__(self, outputs, inputs, params=None):
        super().__init__(prefix=None, params=params)
        from .. import symbol as sym_mod
        if isinstance(inputs, sym_mod.Symbol):
            inputs = [inputs]
        if isinstance(outputs, (list, tuple)):
            outputs = sym_mod.Group(list(outputs))
        self._in_names = [i.name for i in inputs]
        self._symbol = outputs
        arg_names = set(outputs.list_arguments())
        aux_names = set(outputs.list_auxiliary_states())
        for name in arg_names - set(self._in_names):
            self.params.get(name[len(self.params.prefix):]
                            if name.startswith(self.params.prefix) else name,
                            allow_deferred_init=True)
        for name in aux_names:
            self.params.get(name[len(self.params.prefix):]
                            if name.startswith(self.params.prefix) else name,
                            grad_req="null", allow_deferred_init=True)
        self._fn = None
        self._op = None

    def forward(self, *args):
        from ..executor import graph_function
        from .. import random as rnd_mod
        if self._fn is None:
            gfn = graph_function(self._symbol)
            arg_names = [n for n in self._symbol.list_arguments()]
            aux_names = list(self._symbol.list_auxiliary_states())
            in_order = self._in_names + \
                [n for n in arg_names if n not in self._in_names]

            def positional(*vals):
                n_args = len(in_order)
                arg_map = dict(zip(in_order, vals[:n_args]))
                aux_map = dict(zip(aux_names, vals[n_args:-1]))
                key = vals[-1]
                outs, _ = gfn(arg_map, aux_map, key,
                              autograd.is_training())
                return tuple(outs)

            self._fn = positional
            self._in_order = in_order
            self._aux_names = aux_names
            self._op = OpDef("_symbol_block_%s" % self.name, positional,
                             num_inputs=None, is_random=False)

        named = dict(zip(self._in_names, args))
        in_nds = []
        for n in self._in_order:
            if n in named:
                a = named[n]
                in_nds.append(a if isinstance(a, NDArray) else NDArray(a))
            else:
                in_nds.append(self.params[n]._active_data())
        in_nds += [self.params[n]._active_data() for n in self._aux_names]
        key_nd = NDArray(rnd_mod.next_key())
        in_nds.append(key_nd)
        in_vals = [a._data for a in in_nds]
        outs = self._fn(*in_vals)
        out_nds = [NDArray(o) for o in outs]
        if autograd.is_recording():
            in_keys = [(a._uid, a._version) for a in in_nds]
            autograd._record_op(self._op, {}, in_keys, in_vals, out_nds)
        return out_nds[0] if len(out_nds) == 1 else out_nds

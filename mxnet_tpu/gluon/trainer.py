"""Gluon Trainer.

Reference: ``python/mxnet/gluon/trainer.py`` — ``Trainer`` (line 26) applies
an Optimizer to a ParameterDict; ``step`` (line 116) pushes grads / pulls
weights through the KVStore per parameter.

TPU note: with one (possibly mesh-replicated) jax.Array per parameter there
is nothing to aggregate on a single host — step() applies the updater
directly; a ``dist`` kvstore routes through push/pull for API parity.
"""
from __future__ import annotations

from .. import optimizer as opt
from .. import _fused
from ..model import _create_kvstore
from .parameter import ParameterDict, Parameter

__all__ = ["Trainer"]


class Trainer(object):
    """(reference: trainer.py:26)."""

    def __init__(self, params, optimizer, optimizer_params=None,
                 kvstore="device"):
        if isinstance(params, (dict, ParameterDict)):
            params = list(params.values())
        if not isinstance(params, (list, tuple)):
            raise ValueError(
                "First argument must be a list or dict of Parameters, "
                "got %s." % type(params))
        self._params = []
        for param in params:
            if not isinstance(param, Parameter):
                raise ValueError(
                    "First argument must be a list or dict of Parameters, "
                    "got list of %s." % type(param))
            if param.grad_req != "null":
                self._params.append(param)

        self._scale = 1.0
        optimizer_params = dict(optimizer_params or {})
        self._init_optimizer(optimizer, optimizer_params)
        self._kv_initialized = False
        self._kvstore_arg = kvstore
        self._kvstore = None
        self._update_on_kvstore = None

    def _init_optimizer(self, optimizer, optimizer_params):
        param_dict = {i: p for i, p in enumerate(self._params)}
        if isinstance(optimizer, opt.Optimizer):
            assert not optimizer_params, \
                "optimizer_params must be None if optimizer is an " \
                "Optimizer instance"
            self._optimizer = optimizer
        else:
            self._optimizer = opt.create(optimizer, **optimizer_params)
        self._optimizer.idx2name = {i: p.name
                                    for i, p in enumerate(self._params)}
        self._optimizer.lr_mult = {p.name: p.lr_mult for p in self._params}
        self._optimizer.wd_mult = {p.name: p.wd_mult for p in self._params}
        self._updaters = opt.get_updater(self._optimizer)
        self._fused_step = _fused.FusedUpdater(self._updaters)

    def _init_kvstore(self):
        arg_arrays = {p.name: p.data() for p in self._params}
        kvstore, update_on_kvstore = _create_kvstore(
            self._kvstore_arg, 1, arg_arrays)
        if kvstore:
            for i, param in enumerate(self._params):
                kvstore.init(i, param.data())
            if update_on_kvstore:
                kvstore.set_optimizer(self._optimizer)
        self._kvstore = kvstore
        self._update_on_kvstore = update_on_kvstore
        self._kv_initialized = True

    @property
    def learning_rate(self):
        return self._optimizer.lr

    def set_learning_rate(self, lr):
        """(reference: trainer.py set_learning_rate)."""
        self._optimizer.lr = lr

    def step(self, batch_size, ignore_stale_grad=False):
        """Apply one optimizer step with grads rescaled by 1/batch_size
        (reference: trainer.py:116)."""
        if not self._kv_initialized:
            self._init_kvstore()
        self._optimizer.rescale_grad = self._scale / batch_size

        live = [i for i, p in enumerate(self._params)
                if p.grad_req != "null"]
        if self._kvstore is not None:
            # all pushes before any pull: pushes are asynchronous
            # (reference ZPush), and the dist kvstore fuses every staged
            # key into one allreduce at the first pull — per-key RPC
            # round trips collapse into one per step
            for i in live:
                self._kvstore.push(i, self._params[i].grad())
            for i in live:
                param = self._params[i]
                if self._update_on_kvstore:
                    self._kvstore.pull(i, out=param.data())
                else:
                    self._kvstore.pull(i, out=param.grad())
                    self._updaters(i, param.grad(), param.data())
            return

        # fused fast path: every live (weight, grad, state) triple in ONE
        # structure-cached, donated jitted program — per-param fallback
        # when disabled, the updater was swapped for a custom one, or the
        # optimizer/structure can't fuse (e.g. SGLD's per-step noise)
        items = [(i, self._params[i].data(), self._params[i].grad())
                 for i in live]
        if self._fused_step.try_step(self._updaters, items):
            return
        for i, weight, grad in items:
            self._updaters(i, grad, weight)

    def save_states(self, fname):
        """(reference: trainer.py save_states)."""
        assert self._optimizer is not None
        if self._update_on_kvstore and self._kvstore is not None:
            self._kvstore.save_optimizer_states(fname)
        else:
            with open(fname, "wb") as fout:
                fout.write(self._updaters.get_states())

    def load_states(self, fname):
        """(reference: trainer.py load_states)."""
        if not self._kv_initialized:
            self._init_kvstore()
        if self._update_on_kvstore and self._kvstore is not None:
            self._kvstore.load_optimizer_states(fname)
        else:
            with open(fname, "rb") as fin:
                self._updaters.set_states(fin.read())

"""Gluon losses.

Reference: ``python/mxnet/gluon/loss.py`` — L1Loss, L2Loss,
SigmoidBinaryCrossEntropyLoss, SoftmaxCrossEntropyLoss, KLDivLoss (v0.11
set), each with sample_weight + batch_axis semantics.
"""
from __future__ import annotations

from .block import HybridBlock

__all__ = ["Loss", "L1Loss", "L2Loss", "SigmoidBinaryCrossEntropyLoss",
           "SigmoidBCELoss", "SoftmaxCrossEntropyLoss", "SoftmaxCELoss",
           "KLDivLoss"]


def _apply_weighting(F, loss, weight=None, sample_weight=None):
    """(reference: loss.py _apply_weighting)."""
    if sample_weight is not None:
        loss = F.broadcast_mul(loss, sample_weight)
    if weight is not None:
        assert isinstance(weight, (float, int))
        loss = loss * weight
    return loss


class Loss(HybridBlock):
    """Base loss (reference: loss.py Loss)."""

    def __init__(self, weight, batch_axis, **kwargs):
        super().__init__(**kwargs)
        self._weight = weight
        self._batch_axis = batch_axis

    def __repr__(self):
        return "%s(batch_axis=%s, w=%s)" % (
            self.__class__.__name__, self._batch_axis, self._weight)

    def hybrid_forward(self, F, x, *args, **kwargs):
        raise NotImplementedError


class L1Loss(Loss):
    """(reference: loss.py L1Loss)."""

    def __init__(self, weight=None, batch_axis=0, **kwargs):
        super().__init__(weight, batch_axis, **kwargs)

    def hybrid_forward(self, F, pred, label, sample_weight=None):
        label = label.reshape(pred.shape)
        loss = F.abs(pred - label)
        loss = _apply_weighting(F, loss, self._weight, sample_weight)
        return F.mean(loss, axis=self._batch_axis, exclude=True)


class L2Loss(Loss):
    """(reference: loss.py L2Loss — note the 1/2 factor)."""

    def __init__(self, weight=1.0, batch_axis=0, **kwargs):
        super().__init__(weight, batch_axis, **kwargs)

    def hybrid_forward(self, F, pred, label, sample_weight=None):
        label = label.reshape(pred.shape)
        loss = F.square(pred - label)
        loss = _apply_weighting(F, loss, self._weight / 2, sample_weight)
        return F.mean(loss, axis=self._batch_axis, exclude=True)


class SigmoidBinaryCrossEntropyLoss(Loss):
    """(reference: loss.py SigmoidBinaryCrossEntropyLoss — the
    from_sigmoid=False path uses the numerically-stable log-sum-exp form)."""

    def __init__(self, from_sigmoid=False, weight=None, batch_axis=0,
                 **kwargs):
        super().__init__(weight, batch_axis, **kwargs)
        self._from_sigmoid = from_sigmoid

    def hybrid_forward(self, F, pred, label, sample_weight=None):
        label = label.reshape(pred.shape)
        if not self._from_sigmoid:
            max_val = F.maximum(-pred, F.zeros_like(pred))
            loss = pred - pred * label + max_val + \
                F.log(F.exp(-max_val) + F.exp(-pred - max_val))
        else:
            eps = 1e-12
            loss = -(F.log(pred + eps) * label +
                     F.log(1. - pred + eps) * (1. - label))
        loss = _apply_weighting(F, loss, self._weight, sample_weight)
        return F.mean(loss, axis=self._batch_axis, exclude=True)


SigmoidBCELoss = SigmoidBinaryCrossEntropyLoss


class SoftmaxCrossEntropyLoss(Loss):
    """(reference: loss.py SoftmaxCrossEntropyLoss)."""

    def __init__(self, axis=-1, sparse_label=True, from_logits=False,
                 weight=None, batch_axis=0, **kwargs):
        super().__init__(weight, batch_axis, **kwargs)
        self._axis = axis
        self._sparse_label = sparse_label
        self._from_logits = from_logits

    def hybrid_forward(self, F, pred, label, sample_weight=None):
        if not self._from_logits:
            pred = F.log_softmax(pred, axis=self._axis)
        if self._sparse_label:
            loss = -F.pick(pred, label, axis=self._axis, keepdims=True)
        else:
            loss = -F.sum(pred * label, axis=self._axis, keepdims=True)
        loss = _apply_weighting(F, loss, self._weight, sample_weight)
        return F.mean(loss, axis=self._batch_axis, exclude=True)


SoftmaxCELoss = SoftmaxCrossEntropyLoss


class KLDivLoss(Loss):
    """(reference: loss.py KLDivLoss)."""

    def __init__(self, from_logits=True, weight=None, batch_axis=0, **kwargs):
        super().__init__(weight, batch_axis, **kwargs)
        self._from_logits = from_logits

    def hybrid_forward(self, F, pred, label, sample_weight=None):
        if not self._from_logits:
            pred = F.log_softmax(pred)
        loss = label * (F.log(label + 1e-12) - pred)
        loss = _apply_weighting(F, loss, self._weight, sample_weight)
        return F.mean(loss, axis=self._batch_axis, exclude=True)

"""Gluon utilities.

Reference: ``python/mxnet/gluon/utils.py`` — split_data / split_and_load
(manual batch slicing for multi-device) and clip_global_norm.

TPU note: split_and_load can instead shard one array over a mesh when given
several contexts — one logical array, XLA moves the shards.
"""
from __future__ import annotations

import math
from typing import List

import numpy as np

from .. import ndarray as nd
from ..context import Context

__all__ = ["split_data", "split_and_load", "clip_global_norm"]


def split_data(data, num_slice, batch_axis=0, even_split=True):
    """Split along batch_axis into num_slice slices (reference:
    utils.py split_data)."""
    size = data.shape[batch_axis]
    if size < num_slice:
        raise ValueError(
            "Too many slices for data with shape %s. Arguments are "
            "num_slice=%d and batch_axis=%d." % (data.shape, num_slice,
                                                 batch_axis))
    if even_split and size % num_slice != 0:
        raise ValueError(
            "data with shape %s cannot be evenly split into %d slices "
            "along axis %d. Use a batch size that's multiple of %d or set "
            "even_split=False to allow uneven partitioning of data."
            % (data.shape, num_slice, batch_axis, num_slice))

    step = size // num_slice
    slices = []
    for i in range(num_slice):
        begin = i * step
        end = (i + 1) * step if i < num_slice - 1 else size
        if batch_axis == 0:
            slices.append(data[begin:end])
        else:
            slices.append(nd.slice_axis(data, axis=batch_axis,
                                        begin=begin, end=end))
    return slices


def split_and_load(data, ctx_list, batch_axis=0, even_split=True):
    """Load a batch for the given contexts (reference: utils.py
    split_and_load returns one slice per context).

    TPU form: with several contexts the batch becomes ONE array sharded
    over a data-parallel mesh — returned as a single-element list so the
    reference's ``for x in split_and_load(...)`` loop runs once and GSPMD
    executes it on every device. Parameters initialized with the same
    context list are mesh-replicated (gluon.Parameter._finish_init), so
    XLA inserts the gradient psum the reference's kvstore did manually.
    """
    if not isinstance(data, nd.NDArray):
        data = nd.array(data, ctx=ctx_list[0])
    if len(ctx_list) == 1:
        return [data.as_in_context(ctx_list[0])]
    if data.shape[batch_axis] % len(ctx_list) != 0:
        # a GSPMD-sharded array cannot hold uneven per-device slices (the
        # reference's even_split=False form) — pad the batch instead, e.g.
        # DataIter(last_batch_handle="pad")
        raise ValueError(
            "data with shape %s cannot be sharded over %d contexts along "
            "axis %d: mesh data parallelism needs a divisible batch (pad "
            "the last batch, e.g. last_batch_handle='pad')."
            % (data.shape, len(ctx_list), batch_axis))
    from ..parallel.mesh import data_parallel_mesh, shard_batch
    mesh = data_parallel_mesh(ctx_list)
    return [nd.NDArray(shard_batch(mesh, data.data, batch_dim=batch_axis))]


def clip_global_norm(arrays, max_norm):
    """Rescale arrays so the sum of their 2-norms is <= max_norm
    (reference: utils.py clip_global_norm)."""
    assert len(arrays) > 0
    total_norm = 0.0
    for arr in arrays:
        norm = float(nd.sum(arr * arr).asscalar())
        total_norm += norm
    total_norm = math.sqrt(total_norm)
    scale = max_norm / (total_norm + 1e-8)
    if scale < 1.0:
        for arr in arrays:
            arr *= scale
    return total_norm

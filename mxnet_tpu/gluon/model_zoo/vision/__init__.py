"""Vision model zoo.

Reference: ``python/mxnet/gluon/model_zoo/vision/`` — alexnet, densenet,
inception-v3, resnet v1/v2 (18-152), squeezenet, vgg 11-19 (+bn), mobilenet.

``pretrained`` semantics: ``True`` (the reference's model-store download,
model_store.py:1-118) is unavailable in this no-egress environment and
raises; a **path/URI string** loads the weights from a local or
``mx.filesystem`` checkpoint instead — either this zoo's own
``save_params`` output or a reference-era binary ``.params`` blob
(``arg:``/``aux:`` module-checkpoint prefixes are stripped; the binary
layout parses via ndarray/legacy_format.py).
"""
from .alexnet import *
from .densenet import *
from .inception import *
from .resnet import *
from .squeezenet import *
from .vgg import *
from .mobilenet import *

_models = {}


def _register_models():
    import importlib
    mods = [importlib.import_module(__name__ + "." + m)
            for m in ("alexnet", "densenet", "inception", "resnet",
                      "squeezenet", "vgg", "mobilenet")]
    for mod in mods:
        for name in mod.__all__:
            fn = getattr(mod, name)
            if callable(fn) and not name[0].isupper() and \
                    not name.startswith("get_"):
                _models[name] = fn


_register_models()


def _suffix_map(names):
    """Map name-scope-stripped suffixes to full names: cut the shared
    prefix at its last underscore, so 'squeezenet0_conv2d0_weight' and
    'squeezenet1_conv2d0_weight' meet at 'conv2d0_weight' (v0.11 gluon
    saves full prefixed names; instance counters differ across runs)."""
    import os.path as _osp
    names = list(names)
    pref = _osp.commonprefix(names)
    cut = pref.rfind("_") + 1
    return {n[cut:]: n for n in names}


def _load_pretrained(net, path):
    from .... import ndarray as nd
    data = nd.load(path)
    if isinstance(data, list):
        raise ValueError(
            "pretrained file %r holds an unnamed array list; a named "
            "parameter dict is required" % path)
    from ....ndarray.legacy_format import strip_arg_aux
    data = strip_arg_aux(data)
    params = net.collect_params()
    by_suffix = None
    for name in params.keys():
        src = name
        if src not in data:
            if by_suffix is None:
                by_suffix = _suffix_map(data.keys())
                net_suffix = _suffix_map(params.keys())
            suf = next((s for s, n in net_suffix.items() if n == name),
                       None)
            src = by_suffix.get(suf)
            if src is None:
                raise ValueError(
                    "Parameter %s missing in pretrained file %r "
                    "(has e.g. %s)" % (name, path, sorted(data)[:3]))
        params[name]._load_init(data[src], None)
    return net


def get_model(name, pretrained=False, **kwargs):
    """Create a model by name (reference: model_zoo/__init__.py
    get_model). ``pretrained`` may be a checkpoint path/URI — see the
    module docstring."""
    name = name.lower()
    if name not in _models:
        raise ValueError(
            "Model %s is not supported. Available: %s"
            % (name, sorted(_models.keys())))
    net = _models[name](**kwargs)
    if pretrained:
        if pretrained is True:
            raise ValueError(
                "pretrained=True needs the reference's download store, "
                "which this environment cannot reach; pass a checkpoint "
                "path (get_model(name, pretrained='/path/model.params'))")
        _load_pretrained(net, pretrained)
    return net


__all__ = ["get_model"] + sorted(_models.keys())

"""Vision model zoo.

Reference: ``python/mxnet/gluon/model_zoo/vision/`` — alexnet, densenet,
inception-v3, resnet v1/v2 (18-152), squeezenet, vgg 11-19 (+bn), mobilenet.

``pretrained`` semantics: ``True`` (the reference's model-store download,
model_store.py:1-118) is unavailable in this no-egress environment and
raises; a **path/URI string** loads the weights from a local or
``mx.filesystem`` checkpoint instead — either this zoo's own
``save_params`` output or a reference-era binary ``.params`` blob
(``arg:``/``aux:`` module-checkpoint prefixes are stripped; the binary
layout parses via ndarray/legacy_format.py).
"""
from .alexnet import *
from .densenet import *
from .inception import *
from .resnet import *
from .squeezenet import *
from .vgg import *
from .mobilenet import *

_models = {}


def _register_models():
    import importlib
    mods = [importlib.import_module(__name__ + "." + m)
            for m in ("alexnet", "densenet", "inception", "resnet",
                      "squeezenet", "vgg", "mobilenet")]
    for mod in mods:
        for name in mod.__all__:
            fn = getattr(mod, name)
            if callable(fn) and not name[0].isupper() and \
                    not name.startswith("get_"):
                _models[name] = fn


_register_models()


def get_model(name, pretrained=False, **kwargs):
    """Create a model by name (reference: model_zoo/__init__.py
    get_model). ``pretrained`` may be a checkpoint path/URI — see the
    module docstring."""
    name = name.lower()
    if name not in _models:
        raise ValueError(
            "Model %s is not supported. Available: %s"
            % (name, sorted(_models.keys())))
    # factories handle pretrained themselves (vision/_pretrained.py)
    return _models[name](pretrained=pretrained, **kwargs)


__all__ = ["get_model"] + sorted(_models.keys())

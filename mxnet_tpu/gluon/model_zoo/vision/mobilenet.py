"""Gluon MobileNet v1 (capability twin of the reference's
example/image-classification/symbols/mobilenet.py, in gluon form —
depthwise-separable convs map to grouped XLA convolutions)."""
from ._pretrained import finish_pretrained
from ...block import HybridBlock
from ... import nn

__all__ = ["MobileNet", "mobilenet1_0", "mobilenet0_75", "mobilenet0_5",
           "mobilenet0_25"]


def _add_conv(out, channels, kernel=1, stride=1, pad=0, num_group=1):
    out.add(nn.Conv2D(channels, kernel, stride, pad, groups=num_group,
                      use_bias=False))
    out.add(nn.BatchNorm())
    out.add(nn.Activation("relu"))


def _add_conv_dw(out, dw_channels, channels, stride):
    _add_conv(out, dw_channels, kernel=3, stride=stride, pad=1,
              num_group=dw_channels)
    _add_conv(out, channels)


class MobileNet(HybridBlock):
    """(reference capability: symbols/mobilenet.py get_symbol)."""

    def __init__(self, multiplier=1.0, classes=1000, **kwargs):
        super().__init__(**kwargs)
        with self.name_scope():
            self.features = nn.HybridSequential(prefix="")
            with self.features.name_scope():
                _add_conv(self.features, int(32 * multiplier), kernel=3,
                          stride=2, pad=1)
                dw_channels = [int(x * multiplier) for x in
                               [32, 64] + [128] * 2 + [256] * 2 +
                               [512] * 6 + [1024]]
                channels = [int(x * multiplier) for x in
                            [64] + [128] * 2 + [256] * 2 + [512] * 6 +
                            [1024] * 2]
                strides = [1, 2] * 3 + [1] * 5 + [2, 1]
                for dwc, c, s in zip(dw_channels, channels, strides):
                    _add_conv_dw(self.features, dwc, c, s)
                self.features.add(nn.GlobalAvgPool2D())
                self.features.add(nn.Flatten())
            self.output = nn.Dense(classes)

    def hybrid_forward(self, F, x):
        x = self.features(x)
        return self.output(x)


def mobilenet1_0(pretrained=False, **kwargs):
    return finish_pretrained(MobileNet(1.0, **kwargs), pretrained)


def mobilenet0_75(pretrained=False, **kwargs):
    return finish_pretrained(MobileNet(0.75, **kwargs), pretrained)


def mobilenet0_5(pretrained=False, **kwargs):
    return finish_pretrained(MobileNet(0.5, **kwargs), pretrained)


def mobilenet0_25(pretrained=False, **kwargs):
    return finish_pretrained(MobileNet(0.25, **kwargs), pretrained)

"""Shared pretrained-checkpoint loading for the vision zoo factories.

``pretrained`` semantics (reference: model_zoo downloads from its model
store, model_store.py:1-118): ``True`` is unavailable here (no egress)
and raises; a path/URI string loads local weights — this zoo's own
``save_params`` output or a reference-era binary ``.params`` blob
(``arg:``/``aux:`` module prefixes stripped; name-scope instance
counters matched by common-prefix suffix).
"""
from __future__ import annotations

__all__ = ["finish_pretrained"]


def _suffix_map(names):
    """Map name-scope-stripped suffixes to full names: cut the shared
    prefix at its last underscore, so 'squeezenet0_conv2d0_weight' and
    'squeezenet1_conv2d0_weight' meet at 'conv2d0_weight' (v0.11 gluon
    saves full prefixed names; instance counters differ across runs)."""
    import os.path as _osp
    names = list(names)
    pref = _osp.commonprefix(names)
    cut = pref.rfind("_") + 1
    # suffixes cannot collide within one call: every name shares its
    # first `cut` characters (cut <= len(commonprefix)), so distinct
    # names keep distinct suffixes. Cross-map ambiguity (net vs
    # checkpoint cut at different depths) surfaces as a shape mismatch
    # in Parameter._load_init; a shape-compatible wrong pairing is not
    # detectable by name — load by exact names (net.load_params) when
    # the checkpoint's scoping is untrusted.
    return {n[cut:]: n for n in names}


def finish_pretrained(net, pretrained):
    """Apply the ``pretrained`` argument to a freshly built net."""
    if not pretrained:
        return net
    if pretrained is True:
        raise ValueError(
            "pretrained=True needs the reference's download store, which "
            "this environment cannot reach; pass a checkpoint path "
            "(pretrained='/path/model.params')")
    from .... import ndarray as nd
    from ....ndarray.legacy_format import strip_arg_aux
    data = nd.load(pretrained)
    if isinstance(data, list):
        raise ValueError(
            "pretrained file %r holds an unnamed array list; a named "
            "parameter dict is required" % pretrained)
    data = strip_arg_aux(data)
    params = net.collect_params()
    by_suffix = net_suffix = None
    for name in params.keys():
        src = name
        if src not in data:
            if by_suffix is None:
                by_suffix = _suffix_map(data.keys())
                net_suffix = _suffix_map(params.keys())
            suf = next((s for s, n in net_suffix.items() if n == name),
                       None)
            src = by_suffix.get(suf)
            if src is None:
                raise ValueError(
                    "Parameter %s missing in pretrained file %r "
                    "(has e.g. %s)" % (name, pretrained,
                                       sorted(data)[:3]))
        params[name]._load_init(data[src], None)
    return net

"""Gluon AlexNet (reference: model_zoo/vision/alexnet.py)."""
from ._pretrained import finish_pretrained
from ...block import HybridBlock
from ... import nn

__all__ = ["AlexNet", "alexnet"]


class AlexNet(HybridBlock):
    """(reference: alexnet.py AlexNet)."""

    def __init__(self, classes=1000, **kwargs):
        super().__init__(**kwargs)
        with self.name_scope():
            self.features = nn.HybridSequential(prefix="")
            with self.features.name_scope():
                self.features.add(nn.Conv2D(64, kernel_size=11, strides=4,
                                            padding=2, activation="relu"))
                self.features.add(nn.MaxPool2D(pool_size=3, strides=2))
                self.features.add(nn.Conv2D(192, kernel_size=5, padding=2,
                                            activation="relu"))
                self.features.add(nn.MaxPool2D(pool_size=3, strides=2))
                self.features.add(nn.Conv2D(384, kernel_size=3, padding=1,
                                            activation="relu"))
                self.features.add(nn.Conv2D(256, kernel_size=3, padding=1,
                                            activation="relu"))
                self.features.add(nn.Conv2D(256, kernel_size=3, padding=1,
                                            activation="relu"))
                self.features.add(nn.MaxPool2D(pool_size=3, strides=2))
                self.features.add(nn.Flatten())
            self.classifier = nn.HybridSequential(prefix="")
            with self.classifier.name_scope():
                self.classifier.add(nn.Dense(4096, activation="relu"))
                self.classifier.add(nn.Dropout(0.5))
                self.classifier.add(nn.Dense(4096, activation="relu"))
                self.classifier.add(nn.Dropout(0.5))
                self.classifier.add(nn.Dense(classes))

    def hybrid_forward(self, F, x):
        x = self.features(x)
        return self.classifier(x)


def alexnet(pretrained=False, **kwargs):
    """(reference: alexnet.py alexnet)."""
    return finish_pretrained(AlexNet(**kwargs), pretrained)

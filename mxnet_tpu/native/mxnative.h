// mxnet_tpu native data path — C ABI.
//
// TPU-native equivalent of the reference's C++ data layer
// (dmlc-core RecordIO codec + src/io/iter_image_recordio_2.cc fused
// decode/augment/batch thread pool).  The compute path is JAX/XLA; this
// library owns the host-side IO hot loop: record container codec, JPEG/PNG
// decode, augmentation, and a threaded prefetch pipeline that assembles
// ready float32 NCHW batches off the Python thread (no GIL).
//
// Exposed over a flat C ABI (ctypes binding in mxnet_tpu/native/__init__.py)
// the way the reference exposes its core over include/mxnet/c_api.h.
#ifndef MXNATIVE_H_
#define MXNATIVE_H_

#include <stdint.h>

#ifdef __cplusplus
extern "C" {
#endif

// ---------------------------------------------------------------- recordio
// dmlc recordio framing: uint32 magic 0xced7230a, uint32 lrecord
// (upper 3 bits continuation flag, lower 29 length), payload padded to 4.

// Open a record file for reading; mmaps it and indexes logical records.
// Returns NULL on failure.
void* mxrio_open(const char* path);
int64_t mxrio_count(void* handle);
// Byte offset of logical record i (for index sidecars).
int64_t mxrio_offset(void* handle, int64_t i);
// Pointer/length of record i's payload. For single-part records this points
// into the mmap (zero copy); multi-part records are assembled into a
// thread-local scratch buffer (valid until the calling thread's next
// mxrio_get). Safe to call concurrently from multiple threads on one handle.
int64_t mxrio_get(void* handle, int64_t i, const uint8_t** out);
// Logical record index at byte offset `off` (-1 if not a record boundary).
int64_t mxrio_index_of(void* handle, int64_t off);
void mxrio_close(void* handle);

void* mxrio_writer_open(const char* path);
// Returns the byte offset the record was written at, or -1 on error.
int64_t mxrio_writer_write(void* handle, const uint8_t* buf, int64_t len);
int mxrio_writer_close(void* handle);

// ---------------------------------------------------------------- image
// Decode JPEG/PNG (format sniffed from magic bytes) into an RGB/gray HWC
// uint8 buffer allocated by the library.  Returns 0 on success.
// channels: 0 = keep source, 1 = force gray, 3 = force RGB.
int mximg_decode(const uint8_t* buf, int64_t len, int channels,
                 uint8_t** out, int* h, int* w, int* c);
void mximg_free(uint8_t* buf);
// Bilinear resize HWC uint8.
void mximg_resize(const uint8_t* src, int sh, int sw, int c,
                  uint8_t* dst, int dh, int dw);

// ---------------------------------------------------------------- pipeline
// Fused decode → augment → normalize → batch pipeline with worker threads
// and a bounded ready-batch queue (reference: iter_image_recordio_2.cc
// thread pool + iter_prefetcher.h double buffering).
typedef struct {
  int batch_size;
  int target_h, target_w, target_c;  // output CHW shape
  int label_width;
  int resize;          // short-side resize before crop; <=0 disables
  int rand_crop;       // else center crop
  int rand_mirror;
  float mean[3];
  float std_[3];
  float scale;
  uint64_t seed;
  int num_threads;
  int queue_depth;     // max ready batches buffered
  int round_batch;     // pad last batch by repeating the final sample
} MXPipeConfig;

// rec: handle from mxrio_open (borrowed; caller keeps it open).
void* mxpipe_create(void* rec, const MXPipeConfig* cfg);
// Begin an epoch visiting records in `order` (indices into the rec handle).
void mxpipe_start_epoch(void* handle, const int64_t* order, int64_t n);
// Copy the next ready batch into caller buffers.
//   data: batch*c*h*w float32   label: batch*label_width float32
// Returns 0 ok, 1 epoch done, -1 error (message via mxpipe_error).
int mxpipe_next(void* handle, float* data, float* label, int* pad);
const char* mxpipe_error(void* handle);
void mxpipe_close(void* handle);

#ifdef __cplusplus
}
#endif
#endif  // MXNATIVE_H_

// RecordIO codec: mmap'd indexed reader + append writer.
//
// Framing per dmlc-core recordio (SURVEY.md §2.11, reference
// docs/architecture/note_data_loading.md): each part is
//   uint32 magic 0xced7230a
//   uint32 lrec   — upper 3 bits cflag (0 whole, 1 begin, 2 middle, 3 end),
//                   lower 29 bits payload length
//   payload, zero-padded to 4-byte alignment
// A logical record is one cflag=0 part or a 1,2*,3 chain.
#include "mxnative.h"

#include <fcntl.h>
#include <sys/mman.h>
#include <sys/stat.h>
#include <unistd.h>

#include <cstdio>
#include <cstring>
#include <string>
#include <vector>

namespace {

constexpr uint32_t kMagic = 0xced7230a;

struct Part {
  int64_t payload_off;
  int64_t payload_len;
};

struct Record {
  int64_t file_off;    // offset of the first part's magic (index sidecar key)
  int32_t first_part;  // into parts vector
  int32_t n_parts;
  int64_t total_len;
};

struct Reader {
  int fd = -1;
  const uint8_t* base = nullptr;
  int64_t size = 0;
  std::vector<Part> parts;
  std::vector<Record> records;
};

// Multi-part assembly buffer. Thread-local (not per-handle) because the
// pipeline's worker threads call mxrio_get concurrently on one shared
// Reader; the returned pointer stays valid until the same thread's next
// mxrio_get.
thread_local std::vector<uint8_t> tls_scratch;

struct Writer {
  FILE* f = nullptr;
  int64_t pos = 0;
};

bool IndexFile(Reader* r) {
  int64_t off = 0;
  int32_t open_first = -1;  // first part index of an in-progress chain
  int64_t open_off = 0, open_len = 0;
  while (off + 8 <= r->size) {
    uint32_t magic, lrec;
    std::memcpy(&magic, r->base + off, 4);
    std::memcpy(&lrec, r->base + off + 4, 4);
    if (magic != kMagic) return false;
    uint32_t cflag = lrec >> 29;
    int64_t len = lrec & ((1u << 29) - 1);
    if (off + 8 + len > r->size) return false;
    r->parts.push_back({off + 8, len});
    int32_t pi = static_cast<int32_t>(r->parts.size()) - 1;
    if (cflag == 0) {
      r->records.push_back({off, pi, 1, len});
    } else if (cflag == 1) {
      open_first = pi;
      open_off = off;
      open_len = len;
    } else {  // 2 middle, 3 end
      if (open_first < 0) return false;
      open_len += len;
      if (cflag == 3) {
        r->records.push_back(
            {open_off, open_first, pi - open_first + 1, open_len});
        open_first = -1;
      }
    }
    off += 8 + len + ((-len) & 3);
  }
  return open_first < 0;
}

}  // namespace

extern "C" {

void* mxrio_open(const char* path) {
  int fd = ::open(path, O_RDONLY);
  if (fd < 0) return nullptr;
  struct stat st;
  if (fstat(fd, &st) != 0) {
    ::close(fd);
    return nullptr;
  }
  Reader* r = new Reader();
  r->fd = fd;
  r->size = st.st_size;
  if (r->size > 0) {
    void* m = mmap(nullptr, r->size, PROT_READ, MAP_PRIVATE, fd, 0);
    if (m == MAP_FAILED) {
      ::close(fd);
      delete r;
      return nullptr;
    }
    r->base = static_cast<const uint8_t*>(m);
  }
  if (!IndexFile(r)) {
    mxrio_close(r);
    return nullptr;
  }
  return r;
}

int64_t mxrio_count(void* handle) {
  return static_cast<Reader*>(handle)->records.size();
}

int64_t mxrio_offset(void* handle, int64_t i) {
  Reader* r = static_cast<Reader*>(handle);
  if (i < 0 || i >= static_cast<int64_t>(r->records.size())) return -1;
  return r->records[i].file_off;
}

int64_t mxrio_index_of(void* handle, int64_t off) {
  Reader* r = static_cast<Reader*>(handle);
  int64_t lo = 0, hi = static_cast<int64_t>(r->records.size()) - 1;
  while (lo <= hi) {
    int64_t mid = (lo + hi) / 2;
    int64_t o = r->records[mid].file_off;
    if (o == off) return mid;
    if (o < off) lo = mid + 1; else hi = mid - 1;
  }
  return -1;
}

int64_t mxrio_get(void* handle, int64_t i, const uint8_t** out) {
  Reader* r = static_cast<Reader*>(handle);
  if (i < 0 || i >= static_cast<int64_t>(r->records.size())) return -1;
  const Record& rec = r->records[i];
  if (rec.n_parts == 1) {
    const Part& p = r->parts[rec.first_part];
    *out = r->base + p.payload_off;
    return p.payload_len;
  }
  tls_scratch.resize(rec.total_len);
  int64_t pos = 0;
  for (int32_t k = 0; k < rec.n_parts; ++k) {
    const Part& p = r->parts[rec.first_part + k];
    std::memcpy(tls_scratch.data() + pos, r->base + p.payload_off,
                p.payload_len);
    pos += p.payload_len;
  }
  *out = tls_scratch.data();
  return rec.total_len;
}

void mxrio_close(void* handle) {
  Reader* r = static_cast<Reader*>(handle);
  if (r->base) munmap(const_cast<uint8_t*>(r->base), r->size);
  if (r->fd >= 0) ::close(r->fd);
  delete r;
}

void* mxrio_writer_open(const char* path) {
  FILE* f = std::fopen(path, "wb");
  if (!f) return nullptr;
  Writer* w = new Writer();
  w->f = f;
  return w;
}

int64_t mxrio_writer_write(void* handle, const uint8_t* buf, int64_t len) {
  Writer* w = static_cast<Writer*>(handle);
  if (len < 0 || len >= (int64_t{1} << 29)) return -1;  // lrec length field
  int64_t at = w->pos;                                  // holds 29 bits only
  uint32_t hdr[2] = {kMagic,
                     static_cast<uint32_t>(len) & ((1u << 29) - 1)};
  if (std::fwrite(hdr, 4, 2, w->f) != 2) return -1;
  if (len && std::fwrite(buf, 1, len, w->f) != static_cast<size_t>(len))
    return -1;
  static const uint8_t zeros[4] = {0, 0, 0, 0};
  int64_t pad = (-len) & 3;
  if (pad && std::fwrite(zeros, 1, pad, w->f) != static_cast<size_t>(pad))
    return -1;
  w->pos += 8 + len + pad;
  return at;
}

int mxrio_writer_close(void* handle) {
  Writer* w = static_cast<Writer*>(handle);
  int rc = std::fclose(w->f);
  delete w;
  return rc;
}

}  // extern "C"

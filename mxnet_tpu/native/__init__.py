"""Native data-path library: build + ctypes binding.

The C++ sources in this directory (recordio.cc, image.cc, pipeline.cc)
implement the host-side IO hot loop — the TPU-native counterpart of the
reference's C++ data layer (dmlc-core RecordIO, src/io/iter_image_recordio_2.cc).
They are compiled once into ``libmxnative.so`` next to the sources (g++,
linked against the system libjpeg/libpng) and loaded via ctypes; everything
degrades gracefully to the pure-Python/cv2 path when the toolchain or the
image libraries are unavailable (``lib() is None``).

Set ``MXNET_USE_NATIVE_IO=0`` to force the Python path (config.py knob).
"""
from __future__ import annotations

import ctypes
import os
import subprocess
import threading

_DIR = os.path.dirname(os.path.abspath(__file__))
_SO = os.path.join(_DIR, "libmxnative.so")
_SOURCES = ["recordio.cc", "image.cc", "pipeline.cc"]
_DEPS = _SOURCES + ["mxnative.h"]  # staleness check includes the header

_lock = threading.Lock()
_lib = None
_tried = False


class MXPipeConfig(ctypes.Structure):
    _fields_ = [
        ("batch_size", ctypes.c_int),
        ("target_h", ctypes.c_int),
        ("target_w", ctypes.c_int),
        ("target_c", ctypes.c_int),
        ("label_width", ctypes.c_int),
        ("resize", ctypes.c_int),
        ("rand_crop", ctypes.c_int),
        ("rand_mirror", ctypes.c_int),
        ("mean", ctypes.c_float * 3),
        ("std_", ctypes.c_float * 3),
        ("scale", ctypes.c_float),
        ("seed", ctypes.c_uint64),
        ("num_threads", ctypes.c_int),
        ("queue_depth", ctypes.c_int),
        ("round_batch", ctypes.c_int),
    ]


def _build() -> bool:
    """Compile libmxnative.so if missing or older than sources/header.

    Compiles to a process-unique temp path and renames into place so
    concurrent importers (multi-process data parallel, pytest workers)
    never observe a half-written .so.
    """
    deps = [os.path.join(_DIR, s) for s in _DEPS]
    if os.path.exists(_SO) and all(
            os.path.getmtime(_SO) >= os.path.getmtime(s) for s in deps):
        return True
    tmp = "%s.%d.tmp" % (_SO, os.getpid())
    srcs = [os.path.join(_DIR, s) for s in _SOURCES]
    cmd = ["g++", "-std=c++17", "-O2", "-fPIC", "-shared", "-pthread",
           "-o", tmp] + srcs + ["-ljpeg", "-lpng"]
    try:
        subprocess.run(cmd, check=True, capture_output=True, timeout=180)
        os.replace(tmp, _SO)   # atomic on POSIX
        return True
    except Exception:
        try:
            os.remove(tmp)
        except OSError:
            pass
        return False


def _bind(lib: ctypes.CDLL) -> ctypes.CDLL:
    u8p = ctypes.POINTER(ctypes.c_uint8)
    lib.mxrio_open.restype = ctypes.c_void_p
    lib.mxrio_open.argtypes = [ctypes.c_char_p]
    lib.mxrio_count.restype = ctypes.c_int64
    lib.mxrio_count.argtypes = [ctypes.c_void_p]
    lib.mxrio_offset.restype = ctypes.c_int64
    lib.mxrio_offset.argtypes = [ctypes.c_void_p, ctypes.c_int64]
    lib.mxrio_index_of.restype = ctypes.c_int64
    lib.mxrio_index_of.argtypes = [ctypes.c_void_p, ctypes.c_int64]
    lib.mxrio_get.restype = ctypes.c_int64
    lib.mxrio_get.argtypes = [ctypes.c_void_p, ctypes.c_int64,
                              ctypes.POINTER(u8p)]
    lib.mxrio_close.argtypes = [ctypes.c_void_p]
    lib.mxrio_writer_open.restype = ctypes.c_void_p
    lib.mxrio_writer_open.argtypes = [ctypes.c_char_p]
    lib.mxrio_writer_write.restype = ctypes.c_int64
    lib.mxrio_writer_write.argtypes = [ctypes.c_void_p, ctypes.c_char_p,
                                       ctypes.c_int64]
    lib.mxrio_writer_close.restype = ctypes.c_int
    lib.mxrio_writer_close.argtypes = [ctypes.c_void_p]

    lib.mximg_decode.restype = ctypes.c_int
    lib.mximg_decode.argtypes = [ctypes.c_char_p, ctypes.c_int64,
                                 ctypes.c_int, ctypes.POINTER(u8p),
                                 ctypes.POINTER(ctypes.c_int),
                                 ctypes.POINTER(ctypes.c_int),
                                 ctypes.POINTER(ctypes.c_int)]
    lib.mximg_free.argtypes = [u8p]
    lib.mximg_resize.argtypes = [u8p, ctypes.c_int, ctypes.c_int,
                                 ctypes.c_int, u8p, ctypes.c_int,
                                 ctypes.c_int]

    lib.mxpipe_create.restype = ctypes.c_void_p
    lib.mxpipe_create.argtypes = [ctypes.c_void_p,
                                  ctypes.POINTER(MXPipeConfig)]
    lib.mxpipe_start_epoch.argtypes = [ctypes.c_void_p,
                                       ctypes.POINTER(ctypes.c_int64),
                                       ctypes.c_int64]
    lib.mxpipe_next.restype = ctypes.c_int
    lib.mxpipe_next.argtypes = [ctypes.c_void_p,
                                ctypes.POINTER(ctypes.c_float),
                                ctypes.POINTER(ctypes.c_float),
                                ctypes.POINTER(ctypes.c_int)]
    lib.mxpipe_error.restype = ctypes.c_char_p
    lib.mxpipe_error.argtypes = [ctypes.c_void_p]
    lib.mxpipe_close.argtypes = [ctypes.c_void_p]
    return lib


def lib():
    """The loaded native library, or None when unavailable/disabled."""
    global _lib, _tried
    if _tried:
        return _lib
    with _lock:
        if _tried:
            return _lib
        from .. import config as _config
        enabled = True
        try:
            enabled = bool(int(_config.get("MXNET_USE_NATIVE_IO")))
        except Exception:
            pass
        if enabled and _build():
            try:
                _lib = _bind(ctypes.CDLL(_SO))
            except OSError:
                _lib = None
        _tried = True
        return _lib

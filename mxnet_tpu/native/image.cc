// JPEG/PNG decode + bilinear resize for the native data path.
//
// Replaces the reference's OpenCV dependency in the IO hot loop
// (src/io/image_io.cc imdecode, image_aug_default.cc resize) with direct
// libjpeg/libpng decode into HWC uint8.
#include "mxnative.h"

#include <csetjmp>
#include <cstdio>  // jpeglib.h needs FILE declared first
#include <cstdlib>
#include <cstring>
#include <vector>

#include <jpeglib.h>
#include <png.h>

namespace {

// ---------------------------------------------------------------- jpeg
struct JerrMgr {
  jpeg_error_mgr pub;
  jmp_buf jb;
};

void JerrExit(j_common_ptr cinfo) {
  JerrMgr* e = reinterpret_cast<JerrMgr*>(cinfo->err);
  longjmp(e->jb, 1);
}

int DecodeJpeg(const uint8_t* buf, int64_t len, int channels, uint8_t** out,
               int* h, int* w, int* c) {
  jpeg_decompress_struct cinfo;
  JerrMgr jerr;
  cinfo.err = jpeg_std_error(&jerr.pub);
  jerr.pub.error_exit = JerrExit;
  // volatile: written between setjmp and longjmp, read in the handler
  uint8_t* volatile data = nullptr;
  if (setjmp(jerr.jb)) {
    jpeg_destroy_decompress(&cinfo);
    std::free(data);
    return -1;
  }
  jpeg_create_decompress(&cinfo);
  jpeg_mem_src(&cinfo, buf, len);
  jpeg_read_header(&cinfo, TRUE);
  // CMYK/YCCK (Adobe) can't be converted to RGB by libjpeg itself;
  // decode as CMYK and convert below (the cv2 path handles these too).
  bool cmyk = cinfo.jpeg_color_space == JCS_CMYK ||
              cinfo.jpeg_color_space == JCS_YCCK;
  if (cmyk) cinfo.out_color_space = JCS_CMYK;
  else if (channels == 1) cinfo.out_color_space = JCS_GRAYSCALE;
  else if (channels == 3) cinfo.out_color_space = JCS_RGB;
  jpeg_start_decompress(&cinfo);
  int W = cinfo.output_width, H = cinfo.output_height,
      C = cinfo.output_components;
  data = static_cast<uint8_t*>(std::malloc((size_t)W * H * C));
  if (!data) longjmp(jerr.jb, 1);
  while (cinfo.output_scanline < cinfo.output_height) {
    uint8_t* row = data + (size_t)cinfo.output_scanline * W * C;
    jpeg_read_scanlines(&cinfo, &row, 1);
  }
  jpeg_finish_decompress(&cinfo);
  jpeg_destroy_decompress(&cinfo);
  uint8_t* result = data;
  int outC = C;
  if (cmyk) {  // libjpeg yields inverted CMYK: rgb = cmy * k / 255
    int want = channels == 1 ? 1 : 3;
    uint8_t* rgb = static_cast<uint8_t*>(std::malloc((size_t)W * H * want));
    if (!rgb) {
      std::free(data);
      return -1;
    }
    for (int64_t i = 0; i < (int64_t)W * H; ++i) {
      const uint8_t* p = result + i * 4;
      int r = p[0] * p[3] / 255, g = p[1] * p[3] / 255,
          b = p[2] * p[3] / 255;
      if (want == 1) {
        rgb[i] = static_cast<uint8_t>((299 * r + 587 * g + 114 * b) / 1000);
      } else {
        rgb[i * 3] = static_cast<uint8_t>(r);
        rgb[i * 3 + 1] = static_cast<uint8_t>(g);
        rgb[i * 3 + 2] = static_cast<uint8_t>(b);
      }
    }
    std::free(data);
    result = rgb;
    outC = want;
  }
  *out = result;
  *h = H;
  *w = W;
  *c = outC;
  return 0;
}

// ---------------------------------------------------------------- png
struct PngReadState {
  const uint8_t* buf;
  int64_t len;
  int64_t pos;
};

void PngRead(png_structp png, png_bytep out, png_size_t n) {
  PngReadState* s = static_cast<PngReadState*>(png_get_io_ptr(png));
  if (s->pos + static_cast<int64_t>(n) > s->len)
    png_error(png, "png: read past end");
  std::memcpy(out, s->buf + s->pos, n);
  s->pos += n;
}

int DecodePng(const uint8_t* buf, int64_t len, int channels, uint8_t** out,
              int* h, int* w, int* c) {
  png_structp png =
      png_create_read_struct(PNG_LIBPNG_VER_STRING, nullptr, nullptr, nullptr);
  if (!png) return -1;
  png_infop info = png_create_info_struct(png);
  // volatile: written between setjmp and longjmp, read in the handler
  uint8_t* volatile data = nullptr;
  if (!info || setjmp(png_jmpbuf(png))) {
    png_destroy_read_struct(&png, info ? &info : nullptr, nullptr);
    std::free(data);
    return -1;
  }
  PngReadState st{buf, len, 0};
  png_set_read_fn(png, &st, PngRead);
  png_read_info(png, info);
  png_set_strip_16(png);
  png_set_packing(png);
  png_set_strip_alpha(png);
  int color = png_get_color_type(png, info);
  if (color == PNG_COLOR_TYPE_PALETTE) png_set_palette_to_rgb(png);
  if (channels == 3 &&
      (color == PNG_COLOR_TYPE_GRAY || color == PNG_COLOR_TYPE_GRAY_ALPHA))
    png_set_gray_to_rgb(png);
  if (channels == 1 && color != PNG_COLOR_TYPE_GRAY &&
      color != PNG_COLOR_TYPE_GRAY_ALPHA)
    png_set_rgb_to_gray(png, 1, -1, -1);
  png_read_update_info(png, info);
  int W = png_get_image_width(png, info), H = png_get_image_height(png, info);
  int C = png_get_channels(png, info);
  data = static_cast<uint8_t*>(std::malloc((size_t)W * H * C));
  if (!data) png_error(png, "png: oom");
  std::vector<png_bytep> rows(H);
  for (int y = 0; y < H; ++y) rows[y] = data + (size_t)y * W * C;
  png_read_image(png, rows.data());
  png_destroy_read_struct(&png, &info, nullptr);
  *out = data;
  *h = H;
  *w = W;
  *c = C;
  return 0;
}

}  // namespace

extern "C" {

int mximg_decode(const uint8_t* buf, int64_t len, int channels, uint8_t** out,
                 int* h, int* w, int* c) {
  if (len >= 3 && buf[0] == 0xFF && buf[1] == 0xD8 && buf[2] == 0xFF)
    return DecodeJpeg(buf, len, channels, out, h, w, c);
  if (len >= 8 && std::memcmp(buf, "\x89PNG\r\n\x1a\n", 8) == 0)
    return DecodePng(buf, len, channels, out, h, w, c);
  return -2;  // unknown format
}

void mximg_free(uint8_t* buf) { std::free(buf); }

void mximg_resize(const uint8_t* src, int sh, int sw, int c, uint8_t* dst,
                  int dh, int dw) {
  // Bilinear with half-pixel centers (matches cv2.resize INTER_LINEAR).
  const float sy = static_cast<float>(sh) / dh;
  const float sx = static_cast<float>(sw) / dw;
  for (int y = 0; y < dh; ++y) {
    float fy = (y + 0.5f) * sy - 0.5f;
    if (fy < 0) fy = 0;
    int y0 = static_cast<int>(fy);
    if (y0 > sh - 1) y0 = sh - 1;
    int y1 = y0 + 1 < sh ? y0 + 1 : sh - 1;
    float wy = fy - y0;
    for (int x = 0; x < dw; ++x) {
      float fx = (x + 0.5f) * sx - 0.5f;
      if (fx < 0) fx = 0;
      int x0 = static_cast<int>(fx);
      if (x0 > sw - 1) x0 = sw - 1;
      int x1 = x0 + 1 < sw ? x0 + 1 : sw - 1;
      float wx = fx - x0;
      const uint8_t* p00 = src + ((size_t)y0 * sw + x0) * c;
      const uint8_t* p01 = src + ((size_t)y0 * sw + x1) * c;
      const uint8_t* p10 = src + ((size_t)y1 * sw + x0) * c;
      const uint8_t* p11 = src + ((size_t)y1 * sw + x1) * c;
      uint8_t* d = dst + ((size_t)y * dw + x) * c;
      for (int k = 0; k < c; ++k) {
        float v = (1 - wy) * ((1 - wx) * p00[k] + wx * p01[k]) +
                  wy * ((1 - wx) * p10[k] + wx * p11[k]);
        d[k] = static_cast<uint8_t>(v + 0.5f);
      }
    }
  }
}

}  // extern "C"

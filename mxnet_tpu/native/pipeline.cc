// Fused decode → augment → normalize → batch pipeline.
//
// TPU-native equivalent of the reference's ImageRecordIter v2 internals
// (src/io/iter_image_recordio_2.cc:513-566 thread pool +
// iter_batchloader.h batching + iter_prefetcher.h double buffering):
// worker threads each claim a whole batch of records, decode and augment
// them into a float32 NCHW buffer, and a bounded reorder queue hands
// batches to the consumer in epoch order.  Runs entirely off the Python
// thread — ctypes releases the GIL for the duration of mxpipe_next.
//
// Determinism: every record draws from an RNG seeded by
// (seed, epoch, position-in-epoch), so augmentation is reproducible
// regardless of thread scheduling — stronger than the reference, whose
// per-worker RNG makes runs schedule-dependent.
#include "mxnative.h"

#include <atomic>
#include <condition_variable>
#include <cstring>
#include <map>
#include <mutex>
#include <random>
#include <string>
#include <thread>
#include <vector>

namespace {

struct IRHeader {
  uint32_t flag;
  float label;
  uint64_t id, id2;
};

struct Batch {
  std::vector<float> data;
  std::vector<float> label;
  int pad = 0;
};

struct Pipe {
  void* rec;  // borrowed mxrio reader
  MXPipeConfig cfg;
  std::vector<int64_t> order;
  int64_t n_batches = 0;
  uint64_t epoch = 0;

  std::vector<std::thread> workers;
  std::atomic<int64_t> next_claim{0};
  std::mutex mu;
  std::condition_variable cv_ready, cv_space;
  std::map<int64_t, Batch> ready;  // batch seq -> ready batch
  int64_t next_deliver = 0;
  bool stop = false;
  uint64_t generation = 0;  // bumped per epoch so stale workers park
  std::string error;

  ~Pipe() {
    {
      std::lock_guard<std::mutex> l(mu);
      stop = true;
    }
    cv_space.notify_all();
    cv_ready.notify_all();
    for (auto& t : workers) t.join();
  }
};

// Parse the IRHeader + label(s) from a packed record; returns payload ptr.
const uint8_t* ParseHeader(const uint8_t* buf, int64_t len, int label_width,
                           float* label_out, int64_t* payload_len) {
  IRHeader h;
  std::memcpy(&h.flag, buf, 4);
  std::memcpy(&h.label, buf + 4, 4);
  std::memcpy(&h.id, buf + 8, 8);
  std::memcpy(&h.id2, buf + 16, 8);
  const uint8_t* p = buf + 24;
  int64_t rest = len - 24;
  if (h.flag > 0) {  // multi-label: flag = count of float32 labels
    int64_t nl = h.flag;
    if (24 + 4 * nl > len) {  // corrupted/truncated record: labels would
      *payload_len = -1;      // run past the mmap; fail the record
      return nullptr;
    }
    for (int i = 0; i < label_width; ++i) {
      float v = 0.f;
      if (i < nl) std::memcpy(&v, p + 4 * i, 4);
      label_out[i] = v;
    }
    p += 4 * nl;
    rest -= 4 * nl;
  } else {
    label_out[0] = h.label;
    for (int i = 1; i < label_width; ++i) label_out[i] = 0.f;
  }
  *payload_len = rest;
  return p;
}

// Decode + augment one record into dst (CHW float32).
bool ProcessOne(Pipe* pp, int64_t rec_idx, uint64_t rng_seed, float* dst,
                float* label_out) {
  const MXPipeConfig& c = pp->cfg;
  const uint8_t* buf;
  int64_t len = mxrio_get(pp->rec, rec_idx, &buf);
  if (len < 24) return false;
  int64_t payload_len;
  const uint8_t* payload =
      ParseHeader(buf, len, c.label_width, label_out, &payload_len);
  if (payload == nullptr || payload_len <= 0) return false;

  uint8_t* img;
  int h, w, ch;
  if (mximg_decode(payload, payload_len, c.target_c == 1 ? 1 : 3, &img, &h,
                   &w, &ch) != 0)
    return false;

  std::mt19937_64 rng(rng_seed);
  std::vector<uint8_t> owned;
  // short-side resize
  if (c.resize > 0) {
    int nh, nw;
    if (h < w) { nh = c.resize; nw = (int)((int64_t)w * c.resize / h); }
    else       { nw = c.resize; nh = (int)((int64_t)h * c.resize / w); }
    owned.resize((size_t)nh * nw * ch);
    mximg_resize(img, h, w, ch, owned.data(), nh, nw);
    mximg_free(img);
    img = nullptr;
    h = nh; w = nw;
  }
  const uint8_t* cur = owned.empty() ? img : owned.data();
  // upscale if smaller than the crop window
  if (h < c.target_h || w < c.target_w) {
    int nh = h > c.target_h ? h : c.target_h;
    int nw = w > c.target_w ? w : c.target_w;
    std::vector<uint8_t> up((size_t)nh * nw * ch);
    mximg_resize(cur, h, w, ch, up.data(), nh, nw);
    owned.swap(up);
    if (img) { mximg_free(img); img = nullptr; }
    cur = owned.data();
    h = nh; w = nw;
  }
  // crop
  int y0, x0;
  if (c.rand_crop) {
    y0 = (int)(rng() % (uint64_t)(h - c.target_h + 1));
    x0 = (int)(rng() % (uint64_t)(w - c.target_w + 1));
  } else {
    y0 = (h - c.target_h) / 2;
    x0 = (w - c.target_w) / 2;
  }
  bool mirror = c.rand_mirror && (rng() & 1);

  // normalize + HWC->CHW in one pass
  const int TH = c.target_h, TW = c.target_w, TC = c.target_c;
  for (int k = 0; k < TC; ++k) {
    float mean = c.mean[k < 3 ? k : 2], stdv = c.std_[k < 3 ? k : 2];
    float inv = c.scale / (stdv == 0.f ? 1.f : stdv);
    float* out_plane = dst + (size_t)k * TH * TW;
    for (int y = 0; y < TH; ++y) {
      const uint8_t* row = cur + ((size_t)(y0 + y) * w + x0) * ch + k;
      float* orow = out_plane + (size_t)y * TW;
      if (mirror) {
        for (int x = 0; x < TW; ++x)
          orow[x] = (row[(size_t)(TW - 1 - x) * ch] - mean) * inv;
      } else {
        for (int x = 0; x < TW; ++x) orow[x] = (row[(size_t)x * ch] - mean) * inv;
      }
    }
  }
  if (img) mximg_free(img);
  return true;
}

void WorkerLoop(Pipe* pp, uint64_t gen) {
  const MXPipeConfig& c = pp->cfg;
  const size_t img_sz = (size_t)c.target_c * c.target_h * c.target_w;
  for (;;) {
    {
      std::unique_lock<std::mutex> l(pp->mu);
      if (pp->stop || gen != pp->generation) return;
    }
    int64_t b = pp->next_claim.fetch_add(1);
    if (b >= pp->n_batches) return;
    Batch out;
    out.data.resize(img_sz * c.batch_size);
    out.label.resize((size_t)c.label_width * c.batch_size);
    int64_t start = b * c.batch_size;
    int64_t n = pp->order.size() - start;
    if (n > c.batch_size) n = c.batch_size;
    bool ok = true;
    for (int64_t i = 0; i < n && ok; ++i) {
      uint64_t seed = c.seed * 0x9E3779B97F4A7C15ull +
                      pp->epoch * 0x2545F4914F6CDD1Dull + (start + i);
      ok = ProcessOne(pp, pp->order[start + i], seed,
                      out.data.data() + img_sz * i,
                      out.label.data() + (size_t)c.label_width * i);
    }
    for (int64_t i = n; i < c.batch_size; ++i) {  // pad: repeat last sample
      std::memcpy(out.data.data() + img_sz * i,
                  out.data.data() + img_sz * (n - 1), img_sz * sizeof(float));
      std::memcpy(out.label.data() + (size_t)c.label_width * i,
                  out.label.data() + (size_t)c.label_width * (n - 1),
                  (size_t)c.label_width * sizeof(float));
    }
    out.pad = (int)(c.batch_size - n);
    std::unique_lock<std::mutex> l(pp->mu);
    if (!ok) {
      // first error wins: once non-empty the string is never reassigned,
      // so the c_str mxpipe_error hands to Python stays valid
      if (pp->error.empty())
        pp->error = "record decode failed in batch " + std::to_string(b);
      pp->cv_ready.notify_all();
      return;
    }
    pp->cv_space.wait(l, [&] {
      return pp->stop || gen != pp->generation ||
             (int)pp->ready.size() < c.queue_depth ||
             b == pp->next_deliver;  // never block the batch being waited on
    });
    if (pp->stop || gen != pp->generation) return;
    pp->ready.emplace(b, std::move(out));
    pp->cv_ready.notify_all();
  }
}

}  // namespace

extern "C" {

void* mxpipe_create(void* rec, const MXPipeConfig* cfg) {
  if (!rec || !cfg || cfg->batch_size <= 0) return nullptr;
  Pipe* pp = new Pipe();
  pp->rec = rec;
  pp->cfg = *cfg;
  if (pp->cfg.num_threads <= 0) pp->cfg.num_threads = 1;
  if (pp->cfg.queue_depth <= 0) pp->cfg.queue_depth = 2;
  return pp;
}

void mxpipe_start_epoch(void* handle, const int64_t* order, int64_t n) {
  Pipe* pp = static_cast<Pipe*>(handle);
  {
    std::lock_guard<std::mutex> l(pp->mu);
    pp->generation++;
    pp->ready.clear();
    pp->next_deliver = 0;
    pp->error.clear();
  }
  pp->cv_space.notify_all();
  pp->cv_ready.notify_all();
  for (auto& t : pp->workers) t.join();
  pp->workers.clear();

  pp->order.assign(order, order + n);
  if (!pp->cfg.round_batch) {
    n = (n / pp->cfg.batch_size) * pp->cfg.batch_size;
    pp->order.resize(n);
  }
  pp->n_batches = (n + pp->cfg.batch_size - 1) / pp->cfg.batch_size;
  pp->next_claim.store(0);
  pp->epoch++;
  uint64_t gen = pp->generation;
  int nt = pp->cfg.num_threads;
  if (nt > pp->n_batches && pp->n_batches > 0) nt = (int)pp->n_batches;
  for (int i = 0; i < nt; ++i)
    pp->workers.emplace_back(WorkerLoop, pp, gen);
}

int mxpipe_next(void* handle, float* data, float* label, int* pad) {
  Pipe* pp = static_cast<Pipe*>(handle);
  std::unique_lock<std::mutex> l(pp->mu);
  if (pp->next_deliver >= pp->n_batches) return 1;
  pp->cv_ready.wait(l, [&] {
    return pp->stop || !pp->error.empty() ||
           pp->ready.count(pp->next_deliver) > 0;
  });
  if (pp->stop || !pp->error.empty()) return -1;
  auto it = pp->ready.find(pp->next_deliver);
  Batch b = std::move(it->second);
  pp->ready.erase(it);
  pp->next_deliver++;
  l.unlock();
  pp->cv_space.notify_all();
  std::memcpy(data, b.data.data(), b.data.size() * sizeof(float));
  std::memcpy(label, b.label.data(), b.label.size() * sizeof(float));
  *pad = b.pad;
  return 0;
}

const char* mxpipe_error(void* handle) {
  return static_cast<Pipe*>(handle)->error.c_str();
}

void mxpipe_close(void* handle) { delete static_cast<Pipe*>(handle); }

}  // extern "C"

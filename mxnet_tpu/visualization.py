"""``mx.viz`` — network structure inspection.

Reference: ``python/mxnet/visualization.py`` (print_summary:34,
plot_network:152). ``print_summary`` walks the Symbol graph with inferred
shapes and parameter counts; ``plot_network`` emits a graphviz Digraph.
"""
from __future__ import annotations

from typing import Dict, Optional

import numpy as np

__all__ = ["print_summary", "plot_network"]


def _node_params(node, shape_of):
    """Parameter count of a node = total size of its variable inputs that
    look like parameters (weight/bias/gamma/beta)."""
    total = 0
    for src, _ in node.inputs:
        if src.is_variable and src.name.endswith(
                ("weight", "bias", "gamma", "beta")):
            shp = shape_of.get(src.name)
            if shp:
                total += int(np.prod(shp))
    return total


def print_summary(symbol, shape: Optional[Dict] = None, line_length: int = 98,
                  positions=(0.44, 0.64, 0.74, 1.0)):
    """Print a per-layer summary table (reference: visualization.py:34 —
    same columns: Layer (type), Output Shape, Param #, Previous Layer)."""
    from .symbol.symbol import _topo_order

    shape_of: Dict[str, tuple] = {}
    if shape:
        arg_shapes, out_shapes, _ = symbol.infer_shape(**shape)
        for name, shp in zip(symbol.list_arguments(), arg_shapes):
            shape_of[name] = shp
    nodes = _topo_order(symbol._entries)

    positions = [int(line_length * p) for p in positions]
    fields = ["Layer (type)", "Output Shape", "Param #", "Previous Layer"]

    def print_row(values):
        line = ""
        for v, p in zip(values, positions):
            line = (line + str(v))[:p - 1].ljust(p)
        print(line)

    print("_" * line_length)
    print_row(fields)
    print("=" * line_length)
    total = 0
    for node in nodes:
        if node.is_variable:
            continue
        prev = ",".join(src.name for src, _ in node.inputs
                        if not (src.is_variable and src.name != "data"))
        n_params = _node_params(node, shape_of)
        total += n_params
        out_shape = ""
        if shape:
            try:
                from .symbol.symbol import Symbol
                sub = Symbol([(node, 0)])
                needed = {k: v for k, v in shape.items()
                          if k in sub.list_arguments()}
                _, outs, _ = sub.infer_shape_partial(**needed)
                if outs and outs[0]:
                    out_shape = str(tuple(outs[0]))
            except Exception:
                out_shape = "?"
        print_row(["%s (%s)" % (node.name, node.op.name), out_shape,
                   n_params, prev])
    print("=" * line_length)
    print("Total params: %d" % total)
    print("_" * line_length)
    return total


def plot_network(symbol, title="plot", shape=None, node_attrs=None,
                 save_format="pdf"):
    """Build a graphviz Digraph of the symbol graph (reference:
    visualization.py:152). Requires the ``graphviz`` python package."""
    try:
        from graphviz import Digraph
    except ImportError as e:
        raise ImportError("plot_network requires the graphviz package") \
            from e
    from .symbol.symbol import _topo_order

    node_attrs = dict({"shape": "box", "fixedsize": "false"},
                      **(node_attrs or {}))
    dot = Digraph(name=title, format=save_format)
    # palette per op family, loosely matching the reference's color scheme
    palette = {"FullyConnected": "#fb8072", "Convolution": "#fb8072",
               "Activation": "#ffffb3", "BatchNorm": "#bebada",
               "Pooling": "#80b1d3", "SoftmaxOutput": "#fccde5"}
    _param_suffix = ("weight", "bias", "gamma", "beta", "moving_mean",
                     "moving_var", "label")
    for node in _topo_order(symbol._entries):
        if node.is_variable:
            # draw data-like inputs only; parameters would be orphan boxes
            # since their edges are suppressed below
            if not node.name.endswith(_param_suffix):
                dot.node(node.name, node.name,
                         _attributes=dict(node_attrs,
                                          fillcolor="#8dd3c7",
                                          style="filled"))
            continue
        color = palette.get(node.op.name, "#b3de69")
        dot.node(node.name, "%s\n(%s)" % (node.name, node.op.name),
                 _attributes=dict(node_attrs, fillcolor=color,
                                  style="filled"))
        for src, _ in node.inputs:
            # skip parameter variables, like the reference
            if src.is_variable and src.name.endswith(_param_suffix):
                continue
            dot.edge(src.name, node.name)
    return dot

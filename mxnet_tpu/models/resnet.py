"""ResNet v1/v2 symbol builder.

Capability twin of ``example/image-classification/symbols/resnet.py`` in the
reference (He et al. 2015/2016, pre-activation variant for v2). Built fresh
for TPU: NCHW layout, bf16-friendly (convs accumulate fp32 on the MXU
regardless of input dtype), BatchNorm with aux moving stats.
"""
from __future__ import annotations

from .. import symbol as sym

__all__ = ["get_symbol", "resnet"]

# depth -> (block counts per stage, bottleneck?)
_CONFIGS = {
    18: ([2, 2, 2, 2], False),
    34: ([3, 4, 6, 3], False),
    50: ([3, 4, 6, 3], True),
    101: ([3, 4, 23, 3], True),
    152: ([3, 8, 36, 3], True),
}


def _conv(data, num_filter, kernel, stride, pad, name):
    return sym.Convolution(data=data, num_filter=num_filter, kernel=kernel,
                           stride=stride, pad=pad, no_bias=True, name=name)


def _stem_s2d(data, num_filter, height, name="conv0"):
    """The imagenet 7x7/2 stem rewritten as a mathematically identical
    4x4/1 valid conv on the 2x2 space-to-depth input (the standard TPU
    ResNet stem transform): Cin 3->12 and no stride map far better onto
    the MXU (measured 25.3 vs 20.2 TF/s fwd+bwd on v5e,
    tools/perf/conv_restructure_sweep.py). The parameter keeps the
    reference's (F, 3, 7, 7) shape — same name, same checkpoint — and is
    re-laid-out in-graph: zero-pad 7->8 taps, split each spatial index
    2a+q, and fold the parity (q, r) planes into channels.
    """
    h2 = height // 2 + 3  # padded-by-3 input, halved: conv input extent
    w = sym.Variable(name + "_weight", shape=(num_filter, 3, 7, 7))
    wp = sym.Pad(w, mode="constant", pad_width=(0, 0, 0, 0, 0, 1, 0, 1))
    wr = sym.Reshape(wp, shape=(num_filter, 3, 4, 2, 4, 2))
    wt = sym.transpose(wr, axes=(0, 1, 3, 5, 2, 4))
    wf = sym.Reshape(wt, shape=(num_filter, 12, 4, 4))
    xp = sym.Pad(data, mode="constant", pad_width=(0, 0, 0, 0, 3, 3, 3, 3))
    xr = sym.Reshape(xp, shape=(0, 3, h2, 2, h2, 2))
    xt = sym.transpose(xr, axes=(0, 1, 3, 5, 2, 4))
    xs = sym.Reshape(xt, shape=(0, 12, h2, h2))
    return sym.Convolution(data=xs, weight=wf, num_filter=num_filter,
                           kernel=(4, 4), stride=(1, 1), pad=(0, 0),
                           no_bias=True, name=name)


def _bn(data, name, fix_gamma=False):
    return sym.BatchNorm(data=data, fix_gamma=fix_gamma, eps=2e-5,
                         momentum=0.9, name=name)


def _unit_v1(data, num_filter, stride, dim_match, name, bottleneck):
    """Post-activation residual unit (v1)."""
    if bottleneck:
        b = _conv(data, num_filter // 4, (1, 1), stride, (0, 0), name + "_conv1")
        b = _bn(b, name + "_bn1")
        b = sym.Activation(data=b, act_type="relu", name=name + "_relu1")
        b = _conv(b, num_filter // 4, (3, 3), (1, 1), (1, 1), name + "_conv2")
        b = _bn(b, name + "_bn2")
        b = sym.Activation(data=b, act_type="relu", name=name + "_relu2")
        b = _conv(b, num_filter, (1, 1), (1, 1), (0, 0), name + "_conv3")
        b = _bn(b, name + "_bn3")
    else:
        b = _conv(data, num_filter, (3, 3), stride, (1, 1), name + "_conv1")
        b = _bn(b, name + "_bn1")
        b = sym.Activation(data=b, act_type="relu", name=name + "_relu1")
        b = _conv(b, num_filter, (3, 3), (1, 1), (1, 1), name + "_conv2")
        b = _bn(b, name + "_bn2")
    if dim_match:
        shortcut = data
    else:
        shortcut = _conv(data, num_filter, (1, 1), stride, (0, 0),
                         name + "_sc")
        shortcut = _bn(shortcut, name + "_sc_bn")
    out = b + shortcut
    return sym.Activation(data=out, act_type="relu", name=name + "_relu")


def _unit_v2(data, num_filter, stride, dim_match, name, bottleneck):
    """Pre-activation residual unit (v2 — the reference's default)."""
    bn1 = _bn(data, name + "_bn1")
    act1 = sym.Activation(data=bn1, act_type="relu", name=name + "_relu1")
    if bottleneck:
        b = _conv(act1, num_filter // 4, (1, 1), (1, 1), (0, 0),
                  name + "_conv1")
        b = _bn(b, name + "_bn2")
        b = sym.Activation(data=b, act_type="relu", name=name + "_relu2")
        b = _conv(b, num_filter // 4, (3, 3), stride, (1, 1), name + "_conv2")
        b = _bn(b, name + "_bn3")
        b = sym.Activation(data=b, act_type="relu", name=name + "_relu3")
        b = _conv(b, num_filter, (1, 1), (1, 1), (0, 0), name + "_conv3")
    else:
        b = _conv(act1, num_filter, (3, 3), stride, (1, 1), name + "_conv1")
        b = _bn(b, name + "_bn2")
        b = sym.Activation(data=b, act_type="relu", name=name + "_relu2")
        b = _conv(b, num_filter, (3, 3), (1, 1), (1, 1), name + "_conv2")
    if dim_match:
        shortcut = data
    else:
        shortcut = _conv(act1, num_filter, (1, 1), stride, (0, 0),
                         name + "_sc")
    return b + shortcut


def resnet(units, num_stages, filter_list, num_classes, image_shape,
           bottleneck=True, version=2, stem="7x7"):
    """Assemble a ResNet (reference: symbols/resnet.py resnet()).

    ``stem="s2d"`` lowers the imagenet stem through the space-to-depth
    transform (see ``_stem_s2d``) — identical function and parameters,
    better MXU mapping; requires an even input height."""
    data = sym.Variable("data")
    nchannel, height, _ = image_shape
    unit = _unit_v2 if version == 2 else _unit_v1

    if stem not in ("7x7", "s2d"):
        raise ValueError("stem must be '7x7' or 's2d', got %r" % (stem,))
    if stem == "s2d":
        if height <= 32:
            raise ValueError(
                "stem='s2d' rewrites the imagenet 7x7/2 stem; the cifar "
                "stem (height <= 32) has no 7x7 conv to transform")
        if nchannel != 3 or height % 2 or image_shape[2] != height:
            raise ValueError(
                "stem='s2d' needs a 3-channel, square, even-size input "
                "(got image_shape %s)" % (image_shape,))
    body = data
    if version == 2:
        body = _bn(body, "bn_data", fix_gamma=True)
    if height <= 32:  # cifar-style stem
        body = _conv(body, filter_list[0], (3, 3), (1, 1), (1, 1), "conv0")
    else:             # imagenet stem
        if stem == "s2d":
            body = _stem_s2d(body, filter_list[0], height)
        else:
            body = _conv(body, filter_list[0], (7, 7), (2, 2), (3, 3),
                         "conv0")
        body = _bn(body, "bn0")
        body = sym.Activation(data=body, act_type="relu", name="relu0")
        body = sym.Pooling(data=body, kernel=(3, 3), stride=(2, 2),
                           pad=(1, 1), pool_type="max", name="pool0")

    for i in range(num_stages):
        stride = (1, 1) if i == 0 and height > 32 else \
            ((1, 1) if i == 0 else (2, 2))
        body = unit(body, filter_list[i + 1], stride, False,
                    "stage%d_unit1" % (i + 1), bottleneck)
        for j in range(units[i] - 1):
            body = unit(body, filter_list[i + 1], (1, 1), True,
                        "stage%d_unit%d" % (i + 1, j + 2), bottleneck)

    if version == 2:
        body = _bn(body, "bn1")
        body = sym.Activation(data=body, act_type="relu", name="relu1")
    pool = sym.Pooling(data=body, global_pool=True, kernel=(7, 7),
                       pool_type="avg", name="pool1")
    flat = sym.Flatten(data=pool)
    fc1 = sym.FullyConnected(data=flat, num_hidden=num_classes, name="fc1")
    return sym.SoftmaxOutput(data=fc1, name="softmax")


def get_symbol(num_classes=1000, num_layers=50, image_shape="3,224,224",
               version=2, stem="7x7", **kwargs):
    """(reference: symbols/resnet.py get_symbol)."""
    if isinstance(image_shape, str):
        image_shape = tuple(int(x) for x in image_shape.split(","))
    if image_shape[1] <= 32:
        # cifar config (reference resnet.py: per-depth unit derivation —
        # any depth with (n-2) % 9 == 0 (bottleneck) or % 6 == 0 works,
        # e.g. resnet-8/20/56/110)
        if (num_layers - 2) % 9 == 0 and num_layers >= 164:
            per = (num_layers - 2) // 9
            units, bottleneck = [per] * 3, True
        elif (num_layers - 2) % 6 == 0:
            per = (num_layers - 2) // 6
            units, bottleneck = [per] * 3, False
        else:
            raise ValueError(
                "unsupported small-image resnet depth %d "
                "(need (n-2) %% 6 == 0)" % num_layers)
        filter_list = [16, 64, 128, 256] if bottleneck else [16, 16, 32, 64]
        num_stages = 3
    else:
        if num_layers not in _CONFIGS:
            raise ValueError("unsupported resnet depth %d" % num_layers)
        units, bottleneck = _CONFIGS[num_layers]
        filter_list = [64, 256, 512, 1024, 2048] if bottleneck else \
            [64, 64, 128, 256, 512]
        num_stages = 4
    return resnet(units=units[:num_stages], num_stages=num_stages,
                  filter_list=filter_list, num_classes=num_classes,
                  image_shape=image_shape, bottleneck=bottleneck,
                  version=version, stem=stem)

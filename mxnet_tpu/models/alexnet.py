"""AlexNet symbol (reference: example/image-classification/symbols/alexnet.py
— the 'one weird trick' single-tower variant used for the perf tables)."""
from .. import symbol as sym

__all__ = ["get_symbol"]


def get_symbol(num_classes=1000, **kwargs):
    data = sym.Variable("data")
    c1 = sym.Convolution(data=data, kernel=(11, 11), stride=(4, 4),
                         num_filter=96, name="conv1")
    r1 = sym.Activation(data=c1, act_type="relu", name="relu1")
    n1 = sym.LRN(data=r1, alpha=0.0001, beta=0.75, knorm=2, nsize=5,
                 name="norm1")
    p1 = sym.Pooling(data=n1, kernel=(3, 3), stride=(2, 2), pool_type="max",
                     name="pool1")
    c2 = sym.Convolution(data=p1, kernel=(5, 5), pad=(2, 2), num_filter=256,
                         name="conv2")
    r2 = sym.Activation(data=c2, act_type="relu", name="relu2")
    n2 = sym.LRN(data=r2, alpha=0.0001, beta=0.75, knorm=2, nsize=5,
                 name="norm2")
    p2 = sym.Pooling(data=n2, kernel=(3, 3), stride=(2, 2), pool_type="max",
                     name="pool2")
    c3 = sym.Convolution(data=p2, kernel=(3, 3), pad=(1, 1), num_filter=384,
                         name="conv3")
    r3 = sym.Activation(data=c3, act_type="relu", name="relu3")
    c4 = sym.Convolution(data=r3, kernel=(3, 3), pad=(1, 1), num_filter=384,
                         name="conv4")
    r4 = sym.Activation(data=c4, act_type="relu", name="relu4")
    c5 = sym.Convolution(data=r4, kernel=(3, 3), pad=(1, 1), num_filter=256,
                         name="conv5")
    r5 = sym.Activation(data=c5, act_type="relu", name="relu5")
    p3 = sym.Pooling(data=r5, kernel=(3, 3), stride=(2, 2), pool_type="max",
                     name="pool3")
    f = sym.Flatten(data=p3)
    fc1 = sym.FullyConnected(data=f, num_hidden=4096, name="fc1")
    r6 = sym.Activation(data=fc1, act_type="relu", name="relu6")
    d1 = sym.Dropout(data=r6, p=0.5, name="drop1")
    fc2 = sym.FullyConnected(data=d1, num_hidden=4096, name="fc2")
    r7 = sym.Activation(data=fc2, act_type="relu", name="relu7")
    d2 = sym.Dropout(data=r7, p=0.5, name="drop2")
    fc3 = sym.FullyConnected(data=d2, num_hidden=num_classes, name="fc3")
    return sym.SoftmaxOutput(data=fc3, name="softmax")

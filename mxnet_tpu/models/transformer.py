"""Decoder-only transformer language model (GPT-style) as a Symbol.

The reference's model zoo is conv/RNN-era (SURVEY.md §2.15); this is the
TPU build's modern flagship-class workload: large matmuls that keep the
MXU busy (unlike ResNet's small-spatial convs), built entirely from the
framework's own ops — Embedding, FullyConnected, batch_dot, LayerNorm,
softmax — so it exercises the same Symbol/Module path as every other
model.

Shapes: data (N, T) int token ids, softmax_label (N, T) next-token ids.
"""
from __future__ import annotations

import numpy as np

from .. import symbol as sym

__all__ = ["get_symbol", "get_pipeline_stages", "param_count"]


def _attention(x, n_heads, d_model, T, name, attention="dense"):
    """Causal multi-head self-attention. x: (N, T, D).

    attention="flash" routes the inner loop through the Pallas flash
    kernel (ops/pallas/flash_attention.py — the §2.22 custom-kernel
    path); "dense" is the batch_dot + masked-softmax composition.
    """
    d_head = d_model // n_heads
    qkv = sym.FullyConnected(x, num_hidden=3 * d_model, flatten=False,
                             name="%s_qkv" % name)          # (N, T, 3D)
    qkv = sym.reshape(qkv, (-1, T, 3, n_heads, d_head))
    qkv = sym.transpose(qkv, axes=(2, 0, 3, 1, 4))          # (3,N,H,T,d)
    if attention == "flash":
        q = sym.reshape(sym.slice_axis(qkv, axis=0, begin=0, end=1),
                        (-1, n_heads, T, d_head))           # (N, H, T, d)
        k = sym.reshape(sym.slice_axis(qkv, axis=0, begin=1, end=2),
                        (-1, n_heads, T, d_head))
        v = sym.reshape(sym.slice_axis(qkv, axis=0, begin=2, end=3),
                        (-1, n_heads, T, d_head))
        ctx = sym.FlashAttention(q, k, v, causal=True)      # (N, H, T, d)
        ctx = sym.transpose(ctx, axes=(0, 2, 1, 3))         # (N, T, H, d)
    else:
        q = sym.reshape(sym.slice_axis(qkv, axis=0, begin=0, end=1),
                        (-1, T, d_head))                    # (N*H, T, d)
        k = sym.reshape(sym.slice_axis(qkv, axis=0, begin=1, end=2),
                        (-1, T, d_head))
        v = sym.reshape(sym.slice_axis(qkv, axis=0, begin=2, end=3),
                        (-1, T, d_head))
        scores = sym.batch_dot(q, k, transpose_b=True)      # (N*H, T, T)
        scores = scores * (1.0 / float(np.sqrt(d_head)))
        # causal bias: -1e9 where key position > query position
        pos = sym.arange(start=0, stop=T)
        qpos = sym.reshape(pos, (T, 1))
        kpos = sym.reshape(pos, (1, T))
        future = sym.broadcast_greater(kpos, qpos)          # (T, T)
        bias = sym.reshape(future * -1e9, (1, T, T))
        scores = sym.broadcast_add(scores, bias)
        att = sym.softmax(scores, axis=-1)
        ctx = sym.batch_dot(att, v)                         # (N*H, T, d)
        ctx = sym.reshape(ctx, (-1, n_heads, T, d_head))
        ctx = sym.transpose(ctx, axes=(0, 2, 1, 3))         # (N, T, H, d)
    ctx = sym.reshape(ctx, (-1, T, d_model))
    return sym.FullyConnected(ctx, num_hidden=d_model, flatten=False,
                              name="%s_proj" % name)


def _block(x, n_heads, d_model, d_ff, T, name, attention="dense"):
    ln1 = sym.LayerNorm(x, sym.Variable("%s_ln1_gamma" % name),
                        sym.Variable("%s_ln1_beta" % name))
    x = x + _attention(ln1, n_heads, d_model, T, name + "_att",
                       attention=attention)
    ln2 = sym.LayerNorm(x, sym.Variable("%s_ln2_gamma" % name),
                        sym.Variable("%s_ln2_beta" % name))
    h = sym.FullyConnected(ln2, num_hidden=d_ff, flatten=False,
                           name="%s_ff1" % name)
    h = sym.Activation(h, act_type="relu")
    h = sym.FullyConnected(h, num_hidden=d_model, flatten=False,
                           name="%s_ff2" % name)
    return x + h


def get_symbol(vocab_size=32000, num_layers=12, d_model=768, n_heads=12,
               d_ff=None, seq_len=512, attention="dense"):
    """Build the LM training symbol: embeddings -> L blocks -> tied-free
    output projection -> per-token SoftmaxOutput."""
    d_ff = d_ff or 4 * d_model
    T = seq_len
    data = sym.Variable("data")                             # (N, T) ids
    tok = sym.Embedding(data, sym.Variable("tok_embed_weight"),
                        input_dim=vocab_size, output_dim=d_model,
                        name="tok_embed")                   # (N, T, D)
    pos_ids = sym.arange(start=0, stop=T)
    pos = sym.Embedding(pos_ids, sym.Variable("pos_embed_weight"),
                        input_dim=T, output_dim=d_model,
                        name="pos_embed")                   # (T, D)
    x = sym.broadcast_add(tok, sym.reshape(pos, (1, T, d_model)))
    for i in range(num_layers):
        x = _block(x, n_heads, d_model, d_ff, T, "layer%d" % i,
                   attention=attention)
    x = sym.LayerNorm(x, sym.Variable("final_ln_gamma"),
                      sym.Variable("final_ln_beta"))
    logits = sym.FullyConnected(x, num_hidden=vocab_size, flatten=False,
                                name="lm_head")             # (N, T, V)
    logits = sym.reshape(logits, (-1, vocab_size))          # (N*T, V)
    label = sym.reshape(sym.Variable("softmax_label"), (-1,))
    return sym.SoftmaxOutput(logits, label, name="softmax",
                             normalization="batch")


def get_pipeline_stages(vocab_size=32000, n_stages=2, layers_per_stage=1,
                        d_model=256, n_heads=4, seq_len=128, d_ff=None,
                        moe_experts=0, moe_top_k=2, attention="dense"):
    """Stage symbols for ``mx.mod.PipelineModule``: [embed, body*, head].

    Each body stage holds ``layers_per_stage`` transformer blocks; with
    ``moe_experts > 0`` every block's FFN is a Switch/GShard MoE
    (``sym.MoE``; the router's aux loss is computed per block but not
    added to the pipelined objective — plumb it via the gluon
    ``nn.MoE`` + ``collect_aux_losses`` path when router balance
    matters). The head applies the final LayerNorm + lm head +
    per-token SoftmaxOutput, so gradients follow Module.fit's loss-op
    semantics per microbatch.

    ``d_ff`` may be a list of ``n_stages`` per-stage FFN widths — the
    stages then have *unequal* parameter shapes, which PipelineModule
    runs in its heterogeneous mode (per-stage param trees).
    """
    d_ff = d_ff or 4 * d_model
    if isinstance(d_ff, (list, tuple)):
        if len(d_ff) != n_stages:
            raise ValueError("d_ff list must have n_stages=%d entries"
                             % n_stages)
        stage_ff = list(d_ff)
    else:
        stage_ff = [d_ff] * n_stages
    T = seq_len

    data = sym.Variable("data")
    tok = sym.Embedding(data, sym.Variable("tok_embed_weight"),
                        input_dim=vocab_size, output_dim=d_model,
                        name="tok_embed")
    pos_ids = sym.arange(start=0, stop=T)
    pos = sym.Embedding(pos_ids, sym.Variable("pos_embed_weight"),
                        input_dim=T, output_dim=d_model, name="pos_embed")
    embed = sym.broadcast_add(tok, sym.reshape(pos, (1, T, d_model)))

    def body_stage(si):
        d_ff = stage_ff[si]
        x = sym.Variable("x")
        for li in range(layers_per_stage):
            name = "s%d_layer%d" % (si, li)
            ln1 = sym.LayerNorm(x, sym.Variable("%s_ln1_gamma" % name),
                                sym.Variable("%s_ln1_beta" % name))
            x = x + _attention(ln1, n_heads, d_model, T, name + "_att",
                               attention=attention)
            ln2 = sym.LayerNorm(x, sym.Variable("%s_ln2_gamma" % name),
                                sym.Variable("%s_ln2_beta" % name))
            if moe_experts:
                # expert count isn't derivable from activation shapes, so
                # the MoE variables carry explicit shape hints
                h = sym.MoE(ln2,
                            sym.Variable("%s_moe_router_weight" % name,
                                         shape=(d_model, moe_experts)),
                            sym.Variable("%s_moe_wi_weight" % name,
                                         shape=(moe_experts, d_model,
                                                d_ff)),
                            sym.Variable("%s_moe_wo_weight" % name,
                                         shape=(moe_experts, d_ff,
                                                d_model)),
                            top_k=moe_top_k)[0]
            else:
                h = sym.FullyConnected(ln2, num_hidden=d_ff, flatten=False,
                                       name="%s_ff1" % name)
                h = sym.Activation(h, act_type="relu")
                h = sym.FullyConnected(h, num_hidden=d_model,
                                       flatten=False, name="%s_ff2" % name)
            x = x + h
        return x

    x = sym.Variable("x")
    x = sym.LayerNorm(x, sym.Variable("final_ln_gamma"),
                      sym.Variable("final_ln_beta"))
    logits = sym.FullyConnected(x, num_hidden=vocab_size, flatten=False,
                                name="lm_head")
    logits = sym.reshape(logits, (-1, vocab_size))
    label = sym.reshape(sym.Variable("softmax_label"), (-1,))
    head = sym.SoftmaxOutput(logits, label, name="softmax",
                             normalization="batch")
    return [embed] + [body_stage(i) for i in range(n_stages)] + [head]


def param_count(vocab_size=32000, num_layers=12, d_model=768, n_heads=12,
                d_ff=None, seq_len=512):
    """Analytic parameter count (for FLOP estimates)."""
    d_ff = d_ff or 4 * d_model
    # qkv: weight D x 3D plus a 3D bias (the fused projection has one bias
    # element per output unit, i.e. 3*d_model of them)
    per_layer = 3 * d_model * d_model + 3 * d_model \
        + (d_model + 1) * d_model \
        + (d_model + 1) * d_ff + (d_ff + 1) * d_model + 4 * d_model
    return (vocab_size * d_model + seq_len * d_model
            + num_layers * per_layer + 2 * d_model
            + (d_model + 1) * vocab_size)

"""Symbol-level model definitions (the capability of
``example/image-classification/symbols/`` in the reference, SURVEY.md §2.15).

These build ``mx.sym`` graphs consumed by ``mx.mod.Module``; the Gluon model
zoo (``mxnet_tpu/gluon/model_zoo``) is the imperative twin.
"""
from . import resnet
from . import mlp
from . import lenet
from . import alexnet
from . import vgg
from . import inception
from .resnet import get_symbol as get_resnet

__all__ = ["resnet", "mlp", "lenet", "alexnet", "vgg", "inception", "get_resnet"]

"""Inception symbol models: Inception-BN (v2-era, 224x224) and
Inception-v3 (299x299).

Capability twins of the reference's perf-table networks
(``example/image-classification/symbols/inception-bn.py`` and
``inception-v3.py`` — the models behind the Inception columns of
docs/how_to/perf.md:33-190 / BASELINE.md). Rebuilt from the published
architectures (Szegedy et al., 2015/2016); the branch channel constants
are the architectures' own.
"""
from __future__ import annotations

from .. import symbol as sym

__all__ = ["get_symbol"]


def _conv_bn(x, num_filter, kernel, stride=(1, 1), pad=(0, 0), name=""):
    x = sym.Convolution(data=x, num_filter=num_filter, kernel=kernel,
                        stride=stride, pad=pad, no_bias=True,
                        name="%s_conv" % name)
    x = sym.BatchNorm(data=x, fix_gamma=False, eps=1e-3,
                      name="%s_bn" % name)
    return sym.Activation(data=x, act_type="relu", name="%s_relu" % name)


def _pool(x, kernel, stride, pool_type, pad=(0, 0), name=""):
    return sym.Pooling(data=x, kernel=kernel, stride=stride, pad=pad,
                       pool_type=pool_type, name=name)


# ------------------------------------------------------------ Inception-BN


def _bn_unit_a(x, c1, c3r, c3, d3r, d3, pool, proj, name):
    """1x1 | 1x1-3x3 | 1x1-3x3-3x3 | pool-proj, stride 1."""
    b1 = _conv_bn(x, c1, (1, 1), name="%s_1x1" % name)
    b2 = _conv_bn(x, c3r, (1, 1), name="%s_3x3r" % name)
    b2 = _conv_bn(b2, c3, (3, 3), pad=(1, 1), name="%s_3x3" % name)
    b3 = _conv_bn(x, d3r, (1, 1), name="%s_d3x3r" % name)
    b3 = _conv_bn(b3, d3, (3, 3), pad=(1, 1), name="%s_d3x3a" % name)
    b3 = _conv_bn(b3, d3, (3, 3), pad=(1, 1), name="%s_d3x3b" % name)
    b4 = _pool(x, (3, 3), (1, 1), pool, pad=(1, 1), name="%s_pool" % name)
    b4 = _conv_bn(b4, proj, (1, 1), name="%s_proj" % name)
    return sym.Concat(b1, b2, b3, b4, name="%s_concat" % name)


def _bn_unit_b(x, c3r, c3, d3r, d3, name):
    """Stride-2 grid reduction: 1x1-3x3/2 | 1x1-3x3-3x3/2 | maxpool/2."""
    b1 = _conv_bn(x, c3r, (1, 1), name="%s_3x3r" % name)
    b1 = _conv_bn(b1, c3, (3, 3), stride=(2, 2), pad=(1, 1),
                  name="%s_3x3" % name)
    b2 = _conv_bn(x, d3r, (1, 1), name="%s_d3x3r" % name)
    b2 = _conv_bn(b2, d3, (3, 3), pad=(1, 1), name="%s_d3x3a" % name)
    b2 = _conv_bn(b2, d3, (3, 3), stride=(2, 2), pad=(1, 1),
                  name="%s_d3x3b" % name)
    b3 = _pool(x, (3, 3), (2, 2), "max", pad=(1, 1), name="%s_pool" % name)
    return sym.Concat(b1, b2, b3, name="%s_concat" % name)


def _inception_bn(num_classes):
    data = sym.Variable("data")                       # (N, 3, 224, 224)
    x = _conv_bn(data, 64, (7, 7), stride=(2, 2), pad=(3, 3), name="stem1")
    x = _pool(x, (3, 3), (2, 2), "max", pad=(1, 1), name="stem_pool1")
    x = _conv_bn(x, 64, (1, 1), name="stem2r")
    x = _conv_bn(x, 192, (3, 3), pad=(1, 1), name="stem2")
    x = _pool(x, (3, 3), (2, 2), "max", pad=(1, 1), name="stem_pool2")
    x = _bn_unit_a(x, 64, 64, 64, 64, 96, "avg", 32, "in3a")
    x = _bn_unit_a(x, 64, 64, 96, 64, 96, "avg", 64, "in3b")
    x = _bn_unit_b(x, 128, 160, 64, 96, "in3c")
    x = _bn_unit_a(x, 224, 64, 96, 96, 128, "avg", 128, "in4a")
    x = _bn_unit_a(x, 192, 96, 128, 96, 128, "avg", 128, "in4b")
    x = _bn_unit_a(x, 160, 128, 160, 128, 160, "avg", 128, "in4c")
    x = _bn_unit_a(x, 96, 128, 192, 160, 192, "avg", 128, "in4d")
    x = _bn_unit_b(x, 128, 192, 192, 256, "in4e")
    x = _bn_unit_a(x, 352, 192, 320, 160, 224, "avg", 128, "in5a")
    x = _bn_unit_a(x, 352, 192, 320, 192, 224, "max", 128, "in5b")
    x = sym.Pooling(data=x, global_pool=True, pool_type="avg", kernel=(7, 7),
                    name="global_pool")
    x = sym.Flatten(data=x)
    x = sym.FullyConnected(data=x, num_hidden=num_classes, name="fc1")
    return sym.SoftmaxOutput(data=x, name="softmax")


# ------------------------------------------------------------ Inception-v3


def _v3_a(x, pool_proj, name):
    b1 = _conv_bn(x, 64, (1, 1), name="%s_1x1" % name)
    b2 = _conv_bn(x, 48, (1, 1), name="%s_5x5r" % name)
    b2 = _conv_bn(b2, 64, (5, 5), pad=(2, 2), name="%s_5x5" % name)
    b3 = _conv_bn(x, 64, (1, 1), name="%s_d3r" % name)
    b3 = _conv_bn(b3, 96, (3, 3), pad=(1, 1), name="%s_d3a" % name)
    b3 = _conv_bn(b3, 96, (3, 3), pad=(1, 1), name="%s_d3b" % name)
    b4 = _pool(x, (3, 3), (1, 1), "avg", pad=(1, 1), name="%s_pool" % name)
    b4 = _conv_bn(b4, pool_proj, (1, 1), name="%s_proj" % name)
    return sym.Concat(b1, b2, b3, b4, name="%s_concat" % name)


def _v3_b(x, name):
    b1 = _conv_bn(x, 384, (3, 3), stride=(2, 2), name="%s_3x3" % name)
    b2 = _conv_bn(x, 64, (1, 1), name="%s_d3r" % name)
    b2 = _conv_bn(b2, 96, (3, 3), pad=(1, 1), name="%s_d3a" % name)
    b2 = _conv_bn(b2, 96, (3, 3), stride=(2, 2), name="%s_d3b" % name)
    b3 = _pool(x, (3, 3), (2, 2), "max", name="%s_pool" % name)
    return sym.Concat(b1, b2, b3, name="%s_concat" % name)


def _v3_c(x, c7, name):
    b1 = _conv_bn(x, 192, (1, 1), name="%s_1x1" % name)
    b2 = _conv_bn(x, c7, (1, 1), name="%s_7r" % name)
    b2 = _conv_bn(b2, c7, (1, 7), pad=(0, 3), name="%s_7a" % name)
    b2 = _conv_bn(b2, 192, (7, 1), pad=(3, 0), name="%s_7b" % name)
    b3 = _conv_bn(x, c7, (1, 1), name="%s_77r" % name)
    b3 = _conv_bn(b3, c7, (7, 1), pad=(3, 0), name="%s_77a" % name)
    b3 = _conv_bn(b3, c7, (1, 7), pad=(0, 3), name="%s_77b" % name)
    b3 = _conv_bn(b3, c7, (7, 1), pad=(3, 0), name="%s_77c" % name)
    b3 = _conv_bn(b3, 192, (1, 7), pad=(0, 3), name="%s_77d" % name)
    b4 = _pool(x, (3, 3), (1, 1), "avg", pad=(1, 1), name="%s_pool" % name)
    b4 = _conv_bn(b4, 192, (1, 1), name="%s_proj" % name)
    return sym.Concat(b1, b2, b3, b4, name="%s_concat" % name)


def _v3_d(x, name):
    b1 = _conv_bn(x, 192, (1, 1), name="%s_3r" % name)
    b1 = _conv_bn(b1, 320, (3, 3), stride=(2, 2), name="%s_3" % name)
    b2 = _conv_bn(x, 192, (1, 1), name="%s_7r" % name)
    b2 = _conv_bn(b2, 192, (1, 7), pad=(0, 3), name="%s_7a" % name)
    b2 = _conv_bn(b2, 192, (7, 1), pad=(3, 0), name="%s_7b" % name)
    b2 = _conv_bn(b2, 192, (3, 3), stride=(2, 2), name="%s_7c" % name)
    b3 = _pool(x, (3, 3), (2, 2), "max", name="%s_pool" % name)
    return sym.Concat(b1, b2, b3, name="%s_concat" % name)


def _v3_e(x, name):
    b1 = _conv_bn(x, 320, (1, 1), name="%s_1x1" % name)
    b2 = _conv_bn(x, 384, (1, 1), name="%s_13r" % name)
    b2a = _conv_bn(b2, 384, (1, 3), pad=(0, 1), name="%s_13a" % name)
    b2b = _conv_bn(b2, 384, (3, 1), pad=(1, 0), name="%s_13b" % name)
    b3 = _conv_bn(x, 448, (1, 1), name="%s_d13r" % name)
    b3 = _conv_bn(b3, 384, (3, 3), pad=(1, 1), name="%s_d13" % name)
    b3a = _conv_bn(b3, 384, (1, 3), pad=(0, 1), name="%s_d13a" % name)
    b3b = _conv_bn(b3, 384, (3, 1), pad=(1, 0), name="%s_d13b" % name)
    b4 = _pool(x, (3, 3), (1, 1), "avg", pad=(1, 1), name="%s_pool" % name)
    b4 = _conv_bn(b4, 192, (1, 1), name="%s_proj" % name)
    return sym.Concat(b1, b2a, b2b, b3a, b3b, b4, name="%s_concat" % name)


def _inception_v3(num_classes):
    data = sym.Variable("data")                       # (N, 3, 299, 299)
    x = _conv_bn(data, 32, (3, 3), stride=(2, 2), name="stem1")
    x = _conv_bn(x, 32, (3, 3), name="stem2")
    x = _conv_bn(x, 64, (3, 3), pad=(1, 1), name="stem3")
    x = _pool(x, (3, 3), (2, 2), "max", name="stem_pool1")
    x = _conv_bn(x, 80, (1, 1), name="stem4")
    x = _conv_bn(x, 192, (3, 3), name="stem5")
    x = _pool(x, (3, 3), (2, 2), "max", name="stem_pool2")
    x = _v3_a(x, 32, "mixed5b")
    x = _v3_a(x, 64, "mixed5c")
    x = _v3_a(x, 64, "mixed5d")
    x = _v3_b(x, "mixed6a")
    x = _v3_c(x, 128, "mixed6b")
    x = _v3_c(x, 160, "mixed6c")
    x = _v3_c(x, 160, "mixed6d")
    x = _v3_c(x, 192, "mixed6e")
    x = _v3_d(x, "mixed7a")
    x = _v3_e(x, "mixed7b")
    x = _v3_e(x, "mixed7c")
    x = sym.Pooling(data=x, global_pool=True, pool_type="avg", kernel=(8, 8),
                    name="global_pool")
    x = sym.Flatten(data=x)
    x = sym.Dropout(data=x, p=0.5, name="drop")
    x = sym.FullyConnected(data=x, num_hidden=num_classes, name="fc1")
    return sym.SoftmaxOutput(data=x, name="softmax")


def get_symbol(num_classes=1000, version="v3", **kwargs):
    """``version``: "v3" (299x299) or "bn" (224x224)."""
    if version == "v3":
        return _inception_v3(num_classes)
    if version == "bn":
        return _inception_bn(num_classes)
    raise ValueError("unknown inception version %r" % version)

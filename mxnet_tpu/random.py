"""Global PRNG state for the imperative API.

Reference: ``python/mxnet/random.py`` (mx.random.seed) over per-device mtrand
resources (src/resource.cc:84-180). The TPU build keeps one counter-based
threefry key chain: ``seed()`` resets it, every sampler op consumes one split.
Unlike the reference's per-GPU streams, results are reproducible regardless of
which device or mesh runs the op.
"""
from __future__ import annotations

import threading
import zlib

import jax
import numpy as np

__all__ = ["seed", "next_key", "current_key", "set_key",
           "derive_numpy_rng"]

_state = threading.local()


def _key():
    if not hasattr(_state, "key"):
        _state.key = jax.random.PRNGKey(0)
    return _state.key


def seed(seed_state: int) -> None:
    """Seed the global generator (reference: python/mxnet/random.py seed;
    MXRandomSeed in src/c_api/c_api.cc)."""
    _state.key = jax.random.PRNGKey(int(seed_state))


def next_key():
    """Split off a fresh key for one sampler-op invocation."""
    k, sub = jax.random.split(_key())
    _state.key = k
    return sub


def current_key():
    return _key()


def derive_numpy_rng(tag: str = "") -> np.random.Generator:
    """A numpy ``Generator`` deterministically derived from the global
    key chain (one ``split`` is consumed, optionally folded with
    ``tag``) — the bridge for host-side numpy randomness that must
    follow ``mx.random.seed``. ``fit``'s default parameter initializer
    routes here, so two identically-seeded fits draw identical initial
    weights; before this it read the process-global unseeded
    ``np.random`` (the masked flake source documented in CHANGES PR 4).
    """
    sub = next_key()
    if tag:
        sub = jax.random.fold_in(sub, zlib.crc32(tag.encode()) & 0x7FFFFFFF)
    try:
        data = jax.random.key_data(sub)     # typed key array
    except (AttributeError, TypeError):
        data = sub                          # raw uint32 vector key
    seed_words = [int(x) for x in np.asarray(data).ravel()]
    return np.random.default_rng(seed_words)


def set_key(key) -> None:
    """Restore the generator to an exact previously-captured key — the
    checkpoint-resume twin of ``seed()``: ``mx.checkpoint`` snapshots
    ``current_key()`` and replays it here so every sampler op after a
    resume draws the same stream as the uninterrupted run."""
    _state.key = key

"""``MXNET_TPU_LOCKCHECK`` — the runtime lock witness (``off|warn|abort``).

The static lock-order pass (``analysis/concurrency.py``) approximates
acquisition order through one level of calls; this module records the
order that *actually happens* — the lockset/witness half of the classic
dynamic race tooling (Eraser, Savage et al. 1997; ThreadSanitizer,
Serebryany & Iskhodzhanov 2009), scoped to our own locks.

Our runtime modules create locks through the creation funnels below
(:func:`Lock` / :func:`RLock` / :func:`Condition`) instead of calling
``threading`` directly. With the knob off (the default) each funnel
returns the plain ``threading`` primitive after ONE module-bool check —
no wrapper object exists anywhere and no ``lockcheck_*`` counter ever
moves (subprocess-proven by ``tests/test_lockcheck.py`` and the CI
``analysis`` job, like every other knob). With ``warn``/``abort`` each
lock created *afterwards* is wrapped in a :class:`_WitnessLock` that
maintains a per-thread held-stack and a global site-keyed order graph:

* **Inversion**: recording edge ``B -> A`` (B held while acquiring A)
  when ``A -> B`` is already in the graph flags the ABBA shape — counter
  ``lockcheck_inversion``, one report per unordered site pair: a
  warning naming both acquisition chains under ``warn``, ``MXNetError``
  *before the blocking acquire* under ``abort`` (the thread is stopped
  at the inversion, not inside the deadlock it would cause).
* **Held-lock host sync**: the NDArray sync points (``asnumpy`` /
  ``asscalar`` / ``wait_to_read``) call :func:`note_sync`; a sync while
  ANY witnessed lock is held counts ``lockcheck_held_sync`` and
  warns/aborts — unless every held lock was created with
  ``allow_sync=True``, the runtime twin of the static
  ``# mx-lint: allow(lock-host-sync)`` justification.

Discipline notes:

* Graph nodes are CREATION SITES (``file:line`` plus the optional
  ``name=``), not instances — two servers' ``_lock`` instances share a
  node, so an ABBA between instances of the same class pair is still
  caught; edges between two instances of ONE site are ignored (the
  common address-ordered same-class pattern cannot be told apart from
  an inversion statically-keyed this way).
* Non-blocking try-acquires update held-state but record no edges: a
  trylock never waits, so it cannot complete a deadlock cycle.
* Reentrant re-acquires of a held RLock record no edges (one node, no
  self-order); ``Condition.wait``'s release/re-acquire goes through
  ``_release_save``/``_acquire_restore`` so held-state stays exact and
  the re-acquire is witnessed like any other blocking acquire.
* The witness's own state lives under a RAW ``threading.Lock`` and the
  flag path (profiler counter, logging, raise) runs OUTSIDE it — the
  recorder never feeds its own graph.

The knob is read at lock creation: flipping it at runtime
(``mx.config.set``) affects locks created from then on, which is what
tests want (fresh objects per case) and keeps the off path free of any
per-acquire mode check.
"""
from __future__ import annotations

import logging
import sys
import threading as _threading
from typing import Dict, List, Optional, Set, Tuple

from . import config as _config

__all__ = ["Lock", "RLock", "Condition", "note_sync", "mode",
           "reset_order_graph"]

_MODE = "off"
_ON = False


def _set_mode(value: str) -> None:
    global _MODE, _ON
    _MODE = value
    _ON = value != "off"


_set_mode(_config.get("MXNET_TPU_LOCKCHECK"))
_config.on_change("MXNET_TPU_LOCKCHECK", _set_mode)


def mode() -> str:
    """Current witness mode (``off``/``warn``/``abort``)."""
    return _MODE


# --------------------------------------------------------------- state
# All raw threading primitives: the recorder must never witness itself.
_graph_lock = _threading.Lock()
# (site_a, site_b) -> human chain: how site_b was first acquired under a
_edges: Dict[Tuple[str, str], str] = {}
_flagged: Set[frozenset] = set()        # site pairs already reported
_sync_flagged: Set[Tuple[str, str]] = set()
_tls = _threading.local()


def _held() -> List["_WitnessLock"]:
    h = getattr(_tls, "held", None)
    if h is None:
        h = _tls.held = []
    return h


def reset_order_graph() -> None:
    """Forget every recorded edge and report (test isolation)."""
    with _graph_lock:
        _edges.clear()
        _flagged.clear()
        _sync_flagged.clear()


def _shorten(fn: str) -> str:
    for marker in ("mxnet_tpu", "tests", "tools"):
        idx = fn.rfind(marker)
        if idx >= 0:
            return fn[idx:]
    return fn


def _caller_site(depth: int) -> str:
    """file:line of the nearest frame OUTSIDE this module — the user's
    ``with``/``acquire`` line, not our wrapper plumbing."""
    try:
        frame = sys._getframe(depth)
        while frame is not None and frame.f_code.co_filename == __file__:
            frame = frame.f_back
        if frame is None:
            return "<unknown>"
        return "%s:%d" % (_shorten(frame.f_code.co_filename),
                          frame.f_lineno)
    except Exception:                                   # noqa: BLE001
        return "<unknown>"


def _abort(message: str) -> None:
    from .base import MXNetError
    raise MXNetError(message)


def _flag_inversion(pair_msgs: List[str]) -> None:
    from . import profiler as _profiler
    for msg in pair_msgs:
        _profiler.incr_counter("lockcheck_inversion")
        full = ("lockcheck: lock-order inversion (ABBA) observed — %s. "
                "Two threads taking these paths concurrently deadlock." % msg)
        if _MODE == "abort":
            _abort(full)
        logging.getLogger(__name__).warning(full)


class _WitnessLock:
    """Order-witnessing wrapper around one ``threading`` primitive.

    Duck-types the lock protocol (``acquire``/``release``/``locked``/
    context manager) plus the private hooks ``threading.Condition``
    probes for (``_is_owned``/``_release_save``/``_acquire_restore``),
    so it can back a Condition transparently.
    """

    __slots__ = ("_inner", "_site", "_allow_sync", "_reentrant")

    def __init__(self, inner, site: str, allow_sync: bool = False,
                 reentrant: bool = False):
        self._inner = inner
        self._site = site
        self._allow_sync = allow_sync
        self._reentrant = reentrant

    # ------------------------------------------------------ lock protocol
    def acquire(self, blocking: bool = True, timeout: float = -1):
        if blocking:
            self._note_acquire()
        got = self._inner.acquire(blocking, timeout) if blocking \
            else self._inner.acquire(False)
        if got:
            _held().append(self)
        return got

    def release(self):
        self._inner.release()
        h = _held()
        for i in range(len(h) - 1, -1, -1):
            if h[i] is self:
                del h[i]
                break

    def locked(self):
        return self._inner.locked()

    def __enter__(self):
        self.acquire()
        return self

    def __exit__(self, *exc):
        self.release()
        return False

    def __repr__(self):
        return "<witness %r wrapping %r>" % (self._site, self._inner)

    # ------------------------------------------- Condition compatibility
    def _is_owned(self):
        inner = self._inner
        if hasattr(inner, "_is_owned"):
            return inner._is_owned()
        # plain Lock: CPython's own probe, against the INNER lock so the
        # witness records nothing for it
        if inner.acquire(False):
            inner.release()
            return False
        return True

    def _release_save(self):
        # Condition.wait drops the lock wholesale (all recursion levels)
        h = _held()
        n = 0
        for i in range(len(h) - 1, -1, -1):
            if h[i] is self:
                del h[i]
                n += 1
        if hasattr(self._inner, "_release_save"):
            return (self._inner._release_save(), n)
        self._inner.release()
        return (None, n)

    def _acquire_restore(self, saved):
        state, n = saved
        # the post-wait re-acquire blocks like any other acquisition —
        # witness it (a cond re-acquire under an unrelated held lock is
        # a genuine ordering event)
        self._note_acquire()
        if hasattr(self._inner, "_acquire_restore"):
            self._inner._acquire_restore(state)
        else:
            self._inner.acquire()
        _held().extend([self] * max(1, n))

    # ------------------------------------------------------ order graph
    def _note_acquire(self):
        held = _held()
        if not held:
            return
        if any(h is self for h in held):
            return                       # reentrant: one node, no order
        site_b = self._site
        where = _caller_site(3)
        thread = _threading.current_thread().name
        inversions: List[str] = []
        with _graph_lock:
            for h in held:
                site_a = h._site
                if site_a == site_b:
                    continue             # two instances of one site
                key = (site_a, site_b)
                chain = ("thread %r acquires lock[%s] at %s while "
                         "holding lock[%s]" % (thread, site_b, where,
                                               site_a))
                if key not in _edges:
                    _edges[key] = chain
                rev = _edges.get((site_b, site_a))
                pair = frozenset((site_a, site_b))
                if rev is not None and pair not in _flagged:
                    _flagged.add(pair)
                    inversions.append("%s; but earlier %s"
                                      % (chain, rev))
        if inversions:
            _flag_inversion(inversions)


# ------------------------------------------------------------- funnels


def Lock(name: Optional[str] = None, allow_sync: bool = False):
    """``threading.Lock()`` through the witness funnel. ``allow_sync``
    exempts the lock from held-sync flagging (a justified lock-held
    device fetch, e.g. serve's ``_model_lock`` — pair it with the static
    ``# mx-lint: allow(lock-host-sync)`` and a why-comment)."""
    if not _ON:
        return _threading.Lock()
    site = name or _caller_site(2)
    return _WitnessLock(_threading.Lock(), site, allow_sync=allow_sync)


def RLock(name: Optional[str] = None, allow_sync: bool = False):
    """``threading.RLock()`` through the witness funnel."""
    if not _ON:
        return _threading.RLock()
    site = name or _caller_site(2)
    return _WitnessLock(_threading.RLock(), site, allow_sync=allow_sync,
                        reentrant=True)


def Condition(lock=None, name: Optional[str] = None):
    """``threading.Condition()`` through the witness funnel. A condition
    sharing an already-witnessed lock is witnessed through it; a bare
    ``Condition()`` gets a witnessed RLock like threading's default."""
    if not _ON:
        return _threading.Condition(lock)
    if lock is None:
        site = name or _caller_site(2)
        lock = _WitnessLock(_threading.RLock(), site, reentrant=True)
    return _threading.Condition(lock)


# ---------------------------------------------------------- sync hook


def note_sync(what: str = "host-sync") -> None:
    """Called from the NDArray sync points (behind an ``if
    lockcheck._ON`` module-bool so the off path costs one attribute
    read): flag a device sync performed while witnessed locks are
    held — the runtime ground truth behind the static
    ``lock-host-sync`` pass."""
    if not _ON:
        return
    held = [h for h in _held() if not h._allow_sync]
    if not held:
        return
    where = _caller_site(2)
    sites = ", ".join(h._site for h in held)
    keys = [(h._site, what) for h in held]
    with _graph_lock:
        fresh = [k for k in keys if k not in _sync_flagged]
        _sync_flagged.update(fresh)
    if not fresh:
        return
    from . import profiler as _profiler
    _profiler.incr_counter("lockcheck_held_sync", len(fresh))
    msg = ("lockcheck: host sync %r at %s while holding lock(s) [%s] — "
           "other threads queue behind the device; callback re-entry "
           "deadlocks (the PR 2 train_rcnn shape). Create the lock with "
           "allow_sync=True only with a justification comment."
           % (what, where, sites))
    if _MODE == "abort":
        _abort(msg)
    logging.getLogger(__name__).warning(msg)
